"""Chaos harness: run a short training loop under an injected fault spec
and exit nonzero unless the run RECOVERS.

Usage::

    python -m paddle_tpu.tools.chaos \
        --steps 9 --spec "nan_grad@step=3;ckpt_write_fail@step=5;worker_kill@step=7"

The driver supervises a training *worker* subprocess (this same module
with ``--worker``) the way a production job controller supervises a
trainer:

* the worker trains a fixed deterministic model, pins the injector step
  each iteration, saves an atomic versioned checkpoint every step, and
  auto-resumes from the latest intact version on boot;
* the driver restarts a killed/hung worker with jittered backoff (up to
  ``--max-restarts``), bounding each incarnation with a wall-clock
  timeout so an injected hang also surfaces;
* after the final incarnation finishes, the driver replays the SAME
  schedule fault-free in-process, *skipping* the steps the guarded
  worker skipped, and demands the final parameter digest match
  bit-for-bit.

Exit status: 0 = recovered and matched; 1 = survived but diverged;
2 = did not survive (restarts exhausted / no completion).

This is the executable form of the ISSUE-2 acceptance scenario — CI runs
it with the spec above; any spec drawn from the
``PADDLE_TPU_FAULT_SPEC`` grammar works.

``--elastic`` runs the ISSUE-12 acceptance scenario instead: an
elastic cluster of ``--elastic-world`` workers trains a shared global
batch, one worker is killed mid-run, and the survivors must re-plan,
reshard and resume IN-PROCESS at the shrunk world size — no restart.
The post-recovery loss curve is diffed against a same-seed oracle run
uninterrupted at the shrunk world size (exit 1 beyond ``--tolerance``),
and the journal must show the
``worker-lost → replan → reshard → resume`` incident chain.

``--quant`` runs the quantized-collective A/B drill (ISSUE-15): twin
same-seed data-parallel training runs where the control reduces
gradients densely and the quant twin pushes every gradient bucket
through the real int8 block-quantized reduction pipeline
(quantize → dequant-sum → requant → dequant, exactly the
``quant/collective.py`` wire math for a 2-rank ring).  Every step the
measured quantization error is checked against the documented error
model and fed to the ``quant_error`` drift gauge; the drill exits 1
unless the two loss curves stay within ``--tolerance`` relative error
AND both converge.
"""

import argparse
import hashlib
import os
import subprocess
import sys
import tempfile
import time

def _force_cpu():
    """Both the worker and the in-process oracle run on CPU: the drill
    verifies recovery logic, and the bit-for-bit digest comparison needs
    one platform on both sides (the env var alone can be ignored when an
    image pins a TPU plugin via jax config)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


# deterministic tiny regression problem — the model must be
# dropout-free so a skipped step is exactly "one batch not applied"
_DATA_SEED = 1234
_MODEL_SEED = 77
_BATCH = 16
_FEATS = 4
_HIDDEN = 8
_LR = 0.1


def _build_model():
    import paddle_tpu as fluid

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = _MODEL_SEED
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[_FEATS], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=_HIDDEN, act="relu")
        p = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(p - y))
        fluid.optimizer.Adam(learning_rate=_LR).minimize(loss)
    return main, startup, loss


def _batches(steps):
    import numpy as np

    rng = np.random.RandomState(_DATA_SEED)
    out = []
    for _ in range(steps):
        xb = rng.randn(_BATCH, _FEATS).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True)
              + 0.1 * rng.randn(_BATCH, 1)).astype("float32")
        out.append((xb, yb))
    return out


def _param_digest(scope, program):
    import numpy as np

    h = hashlib.sha256()
    for v in sorted(program.list_vars(), key=lambda v: v.name):
        if not v.persistable:
            continue
        val = scope.get(v.name)
        if val is None:
            continue
        h.update(v.name.encode())
        h.update(np.ascontiguousarray(np.asarray(val)).tobytes())
    return h.hexdigest()


def _run_worker(args):
    """One trainer incarnation: resume → train → checkpoint each step."""
    import warnings

    import numpy as np  # noqa: F401

    _force_cpu()
    import paddle_tpu as fluid
    from paddle_tpu.resilience import checkpoint, faults, guard

    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    start_step = 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        info = checkpoint.try_load_latest_checkpoint(
            exe, args.ckpt_dir, main_program=main)
    if info is not None:
        start_step = int(info.state.get("next_step", info.step + 1))
        print("CHAOS_RESUME step=%d from=%s"
              % (start_step, os.path.basename(info.path)), flush=True)
        from paddle_tpu.observability import journal as _journal

        _journal.emit("resume", step=start_step,
                      source=os.path.basename(info.path))

    for k, (xb, yb) in enumerate(_batches(args.steps)):
        if k < start_step:
            continue
        faults.set_step(k)
        skipped_before = guard.stats.skipped_steps
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
        skipped = int(guard.stats.skipped_steps > skipped_before)
        print("CHAOS_STEP %d loss=%.8f skipped=%d"
              % (k, float(np.asarray(lv).reshape(())), skipped),
              flush=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            checkpoint.save_checkpoint(
                exe, args.ckpt_dir, main_program=main, step=k,
                state={"next_step": k + 1}, retain=3)
    digest = _param_digest(fluid.global_scope(), main)
    print("CHAOS_FINAL params_sha=%s skipped_total=%d"
          % (digest, guard.stats.skipped_steps), flush=True)
    print("CHAOS_OK", flush=True)
    return 0


def _oracle_digest(steps, skip_steps):
    """Fault-free replay in-process, not applying the skipped steps —
    the trajectory the recovered run must land on exactly."""
    import warnings

    _force_cpu()
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.resilience import faults

    faults.set_fault_spec("")
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        for k, (xb, yb) in enumerate(_batches(steps)):
            if k in skip_steps:
                continue
            faults.set_step(k)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        return _param_digest(fluid.global_scope(), main)


# elastic drill: a constant GLOBAL batch sliced by membership index —
# divisible by both the full and the shrunk world, so the global
# gradient (sum of member means / world) is identical at every world
# size and the shrunk-world oracle is comparable within fp tolerance
_GLOBAL_BATCH = 24


def _elastic_batches(steps):
    import numpy as np

    rng = np.random.RandomState(_DATA_SEED)
    out = []
    for _ in range(steps):
        xb = rng.randn(_GLOBAL_BATCH, _FEATS).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True)
              + 0.1 * rng.randn(_GLOBAL_BATCH, 1)).astype("float32")
        out.append((xb, yb))
    return out


def _elastic_feed(batches):
    def make_feed(step, index, world):
        xb, yb = batches[step]
        n = xb.shape[0] // world
        sl = slice(index * n, (index + 1) * n)
        return {"x": xb[sl], "y": yb[sl]}
    return make_feed


def _run_elastic_worker(args):
    """One elastic cluster member: the ElasticTrainer owns the loop —
    worker loss is recovered in here, never by a process restart."""
    import warnings

    import numpy as np

    _force_cpu()
    import paddle_tpu as fluid
    from paddle_tpu.resilience import elastic

    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    batches = _elastic_batches(args.steps)
    delay = float(getattr(args, "step_delay", 0.0) or 0.0)

    def on_step(step, fetches, trainer):
        print("ELASTIC_STEP %d rank=%d index=%d world=%d epoch=%d "
              "loss=%.8f"
              % (step, trainer.rank, trainer.index, trainer.world,
                 trainer.epoch,
                 float(np.asarray(fetches[0]).reshape(()))), flush=True)
        if delay > 0:
            # rejoin drills pace the fleet so a relaunched worker has
            # live steps left to join
            time.sleep(delay)

    trainer = elastic.ElasticTrainer(
        main, startup, exe, rank=args.rank, world=args.world,
        workdir=args.ckpt_dir, fetch_list=[loss.name],
        batch_size=_GLOBAL_BATCH, ckpt_every=1,
        stale_timeout=args.stale_timeout,
        wedge_timeout=args.worker_timeout)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            trainer.run(args.steps, _elastic_feed(batches), on_step,
                        join=bool(getattr(args, "join", False)))
    except elastic.ElasticEvictedError as e:
        print("ELASTIC_EVICTED %s" % e, flush=True)
        return elastic.ELASTIC_EVICTED_EXIT_CODE
    digest = _param_digest(fluid.global_scope(), trainer.train_prog)
    print("ELASTIC_FINAL rank=%d params_sha=%s world=%d epoch=%d"
          % (trainer.rank, digest, trainer.world, trainer.epoch),
          flush=True)
    print("ELASTIC_OK", flush=True)
    return 0


def _elastic_oracle(steps, world):
    """Uninterrupted same-seed trajectory at the shrunk world size,
    simulated in one process through the SAME plan/split/reduce helpers
    the distributed workers run — per-step, per-member losses."""
    import warnings

    _force_cpu()
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.resilience import elastic, faults

    faults.set_fault_spec("")
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    batches = _elastic_batches(steps)
    make_feed = _elastic_feed(batches)
    per_step = []
    with scope_guard(Scope()), warnings.catch_warnings():
        warnings.simplefilter("ignore")
        prog, st, split, _result, _applied = elastic.plan_world(
            main, startup, world, batch_size=_GLOBAL_BATCH)
        exe.run(program=st if st is not None else startup)
        for k in range(steps):
            if split is None:
                out = exe.run(program=prog, feed=make_feed(k, 0, 1),
                              fetch_list=[loss.name])
                per_step.append(
                    [float(np.asarray(out[0]).reshape(()))])
                continue
            ng = len(split.grad_names)
            per_member, member_losses, passthrough = [], [], {}
            for idx in range(world):
                out = exe.run(
                    program=split.head, feed=make_feed(k, idx, world),
                    fetch_list=[loss.name] + split.grad_names
                    + split.passthrough)
                member_losses.append(
                    float(np.asarray(out[0]).reshape(())))
                per_member.append(
                    dict(zip(split.grad_names, out[1:1 + ng])))
                if idx == 0:
                    passthrough = dict(zip(split.passthrough,
                                           out[1 + ng:]))
            reduced = elastic.reduce_gradients(per_member,
                                               split.pre_scale)
            feed = dict(passthrough)
            feed.update(reduced)
            exe.run(program=split.tail, feed=feed, fetch_list=[])
            per_step.append(member_losses)
    return per_step


def _parse_elastic_output(text):
    """{step: (index, world, epoch, loss)} plus final/evicted flags."""
    steps = {}
    final = None
    for line in text.splitlines():
        if line.startswith("ELASTIC_STEP "):
            parts = line.split()
            k = int(parts[1])
            kv = dict(p.split("=") for p in parts[2:])
            steps[k] = (int(kv["index"]), int(kv["world"]),
                        int(kv["epoch"]), float(kv["loss"]))
        elif line.startswith("ELASTIC_FINAL "):
            parts = line.split()
            kv = dict(p.split("=") for p in parts[1:])
            final = kv
    return steps, final


def _run_elastic_driver(args):
    """Spawn the elastic cluster, kill one worker, verify the survivors
    recover in-process and track the shrunk-world oracle."""
    import subprocess as sp

    from paddle_tpu.resilience.faults import KILL_EXIT_CODE

    world = args.elastic_world
    kill_rank = world - 1 if args.kill_rank is None else args.kill_rank
    workdir = args.ckpt_dir or tempfile.mkdtemp(
        prefix="paddle_tpu_elastic_")
    os.makedirs(workdir, exist_ok=True)
    telemetry_dir = args.telemetry_dir \
        or os.path.join(workdir, "telemetry")
    print("chaos[elastic]: world=%d kill rank %d at step %d, %d steps, "
          "workdir=%s" % (world, kill_rank, args.kill_step, args.steps,
                          workdir), flush=True)

    # one traceparent for the whole drill: every worker's spans join
    # this trace, so worker-lost→replan→reshard→resume reconstructs as
    # ONE trace across victim + survivors (tools.trace --elastic)
    from paddle_tpu.observability import tracing as _tracing

    drill_ctx = _tracing.new_trace_context()
    drill_tp = _tracing.format_traceparent(drill_ctx)
    print("chaos[elastic]: trace %s" % drill_ctx.trace_id, flush=True)

    procs, logs = [], []
    for rank in range(world):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        env["PADDLE_TPU_TELEMETRY_DIR"] = telemetry_dir
        env["PADDLE_TPU_TRACEPARENT"] = drill_tp
        # drills are short and killed mid-flight: flush every span so
        # the victim's pre-death spans reach disk before the kill
        env.setdefault("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        env.pop("PADDLE_TPU_NAN_GUARD", None)
        if rank == kill_rank:
            env["PADDLE_TPU_FAULT_SPEC"] = (
                "worker_kill@step=%d" % args.kill_step)
            env["PADDLE_TPU_FAULT_STATE_FILE"] = os.path.join(
                workdir, "fault_state_r%d.json" % rank)
        cmd = [sys.executable, "-m", "paddle_tpu.tools.chaos",
               "--elastic-worker", "--rank", str(rank),
               "--world", str(world), "--steps", str(args.steps),
               "--ckpt-dir", workdir,
               "--stale-timeout", str(args.stale_timeout),
               "--worker-timeout", str(args.worker_timeout)]
        if args.step_delay:
            cmd += ["--step-delay", str(args.step_delay)]
        logf = open(os.path.join(workdir, "worker-r%d.log" % rank),
                    "w+")
        logs.append(logf)
        procs.append(sp.Popen(cmd, env=env, stdout=logf,
                              stderr=sp.STDOUT))

    deadline = time.time() + args.worker_timeout

    def _abort(msg):
        print("chaos[elastic]: FAIL — %s" % msg, flush=True)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for logf in logs:
            logf.close()
        return 2

    if args.rejoin:
        from paddle_tpu.observability.journal import read_journal

        victim = procs[kill_rank]
        while victim.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if victim.returncode != KILL_EXIT_CODE:
            return _abort("victim rank %d exited %s before the rejoin "
                          "could be staged, expected the injected kill "
                          "(%d)" % (kill_rank, victim.returncode,
                                    KILL_EXIT_CODE))
        # relaunch only once the shrunk fleet is stepping again (its
        # "resume" journal event has landed), so the incident chain
        # reads worker-lost -> replan -> reshard -> join-request in
        # causal order rather than racing the shrink
        seen_resume = False
        while time.time() < deadline:
            if any(e.get("kind") == "resume"
                   for e in read_journal(telemetry_dir)):
                seen_resume = True
                break
            time.sleep(0.2)
        if not seen_resume:
            return _abort("survivors never resumed at world %d; cannot "
                          "stage the rejoin" % (world - 1))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        env["PADDLE_TPU_TELEMETRY_DIR"] = telemetry_dir
        env["PADDLE_TPU_TRACEPARENT"] = drill_tp
        env.setdefault("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        # the second life joins clean — it must NOT re-inherit the kill
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        env.pop("PADDLE_TPU_FAULT_STATE_FILE", None)
        env.pop("PADDLE_TPU_NAN_GUARD", None)
        cmd = [sys.executable, "-m", "paddle_tpu.tools.chaos",
               "--elastic-worker", "--join", "--rank", str(kill_rank),
               "--world", str(world), "--steps", str(args.steps),
               "--ckpt-dir", workdir,
               "--stale-timeout", str(args.stale_timeout),
               "--worker-timeout", str(args.worker_timeout)]
        if args.step_delay:
            cmd += ["--step-delay", str(args.step_delay)]
        print("chaos[elastic]: victim died with %d; relaunching rank %d "
              "as a joiner" % (KILL_EXIT_CODE, kill_rank), flush=True)
        logf = open(os.path.join(
            workdir, "worker-r%d-rejoin.log" % kill_rank), "w+")
        logs.append(logf)
        procs.append(sp.Popen(cmd, env=env, stdout=logf,
                              stderr=sp.STDOUT))

    while any(p.poll() is None for p in procs) \
            and time.time() < deadline:
        time.sleep(0.2)
    hung = [r for r, p in enumerate(procs) if p.poll() is None]
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.wait()
    outputs = []
    for logf in logs:
        logf.seek(0)
        outputs.append(logf.read())
        logf.close()
    rcs = [p.returncode for p in procs]
    print("chaos[elastic]: exit codes %s%s"
          % (rcs, " (killed hung: %s)" % hung if hung else ""),
          flush=True)
    if hung:
        print("chaos[elastic]: FAIL — worker(s) %s hung past %.0fs; "
              "rank 0 tail:\n%s" % (hung, args.worker_timeout,
                                    outputs[0][-2000:]), flush=True)
        return 2
    if rcs[kill_rank] != KILL_EXIT_CODE:
        print("chaos[elastic]: FAIL — victim rank %d exited %s, "
              "expected the injected kill (%d)"
              % (kill_rank, rcs[kill_rank], KILL_EXIT_CODE), flush=True)
        return 2
    survivors = [r for r in range(world) if r != kill_rank]
    bad = [r for r in survivors if rcs[r] != 0]
    if bad:
        print("chaos[elastic]: FAIL — survivor(s) %s exited nonzero; "
              "rank %d tail:\n%s"
              % (bad, bad[0], outputs[bad[0]][-3000:]), flush=True)
        return 2

    if args.rejoin:
        return _verify_rejoin(args, world, kill_rank, rcs, outputs,
                              telemetry_dir, drill_ctx)

    shrunk = world - 1
    parsed = {r: _parse_elastic_output(outputs[r]) for r in survivors}
    for r in survivors:
        steps_seen, final = parsed[r]
        missing = [k for k in range(args.steps) if k not in steps_seen]
        if missing or final is None:
            print("chaos[elastic]: FAIL — rank %d missed steps %s "
                  "(in-process resume must cover every step)"
                  % (r, missing), flush=True)
            return 2
        post = [k for k, (_i, w, _e, _l) in steps_seen.items()
                if w == shrunk]
        if not post or min(post) > args.kill_step:
            print("chaos[elastic]: FAIL — rank %d never re-ran step "
                  "%d at world %d (post-recovery steps: %s)"
                  % (r, args.kill_step, shrunk, sorted(post)),
                  flush=True)
            return 2
    digests = {parsed[r][1]["params_sha"] for r in survivors}
    if len(digests) != 1:
        print("chaos[elastic]: FAIL — survivors ended on different "
              "params: %s" % sorted(digests), flush=True)
        return 1
    print("chaos[elastic]: survivors recovered in-process at world=%d "
          "(one log per rank — no restarts) and agree on params %s"
          % (shrunk, next(iter(digests))[:16]), flush=True)

    # the oracle is bookkeeping: keep it out of the workers' telemetry
    from paddle_tpu.observability import metrics as _metrics

    _metrics.set_telemetry_enabled(False)
    try:
        oracle = _elastic_oracle(args.steps, shrunk)
    finally:
        _metrics.set_telemetry_enabled(None)
    worst = 0.0
    for r in survivors:
        steps_seen, _final = parsed[r]
        for k, (index, w, _epoch, lv) in sorted(steps_seen.items()):
            if w != shrunk:
                continue  # pre-kill steps ran at the full world
            want = oracle[k][index]
            rel = abs(lv - want) / max(abs(want), 1e-6)
            worst = max(worst, rel)
            if rel > args.tolerance:
                print("chaos[elastic]: FAIL — rank %d step %d loss "
                      "%.8f vs shrunk-world oracle %.8f (rel %.2e > "
                      "%.2e)" % (r, k, lv, want, rel, args.tolerance),
                      flush=True)
                return 1
    print("chaos[elastic]: post-recovery loss curve tracks the "
          "world-%d oracle (worst rel err %.2e <= %.2e)"
          % (shrunk, worst, args.tolerance), flush=True)

    from paddle_tpu.observability.journal import read_journal

    kinds = {e.get("kind") for e in read_journal(telemetry_dir)}
    chain = ["worker-lost", "replan", "reshard", "checkpoint-loaded",
             "resume"]
    gone = [k for k in chain if k not in kinds]
    if gone:
        print("chaos[elastic]: FAIL — journal is missing incident "
              "events %s (have %s)" % (gone, sorted(kinds)), flush=True)
        return 1
    print("chaos[elastic]: journal shows the full incident chain "
          "%s — view it with: python -m paddle_tpu.tools.monitor "
          "--once %s" % (" -> ".join(chain), telemetry_dir),
          flush=True)

    # every rank's spans — victim included — must have joined the ONE
    # drill trace, with the recovery phases visible inside it
    spans = [r for r in _tracing.read_traces(telemetry_dir)
             if r.get("trace") == drill_ctx.trace_id]
    span_ranks = {r.get("rank") for r in spans}
    span_names = {r.get("name") for r in spans}
    want_names = {"elastic.worker", "elastic.recover", "elastic.replan",
                  "elastic.restore"}
    missing_ranks = set(range(world)) - span_ranks
    missing_names = want_names - span_names
    if missing_ranks or missing_names:
        print("chaos[elastic]: FAIL — drill trace %s is missing "
              "rank(s) %s / span(s) %s (have ranks %s, %d spans)"
              % (drill_ctx.trace_id, sorted(missing_ranks),
                 sorted(missing_names), sorted(span_ranks), len(spans)),
              flush=True)
        return 1
    print("chaos[elastic]: ONE trace %s spans all %d ranks through "
          "recovery (%d spans) — reconstruct it with: python -m "
          "paddle_tpu.tools.trace --elastic %s"
          % (drill_ctx.trace_id, world, len(spans), telemetry_dir),
          flush=True)
    print("chaos[elastic]: PASS", flush=True)
    return 0


def _verify_rejoin(args, world, kill_rank, rcs, outputs, telemetry_dir,
                   drill_ctx):
    """Rejoin half of the verdict: the victim's second life joined, the
    fleet grew back to the full world, every run's losses track the
    per-world oracles, and the journal reads the whole incident —
    shrink, join, warm-up, grow — as ONE causally ordered trace."""
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.observability import tracing as _tracing
    from paddle_tpu.observability.journal import read_journal

    survivors = [r for r in range(world) if r != kill_rank]
    if rcs[-1] != 0:
        print("chaos[elastic]: FAIL — the victim's second life exited "
              "%s (a rejoined worker must exit 0); tail:\n%s"
              % (rcs[-1], outputs[-1][-3000:]), flush=True)
        return 2

    parsed = {r: _parse_elastic_output(outputs[r]) for r in survivors}
    jsteps, jfinal = _parse_elastic_output(outputs[-1])
    for r in survivors:
        steps_seen, final = parsed[r]
        missing = [k for k in range(args.steps) if k not in steps_seen]
        if missing or final is None:
            print("chaos[elastic]: FAIL — rank %d missed steps %s "
                  "(in-process resume must cover every step)"
                  % (r, missing), flush=True)
            return 2
        if int(final["world"]) != world:
            print("chaos[elastic]: FAIL — rank %d finished at world=%s; "
                  "the fleet never grew back to %d"
                  % (r, final["world"], world), flush=True)
            return 2
    if jfinal is None or int(jfinal["world"]) != world:
        print("chaos[elastic]: FAIL — the joiner finished at world=%s "
              "(want %d); tail:\n%s"
              % (jfinal and jfinal.get("world"), world,
                 outputs[-1][-3000:]), flush=True)
        return 2
    if not jsteps:
        print("chaos[elastic]: FAIL — the joiner was admitted but ran "
              "no steps", flush=True)
        return 2
    off_world = sorted(k for k, (_i, w, _e, _l) in jsteps.items()
                       if w != world)
    if off_world:
        print("chaos[elastic]: FAIL — the joiner stepped outside the "
              "grown world at steps %s (must only run at world=%d)"
              % (off_world, world), flush=True)
        return 2
    join_step = min(jsteps)
    if join_step <= args.kill_step:
        print("chaos[elastic]: FAIL — the joiner's first step %d is "
              "not after the kill at step %d" % (join_step,
                                                 args.kill_step),
              flush=True)
        return 2
    digests = {parsed[r][1]["params_sha"] for r in survivors}
    digests.add(jfinal["params_sha"])
    if len(digests) != 1:
        print("chaos[elastic]: FAIL — survivors and joiner ended on "
              "different params: %s" % sorted(digests), flush=True)
        return 1
    print("chaos[elastic]: fleet grew back to world=%d (joiner entered "
          "at step %d) and all %d workers agree on params %s"
          % (world, join_step, world, next(iter(digests))[:16]),
          flush=True)

    # two oracles: world-N before the kill and after the grow,
    # world-(N-1) in between — every printed step names its world and
    # shard index, so each loss is compared against the right one
    _metrics.set_telemetry_enabled(False)
    try:
        oracles = {world: _elastic_oracle(args.steps, world),
                   world - 1: _elastic_oracle(args.steps, world - 1)}
    finally:
        _metrics.set_telemetry_enabled(None)
    runs = [("rank %d" % r, parsed[r][0]) for r in survivors]
    runs.append(("rank %d (rejoined)" % kill_rank, jsteps))
    worst = 0.0
    for label, steps_seen in runs:
        for k, (index, w, _epoch, lv) in sorted(steps_seen.items()):
            want = oracles[w][k][index]
            rel = abs(lv - want) / max(abs(want), 1e-6)
            worst = max(worst, rel)
            if rel > args.tolerance:
                print("chaos[elastic]: FAIL — %s step %d loss %.8f vs "
                      "world-%d oracle %.8f (rel %.2e > %.2e)"
                      % (label, k, lv, w, want, rel, args.tolerance),
                      flush=True)
                return 1
    print("chaos[elastic]: loss curve tracks the world-%d/world-%d "
          "oracles across shrink and grow (worst rel err %.2e <= %.2e)"
          % (world, world - 1, worst, args.tolerance), flush=True)

    # the whole incident must read causally in ONE trace: walk the
    # required kinds, each picked event at-or-after the previous one
    events = sorted(read_journal(telemetry_dir),
                    key=lambda e: e.get("ts", 0.0))
    chain = ["worker-lost", "replan", "reshard", "join-request",
             "admitted", "warmup", "replan", "reshard", "resume"]
    t = float("-inf")
    for kind in chain:
        pick = next(
            (e for e in events
             if e.get("kind") == kind and e.get("ts", 0.0) >= t
             and e.get("trace") == drill_ctx.trace_id), None)
        if pick is None:
            have = sorted({e.get("kind") for e in events})
            print("chaos[elastic]: FAIL — journal has no '%s' event "
                  "after the previous link in trace %s (chain %s, "
                  "kinds present: %s)"
                  % (kind, drill_ctx.trace_id, " -> ".join(chain),
                     have), flush=True)
            return 1
        t = pick.get("ts", t)
    print("chaos[elastic]: journal reads %s in causal order inside "
          "one trace — view it with: python -m paddle_tpu.tools."
          "monitor --once %s" % (" -> ".join(chain), telemetry_dir),
          flush=True)

    spans = [s for s in _tracing.read_traces(telemetry_dir)
             if s.get("trace") == drill_ctx.trace_id]
    span_ranks = {s.get("rank") for s in spans}
    span_names = {s.get("name") for s in spans}
    want_names = {"elastic.worker", "elastic.recover", "elastic.replan",
                  "elastic.restore", "elastic.join", "elastic.warmup",
                  "elastic.grow"}
    missing_ranks = set(range(world)) - span_ranks
    missing_names = want_names - span_names
    if missing_ranks or missing_names:
        print("chaos[elastic]: FAIL — drill trace %s is missing "
              "rank(s) %s / span(s) %s (have ranks %s, %d spans)"
              % (drill_ctx.trace_id, sorted(missing_ranks),
                 sorted(missing_names), sorted(span_ranks), len(spans)),
              flush=True)
        return 1
    print("chaos[elastic]: ONE trace %s spans all %d ranks through "
          "shrink, rejoin and grow (%d spans)"
          % (drill_ctx.trace_id, world, len(spans)), flush=True)

    rejoin_ms = [e.get("rejoin_ms") for e in events
                 if e.get("kind") == "resume"
                 and e.get("rejoin_ms") is not None]
    if rejoin_ms:
        print("chaos[elastic]: elastic_rejoin_ms=%.0f (join request -> "
              "first grown step)" % rejoin_ms[-1], flush=True)
    print("chaos[elastic]: PASS", flush=True)
    return 0


def _parse_worker_output(text, losses, skipped):
    final = None
    resumed = []
    for line in text.splitlines():
        if line.startswith("CHAOS_STEP "):
            parts = line.split()
            k = int(parts[1])
            losses[k] = float(parts[2].split("=")[1])
            if int(parts[3].split("=")[1]):
                skipped.add(k)
            else:
                # a later incarnation re-ran this step cleanly (e.g. the
                # skip happened just before a crash and the resumed
                # worker applied it): the newest verdict wins
                skipped.discard(k)
        elif line.startswith("CHAOS_FINAL "):
            final = line.split()[1].split("=")[1]
        elif line.startswith("CHAOS_RESUME "):
            resumed.append(int(line.split()[1].split("=")[1]))
    return final, resumed


def _run_driver(args):
    from paddle_tpu.resilience import retry as _retry

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="paddle_tpu_chaos_")
    from paddle_tpu.resilience import checkpoint as _ckpt

    existing = _ckpt.list_checkpoints(ckpt_dir)
    if existing and existing[0][0] >= args.steps - 1:
        print("chaos: ERROR — --ckpt-dir already holds a completed run "
              "(newest version: step %d); the worker would resume past "
              "every step.  Use a fresh --ckpt-dir." % existing[0][0],
              flush=True)
        return 2
    losses, skipped, final_sha = {}, set(), None
    all_resumes = []
    backoff = _retry.RetryPolicy(max_attempts=args.max_restarts + 1,
                                 base_delay=0.2, max_delay=2.0, seed=7)
    delays = backoff.delays()
    # the drill doubles as the observability acceptance scenario: every
    # incarnation journals into one shared dir, so the monitor CLI can
    # replay the fault -> guard-skip -> restore story afterwards
    from paddle_tpu.observability.metrics import telemetry_enabled

    telemetry_dir = args.telemetry_dir
    if telemetry_dir is None and telemetry_enabled():
        telemetry_dir = os.path.join(ckpt_dir, "telemetry")
    print("chaos: spec=%r steps=%d ckpt=%s telemetry=%s"
          % (args.spec, args.steps, ckpt_dir, telemetry_dir or "off"),
          flush=True)

    from paddle_tpu.observability import tracing as _tracing

    # one trace across every incarnation of the worker
    drill_tp = _tracing.format_traceparent(_tracing.new_trace_context())

    for incarnation in range(args.max_restarts + 1):
        env = dict(os.environ)
        env.update({
            "PADDLE_TPU_FAULT_SPEC": args.spec,
            # firing budgets span restarts: a worker_kill is ONE
            # preemption, not one per incarnation
            "PADDLE_TPU_FAULT_STATE_FILE":
                os.path.join(ckpt_dir, "fault_state.json"),
            "PADDLE_TPU_NAN_GUARD": "1",
            "PADDLE_TPU_TRACEPARENT": drill_tp,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        })
        env.setdefault("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        if telemetry_dir:
            env["PADDLE_TPU_TELEMETRY_DIR"] = telemetry_dir
        cmd = [sys.executable, "-m", "paddle_tpu.tools.chaos", "--worker",
               "--steps", str(args.steps), "--ckpt-dir", ckpt_dir]
        with tempfile.NamedTemporaryFile("w+", suffix=".log",
                                         delete=False) as logf:
            t0 = time.time()
            proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                    stderr=subprocess.STDOUT)
            try:
                rc = proc.wait(timeout=args.worker_timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                rc = "timeout"
            logf.seek(0)
            out = logf.read()
        final_sha, resumes = _parse_worker_output(out, losses, skipped)
        all_resumes.extend(resumes)
        print("chaos: incarnation %d rc=%s (%.1fs) steps_done=%d"
              % (incarnation, rc, time.time() - t0, len(losses)),
              flush=True)
        if rc == 0 and final_sha is not None:
            break
        if incarnation == args.max_restarts:
            print("chaos: FAIL — worker never completed within %d "
                  "restarts; last output:\n%s"
                  % (args.max_restarts, out[-2000:]), flush=True)
            return 2
        try:
            delay = next(delays)
        except StopIteration:
            delay = 1.0
        print("chaos: restarting worker (auto-resume) in %.2fs" % delay,
              flush=True)
        time.sleep(delay)

    missing = [k for k in range(args.steps) if k not in losses]
    if missing:
        print("chaos: FAIL — steps %s never ran" % missing, flush=True)
        return 2
    print("chaos: worker recovered; skipped steps=%s resumes=%s"
          % (sorted(skipped), all_resumes), flush=True)

    # the oracle replay is bookkeeping, not training: keep its steps and
    # checkpoints out of the telemetry the workers just wrote
    from paddle_tpu.observability import metrics as _metrics

    _metrics.set_telemetry_enabled(False)
    try:
        oracle = _oracle_digest(args.steps, skipped)
    finally:
        _metrics.set_telemetry_enabled(None)
    if oracle != final_sha:
        print("chaos: FAIL — final params %s != fault-free oracle %s "
              "(recovery diverged)" % (final_sha[:16], oracle[:16]),
              flush=True)
        return 1
    print("chaos: PASS — final params match the fault-free trajectory "
          "(sha %s)" % final_sha[:16], flush=True)
    return 0


def _run_quant_driver(args):
    """ISSUE-15 acceptance drill: quantized vs dense collective twins.

    Both twins train the same deterministic model from the same seed on
    the same batches, each step splitting the batch across a simulated
    2-rank data-parallel ring.  The control sums the per-rank gradients
    in full precision; the quant twin runs them through the identical
    quantize → dequant-sum → requant → dequant pipeline the
    ``c_allreduce_quant`` op executes on the wire (same primitives, same
    block size, same fixed reduction order), so the injected error IS
    the collective's error — not a stand-in.  Runs on one CPU device;
    no mesh is needed because a 2-rank quantized ring's arithmetic is
    rank-count-independent pointwise math once the shards are in hand.
    """
    _force_cpu()
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from paddle_tpu.observability.drift import monitor, reset_drift
    from paddle_tpu.quant import (block_dequantize, block_quantize,
                                  predicted_rms_error, quant_block)

    steps = max(args.steps, 6)
    lr = 0.05
    print("chaos: quant A/B drill — %d steps, block=%d, tolerance=%g"
          % (steps, quant_block(), args.tolerance), flush=True)

    def init_params():
        k = jax.random.PRNGKey(_MODEL_SEED)
        k1, k2 = jax.random.split(k)
        return {
            "w1": jax.random.normal(k1, (_FEATS, _HIDDEN)) * 0.5,
            "b1": jnp.zeros((_HIDDEN,)),
            "w2": jax.random.normal(k2, (_HIDDEN, 1)) * 0.5,
            "b2": jnp.zeros((1,)),
        }

    def loss_fn(params, xb, yb):
        h = jnp.maximum(xb @ params["w1"] + params["b1"], 0.0)
        p = h @ params["w2"] + params["b2"]
        return jnp.mean((p - yb) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    def quant_reduce(flats):
        """Mirror quantized_allreduce on already-materialized shards:
        each rank's contribution crosses the wire as int8 + scales both
        directions (reduce-scatter then allgather)."""
        numel = int(flats[0].size)
        parts, preds = [], []
        for f in flats:
            q, s = block_quantize(f)
            parts.append(block_dequantize(q, s, size=numel))
            preds.append(float(predicted_rms_error(s)))
        acc = parts[0]
        for p in parts[1:]:
            acc = acc + p
        q_r, s_r = block_quantize(acc)
        out = block_dequantize(q_r, s_r, size=numel)
        preds.append(float(predicted_rms_error(s_r)))
        predicted = float(np.sqrt(sum(p * p for p in preds)))
        return out, predicted

    reset_drift()
    mon = monitor()
    params_c = init_params()
    params_q = jax.tree_util.tree_map(lambda a: a, params_c)
    losses_c, losses_q = [], []
    worst_rel, worst_err_ratio = 0.0, 0.0
    for k, (xb, yb) in enumerate(_batches(steps)):
        half = _BATCH // 2
        shards = [(xb[:half], yb[:half]), (xb[half:], yb[half:])]

        # control twin: dense mean-of-shards reduction
        lv_c = grad_fn(params_c, xb, yb)[0]
        gflats_c = []
        unravel = None
        for xs, ys in shards:
            _, g = grad_fn(params_c, xs, ys)
            flat, unravel = ravel_pytree(g)
            gflats_c.append(flat * 0.5)
        dense_c = gflats_c[0] + gflats_c[1]
        params_c = unravel(ravel_pytree(params_c)[0] - lr * dense_c)

        # quant twin: same shards, int8 wire reduction; the dense sum of
        # ITS OWN gradients is the per-step error reference
        lv_q = grad_fn(params_q, xb, yb)[0]
        gflats_q = []
        for xs, ys in shards:
            _, g = grad_fn(params_q, xs, ys)
            flat, _ = ravel_pytree(g)
            gflats_q.append(flat * 0.5)
        dense_q = gflats_q[0] + gflats_q[1]
        reduced, predicted = quant_reduce(gflats_q)
        measured = float(jnp.sqrt(jnp.mean((reduced - dense_q) ** 2)))
        mon.observe_quant_error(measured, predicted=predicted,
                                bucket="grads")
        if predicted > 0:
            worst_err_ratio = max(worst_err_ratio, measured / predicted)
        params_q = unravel(ravel_pytree(params_q)[0] - lr * reduced)

        lc, lq = float(lv_c), float(lv_q)
        losses_c.append(lc)
        losses_q.append(lq)
        rel = abs(lq - lc) / max(abs(lc), 1e-8)
        worst_rel = max(worst_rel, rel)
        print("CHAOS_QUANT_STEP %d loss_dense=%.8f loss_quant=%.8f "
              "rel=%.2e quant_rms=%.3e model_rms=%.3e"
              % (k, lc, lq, rel, measured, predicted), flush=True)

    converged_c = losses_c[-1] < losses_c[0]
    converged_q = losses_q[-1] < losses_q[0]
    # 3x headroom over the RMS model: per-step error is a random draw,
    # the model is its expectation
    model_ok = worst_err_ratio <= 3.0
    print("chaos: quant drill worst_loss_rel=%.2e worst_error_vs_model="
          "%.2fx converged dense=%s quant=%s"
          % (worst_rel, worst_err_ratio, converged_c, converged_q),
          flush=True)
    if worst_rel > args.tolerance:
        print("chaos: FAIL — quant twin loss diverged %.2e > "
              "tolerance %g" % (worst_rel, args.tolerance), flush=True)
        return 1
    if not (converged_c and converged_q):
        print("chaos: FAIL — a twin failed to converge "
              "(dense %s, quant %s)" % (converged_c, converged_q),
              flush=True)
        return 1
    if not model_ok:
        print("chaos: FAIL — measured quant error %.2fx the documented "
              "model (alert 'quant_error_ratio>2' would page)"
              % worst_err_ratio, flush=True)
        return 1
    print("chaos: PASS — quantized twin matched the dense loss curve "
          "within %g and the error stayed inside the model"
          % args.tolerance, flush=True)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.chaos",
        description="Fault-injection chaos run: train, inject, recover, "
                    "verify against the fault-free trajectory.")
    parser.add_argument("--spec", default=os.environ.get(
        "PADDLE_TPU_FAULT_SPEC",
        "nan_grad@step=3;ckpt_write_fail@step=5;worker_kill@step=7"),
        help="fault spec (see resilience/faults.py grammar)")
    parser.add_argument("--steps", type=int, default=None,
                        help="training steps (default 9; 24 for "
                             "--elastic --rejoin so the joiner has "
                             "live steps left to enter)")
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--telemetry-dir", default=None,
                        help="journal/metrics dir for the workers "
                             "(default: <ckpt-dir>/telemetry when "
                             "telemetry is on); tail it with "
                             "python -m paddle_tpu.tools.monitor")
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--worker-timeout", type=float, default=300.0,
                        help="seconds per worker incarnation (bounds "
                             "injected hangs)")
    parser.add_argument("--elastic", action="store_true",
                        help="run the elastic drill instead: kill one "
                             "of --elastic-world workers mid-run and "
                             "demand an in-process re-plan/reshard/"
                             "resume at the shrunk world size")
    parser.add_argument("--quant", action="store_true",
                        help="run the quantized-collective A/B drill "
                             "instead: same-seed twins (dense vs int8 "
                             "block-quantized gradient reduction) must "
                             "match loss curves within --tolerance")
    parser.add_argument("--rejoin", action="store_true",
                        help="with --elastic: after the shrink "
                             "recovery, relaunch the victim as a "
                             "joiner and demand the fleet grows back "
                             "to the full world (matching digests, "
                             "causally ordered journal, one trace)")
    parser.add_argument("--step-delay", type=float, default=None,
                        help="seconds each worker sleeps per step "
                             "(default 0; 0.4 for --rejoin so the "
                             "joiner warms up behind a live fleet)")
    parser.add_argument("--elastic-world", type=int, default=3,
                        help="elastic cluster size before the kill")
    parser.add_argument("--kill-step", type=int, default=3,
                        help="step at which the victim is killed")
    parser.add_argument("--kill-rank", type=int, default=None,
                        help="victim rank (default: highest rank, so "
                             "the leader path stays exercised; pick 0 "
                             "to drill a leader loss)")
    parser.add_argument("--tolerance", type=float, default=0.02,
                        help="max relative loss error vs the "
                             "shrunk-world oracle")
    parser.add_argument("--stale-timeout", type=float, default=2.0,
                        help="seconds without a heartbeat before a "
                             "peer is declared lost")
    parser.add_argument("--worker", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--elastic-worker", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--join", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--rank", type=int, default=0,
                        help=argparse.SUPPRESS)
    parser.add_argument("--world", type=int, default=1,
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)
    rejoin_drill = args.elastic and args.rejoin
    if args.steps is None:
        args.steps = 24 if rejoin_drill else 9
    if args.step_delay is None:
        args.step_delay = 0.4 if rejoin_drill else 0.0
    if args.worker:
        return _run_worker(args)
    if args.elastic_worker:
        return _run_elastic_worker(args)
    if args.quant:
        return _run_quant_driver(args)
    if args.elastic:
        return _run_elastic_driver(args)
    return _run_driver(args)


if __name__ == "__main__":
    import numpy as np  # noqa: F401  (worker fast-fail if numpy absent)

    sys.exit(main())
