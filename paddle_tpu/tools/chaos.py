"""Chaos harness: run a short training loop under an injected fault spec
and exit nonzero unless the run RECOVERS.

Usage::

    python -m paddle_tpu.tools.chaos \
        --steps 9 --spec "nan_grad@step=3;ckpt_write_fail@step=5;worker_kill@step=7"

The driver supervises a training *worker* subprocess (this same module
with ``--worker``) the way a production job controller supervises a
trainer:

* the worker trains a fixed deterministic model, pins the injector step
  each iteration, saves an atomic versioned checkpoint every step, and
  auto-resumes from the latest intact version on boot;
* the driver restarts a killed/hung worker with jittered backoff (up to
  ``--max-restarts``), bounding each incarnation with a wall-clock
  timeout so an injected hang also surfaces;
* after the final incarnation finishes, the driver replays the SAME
  schedule fault-free in-process, *skipping* the steps the guarded
  worker skipped, and demands the final parameter digest match
  bit-for-bit.

Exit status: 0 = recovered and matched; 1 = survived but diverged;
2 = did not survive (restarts exhausted / no completion).

This is the executable form of the ISSUE-2 acceptance scenario — CI runs
it with the spec above; any spec drawn from the
``PADDLE_TPU_FAULT_SPEC`` grammar works.
"""

import argparse
import hashlib
import os
import subprocess
import sys
import tempfile
import time

def _force_cpu():
    """Both the worker and the in-process oracle run on CPU: the drill
    verifies recovery logic, and the bit-for-bit digest comparison needs
    one platform on both sides (the env var alone can be ignored when an
    image pins a TPU plugin via jax config)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


# deterministic tiny regression problem — the model must be
# dropout-free so a skipped step is exactly "one batch not applied"
_DATA_SEED = 1234
_MODEL_SEED = 77
_BATCH = 16
_FEATS = 4
_HIDDEN = 8
_LR = 0.1


def _build_model():
    import paddle_tpu as fluid

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = _MODEL_SEED
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[_FEATS], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=_HIDDEN, act="relu")
        p = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(p - y))
        fluid.optimizer.Adam(learning_rate=_LR).minimize(loss)
    return main, startup, loss


def _batches(steps):
    import numpy as np

    rng = np.random.RandomState(_DATA_SEED)
    out = []
    for _ in range(steps):
        xb = rng.randn(_BATCH, _FEATS).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True)
              + 0.1 * rng.randn(_BATCH, 1)).astype("float32")
        out.append((xb, yb))
    return out


def _param_digest(scope, program):
    import numpy as np

    h = hashlib.sha256()
    for v in sorted(program.list_vars(), key=lambda v: v.name):
        if not v.persistable:
            continue
        val = scope.get(v.name)
        if val is None:
            continue
        h.update(v.name.encode())
        h.update(np.ascontiguousarray(np.asarray(val)).tobytes())
    return h.hexdigest()


def _run_worker(args):
    """One trainer incarnation: resume → train → checkpoint each step."""
    import warnings

    import numpy as np  # noqa: F401

    _force_cpu()
    import paddle_tpu as fluid
    from paddle_tpu.resilience import checkpoint, faults, guard

    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    start_step = 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        info = checkpoint.try_load_latest_checkpoint(
            exe, args.ckpt_dir, main_program=main)
    if info is not None:
        start_step = int(info.state.get("next_step", info.step + 1))
        print("CHAOS_RESUME step=%d from=%s"
              % (start_step, os.path.basename(info.path)), flush=True)
        from paddle_tpu.observability import journal as _journal

        _journal.emit("resume", step=start_step,
                      source=os.path.basename(info.path))

    for k, (xb, yb) in enumerate(_batches(args.steps)):
        if k < start_step:
            continue
        faults.set_step(k)
        skipped_before = guard.stats.skipped_steps
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
        skipped = int(guard.stats.skipped_steps > skipped_before)
        print("CHAOS_STEP %d loss=%.8f skipped=%d"
              % (k, float(np.asarray(lv).reshape(())), skipped),
              flush=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            checkpoint.save_checkpoint(
                exe, args.ckpt_dir, main_program=main, step=k,
                state={"next_step": k + 1}, retain=3)
    digest = _param_digest(fluid.global_scope(), main)
    print("CHAOS_FINAL params_sha=%s skipped_total=%d"
          % (digest, guard.stats.skipped_steps), flush=True)
    print("CHAOS_OK", flush=True)
    return 0


def _oracle_digest(steps, skip_steps):
    """Fault-free replay in-process, not applying the skipped steps —
    the trajectory the recovered run must land on exactly."""
    import warnings

    _force_cpu()
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.resilience import faults

    faults.set_fault_spec("")
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        for k, (xb, yb) in enumerate(_batches(steps)):
            if k in skip_steps:
                continue
            faults.set_step(k)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        return _param_digest(fluid.global_scope(), main)


def _parse_worker_output(text, losses, skipped):
    final = None
    resumed = []
    for line in text.splitlines():
        if line.startswith("CHAOS_STEP "):
            parts = line.split()
            k = int(parts[1])
            losses[k] = float(parts[2].split("=")[1])
            if int(parts[3].split("=")[1]):
                skipped.add(k)
            else:
                # a later incarnation re-ran this step cleanly (e.g. the
                # skip happened just before a crash and the resumed
                # worker applied it): the newest verdict wins
                skipped.discard(k)
        elif line.startswith("CHAOS_FINAL "):
            final = line.split()[1].split("=")[1]
        elif line.startswith("CHAOS_RESUME "):
            resumed.append(int(line.split()[1].split("=")[1]))
    return final, resumed


def _run_driver(args):
    from paddle_tpu.resilience import retry as _retry

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="paddle_tpu_chaos_")
    from paddle_tpu.resilience import checkpoint as _ckpt

    existing = _ckpt.list_checkpoints(ckpt_dir)
    if existing and existing[0][0] >= args.steps - 1:
        print("chaos: ERROR — --ckpt-dir already holds a completed run "
              "(newest version: step %d); the worker would resume past "
              "every step.  Use a fresh --ckpt-dir." % existing[0][0],
              flush=True)
        return 2
    losses, skipped, final_sha = {}, set(), None
    all_resumes = []
    backoff = _retry.RetryPolicy(max_attempts=args.max_restarts + 1,
                                 base_delay=0.2, max_delay=2.0, seed=7)
    delays = backoff.delays()
    # the drill doubles as the observability acceptance scenario: every
    # incarnation journals into one shared dir, so the monitor CLI can
    # replay the fault -> guard-skip -> restore story afterwards
    from paddle_tpu.observability.metrics import telemetry_enabled

    telemetry_dir = args.telemetry_dir
    if telemetry_dir is None and telemetry_enabled():
        telemetry_dir = os.path.join(ckpt_dir, "telemetry")
    print("chaos: spec=%r steps=%d ckpt=%s telemetry=%s"
          % (args.spec, args.steps, ckpt_dir, telemetry_dir or "off"),
          flush=True)

    for incarnation in range(args.max_restarts + 1):
        env = dict(os.environ)
        env.update({
            "PADDLE_TPU_FAULT_SPEC": args.spec,
            # firing budgets span restarts: a worker_kill is ONE
            # preemption, not one per incarnation
            "PADDLE_TPU_FAULT_STATE_FILE":
                os.path.join(ckpt_dir, "fault_state.json"),
            "PADDLE_TPU_NAN_GUARD": "1",
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        })
        if telemetry_dir:
            env["PADDLE_TPU_TELEMETRY_DIR"] = telemetry_dir
        cmd = [sys.executable, "-m", "paddle_tpu.tools.chaos", "--worker",
               "--steps", str(args.steps), "--ckpt-dir", ckpt_dir]
        with tempfile.NamedTemporaryFile("w+", suffix=".log",
                                         delete=False) as logf:
            t0 = time.time()
            proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                    stderr=subprocess.STDOUT)
            try:
                rc = proc.wait(timeout=args.worker_timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                rc = "timeout"
            logf.seek(0)
            out = logf.read()
        final_sha, resumes = _parse_worker_output(out, losses, skipped)
        all_resumes.extend(resumes)
        print("chaos: incarnation %d rc=%s (%.1fs) steps_done=%d"
              % (incarnation, rc, time.time() - t0, len(losses)),
              flush=True)
        if rc == 0 and final_sha is not None:
            break
        if incarnation == args.max_restarts:
            print("chaos: FAIL — worker never completed within %d "
                  "restarts; last output:\n%s"
                  % (args.max_restarts, out[-2000:]), flush=True)
            return 2
        try:
            delay = next(delays)
        except StopIteration:
            delay = 1.0
        print("chaos: restarting worker (auto-resume) in %.2fs" % delay,
              flush=True)
        time.sleep(delay)

    missing = [k for k in range(args.steps) if k not in losses]
    if missing:
        print("chaos: FAIL — steps %s never ran" % missing, flush=True)
        return 2
    print("chaos: worker recovered; skipped steps=%s resumes=%s"
          % (sorted(skipped), all_resumes), flush=True)

    # the oracle replay is bookkeeping, not training: keep its steps and
    # checkpoints out of the telemetry the workers just wrote
    from paddle_tpu.observability import metrics as _metrics

    _metrics.set_telemetry_enabled(False)
    try:
        oracle = _oracle_digest(args.steps, skipped)
    finally:
        _metrics.set_telemetry_enabled(None)
    if oracle != final_sha:
        print("chaos: FAIL — final params %s != fault-free oracle %s "
              "(recovery diverged)" % (final_sha[:16], oracle[:16]),
              flush=True)
        return 1
    print("chaos: PASS — final params match the fault-free trajectory "
          "(sha %s)" % final_sha[:16], flush=True)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.chaos",
        description="Fault-injection chaos run: train, inject, recover, "
                    "verify against the fault-free trajectory.")
    parser.add_argument("--spec", default=os.environ.get(
        "PADDLE_TPU_FAULT_SPEC",
        "nan_grad@step=3;ckpt_write_fail@step=5;worker_kill@step=7"),
        help="fault spec (see resilience/faults.py grammar)")
    parser.add_argument("--steps", type=int, default=9)
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--telemetry-dir", default=None,
                        help="journal/metrics dir for the workers "
                             "(default: <ckpt-dir>/telemetry when "
                             "telemetry is on); tail it with "
                             "python -m paddle_tpu.tools.monitor")
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--worker-timeout", type=float, default=300.0,
                        help="seconds per worker incarnation (bounds "
                             "injected hangs)")
    parser.add_argument("--worker", action="store_true",
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)
    if args.worker:
        return _run_worker(args)
    return _run_driver(args)


if __name__ == "__main__":
    import numpy as np  # noqa: F401  (worker fast-fail if numpy absent)

    sys.exit(main())
