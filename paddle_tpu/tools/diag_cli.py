"""Shared CLI plumbing for the static-analysis tools
(``lint_program`` and ``analyze_program``): program loading from a
saved model dir or a bare serialized Program, and the diagnostics
emitter (text or ``--json``) with the ``--fail-on`` severity gate.

Both tools speak the same machine-readable format — a wrapper object
``{"schema": N, "diagnostics": [Diagnostic.to_dict(), ...], ...}`` —
so CI/monitor consumers can parse one schema forward-compatibly.
``DIAG_SCHEMA_VERSION`` bumps whenever a field changes meaning;
version 1 was the unversioned bare-array era.
"""

import json
import os
import sys

__all__ = ["DIAG_SCHEMA_VERSION", "add_program_args",
           "add_emitter_args", "load_program_arg",
           "emit_diagnostics", "severity_gate"]

#: version of the --json payload (v1: bare array, no stamp; v2: wrapper
#: object with "schema" + "diagnostics" keys, analyzer extras merged in)
DIAG_SCHEMA_VERSION = 2


def add_program_args(parser):
    """MODEL_DIR / --program-json / --model-filename trio."""
    parser.add_argument("model_dir", nargs="?", default=None,
                        help="directory written by save_inference_model")
    parser.add_argument("--model-filename", default=None,
                        help="program file inside model_dir "
                             "(default __model__)")
    parser.add_argument("--program-json", default=None,
                        help="operate on a bare serialized Program "
                             "instead of a model dir (no fetch targets)")


def add_emitter_args(parser, default_fail_on="ERROR"):
    """--json / --fail-on pair shared by both tools."""
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit machine-readable JSON instead of text")
    parser.add_argument("--fail-on", default=default_fail_on,
                        type=str.upper,
                        choices=["ERROR", "WARNING", "INFO"],
                        help="lowest severity that fails the run — "
                             "case-insensitive (default %s)"
                        % default_fail_on)


def load_program_arg(args):
    """Load (program, fetch_targets) per the shared program args.
    Raises whatever the loader raises — callers map that to exit 2."""
    from ..proto import load_program

    if args.program_json:
        return load_program(args.program_json), []
    model_path = os.path.join(args.model_dir,
                              args.model_filename or "__model__")
    prog = load_program(model_path)
    targets = []
    meta_path = os.path.join(args.model_dir, "__meta__.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            targets = json.load(f).get("fetch", [])
    return prog, targets


def emit_diagnostics(diags, as_json, extra_json=None, header=None):
    """Print diagnostics (a schema-stamped JSON wrapper object, or
    formatted text with an optional header line).  ``extra_json``: dict
    merged into the wrapper when the caller has more than diagnostics
    to report (the analyzer's cost/schedule/concurrency payload)."""
    from ..static_analysis import format_diagnostics

    if as_json:
        out = dict(extra_json) if extra_json is not None else {}
        out["schema"] = DIAG_SCHEMA_VERSION
        out["diagnostics"] = [d.to_dict() for d in diags]
        print(json.dumps(out, indent=2))
    elif diags:
        print(format_diagnostics(diags, header=header))
    else:
        print("clean: no findings")


def severity_gate(diags, fail_on, as_json):
    """Exit code for the run: 1 when any finding reaches ``fail_on``."""
    from ..static_analysis import Severity

    gate = Severity[fail_on]
    failing = [d for d in diags if d.severity >= gate]
    if failing:
        if not as_json:
            print("\n%d finding(s) at or above %s" % (len(failing), gate),
                  file=sys.stderr)
        return 1
    return 0
