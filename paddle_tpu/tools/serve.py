"""Serving CLI: ``python -m paddle_tpu.tools.serve``.

Loads one or more saved inference programs as co-resident tenants of a
:class:`~paddle_tpu.serving.PredictorServer` (the scope-overlap proof
gates the placement, the zero-sync certificate gates the hot loop) and
drives the built-in load generator against them::

    # one tenant, defaults
    python -m paddle_tpu.tools.serve /models/mnist --requests 200

    # two co-resident tenants, explicit buckets + SLA, JSON report
    python -m paddle_tpu.tools.serve \\
        --tenants mnist=/models/mnist,bert=/models/bert \\
        --buckets 1,2,4,8 --max-in-flight 3 --sla-ms 500 \\
        --qps 100 --requests 500 --json

The serving hot loop runs under ``PADDLE_TPU_STRICT_SYNC=1`` (set by
this CLI unless already set): any host-sync construct in a tenant
program is a hard startup error, not a latency cliff discovered in
production.  ``--certify-zero-sync`` prints each tenant's certificate
and exits — the preflight check.  Exit codes: 0 OK, 1 a gate failed or
the run shed/rejected with ``--fail-on-shed``, 2 bad arguments.
"""

import argparse
import json
import sys

__all__ = ["main"]


def _parse_tenants(args):
    tenants = []
    if args.tenants:
        for part in args.tenants.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    "--tenants wants name=model_dir[,name=dir...], "
                    "got %r" % part)
            name, path = part.split("=", 1)
            tenants.append((name.strip(), path.strip()))
    for i, path in enumerate(args.model_dir):
        tenants.append(("tenant%d" % i if len(args.model_dir) > 1
                        or args.tenants else "default", path))
    if not tenants:
        raise ValueError("no tenants: pass MODEL_DIR or --tenants")
    return tenants


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.serve",
        description="continuous-batching predictor server + load "
                    "generator over saved inference programs")
    ap.add_argument("model_dir", nargs="*",
                    help="saved inference model dir(s) "
                         "(save_inference_model output)")
    ap.add_argument("--tenants", default=None, metavar="N=DIR,...",
                    help="named tenants: mnist=/m/mnist,bert=/m/bert")
    ap.add_argument("--buckets", default=None, metavar="1,2,4,8",
                    help="padded batch-size buckets (default: env "
                         "PADDLE_TPU_SERVING_BUCKETS or 1,2,4,8)")
    ap.add_argument("--bucket-cap", type=int, default=None,
                    help="max bucket count (jit signatures per tenant)")
    ap.add_argument("--max-in-flight", type=int, default=2,
                    help="dispatched batches kept un-synced (default 2)")
    ap.add_argument("--sla-ms", type=float, default=None,
                    help="default per-request deadline; late requests "
                         "are shed, not served stale")
    ap.add_argument("--queue-cap", type=int, default=256,
                    help="bounded queue size; beyond it submits are "
                         "rejected (backpressure, default 256)")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="load-generator offered QPS (default 100)")
    ap.add_argument("--requests", type=int, default=200,
                    help="load-generator request count (default 200)")
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per generated request (default 1)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-verify", action="store_true",
                    help="skip the scope-overlap proof and async-path "
                         "verification (NOT for production)")
    ap.add_argument("--certify-zero-sync", action="store_true",
                    help="print each tenant's zero-sync certificate "
                         "and exit (0 all pass, 1 any fail)")
    ap.add_argument("--fail-on-shed", action="store_true",
                    help="exit 1 if any request was shed or rejected")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    args = ap.parse_args(argv)

    import os

    # the serving hot loop runs strict: a host-sync construct is a
    # startup error (the zero-sync certificate), never a latency cliff
    os.environ.setdefault("PADDLE_TPU_STRICT_SYNC", "1")

    import numpy as np

    from .. import serving
    from ..inference import AnalysisConfig, AnalysisPredictor
    from ..static_analysis.verifier import VerifyError

    try:
        tenant_dirs = _parse_tenants(args)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2

    preds = {}
    for name, path in tenant_dirs:
        preds[name] = AnalysisPredictor(AnalysisConfig(model_dir=path))

    try:
        server = serving.PredictorServer(
            preds, max_in_flight=args.max_in_flight, sla_ms=args.sla_ms,
            queue_cap=args.queue_cap, buckets=args.buckets,
            bucket_cap=args.bucket_cap, verify=not args.no_verify,
            auto_start=False)
    except VerifyError as exc:
        print("placement/hot-loop verification failed:\n%s" % exc,
              file=sys.stderr)
        return 1

    if args.certify_zero_sync:
        ok = True
        for name, cert in server.certificates.items():
            print(cert.format())
            ok = ok and cert.ok
        return 0 if ok else 1

    rng = np.random.RandomState(args.seed)
    samplers = {
        name: serving.make_feed_sampler(pred, rows=args.rows, rng=rng)
        for name, pred in preds.items()
    }
    server.warmup({
        name: serving.make_feed_sampler(pred, rows=1, rng=rng)()
        for name, pred in preds.items()})
    server.start()
    try:
        report = serving.run_load(
            server, samplers, qps=args.qps, requests=args.requests,
            sla_ms=args.sla_ms)
    finally:
        server.close()
    stats = server.stats()
    report["buckets"] = stats["buckets"]
    report["zero_sync"] = stats["zero_sync"]
    report["dispatched_batches"] = stats["dispatches"]
    report["jit_entries"] = {
        name: len(pred._exe._cache) for name, pred in preds.items()}

    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print("served %d requests over %d tenant(s): "
              "p50=%.2fms p99=%.2fms qps=%.1f shed=%d rejected=%d"
              % (report["completed"], len(preds),
                 report["p50_ms"] or 0.0, report["p99_ms"] or 0.0,
                 report["qps"], report["shed"], report["rejected"]))
        print("buckets=%s zero_sync=%s jit_entries=%s"
              % (report["buckets"], report["zero_sync"],
                 report["jit_entries"]))
    if args.fail_on_shed and (report["shed"] or report["rejected"]
                              or report["failed"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
