"""Whole-program static analysis from the command line.

Usage::

    python -m paddle_tpu.tools.analyze_program MODEL_DIR [options]
    python -m paddle_tpu.tools.analyze_program --program-json prog.json \
        --workers w0.json w1.json --hbm-budget 16G --batch 64

Loads a serialized Program (same inputs as ``lint_program``) and runs
``Program.analyze()``: the abstract interpretation, the static
FLOP/byte/ICI cost model with the liveness-based peak-memory estimate,
the per-ring collective schedule, and — when ``--workers`` supplies the
N transpiled per-worker programs — the cross-worker collective schedule
deadlock-freedom proof.  Prints the cost/memory table (or ``--json``
for the full machine-readable report; same emitter as the lint CLI)
and exits:

* 0 — no findings at or above ``--fail-on`` (default ERROR)
* 1 — findings at or above the gate (CI-friendly)
* 2 — could not load a program

``--bench-json PATH`` additionally writes the BENCH-style static cost
metrics so perf PRs can cite the static baseline next to measured
numbers.

``--concurrency`` adds the whole-program concurrency battery
(``race-inflight-write``, ``donated-buffer-live-read``,
``scope-overlap``, ``sync-in-hot-loop``) at ``--max-in-flight K``;
``--certify-zero-sync`` prints the zero-sync certificate for the hot
loop and fails the gate if any host-sync point remains; ``--coresident
P.json ...`` proves scope isolation against programs that will share
the Executor.
"""

import argparse
import sys

from .diag_cli import (add_emitter_args, add_program_args,
                       emit_diagnostics, load_program_arg,
                       severity_gate)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.analyze_program",
        description="Static cost/memory/collective-schedule analysis "
                    "of a saved paddle_tpu program.")
    add_program_args(parser)
    parser.add_argument("--workers", nargs="+", default=None,
                        metavar="PROG_JSON",
                        help="serialized per-worker main programs (ALL "
                             "workers, in rank order) — enables the "
                             "cross-worker schedule proof")
    parser.add_argument("--nranks", type=int, default=None,
                        help="worker count for the sharding/ICI model "
                             "(default: len(--workers) or the recorded "
                             "trainer count)")
    parser.add_argument("--batch", type=int, default=None,
                        help="what -1 (batch) dims resolve to (default "
                             "PADDLE_TPU_ANALYZE_BATCH or 1)")
    parser.add_argument("--hbm-budget", default=None,
                        help="peak-memory budget (bytes; K/M/G suffix) "
                             "— overrides PADDLE_TPU_HBM_BUDGET")
    parser.add_argument("--top", type=int, default=12,
                        help="rows in the top-ops-by-FLOPs table")
    parser.add_argument("--bench-json", default=None, metavar="PATH",
                        help="also write BENCH-style static cost "
                             "metric lines to PATH")
    parser.add_argument("--concurrency", action="store_true",
                        help="run the whole-program concurrency "
                             "analyzer: in-flight race detection, "
                             "donated-buffer hazards, host-sync audit")
    parser.add_argument("--max-in-flight", type=int, default=None,
                        metavar="K",
                        help="in-flight step depth the race analysis "
                             "assumes (default: the program's recorded "
                             "depth, PADDLE_TPU_MAX_IN_FLIGHT, or 2)")
    parser.add_argument("--certify-zero-sync", action="store_true",
                        help="prove the program's hot loop issues no "
                             "host syncs (prints the certificate; any "
                             "violation is an ERROR naming the "
                             "introducing API)")
    parser.add_argument("--coresident", nargs="+", default=None,
                        metavar="PROG_JSON",
                        help="serialized programs that will share this "
                             "program's Executor/scope — proves their "
                             "scope-variable footprints are disjoint")
    parser.add_argument("--plan", default=None, metavar="CLUSTER_SPEC",
                        help="run the auto-parallelism planner against "
                             "this ClusterSpec (JSON file, inline JSON, "
                             "or a bare chip count) and print the "
                             "candidate table — predicted step cost, "
                             "ICI bytes, peak HBM, deadlock verdict, "
                             "chosen/rejected reason — without "
                             "executing anything")
    parser.add_argument("--overlap", action="store_true",
                        help="run the fusion + overlap-scheduler "
                             "rewrite (ISSUE 16) and print the "
                             "per-window table: bucket, start/wait op "
                             "coords, window compute ms, wire ms, "
                             "exposed ms, verdict — priced against "
                             "the --plan ClusterSpec when given, else "
                             "the generic default chip")
    add_emitter_args(parser)
    args = parser.parse_args(argv)
    if not args.model_dir and not args.program_json:
        parser.error("need MODEL_DIR or --program-json")

    from ..proto import load_program
    from ..static_analysis.cost import parse_size

    try:
        program, targets = load_program_arg(args)
        workers = None
        if args.workers:
            workers = [load_program(p) for p in args.workers]
        coresident = None
        if args.coresident:
            coresident = [(p, load_program(p)) for p in args.coresident]
    except Exception as e:
        print("error: could not load program: %s" % e, file=sys.stderr)
        return 2

    budget = parse_size(args.hbm_budget) if args.hbm_budget else None
    report = program.analyze(
        targets=targets, workers=workers, nranks=args.nranks,
        batch_size=args.batch, hbm_budget=budget,
        concurrency=args.concurrency, max_in_flight=args.max_in_flight,
        coresident=coresident,
        certify_zero_sync=args.certify_zero_sync)

    plan_result = None
    if args.plan:
        from ..parallel.planner import ClusterSpec, auto_transpile

        try:
            spec = ClusterSpec.coerce(args.plan)
        except Exception as e:
            print("error: bad --plan cluster spec: %s" % e,
                  file=sys.stderr)
            return 2
        plan_result = auto_transpile(program, spec, targets=targets,
                                     batch_size=args.batch)

    overlap_info = overlap_lines = None
    if args.overlap:
        from ..static_analysis.cost import (estimate_cost,
                                            overlap_window_table)
        from ..static_analysis.fusion import resolve_fused_program

        resolved, _ = resolve_fused_program(program, targets=targets)
        cost_r = estimate_cost(resolved, nranks=args.nranks,
                               targets=targets, batch_size=args.batch,
                               budget=budget)
        price_kw = {}
        if plan_result is not None:
            c = plan_result.cluster
            price_kw = {"peak_tflops": c.peak_tflops,
                        "hbm_gbps": c.hbm_gbps,
                        "ici_gbps": c.ici_gbps}
        rows = overlap_window_table(cost_r, **price_kw)
        ovr = getattr(resolved, "_overlap_report", None)
        overlap_info = {"windows": rows,
                        "report": ovr.to_dict() if ovr else None}
        overlap_lines = ["overlap windows (%d):" % len(rows),
                         "  %-6s %-10s %-10s %5s %5s %12s %10s %11s  %s"
                         % ("bucket", "start", "wait", "vars", "quant",
                            "compute ms", "wire ms", "exposed ms",
                            "verdict")]
        for r in rows:
            overlap_lines.append(
                "  %-6d %-10s %-10s %5d %5s %12.4f %10.4f %11.4f  %s"
                % (r["bucket"], tuple(r["start"]), tuple(r["wait"]),
                   r["vars"], "int8" if r["quant"] else "-",
                   r["window_compute_ms"], r["wire_ms"],
                   r["exposed_ms"], r["verdict"]))
        if ovr is not None:
            overlap_lines.append(ovr.format())

    if args.as_json:
        extra = {k: v for k, v in report.to_dict().items()
                 if k != "diagnostics"}
        if plan_result is not None:
            extra["plan"] = plan_result.to_dict()
            tier_rows = plan_result.tier_wire_table()
            if tier_rows is not None:
                extra["plan"]["tier_wire_table"] = tier_rows
        if overlap_info is not None:
            extra["overlap"] = overlap_info
        emit_diagnostics(report.diagnostics, True, extra_json=extra)
    else:
        print(report.format(top_ops=args.top))
        if plan_result is not None:
            print(plan_result.format_table())
            tier_rows = plan_result.tier_wire_table()
            if tier_rows:
                print("per-tier wire (winner's realized schedule):")
                print("  %-8s %-5s %14s %10s %6s"
                      % ("ring", "tier", "bytes", "wire ms", "quant"))
                for r in tier_rows:
                    print("  %-8s %-5s %14d %10.4f %6s"
                          % (r["ring"], r["tier"], r["bytes"], r["ms"],
                             "int8" if r["quant"] else "-"))
        if overlap_lines is not None:
            print("\n".join(overlap_lines))

    if args.bench_json:
        with open(args.bench_json, "w") as f:
            f.write(report.cost.bench_json() + "\n")

    return severity_gate(report.diagnostics, args.fail_on, args.as_json)


if __name__ == "__main__":
    sys.exit(main())
