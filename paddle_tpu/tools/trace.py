"""Trace reconstruction: ``python -m paddle_tpu.tools.trace DIR``.

Merges every ``trace-r<rank>-<pid>.jsonl`` a job's processes wrote into
``PADDLE_TPU_TELEMETRY_DIR`` (torn-write tolerant, like the journal
reader), stitches the spans back into per-trace trees, and computes each
trace's *critical path* — the chain of spans that actually bounded its
wall time, attributed per phase::

    p99 request = 1.2ms serving.queue_wait + 0.3ms serving.pad
                + 4.1ms serving.device + 2.0ms serving.sync

Modes::

    python -m paddle_tpu.tools.trace DIR                 # slowest traces
    python -m paddle_tpu.tools.trace DIR --slowest 10
    python -m paddle_tpu.tools.trace DIR --id 3f2a       # one trace tree
    python -m paddle_tpu.tools.trace DIR --serving       # phase p50/p99
    python -m paddle_tpu.tools.trace DIR --elastic       # recovery story
    python -m paddle_tpu.tools.trace DIR --flights       # hang postmortems
    python -m paddle_tpu.tools.trace DIR --chrome out.json
    python -m paddle_tpu.tools.trace DIR --serving \\
        --alert 'queue_wait_p99_ms>5'                    # exit 1 if hot

``--id`` accepts a trace-id prefix (the 8-char form the monitor and the
journal print is enough).  ``--elastic`` finds the trace that crossed a
worker-lost recovery and prints the chain — one trace covering
worker-lost→agree→replan→reshard→restore→resume across every surviving
rank.  ``--alert`` reuses the monitor's expression grammar against the
``--json`` fields of the selected view; exit codes 0 OK, 1 tripped,
2 no data.
"""

import argparse
import json
import sys
from collections import OrderedDict

from ..observability import tracing as _tracing

__all__ = ["group_traces", "trace_summary", "critical_path",
           "serving_stats", "elastic_traces", "main"]

#: cross-process wall clocks drift; child ends this close to (or past)
#: the parent cursor still count as on the critical path (seconds)
_CLOCK_SKEW_S = 5e-4

# span names whose presence marks a trace as an elastic-recovery story
_ELASTIC_MARKERS = ("elastic.recover", "elastic.replan", "elastic.reshard")


def _ts(rec):
    return float(rec.get("ts") or 0.0)


def _dur_s(rec):
    d = rec.get("dur_ms")
    return None if d is None else float(d) / 1000.0


def _end(rec):
    d = _dur_s(rec)
    return _ts(rec) + (d or 0.0)


def group_traces(records):
    """``{trace_id: [span records, ts-sorted]}`` in first-seen order."""
    traces = OrderedDict()
    for rec in records:
        tid = rec.get("trace")
        if tid:
            traces.setdefault(tid, []).append(rec)
    for spans in traces.values():
        spans.sort(key=_ts)
    return traces


def _index(spans):
    """(by_id, children) for one trace's records; duplicate span ids
    (a span record re-read from ring AND file) keep the first."""
    by_id, children = {}, {}
    for rec in spans:
        sid = rec.get("span")
        if sid and sid not in by_id:
            by_id[sid] = rec
    for rec in by_id.values():
        parent = rec.get("parent")
        if parent in by_id:
            children.setdefault(parent, []).append(rec)
    return by_id, children


def _roots(spans, by_id):
    return [rec for rec in by_id.values()
            if rec.get("parent") not in by_id]


def critical_path(spans):
    """The spans that bounded this trace's wall time, with the self-time
    each contributed.  Walks the tree backwards from the root's end:
    at every node the child ending latest (within clock skew) is on the
    path for its window, and whatever the children don't cover is the
    node's own time.  Returns ``[(record, self_ms)]`` in start order —
    their self-times sum to (about) the root duration."""
    by_id, children = _index(spans)
    closed_roots = [r for r in _roots(spans, by_id)
                    if _dur_s(r) is not None]
    if not closed_roots:
        return []
    root = max(closed_roots, key=lambda r: _dur_s(r) or 0.0)
    segments = []

    def walk(rec, window_hi):
        lo = _ts(rec)
        cursor = min(_end(rec), window_hi)
        self_s = 0.0
        kids = [k for k in children.get(rec["span"], ())
                if _dur_s(k) is not None and _end(k) > lo]
        kids.sort(key=_end, reverse=True)
        for kid in kids:
            if _end(kid) > cursor + _CLOCK_SKEW_S:
                continue  # concurrent sibling already covered
            self_s += max(cursor - _end(kid), 0.0)
            walk(kid, min(_end(kid), cursor))
            cursor = min(_ts(kid), cursor)
            if cursor <= lo:
                break
        self_s += max(cursor - lo, 0.0)
        segments.append((rec, self_s * 1000.0))

    walk(root, _end(root))
    # start order; an enclosing span starting at the same instant as
    # its child (queue_wait at t0 of the request) sorts first
    segments.sort(
        key=lambda seg: (_ts(seg[0]), -(_dur_s(seg[0]) or 0.0)))
    return segments


def _path_breakdown(segments):
    """Critical-path self-times pooled by span name, start order."""
    order, totals = [], {}
    for rec, self_ms in segments:
        name = rec.get("name", "?")
        if name not in totals:
            order.append(name)
            totals[name] = 0.0
        totals[name] += self_ms
    return [(name, totals[name]) for name in order]


def trace_summary(trace_id, spans):
    """One trace's headline dict (root, duration, ranks, worst status)."""
    by_id, _ = _index(spans)
    roots = _roots(spans, by_id)
    closed = [r for r in roots if _dur_s(r) is not None]
    root = (max(closed, key=lambda r: _dur_s(r) or 0.0) if closed
            else (roots[0] if roots else spans[0]))
    bad = sorted({r.get("status", "ok") for r in spans
                  if r.get("status", "ok") != "ok"})
    return {
        "trace": trace_id,
        "root": root.get("name", "?"),
        "dur_ms": root.get("dur_ms"),
        "spans": len(by_id),
        "ranks": sorted({r.get("rank", 0) for r in spans}),
        "status": bad[0] if bad else "ok",
    }


def _percentile(values, p):
    if not values:
        return None
    values = sorted(values)
    if len(values) == 1:
        return values[0]
    idx = max(p, 0.0) / 100.0 * (len(values) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(values) - 1)
    return values[lo] + (idx - lo) * (values[hi] - values[lo])


def serving_stats(traces):
    """Aggregate serving.request traces: request latency and per-phase
    critical-path p50/p99 — the "where does the p99 go" answer."""
    durations, phases = [], {}
    for spans in traces.values():
        segments = critical_path(spans)
        if not segments or segments[0][0].get("name") != "serving.request":
            continue
        root = segments[0][0]
        if root.get("dur_ms") is None:
            continue
        durations.append(float(root["dur_ms"]))
        for name, self_ms in _path_breakdown(segments):
            phases.setdefault(name, []).append(self_ms)
    if not durations:
        return None
    stats = {"requests": len(durations),
             "request_p50_ms": _percentile(durations, 50.0),
             "request_p99_ms": _percentile(durations, 99.0),
             "phases": {}}
    for name, vals in sorted(phases.items()):
        stats["phases"][name] = {"p50_ms": _percentile(vals, 50.0),
                                 "p99_ms": _percentile(vals, 99.0)}
    # flat aliases so --alert 'queue_wait_p99_ms>5' just works
    for name, alias in (("serving.queue_wait", "queue_wait"),
                        ("serving.pad", "pad"),
                        ("serving.dispatch", "dispatch"),
                        ("serving.device", "device"),
                        ("serving.sync", "sync"),
                        # decode tenants: prompt ingest vs per-token
                        # generation — the TTFT / steady-state split
                        ("serving.prefill", "prefill"),
                        # disaggregated serving: finished-prefill ->
                        # decode-slot block handoff, the third TTFT leg
                        ("serving.kv_handoff", "kv_handoff"),
                        ("serving.decode", "decode")):
        if name in stats["phases"]:
            stats["%s_p50_ms" % alias] = stats["phases"][name]["p50_ms"]
            stats["%s_p99_ms" % alias] = stats["phases"][name]["p99_ms"]
    return stats


def elastic_traces(traces):
    """Traces that crossed a worker-lost recovery, slowest first."""
    out = []
    for tid, spans in traces.items():
        names = {r.get("name") for r in spans}
        if names.intersection(_ELASTIC_MARKERS):
            out.append((tid, spans))
    out.sort(key=lambda item: -(trace_summary(*item)["dur_ms"] or 0.0))
    return out


def _fmt_ms(v):
    return "-" if v is None else "%.3gms" % v


def _render_breakdown(segments, head):
    parts = ["%.3gms %s" % (ms, name)
             for name, ms in _path_breakdown(segments)]
    return "%s = %s" % (head, " + ".join(parts)) if parts else head


def _render_tree(spans, out):
    by_id, children = _index(spans)
    crit = {rec["span"] for rec, _ in critical_path(spans)}

    def show(rec, depth):
        mark = "*" if rec.get("span") in crit else " "
        status = rec.get("status", "ok")
        out.append("  %s%s%s r%s %s  %s%s" % (
            mark, "  " * depth, rec.get("name", "?"),
            rec.get("rank", 0), _fmt_ms(rec.get("dur_ms")),
            "" if status == "ok" else "[%s] " % status,
            "open " if rec.get("open") else ""))
        kids = sorted(children.get(rec.get("span"), ()), key=_ts)
        for kid in kids:
            show(kid, depth + 1)

    for root in sorted(_roots(spans, by_id), key=_ts):
        show(root, 0)


def _render_trace(tid, spans, out):
    info = trace_summary(tid, spans)
    out.append("trace %s  root=%s  %s  spans=%d  ranks=%s%s" % (
        tid[:16], info["root"], _fmt_ms(info["dur_ms"]), info["spans"],
        ",".join(str(r) for r in info["ranks"]),
        "" if info["status"] == "ok" else "  status=%s" % info["status"]))
    segments = critical_path(spans)
    if segments:
        out.append("  critical path: " + _render_breakdown(
            segments, "%s %s" % (info["root"],
                                 _fmt_ms(info["dur_ms"]))))
    _render_tree(spans, out)


def _elastic_report(traces, out):
    """The chaos acceptance view: ONE trace spanning the recovery."""
    found = elastic_traces(traces)
    if not found:
        out.append("no elastic-recovery trace found (no elastic.recover"
                   "/replan/reshard spans)")
        return None
    tid, spans = found[0]
    info = trace_summary(tid, spans)
    chain = [r for r in spans
             if r.get("name") in ("elastic.worker", "elastic.recover",
                                  "elastic.agree", "elastic.replan",
                                  "elastic.restore", "elastic.reshard")]
    chain.sort(key=_ts)
    out.append("elastic recovery trace %s  ranks=%s  spans=%d" % (
        tid, ",".join(str(r) for r in info["ranks"]), info["spans"]))
    seen = []
    for rec in chain:
        step = rec.get("attrs", {}).get("step")
        seen.append("%s(r%s%s)" % (
            rec.get("name", "?").replace("elastic.", ""),
            rec.get("rank", 0),
            "@%s" % step if step is not None else ""))
    out.append("  chain: " + " -> ".join(seen[:24])
               + (" ..." if len(seen) > 24 else ""))
    recs = [r for r in spans if r.get("name") == "elastic.recover"
            and r.get("dur_ms") is not None]
    if recs:
        rec = max(recs, key=lambda r: r["dur_ms"])
        # critical path over the recover span's own subtree, so a
        # post-recovery step can't masquerade as the root
        _, children = _index(spans)
        subtree, frontier = [], [rec]
        while frontier:
            node = frontier.pop()
            subtree.append(node)
            frontier.extend(children.get(node.get("span"), ()))
        segments = critical_path(subtree)
        out.append("  recovery critical path: " + _render_breakdown(
            segments, "recover %s" % _fmt_ms(rec["dur_ms"])))
    stats = {"trace": tid, "ranks": info["ranks"], "spans": info["spans"],
             "recover_ms": recs[0]["dur_ms"] if recs else None}
    return stats


def _flights_report(dirname, out):
    flights = _tracing.read_flight_records(dirname)
    if not flights:
        out.append("no flight records under %s" % dirname)
        return flights
    for rec in flights:
        out.append("flight r%s pid=%s  %s" % (
            rec.get("rank", "?"), rec.get("pid", "?"),
            rec.get("reason", "")))
        for span in rec.get("open_spans", []):
            out.append("  OPEN %s r%s %s  trace=%s" % (
                span.get("name", "?"), span.get("rank", 0),
                _fmt_ms(span.get("dur_ms")),
                str(span.get("trace"))[:8]))
        out.append("  recent: " + " -> ".join(
            s.get("name", "?")
            for s in rec.get("recent_spans", [])[-8:]))
    return flights


def _write_chrome(records, path):
    events = _tracing.spans_to_chrome_events(records)
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.trace",
        description="reconstruct distributed traces from a "
                    "PADDLE_TPU_TELEMETRY_DIR")
    ap.add_argument("dir", help="telemetry dir (or one trace-*.jsonl)")
    ap.add_argument("--slowest", type=int, default=5, metavar="K",
                    help="how many traces to detail (default 5)")
    ap.add_argument("--id", default=None, metavar="TRACE",
                    help="show one trace (id prefix ok)")
    ap.add_argument("--serving", action="store_true",
                    help="aggregate serving.request phase breakdown")
    ap.add_argument("--elastic", action="store_true",
                    help="reconstruct the worker-lost recovery trace")
    ap.add_argument("--flights", action="store_true",
                    help="list flight-recorder postmortems")
    ap.add_argument("--chrome", default=None, metavar="OUT",
                    help="export all spans as a chrome://tracing file "
                         "(flow arrows across threads/ranks; load "
                         "alongside a profiler timeline)")
    ap.add_argument("--alert", action="append", default=[],
                    metavar="EXPR",
                    help="e.g. 'queue_wait_p99_ms>5' with --serving, "
                         "'recover_ms>5000' with --elastic; exit 1 "
                         "tripped, 2 no data (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    records = _tracing.read_traces(args.dir)
    traces = group_traces(records)
    out, stats = [], None

    if args.flights:
        flights = _flights_report(args.dir, out)
        stats = {"flights": len(flights)}
    elif args.serving:
        stats = serving_stats(traces)
        if stats is None:
            out.append("no closed serving.request traces under %s"
                       % args.dir)
        else:
            out.append("serving: %d requests  p50=%s  p99=%s" % (
                stats["requests"], _fmt_ms(stats["request_p50_ms"]),
                _fmt_ms(stats["request_p99_ms"])))
            parts = ["%s %s" % (_fmt_ms(v["p99_ms"]), name)
                     for name, v in stats["phases"].items()
                     if name != "serving.request"]
            out.append("  p99 request = " + " + ".join(parts))
    elif args.elastic:
        stats = _elastic_report(traces, out)
    elif args.id:
        matches = [tid for tid in traces if tid.startswith(args.id)]
        if not matches:
            out.append("no trace matching %r under %s"
                       % (args.id, args.dir))
        else:
            for tid in matches:
                _render_trace(tid, traces[tid], out)
            stats = trace_summary(matches[0], traces[matches[0]])
    else:
        out.append("%d spans, %d traces under %s"
                   % (len(records), len(traces), args.dir))
        ranked = sorted(
            traces.items(),
            key=lambda item: -(trace_summary(*item)["dur_ms"] or 0.0))
        for tid, spans in ranked[:max(args.slowest, 0)]:
            _render_trace(tid, spans, out)
        stats = {"spans": len(records), "traces": len(traces)}

    if args.chrome:
        n = _write_chrome(records, args.chrome)
        out.append("wrote %d chrome events to %s" % (n, args.chrome))

    if args.json:
        print(json.dumps(stats if stats is not None else {},
                         sort_keys=True, default=str))
    else:
        print("\n".join(out))

    code = 0
    for expr in args.alert:
        from .monitor import check_alert

        c, msg = check_alert(stats or {}, expr)
        print(msg, file=sys.stderr)
        code = max(code, c)
    if not args.alert and not records and not args.flights:
        print("no trace files under %s" % args.dir, file=sys.stderr)
        return 2
    return code


if __name__ == "__main__":
    sys.exit(main())
