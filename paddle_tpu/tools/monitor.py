"""Live telemetry monitor: ``python -m paddle_tpu.tools.monitor DIR``.

Tails a ``PADDLE_TPU_TELEMETRY_DIR`` produced by a running (or finished)
job and reports the operator view: step progress and rate, p50/p99 step
latency, NaN-guard skip rate, predicted-vs-measured drift, checkpoint
age, and per-rank liveness — including the wedged-but-alive case where
heartbeats stay fresh but the step counter inside them froze.

Everything is read-only and torn-write tolerant: journals via
:func:`~paddle_tpu.observability.journal.read_journal` (skips torn
lines), metrics via the atomic ``metrics-r*.json`` snapshots, liveness
via :func:`~paddle_tpu.resilience.watchdog.read_heartbeat`.

Modes::

    python -m paddle_tpu.tools.monitor DIR                # live tail
    python -m paddle_tpu.tools.monitor DIR --once         # one report
    python -m paddle_tpu.tools.monitor DIR --once --json  # machine form
    python -m paddle_tpu.tools.monitor DIR --once \\
        --alert 'p99_step_ms>50'                          # exit 1 if hot
    python -m paddle_tpu.tools.monitor DIR --once \\
        --alert 'quant_error>0.05'        # int8 collectives degrading

Alert expressions are ``<field><op><number>`` with op one of
``> >= < <= == !=`` against any numeric field of the ``--json`` output
(dotted paths allowed, e.g. ``drift.step_ms``; ``quant_error`` is the
worst per-bucket measured quantization error of the int8 collectives).
Exit codes: 0 OK, 1 alert tripped, 2 no data for the alerted field (or
an empty dir).
"""

import argparse
import json
import os
import re
import sys
import time

__all__ = ["collect_status", "check_alert", "main"]

#: a heartbeat older than this many seconds marks the rank dead
DEFAULT_STALE_S = 15.0
#: fresh beats but no step progress for this long marks the rank wedged
DEFAULT_WEDGE_S = 30.0

_HB_RE = re.compile(r"^hb-(\d+)$")
_ALERT_RE = re.compile(
    r"^\s*([A-Za-z_][\w.]*)\s*(>=|<=|==|!=|>|<)\s*(-?[\d.]+)\s*$")

# the journal kinds an incident reads as a story, in the order the
# chaos acceptance scenarios expect them: fault -> skip -> restore, the
# elastic shrink chain worker-lost -> replan -> reshard -> resume, and
# the grow chain join-request -> admitted -> warmup -> replan ->
# reshard -> resume (race-detected: a concurrency gate tripped before
# dispatch; dispatcher-died: the serving dispatch thread crashed;
# autoscale: an SLO-policy decision)
_SEQUENCE_KINDS = ("fault-injected", "guard-skip", "race-detected",
                   "dispatcher-died", "worker-lost", "replan",
                   "reshard", "checkpoint-saved",
                   "checkpoint-loaded", "join-request", "admitted",
                   "warmup", "autoscale", "resume")

_MEMBER_RE = re.compile(r"^member-(\d{8})\.json$")
_JOIN_RE = re.compile(r"^join-(\d{8})-r(\d+)\.json$")


def _elastic_fs_view(hb_dir, ranks):
    """Elastic membership read straight off the rendezvous dir: the
    newest ``member-*`` record's epoch/world, plus how many *live*
    non-member ranks have a join request posted at (or past) it.  A
    dead job's leftovers still render — gauges need a live snapshot,
    files do not."""
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return {}
    epochs = [int(m.group(1))
              for m in (_MEMBER_RE.match(n) for n in names) if m]
    joins = [(int(m.group(1)), int(m.group(2)))
             for m in (_JOIN_RE.match(n) for n in names) if m]
    if not epochs and not joins:
        return {}
    out = {}
    members = set()
    if epochs:
        newest = max(epochs)
        out["epoch"] = newest
        try:
            with open(os.path.join(
                    hb_dir, "member-%08d.json" % newest)) as f:
                rec = json.load(f)
            members = set(rec.get("members") or [])
            out["world"] = len(members) or None
        except (OSError, ValueError):
            pass  # torn write: epoch still stands, world unknown
    floor = max(epochs) if epochs else 0
    pending = set()
    for epoch, rank in joins:
        if epoch < floor or rank in members:
            continue
        r = ranks.get(str(rank))
        if r is not None and r["alive"] and not r["done"]:
            pending.add(rank)
    out["pending"] = len(pending)
    return out


def _read_snapshots(dirname):
    """Newest-first list of parsed ``metrics-r*.json`` snapshots."""
    snaps = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return []
    for name in names:
        if not (name.startswith("metrics-r") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(dirname, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue  # torn/raced write: the next refresh will have it
        if isinstance(snap, dict) and isinstance(snap.get("metrics"),
                                                 dict):
            snaps.append(snap)
    snaps.sort(key=lambda s: s.get("ts", 0.0), reverse=True)
    return snaps


def _merged_metrics(snaps):
    """Merge per-process snapshots: counters sum, gauges take the
    newest writer's value, histograms pool buckets/sums/counts."""
    merged = {}
    for snap in snaps:  # newest first: first writer wins for gauges
        for key, m in snap["metrics"].items():
            kind = m.get("type")
            have = merged.get(key)
            if have is None:
                merged[key] = dict(m)
            elif kind == "counter":
                have["value"] = have.get("value", 0) + m.get("value", 0)
            elif kind == "histogram":
                have["count"] = have.get("count", 0) + m.get("count", 0)
                have["sum"] = have.get("sum", 0.0) + m.get("sum", 0.0)
                if len(have.get("counts", [])) == len(m.get("counts", [])):
                    have["counts"] = [a + b for a, b in
                                      zip(have["counts"], m["counts"])]
                for f, pick in (("min", min), ("max", max)):
                    if m.get(f) is not None:
                        have[f] = (m[f] if have.get(f) is None
                                   else pick(have[f], m[f]))
    return merged


def _hist_percentile(h, p):
    """Monitor-grade percentile from a merged histogram dict (same
    linear interpolation as ``Histogram.percentile``)."""
    count = h.get("count", 0)
    if not count:
        return None
    target = max(p, 0.0) / 100.0 * count
    buckets, counts = h.get("buckets", []), h.get("counts", [])
    cum, lo = 0, 0.0
    for ub, c in zip(buckets, counts):
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            est = lo + frac * (ub - lo)
            hi = h.get("max")
            return min(est, hi) if hi is not None else est
        cum += c
        lo = ub
    return h.get("max")


def _metric_value(merged, name, labels=None):
    """Sum of matching counter/gauge series (exact-name series plus any
    labeled series of the name); None when absent."""
    total, seen = 0.0, False
    for key, m in merged.items():
        base = key.split("{", 1)[0]
        if base != name:
            continue
        if labels and not all(
                '%s="%s"' % (k, v) in key for k, v in labels.items()):
            continue
        total += float(m.get("value", 0.0))
        seen = True
    return total if seen else None


def _metric_max(merged, name):
    """Max over matching gauge series — for per-bucket gauges (e.g.
    ``quant_error``) where the alert should watch the WORST bucket, not
    the sum of all of them; None when absent."""
    worst = None
    for key, m in merged.items():
        if key.split("{", 1)[0] != name:
            continue
        v = float(m.get("value", 0.0))
        worst = v if worst is None else max(worst, v)
    return worst


def _merged_histogram(merged, name):
    """All series of histogram ``name`` pooled into one dict."""
    out = None
    for key, m in merged.items():
        if key.split("{", 1)[0] != name or m.get("type") != "histogram":
            continue
        if out is None:
            out = dict(m)
            out["counts"] = list(m.get("counts", []))
        else:
            out["count"] += m.get("count", 0)
            out["sum"] += m.get("sum", 0.0)
            if len(out["counts"]) == len(m.get("counts", [])):
                out["counts"] = [a + b for a, b in
                                 zip(out["counts"], m["counts"])]
            for f, pick in (("min", min), ("max", max)):
                if m.get(f) is not None:
                    out[f] = (m[f] if out.get(f) is None
                              else pick(out[f], m[f]))
    return out


def _read_ranks(hb_dir, now, stale_after, wedge_after):
    """Per-rank liveness from ``hb-<rank>`` heartbeat files."""
    from ..resilience.watchdog import read_heartbeat

    ranks = {}
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return ranks
    for name in names:
        match = _HB_RE.match(name)
        if not match:
            continue
        rank = int(match.group(1))
        hb = read_heartbeat(hb_dir, rank)
        if hb is None:
            continue
        done = os.path.exists(os.path.join(hb_dir, name + ".done"))
        age = now - hb["mtime"]
        alive = done or age <= stale_after
        # fresh beats with a frozen step counter: the daemon heartbeat
        # thread outlives a worker wedged inside a collective — exactly
        # the silent-hang case the watchdog layer documents
        step_ts = hb.get("step_ts")
        wedged = bool(alive and not done and step_ts is not None
                      and now - step_ts > wedge_after)
        ranks[str(rank)] = {
            "alive": bool(alive),
            "done": bool(done),
            "beat_age_s": round(age, 2),
            "step": hb.get("step"),
            "step_ms": hb.get("step_ms"),
            "wedged": wedged,
        }
    return ranks


def collect_status(dirname, hb_dir=None, now=None,
                   stale_after=DEFAULT_STALE_S,
                   wedge_after=DEFAULT_WEDGE_S):
    """One read of the telemetry dir -> the status dict ``--json``
    prints.  Missing inputs yield None fields, never a raise."""
    from ..observability.journal import read_journal

    now = time.time() if now is None else now
    events = read_journal(dirname)
    merged = _merged_metrics(_read_snapshots(dirname))
    ranks = _read_ranks(hb_dir or dirname, now, stale_after, wedge_after)

    step_events = [e for e in events if e.get("kind") == "step"]
    steps = None
    if step_events:
        nums = [e["step"] for e in step_events
                if isinstance(e.get("step"), (int, float))]
        steps = int(max(nums)) if nums else len(step_events)
    elif _metric_value(merged, "steps_total") is not None:
        steps = int(_metric_value(merged, "steps_total"))

    step_rate = None
    if len(step_events) >= 2:
        span = step_events[-1].get("ts", 0) - step_events[0].get("ts", 0)
        if span > 0:
            step_rate = round((len(step_events) - 1) / span, 3)

    wall = _merged_histogram(merged, "step_wall_ms")
    p50 = p99 = None
    if wall is None and step_events:
        # no snapshot yet (short run): fall back to the journaled steps
        ms = sorted(e["wall_ms"] for e in step_events
                    if isinstance(e.get("wall_ms"), (int, float)))
        if ms:
            p50 = ms[min(len(ms) // 2, len(ms) - 1)]
            p99 = ms[min(int(len(ms) * 0.99), len(ms) - 1)]
    elif wall is not None:
        p50 = _hist_percentile(wall, 50)
        p99 = _hist_percentile(wall, 99)

    guard_total = _metric_value(merged, "guard_steps_total")
    guard_skips = _metric_value(merged, "guard_skips_total")
    journal_skips = sum(1 for e in events
                        if e.get("kind") == "guard-skip")
    if guard_skips is None and journal_skips:
        guard_skips = float(journal_skips)
    skip_rate = None
    if guard_total:
        skip_rate = round((guard_skips or 0.0) / guard_total, 4)

    drift = {}
    for kind in ("step_ms", "peak_hbm", "ici_bytes"):
        v = _metric_value(merged, "drift_ratio", labels={"kind": kind})
        if v is not None:
            drift[kind] = round(v, 4)
    if not drift:
        # journal fallback: the periodic drift events carry the ratios
        for e in reversed(events):
            if e.get("kind") == "drift" \
                    and isinstance(e.get("ratios"), dict):
                for kind, v in e["ratios"].items():
                    if isinstance(v, (int, float)):
                        drift[kind] = round(float(v), 4)
                break

    # per-tier wire bytes (static_analysis/hierarchy + the topology
    # tree): predicted ICI vs DCN traffic of the registered programs —
    # a hierarchical plan shows its slow-tier cut here, a flat plan on
    # a multi-slice spec shows every gradient byte riding DCN
    tier_bytes = {}
    for tier in ("ici", "dcn", "pod"):
        v = _metric_value(merged, "predicted_tier_bytes",
                          labels={"tier": tier})
        if v is not None:
            tier_bytes[tier] = int(v)

    # quantized-collective health (paddle_tpu/quant): worst per-bucket
    # measured relative error and its drift against the blockwise error
    # model — the '--alert quant_error>0.05' production gate
    quant_err = _metric_max(merged, "quant_error")
    quant_ratio = _metric_max(merged, "quant_error_ratio")

    ckpt_ts = _metric_value(merged, "checkpoint_last_save_ts")
    if not ckpt_ts:
        saved = [e for e in events if e.get("kind") == "checkpoint-saved"]
        ckpt_ts = saved[-1].get("ts") if saved else None
    checkpoint_age_s = (round(now - ckpt_ts, 2)
                        if ckpt_ts else None)

    # serving view (paddle_tpu/serving): latency percentiles from the
    # pooled serving_latency_ms histogram, throughput/depth gauges, and
    # the shed rate (SLA evictions over submitted requests)
    srv_lat = _merged_histogram(merged, "serving_latency_ms")
    srv_p50 = _hist_percentile(srv_lat, 50) if srv_lat else None
    srv_p99 = _hist_percentile(srv_lat, 99) if srv_lat else None
    # queue-wait percentiles (fed from the serving.queue_wait spans):
    # the component that explains shedding, invisible in end-to-end
    srv_qw = _merged_histogram(merged, "serving_queue_wait_ms")
    srv_qw_p50 = _hist_percentile(srv_qw, 50) if srv_qw else None
    srv_qw_p99 = _hist_percentile(srv_qw, 99) if srv_qw else None
    srv_sync = _merged_histogram(merged, "serving_sync_ms")
    srv_sync_p99 = _hist_percentile(srv_sync, 99) if srv_sync else None
    srv_qps = _metric_value(merged, "serving_throughput_qps")
    srv_reqs = _metric_value(merged, "serving_requests_total")
    srv_shed = _metric_value(merged, "serving_shed_total")
    srv_shed_rate = None
    if srv_reqs:
        srv_shed_rate = round((srv_shed or 0.0) / srv_reqs, 4)
    # decode-tenant view: generated tokens, per-request generated-length
    # percentiles, and the steady-state tokens/sec gauge
    dec_tokens = _metric_value(merged, "serving_decode_tokens_total")
    dec_len = _merged_histogram(merged, "serving_generated_len")
    dec_len_p50 = _hist_percentile(dec_len, 50) if dec_len else None
    dec_len_p99 = _hist_percentile(dec_len, 99) if dec_len else None
    dec_tps = _metric_value(merged, "decode_tokens_per_sec")
    # paged-KV pool: totals sum across engines, occupancy takes the
    # WORST engine (the one about to backpressure admissions)
    kv_total = _metric_value(merged, "kv_blocks_total")
    kv_free = _metric_value(merged, "kv_blocks_free")
    kv_occ = _metric_max(merged, "kv_pool_occupancy")
    kv_handoffs = _metric_value(merged, "serving_kv_handoffs_total")
    # acceptance from the raw counters so multi-tenant rates merge as
    # a true token-weighted ratio, not an average of gauges
    spec_prop = _metric_value(merged, "spec_tokens_proposed_total")
    spec_acc = _metric_value(merged, "spec_tokens_accepted_total")
    spec_rate = (spec_acc or 0.0) / spec_prop if spec_prop else None

    # elastic view (resilience/elastic + autoscale): world/epoch from
    # the gauges when a live snapshot exists, else from the membership
    # files; pending joiners from the join files (ground truth), else
    # the leader's gauge; plus the autoscaler's last journaled decision
    fs = _elastic_fs_view(hb_dir or dirname, ranks)
    elastic_world = _metric_value(merged, "elastic_world_size")
    if elastic_world is None:
        elastic_world = fs.get("world")
    membership_epoch = _metric_value(merged, "membership_epoch")
    if membership_epoch is None:
        membership_epoch = fs.get("epoch")
    pending_joins = fs.get("pending")
    if pending_joins is None:
        pending_joins = _metric_value(merged, "elastic_pending_joins")
    autoscale = None
    for e in reversed(events):
        if e.get("kind") == "autoscale":
            autoscale = {"action": e.get("action"),
                         "reason": e.get("reason"),
                         "world": e.get("world"),
                         "target_world": e.get("target_world"),
                         "ts": e.get("ts")}
            break

    counts = {}
    for e in events:
        counts[e["kind"]] = counts.get(e["kind"], 0) + 1
    sequence = [
        {"kind": e["kind"], "ts": e.get("ts"), "rank": e.get("rank"),
         "step": e.get("step"), "trace": e.get("trace")}
        for e in events if e.get("kind") in _SEQUENCE_KINDS
    ]

    alive = sum(1 for r in ranks.values() if r["alive"])
    return {
        "dir": dirname,
        "ts": now,
        "steps": steps,
        "step_rate": step_rate,
        "p50_step_ms": None if p50 is None else round(p50, 3),
        "p99_step_ms": None if p99 is None else round(p99, 3),
        "skip_rate": skip_rate,
        "guard_skips": None if guard_skips is None else int(guard_skips),
        "faults": counts.get("fault-injected", 0),
        "restores": counts.get("checkpoint-loaded", 0),
        "drift": drift or None,
        "tier_bytes": tier_bytes or None,
        "quant_error": (None if quant_err is None
                        else round(quant_err, 6)),
        "quant_error_ratio": (None if quant_ratio is None
                              else round(quant_ratio, 4)),
        "checkpoint_age_s": checkpoint_age_s,
        "p50_serving_latency_ms": (None if srv_p50 is None
                                   else round(srv_p50, 3)),
        "p99_serving_latency_ms": (None if srv_p99 is None
                                   else round(srv_p99, 3)),
        "p50_serving_queue_wait_ms": (None if srv_qw_p50 is None
                                      else round(srv_qw_p50, 3)),
        "p99_serving_queue_wait_ms": (None if srv_qw_p99 is None
                                      else round(srv_qw_p99, 3)),
        "p99_serving_sync_ms": (None if srv_sync_p99 is None
                                else round(srv_sync_p99, 3)),
        "serving_throughput_qps": (None if srv_qps is None
                                   else round(srv_qps, 3)),
        "serving_queue_depth": _metric_value(merged,
                                             "serving_queue_depth"),
        "serving_requests": (None if srv_reqs is None
                             else int(srv_reqs)),
        "serving_rejected": _metric_value(merged,
                                          "serving_rejected_total"),
        "serving_shed_rate": srv_shed_rate,
        "serving_decode_tokens": (None if dec_tokens is None
                                  else int(dec_tokens)),
        "p50_generated_len": (None if dec_len_p50 is None
                              else round(dec_len_p50, 1)),
        "p99_generated_len": (None if dec_len_p99 is None
                              else round(dec_len_p99, 1)),
        "decode_tokens_per_sec": (None if dec_tps is None
                                  else round(dec_tps, 3)),
        "kv_blocks_total": (None if kv_total is None
                            else int(kv_total)),
        "kv_blocks_free": (None if kv_free is None else int(kv_free)),
        "kv_pool_occupancy": (None if kv_occ is None
                              else round(kv_occ, 4)),
        "kv_handoffs": (None if kv_handoffs is None
                        else int(kv_handoffs)),
        "spec_acceptance_rate": (None if spec_rate is None
                                 else round(spec_rate, 4)),
        "elastic_world_size": (None if elastic_world is None
                               else int(elastic_world)),
        "membership_epoch": (None if membership_epoch is None
                             else int(membership_epoch)),
        "pending_joins": (None if pending_joins is None
                          else int(pending_joins)),
        "autoscale": autoscale,
        "ranks": ranks or None,
        "alive_ranks": alive if ranks else None,
        "lost_ranks": (len(ranks) - alive) if ranks else None,
        "event_counts": counts or None,
        "sequence": sequence or None,
    }


def _lookup(status, path):
    """Dotted-path numeric lookup into the status dict."""
    cur = status
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


_OPS = {
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
}


def check_alert(status, expr):
    """Evaluate one alert expression against a status dict.  Returns
    (exit_code, message): 0 OK, 1 tripped, 2 no data."""
    match = _ALERT_RE.match(expr)
    if not match:
        raise ValueError(
            "bad alert %r (want e.g. 'p99_step_ms>50')" % expr)
    field, op, threshold = match.groups()
    # convenience aliases into the nested drift dict
    value = _lookup(status, field)
    if value is None and not field.startswith("drift."):
        value = _lookup(status, "drift." + field)
    if value is None:
        return 2, "ALERT %s: no data" % expr
    if _OPS[op](value, float(threshold)):
        return 1, "ALERT %s TRIPPED (value=%s)" % (expr, value)
    return 0, "alert %s ok (value=%s)" % (expr, value)


def _fmt(v, suffix=""):
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.3g%s" % (v, suffix)
    return "%s%s" % (v, suffix)


def render_status(status):
    """Human one-screen rendering of a status dict."""
    lines = ["telemetry %s @ %s" % (
        status["dir"],
        time.strftime("%H:%M:%S", time.localtime(status["ts"])))]
    lines.append(
        "  steps=%s  rate=%s/s  step_ms p50=%s p99=%s" % (
            _fmt(status["steps"]), _fmt(status["step_rate"]),
            _fmt(status["p50_step_ms"]), _fmt(status["p99_step_ms"])))
    lines.append(
        "  skip_rate=%s  faults=%s  restores=%s  ckpt_age=%s" % (
            _fmt(status["skip_rate"]), _fmt(status["faults"]),
            _fmt(status["restores"]),
            _fmt(status["checkpoint_age_s"], "s")))
    if status["drift"]:
        lines.append("  drift " + "  ".join(
            "%s=%s" % (k, _fmt(v))
            for k, v in sorted(status["drift"].items())))
    if status.get("tier_bytes"):
        lines.append("  wire " + "  ".join(
            "%s=%sB" % (k, _fmt(v))
            for k, v in sorted(status["tier_bytes"].items())))
    if status.get("quant_error") is not None:
        lines.append("  quant: error=%s  vs_model=%sx" % (
            _fmt(status["quant_error"]),
            _fmt(status.get("quant_error_ratio"))))
    if status.get("serving_requests") is not None:
        lines.append(
            "  serving: reqs=%s  qps=%s  lat_ms p50=%s p99=%s  "
            "qwait_ms p50=%s p99=%s  queue=%s  shed_rate=%s" % (
                _fmt(status["serving_requests"]),
                _fmt(status["serving_throughput_qps"]),
                _fmt(status["p50_serving_latency_ms"]),
                _fmt(status["p99_serving_latency_ms"]),
                _fmt(status.get("p50_serving_queue_wait_ms")),
                _fmt(status.get("p99_serving_queue_wait_ms")),
                _fmt(status["serving_queue_depth"]),
                _fmt(status["serving_shed_rate"])))
    if status.get("serving_decode_tokens") is not None:
        lines.append(
            "  decode: tokens=%s  tok/s=%s  gen_len p50=%s p99=%s" % (
                _fmt(status["serving_decode_tokens"]),
                _fmt(status["decode_tokens_per_sec"]),
                _fmt(status["p50_generated_len"]),
                _fmt(status["p99_generated_len"])))
    if status.get("kv_blocks_total") is not None:
        kv = "  kv_pool: blocks=%s free=%s occupancy=%s" % (
            _fmt(status["kv_blocks_total"]),
            _fmt(status["kv_blocks_free"]),
            _fmt(status["kv_pool_occupancy"]))
        if status.get("kv_handoffs") is not None:
            kv += "  handoffs=%s" % _fmt(status["kv_handoffs"])
        if status.get("spec_acceptance_rate") is not None:
            kv += "  spec_accept=%s" % _fmt(
                status["spec_acceptance_rate"])
        lines.append(kv)
    if status.get("elastic_world_size") is not None \
            or status.get("pending_joins"):
        lines.append("  elastic: world=%s  epoch=%s  pending_joins=%s"
                     % (_fmt(status.get("elastic_world_size")),
                        _fmt(status.get("membership_epoch")),
                        _fmt(status.get("pending_joins"))))
    if status.get("autoscale"):
        a = status["autoscale"]
        lines.append("  autoscale: %s (%s)"
                     % (a.get("action"), a.get("reason")))
    if status["ranks"]:
        for rank in sorted(status["ranks"], key=int):
            r = status["ranks"][rank]
            state = ("done" if r["done"]
                     else "WEDGED" if r["wedged"]
                     else "alive" if r["alive"] else "LOST")
            lines.append(
                "  rank %s: %s  beat_age=%ss  step=%s  step_ms=%s" % (
                    rank, state, r["beat_age_s"], _fmt(r["step"]),
                    _fmt(r["step_ms"])))
    if status["sequence"]:
        # collapse consecutive repeats (routine per-step checkpoints)
        # so they cannot scroll an incident chain out of the window
        collapsed = []
        for e in status["sequence"]:
            if collapsed and collapsed[-1][0]["kind"] == e["kind"]:
                collapsed[-1] = (e, collapsed[-1][1] + 1)
            else:
                collapsed.append((e, 1))
        tail = collapsed[-8:]
        lines.append("  recent: " + " -> ".join(
            e["kind"]
            + ("@%s" % e["step"] if e.get("step") is not None else "")
            + (" x%d" % n if n > 1 else "")
            for e, n in tail))
        # point the operator at `tools.trace --id` for the incident chain
        traces = []
        for e, _ in tail:
            t = e.get("trace")
            if t and t not in traces:
                traces.append(t)
        if traces:
            lines.append("  trace: " + " ".join(t[:8] for t in traces)
                         + "  (paddle_tpu.tools.trace --id <id> DIR)")
    return "\n".join(lines)


def _has_data(status):
    return any(status.get(k) is not None
               for k in ("steps", "ranks", "event_counts"))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.monitor",
        description="tail a paddle_tpu telemetry directory")
    ap.add_argument("dir", help="PADDLE_TPU_TELEMETRY_DIR of the job")
    ap.add_argument("--hb-dir", default=None,
                    help="heartbeat dir when separate from the "
                         "telemetry dir")
    ap.add_argument("--once", action="store_true",
                    help="print one report and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--alert", action="append", default=[],
                    metavar="EXPR",
                    help="e.g. 'p99_step_ms>50' or, for a serving job, "
                         "'p99_serving_latency_ms>250' / "
                         "'serving_shed_rate>0'; decode tenants add "
                         "'decode_tokens_per_sec<100' / "
                         "'serving_decode_tokens==0' / "
                         "'p99_generated_len>512'; paged-KV serving "
                         "adds 'kv_pool_occupancy>0.9' (the worst "
                         "engine's pool is nearly exhausted — "
                         "admissions are about to backpressure) / "
                         "'kv_blocks_free==0' / "
                         "'spec_acceptance_rate<0.3' (the draft "
                         "stopped paying for itself); "
                         "quantized-collective "
                         "jobs add 'quant_error>0.05' (worst per-bucket "
                         "int8 error) / 'quant_error_ratio>2' (error "
                         "model drift); elastic jobs add "
                         "'pending_joins>0' (a worker is waiting for "
                         "admission) / 'elastic_world_size<4'; exit 1 "
                         "when tripped, 2 when the field has no data "
                         "(repeatable)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-mode refresh seconds (default 2)")
    ap.add_argument("--stale-after", type=float,
                    default=DEFAULT_STALE_S,
                    help="heartbeat age marking a rank lost")
    ap.add_argument("--wedge-after", type=float,
                    default=DEFAULT_WEDGE_S,
                    help="step-progress age marking a rank wedged")
    args = ap.parse_args(argv)

    def _report():
        status = collect_status(
            args.dir, hb_dir=args.hb_dir,
            stale_after=args.stale_after, wedge_after=args.wedge_after)
        if args.json:
            print(json.dumps(status, sort_keys=True, default=str))
        else:
            print(render_status(status))
        code = 0
        for expr in args.alert:
            c, msg = check_alert(status, expr)
            print(msg, file=sys.stderr)
            code = max(code, c)
        if not args.alert and not _has_data(status):
            print("no telemetry found under %s" % args.dir,
                  file=sys.stderr)
            code = 2
        return code

    if args.once:
        return _report()
    code = 0
    try:
        while True:
            code = _report()
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return code


if __name__ == "__main__":
    sys.exit(main())
