"""Dataset → executor bridge (reference: ``Executor::RunFromDataset``,
``executor.cc:120`` → trainers/device workers).  The Dataset/DataFeed
pipeline lands with the CTR batch; this keeps the Executor entry points
importable."""


def run_from_dataset(executor, program, dataset, scope, fetch_list,
                     fetch_info, print_period, train=True):
    if dataset is None:
        raise ValueError("dataset is required")
    it = dataset.batch_iterator()
    results = []
    for i, feed in enumerate(it):
        out = executor.run(
            program, feed=feed, fetch_list=fetch_list, scope=scope
        )
        if fetch_list and print_period and i % print_period == 0:
            names = fetch_info or [
                getattr(v, "name", str(v)) for v in fetch_list
            ]
            msg = ", ".join(
                "%s=%s" % (n, o.reshape(-1)[:3]) for n, o in zip(names, out)
            )
            print("[paddle_tpu] step %d: %s" % (i, msg))
        results.append(out)
    return results
