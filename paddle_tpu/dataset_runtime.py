"""Dataset → executor bridge (reference: ``Executor::RunFromDataset``,
``executor.cc:120`` → TrainerFactory → trainers/device workers).  The
thread-per-core C++ worker runtime is subsumed by the jitted SPMD step;
the TrainerDesc/DeviceWorker configuration surface survives via
``trainer_desc.TrainerFactory`` (reference trainer_factory.cc)."""


def run_from_dataset(executor, program, dataset, scope, fetch_list,
                     fetch_info, print_period, train=True):
    from .trainer_desc import TrainerFactory

    if dataset is None:
        raise ValueError("dataset is required")
    opt_info = getattr(program, "_opt_info", None) or {}
    trainer = TrainerFactory()._create_trainer(opt_info)
    trainer._set_program(program)
    trainer._set_infer(not train)
    trainer._set_fetch_var_and_info(fetch_list, fetch_info, print_period)
    program._trainer_desc = trainer

    from . import pipeline as pl

    # Async dispatch loop (SURVEY §7g; the reference's DataFeed worker
    # threads + double-buffer queue): a background thread parses AND
    # device_puts upcoming batches (depth PADDLE_TPU_PIPELINE_DEPTH,
    # default 2), every step returns lazy FetchHandles, and the ONLY
    # device→host syncs are one batched materialize per print_period
    # window — host prep of batch k+1 overlaps device compute of batch k
    # with no per-step round trip anywhere in the loop.
    it = pl.DeviceFeedPipeline(dataset.batch_iterator)
    # sync window = print_period (the printed line needs the values
    # anyway); without printing, a pipeline-depth-sized window keeps
    # device residency of un-synced fetches O(depth) — the batched sync
    # of completed steps still overlaps the steps in flight behind it
    window = (print_period if (fetch_list and print_period)
              else max(2, pl.pipeline_depth() * 2))
    results = []
    unsynced = 0

    def _sync_window():
        # ONE batched sync for every fetch still in flight
        pl.materialize([h for step in results[len(results) - unsynced:]
                        for h in step])

    for i, feed in enumerate(it):
        out = executor.run(
            program, feed=feed, fetch_list=fetch_list, scope=scope,
            return_numpy=False,
        )
        results.append(out)
        unsynced += 1
        if fetch_list and print_period and i % print_period == 0:
            _sync_window()
            unsynced = 0
            names = fetch_info or [
                getattr(v, "name", str(v)) for v in fetch_list
            ]
            msg = ", ".join(
                "%s=%s" % (n, o.numpy().reshape(-1)[:3])
                for n, o in zip(names, out)
            )
            print("[paddle_tpu] step %d: %s" % (i, msg))
        elif unsynced >= window:
            _sync_window()
            unsynced = 0
    if unsynced:
        _sync_window()
    # contract: numpy values per step (handles are synced — free here)
    return [pl.materialize(step) for step in results]
