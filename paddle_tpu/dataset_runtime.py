"""Dataset → executor bridge (reference: ``Executor::RunFromDataset``,
``executor.cc:120`` → TrainerFactory → trainers/device workers).  The
thread-per-core C++ worker runtime is subsumed by the jitted SPMD step;
the TrainerDesc/DeviceWorker configuration surface survives via
``trainer_desc.TrainerFactory`` (reference trainer_factory.cc)."""


def run_from_dataset(executor, program, dataset, scope, fetch_list,
                     fetch_info, print_period, train=True):
    from .trainer_desc import TrainerFactory

    if dataset is None:
        raise ValueError("dataset is required")
    opt_info = getattr(program, "_opt_info", None) or {}
    trainer = TrainerFactory()._create_trainer(opt_info)
    trainer._set_program(program)
    trainer._set_infer(not train)
    trainer._set_fetch_var_and_info(fetch_list, fetch_info, print_period)
    program._trainer_desc = trainer
    it = dataset.batch_iterator()
    results = []
    for i, feed in enumerate(it):
        out = executor.run(
            program, feed=feed, fetch_list=fetch_list, scope=scope
        )
        if fetch_list and print_period and i % print_period == 0:
            names = fetch_info or [
                getattr(v, "name", str(v)) for v in fetch_list
            ]
            msg = ", ".join(
                "%s=%s" % (n, o.reshape(-1)[:3]) for n, o in zip(names, out)
            )
            print("[paddle_tpu] step %d: %s" % (i, msg))
        results.append(out)
    return results
