"""Dataset → executor bridge (reference: ``Executor::RunFromDataset``,
``executor.cc:120`` → TrainerFactory → trainers/device workers).  The
thread-per-core C++ worker runtime is subsumed by the jitted SPMD step;
the TrainerDesc/DeviceWorker configuration surface survives via
``trainer_desc.TrainerFactory`` (reference trainer_factory.cc)."""


def run_from_dataset(executor, program, dataset, scope, fetch_list,
                     fetch_info, print_period, train=True):
    from .trainer_desc import TrainerFactory

    if dataset is None:
        raise ValueError("dataset is required")
    opt_info = getattr(program, "_opt_info", None) or {}
    trainer = TrainerFactory()._create_trainer(opt_info)
    trainer._set_program(program)
    trainer._set_infer(not train)
    trainer._set_fetch_var_and_info(fetch_list, fetch_info, print_period)
    program._trainer_desc = trainer
    import numpy as np

    from .reader_decorators import buffered

    # Input-pipeline overlap (SURVEY §7g; the reference's DataFeed worker
    # threads): parse batches on a background thread (2-deep buffer) and
    # keep per-step fetches as DEVICE arrays — jax dispatch is async, so
    # the host parses batch i+1 while the chip runs step i.  One numpy
    # sync at the end (or at each print_period line) instead of per step.
    it = buffered(dataset.batch_iterator, 2)()
    results = []
    for i, feed in enumerate(it):
        out = executor.run(
            program, feed=feed, fetch_list=fetch_list, scope=scope,
            return_numpy=False,
        )
        if fetch_list and print_period and i % print_period == 0:
            names = fetch_info or [
                getattr(v, "name", str(v)) for v in fetch_list
            ]
            msg = ", ".join(
                "%s=%s" % (n, np.asarray(o).reshape(-1)[:3])
                for n, o in zip(names, out)
            )
            print("[paddle_tpu] step %d: %s" % (i, msg))
        results.append(out)
        if len(results) >= 2:
            # one-step-lag host conversion: step i is dispatched, so
            # pulling step i-1's (already computed) fetches costs no
            # pipeline stall, and device residency stays O(1) in steps
            results[-2] = [np.asarray(o) for o in results[-2]]
    if results:
        results[-1] = [np.asarray(o) for o in results[-1]]
    return results
