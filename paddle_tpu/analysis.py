"""Inference analysis pass pipeline (reference:
``paddle/fluid/inference/analysis/`` — Analyzer runs a configured pass
pipeline (ir_graph_build, ir_analysis passes, memory_optimize) over the
loaded program before handing it to the executor;
``paddle/fluid/framework/ir/fc_fuse_pass.cc`` and friends).

TPU note: XLA performs instruction-level fusion and DCE at jit time, so
these passes exist for PROGRAM-level parity (smaller op lists, fused op
types visible to program inspection/serialization) and for numeric folds
that change weights (conv+bn).  Passes are program→program functions on
the framework IR, registered by name like the reference's PassRegistry."""

__all__ = ["register_pass", "get_pass", "PassBuilder", "Analyzer",
           "fc_fuse_pass", "dead_code_elimination_pass",
           "conv_bn_fuse_pass", "verify_pass"]

_PASSES = {}


def register_pass(name):
    def deco(fn):
        _PASSES[name] = fn
        return fn

    return deco


def get_pass(name):
    return _PASSES[name]


@register_pass("conv_bn_fuse_pass")
def conv_bn_fuse_pass(program, scope=None, targets=None):
    """Fold batch-norm statistics into conv weights
    (ir/conv_bn_fuse_pass.cc; numeric rewrite of the weights)."""
    from .inference import fuse_conv_bn

    if scope is None:
        from .executor import global_scope

        scope = global_scope()
    fuse_conv_bn(program, scope)
    return program


@register_pass("fc_fuse_pass")
def fc_fuse_pass(program, scope=None, targets=None):
    """mul + elementwise_add(bias) → one fc op (ir/fc_fuse_pass.cc).

    Matches when the mul output has exactly one consumer (the add) and
    the add's Y operand is a 1-D persistable bias.

    The consumer map is rebuilt after every fusion: each fusion replaces
    two ops with one, so a map built once over the original op list goes
    stale (it holds removed ``elementwise_add`` objects and misses the
    new ``fc`` reads), silently breaking chained mul+add pairs.  Sub-block
    reads also count as consumers — fusing away a var a ``while`` body
    captures by closure would leave a dangling read the op's input slots
    never show."""
    from .framework import Operator
    from .static_analysis import sub_block_reads_recursive
    from .static_analysis.defuse import resolve_sub_block

    block = program.global_block()

    # per-op sub-block closure reads are invariant across the pass (fusion
    # only rewrites the global block), so walk each sub-block once
    closure_reads = {}
    for o in block.ops:
        sub = resolve_sub_block(program, o, host_block_idx=block.idx)
        if sub is not None:
            closure_reads[id(o)] = sub_block_reads_recursive(program, sub)

    def build_consumers():
        consumers = {}
        for o in block.ops:
            for n in o.input_arg_names:
                consumers.setdefault(n, []).append(o)
            for n in closure_reads.get(id(o), ()):
                consumers.setdefault(n, []).append(o)
        return consumers

    consumers = build_consumers()
    fused = 0
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type != "mul" or int(op.attrs.get("y_num_col_dims", 1)) != 1:
            i += 1
            continue
        out = op.outputs["Out"][0]
        if targets and out in targets:
            # the intermediate is itself a fetch target: fusing would
            # leave it unproduced
            i += 1
            continue
        cons = consumers.get(out, [])
        if len(cons) != 1 or cons[0].type != "elementwise_add":
            i += 1
            continue
        add = cons[0]
        if add.inputs.get("X", [None])[0] != out:
            i += 1
            continue
        # the bias must broadcast over the LAST dim (fc semantics): axis
        # -1 or == mul's x_num_col_dims
        axis = int(add.attrs.get("axis", -1))
        if axis not in (-1, int(op.attrs.get("x_num_col_dims", 1))):
            i += 1
            continue
        bias_name = add.inputs.get("Y", [None])[0]
        bias_var = block._find_var_recursive(bias_name)
        if bias_var is None or not bias_var.persistable \
                or len(bias_var.shape or ()) != 1:
            i += 1
            continue
        try:
            j = block.ops.index(add)
        except ValueError:
            i += 1
            continue
        if j <= i:
            # the add precedes the mul (rewritten/deserialized op order):
            # fusing here would move the output's production past
            # consumers between j and i
            i += 1
            continue
        fc = Operator(
            block, "fc",
            {"Input": list(op.inputs["X"]), "W": list(op.inputs["Y"]),
             "Bias": [bias_name]},
            {"Out": list(add.outputs["Out"])},
            {"in_num_col_dims": int(op.attrs.get("x_num_col_dims", 1))},
        )
        block.ops[i] = fc
        del block.ops[j]
        fused += 1
        consumers = build_consumers()
        i += 1
    if fused:
        program._bump_version()
    return program


@register_pass("dead_code_elimination_pass")
def dead_code_elimination_pass(program, scope=None, targets=None):
    """Remove ops whose outputs never reach the targets (the analysis
    memory_optimize/prune role; XLA also DCEs at jit, this shrinks the
    PROGRAM).

    Liveness follows ``input_arg_names`` AND sub-block closure reads: a
    ``conditional_block`` lists only ``Cond`` as a formal input, and a
    ``recurrent`` only its sequence/state slots, so vars read exclusively
    inside ``attrs["sub_block"]`` (via
    ``cf_ops.sub_block_external_reads``, cf. backward.py:250) must be
    marked live when the control-flow op is kept — otherwise their
    producers are eliminated and the program fails at trace time."""
    if not targets:
        return program
    from .static_analysis import sub_block_reads_recursive
    from .static_analysis.defuse import resolve_sub_block

    block = program.global_block()
    needed = set(targets)
    keep = []
    for op in reversed(block.ops):
        outs = set(op.output_arg_names)
        writes_persistable = any(
            (v := block._find_var_recursive(n)) is not None and v.persistable
            for n in outs)
        if outs & needed or writes_persistable or op.type in (
                "feed", "fetch", "print"):
            keep.append(op)
            needed.update(op.input_arg_names)
            sub = resolve_sub_block(program, op, host_block_idx=block.idx)
            if sub is not None:
                needed.update(sub_block_reads_recursive(program, sub))
    if len(keep) != len(block.ops):
        block.ops[:] = list(reversed(keep))
        program._bump_version()
    return program


@register_pass("verify_pass")
def verify_pass(program, scope=None, targets=None, context=None):
    """Run the static_analysis verifier as a pipeline pass (the TVM/XLA
    lesson: rewrite-heavy pipelines need invariant checks BETWEEN passes).
    Raises ``VerifyError`` with structured diagnostics on ERROR-severity
    findings; warnings/advisories pass through silently.  ``context``
    names the surrounding pass in the failure header."""
    from .static_analysis import assert_valid

    header = ("program failed verification%s:"
              % (" (%s)" % context if context else ""))
    assert_valid(program, targets=targets, header=header)
    return program


class PassBuilder:
    """Mutable pass pipeline (reference paddle_pass_builder.h)."""

    DEFAULT = ["conv_bn_fuse_pass", "fc_fuse_pass",
               "dead_code_elimination_pass"]

    def __init__(self, passes=None):
        self._passes = list(passes if passes is not None else self.DEFAULT)

    def all_passes(self):
        return list(self._passes)

    def append_pass(self, name):
        self._passes.append(name)

    def delete_pass(self, name):
        self._passes = [p for p in self._passes if p != name]

    def insert_pass(self, idx, name):
        self._passes.insert(idx, name)


class Analyzer:
    """Run the configured pipeline (reference analysis/analyzer.h:
    Analyzer::RunAnalysis).

    With verification enabled (``verify=True``, or the default resolving
    from ``PADDLE_TPU_VERIFY_PASSES`` — on in tests via conftest), the
    program is verified before the pipeline and re-verified after every
    rewrite pass, so the offending pass is named instead of surfacing as
    an opaque trace error at ``Executor.run``."""

    def __init__(self, pass_builder=None):
        self._builder = pass_builder or PassBuilder()

    def run(self, program, scope=None, targets=None, verify=None):
        if verify is None:
            from .static_analysis import pass_verification_enabled

            verify = pass_verification_enabled()
        if verify:
            verify_pass(program, scope=scope, targets=targets,
                        context="before analysis pipeline")
        for name in self._builder.all_passes():
            program = get_pass(name)(program, scope=scope, targets=targets)
            if verify and name != "verify_pass":
                verify_pass(program, scope=scope, targets=targets,
                            context="after %s" % name)
        return program
