"""Inference analysis pass pipeline (reference:
``paddle/fluid/inference/analysis/`` — Analyzer runs a configured pass
pipeline (ir_graph_build, ir_analysis passes, memory_optimize) over the
loaded program before handing it to the executor;
``paddle/fluid/framework/ir/fc_fuse_pass.cc`` and friends).

TPU note: XLA performs instruction-level fusion and DCE at jit time, so
these passes exist for PROGRAM-level parity (smaller op lists, fused op
types visible to program inspection/serialization) and for numeric folds
that change weights (conv+bn).  Passes are program→program functions on
the framework IR, registered by name like the reference's PassRegistry."""

__all__ = ["register_pass", "get_pass", "PassBuilder", "Analyzer",
           "fc_fuse_pass", "dead_code_elimination_pass",
           "conv_bn_fuse_pass"]

_PASSES = {}


def register_pass(name):
    def deco(fn):
        _PASSES[name] = fn
        return fn

    return deco


def get_pass(name):
    return _PASSES[name]


@register_pass("conv_bn_fuse_pass")
def conv_bn_fuse_pass(program, scope=None, targets=None):
    """Fold batch-norm statistics into conv weights
    (ir/conv_bn_fuse_pass.cc; numeric rewrite of the weights)."""
    from .inference import fuse_conv_bn

    if scope is None:
        from .executor import global_scope

        scope = global_scope()
    fuse_conv_bn(program, scope)
    return program


@register_pass("fc_fuse_pass")
def fc_fuse_pass(program, scope=None, targets=None):
    """mul + elementwise_add(bias) → one fc op (ir/fc_fuse_pass.cc).

    Matches when the mul output has exactly one consumer (the add) and
    the add's Y operand is a 1-D persistable bias."""
    block = program.global_block()
    ops = block.ops
    consumers = {}
    for op in ops:
        for n in op.input_arg_names:
            consumers.setdefault(n, []).append(op)
    fused = 0
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type != "mul" or int(op.attrs.get("y_num_col_dims", 1)) != 1:
            i += 1
            continue
        out = op.outputs["Out"][0]
        if targets and out in targets:
            # the intermediate is itself a fetch target: fusing would
            # leave it unproduced
            i += 1
            continue
        cons = consumers.get(out, [])
        if len(cons) != 1 or cons[0].type != "elementwise_add":
            i += 1
            continue
        add = cons[0]
        if add.inputs.get("X", [None])[0] != out:
            i += 1
            continue
        # the bias must broadcast over the LAST dim (fc semantics): axis
        # -1 or == mul's x_num_col_dims
        axis = int(add.attrs.get("axis", -1))
        if axis not in (-1, int(op.attrs.get("x_num_col_dims", 1))):
            i += 1
            continue
        bias_name = add.inputs.get("Y", [None])[0]
        bias_var = block._find_var_recursive(bias_name)
        if bias_var is None or not bias_var.persistable \
                or len(bias_var.shape or ()) != 1:
            i += 1
            continue
        j = block.ops.index(add)
        from .framework import Operator

        fc = Operator(
            block, "fc",
            {"Input": list(op.inputs["X"]), "W": list(op.inputs["Y"]),
             "Bias": [bias_name]},
            {"Out": list(add.outputs["Out"])},
            {"in_num_col_dims": int(op.attrs.get("x_num_col_dims", 1))},
        )
        block.ops[i] = fc
        del block.ops[j]
        fused += 1
        i += 1
    if fused:
        program._bump_version()
    return program


@register_pass("dead_code_elimination_pass")
def dead_code_elimination_pass(program, scope=None, targets=None):
    """Remove ops whose outputs never reach the targets (the analysis
    memory_optimize/prune role; XLA also DCEs at jit, this shrinks the
    PROGRAM)."""
    if not targets:
        return program
    block = program.global_block()
    needed = set(targets)
    keep = []
    for op in reversed(block.ops):
        outs = set(op.output_arg_names)
        writes_persistable = any(
            (v := block._find_var_recursive(n)) is not None and v.persistable
            for n in outs)
        if outs & needed or writes_persistable or op.type in (
                "feed", "fetch", "print"):
            keep.append(op)
            needed.update(op.input_arg_names)
    if len(keep) != len(block.ops):
        block.ops[:] = list(reversed(keep))
        program._bump_version()
    return program


class PassBuilder:
    """Mutable pass pipeline (reference paddle_pass_builder.h)."""

    DEFAULT = ["conv_bn_fuse_pass", "fc_fuse_pass",
               "dead_code_elimination_pass"]

    def __init__(self, passes=None):
        self._passes = list(passes if passes is not None else self.DEFAULT)

    def all_passes(self):
        return list(self._passes)

    def append_pass(self, name):
        self._passes.append(name)

    def delete_pass(self, name):
        self._passes = [p for p in self._passes if p != name]

    def insert_pass(self, idx, name):
        self._passes.insert(idx, name)


class Analyzer:
    """Run the configured pipeline (reference analysis/analyzer.h:
    Analyzer::RunAnalysis)."""

    def __init__(self, pass_builder=None):
        self._builder = pass_builder or PassBuilder()

    def run(self, program, scope=None, targets=None):
        for name in self._builder.all_passes():
            program = get_pass(name)(program, scope=scope, targets=targets)
        return program
