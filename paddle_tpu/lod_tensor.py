"""LoDTensor construction helpers (reference:
``python/paddle/fluid/lod_tensor.py`` create_lod_tensor /
create_random_int_lodtensor and the pybind ``LoDTensor`` type).

TPU representation: a host-side container of the FLAT [T, ...] data plus
recursive sequence lengths.  ``np.asarray()`` yields the flat data, so a
LoDTensor feeds straight into ``Executor.run``; models consume ragged
batches as padded+mask / SeqLen tensors (SURVEY §5), and
``to_padded()`` converts when needed."""

import numpy as np

__all__ = ["LoDTensor", "LoDTensorArray", "create_lod_tensor",
           "create_random_int_lodtensor"]


class LoDTensor:
    def __init__(self, data=None, recursive_seq_lens=None):
        self._data = None if data is None else np.asarray(data)
        self._seq_lens = [list(l) for l in (recursive_seq_lens or [])]

    def set(self, data, place=None):
        self._data = np.asarray(data)

    def set_recursive_sequence_lengths(self, lens):
        self._seq_lens = [list(l) for l in lens]

    def recursive_sequence_lengths(self):
        return [list(l) for l in self._seq_lens]

    def set_lod(self, lod):
        # offsets -> lengths
        self._seq_lens = [
            [b - a for a, b in zip(level[:-1], level[1:])] for level in lod
        ]

    def lod(self):
        out = []
        for lens in self._seq_lens:
            level = [0]
            for n in lens:
                level.append(level[-1] + n)
            out.append(level)
        return out

    def shape(self):
        return list(self._data.shape) if self._data is not None else []

    def __array__(self, dtype=None):
        a = self._data
        return a.astype(dtype) if dtype is not None else a

    def has_valid_recursive_sequence_lengths(self):
        if not self._seq_lens or self._data is None:
            return True
        total = sum(self._seq_lens[-1])
        return total == self._data.shape[0]

    def to_padded(self, maxlen=None, pad_value=0):
        """[B, L, ...] padded batch + [B] lengths from the finest level."""
        lens = self._seq_lens[-1]
        L = maxlen or (max(lens) if lens else 0)
        b = len(lens)
        out = np.full((b, L) + self._data.shape[1:], pad_value,
                      self._data.dtype)
        off = 0
        for i, n in enumerate(lens):
            out[i, :min(n, L)] = self._data[off:off + min(n, L)]
            off += n
        return out, np.asarray(lens, np.int64)


class LoDTensorArray(list):
    """reference pybind LoDTensorArray: a list of LoDTensors."""

    def append(self, t):
        if not isinstance(t, LoDTensor):
            t = LoDTensor(np.asarray(t))
        super().append(t)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference lod_tensor.py:create_lod_tensor — data is a numpy array
    of flat shape [sum(lens), ...], a list of sequences, or a LoDTensor."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(np.asarray(data), recursive_seq_lens,
                                 place)
    if isinstance(data, list):
        flat = np.concatenate([np.asarray(s).reshape(len(s), -1)
                               for s in data], axis=0)
        t = LoDTensor(flat, recursive_seq_lens)
    else:
        t = LoDTensor(np.asarray(data), recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError(
            "recursive_seq_lens %r do not sum to the data's first dim %d"
            % (recursive_seq_lens, np.asarray(t).shape[0]))
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    total = sum(recursive_seq_lens[-1])
    data = np.random.randint(low, high + 1,
                             size=[total] + list(base_shape))
    return create_lod_tensor(data.astype("int64"), recursive_seq_lens,
                             place)
