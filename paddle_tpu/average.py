"""Weighted averaging helper (reference:
``python/paddle/fluid/average.py`` — WeightedAverage used by book tests to
track running losses/metrics on the host)."""

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(var):
    return isinstance(var, (int, float, np.ndarray)) or (
        isinstance(var, (list, tuple))
        and all(isinstance(v, (int, float)) for v in var))


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError(
                "The 'value' must be a number(int, float) or a numpy array.")
        if not isinstance(weight, (int, float)):
            raise ValueError("The 'weight' must be a number(int, float).")
        value = np.mean(np.asarray(value, dtype="float64"))
        if self.numerator is None:
            self.numerator = value * weight
            self.denominator = float(weight)
        else:
            self.numerator += value * weight
            self.denominator += float(weight)

    def eval(self):
        if self.numerator is None or self.denominator == 0.0:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage.")
        return self.numerator / self.denominator
