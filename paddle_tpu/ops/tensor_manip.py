"""Shape-manipulation ops (reference: ``paddle/fluid/operators/reshape_op.cc``,
``transpose_op.cc``, ``concat_op.cc``, ``split_op.cc``, ``slice_op.cc``,
``gather_op.cc``, ``expand_op.cc`` …).

The `*2` variants (reshape2/transpose2/…) also emit an `XShape` output which
the reference's grad kernels use to recover the input shape
(``reshape_op.cc`` ReshapeGradOp); here XShape is a zero-size placeholder —
the vjp-derived grads recover shapes from tracing — kept for program-structure
parity with serialized reference models.
"""

import jax
import jax.numpy as jnp

from .registry import register_op


def _resolve_new_shape(shape_attr, in_shape):
    """Fluid reshape semantics: 0 copies the input dim, -1 infers."""
    out = []
    for i, s in enumerate(shape_attr):
        if s == 0:
            out.append(in_shape[i])
        else:
            out.append(int(s))
    return tuple(out)


def _xshape(x):
    return jnp.zeros((0,) + tuple(jnp.shape(x)), x.dtype)


def _infer_reshape(op, block):
    for slot in ("X",):
        name = op.inputs.get(slot, [None])[0]
        var = block._find_var_recursive(name) if name else None
    out_name = op.outputs["Out"][0]
    out_var = block._find_var_recursive(out_name)
    shape_attr = op.attrs.get("shape", [])
    if out_var is None or var is None:
        return
    if var.shape is not None:
        in_shape = var.shape
        new = []
        for i, s in enumerate(shape_attr):
            if s == 0 and i < len(in_shape):
                new.append(in_shape[i])
            else:
                new.append(int(s))
        # resolve a single -1 if the other dims are static
        if new.count(-1) == 1 and all(d >= 0 for d in in_shape):
            known = 1
            for d in new:
                if d != -1:
                    known *= d
            total = 1
            for d in in_shape:
                total *= d
            if known > 0 and total % known == 0:
                new[new.index(-1)] = total // known
        out_var.shape = tuple(new)
    else:
        out_var.shape = tuple(int(s) for s in shape_attr)
    out_var.dtype = var.dtype
    if "XShape" in op.outputs:
        xs = block._find_var_recursive(op.outputs["XShape"][0])
        if xs is not None and var.shape is not None:
            xs.shape = (0,) + tuple(var.shape)
            xs.dtype = var.dtype


@register_op("reshape", inputs=["X", "Shape"], outputs=["Out"],
             infer_shape=_infer_reshape)
def reshape(ctx, attrs, X, Shape):
    new_shape = _resolve_new_shape(attrs.get("shape", []), jnp.shape(X))
    return jnp.reshape(X, new_shape)


@register_op("reshape2", inputs=["X", "Shape"], outputs=["Out", "XShape"],
             infer_shape=_infer_reshape, stateful_outputs=("XShape",))
def reshape2(ctx, attrs, X, Shape):
    new_shape = _resolve_new_shape(attrs.get("shape", []), jnp.shape(X))
    return {"Out": jnp.reshape(X, new_shape), "XShape": _xshape(X)}


@register_op("transpose", inputs=["X"], outputs=["Out"])
def transpose(ctx, attrs, X):
    return jnp.transpose(X, attrs.get("axis"))


@register_op("transpose2", inputs=["X"], outputs=["Out", "XShape"],
             stateful_outputs=("XShape",))
def transpose2(ctx, attrs, X):
    return {"Out": jnp.transpose(X, attrs.get("axis")), "XShape": _xshape(X)}


@register_op("concat", inputs=["X*"], outputs=["Out"])
def concat(ctx, attrs, X):
    return jnp.concatenate(X, axis=int(attrs.get("axis", 0)))


@register_op("split", inputs=["X"], outputs=["Out*"])
def split(ctx, attrs, X):
    axis = int(attrs.get("axis", 0))
    sections = attrs.get("sections", [])
    num = int(attrs.get("num", 0))
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += int(s)
            idx.append(acc)
        parts = jnp.split(X, idx, axis=axis)
    else:
        parts = jnp.split(X, num, axis=axis)
    return {"Out": parts}


@register_op("slice", inputs=["Input"], outputs=["Out"])
def slice_op(ctx, attrs, Input):
    axes = attrs.get("axes", [])
    starts = attrs.get("starts", [])
    ends = attrs.get("ends", [])
    idx = [slice(None)] * jnp.ndim(Input)
    shape = jnp.shape(Input)
    for ax, st, en in zip(axes, starts, ends):
        st = int(st)
        en = min(int(en), shape[ax]) if int(en) >= 0 else int(en)
        idx[ax] = slice(st, en)
    out = Input[tuple(idx)]
    decrease = attrs.get("decrease_axis", [])
    if decrease:
        out = jnp.squeeze(out, axis=tuple(decrease))
    return out


@register_op("squeeze", inputs=["X"], outputs=["Out"])
def squeeze(ctx, attrs, X):
    axes = [a % jnp.ndim(X) for a in attrs.get("axes", [])]
    if not axes:
        return jnp.squeeze(X)
    axes = [a for a in axes if jnp.shape(X)[a] == 1]
    return jnp.squeeze(X, axis=tuple(axes))


@register_op("squeeze2", inputs=["X"], outputs=["Out", "XShape"],
             stateful_outputs=("XShape",))
def squeeze2(ctx, attrs, X):
    return {"Out": squeeze(ctx, attrs, X), "XShape": _xshape(X)}


@register_op("unsqueeze", inputs=["X"], outputs=["Out"])
def unsqueeze(ctx, attrs, X):
    out = X
    for a in sorted(attrs.get("axes", [])):
        out = jnp.expand_dims(out, a)
    return out


@register_op("unsqueeze2", inputs=["X"], outputs=["Out", "XShape"],
             stateful_outputs=("XShape",))
def unsqueeze2(ctx, attrs, X):
    out = X
    for a in sorted(attrs.get("axes", [])):
        out = jnp.expand_dims(out, a)
    return {"Out": out, "XShape": _xshape(X)}


@register_op("flatten", inputs=["X"], outputs=["Out"])
def flatten(ctx, attrs, X):
    axis = int(attrs.get("axis", 1))
    shape = jnp.shape(X)
    lead = 1
    for d in shape[:axis]:
        lead *= d
    return jnp.reshape(X, (lead, -1))


@register_op("flatten2", inputs=["X"], outputs=["Out", "XShape"],
             stateful_outputs=("XShape",))
def flatten2(ctx, attrs, X):
    axis = int(attrs.get("axis", 1))
    shape = jnp.shape(X)
    lead = 1
    for d in shape[:axis]:
        lead *= d
    return {"Out": jnp.reshape(X, (lead, -1)), "XShape": _xshape(X)}


@register_op("stack", inputs=["X*"], outputs=["Y"])
def stack(ctx, attrs, X):
    return jnp.stack(X, axis=int(attrs.get("axis", 0)))


@register_op("unstack", inputs=["X"], outputs=["Y*"])
def unstack(ctx, attrs, X):
    axis = int(attrs.get("axis", 0))
    num = attrs.get("num") or jnp.shape(X)[axis]
    parts = jnp.split(X, int(num), axis=axis)
    return {"Y": [jnp.squeeze(p, axis=axis) for p in parts]}


@register_op("gather", inputs=["X", "Index"], outputs=["Out"])
def gather(ctx, attrs, X, Index):
    idx = Index.astype(jnp.int32)
    if idx.ndim > 1 and idx.shape[-1] == 1:
        idx = idx[..., 0]
    return jnp.take(X, idx, axis=0)


@register_op("gather_nd", inputs=["X", "Index"], outputs=["Out"])
def gather_nd(ctx, attrs, X, Index):
    idx = Index.astype(jnp.int32)
    return X[tuple(jnp.moveaxis(idx, -1, 0))]


@register_op("scatter", inputs=["X", "Ids", "Updates"], outputs=["Out"])
def scatter(ctx, attrs, X, Ids, Updates):
    ids = Ids.astype(jnp.int32)
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    if attrs.get("overwrite", True):
        return X.at[ids].set(Updates)
    return X.at[ids].add(Updates)


@register_op("expand", inputs=["X"], outputs=["Out"])
def expand(ctx, attrs, X):
    times = [int(t) for t in attrs.get("expand_times", [])]
    return jnp.tile(X, times)


@register_op("expand_as", inputs=["X", "target_tensor"], outputs=["Out"])
def expand_as(ctx, attrs, X, target_tensor):
    times = [
        t // s for t, s in zip(jnp.shape(target_tensor), jnp.shape(X))
    ]
    return jnp.tile(X, times)


@register_op("tile", inputs=["X"], outputs=["Out"])
def tile(ctx, attrs, X):
    return jnp.tile(X, [int(t) for t in attrs.get("repeat_times", [])])


@register_op("pad", inputs=["X"], outputs=["Out"])
def pad(ctx, attrs, X):
    p = attrs.get("paddings", [])
    pairs = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(jnp.ndim(X))]
    return jnp.pad(X, pairs, constant_values=attrs.get("pad_value", 0.0))


@register_op("pad2d", inputs=["X"], outputs=["Out"])
def pad2d(ctx, attrs, X):
    p = [int(v) for v in attrs.get("paddings", [0, 0, 0, 0])]
    mode = attrs.get("mode", "constant")
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pairs = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        return jnp.pad(X, pairs, constant_values=attrs.get("pad_value", 0.0))
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return jnp.pad(X, pairs, mode=jmode)


@register_op("reverse", inputs=["X"], outputs=["Out"])
def reverse(ctx, attrs, X):
    return jnp.flip(X, axis=tuple(attrs.get("axis", [0])))


@register_op("lod_reset", inputs=["X", "Y"], outputs=["Out"])
def lod_reset(ctx, attrs, X, Y):
    # LoD metadata is carried out-of-band on TPU (segment companions);
    # values pass through
    return X


@register_op("im2sequence", inputs=["X"], outputs=["Out"], no_grad=True)
def im2sequence(ctx, attrs, X):
    kernels = attrs.get("kernels")
    strides = attrs.get("strides", [1, 1])
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0, 0])]
    # reference im2sequence_op.cc padding order: [up, left, down, right]
    padding = ((pads[0], pads[2]), (pads[1], pads[3]))
    n, c, h, w = jnp.shape(X)
    patches = jax.lax.conv_general_dilated_patches(
        X, kernels, strides, padding,
        dimension_numbers=("NCHW", "OIHW", "NCHW")
    )
    oh, ow = patches.shape[2], patches.shape[3]
    return patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, -1)
