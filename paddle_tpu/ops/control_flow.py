"""Control-flow op lowerings: sub-block ops → lax control flow.

Reference: ``paddle/fluid/operators/controlflow/while_op.cc`` (interprets the
sub-block per iteration against step scopes) and
``conditional_block_op.cc``; ``recurrent_op.cc`` for StaticRNN.

TPU-native: the sub-block is lowered ONCE into a pure jax function and run
under ``lax.while_loop`` / ``lax.cond`` / ``lax.scan`` — no per-iteration
host dispatch, fully compiled, fixed shapes.  Loop state (the carry) is the
set of sub-block-written vars that are loop-carried (read before written, or
live-out); everything else is a per-iteration temporary.

LoDTensorArray (beam-search/RNN collectors) is a fixed-capacity device
buffer + length scalar — `array_write` is a dynamic_update_slice, the
TPU-static analogue of the reference's growable vector<LoDTensor>.
"""

import numpy as np

from .registry import register_op, EMPTY_VAR_NAME

SUB_BLOCK_OPS = ("while", "conditional_block", "recurrent",
                 "recurrent_grad", "conditional_block_grad", "while_grad",
                 "recompute_block", "recompute_block_grad")

ARRAY_CAPACITY_ATTR = "tensor_array_capacity"
DEFAULT_ARRAY_CAPACITY = 128


def _gather_inputs(op, env):
    ins = {}
    for slot, names in op.inputs.items():
        ins[slot] = [
            None if (not n or n == EMPTY_VAR_NAME) else env.get(n)
            for n in names
        ]
    return ins


def _carry_analysis(sub_block, outer_env):
    """Split sub-block-written vars into loop-carried vs temporaries.

    carried := written vars that are (a) read within the body before their
    first write (previous-iteration value used), or (b) present in the
    outer env (live-in/live-out state).
    """
    written_order = []
    written = set()
    read_before_write = set()
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written:
                read_before_write.add(n)
        for n in op.output_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written:
                written.add(n)
                written_order.append(n)
    carried = [
        n for n in written_order
        if n in read_before_write or n in outer_env
    ]
    return carried, written_order


def sub_block_external_reads(sub_block, exclude=()):
    """Names read by the sub-block before any write (closure captures)."""
    written = set(exclude)
    reads = []
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written and n not in reads:
                reads.append(n)
        written.update(op.output_arg_names)
    return reads


def _nonzero_cotangent(g, primal):
    import jax
    import jax.numpy as jnp

    if g is None:
        return jnp.zeros_like(primal)
    return g


def _clean_grad(g, primal):
    import jax
    import jax.numpy as jnp

    if g is None or g.dtype == jax.dtypes.float0:
        return jnp.zeros(jnp.shape(primal), jnp.float32)
    return g


def run_sub_block_op(op, block, env, ctx, run_block_fn):
    import jax
    import jax.numpy as jnp

    program = block.program
    sub_block = program.block(op.attrs["sub_block"])

    if op.type == "recurrent_grad":
        _run_recurrent_grad(op, sub_block, env, ctx, run_block_fn)
        return
    if op.type == "conditional_block_grad":
        _run_conditional_grad(op, sub_block, env, ctx, run_block_fn)
        return
    if op.type == "while_grad":
        _run_while_grad(op, sub_block, env, ctx, run_block_fn)
        return

    if op.type == "while":
        cond_name = op.inputs["Condition"][0]
        carried, written = _carry_analysis(sub_block, env)
        if cond_name not in carried:
            carried = carried + [cond_name]
        missing = [n for n in carried if n not in env]
        if missing:
            raise RuntimeError(
                "while op: loop-carried vars %s have no initial value "
                "before the loop" % missing
            )
        carry0 = {n: env[n] for n in carried}
        outer = dict(env)

        def body(carry):
            e = dict(outer)
            e.update(carry)
            run_block_fn(sub_block, e, ctx)
            return {n: e[n] for n in carried}

        def cond(carry):
            return jnp.reshape(carry[cond_name], ()).astype(bool)

        if ctx.probing and not op.attrs.get("max_trip_count"):
            # two-pass unbounded-while-grad support: concrete host loop
            # that counts trips (max over re-entries for nested loops).
            # Bounded whiles keep the lax path and are NOT recorded —
            # their counts would join the jit-cache key and trigger
            # spurious recompiles when the data-dependent count varies
            carry = carry0
            trips = 0
            while bool(cond(carry)):
                carry = body(carry)
                trips += 1
            idx = int(op.attrs["sub_block"])
            ctx.trip_counts[idx] = max(ctx.trip_counts.get(idx, 0), trips)
            env.update(carry)
            return

        final = jax.lax.while_loop(cond, body, carry0)
        env.update(final)
        return

    if op.type == "recompute_block":
        # forward of the remat region: a PLAIN run of the sub-block (this
        # call is never differentiated by jax — grads are explicit ops),
        # emitting every written name into env.  Unconsumed entries are
        # ordinary unbarriered values, so XLA DCEs them; the remat effect
        # lives entirely in the GRAD op's barriered re-forward.
        out_names = list(op.outputs.get("Out", []))
        cap = [n for n in op.inputs.get("Captured", [])
               or sub_block_external_reads(sub_block) if n in env]
        outer = dict(env)

        def region(cap_vals):
            e = dict(outer)
            e.update(dict(zip(cap, cap_vals)))
            run_block_fn(sub_block, e, ctx)
            return tuple(e[n] for n in out_names)

        # plain run: this call is never differentiated by jax (grads are
        # explicit ops), so the region's unexported intermediates die
        # here; the grad op recomputes them behind a barrier
        outs = region(tuple(env[n] for n in cap))
        env.update(dict(zip(out_names, outs)))
        return

    if op.type == "recompute_block_grad":
        _run_recompute_grad(op, sub_block, env, ctx, run_block_fn)
        return

    if op.type == "conditional_block":
        cond_val = env[op.inputs["Cond"][0]]
        carried, written = _carry_analysis(sub_block, env)
        outer = dict(env)
        branch_outs = [n for n in written if n in env] or carried
        branch_outs = list(dict.fromkeys(branch_outs))

        def true_fn(carry):
            e = dict(outer)
            e.update(carry)
            run_block_fn(sub_block, e, ctx)
            return {n: e[n] for n in branch_outs}

        def false_fn(carry):
            return dict(carry)

        carry0 = {n: env[n] for n in branch_outs}
        pred = jnp.reshape(cond_val, ()).astype(bool)
        result = jax.lax.cond(pred, true_fn, false_fn, carry0)
        env.update(result)
        return

    if op.type == "recurrent":
        _run_recurrent(op, sub_block, env, ctx, run_block_fn)
        return

    raise NotImplementedError(op.type)


def _block_carry_sets(sub_block):
    """Env-independent carry analysis: (written-in-order, read-before-write).

    The grad pass must reproduce the forward loop's math without depending on
    the runtime env contents, so it uses only block structure + the
    pre-loop snapshots recorded by the While layer."""
    written_order = []
    written = set()
    read_before_write = set()
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written:
                read_before_write.add(n)
        for n in op.output_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written:
                written.add(n)
                written_order.append(n)
    return written_order, read_before_write


def _run_while_grad(op, sub_block, env, ctx, run_block_fn):
    """Reverse-mode through a bounded `while`: re-run the loop as a
    lax.scan over ``max_trip_count`` steps with an active mask (the standard
    XLA answer to differentiating data-dependent loops — scan is
    transposable, while_loop is not), then jax.vjp w.r.t. the pre-loop
    carry values and the captured outer vars.

    Reference: ``paddle/fluid/operators/controlflow/while_op.cc``
    (WhileGradOp interprets the block in reverse per step scope); here the
    whole masked loop is one differentiable scan."""
    import jax
    import jax.numpy as jnp

    out_names = op.inputs.get("Out", [])
    gout_names = op.inputs.get("Out@GRAD", [])
    cap_names = op.inputs.get("Captured", [])
    cond_name = op.inputs["Condition"][0]
    snap_vars = op.attrs.get("snapshot_vars", [])
    snap_pres = op.attrs.get("snapshot_pres", [])
    pre_of = dict(zip(snap_vars, snap_pres))
    max_trip = int(op.attrs.get("max_trip_count") or 0)
    if not max_trip:
        # unbounded while: the executor's probe pass ran the loop on
        # concrete values and recorded the trip count; use it as the
        # static scan length (masking keeps extra steps inert; a
        # legitimately zero-trip loop scans 0 steps → zero grads)
        idx = int(op.attrs["sub_block"])
        if idx not in (ctx.trip_counts or {}):
            raise NotImplementedError(
                "gradients through an unbounded `while` need the "
                "executor's trip-count probe (Executor.run does this "
                "automatically); in this context pass "
                "While(cond, max_trip_count=N) or use StaticRNN"
            )
        max_trip = int(ctx.trip_counts[idx])

    written_order, read_before_write = _block_carry_sets(sub_block)
    carried = [
        n for n in written_order
        if n in read_before_write or n in pre_of
    ]
    if cond_name not in carried:
        carried.append(cond_name)

    init_vals = []
    for n in carried:
        pre = pre_of.get(n)
        if pre is not None and pre in env:
            init_vals.append(env[pre])
        elif n in env:
            # not written before the loop in the parent block: current env
            # value IS the pre-loop value (never snapshotted)
            init_vals.append(env[n])
        else:
            raise RuntimeError(
                "while_grad: no pre-loop value for carried var %r" % n
            )
    cap_vals = tuple(env[n] for n in cap_names)
    active0 = jnp.reshape(init_vals[carried.index(cond_name)], ()).astype(bool)
    outer = dict(env)

    def f(init_vals, cap_vals):
        caps = dict(zip(cap_names, cap_vals))

        def step(state, _):
            carry, active = state
            e = dict(outer)
            e.update(caps)
            e.update(dict(zip(carried, carry)))
            run_block_fn(sub_block, e, ctx)
            new_carry = tuple(
                jnp.where(active, e[n], old)
                for n, old in zip(carried, carry)
            )
            new_cond = jnp.reshape(
                new_carry[carried.index(cond_name)], ()
            ).astype(bool)
            return (new_carry, jnp.logical_and(active, new_cond)), None

        (final, _), _ = jax.lax.scan(
            step, (tuple(init_vals), active0), None, length=max_trip
        )
        # only float-dtype finals need cotangents
        return tuple(
            final[i] for i in range(len(carried))
            if jnp.issubdtype(final[i].dtype, jnp.inexact)
        )

    float_idx = [
        i for i, v in enumerate(init_vals)
        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)
    ]
    primal, vjp_fn = jax.vjp(f, tuple(init_vals), cap_vals)
    grad_of_out = dict(zip(out_names, gout_names))
    cots = []
    for k, i in enumerate(float_idx):
        n = carried[i]
        gname = grad_of_out.get(n)
        g = env.get(gname) if gname and gname != EMPTY_VAR_NAME else None
        if g is not None:
            cots.append(g.astype(primal[k].dtype))
        else:
            cots.append(jnp.zeros_like(primal[k]))
    ginit, gcap = vjp_fn(tuple(cots))
    gi_of = dict(zip(carried, ginit))
    names = op.outputs.get("StateIn@GRAD", [])
    for n, gn in zip(out_names, names):
        if gn and gn != EMPTY_VAR_NAME and n in gi_of:
            pre = pre_of.get(n)
            p = env[pre] if pre is not None and pre in env else env[n]
            env[gn] = _clean_grad(gi_of[n], p)
    names = op.outputs.get("Captured@GRAD", [])
    for n, g, p in zip(names, gcap, cap_vals):
        if n and n != EMPTY_VAR_NAME:
            env[n] = _clean_grad(g, p)


def _seq_lengths(env, op):
    """[B] int32 lengths from the optional sequence_length input (DynamicRNN
    masked-scan path); None for the StaticRNN full-length path."""
    import jax.numpy as jnp

    names = op.inputs.get("sequence_length", [])
    if not names or not names[0] or names[0] == EMPTY_VAR_NAME:
        return None
    lengths = jnp.reshape(env[names[0]], (-1,)).astype(jnp.int32)  # [B]
    return lengths


def _make_step(outer, sub_block, ctx, run_block_fn, op, masked):
    """Shared scan-step closure for recurrent fwd + grad lowerings."""
    import jax.numpy as jnp

    step_inputs = op.attrs["step_input_names"]
    state_names = op.attrs["state_names"]
    state_out_names = op.attrs["state_out_names"]
    step_output_names = op.attrs["step_output_names"]

    def step(caps, carry, xt, mt):
        e = dict(outer)
        e.update(caps)
        for name, val in zip(state_names, carry):
            e[name] = val
        for name, val in zip(step_inputs, xt):
            e[name] = val
        run_block_fn(sub_block, e, ctx)
        new_carry = tuple(e[n] for n in state_out_names)
        ys = tuple(e[n] for n in step_output_names)
        if masked:
            def bmask(v):
                return jnp.reshape(mt, (-1,) + (1,) * (v.ndim - 1))

            # inactive (t >= length) rows keep their previous state; padded
            # step outputs are zeroed (the padded-batch representation of
            # "no output at this step")
            new_carry = tuple(
                jnp.where(bmask(nv), nv, ov)
                for nv, ov in zip(new_carry, carry)
            )
            ys = tuple(jnp.where(bmask(y), y, jnp.zeros_like(y)) for y in ys)
        return new_carry, ys

    return step


def _run_recurrent(op, sub_block, env, ctx, run_block_fn):
    """StaticRNN (reference recurrent_op.cc): scan the sub-block over the
    time axis of the sequence inputs.  With attr time_major=False +
    a sequence_length input this is the DynamicRNN lowering: batch-major
    padded [B,T,...] sequences, state updates masked by t < length
    (the TPU-static replacement for the reference's lod_rank_table
    shrinking-batch reordering, control_flow.py:1700)."""
    import jax
    import jax.numpy as jnp

    seq_inputs = op.inputs.get("inputs", [])
    init_states = op.inputs.get("initial_states", [])  # [B, ...] outer vars
    outputs = op.outputs.get("outputs", [])          # stacked outs
    time_major = op.attrs.get("time_major", True)

    outer = dict(env)
    xs = [env[n] for n in seq_inputs]
    if not time_major:
        xs = [jnp.moveaxis(x, 1, 0) for x in xs]  # [B,T,...] -> [T,B,...]
    carry0 = tuple(env[n] for n in init_states)
    lengths = _seq_lengths(env, op)
    T = jnp.shape(xs[0])[0] if xs else int(op.attrs.get("max_len", 0))
    if lengths is not None:
        mask = jnp.arange(T)[:, None] < lengths[None, :]  # [T, B]
    else:
        mask = None

    step_fn = _make_step(outer, sub_block, ctx, run_block_fn, op,
                         masked=mask is not None)

    def step(carry, inp):
        xt, mt = inp
        return step_fn({}, carry, xt, mt)

    final_carry, stacked = jax.lax.scan(
        step, carry0, (tuple(xs), mask), length=None if xs else T
    )
    for name, val in zip(outputs, stacked):
        if not time_major:
            val = jnp.moveaxis(val, 0, 1)  # [T,B,...] -> [B,T,...]
        env[name] = val
    for name, val in zip(op.outputs.get("final_states", []), final_carry):
        env[name] = val


def _run_recurrent_grad(op, sub_block, env, ctx, run_block_fn):
    """Grad of the StaticRNN scan: jax.vjp over the SAME scan closure,
    differentiating w.r.t. sequence inputs, initial states, AND captured
    outer vars (the parameters used inside the step block) — the role of
    the reference's recurrent_grad op (recurrent_op.cc RecurrentGradOp)."""
    import jax
    import jax.numpy as jnp

    seq_names = op.inputs.get("inputs", [])
    init_names = op.inputs.get("initial_states", [])
    cap_names = op.inputs.get("Captured", [])
    out_names = op.inputs.get("outputs", [])
    gout_names = op.inputs.get("outputs@GRAD", [])
    time_major = op.attrs.get("time_major", True)
    outer = dict(env)
    lengths = _seq_lengths(env, op)

    def f(seq_vals, init_vals, cap_vals):
        caps = dict(zip(cap_names, cap_vals))
        xs = list(seq_vals)
        if not time_major:
            xs = [jnp.moveaxis(x, 1, 0) for x in xs]
        T = jnp.shape(xs[0])[0]
        mask = (jnp.arange(T)[:, None] < lengths[None, :]
                if lengths is not None else None)
        step_fn = _make_step(outer, sub_block, ctx, run_block_fn, op,
                             masked=mask is not None)

        def step(carry, inp):
            xt, mt = inp
            return step_fn(caps, carry, xt, mt)

        _, ys = jax.lax.scan(step, tuple(init_vals), (tuple(xs), mask))
        if not time_major:
            ys = tuple(jnp.moveaxis(y, 0, 1) for y in ys)
        return ys

    seq_vals = tuple(env[n] for n in seq_names)
    init_vals = tuple(env[n] for n in init_names)
    cap_vals = tuple(env[n] for n in cap_names)
    primal, vjp_fn = jax.vjp(f, seq_vals, init_vals, cap_vals)
    cots = []
    for i, p in enumerate(primal):
        gname = gout_names[i] if i < len(gout_names) else EMPTY_VAR_NAME
        g = env.get(gname) if gname and gname != EMPTY_VAR_NAME else None
        cots.append(_nonzero_cotangent(g, p))
    gseq, ginit, gcap = vjp_fn(tuple(cots))
    for slot, gvals, primals in (
        ("inputs@GRAD", gseq, seq_vals),
        ("initial_states@GRAD", ginit, init_vals),
        ("Captured@GRAD", gcap, cap_vals),
    ):
        names = op.outputs.get(slot, [])
        for n, g, p in zip(names, gvals, primals):
            if n and n != EMPTY_VAR_NAME:
                env[n] = _clean_grad(g, p)


def _run_recompute_grad(op, sub_block, env, ctx, run_block_fn):
    """Grad of recompute_block: jax.vjp over the region re-run from
    BARRIERED inputs.  The optimization_barrier on the captured values
    (jax.checkpoint's own mechanism) makes the recompute a distinct
    subgraph XLA cannot CSE with the forward op's chain — without it the
    'recompute' would alias the original activations and their liveness
    would span fwd→bwd again, defeating the remat."""
    import jax

    cap_names = op.inputs.get("Captured", [])
    out_names = op.inputs.get("Out", [])
    gout_names = op.inputs.get("Out@GRAD", [])
    outer = dict(env)

    def f(cap_vals):
        e = dict(outer)
        e.update(dict(zip(cap_names, cap_vals)))
        run_block_fn(sub_block, e, ctx)
        return tuple(e[n] for n in out_names)

    cap_vals = tuple(env[n] for n in cap_names)
    if cap_vals:
        cap_vals = jax.lax.optimization_barrier(cap_vals)
    primal, vjp_fn = jax.vjp(f, cap_vals)
    cots = []
    for i, p in enumerate(primal):
        gname = gout_names[i] if i < len(gout_names) else EMPTY_VAR_NAME
        g = env.get(gname) if gname and gname != EMPTY_VAR_NAME else None
        cots.append(_nonzero_cotangent(g, p))
    (gcap,) = vjp_fn(tuple(cots))
    names = op.outputs.get("Captured@GRAD", [])
    for n, g, p in zip(names, gcap, cap_vals):
        if n and n != EMPTY_VAR_NAME:
            env[n] = _clean_grad(g, p)


def _run_conditional_grad(op, sub_block, env, ctx, run_block_fn):
    """Grad of conditional_block via vjp over lax.cond, w.r.t. captured
    outer vars.  Note: grads w.r.t. the PRE-values of vars overwritten by
    the block (the false-branch passthrough) are not propagated — those
    pre-values are no longer live in the SSA env; typical conditional
    blocks (lr bands, metric branches) have no grad flow through them."""
    import jax
    import jax.numpy as jnp

    cond_name = op.inputs["Cond"][0]
    cap_names = op.inputs.get("Captured", [])
    out_names = op.inputs.get("Out", [])
    gout_names = op.inputs.get("Out@GRAD", [])
    outer = dict(env)
    pred = jnp.reshape(env[cond_name], ()).astype(bool)

    def f(cap_vals):
        caps = dict(zip(cap_names, cap_vals))

        def true_fn(cap):
            e = dict(outer)
            e.update(dict(zip(cap_names, cap)))
            run_block_fn(sub_block, e, ctx)
            return tuple(e[n] for n in out_names)

        def false_fn(cap):
            return tuple(outer[n] for n in out_names)

        return jax.lax.cond(pred, true_fn, false_fn, cap_vals)

    cap_vals = tuple(env[n] for n in cap_names)
    primal, vjp_fn = jax.vjp(f, cap_vals)
    cots = []
    for i, p in enumerate(primal):
        gname = gout_names[i] if i < len(gout_names) else EMPTY_VAR_NAME
        g = env.get(gname) if gname and gname != EMPTY_VAR_NAME else None
        cots.append(_nonzero_cotangent(g, p))
    (gcap,) = vjp_fn(tuple(cots))
    names = op.outputs.get("Captured@GRAD", [])
    for n, g, p in zip(names, gcap, cap_vals):
        if n and n != EMPTY_VAR_NAME:
            env[n] = _clean_grad(g, p)


# ---------------------------------------------------------------------------
# LoDTensorArray ops (reference: lod_tensor_array ops + lod_array_length_op)
# ---------------------------------------------------------------------------

def _no_infer(op, block):
    pass


@register_op("write_to_array", inputs=["X", "I", "Array"], outputs=["Out"],
             no_grad=True, infer_shape=_no_infer)
def write_to_array(ctx, attrs, X, I, Array):
    import jax
    import jax.numpy as jnp

    idx = jnp.reshape(I, ()).astype(jnp.int32)
    cap = int(attrs.get(ARRAY_CAPACITY_ATTR, DEFAULT_ARRAY_CAPACITY))
    if Array is None:
        buf = jnp.zeros((cap,) + tuple(jnp.shape(X)), X.dtype)
        length = jnp.asarray(0, jnp.int32)
    else:
        buf, length = Array["buffer"], Array["length"]
    buf = jax.lax.dynamic_update_index_in_dim(buf, X, idx, 0)
    return {"Out": {"buffer": buf, "length": jnp.maximum(length, idx + 1)}}


@register_op("read_from_array", inputs=["X", "I"], outputs=["Out"],
             no_grad=True, infer_shape=_no_infer)
def read_from_array(ctx, attrs, X, I):
    import jax
    import jax.numpy as jnp

    idx = jnp.reshape(I, ()).astype(jnp.int32)
    return jax.lax.dynamic_index_in_dim(X["buffer"], idx, 0, keepdims=False)


@register_op("lod_array_length", inputs=["X"], outputs=["Out"], no_grad=True,
             infer_shape=_no_infer)
def lod_array_length(ctx, attrs, X):
    import jax.numpy as jnp

    return jnp.reshape(X["length"], (1,)).astype(jnp.int32)


@register_op("split_lod_tensor", inputs=["X", "Mask"],
             outputs=["OutTrue", "OutFalse"])
def split_lod_tensor(ctx, attrs, X, Mask):
    """Reference split_lod_tensor_op.cc partitions rows by mask into two
    ragged tensors.  Under XLA static shapes both 'halves' keep the full
    batch (masked-execution semantics): the row selection happens at
    merge_lod_tensor, so each branch computes on all rows and inactive
    rows are discarded by the final select — the TPU-standard way to run
    data-dependent per-row branches."""
    return {"OutTrue": X, "OutFalse": X}


@register_op("merge_lod_tensor", inputs=["InTrue", "InFalse", "Mask", "X"],
             outputs=["Out"])
def merge_lod_tensor(ctx, attrs, InTrue, InFalse, Mask, X):
    """Row-wise select by mask (merge_lod_tensor_op.cc re-interleaving,
    expressed as a where select over the full batch)."""
    import jax.numpy as jnp

    m = Mask
    if m.ndim < InTrue.ndim:
        m = m.reshape(m.shape + (1,) * (InTrue.ndim - m.ndim))
    elif m.ndim > InTrue.ndim:
        m = m.reshape(m.shape[: InTrue.ndim])
    return jnp.where(m.astype(bool), InTrue, InFalse)


@register_op("lod_rank_table", inputs=["X"], outputs=["Out"], no_grad=True,
             infer_shape=_no_infer)
def lod_rank_table(ctx, attrs, X):
    """Reference lod_rank_table_op.cc sorts sequences by length for the
    shrinking-batch DynamicRNN.  Padded batches need no reorder: the
    'rank table' is the lengths tensor itself (descending sort indices
    attached for parity consumers)."""
    import jax.numpy as jnp

    lengths = jnp.reshape(X, (-1,)) if X.ndim <= 1 else \
        jnp.full((X.shape[0],), X.shape[1], jnp.int32)
    order = jnp.argsort(-lengths.astype(jnp.int32))
    return {"Out": {"lengths": lengths, "order": order}}


@register_op("max_sequence_len2", inputs=["RankTable"], outputs=["Out"],
             no_grad=True, infer_shape=_no_infer)
def max_sequence_len2(ctx, attrs, RankTable):
    import jax.numpy as jnp

    return jnp.max(RankTable["lengths"]).reshape(1).astype(jnp.int64)


@register_op("lod_tensor_to_array", inputs=["X", "RankTable"],
             outputs=["Out"], infer_shape=_no_infer)
def lod_tensor_to_array(ctx, attrs, X, RankTable):
    """Reference lod_tensor_to_array_op.cc slices a ragged batch into
    per-timestep tensors.  Padded [B, T, ...] form: the 'array' is the
    time-major view in a fixed-capacity buffer."""
    import jax.numpy as jnp

    tm = jnp.moveaxis(X, 1, 0)  # [T, B, ...]
    return {"Out": {"buffer": tm,
                    "length": jnp.asarray(tm.shape[0], jnp.int32)}}


@register_op("array_to_lod_tensor", inputs=["X", "RankTable"],
             outputs=["Out"], infer_shape=_no_infer)
def array_to_lod_tensor(ctx, attrs, X, RankTable):
    """Inverse of lod_tensor_to_array: stack the time-major buffer back
    to batch-major (array_to_lod_tensor_op.cc)."""
    import jax.numpy as jnp

    return jnp.moveaxis(X["buffer"], 0, 1)


@register_op("shrink_rnn_memory", inputs=["X", "RankTable", "I"],
             outputs=["Out"], infer_shape=_no_infer)
def shrink_rnn_memory(ctx, attrs, X, RankTable, I):
    """Reference shrink_rnn_memory_op.cc drops finished sequences from
    the RNN state as t grows; with masked-scan recurrence the state is
    full-width and masking handles completion — identity passthrough."""
    return X


@register_op("reorder_lod_tensor_by_rank", inputs=["X", "RankTable"],
             outputs=["Out"], infer_shape=_no_infer)
def reorder_lod_tensor_by_rank(ctx, attrs, X, RankTable):
    """Row reorder by the rank table's descending-length order
    (reorder_lod_tensor_by_rank_op.cc)."""
    return X[RankTable["order"]]


@register_op("tensor_array_to_tensor", inputs=["X"],
             outputs=["Out", "OutIndex"], infer_shape=_no_infer,
             stateful_outputs=("OutIndex",))
def tensor_array_to_tensor(ctx, attrs, X):
    """Concatenate the tensor-array buffer along `axis` with the leading
    array dim folded in (tensor_array_to_tensor_op.cc)."""
    import jax.numpy as jnp

    axis = int(attrs.get("axis", 1))
    buf = X["buffer"]  # [K, ...]
    k = buf.shape[0]
    parts = [buf[i] for i in range(k)]
    out = jnp.concatenate(parts, axis=axis) if axis != 0 else jnp.stack(
        parts, axis=0).reshape((-1,) + buf.shape[2:])
    sizes = jnp.full((k,), parts[0].shape[axis] if parts else 0, jnp.int32)
    return {"Out": out, "OutIndex": sizes}
