"""Control-flow op lowerings: sub-block ops → lax control flow.

Reference: ``paddle/fluid/operators/controlflow/while_op.cc`` (interprets the
sub-block per iteration against step scopes) and
``conditional_block_op.cc``; ``recurrent_op.cc`` for StaticRNN.

TPU-native: the sub-block is lowered ONCE into a pure jax function and run
under ``lax.while_loop`` / ``lax.cond`` / ``lax.scan`` — no per-iteration
host dispatch, fully compiled, fixed shapes.  Loop state (the carry) is the
set of sub-block-written vars that are loop-carried (read before written, or
live-out); everything else is a per-iteration temporary.

LoDTensorArray (beam-search/RNN collectors) is a fixed-capacity device
buffer + length scalar — `array_write` is a dynamic_update_slice, the
TPU-static analogue of the reference's growable vector<LoDTensor>.
"""

import numpy as np

from .registry import register_op, EMPTY_VAR_NAME

SUB_BLOCK_OPS = ("while", "conditional_block", "recurrent",
                 "recurrent_grad", "conditional_block_grad")

ARRAY_CAPACITY_ATTR = "tensor_array_capacity"
DEFAULT_ARRAY_CAPACITY = 128


def _gather_inputs(op, env):
    ins = {}
    for slot, names in op.inputs.items():
        ins[slot] = [
            None if (not n or n == EMPTY_VAR_NAME) else env.get(n)
            for n in names
        ]
    return ins


def _carry_analysis(sub_block, outer_env):
    """Split sub-block-written vars into loop-carried vs temporaries.

    carried := written vars that are (a) read within the body before their
    first write (previous-iteration value used), or (b) present in the
    outer env (live-in/live-out state).
    """
    written_order = []
    written = set()
    read_before_write = set()
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written:
                read_before_write.add(n)
        for n in op.output_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written:
                written.add(n)
                written_order.append(n)
    carried = [
        n for n in written_order
        if n in read_before_write or n in outer_env
    ]
    return carried, written_order


def sub_block_external_reads(sub_block, exclude=()):
    """Names read by the sub-block before any write (closure captures)."""
    written = set(exclude)
    reads = []
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n != EMPTY_VAR_NAME and n not in written and n not in reads:
                reads.append(n)
        written.update(op.output_arg_names)
    return reads


def _nonzero_cotangent(g, primal):
    import jax
    import jax.numpy as jnp

    if g is None:
        return jnp.zeros_like(primal)
    return g


def _clean_grad(g, primal):
    import jax
    import jax.numpy as jnp

    if g is None or g.dtype == jax.dtypes.float0:
        return jnp.zeros(jnp.shape(primal), jnp.float32)
    return g


def run_sub_block_op(op, block, env, ctx, run_block_fn):
    import jax
    import jax.numpy as jnp

    program = block.program
    sub_block = program.block(op.attrs["sub_block"])

    if op.type == "recurrent_grad":
        _run_recurrent_grad(op, sub_block, env, ctx, run_block_fn)
        return
    if op.type == "conditional_block_grad":
        _run_conditional_grad(op, sub_block, env, ctx, run_block_fn)
        return

    if op.type == "while":
        cond_name = op.inputs["Condition"][0]
        carried, written = _carry_analysis(sub_block, env)
        if cond_name not in carried:
            carried = carried + [cond_name]
        missing = [n for n in carried if n not in env]
        if missing:
            raise RuntimeError(
                "while op: loop-carried vars %s have no initial value "
                "before the loop" % missing
            )
        carry0 = {n: env[n] for n in carried}
        outer = dict(env)

        def body(carry):
            e = dict(outer)
            e.update(carry)
            run_block_fn(sub_block, e, ctx)
            return {n: e[n] for n in carried}

        def cond(carry):
            return jnp.reshape(carry[cond_name], ()).astype(bool)

        final = jax.lax.while_loop(cond, body, carry0)
        env.update(final)
        return

    if op.type == "conditional_block":
        cond_val = env[op.inputs["Cond"][0]]
        carried, written = _carry_analysis(sub_block, env)
        outer = dict(env)
        branch_outs = [n for n in written if n in env] or carried
        branch_outs = list(dict.fromkeys(branch_outs))

        def true_fn(carry):
            e = dict(outer)
            e.update(carry)
            run_block_fn(sub_block, e, ctx)
            return {n: e[n] for n in branch_outs}

        def false_fn(carry):
            return dict(carry)

        carry0 = {n: env[n] for n in branch_outs}
        pred = jnp.reshape(cond_val, ()).astype(bool)
        result = jax.lax.cond(pred, true_fn, false_fn, carry0)
        env.update(result)
        return

    if op.type == "recurrent":
        _run_recurrent(op, sub_block, env, ctx, run_block_fn)
        return

    raise NotImplementedError(op.type)


def _run_recurrent(op, sub_block, env, ctx, run_block_fn):
    """StaticRNN (reference recurrent_op.cc): scan the sub-block over the
    time axis of the sequence inputs."""
    import jax
    import jax.numpy as jnp

    seq_inputs = op.inputs.get("inputs", [])         # [B, T, ...] outer vars
    step_inputs = op.attrs["step_input_names"]       # per-step names in body
    init_states = op.inputs.get("initial_states", [])  # [B, ...] outer vars
    state_names = op.attrs["state_names"]            # pre-state name in body
    state_out_names = op.attrs["state_out_names"]    # post-state name in body
    step_output_names = op.attrs["step_output_names"]
    outputs = op.outputs.get("outputs", [])          # stacked [B,T,...] outs

    outer = dict(env)
    # StaticRNN steps over axis 0 (time-major [T, B, ...] inputs, matching
    # the reference's recurrent_op slicing)
    xs = [env[n] for n in seq_inputs]
    carry0 = tuple(env[n] for n in init_states)

    def step(carry, xt):
        e = dict(outer)
        for name, val in zip(state_names, carry):
            e[name] = val
        for name, val in zip(step_inputs, xt):
            e[name] = val
        run_block_fn(sub_block, e, ctx)
        new_carry = tuple(e[n] for n in state_out_names)
        ys = tuple(e[n] for n in step_output_names)
        return new_carry, ys

    final_carry, stacked = jax.lax.scan(step, carry0, tuple(xs))
    for name, val in zip(outputs, stacked):
        env[name] = val  # [T, B, ...]
    for name, val in zip(op.outputs.get("final_states", []), final_carry):
        env[name] = val


def _run_recurrent_grad(op, sub_block, env, ctx, run_block_fn):
    """Grad of the StaticRNN scan: jax.vjp over the SAME scan closure,
    differentiating w.r.t. sequence inputs, initial states, AND captured
    outer vars (the parameters used inside the step block) — the role of
    the reference's recurrent_grad op (recurrent_op.cc RecurrentGradOp)."""
    import jax
    import jax.numpy as jnp

    seq_names = op.inputs.get("inputs", [])
    init_names = op.inputs.get("initial_states", [])
    cap_names = op.inputs.get("Captured", [])
    out_names = op.inputs.get("outputs", [])
    gout_names = op.inputs.get("outputs@GRAD", [])
    step_inputs = op.attrs["step_input_names"]
    state_names = op.attrs["state_names"]
    state_out_names = op.attrs["state_out_names"]
    step_output_names = op.attrs["step_output_names"]
    outer = dict(env)

    def f(seq_vals, init_vals, cap_vals):
        caps = dict(zip(cap_names, cap_vals))

        def step(carry, xts):
            e = dict(outer)
            e.update(caps)
            for name, val in zip(state_names, carry):
                e[name] = val
            for name, val in zip(step_inputs, xts):
                e[name] = val
            run_block_fn(sub_block, e, ctx)
            return (
                tuple(e[n] for n in state_out_names),
                tuple(e[n] for n in step_output_names),
            )

        _, ys = jax.lax.scan(step, tuple(init_vals), tuple(seq_vals))
        return ys

    seq_vals = tuple(env[n] for n in seq_names)
    init_vals = tuple(env[n] for n in init_names)
    cap_vals = tuple(env[n] for n in cap_names)
    primal, vjp_fn = jax.vjp(f, seq_vals, init_vals, cap_vals)
    cots = []
    for i, p in enumerate(primal):
        gname = gout_names[i] if i < len(gout_names) else EMPTY_VAR_NAME
        g = env.get(gname) if gname and gname != EMPTY_VAR_NAME else None
        cots.append(_nonzero_cotangent(g, p))
    gseq, ginit, gcap = vjp_fn(tuple(cots))
    for slot, gvals, primals in (
        ("inputs@GRAD", gseq, seq_vals),
        ("initial_states@GRAD", ginit, init_vals),
        ("Captured@GRAD", gcap, cap_vals),
    ):
        names = op.outputs.get(slot, [])
        for n, g, p in zip(names, gvals, primals):
            if n and n != EMPTY_VAR_NAME:
                env[n] = _clean_grad(g, p)


def _run_conditional_grad(op, sub_block, env, ctx, run_block_fn):
    """Grad of conditional_block via vjp over lax.cond, w.r.t. captured
    outer vars.  Note: grads w.r.t. the PRE-values of vars overwritten by
    the block (the false-branch passthrough) are not propagated — those
    pre-values are no longer live in the SSA env; typical conditional
    blocks (lr bands, metric branches) have no grad flow through them."""
    import jax
    import jax.numpy as jnp

    cond_name = op.inputs["Cond"][0]
    cap_names = op.inputs.get("Captured", [])
    out_names = op.inputs.get("Out", [])
    gout_names = op.inputs.get("Out@GRAD", [])
    outer = dict(env)
    pred = jnp.reshape(env[cond_name], ()).astype(bool)

    def f(cap_vals):
        caps = dict(zip(cap_names, cap_vals))

        def true_fn(cap):
            e = dict(outer)
            e.update(dict(zip(cap_names, cap)))
            run_block_fn(sub_block, e, ctx)
            return tuple(e[n] for n in out_names)

        def false_fn(cap):
            return tuple(outer[n] for n in out_names)

        return jax.lax.cond(pred, true_fn, false_fn, cap_vals)

    cap_vals = tuple(env[n] for n in cap_names)
    primal, vjp_fn = jax.vjp(f, cap_vals)
    cots = []
    for i, p in enumerate(primal):
        gname = gout_names[i] if i < len(gout_names) else EMPTY_VAR_NAME
        g = env.get(gname) if gname and gname != EMPTY_VAR_NAME else None
        cots.append(_nonzero_cotangent(g, p))
    (gcap,) = vjp_fn(tuple(cots))
    names = op.outputs.get("Captured@GRAD", [])
    for n, g, p in zip(names, gcap, cap_vals):
        if n and n != EMPTY_VAR_NAME:
            env[n] = _clean_grad(g, p)


# ---------------------------------------------------------------------------
# LoDTensorArray ops (reference: lod_tensor_array ops + lod_array_length_op)
# ---------------------------------------------------------------------------

def _no_infer(op, block):
    pass


@register_op("write_to_array", inputs=["X", "I", "Array"], outputs=["Out"],
             no_grad=True, infer_shape=_no_infer)
def write_to_array(ctx, attrs, X, I, Array):
    import jax
    import jax.numpy as jnp

    idx = jnp.reshape(I, ()).astype(jnp.int32)
    cap = int(attrs.get(ARRAY_CAPACITY_ATTR, DEFAULT_ARRAY_CAPACITY))
    if Array is None:
        buf = jnp.zeros((cap,) + tuple(jnp.shape(X)), X.dtype)
        length = jnp.asarray(0, jnp.int32)
    else:
        buf, length = Array["buffer"], Array["length"]
    buf = jax.lax.dynamic_update_index_in_dim(buf, X, idx, 0)
    return {"Out": {"buffer": buf, "length": jnp.maximum(length, idx + 1)}}


@register_op("read_from_array", inputs=["X", "I"], outputs=["Out"],
             no_grad=True, infer_shape=_no_infer)
def read_from_array(ctx, attrs, X, I):
    import jax
    import jax.numpy as jnp

    idx = jnp.reshape(I, ()).astype(jnp.int32)
    return jax.lax.dynamic_index_in_dim(X["buffer"], idx, 0, keepdims=False)


@register_op("lod_array_length", inputs=["X"], outputs=["Out"], no_grad=True,
             infer_shape=_no_infer)
def lod_array_length(ctx, attrs, X):
    import jax.numpy as jnp

    return jnp.reshape(X["length"], (1,)).astype(jnp.int32)
