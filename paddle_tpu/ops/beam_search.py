"""Beam search ops, static-shape formulation.

Reference: ``paddle/fluid/operators/beam_search_op.cc`` (one expansion step
over LoD-encoded ragged beams) and ``beam_search_decode_op.cc`` (backtrace
of the beam tree recorded across steps into sentences).

TPU-native redesign: beams live in a dense ``[B, K]`` layout (batch ×
beam_size) instead of LoD offsets, so every step is one fused
``top_k(candidates.reshape(B, K*V))`` on device — no host-side ragged
bookkeeping.  The parent chain the reference encodes in the output LoD is
returned explicitly as ``parent_idx`` and replayed by ``beam_search_decode``
with a reverse scan.  Pruned/finished-beam semantics match the reference:
a beam that has emitted ``end_id`` keeps exactly one candidate (``end_id``
again, score unchanged), so it survives top-k without growing.

First-step convention: initialize ``pre_scores`` to ``[0, -1e9, ...]`` per
batch row so that all K identical start beams collapse to beam 0 (the
standard dense-beam trick replacing the reference's "lod has one source
item" case).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

NEG_INF = -1e9


@register_op(
    "beam_search",
    inputs=["pre_ids", "pre_scores", "ids", "scores"],
    outputs=["selected_ids", "selected_scores", "parent_idx"],
    no_grad=True)
def beam_search(ctx, attrs, pre_ids, pre_scores, ids, scores):
    """One beam expansion step.

    pre_ids [B, K] int: last chosen token per beam (end_id marks finished).
    pre_scores [B, K] float: cumulative log-prob per beam.
    scores [B, K, V] float: this step's per-token scores — log-probs when
    ``is_accumulated`` is False (added to pre_scores here), else already
    accumulated totals.
    ids: optional [B, K, V] candidate token table (defaults to 0..V-1).

    Returns selected_ids [B, K], selected_scores [B, K], parent_idx [B, K].
    """
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    is_accumulated = bool(attrs.get("is_accumulated", True))

    B, K, V = scores.shape
    pre_ids = pre_ids.reshape(B, K)
    pre_scores = pre_scores.reshape(B, K).astype(scores.dtype)

    if not is_accumulated:
        cand = pre_scores[:, :, None] + scores
    else:
        cand = scores

    finished = pre_ids == end_id  # [B, K]
    vocab_ids = jnp.arange(V, dtype=jnp.int32)[None, None, :]
    # finished beams: only the end_id column stays alive, score frozen
    frozen = jnp.where(vocab_ids == end_id, pre_scores[:, :, None],
                       jnp.asarray(NEG_INF, scores.dtype))
    cand = jnp.where(finished[:, :, None], frozen, cand)

    flat = cand.reshape(B, K * V)
    top_scores, top_idx = lax.top_k(flat, beam_size)  # [B, beam]
    parent = (top_idx // V).astype(jnp.int32)
    token = (top_idx % V).astype(jnp.int32)
    if ids is not None:
        token = jnp.take_along_axis(
            ids.reshape(B, K * V).astype(jnp.int32), top_idx, axis=1)
        # a selection from a finished beam is its frozen end candidate —
        # emit end_id itself, not the table entry at that column, so the
        # beam stays finished next step
        parent_finished = jnp.take_along_axis(finished, parent, axis=1)
        token = jnp.where(parent_finished, end_id, token)
    return (
        token.astype(jnp.int32),
        top_scores,
        parent,
    )


@register_op(
    "beam_search_decode",
    inputs=["Ids", "Scores", "ParentIdx"],
    outputs=["SentenceIds", "SentenceScores"],
    no_grad=True)
def beam_search_decode(ctx, attrs, Ids, Scores, ParentIdx):
    """Backtrace the beam tree (beam_search_decode_op.cc).

    Ids / Scores / ParentIdx: tensor arrays ({buffer, length}) written once
    per decode step — buffers [T, B, K] (ids/parents int, scores float).
    Returns SentenceIds [B, K, T] (positions past a sentence's end padded
    with end_id) and SentenceScores [B, K] (cumulative score of each final
    beam).
    """
    end_id = int(attrs["end_id"])

    ids_buf = Ids["buffer"]          # [T, B, K]
    parents_buf = ParentIdx["buffer"]
    length = Ids["length"]           # actual number of steps written
    T, B, K = ids_buf.shape

    # walk from the final beams backward; steps >= length are identity
    beam0 = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, :], (B, K))

    def step(cur, t):
        # cur [B, K]: beam index at step t+1 whose ancestry we are tracing
        valid = t < length
        tok = jnp.take_along_axis(ids_buf[t], cur, axis=1)      # [B, K]
        par = jnp.take_along_axis(parents_buf[t], cur, axis=1)
        tok = jnp.where(valid, tok, end_id)
        nxt = jnp.where(valid, par, cur)
        return nxt, tok

    _, toks_rev = lax.scan(step, beam0, jnp.arange(T - 1, -1, -1))
    sent = jnp.moveaxis(toks_rev[::-1], 0, -1)  # [B, K, T]

    scores_buf = Scores["buffer"]  # [T, B, K] cumulative per step
    last = jnp.clip(length - 1, 0, T - 1)
    final_scores = lax.dynamic_index_in_dim(scores_buf, last, 0,
                                            keepdims=False)  # [B, K]

    # positions after each sentence's first end_id → end_id padding
    emitted_end = jnp.cumsum((sent == end_id).astype(jnp.int32), axis=-1)
    after_end = emitted_end - (sent == end_id).astype(jnp.int32) > 0
    sent = jnp.where(after_end, end_id, sent)
    return sent.astype(jnp.int32), final_scores
