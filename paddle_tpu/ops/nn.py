"""Neural-network ops: softmax/losses, conv, pooling, norms, embedding,
dropout.

Reference kernels: ``paddle/fluid/operators/softmax_op.cc`` (+cuDNN variant),
``softmax_with_cross_entropy_op.cc``, ``conv_op.cc``/``conv_cudnn_op.cu.cc``,
``pool_op.cc``, ``batch_norm_op.cc``, ``layer_norm_op.cc``,
``lookup_table_op.cc``, ``dropout_op.cc``.  TPU-native notes:

* conv lowers to ``lax.conv_general_dilated`` — XLA tiles it onto the MXU;
  there is no cuDNN-style algorithm-choice surface.
* batch/layer norm are plain jnp expressions; XLA fuses the reductions. The
  cross-replica variant (sync BN) is the same expression with ``lax.pmean``
  under a mesh axis — see ops/collective.py.
* ``softmax_with_cross_entropy`` is written as logsumexp−logit so its
  autodiff-derived grad is exactly (softmax − onehot), matching the
  reference's hand-written fused grad kernel.
"""

import jax
import jax.numpy as jnp

from .registry import register_op
from .common import normalize_axis


@register_op("softmax", inputs=["X"], outputs=["Out"])
def softmax(ctx, attrs, X):
    axis = int(attrs.get("axis", -1))
    # f32 internals under bf16 AMP (exp/sum accumulate in f32; XLA fuses
    # the casts) — the standard TPU attention-softmax recipe
    if X.dtype == jnp.bfloat16:
        return jax.nn.softmax(X.astype(jnp.float32), axis=axis).astype(
            X.dtype)
    return jax.nn.softmax(X, axis=axis)


@register_op("log_softmax", inputs=["X"], outputs=["Out"])
def log_softmax(ctx, attrs, X):
    axis = int(attrs.get("axis", -1))
    return jax.nn.log_softmax(X, axis=axis)


@register_op("cross_entropy", inputs=["X", "Label"], outputs=["Y"])
def cross_entropy(ctx, attrs, X, Label):
    soft_label = attrs.get("soft_label", False)
    ignore_index = int(attrs.get("ignore_index", -100))
    eps = 1e-12
    if soft_label:
        loss = -jnp.sum(Label * jnp.log(X + eps), axis=-1, keepdims=True)
    else:
        lab = Label.reshape(Label.shape[:-1]) if Label.shape[-1] == 1 else Label
        lab = lab.astype(jnp.int32)
        picked = jnp.take_along_axis(
            X, jnp.maximum(lab, 0)[..., None], axis=-1
        )[..., 0]
        loss = -jnp.log(picked + eps)
        loss = jnp.where(lab == ignore_index, jnp.zeros_like(loss), loss)
        loss = loss[..., None]
    return loss


@register_op(
    "softmax_with_cross_entropy",
    inputs=["Logits", "Label"],
    outputs=["Softmax", "Loss"],
    stateful_outputs=("Softmax",),
)
def softmax_with_cross_entropy(ctx, attrs, Logits, Label):
    axis = normalize_axis(int(attrs.get("axis", -1)), jnp.ndim(Logits))
    soft_label = attrs.get("soft_label", False)
    ignore_index = int(attrs.get("ignore_index", -100))
    # f32 internals for bf16 logits (AMP): the logsumexp reduction and the
    # log-prob gather fuse with the upcast, so no f32 logits tensor is
    # materialized in HBM
    in_dtype = Logits.dtype
    if in_dtype == jnp.bfloat16:
        Logits = Logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(Logits, axis=axis, keepdims=True)
    log_softmax = Logits - lse
    if soft_label:
        loss = -jnp.sum(Label * log_softmax, axis=axis, keepdims=True)
    else:
        lab = Label
        if lab.shape[axis] == 1:
            lab = jnp.squeeze(lab, axis=axis)
        lab = lab.astype(jnp.int32)
        picked = jnp.take_along_axis(
            log_softmax, jnp.expand_dims(jnp.maximum(lab, 0), axis), axis=axis
        )
        loss = -picked
        mask = jnp.expand_dims(lab, axis) == ignore_index
        loss = jnp.where(mask, jnp.zeros_like(loss), loss)
    return {"Softmax": jax.lax.stop_gradient(
        jnp.exp(log_softmax).astype(in_dtype)), "Loss": loss}


@register_op("dropout", inputs=["X"], outputs=["Out", "Mask"],
             stateful_outputs=("Mask",))
def dropout(ctx, attrs, X):
    p = float(attrs.get("dropout_prob", 0.5))
    is_test = attrs.get("is_test", False) or ctx.mode == "infer"
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl == "upscale_in_train":
            out = X
        else:
            out = X * jnp.asarray(1.0 - p, X.dtype)
        return {"Out": out, "Mask": jnp.ones_like(X, dtype=jnp.uint8)}
    seed = int(attrs.get("seed", 0))
    # a user seed pins the stream deterministically but must still vary
    # per step/op — fold it into the per-step key rather than replacing it
    key = ctx.rng()
    if seed:
        key = jax.random.fold_in(key, seed)
    keep = jax.random.bernoulli(key, 1.0 - p, jnp.shape(X))
    if impl == "upscale_in_train":
        scale = 1.0 / (1.0 - p) if p < 1.0 else 0.0
        out = jnp.where(keep, X * jnp.asarray(scale, X.dtype), jnp.zeros_like(X))
    else:
        out = jnp.where(keep, X, jnp.zeros_like(X))
    return {"Out": out, "Mask": keep.astype(jnp.uint8)}


def _lookup(W, Ids, padding_idx):
    ids = Ids
    squeeze_last = ids.ndim > 1 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids[..., 0]
    ids = ids.astype(jnp.int32)
    out = jnp.take(W, jnp.maximum(ids, 0), axis=0)
    if padding_idx is not None and padding_idx != -1:
        out = jnp.where(
            (ids == padding_idx)[..., None], jnp.zeros_like(out), out
        )
    return out


@register_op("lookup_table", inputs=["W", "Ids"], outputs=["Out"])
def lookup_table(ctx, attrs, W, Ids):
    # reference op: Ids shaped [..., 1] int64 (lookup_table_op.cc); grad wrt W
    # is the vjp of take = scatter-add, XLA's native sparse-grad form on TPU
    return _lookup(W, Ids, attrs.get("padding_idx", -1))


@register_op("lookup_table_v2", inputs=["W", "Ids"], outputs=["Out"])
def lookup_table_v2(ctx, attrs, W, Ids):
    return _lookup(W, Ids, attrs.get("padding_idx", -1))


@register_op("embedding", inputs=["W", "Ids"], outputs=["Out"])
def embedding(ctx, attrs, W, Ids):
    return _lookup(W, Ids, attrs.get("padding_idx", -1))


@register_op("lookup_sparse_table", inputs=["W", "Ids"], outputs=["Out"])
def lookup_sparse_table(ctx, attrs, W, Ids):
    """PS-era auto-grown sparse table lookup
    (``lookup_sparse_table_op.cc``: rows materialize in the pserver hash
    table on first touch, init'd U(min,max)).  TPU-native the table is a
    dense row-sharded array, so every row already exists — the lookup
    degenerates to the plain gather; auto_grown_table/is_test only
    control the reference's hash-table bookkeeping and have no dense
    equivalent."""
    return _lookup(W, Ids, attrs.get("padding_idx", -1))


@register_op("one_hot", inputs=["X"], outputs=["Out"], no_grad=True)
def one_hot(ctx, attrs, X):
    depth = int(attrs.get("depth"))
    ids = X
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return jax.nn.one_hot(ids.astype(jnp.int32), depth, dtype=jnp.float32)


@register_op("one_hot_v2", inputs=["X"], outputs=["Out"], no_grad=True)
def one_hot_v2(ctx, attrs, X):
    depth = int(attrs.get("depth"))
    return jax.nn.one_hot(X.astype(jnp.int32), depth, dtype=jnp.float32)


@register_op(
    "layer_norm",
    inputs=["X", "Scale", "Bias"],
    outputs=["Y", "Mean", "Variance"],
    stateful_outputs=("Mean", "Variance"),
)
def layer_norm(ctx, attrs, X, Scale, Bias):
    begin = int(attrs.get("begin_norm_axis", 1))
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(begin, jnp.ndim(X)))
    x32 = X.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    # deliberately the TWO-pass variance (not batch_norm's single-pass
    # E[x^2]-E[x]^2): per-row LN stats see drifting residual-stream
    # means where the cancellation form loses all precision, and norm
    # is 0.2% of the profiled step — there is no perf win to buy here
    var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    # Scale/Bias are stored flattened over the normalized dims
    # (layer_norm_op.cc InferShape); broadcast them back over X's tail
    bshape = (1,) * begin + jnp.shape(X)[begin:]
    if Scale is not None:
        y = y * Scale.astype(jnp.float32).reshape(bshape)
    if Bias is not None:
        y = y + Bias.astype(jnp.float32).reshape(bshape)
    return {
        "Y": y.astype(X.dtype),
        "Mean": jnp.squeeze(mean, axes).reshape(-1),
        "Variance": jnp.squeeze(var, axes).reshape(-1),
    }


@register_op(
    "batch_norm",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    stateful_outputs=("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
)
def batch_norm(ctx, attrs, X, Scale, Bias, Mean, Variance):
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or attrs.get("use_global_stats", False)
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else jnp.ndim(X) - 1
    reduce_axes = tuple(i for i in range(jnp.ndim(X)) if i != c_axis)
    bshape = tuple(
        jnp.shape(X)[i] if i == c_axis else 1 for i in range(jnp.ndim(X))
    )
    x32 = X.astype(jnp.float32)
    if is_test:
        use_mean, use_var = Mean, Variance
        mean_out, var_out = Mean, Variance
        saved_mean, saved_var = Mean, Variance
    else:
        bm = jnp.mean(x32, axis=reduce_axes)
        # single-pass variance E[x^2] - E[x]^2: both reductions read x
        # ONCE (XLA fuses them into one sweep) instead of the dependent
        # two-pass mean(square(x - mean)) form, which forces a second
        # full pass over the activation per BN site.  f32 accumulation;
        # clamped >= 0 against cancellation on near-constant channels.
        bv = jnp.maximum(
            jnp.mean(jnp.square(x32), axis=reduce_axes) - jnp.square(bm),
            0.0)
        use_mean, use_var = bm, bv
        mean_out = Mean * momentum + bm * (1 - momentum)
        var_out = Variance * momentum + bv * (1 - momentum)
        saved_mean, saved_var = bm, jax.lax.rsqrt(bv + eps)
    y = (x32 - use_mean.reshape(bshape)) * jax.lax.rsqrt(
        use_var.reshape(bshape) + eps
    )
    y = y * Scale.reshape(bshape) + Bias.reshape(bshape)
    return {
        "Y": y.astype(X.dtype),
        "MeanOut": jax.lax.stop_gradient(mean_out),
        "VarianceOut": jax.lax.stop_gradient(var_out),
        "SavedMean": jax.lax.stop_gradient(saved_mean),
        "SavedVariance": jax.lax.stop_gradient(saved_var),
    }


def _conv_padding(paddings, ksize, dilations):
    if isinstance(paddings, str):
        return paddings  # 'SAME' / 'VALID'
    if len(paddings) == len(ksize):
        return [(p, p) for p in paddings]
    # already pairs
    return [
        (paddings[2 * i], paddings[2 * i + 1]) for i in range(len(ksize))
    ]


def _conv_transpose_padding(paddings, ksize, dilations):
    """Map the reference's symmetric transpose-conv padding p (output =
    (in-1)*s + dilated_k - 2p) onto jax.lax.conv_transpose's input-side
    pads of the fractionally-strided conv: lo = hi = d*(k-1) - p."""
    if isinstance(paddings, str):
        return paddings
    if len(paddings) == len(ksize):
        pairs = [(int(p), int(p)) for p in paddings]
    else:
        pairs = [(int(paddings[2 * i]), int(paddings[2 * i + 1]))
                 for i in range(len(ksize))]
    return [
        (d * (int(k) - 1) - lo, d * (int(k) - 1) - hi)
        for (lo, hi), k, d in zip(pairs, ksize, dilations)
    ]


def _conv_nd(ctx, attrs, Input, Filter, nd):
    strides = [int(s) for s in attrs.get("strides", [1] * nd)]
    paddings = attrs.get("paddings", [0] * nd)
    dilations = [int(d) for d in attrs.get("dilations", [1] * nd)]
    groups = int(attrs.get("groups", 1) or 1)
    layout = attrs.get("data_format", "NCHW")
    ksize = jnp.shape(Filter)[2:]
    pad = _conv_padding(paddings, ksize, dilations)
    if nd == 2:
        dn_in = "NCHW" if layout in ("NCHW", "AnyLayout") else "NHWC"
        dn = (dn_in, "OIHW", dn_in)
    else:
        dn_in = "NCDHW" if layout in ("NCDHW", "AnyLayout", "NCHW") else "NDHWC"
        dn = (dn_in, "OIDHW", dn_in)
    # NO preferred_element_type here: jax's conv transpose rule feeds the
    # fp32 cotangent of the widened output straight into a conv against
    # the bf16 filter and dies with a dtype mismatch — which would crash
    # every AMP conv BACKWARD at trace time (found pre-staging the
    # resnet50 AMP bench).  The natural bf16×bf16→bf16 conv is
    # numerically identical on TPU anyway: the MXU always accumulates in
    # fp32 internally and rounds once on output.
    out = jax.lax.conv_general_dilated(
        Input,
        Filter,
        window_strides=strides,
        padding=pad,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    return out.astype(jnp.result_type(Input, Filter))


@register_op("conv2d", inputs=["Input", "Filter"], outputs=["Output"])
def conv2d(ctx, attrs, Input, Filter):
    return _conv_nd(ctx, attrs, Input, Filter, 2)


@register_op("depthwise_conv2d", inputs=["Input", "Filter"], outputs=["Output"])
def depthwise_conv2d(ctx, attrs, Input, Filter):
    return _conv_nd(ctx, attrs, Input, Filter, 2)


@register_op("conv3d", inputs=["Input", "Filter"], outputs=["Output"])
def conv3d(ctx, attrs, Input, Filter):
    return _conv_nd(ctx, attrs, Input, Filter, 3)


@register_op("conv2d_transpose", inputs=["Input", "Filter"], outputs=["Output"])
def conv2d_transpose(ctx, attrs, Input, Filter):
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = attrs.get("paddings", [0, 0])
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    groups = int(attrs.get("groups", 1) or 1)
    ksize = jnp.shape(Filter)[2:]
    pad = _conv_transpose_padding(paddings, ksize, dilations)

    # kernel stays in the reference's [C_in, C_out/g, kh, kw] layout: under
    # transpose_kernel=True that is spec OIHW (O = the fwd conv's output =
    # C_in) — verified against the scatter oracle incl. C_in != C_out and
    # paddings (round-1 used IOHW, which breaks for C_in != C_out)
    def one(inp, flt):
        return jax.lax.conv_transpose(
            inp,
            flt,
            strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True,
        )

    if groups == 1:
        return one(Input, Filter)
    # grouped (conv_transpose_op.cc:67: out channels = filter_dims[1]*g):
    # static per-group slices; XLA fuses the g small convs + concat.
    return jnp.concatenate(
        [one(x, f) for x, f in zip(jnp.split(Input, groups, axis=1),
                                   jnp.split(Filter, groups, axis=0))],
        axis=1)


def _pool_nd(attrs, X, nd):
    """Shared max/avg pooling (pool_op.cc 2-D/3-D): global/adaptive
    handling + the trace-time-constant init for reduce_window (its grad
    rule, select-and-scatter, cannot linearize a traced init value)."""
    import numpy as np

    ptype = attrs.get("pooling_type", "max")
    ksize = [int(k) for k in attrs.get("ksize", [2] * nd)]
    strides = [int(s) for s in attrs.get("strides", [2] * nd)]
    paddings = [int(p) for p in attrs.get("paddings", [0] * nd)]
    global_pooling = attrs.get("global_pooling", False)
    adaptive = attrs.get("adaptive", False)
    exclusive = attrs.get("exclusive", True)
    # same predicate as the conv lowering (anything not NC* is
    # channels-last) — a mismatch would silently build a mixed-layout
    # model that traces fine and computes garbage
    channels_last = attrs.get("data_format", "NCHW") not in (
        "NCHW", "NCDHW", "AnyLayout")
    spatial = (jnp.shape(X)[1:-1] if channels_last
               else jnp.shape(X)[2:])
    if global_pooling or (adaptive and ksize == [1] * nd):
        ksize = list(spatial)
        strides = [1] * nd
        paddings = [0] * nd
    elif adaptive:
        ksize = [s // k for s, k in zip(spatial, ksize)]
        strides = list(ksize)
        paddings = [0] * nd
    if channels_last:
        window = (1,) + tuple(ksize) + (1,)
        wstrides = (1,) + tuple(strides) + (1,)
        pad = ((0, 0),) + tuple((p, p) for p in paddings) + ((0, 0),)
    else:
        window = (1, 1) + tuple(ksize)
        wstrides = (1, 1) + tuple(strides)
        pad = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    if ptype == "max":
        if jnp.issubdtype(X.dtype, jnp.floating):
            import ml_dtypes

            np_dt = (ml_dtypes.bfloat16 if X.dtype == jnp.bfloat16
                     else np.dtype(X.dtype))
            init = np.asarray(-np.inf, np_dt)
        else:
            init = np.asarray(np.iinfo(np.dtype(X.dtype)).min, X.dtype)
        return jax.lax.reduce_window(
            X, init, jax.lax.max, window, wstrides, pad)
    s = jax.lax.reduce_window(
        X.astype(jnp.float32), 0.0, jax.lax.add, window, wstrides, pad)
    if exclusive and any(paddings):
        ones_shape = ((1,) + tuple(spatial) + (1,) if channels_last
                      else (1, 1) + tuple(spatial))
        ones = jnp.ones(ones_shape, jnp.float32)
        cnt = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add, window, wstrides, pad)
        out = s / cnt
    else:
        import math as _math

        out = s / float(_math.prod(ksize))
    return out.astype(X.dtype)


@register_op("pool2d", inputs=["X"], outputs=["Out"])
def pool2d(ctx, attrs, X):
    return _pool_nd(attrs, X, 2)


@register_op("accuracy", inputs=["Out", "Indices", "Label"],
             outputs=["Accuracy", "Correct", "Total"], no_grad=True)
def accuracy(ctx, attrs, Out, Indices, Label):
    lab = Label
    if lab.ndim > 1 and lab.shape[-1] == 1:
        lab = lab[..., 0]
    hit = jnp.any(Indices == lab[:, None].astype(Indices.dtype), axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(lab.shape[0], jnp.int32)
    return {
        "Accuracy": (correct / total).astype(jnp.float32).reshape(1),
        "Correct": correct.reshape(1),
        "Total": total.reshape(1),
    }


@register_op("huber_loss", inputs=["X", "Y"], outputs=["Out", "Residual"],
             stateful_outputs=("Residual",))
def huber_loss(ctx, attrs, X, Y):
    delta = attrs.get("delta", 1.0)
    r = Y - X
    ar = jnp.abs(r)
    loss = jnp.where(
        ar <= delta, 0.5 * jnp.square(r), delta * (ar - 0.5 * delta)
    )
    return {"Out": loss, "Residual": jax.lax.stop_gradient(r)}


@register_op("square_error_cost", inputs=["X", "Y"], outputs=["Out"])
def square_error_cost(ctx, attrs, X, Y):
    return jnp.square(X - Y)


@register_op("sigmoid_cross_entropy_with_logits", inputs=["X", "Label"],
             outputs=["Out"])
def sigmoid_cross_entropy_with_logits(ctx, attrs, X, Label):
    ignore_index = attrs.get("ignore_index", -100)
    loss = jnp.maximum(X, 0) - X * Label + jnp.log1p(jnp.exp(-jnp.abs(X)))
    loss = jnp.where(Label == ignore_index, jnp.zeros_like(loss), loss)
    if attrs.get("normalize", False):
        norm = jnp.maximum(
            jnp.sum((Label != ignore_index).astype(loss.dtype)), 1.0
        )
        loss = loss / norm
    return loss


@register_op("smooth_l1_loss", inputs=["X", "Y", "InsideWeight", "OutsideWeight"],
             outputs=["Diff", "Out"], stateful_outputs=("Diff",))
def smooth_l1_loss(ctx, attrs, X, Y, InsideWeight, OutsideWeight):
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = X - Y
    if InsideWeight is not None:
        d = d * InsideWeight
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * jnp.square(d), ad - 0.5 / s2)
    if OutsideWeight is not None:
        loss = loss * OutsideWeight
    loss = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {"Diff": jax.lax.stop_gradient(d), "Out": loss}


@register_op("label_smooth", inputs=["X", "PriorDist"], outputs=["Out"])
def label_smooth(ctx, attrs, X, PriorDist):
    eps = attrs.get("epsilon", 0.0)
    if PriorDist is not None:
        return (1 - eps) * X + eps * PriorDist
    return (1 - eps) * X + eps / X.shape[-1]


@register_op("prelu", inputs=["X", "Alpha"], outputs=["Out"])
def prelu(ctx, attrs, X, Alpha):
    mode = attrs.get("mode", "all")
    if mode == "all":
        a = Alpha.reshape(())
    elif mode == "channel":
        a = Alpha.reshape((1, -1) + (1,) * (jnp.ndim(X) - 2))
    else:
        a = Alpha.reshape((1,) + jnp.shape(X)[1:])
    return jnp.where(X >= 0, X, a * X)


@register_op("fused_multihead_attention", inputs=["Q", "K", "V", "BiasQK"],
             outputs=["Out"])
def fused_multihead_attention(ctx, attrs, Q, K, V, BiasQK=None):
    """Fused scaled-dot-product attention (reference analogue: the
    fusion_* attention kernels under ``paddle/fluid/operators/fused/``).
    Q,K,V: [B, H, T, Dh]; BiasQK: additive key bias [B, Tk] or
    [B,1,1,Tk].  Lowered to the Pallas FlashAttention-2 TPU kernel when
    profitable, XLA attention otherwise (ops/pallas/flash_attention.py);
    its backward is the custom-vjp flash backward, reached through the
    registry's generic jax.vjp grad derivation."""
    from .pallas.flash_attention import flash_attention

    causal = bool(attrs.get("causal", False))
    scale = attrs.get("scale", None)
    if scale is not None:
        scale = float(scale)
    rate = float(attrs.get("dropout_rate", 0.0) or 0.0)
    if attrs.get("is_test"):
        rate = 0.0  # clone(for_test=True) flips this attr (framework.py)
    seed = None
    if rate > 0.0 and ctx.mode == "train":
        # per-step, per-op seed from the deterministic ctx key chain (the
        # grad op's recompute draws the SAME seed → identical mask)
        seed = jax.random.randint(ctx.rng(), (1,), 0, 2 ** 31 - 1,
                                  dtype=jnp.int32)
    else:
        rate = 0.0
    return flash_attention(Q, K, V, bias=BiasQK, causal=causal,
                           sm_scale=scale, dropout_rate=rate,
                           dropout_seed=seed)


@register_op("fused_dropout_add_ln", inputs=["X", "Residual", "Scale",
                                             "Bias"],
             outputs=["Out"])
def fused_dropout_add_ln(ctx, attrs, X, Residual, Scale, Bias):
    """``layer_norm(residual + dropout(x))`` in one Pallas pass
    (ops/pallas/fused_ln.py; reference analogue: the fused_elemwise /
    layer_norm JIT kernels).  X/Residual: [..., D] normalized over the
    last axis; Scale/Bias: [D]."""
    from .pallas.fused_ln import fused_dropout_add_ln as _fused

    rate = float(attrs.get("dropout_prob", 0.0) or 0.0)
    if attrs.get("is_test") or ctx.mode == "infer":
        rate = 0.0
    eps = float(attrs.get("epsilon", 1e-5))
    seed = None
    if rate > 0.0:
        # per-step, per-op seed from the deterministic ctx key chain
        # (the grad op's recompute draws the SAME seed/mask)
        seed = jax.random.randint(ctx.rng(), (1,), 0, 2 ** 31 - 1,
                                  dtype=jnp.int32)
    shape = jnp.shape(X)
    d = shape[-1]
    out = _fused(X.reshape(-1, d), Residual.reshape(-1, d), Scale, Bias,
                 dropout_rate=rate, eps=eps, seed=seed)
    return out.reshape(shape)


@register_op("fused_bias_act", inputs=["X", "Bias"], outputs=["Out"])
def fused_bias_act(ctx, attrs, X, Bias):
    """``act(x + bias)`` in one op — the fusion pipeline's rewrite of
    Fluid's ``fuse_elewise_add_act_pass`` (the fc bias+activation tail).
    Bit-exact by construction: it calls the SAME registered
    ``elementwise_add`` broadcast helper and the SAME registered
    activation lowering the unfused pair uses."""
    from .common import fluid_broadcast
    from .registry import get_op_def

    x, b = fluid_broadcast(X, Bias, attrs.get("axis", -1))
    y = jnp.add(x, b)
    act = attrs.get("act_type", "relu")
    return get_op_def(act).fn(ctx, dict(attrs), y)


@register_op(
    "fused_conv_bn_act",
    inputs=["Input", "Filter", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Out", "MeanOut", "VarianceOut"],
    stateful_outputs=("MeanOut", "VarianceOut"),
)
def fused_conv_bn_act(ctx, attrs, Input, Filter, Scale, Bias, Mean,
                      Variance):
    """conv2d → batch_norm → activation as one op (the reference's
    ``fuse_bn_act_ops`` pass + inference conv+bn fold, fused at train
    time too).  The conv runs through the SAME ``_conv_nd`` lowering as
    the unfused op (XLA owns the MXU schedule); the BN statistics use
    the SAME single-pass form as ``batch_norm``; the normalize+affine+
    act epilogue is one Pallas VMEM pass when eligible
    (ops/pallas/conv_bn_act.py) and the bit-exact XLA composite
    otherwise.  Running-stat updates (MeanOut/VarianceOut) ride along
    exactly as in ``batch_norm``."""
    from .pallas.conv_bn_act import bn_act_epilogue, epilogue_eligible

    conv = _conv_nd(ctx, attrs, Input, Filter, 2)
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) \
        or attrs.get("use_global_stats", False)
    layout = attrs.get("data_layout", attrs.get("data_format", "NCHW"))
    if layout == "AnyLayout":
        layout = "NCHW"
    c_axis = 1 if layout == "NCHW" else jnp.ndim(conv) - 1
    reduce_axes = tuple(i for i in range(jnp.ndim(conv)) if i != c_axis)
    bshape = tuple(
        jnp.shape(conv)[i] if i == c_axis else 1
        for i in range(jnp.ndim(conv)))
    x32 = conv.astype(jnp.float32)
    if is_test:
        use_mean, use_var = Mean, Variance
        mean_out, var_out = Mean, Variance
    else:
        bm = jnp.mean(x32, axis=reduce_axes)
        # single-pass E[x^2] - E[x]^2, clamped — identical to batch_norm
        bv = jnp.maximum(
            jnp.mean(jnp.square(x32), axis=reduce_axes) - jnp.square(bm),
            0.0)
        use_mean, use_var = bm, bv
        mean_out = Mean * momentum + bm * (1 - momentum)
        var_out = Variance * momentum + bv * (1 - momentum)
    act = attrs.get("act_type", "") or "identity"
    rows = 1
    for i in reduce_axes:
        rows *= jnp.shape(conv)[i]
    channels = jnp.shape(conv)[c_axis]
    if c_axis == jnp.ndim(conv) - 1 \
            and epilogue_eligible(rows, channels, act):
        rstd = jax.lax.rsqrt(use_var.astype(jnp.float32) + eps)
        out2d = bn_act_epilogue(
            conv.reshape(-1, channels), Scale, Bias, use_mean, rstd,
            act=act)
        y = out2d.reshape(jnp.shape(conv))
    else:
        # the XLA composite — the exact float sequence of the unfused
        # batch_norm lowering followed by the registered activation, so
        # fusion-on matches fusion-off bit-for-bit on this path
        y = (x32 - use_mean.reshape(bshape)) * jax.lax.rsqrt(
            use_var.reshape(bshape) + eps)
        y = y * Scale.reshape(bshape) + Bias.reshape(bshape)
        y = y.astype(conv.dtype)
        if act != "identity":
            from .registry import get_op_def

            y = get_op_def(act).fn(ctx, dict(attrs), y)
    return {
        "Out": y,
        "MeanOut": jax.lax.stop_gradient(mean_out),
        "VarianceOut": jax.lax.stop_gradient(var_out),
    }


@register_op("fused_embedding_gather", inputs=["W", "Ids"],
             outputs=["Out"])
def fused_embedding_gather(ctx, attrs, W, Ids):
    """Embedding lookup dispatched to the Pallas row-DMA gather kernel
    on TPU (ops/pallas/embedding.py; XLA take elsewhere) — the device-
    side form of the reference's distributed lookup_table prefetch.
    Semantics (clamping, padding_idx, scatter-add grad) are identical
    to ``lookup_table``, so the fusion rewrite is value-preserving."""
    from .pallas.embedding import embedding_gather

    return embedding_gather(W, Ids, attrs.get("padding_idx", -1))


@register_op("selu", inputs=["X"], outputs=["Out"])
def selu(ctx, attrs, X):
    """scale * (max(0,x) + min(0, alpha*(exp(x)-1))) (selu_op.cc)."""
    scale = float(attrs.get("scale", 1.0507009873554805))
    alpha = float(attrs.get("alpha", 1.6732632423543772))
    return scale * jnp.where(X > 0, X, alpha * (jnp.exp(X) - 1.0))


@register_op("multiplex", inputs=["X*", "Ids"], outputs=["Out"])
def multiplex(ctx, attrs, X, Ids):
    """Row-wise select among k candidate tensors (multiplex_op.cc):
    out[i] = X[ids[i]][i]."""
    stacked = jnp.stack(X, axis=0)  # [k, B, ...]
    ids = jnp.reshape(Ids, (-1,)).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[ids, rows]


@register_op("sampling_id", inputs=["X"], outputs=["Out"], no_grad=True)
def sampling_id(ctx, attrs, X):
    """Sample one column index per row of a probability matrix
    (sampling_id_op.cc)."""
    key = ctx.rng()
    return jax.random.categorical(
        key, jnp.log(jnp.maximum(X, 1e-38)), axis=-1
    ).astype(jnp.int64)


@register_op("uniform_random_batch_size_like", inputs=["Input"],
             outputs=["Out"], no_grad=True)
def uniform_random_batch_size_like(ctx, attrs, Input):
    from .common import resolve_dtype

    shape = [int(s) for s in attrs["shape"]]
    idx_in = int(attrs.get("input_dim_idx", 0))
    idx_out = int(attrs.get("output_dim_idx", 0))
    shape[idx_out] = Input.shape[idx_in]
    dtype = resolve_dtype(attrs.get("dtype", 5))
    lo = float(attrs.get("min", -1.0))
    hi = float(attrs.get("max", 1.0))
    return jax.random.uniform(ctx.rng(), shape, dtype, lo, hi)


@register_op("gaussian_random_batch_size_like", inputs=["Input"],
             outputs=["Out"], no_grad=True)
def gaussian_random_batch_size_like(ctx, attrs, Input):
    from .common import resolve_dtype

    shape = [int(s) for s in attrs["shape"]]
    idx_in = int(attrs.get("input_dim_idx", 0))
    idx_out = int(attrs.get("output_dim_idx", 0))
    shape[idx_out] = Input.shape[idx_in]
    dtype = resolve_dtype(attrs.get("dtype", 5))
    mean = float(attrs.get("mean", 0.0))
    std = float(attrs.get("std", 1.0))
    return mean + std * jax.random.normal(ctx.rng(), shape, dtype)


@register_op("add_position_encoding", inputs=["X"], outputs=["Out"])
def add_position_encoding(ctx, attrs, X):
    """alpha*x + beta*PE with PE[j, k<half] = sin(j / 10000^(k/(half-1))),
    PE[j, half+k] = cos(same) (add_position_encoding_op.h)."""
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    b, t, d = X.shape
    half = d // 2
    j = jnp.arange(t, dtype=jnp.float32)[:, None]
    k = jnp.arange(half, dtype=jnp.float32)[None, :]
    denom = jnp.power(10000.0, k / max(half - 1, 1))
    val = j / denom
    parts = [jnp.sin(val), jnp.cos(val)]
    if d % 2:
        # odd feature dim: the reference kernel leaves the last column
        # unwritten; define it as passthrough (pe = 0) instead of UB
        parts.append(jnp.zeros((t, 1), jnp.float32))
    pe = jnp.concatenate(parts, axis=1)  # [T, D]
    return alpha * X + beta * pe[None, :, :].astype(X.dtype)


@register_op("hash", inputs=["X"], outputs=["Out"], no_grad=True)
def hash_op(ctx, attrs, X):
    """num_hash integer hashes of each id row, mod mod_by (hash_op.h).
    The reference uses XXH64; here a splitmix64-style mix — deterministic
    and well-distributed, but NOT bit-identical to xxhash (documented
    deviation: hashed-embedding training is seed-compatible within this
    framework, not across frameworks)."""
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    x = X.astype(jnp.uint32)
    # combine each row's ids into one 32-bit state per hash seed
    outs = []
    for seed in range(num_hash):
        h = jnp.full(x.shape[:-1], 0x9E3779B9 * (seed + 1), jnp.uint32)
        for i in range(x.shape[-1]):
            v = x[..., i]
            v = v * jnp.uint32(0x85EBCA6B)
            v = v ^ (v >> 13)
            v = v * jnp.uint32(0xC2B2AE35)
            h = (h ^ v) * jnp.uint32(0x01000193)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    out = jnp.stack(outs, axis=-1)  # [..., num_hash]
    return out[..., None] if X.ndim == 2 else out


@register_op("data_norm", inputs=["X", "BatchSize", "BatchSum",
                                  "BatchSquareSum"],
             outputs=["Y", "Means", "Scales"],
             stateful_outputs=("Means", "Scales"))
def data_norm(ctx, attrs, X, BatchSize, BatchSum, BatchSquareSum):
    """CTR feature normalization (data_norm_op.cc): means = sum/size,
    scales = sqrt(size/square_sum); y = (x - means) * scales.  The stat
    accumulators are persistable params updated by the training loop."""
    means = BatchSum / BatchSize
    scales = jnp.sqrt(BatchSize / BatchSquareSum)
    y = (X - means[None, :]) * scales[None, :]
    return {"Y": y, "Means": means, "Scales": scales}


@register_op("spectral_norm", inputs=["Weight", "U", "V"], outputs=["Out"])
def spectral_norm(ctx, attrs, Weight, U, V):
    """Power-iteration spectral normalization (spectral_norm_op.h):
    repeat {v = W^T u / ||.||; u = W v / ||.||}; sigma = u^T W v;
    out = W / sigma.  dim selects the 'height' axis (transposed first)."""
    dim = int(attrs.get("dim", 0))
    power_iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    w = Weight
    perm = None
    if dim != 0:
        perm = [dim] + [i for i in range(w.ndim) if i != dim]
        w = jnp.transpose(w, perm)
    h = w.shape[0]
    mat = w.reshape(h, -1)
    u = jnp.reshape(U, (h,))
    v = jnp.reshape(V, (-1,))
    for _ in range(power_iters):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ (mat @ v)
    out = w / sigma
    if perm is not None:
        inv = [perm.index(i) for i in range(len(perm))]
        out = jnp.transpose(out, inv)
    return out


@register_op("row_conv", inputs=["X", "Filter"], outputs=["Out"])
def row_conv(ctx, attrs, X, Filter):
    """Lookahead row convolution (row_conv_op.cc): for padded [B,T,D]
    input and [K,D] filter, out[t] = sum_{i<K, t+i<T} x[t+i] * w[i]."""
    k = Filter.shape[0]
    b, t, d = X.shape
    out = jnp.zeros_like(X)
    for i in range(k):
        shifted = jnp.pad(X[:, i:, :], ((0, 0), (0, i), (0, 0)))
        out = out + shifted * Filter[i][None, None, :]
    return out


def _sampler_logq(sampler, ids, n):
    """log q(id) under the negative sampler (nce_op.h samplers):
    0=uniform, 1=log-uniform (Zipf: q(c)=log((c+2)/(c+1))/log(n+1))."""
    if sampler == 1:
        ids_f = ids.astype(jnp.float32)
        q = jnp.log((ids_f + 2.0) / (ids_f + 1.0)) / jnp.log(n + 1.0)
        return jnp.log(jnp.maximum(q, 1e-20))
    return jnp.full(jnp.shape(ids), -jnp.log(float(n)))


def _draw_negatives(ctx, sampler, k, n, seed=0):
    key = ctx.rng()
    if seed:
        key = jax.random.fold_in(key, int(seed))
    if sampler == 1:
        # inverse-CDF of the Zipfian log-uniform distribution
        u = jax.random.uniform(key, (k,))
        ids = jnp.exp(u * jnp.log(n + 1.0)) - 1.0
        return jnp.clip(ids.astype(jnp.int32), 0, n - 1)
    return jax.random.randint(key, (k,), 0, n, jnp.int32)


@register_op("nce", inputs=["Input", "Label", "Weight", "Bias",
                            "SampleWeight"],
             outputs=["Cost", "SampleLogits", "SampleLabels"],
             stateful_outputs=("SampleLogits", "SampleLabels"))
def nce(ctx, attrs, Input, Label, Weight, Bias, SampleWeight):
    """Noise-contrastive estimation (nce_op.h): binary logistic loss for
    the true class against k sampled noise classes with the sampler-
    probability correction s - log(k*q)."""
    k = int(attrs.get("num_neg_samples", 10))
    n = int(attrs.get("num_total_classes"))
    sampler = int(attrs.get("sampler", 0))
    B = Input.shape[0]
    lbl = jnp.reshape(Label, (B, -1))[:, 0].astype(jnp.int32)
    neg = _draw_negatives(ctx, sampler, k, n,
                          attrs.get("seed", 0))  # [K], shared across batch
    # true-class logit: row-wise dot, not a [B,B] matmul
    s_true = jnp.einsum("bd,bd->b", Input, Weight[lbl])[:, None]
    if Bias is not None:
        s_true = s_true + jnp.reshape(Bias, (-1,))[lbl][:, None]
    s_neg = jnp.matmul(Input, Weight[neg].T)  # [B, K]
    if Bias is not None:
        s_neg = s_neg + jnp.reshape(Bias, (-1,))[neg][None, :]
    adj_true = s_true - (jnp.log(float(k)) + _sampler_logq(sampler, lbl, n)
                         )[:, None]
    adj_neg = s_neg - (jnp.log(float(k)) + _sampler_logq(sampler, neg, n)
                       )[None, :]
    # -log sigma(true) - sum log(1 - sigma(neg)), in stable softplus form
    cost = (jnp.logaddexp(0.0, -adj_true)[:, 0]
            + jnp.sum(jnp.logaddexp(0.0, adj_neg), axis=1))
    if SampleWeight is not None:
        cost = cost * jnp.reshape(SampleWeight, (-1,))
    sample_logits = jnp.concatenate([s_true, s_neg], axis=1)
    sample_labels = jnp.concatenate(
        [lbl[:, None], jnp.broadcast_to(neg[None, :], (B, k))], axis=1)
    return {"Cost": cost[:, None], "SampleLogits": sample_logits,
            "SampleLabels": sample_labels.astype(jnp.int64)}


@register_op("hierarchical_sigmoid", inputs=["X", "W", "Label", "Bias"],
             outputs=["Out", "PreOut"], stateful_outputs=("PreOut",))
def hierarchical_sigmoid(ctx, attrs, X, W, Label, Bias):
    """Hierarchical sigmoid over the complete binary 'SimpleCode' tree
    (hierarchical_sigmoid_op.h + framework MatrixBitCode): for class c,
    code = c + num_classes; node j has index (code>>(j+1))-1 and bit
    (code>>j)&1; loss = sum_j BCE(sigmoid(x.w_idx + b_idx), bit)."""
    n = int(attrs.get("num_classes"))
    B = X.shape[0]
    lbl = jnp.reshape(Label, (B,)).astype(jnp.int32)
    code = lbl + n
    import math as _math

    max_len = int(_math.ceil(_math.log2(2 * n)))
    losses = jnp.zeros((B,), jnp.float32)
    length = jnp.floor(
        jnp.log2(code.astype(jnp.float32) + 1e-6)).astype(jnp.int32)
    for j in range(max_len):
        idx = (code >> (j + 1)) - 1          # [B]
        bit = ((code >> j) & 1).astype(jnp.float32)
        valid = j < length
        idx_safe = jnp.clip(idx, 0, W.shape[0] - 1)
        pre = jnp.sum(X * W[idx_safe], axis=1)
        if Bias is not None:
            pre = pre + jnp.reshape(Bias, (-1,))[idx_safe]
        # BCE with logit `pre`, label `bit`
        term = jnp.logaddexp(0.0, pre) - bit * pre
        losses = losses + jnp.where(valid, term, 0.0)
    return {"Out": losses[:, None],
            "PreOut": jnp.zeros((B, max_len), jnp.float32)}


@register_op("sampled_softmax_with_cross_entropy",
             inputs=["Logits", "Label"], outputs=["Softmax", "Loss"],
             stateful_outputs=("Softmax",))
def sampled_softmax_with_cross_entropy(ctx, attrs, Logits, Label):
    """Softmax CE over {true, S sampled} classes with -log q correction
    (reference python sampled_softmax_with_cross_entropy →
    sample_logits_op + softmax; single fused lowering here)."""
    s_count = int(attrs.get("num_samples", 10))
    B, C = Logits.shape
    lbl = jnp.reshape(Label, (B,)).astype(jnp.int32)
    neg = _draw_negatives(ctx, 1, s_count, C, attrs.get("seed", 0))
    s_true = jnp.take_along_axis(Logits, lbl[:, None], axis=1)
    s_neg = jnp.take(Logits, neg, axis=1)
    adj_true = s_true - _sampler_logq(1, lbl, C)[:, None]
    adj_neg = s_neg - _sampler_logq(1, neg, C)[None, :]
    if attrs.get("remove_accidental_hits", True):
        # a sampled negative equal to the true label would double-count
        # the true class in the denominator; mask it out (reference
        # sample_logits_op remove_accidental_hits)
        hit = neg[None, :] == lbl[:, None]
        adj_neg = jnp.where(hit, -1e30, adj_neg)
    z = jnp.concatenate([adj_true, adj_neg], axis=1)  # true at col 0
    logp = jax.nn.log_softmax(z, axis=1)
    return {"Loss": -logp[:, :1], "Softmax": jnp.exp(logp)}


@register_op("conv3d_transpose", inputs=["Input", "Filter"],
             outputs=["Output"])
def conv3d_transpose(ctx, attrs, Input, Filter):
    """NCDHW transposed 3-D conv (conv3d_transpose variant of
    conv_transpose_op.cc)."""
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1])]
    paddings = attrs.get("paddings", [0, 0, 0])
    dilations = [int(d) for d in attrs.get("dilations", [1, 1, 1])]
    groups = int(attrs.get("groups", 1) or 1)

    ksize = jnp.shape(Filter)[2:]
    pad = _conv_transpose_padding(paddings, ksize, dilations)

    def one(inp, flt):
        return jax.lax.conv_transpose(
            inp, flt, strides=strides, padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
            transpose_kernel=True,
        )

    if groups == 1:
        return one(Input, Filter)
    return jnp.concatenate(
        [one(x, f) for x, f in zip(jnp.split(Input, groups, axis=1),
                                   jnp.split(Filter, groups, axis=0))],
        axis=1)


@register_op("pool3d", inputs=["X"], outputs=["Out"])
def pool3d(ctx, attrs, X):
    """NCDHW pooling (pool_op.cc 3-D registration)."""
    return _pool_nd(attrs, X, 3)


@register_op("group_norm", inputs=["X", "Scale", "Bias"],
             outputs=["Y", "Mean", "Variance"],
             stateful_outputs=("Mean", "Variance"))
def group_norm_op(ctx, attrs, X, Scale, Bias):
    """Group normalization (group_norm_op.cc): NCHW, stats per (n, group)."""
    g = int(attrs.get("groups", 1))
    eps = float(attrs.get("epsilon", 1e-5))
    n, c = X.shape[0], X.shape[1]
    xg = X.reshape((n, g, c // g) + X.shape[2:]).astype(jnp.float32)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    # single-pass E[x^2]-E[x]^2 (see batch_norm); stats in f32 — the
    # cancellation form needs full-precision accumulation under AMP
    var = jnp.maximum(
        jnp.mean(jnp.square(xg), axis=axes, keepdims=True)
        - jnp.square(mean), 0.0)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(X.shape)
    shape = (1, c) + (1,) * (X.ndim - 2)
    if Scale is not None:
        y = y * Scale.reshape(shape).astype(jnp.float32)
    if Bias is not None:
        y = y + Bias.reshape(shape).astype(jnp.float32)
    return {"Y": y.astype(X.dtype), "Mean": mean.reshape(n, g),
            "Variance": var.reshape(n, g)}


@register_op(
    "sync_batch_norm",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    stateful_outputs=("MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance"),
)
def sync_batch_norm(ctx, attrs, X, Scale, Bias, Mean, Variance):
    """Cross-device batch norm (sync_batch_norm_op.cu).  Under jit+GSPMD
    batch stats of a batch-sharded input are ALREADY global, so this is
    the plain batch_norm lowering registered under the sync name
    (tests/test_grad_accum_syncbn.py proves the global-stats parity)."""
    from .registry import get_op_def

    return get_op_def("batch_norm").fn(ctx, attrs, X, Scale, Bias, Mean,
                                       Variance)
