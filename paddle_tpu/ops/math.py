"""Matmul / elementwise / reduction ops.

Reference kernels: ``paddle/fluid/operators/mul_op.cc`` (cuBLAS via
``math/blas.h``), ``matmul_op.cc``, ``elementwise/*``, ``reduce_ops/*``,
``mean_op.cc``.  On TPU these lower to jnp/lax so XLA schedules them on the
MXU (matmuls accumulate in fp32 via preferred_element_type when inputs are
bf16) and fuses the elementwise ops into neighbors.
"""

import jax.numpy as jnp

from .registry import register_op
from .common import fluid_broadcast


def _mm_accum_dtype(x, y):
    d = jnp.result_type(x, y)
    if d in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return None


@register_op("mul", inputs=["X", "Y"], outputs=["Out"])
def mul(ctx, attrs, X, Y):
    import math as _math

    xd = int(attrs.get("x_num_col_dims", 1))
    yd = int(attrs.get("y_num_col_dims", 1))
    xs, ys = jnp.shape(X), jnp.shape(Y)
    xm = X.reshape(_math.prod(xs[:xd]), -1) if len(xs) != 2 or xd != 1 else X
    ym = Y.reshape(_math.prod(ys[:yd]), -1) if len(ys) != 2 or yd != 1 else Y
    out = jnp.matmul(xm, ym, preferred_element_type=_mm_accum_dtype(X, Y))
    out = out.astype(jnp.result_type(X, Y))
    return out.reshape(xs[:xd] + ys[yd:])


@register_op("matmul", inputs=["X", "Y"], outputs=["Out"])
def matmul(ctx, attrs, X, Y):
    x, y = X, Y
    if attrs.get("transpose_X", False):
        axes = list(range(jnp.ndim(x)))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        x = jnp.transpose(x, axes) if jnp.ndim(x) > 1 else x
    if attrs.get("transpose_Y", False):
        axes = list(range(jnp.ndim(y)))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        y = jnp.transpose(y, axes) if jnp.ndim(y) > 1 else y
    out = jnp.matmul(x, y, preferred_element_type=_mm_accum_dtype(x, y))
    out = out.astype(jnp.result_type(X, Y))
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return out


def _elementwise(name, fn):
    @register_op(name, inputs=["X", "Y"], outputs=["Out"])
    def _op(ctx, attrs, X, Y, _fn=fn):
        x, y = fluid_broadcast(X, Y, attrs.get("axis", -1))
        return _fn(x, y)

    return _op


_elementwise("elementwise_add", jnp.add)
_elementwise("elementwise_sub", jnp.subtract)
_elementwise("elementwise_mul", jnp.multiply)
_elementwise("elementwise_div", jnp.divide)
_elementwise("elementwise_min", jnp.minimum)
_elementwise("elementwise_max", jnp.maximum)
_elementwise("elementwise_pow", jnp.power)
_elementwise("elementwise_mod", jnp.mod)
_elementwise("elementwise_floordiv", jnp.floor_divide)


def _reduce_axes(attrs, x):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % jnp.ndim(x) if d < 0 else d for d in dim)


def _reduction(name, fn):
    @register_op(name, inputs=["X"], outputs=["Out"])
    def _op(ctx, attrs, X, _fn=fn):
        axes = _reduce_axes(attrs, X)
        keep = attrs.get("keep_dim", False)
        out = _fn(X, axis=axes, keepdims=keep)
        if jnp.ndim(out) == 0:
            out = out.reshape(1)  # reference reduces to shape [1], not []
        return out

    return _op


_reduction("reduce_sum", jnp.sum)
_reduction("reduce_mean", jnp.mean)
_reduction("reduce_max", jnp.max)
_reduction("reduce_min", jnp.min)
_reduction("reduce_prod", jnp.prod)
_reduction("reduce_all", jnp.all)
_reduction("reduce_any", jnp.any)


@register_op("mean", inputs=["X"], outputs=["Out"])
def mean(ctx, attrs, X):
    return jnp.mean(X).reshape(1)


@register_op("pow", inputs=["X"], outputs=["Out"])
def pow_op(ctx, attrs, X):
    return jnp.power(X, jnp.asarray(attrs.get("factor", 1.0), X.dtype))


@register_op("top_k", inputs=["X"], outputs=["Out", "Indices"])
def top_k(ctx, attrs, X):
    import jax

    k = int(attrs.get("k", 1))
    vals, idx = jax.lax.top_k(X, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int32)}


@register_op("arg_max", inputs=["X"], outputs=["Out"], no_grad=True)
def arg_max(ctx, attrs, X):
    axis = int(attrs.get("axis", -1))
    return jnp.argmax(X, axis=axis).astype(jnp.int32)


@register_op("arg_min", inputs=["X"], outputs=["Out"], no_grad=True)
def arg_min(ctx, attrs, X):
    axis = int(attrs.get("axis", -1))
    return jnp.argmin(X, axis=axis).astype(jnp.int32)


@register_op("argsort", inputs=["X"], outputs=["Out", "Indices"], no_grad=True)
def argsort(ctx, attrs, X):
    axis = int(attrs.get("axis", -1))
    idx = jnp.argsort(X, axis=axis)
    return {"Out": jnp.sort(X, axis=axis), "Indices": idx.astype(jnp.int32)}


@register_op("cumsum", inputs=["X"], outputs=["Out"])
def cumsum(ctx, attrs, X):
    axis = attrs.get("axis", -1)
    x = X
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    if attrs.get("reverse", False):
        out = jnp.flip(
            jnp.cumsum(jnp.flip(x, axis=axis), axis=axis), axis=axis
        )
    else:
        out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    return out


@register_op("maximum", inputs=["X", "Y"], outputs=["Out"])
def maximum(ctx, attrs, X, Y):
    return jnp.maximum(X, Y)


@register_op("minimum", inputs=["X", "Y"], outputs=["Out"])
def minimum(ctx, attrs, X, Y):
    return jnp.minimum(X, Y)
