"""Detection ops: SSD/YOLO/RPN box generation, coding, NMS, ROI pooling.

Reference: ``paddle/fluid/operators/detection/`` (prior_box_op.h,
box_coder_op.h, yolo_box_op.h, multiclass_nms_op.cc, iou_similarity_op.h,
box_clip_op.h, anchor_generator_op.h, density_prior_box_op.h,
sigmoid_focal_loss_op.cc, polygon_box_transform_op.cc) and
``paddle/fluid/operators/roi_align_op.cc``.

TPU-native design notes:

* All shapes are static.  The reference's ``multiclass_nms`` emits a
  variable-row LoDTensor ``[M, 6]``; here the output is a fixed
  ``[N, keep_top_k, 6]`` tensor padded with rows of ``-1`` (the reference
  itself uses ``label = -1`` rows to signal "no detection"), plus an
  ``NmsRoisNum``-style count output.  Downstream consumers mask on
  ``label >= 0``.
* NMS is the classic greedy suppression re-expressed as a
  ``lax.fori_loop`` over a statically sized candidate set with an O(k²)
  IoU matrix — sequential dependencies live in a tiny boolean carry while
  the heavy work (IoU matrix) is one batched computation on the MXU-adjacent
  vector unit; classes and batch are handled by ``vmap``.
* ``roi_align`` is expressed with gather-based bilinear interpolation so the
  whole op is differentiable w.r.t. ``X`` via the registry's generic vjp;
  the data-dependent adaptive sampling grid of the reference
  (``sampling_ratio <= 0`` → ``ceil(roi_size/pooled_size)``) is replaced by
  a static grid (``sampling_ratio`` when positive, else 2) because XLA
  requires static shapes.  Batch membership of ROIs comes from an explicit
  ``RoisNum`` [B] companion instead of LoD offsets (sequence-op convention).
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    """prior_box_op.h:28 ExpandAspectRatios: dedup, prepend 1.0, add 1/r."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def _box_area(boxes, normalized):
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    if not normalized:
        w = w + 1.0
        h = h + 1.0
    area = w * h
    # invalid box (xmax < xmin) → 0 (multiclass_nms_op.cc BBoxArea)
    valid = (boxes[..., 2] >= boxes[..., 0]) & (boxes[..., 3] >= boxes[..., 1])
    return jnp.where(valid, area, 0.0)


def _pairwise_iou(a, b, normalized):
    """[..., Na, 4] x [..., Nb, 4] -> [..., Na, Nb] Jaccard overlap."""
    norm = 0.0 if normalized else 1.0
    xmin = jnp.maximum(a[..., :, None, 0], b[..., None, :, 0])
    ymin = jnp.maximum(a[..., :, None, 1], b[..., None, :, 1])
    xmax = jnp.minimum(a[..., :, None, 2], b[..., None, :, 2])
    ymax = jnp.minimum(a[..., :, None, 3], b[..., None, :, 3])
    iw = jnp.maximum(xmax - xmin + norm, 0.0)
    ih = jnp.maximum(ymax - ymin + norm, 0.0)
    inter = iw * ih
    area_a = _box_area(a, normalized)[..., :, None]
    area_b = _box_area(b, normalized)[..., None, :]
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


# ---------------------------------------------------------------------------
# prior / anchor generation
# ---------------------------------------------------------------------------

def _prior_box_shapes(min_sizes, max_sizes, aspect_ratios, flip):
    ars = _expand_aspect_ratios(aspect_ratios, flip)
    num = len(ars) * len(min_sizes) + len(max_sizes)
    return ars, num


@register_op("prior_box", inputs=["Input", "Image"],
             outputs=["Boxes", "Variances"], no_grad=True)
def prior_box(ctx, attrs, Input, Image):
    """SSD prior boxes (prior_box_op.h:52).  Out: [H, W, P, 4] each."""
    min_sizes = [float(v) for v in attrs.get("min_sizes", [])]
    max_sizes = [float(v) for v in attrs.get("max_sizes", [])]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    flip = bool(attrs.get("flip", False))
    clip = bool(attrs.get("clip", False))
    step_w = float(attrs.get("step_w", 0.0))
    step_h = float(attrs.get("step_h", 0.0))
    offset = float(attrs.get("offset", 0.5))
    mmar_order = bool(attrs.get("min_max_aspect_ratios_order", False))

    ars, num_priors = _prior_box_shapes(
        min_sizes, max_sizes, attrs.get("aspect_ratios", []), flip)

    img_h, img_w = Image.shape[2], Image.shape[3]
    feat_h, feat_w = Input.shape[2], Input.shape[3]
    step_width = step_w if step_w else img_w / feat_w
    step_height = step_h if step_h else img_h / feat_h

    # per-prior half extents (static python lists, ordering per reference)
    half_w, half_h = [], []
    for s, mn in enumerate(min_sizes):
        per_min_w, per_min_h = [], []
        for ar in ars:
            per_min_w.append(mn * math.sqrt(ar) / 2.0)
            per_min_h.append(mn / math.sqrt(ar) / 2.0)
        if mmar_order:
            # min, [max], then ratios != 1
            half_w.append(per_min_w[0]); half_h.append(per_min_h[0])
            if max_sizes:
                sq = math.sqrt(mn * max_sizes[s]) / 2.0
                half_w.append(sq); half_h.append(sq)
            for ar, w_, h_ in zip(ars, per_min_w, per_min_h):
                if abs(ar - 1.0) < 1e-6:
                    continue
                half_w.append(w_); half_h.append(h_)
        else:
            half_w.extend(per_min_w); half_h.extend(per_min_h)
            if max_sizes:
                sq = math.sqrt(mn * max_sizes[s]) / 2.0
                half_w.append(sq); half_h.append(sq)

    hw = jnp.asarray(half_w, jnp.float32)  # [P]
    hh = jnp.asarray(half_h, jnp.float32)

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * step_width   # [W]
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * step_height  # [H]
    cx = cx[None, :, None]  # [1, W, 1]
    cy = cy[:, None, None]  # [H, 1, 1]
    boxes = jnp.stack(
        [
            jnp.broadcast_to((cx - hw) / img_w, (feat_h, feat_w, len(half_w))),
            jnp.broadcast_to((cy - hh) / img_h, (feat_h, feat_w, len(half_w))),
            jnp.broadcast_to((cx + hw) / img_w, (feat_h, feat_w, len(half_w))),
            jnp.broadcast_to((cy + hh) / img_h, (feat_h, feat_w, len(half_w))),
        ],
        axis=-1,
    )
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (feat_h, feat_w, num_priors, 4)
    )
    return boxes, var


@register_op("density_prior_box", inputs=["Input", "Image"],
             outputs=["Boxes", "Variances"], no_grad=True)
def density_prior_box(ctx, attrs, Input, Image):
    """Densified priors (density_prior_box_op.h): each fixed_size is tiled
    density×density per cell with shifts.  Out: [H, W, P, 4]."""
    fixed_sizes = [float(v) for v in attrs.get("fixed_sizes", [])]
    fixed_ratios = [float(v) for v in attrs.get("fixed_ratios", [1.0])]
    densities = [int(v) for v in attrs.get("densities", [])]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = bool(attrs.get("clip", False))
    step_w = float(attrs.get("step_w", 0.0))
    step_h = float(attrs.get("step_h", 0.0))
    offset = float(attrs.get("offset", 0.5))

    img_h, img_w = Image.shape[2], Image.shape[3]
    feat_h, feat_w = Input.shape[2], Input.shape[3]
    step_width = step_w if step_w else img_w / feat_w
    step_height = step_h if step_h else img_h / feat_h

    # per-prior (shift_x, shift_y, half_w, half_h) relative to cell center;
    # both axes shift by step_average (density_prior_box_op.h:69,91 — int
    # truncation kept for parity)
    step_average = int((step_width + step_height) * 0.5)
    sx, sy, hw, hh = [], [], [], []
    for size, density in zip(fixed_sizes, densities):
        shift = int(step_average / density)
        for ratio in fixed_ratios:
            bw = size * math.sqrt(ratio) / 2.0
            bh = size / math.sqrt(ratio) / 2.0
            for di in range(density):
                for dj in range(density):
                    sx.append(-step_average / 2.0 + shift / 2.0 + dj * shift)
                    sy.append(-step_average / 2.0 + shift / 2.0 + di * shift)
                    hw.append(bw)
                    hh.append(bh)
    P = len(sx)
    sx = jnp.asarray(sx, jnp.float32)
    sy = jnp.asarray(sy, jnp.float32)
    hw = jnp.asarray(hw, jnp.float32)
    hh = jnp.asarray(hh, jnp.float32)

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * step_width
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * step_height
    cx = cx[None, :, None] + sx  # [1, W, P]
    cy = cy[:, None, None] + sy  # [H, 1, P]
    boxes = jnp.stack(
        [
            jnp.broadcast_to((cx - hw) / img_w, (feat_h, feat_w, P)),
            jnp.broadcast_to((cy - hh) / img_h, (feat_h, feat_w, P)),
            jnp.broadcast_to((cx + hw) / img_w, (feat_h, feat_w, P)),
            jnp.broadcast_to((cy + hh) / img_h, (feat_h, feat_w, P)),
        ],
        axis=-1,
    )
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (feat_h, feat_w, P, 4)
    )
    return boxes, var


@register_op("anchor_generator", inputs=["Input"],
             outputs=["Anchors", "Variances"], no_grad=True)
def anchor_generator(ctx, attrs, Input):
    """RPN anchors in absolute pixels (anchor_generator_op.h).
    Out: [H, W, A, 4]."""
    anchor_sizes = [float(v) for v in attrs.get("anchor_sizes", [64., 128., 256., 512.])]
    aspect_ratios = [float(v) for v in attrs.get("aspect_ratios", [0.5, 1.0, 2.0])]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    stride = [float(v) for v in attrs.get("stride", [16.0, 16.0])]
    offset = float(attrs.get("offset", 0.5))

    feat_h, feat_w = Input.shape[2], Input.shape[3]
    hw, hh = [], []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            area = stride[0] * stride[1]
            area_ratios = area / ar
            base_w = round(math.sqrt(area_ratios))
            base_h = round(base_w * ar)
            scale_w = size / stride[0]
            scale_h = size / stride[1]
            hw.append(0.5 * (scale_w * base_w - 1))
            hh.append(0.5 * (scale_h * base_h - 1))
    A = len(hw)
    hw = jnp.asarray(hw, jnp.float32)
    hh = jnp.asarray(hh, jnp.float32)
    # center convention: offset*(stride-1), matching the reference
    # (anchor_generator_op.h:55-56) so anchors parity with ref-trained RPNs
    cx = (jnp.arange(feat_w, dtype=jnp.float32) * stride[0] + offset * (stride[0] - 1))[None, :, None]
    cy = (jnp.arange(feat_h, dtype=jnp.float32) * stride[1] + offset * (stride[1] - 1))[:, None, None]
    anchors = jnp.stack(
        [
            jnp.broadcast_to(cx - hw, (feat_h, feat_w, A)),
            jnp.broadcast_to(cy - hh, (feat_h, feat_w, A)),
            jnp.broadcast_to(cx + hw, (feat_h, feat_w, A)),
            jnp.broadcast_to(cy + hh, (feat_h, feat_w, A)),
        ],
        axis=-1,
    )
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (feat_h, feat_w, A, 4)
    )
    return anchors, var


# ---------------------------------------------------------------------------
# box coding / clipping / IoU
# ---------------------------------------------------------------------------

def _center_size(boxes, normalized):
    norm = 0.0 if normalized else 1.0
    w = boxes[..., 2] - boxes[..., 0] + norm
    h = boxes[..., 3] - boxes[..., 1] + norm
    cx = boxes[..., 0] + w / 2.0
    cy = boxes[..., 1] + h / 2.0
    return cx, cy, w, h


@register_op("box_coder", inputs=["PriorBox", "PriorBoxVar", "TargetBox"],
             outputs=["OutputBox"])
def box_coder(ctx, attrs, PriorBox, PriorBoxVar, TargetBox):
    """Encode/decode center-size box deltas (box_coder_op.h).

    encode: TargetBox [R, 4], PriorBox [C, 4] → [R, C, 4]
    decode: TargetBox [R, C, 4], PriorBox [C, 4] (axis=0) or [R, 4] (axis=1)
            → [R, C, 4]
    """
    code_type = attrs.get("code_type", "encode_center_size")
    normalized = bool(attrs.get("box_normalized", True))
    axis = int(attrs.get("axis", 0))
    variance = [float(v) for v in attrs.get("variance", [])]

    pcx, pcy, pw, ph = _center_size(PriorBox, normalized)

    if code_type == "encode_center_size":
        tcx = (TargetBox[:, 2] + TargetBox[:, 0]) / 2.0
        tcy = (TargetBox[:, 3] + TargetBox[:, 1]) / 2.0
        norm = 0.0 if normalized else 1.0
        tw = TargetBox[:, 2] - TargetBox[:, 0] + norm
        th = TargetBox[:, 3] - TargetBox[:, 1] + norm
        # [R, C]
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        dh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)  # [R, C, 4]
        if PriorBoxVar is not None:
            out = out / PriorBoxVar[None, :, :]
        elif variance:
            out = out / jnp.asarray(variance, out.dtype)
        return out

    # decode_center_size: prior broadcast along `axis`
    if axis == 0:
        pcx_b, pcy_b = pcx[None, :], pcy[None, :]
        pw_b, ph_b = pw[None, :], ph[None, :]
        var_b = PriorBoxVar[None, :, :] if PriorBoxVar is not None else None
    else:
        pcx_b, pcy_b = pcx[:, None], pcy[:, None]
        pw_b, ph_b = pw[:, None], ph[:, None]
        var_b = PriorBoxVar[:, None, :] if PriorBoxVar is not None else None

    t = TargetBox
    if var_b is not None:
        v = var_b
    elif variance:
        v = jnp.asarray(variance, t.dtype)
    else:
        v = jnp.ones((4,), t.dtype)
    dcx = v[..., 0] * t[..., 0] * pw_b + pcx_b
    dcy = v[..., 1] * t[..., 1] * ph_b + pcy_b
    dw = jnp.exp(v[..., 2] * t[..., 2]) * pw_b
    dh = jnp.exp(v[..., 3] * t[..., 3]) * ph_b
    norm = 0.0 if normalized else 1.0
    out = jnp.stack(
        [
            dcx - dw / 2.0,
            dcy - dh / 2.0,
            dcx + dw / 2.0 - norm,
            dcy + dh / 2.0 - norm,
        ],
        axis=-1,
    )
    return out


@register_op("box_clip", inputs=["Input", "ImInfo"], outputs=["Output"])
def box_clip(ctx, attrs, Input, ImInfo):
    """Clip boxes to image bounds (box_clip_op.h).  Input [B, R, 4] or
    [R, 4] (then ImInfo row 0 is used); ImInfo [B, 3] = (h, w, scale)."""
    boxes = Input
    squeeze = False
    if boxes.ndim == 2:
        boxes = boxes[None]
        squeeze = True
    # reference rounds the recovered extents (box_clip_op.h)
    im_h = jnp.round(ImInfo[:, 0] / ImInfo[:, 2])
    im_w = jnp.round(ImInfo[:, 1] / ImInfo[:, 2])
    xmax = (im_w - 1.0)[:, None]
    ymax = (im_h - 1.0)[:, None]
    out = jnp.stack(
        [
            jnp.minimum(jnp.maximum(boxes[..., 0], 0.0), xmax),
            jnp.minimum(jnp.maximum(boxes[..., 1], 0.0), ymax),
            jnp.minimum(jnp.maximum(boxes[..., 2], 0.0), xmax),
            jnp.minimum(jnp.maximum(boxes[..., 3], 0.0), ymax),
        ],
        axis=-1,
    )
    return out[0] if squeeze else out


@register_op("iou_similarity", inputs=["X", "Y"], outputs=["Out"])
def iou_similarity(ctx, attrs, X, Y):
    """Pairwise IoU [N, M] (iou_similarity_op.h)."""
    normalized = bool(attrs.get("box_normalized", True))
    return _pairwise_iou(X, Y, normalized)


@register_op("polygon_box_transform", inputs=["Input"], outputs=["Output"],
             no_grad=True)
def polygon_box_transform(ctx, attrs, Input):
    """EAST-style offset→vertex transform (polygon_box_transform_op.cc):
    out[b, 2k, h, w]   = 4*w_idx - in[b, 2k, h, w]
    out[b, 2k+1, h, w] = 4*h_idx - in[b, 2k+1, h, w]."""
    B, C, H, W = Input.shape
    wi = jnp.arange(W, dtype=Input.dtype)[None, None, None, :]
    hi = jnp.arange(H, dtype=Input.dtype)[None, None, :, None]
    even = jnp.arange(C) % 2 == 0
    grid = jnp.where(even[None, :, None, None], 4.0 * wi, 4.0 * hi)
    return grid - Input


# ---------------------------------------------------------------------------
# YOLO box decoding
# ---------------------------------------------------------------------------

@register_op("yolo_box", inputs=["X", "ImgSize"], outputs=["Boxes", "Scores"],
             no_grad=True)
def yolo_box(ctx, attrs, X, ImgSize):
    """Decode YOLOv3 head output (yolo_box_op.h:46 GetYoloBox).

    X: [N, A*(5+C), H, W]; ImgSize: [N, 2] (h, w) int.
    Boxes: [N, A*H*W, 4]; Scores: [N, A*H*W, C].
    """
    anchors = [int(v) for v in attrs.get("anchors", [])]
    class_num = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.01))
    downsample = int(attrs.get("downsample_ratio", 32))

    N, _, H, W = X.shape
    A = len(anchors) // 2
    input_size = downsample * H

    x = X.reshape(N, A, 5 + class_num, H, W)
    img_h = ImgSize[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = ImgSize[:, 1].astype(jnp.float32)[:, None, None, None]

    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    an_w = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    an_h = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]

    cx = (gx + jax.nn.sigmoid(x[:, :, 0])) * img_w / W
    cy = (gy + jax.nn.sigmoid(x[:, :, 1])) * img_h / H
    bw = jnp.exp(x[:, :, 2]) * an_w * img_w / input_size
    bh = jnp.exp(x[:, :, 3]) * an_h * img_h / input_size

    conf = jax.nn.sigmoid(x[:, :, 4])
    valid = conf >= conf_thresh

    xmin = jnp.maximum(cx - bw / 2.0, 0.0)
    ymin = jnp.maximum(cy - bh / 2.0, 0.0)
    xmax = jnp.minimum(cx + bw / 2.0, img_w - 1.0)
    ymax = jnp.minimum(cy + bh / 2.0, img_h - 1.0)
    boxes = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)  # [N, A, H, W, 4]
    boxes = jnp.where(valid[..., None], boxes, 0.0)

    scores = conf[..., None] * jax.nn.sigmoid(
        jnp.moveaxis(x[:, :, 5:], 2, -1)
    )  # [N, A, H, W, C]
    scores = jnp.where(valid[..., None], scores, 0.0)

    return (
        boxes.reshape(N, A * H * W, 4),
        scores.reshape(N, A * H * W, class_num),
    )


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------

def _nms_single_class(boxes, scores, score_threshold, nms_threshold, eta,
                      top_k, normalized):
    """Greedy NMS over one class (multiclass_nms_op.cc NMSFast).

    boxes [R, 4], scores [R] → keep mask [K] + (scores, boxes) of the top_k
    candidates, K = min(top_k, R) (static).
    """
    R = boxes.shape[0]
    K = min(int(top_k), R) if top_k > 0 else R
    cand = scores > score_threshold
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    masked = jnp.where(cand, scores, neg_inf)
    top_scores, idx = lax.top_k(masked, K)  # descending, stable
    top_boxes = boxes[idx]
    valid = top_scores > neg_inf

    iou = _pairwise_iou(top_boxes, top_boxes, normalized)  # [K, K]

    def body(i, carry):
        keep, thresh = carry
        # kept earlier & IoU over current adaptive threshold → suppressed
        sup = jnp.any(
            jnp.where((jnp.arange(K) < i) & keep, iou[i], 0.0) > thresh)
        ki = valid[i] & ~sup
        keep = keep.at[i].set(ki)
        # adaptive NMS (eta < 1): shrink threshold after each kept box
        thresh = jnp.where(
            ki & (eta < 1.0) & (thresh > 0.5), thresh * eta, thresh)
        return keep, thresh

    keep0 = jnp.zeros((K,), bool)
    keep, _ = lax.fori_loop(
        0, K, body, (keep0, jnp.asarray(nms_threshold, jnp.float32)))
    return keep, top_scores, top_boxes, idx


def _multiclass_nms_one(bboxes, scores, background_label, score_threshold,
                        nms_top_k, keep_top_k, nms_threshold, eta, normalized):
    """One batch element.  bboxes [R, C, 4] (shared → broadcast), scores
    [C, R] → ([keep_top_k, 6], count, candidate indices into R)."""
    C, R = scores.shape

    def per_class(c_boxes, c_scores):
        return _nms_single_class(
            c_boxes, c_scores, score_threshold, nms_threshold, eta,
            nms_top_k, normalized)

    class_boxes = jnp.moveaxis(bboxes, 1, 0)  # [C, R, 4]
    keep, top_scores, top_boxes, top_idx = jax.vmap(per_class)(
        class_boxes, scores)
    # [C, K] / [C, K, 4]
    K = keep.shape[1]
    labels = jnp.broadcast_to(jnp.arange(C)[:, None], (C, K))
    is_bg = labels == background_label
    sel = keep & ~is_bg

    flat_scores = jnp.where(sel, top_scores, -jnp.inf).reshape(-1)
    flat_boxes = top_boxes.reshape(-1, 4)
    flat_labels = labels.reshape(-1)
    flat_orig = top_idx.reshape(-1)

    M = min(int(keep_top_k), flat_scores.shape[0]) if keep_top_k > 0 else flat_scores.shape[0]
    fin_scores, fin_idx = lax.top_k(flat_scores, M)
    fin_valid = fin_scores > -jnp.inf
    fin_boxes = flat_boxes[fin_idx]
    fin_labels = flat_labels[fin_idx]
    fin_orig = jnp.where(fin_valid, flat_orig[fin_idx], -1).astype(jnp.int32)

    out = jnp.concatenate(
        [
            jnp.where(fin_valid, fin_labels, -1).astype(jnp.float32)[:, None],
            jnp.where(fin_valid, fin_scores, -1.0)[:, None],
            jnp.where(fin_valid[:, None], fin_boxes, -1.0),
        ],
        axis=1,
    )  # [M, 6]
    if 0 <= M < keep_top_k:
        # honor the documented [keep_top_k, 6] shape contract even when
        # the candidate pool (C*nms_top_k) is smaller: -1 padding rows
        out = jnp.concatenate(
            [out, jnp.full((keep_top_k - M, 6), -1.0, out.dtype)], axis=0
        )
        fin_orig = jnp.concatenate(
            [fin_orig, jnp.full((keep_top_k - M,), -1, fin_orig.dtype)]
        )
    count = jnp.sum(fin_valid.astype(jnp.int32))
    return out, count, fin_orig


@register_op("multiclass_nms", inputs=["BBoxes", "Scores"],
             outputs=["Out", "NmsRoisNum"], no_grad=True)
def multiclass_nms(ctx, attrs, BBoxes, Scores):
    """Per-class greedy NMS + cross-class top-k (multiclass_nms_op.cc).

    BBoxes [N, R, 4], Scores [N, C, R] → Out [N, keep_top_k, 6]
    (label, score, x1, y1, x2, y2; -1-padded), NmsRoisNum [N].
    The reference emits a ragged LoDTensor; fixed-size padding is the
    TPU-static equivalent (see module docstring).
    """
    background_label = int(attrs.get("background_label", 0))
    score_threshold = float(attrs["score_threshold"])
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    nms_threshold = float(attrs.get("nms_threshold", 0.3))
    eta = float(attrs.get("nms_eta", 1.0))
    normalized = bool(attrs.get("normalized", True))

    bb = BBoxes[:, :, None, :] if BBoxes.ndim == 3 else BBoxes

    def one_fixed(b, s):
        C, R = s.shape
        b4 = jnp.broadcast_to(b, (R, C, 4)) if b.shape[1] == 1 else b
        return _multiclass_nms_one(
            b4, s, background_label, score_threshold, nms_top_k, keep_top_k,
            nms_threshold, eta, normalized)

    out, num, _ = jax.vmap(one_fixed)(bb, Scores)
    return out, num


@register_op("multiclass_nms2", inputs=["BBoxes", "Scores"],
             outputs=["Out", "Index", "NmsRoisNum"], no_grad=True)
def multiclass_nms2(ctx, attrs, BBoxes, Scores):
    """multiclass_nms variant also returning, per detection, the index of
    the kept box among the input candidates R (-1 for padding rows)."""
    background_label = int(attrs.get("background_label", 0))
    score_threshold = float(attrs["score_threshold"])
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    nms_threshold = float(attrs.get("nms_threshold", 0.3))
    eta = float(attrs.get("nms_eta", 1.0))
    normalized = bool(attrs.get("normalized", True))

    bb = BBoxes[:, :, None, :] if BBoxes.ndim == 3 else BBoxes

    def one_fixed(b, s):
        C, R = s.shape
        b4 = jnp.broadcast_to(b, (R, C, 4)) if b.shape[1] == 1 else b
        return _multiclass_nms_one(
            b4, s, background_label, score_threshold, nms_top_k, keep_top_k,
            nms_threshold, eta, normalized)

    out, num, idx = jax.vmap(one_fixed)(bb, Scores)
    return out, idx, num


# ---------------------------------------------------------------------------
# ROI align (differentiable)
# ---------------------------------------------------------------------------

def _bilinear(feat, y, x):
    """feat [C, H, W], y/x scalar grids [...] → [C, ...] bilinear samples.
    Out-of-range (< -1 or > size) samples are 0 (roi_align_op.cc)."""
    H, W = feat.shape[-2], feat.shape[-1]
    oob = (y < -1.0) | (y > H * 1.0) | (x < -1.0) | (x > W * 1.0)
    y = jnp.clip(y, 0.0, None)
    x = jnp.clip(x, 0.0, None)
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly = jnp.clip(y - y0, 0.0, 1.0)
    lx = jnp.clip(x - x0, 0.0, 1.0)
    y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    val = (
        v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
        + v10 * ly * (1 - lx) + v11 * ly * lx
    )
    return jnp.where(oob[None], 0.0, val)


@register_op("roi_align", inputs=["X", "ROIs", "RoisNum"], outputs=["Out"])
def roi_align(ctx, attrs, X, ROIs, RoisNum):
    """ROI Align (roi_align_op.cc).  X [B, C, H, W]; ROIs [R, 4]
    (x1, y1, x2, y2 in image coords); RoisNum [B] optional per-image counts
    (defaults: all ROIs on image 0).  Out [R, C, ph, pw]."""
    spatial_scale = float(attrs.get("spatial_scale", 1.0))
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    sampling_ratio = int(attrs.get("sampling_ratio", -1))
    grid = sampling_ratio if sampling_ratio > 0 else 2  # static grid (see doc)

    B = X.shape[0]
    R = ROIs.shape[0]
    if RoisNum is not None:
        ends = jnp.cumsum(RoisNum.astype(jnp.int32))
        batch_idx = jnp.searchsorted(ends, jnp.arange(R), side="right")
        batch_idx = jnp.clip(batch_idx, 0, B - 1)
    else:
        batch_idx = jnp.zeros((R,), jnp.int32)

    def one_roi(roi, bi):
        feat = X[bi]  # [C, H, W]
        x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
        roi_w = jnp.maximum((x2 - x1) * spatial_scale, 1.0)
        roi_h = jnp.maximum((y2 - y1) * spatial_scale, 1.0)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        # sample grid: [ph, grid] x [pw, grid]
        iy = jnp.arange(ph, dtype=X.dtype)[:, None]
        gy = (iy * bin_h + (jnp.arange(grid, dtype=X.dtype)[None, :] + 0.5)
              * bin_h / grid + y1 * spatial_scale)  # [ph, g]
        ix = jnp.arange(pw, dtype=X.dtype)[:, None]
        gx = (ix * bin_w + (jnp.arange(grid, dtype=X.dtype)[None, :] + 0.5)
              * bin_w / grid + x1 * spatial_scale)  # [pw, g]
        yy = jnp.broadcast_to(gy[:, None, :, None], (ph, pw, grid, grid))
        xx = jnp.broadcast_to(gx[None, :, None, :], (ph, pw, grid, grid))
        samples = _bilinear(feat, yy, xx)  # [C, ph, pw, g, g]
        return jnp.mean(samples, axis=(-2, -1))  # [C, ph, pw]

    return jax.vmap(one_roi)(ROIs.astype(X.dtype), batch_idx)


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

@register_op("sigmoid_focal_loss", inputs=["X", "Label", "FgNum"],
             outputs=["Out"])
def sigmoid_focal_loss(ctx, attrs, X, Label, FgNum):
    """RetinaNet focal loss (sigmoid_focal_loss_op.cc).  X [N, C] logits,
    Label [N, 1] int (0 = background, c in 1..C = foreground class c),
    FgNum [1] normalizer."""
    gamma = float(attrs.get("gamma", 2.0))
    alpha = float(attrs.get("alpha", 0.25))
    N, C = X.shape
    lab = Label.reshape(N).astype(jnp.int32)
    fg = jnp.maximum(FgNum.reshape(()).astype(X.dtype), 1.0)
    # one-hot over classes 1..C mapped to column c-1
    t = (lab[:, None] == (jnp.arange(C)[None, :] + 1)).astype(X.dtype)
    p = jax.nn.sigmoid(X)
    ce_pos = -jnp.log(jnp.clip(p, 1e-12, 1.0))
    ce_neg = -jnp.log(jnp.clip(1.0 - p, 1e-12, 1.0))
    loss = (
        t * alpha * jnp.power(1.0 - p, gamma) * ce_pos
        + (1.0 - t) * (1.0 - alpha) * jnp.power(p, gamma) * ce_neg
    )
    return loss / fg


@register_op("bipartite_match", inputs=["DistMat"],
             outputs=["ColToRowMatchIndices", "ColToRowMatchDist"],
             no_grad=True)
def bipartite_match(ctx, attrs, DistMat):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally-largest remaining (row, col) pair; with
    match_type=per_prediction, afterwards match leftover cols whose best
    row distance exceeds dist_threshold.  DistMat [R, C] (one image);
    outputs are [1, C] row indices (-1 unmatched) and distances.
    TPU-static: the greedy loop is a lax.fori over min(R, C) rounds."""
    import jax as _jax

    match_type = attrs.get("match_type", "bipartite")
    thresh = float(attrs.get("dist_threshold", 0.5))
    batched = DistMat.ndim == 3
    dm = DistMat if batched else DistMat[None]
    R, C = dm.shape[1], dm.shape[2]

    def match_one(d):
        def body(_, state):
            match_idx, match_dist, active = state
            masked = jnp.where(active, d, -1.0)
            flat = jnp.argmax(masked)
            r, c = flat // C, flat % C
            best = masked[r, c]
            do = best >= 0
            match_idx = jnp.where(
                do, match_idx.at[c].set(r.astype(jnp.int32)), match_idx)
            match_dist = jnp.where(
                do, match_dist.at[c].set(best), match_dist)
            active = jnp.where(do, active.at[r, :].set(False), active)
            active = jnp.where(do, active.at[:, c].set(False), active)
            return match_idx, match_dist, active

        init = (jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), d.dtype),
                jnp.ones((R, C), bool))
        match_idx, match_dist, _ = _jax.lax.fori_loop(
            0, min(R, C), body, init)
        if match_type == "per_prediction":
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_dist = jnp.max(d, axis=0)
            extra = (match_idx < 0) & (best_dist >= thresh)
            match_idx = jnp.where(extra, best_row, match_idx)
            match_dist = jnp.where(extra, best_dist, match_dist)
        return match_idx, match_dist

    match_idx, match_dist = _jax.vmap(match_one)(dm)  # [N, C]
    return {"ColToRowMatchIndices": match_idx,
            "ColToRowMatchDist": match_dist}


@register_op("target_assign",
             inputs=["X", "MatchIndices", "NegIndices"],
             outputs=["Out", "OutWeight"], no_grad=True)
def target_assign(ctx, attrs, X, MatchIndices, NegIndices):
    """Assign per-prior targets by match indices (target_assign_op.h):
    out[i, j] = X[match[i, j]] (weight 1) or mismatch_value (weight 0).
    X here is [M, K] per-image entities (padded batch dim folded)."""
    mismatch = attrs.get("mismatch_value", 0)
    mi = MatchIndices.astype(jnp.int32)  # [N, P]
    n, p = mi.shape
    k = X.shape[-1]
    x2 = X.reshape(-1, k)
    gathered = x2[jnp.maximum(mi, 0).reshape(-1)].reshape(n, p, k)
    matched = (mi >= 0)[:, :, None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, gathered.dtype))
    weight = matched.astype(jnp.float32)
    return {"Out": out, "OutWeight": weight[..., 0:1] * jnp.ones((1, 1, 1))}


@register_op("mine_hard_examples",
             inputs=["ClsLoss", "LocLoss", "MatchIndices", "MatchDist"],
             outputs=["NegIndices", "UpdatedMatchIndices"], no_grad=True)
def mine_hard_examples(ctx, attrs, ClsLoss, LocLoss, MatchIndices,
                       MatchDist):
    """OHEM negative mining (mine_hard_examples_op.cc, max_negative
    mode): keep the hardest negatives up to neg_pos_ratio * #positives;
    padded output: NegIndices [N, P] with -1 beyond the kept count."""
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    mi = MatchIndices.astype(jnp.int32)  # [N, P]
    n, p = mi.shape
    loss = ClsLoss
    if LocLoss is not None and attrs.get("mining_type",
                                         "max_negative") == "hard_example":
        loss = loss + LocLoss
    is_neg = mi < 0
    neg_loss = jnp.where(is_neg, loss.reshape(n, p), -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)  # hardest first
    num_pos = jnp.sum(mi >= 0, axis=1)
    num_neg = jnp.sum(is_neg, axis=1)
    quota = jnp.minimum(
        jnp.ceil(num_pos.astype(jnp.float32) * ratio).astype(jnp.int32),
        num_neg)
    rank = jnp.arange(p)[None, :]
    keep = rank < quota[:, None]
    neg_idx = jnp.where(keep, order.astype(jnp.int32), -1)
    return {"NegIndices": neg_idx,
            "UpdatedMatchIndices": mi}


def _sce(x, label):
    """Stable sigmoid cross entropy (yolov3_loss_op.h
    SigmoidCrossEntropy): max(x,0) - x*label + log(1+exp(-|x|))."""
    return jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))


@register_op("yolov3_loss",
             inputs=["X", "GTBox", "GTLabel", "GTScore"],
             outputs=["Loss", "ObjectnessMask", "GTMatchMask"],
             stateful_outputs=("ObjectnessMask", "GTMatchMask"))
def yolov3_loss(ctx, attrs, X, GTBox, GTLabel, GTScore):
    """YOLOv3 training loss (yolov3_loss_op.h): per ground-truth box,
    match the best anchor by centered IoU; at the matched cell compute
    location (sce tx/ty + L1 tw/th, scaled by (2 - gw*gh)) and class
    (per-class sce with optional label smoothing) losses; objectness is
    sce against {1 at matched cells, 0 elsewhere, ignored where the best
    pred-gt IoU exceeds ignore_thresh}.  The reference's per-image host
    loops become batched jnp ops + a static loop over the (small) gt
    capacity."""
    anchors = [int(a) for a in attrs["anchors"]]
    anchor_mask = [int(a) for a in attrs["anchor_mask"]]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", 32))
    use_label_smooth = bool(attrs.get("use_label_smooth", True))
    n, c, h, w = X.shape
    mask_num = len(anchor_mask)
    an_num = len(anchors) // 2
    b = GTBox.shape[1]
    input_size = downsample * h
    x5 = X.reshape(n, mask_num, 5 + class_num, h, w)
    gtb = GTBox  # [N, B, 4] (cx, cy, w, h) normalized
    gtl = jnp.reshape(GTLabel, (n, b)).astype(jnp.int32)
    gts = (jnp.reshape(GTScore, (n, b)) if GTScore is not None
           else jnp.ones((n, b), X.dtype))
    gt_valid = (gtb[:, :, 2] > 0) & (gtb[:, :, 3] > 0)

    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - sw, sw
    else:
        label_pos, label_neg = 1.0, 0.0

    # ---- decode predicted boxes (GetYoloBox) for the ignore mask ----
    gx = (jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
          + jax.nn.sigmoid(x5[:, :, 0])) / w
    gy = (jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
          + jax.nn.sigmoid(x5[:, :, 1])) / h
    aw = jnp.asarray([anchors[2 * m] for m in anchor_mask],
                     jnp.float32)[None, :, None, None]
    ah = jnp.asarray([anchors[2 * m + 1] for m in anchor_mask],
                     jnp.float32)[None, :, None, None]
    pw = jnp.exp(x5[:, :, 2]) * aw / input_size
    ph = jnp.exp(x5[:, :, 3]) * ah / input_size

    def centered_iou(w1, h1, w2, h2):
        inter = jnp.minimum(w1, w2) * jnp.minimum(h1, h2)
        return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

    def box_iou(px, py, pw_, ph_, g):
        # [..., ] pred vs one gt box [4]
        x1 = jnp.maximum(px - pw_ / 2, g[0] - g[2] / 2)
        y1 = jnp.maximum(py - ph_ / 2, g[1] - g[3] / 2)
        x2 = jnp.minimum(px + pw_ / 2, g[0] + g[2] / 2)
        y2 = jnp.minimum(py + ph_ / 2, g[1] + g[3] / 2)
        inter = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
        union = pw_ * ph_ + g[2] * g[3] - inter
        return inter / jnp.maximum(union, 1e-10)

    best_iou = jnp.zeros((n, mask_num, h, w), jnp.float32)
    for t in range(b):
        iou_t = jax.vmap(
            lambda px, py, pw_, ph_, g: box_iou(px, py, pw_, ph_, g)
        )(gx, gy, pw, ph, gtb[:, t])
        iou_t = jnp.where(gt_valid[:, t][:, None, None, None], iou_t, 0.0)
        best_iou = jnp.maximum(best_iou, iou_t)
    obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)

    # ---- per-gt anchor matching + location/class losses ----
    loss = jnp.zeros((n,), jnp.float32)
    gt_match = jnp.full((n, b), -1, jnp.int32)
    an_w = jnp.asarray(anchors[0::2], jnp.float32) / input_size
    an_h = jnp.asarray(anchors[1::2], jnp.float32) / input_size
    mask_of_anchor = jnp.asarray(
        [anchor_mask.index(a) if a in anchor_mask else -1
         for a in range(an_num)], jnp.int32)
    rows = jnp.arange(n)
    for t in range(b):
        g = gtb[:, t]  # [N, 4]
        valid = gt_valid[:, t]
        score = gts[:, t]
        ious = centered_iou(g[:, 2:3], g[:, 3:4], an_w[None, :],
                            an_h[None, :])  # [N, an_num]
        best_n = jnp.argmax(ious, axis=1).astype(jnp.int32)
        mask_idx = mask_of_anchor[best_n]  # [N], -1 if not in this head
        gi = jnp.clip((g[:, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((g[:, 1] * h).astype(jnp.int32), 0, h - 1)
        active = valid & (mask_idx >= 0)
        midx = jnp.maximum(mask_idx, 0)
        cell = x5[rows, midx, :, gj, gi]  # [N, 5+C]
        tx = g[:, 0] * w - gi
        ty = g[:, 1] * h - gj
        # tw = log(gt_w * input_size / anchor_px) = log(gt_w / an_w_norm)
        tw = jnp.log(jnp.maximum(g[:, 2] / an_w[best_n], 1e-9))
        th = jnp.log(jnp.maximum(g[:, 3] / an_h[best_n], 1e-9))
        scale = (2.0 - g[:, 2] * g[:, 3]) * score
        loc = (_sce(cell[:, 0], tx) + _sce(cell[:, 1], ty)
               + jnp.abs(cell[:, 2] - tw)
               + jnp.abs(cell[:, 3] - th)) * scale
        onehot = jax.nn.one_hot(gtl[:, t], class_num)
        cls_target = onehot * label_pos + (1.0 - onehot) * label_neg
        cls = jnp.sum(_sce(cell[:, 5:], cls_target), axis=1) * score
        loss = loss + jnp.where(active, loc + cls, 0.0)
        gt_match = gt_match.at[:, t].set(
            jnp.where(valid, mask_idx, -1))
        obj_mask = obj_mask.at[rows, midx, gj, gi].set(
            jnp.where(active, score, obj_mask[rows, midx, gj, gi]))

    # ---- objectness loss (CalcObjnessLoss) ----
    obj_logit = x5[:, :, 4]
    pos = obj_mask > 1e-5
    neg = (obj_mask > -0.5) & ~pos
    obj_loss = (jnp.where(pos, _sce(obj_logit, 1.0) * obj_mask, 0.0)
                + jnp.where(neg, _sce(obj_logit, 0.0), 0.0))
    loss = loss + jnp.sum(obj_loss, axis=(1, 2, 3))
    return {"Loss": loss, "ObjectnessMask": obj_mask,
            "GTMatchMask": gt_match}


@register_op("rpn_target_assign",
             inputs=["Anchor", "GtBoxes", "IsCrowd", "ImInfo"],
             outputs=["LocationIndex", "ScoreIndex", "TargetLabel",
                      "TargetBBox", "BBoxInsideWeight"],
             no_grad=True)
def rpn_target_assign(ctx, attrs, Anchor, GtBoxes, IsCrowd, ImInfo):
    """RPN anchor labeling (rpn_target_assign_op.cc), TPU-static single
    image: anchors with IoU > positive_overlap (or the argmax anchor per
    gt) are positive, IoU < negative_overlap negative; outputs are padded
    index lists (-1 padding) of fixed capacity rpn_batch_size_per_im."""
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    cap = int(attrs.get("rpn_batch_size_per_im", 256))
    anchors = Anchor.reshape(-1, 4)
    gts = GtBoxes.reshape(-1, 4)
    a = anchors.shape[0]
    iou = _pairwise_iou(anchors, gts, True)  # [A, G]
    gt_valid = (gts[:, 2] > gts[:, 0]) & (gts[:, 3] > gts[:, 1])
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    # anchors that are the best for some gt are positive too
    best_anchor_per_gt = jnp.argmax(iou, axis=0)  # [G]
    # max-combine so a padding gt's False cannot clobber a valid gt's True
    # at the shared argmax fallback index 0
    is_best = jnp.zeros((a,), bool).at[best_anchor_per_gt].max(gt_valid)
    positive = (best_iou >= pos_thr) | is_best
    # anchors overlapping nothing (best_iou == -1 because no valid gt, or
    # genuinely 0) are background negatives, like the reference's
    # max-overlap-0 case
    negative = (best_iou < neg_thr) & ~positive
    labels = jnp.where(positive, 1, jnp.where(negative, 0, -1))
    # padded index lists, positives first (deterministic, no subsampling
    # RNG: the reference subsamples to cap; we keep the hardest-capped
    # deterministic prefix)
    order = jnp.argsort(-labels)  # 1s first, then 0s, then -1s
    loc_idx = jnp.where(jnp.arange(a) < jnp.sum(positive),
                        order, -1)[:cap]
    score_idx = jnp.where(
        jnp.arange(a) < jnp.sum(positive) + jnp.sum(negative),
        order, -1)[:cap]
    tgt_gt = gts[best_gt]
    # encode anchor->gt offsets (box_coder encode_center_size)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + aw / 2
    ay = anchors[:, 1] + ah / 2
    gw = tgt_gt[:, 2] - tgt_gt[:, 0]
    gh = tgt_gt[:, 3] - tgt_gt[:, 1]
    gx = tgt_gt[:, 0] + gw / 2
    gy = tgt_gt[:, 1] + gh / 2
    tgt = jnp.stack([
        (gx - ax) / jnp.maximum(aw, 1e-6),
        (gy - ay) / jnp.maximum(ah, 1e-6),
        jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-6), 1e-6)),
        jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-6), 1e-6)),
    ], axis=1)
    return {
        "LocationIndex": loc_idx.astype(jnp.int32),
        "ScoreIndex": score_idx.astype(jnp.int32),
        "TargetLabel": labels.astype(jnp.int32),
        "TargetBBox": tgt,
        "BBoxInsideWeight": jnp.where(positive[:, None], 1.0, 0.0)
                            * jnp.ones((1, 4)),
    }


@register_op("generate_proposals",
             inputs=["Scores", "BboxDeltas", "ImInfo", "Anchors",
                     "Variances"],
             outputs=["RpnRois", "RpnRoiProbs"], no_grad=True)
def generate_proposals(ctx, attrs, Scores, BboxDeltas, ImInfo, Anchors,
                       Variances):
    """RPN proposal generation (generate_proposals_op.cc): decode anchor
    deltas, clip to the image, take pre_nms_topN by score, NMS to
    post_nms_topN.  TPU-static: fixed-capacity outputs padded with zeros
    (single image per call; batch via vmap upstream)."""
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    scores = Scores.reshape(-1)
    deltas = BboxDeltas.reshape(-1, 4)
    anchors = Anchors.reshape(-1, 4)
    var = (Variances.reshape(-1, 4) if Variances is not None
           else jnp.ones_like(anchors))
    # decode (box_coder decode_center_size with variances)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + aw / 2
    ay = anchors[:, 1] + ah / 2
    cx = var[:, 0] * deltas[:, 0] * aw + ax
    cy = var[:, 1] * deltas[:, 1] * ah + ay
    bw = jnp.exp(jnp.minimum(var[:, 2] * deltas[:, 2], 10.0)) * aw
    bh = jnp.exp(jnp.minimum(var[:, 3] * deltas[:, 3], 10.0)) * ah
    boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                       cx + bw / 2, cy + bh / 2], axis=1)
    if ImInfo is not None:
        im = ImInfo.reshape(-1)
        im_scale = im[2] if im.shape[0] >= 3 else 1.0
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, im[1] - 1),
            jnp.clip(boxes[:, 1], 0, im[0] - 1),
            jnp.clip(boxes[:, 2], 0, im[1] - 1),
            jnp.clip(boxes[:, 3], 0, im[0] - 1)], axis=1)
    else:
        im_scale = 1.0
    # legacy pixel convention (generate_proposals_op.cc): width =
    # x2-x1+1, min_size scaled back to the original image
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    eff_min = jnp.maximum(min_size * im_scale, 1.0)
    keep_size = (ws >= eff_min) & (hs >= eff_min)
    scores = jnp.where(keep_size, scores, -1e9)
    k = min(pre_n, scores.shape[0])
    pre_scores, pre_idx = jax.lax.top_k(scores, k)
    pre_boxes = boxes[pre_idx]
    keep, top_scores, top_boxes, _ = _nms_single_class(
        pre_boxes, pre_scores, -1e8, nms_thresh, 1.0, k, False)
    # left-pack kept boxes to fixed post_n capacity (zero padding)
    kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    dest = jnp.where(keep & (kept_rank < post_n), kept_rank, post_n)
    out_boxes = jnp.zeros((post_n + 1, 4), boxes.dtype).at[dest].set(
        top_boxes)[:post_n]
    out_scores = jnp.zeros((post_n + 1,), scores.dtype).at[dest].set(
        top_scores)[:post_n]
    return {"RpnRois": out_boxes, "RpnRoiProbs": out_scores[:, None]}


@register_op("detection_map",
             inputs=["DetectRes", "Label", "HasState", "PosCount",
                     "TruePos", "FalsePos"],
             outputs=["MAP", "AccumPosCount", "AccumTruePos",
                      "AccumFalsePos"],
             no_grad=True,
             stateful_outputs=("AccumPosCount", "AccumTruePos",
                               "AccumFalsePos"))
def detection_map(ctx, attrs, DetectRes, Label, HasState, PosCount,
                  TruePos, FalsePos):
    """Mean average precision (detection_map_op.h) for ONE padded image
    batch per call: detections [D, 6] (label, score, x1,y1,x2,y2; label
    < 0 = padding) vs gts [G, 5] (label, x1,y1,x2,y2; label < 0 =
    padding).  Greedy per-class matching at overlap_threshold, then
    11-point or integral AP.  The reference's accumulator-state streaming
    (PosCount/TruePos/FalsePos across batches) is not carried — each call
    reports the mAP of its own batch (the common single-eval-pass use);
    accumulator outputs echo fixed-capacity per-class counts."""
    import jax as _jax

    overlap = float(attrs.get("overlap_threshold", 0.5))
    ap_type = attrs.get("ap_type", "integral")
    num_classes = int(attrs.get("class_num", 21))
    evaluate_difficult = bool(attrs.get("evaluate_difficult", True))
    det = jnp.asarray(DetectRes).reshape(-1, 6)
    gt = jnp.asarray(Label).reshape(-1, Label.shape[-1])
    g_lab = gt[:, 0].astype(jnp.int32)
    g_box = gt[:, -4:]
    # 6-column labels carry a difficult flag (label, difficult, box);
    # with evaluate_difficult=False those gts neither count in npos nor
    # penalize matches (PASCAL VOC convention, detection_map_op.h)
    if gt.shape[-1] >= 6 and not evaluate_difficult:
        g_difficult = gt[:, 1] > 0.5
    else:
        g_difficult = jnp.zeros(gt.shape[:1], bool)
    d_lab = det[:, 0].astype(jnp.int32)
    d_score = det[:, 1]
    d_box = det[:, 2:6]
    D, G = det.shape[0], gt.shape[0]
    d_valid = d_lab >= 0
    g_valid = (g_lab >= 0) & ~g_difficult

    iou = _pairwise_iou(d_box, g_box, True)  # [D, G]
    same_class = d_lab[:, None] == g_lab[None, :]
    iou = jnp.where(same_class & g_valid[None, :] & d_valid[:, None],
                    iou, -1.0)

    # process detections in score order; greedily claim the best unmatched
    # same-class gt with IoU >= overlap
    order = jnp.argsort(-jnp.where(d_valid, d_score, -jnp.inf))

    def body(i, carry):
        tp, fp, used = carry
        d = order[i]
        ious = jnp.where(used, -1.0, iou[d])
        best_g = jnp.argmax(ious)
        ok = (ious[best_g] >= overlap) & d_valid[d]
        tp = tp.at[d].set(jnp.where(ok, 1.0, 0.0))
        fp = fp.at[d].set(jnp.where(d_valid[d] & ~ok, 1.0, 0.0))
        used = used.at[best_g].set(used[best_g] | ok)
        return tp, fp, used

    tp0 = jnp.zeros((D,))
    fp0 = jnp.zeros((D,))
    used0 = jnp.zeros((G,), bool)
    tp, fp, _ = lax.fori_loop(0, D, body, (tp0, fp0, used0))

    # per-class AP over the score-sorted list
    aps = []
    present = []
    for c in range(num_classes):
        npos = jnp.sum(g_valid & (g_lab == c)).astype(jnp.float32)
        in_c = (d_lab == c) & d_valid
        # sort class detections by score
        sc = jnp.where(in_c, d_score, -jnp.inf)
        c_order = jnp.argsort(-sc)
        c_tp = tp[c_order] * in_c[c_order]
        c_fp = fp[c_order] * in_c[c_order]
        cum_tp = jnp.cumsum(c_tp)
        cum_fp = jnp.cumsum(c_fp)
        recall = cum_tp / jnp.maximum(npos, 1.0)
        precision = cum_tp / jnp.maximum(cum_tp + cum_fp, 1.0)
        active = in_c[c_order]
        if ap_type == "11point":
            pts = []
            for r in [i / 10.0 for i in range(11)]:
                p_at = jnp.max(jnp.where(active & (recall >= r),
                                         precision, 0.0))
                pts.append(p_at)
            ap = sum(pts) / 11.0
        else:  # integral
            d_rec = jnp.diff(jnp.concatenate([jnp.zeros(1), recall]))
            ap = jnp.sum(jnp.where(active, precision * d_rec, 0.0))
        aps.append(jnp.where(npos > 0, ap, 0.0))
        present.append((npos > 0).astype(jnp.float32))
    aps = jnp.stack(aps)
    present = jnp.stack(present)
    m_ap = jnp.sum(aps) / jnp.maximum(jnp.sum(present), 1.0)
    zeros = jnp.zeros((num_classes, 1), jnp.float32)
    return {"MAP": m_ap.reshape(1),
            "AccumPosCount": zeros.astype(jnp.int32),
            "AccumTruePos": zeros, "AccumFalsePos": zeros}


@register_op("box_decoder_and_assign",
             inputs=["PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"],
             outputs=["DecodeBox", "OutputAssignBox"], no_grad=True)
def box_decoder_and_assign(ctx, attrs, PriorBox, PriorBoxVar, TargetBox,
                           BoxScore):
    """Decode per-class box deltas and assign each prior its best-scoring
    class's box (box_decoder_and_assign_op.cc)."""
    prior = PriorBox.reshape(-1, 4)
    n = prior.shape[0]
    deltas = TargetBox.reshape(n, -1, 4)  # [N, C, 4]
    var = (PriorBoxVar.reshape(-1, 4) if PriorBoxVar is not None
           else jnp.ones((1, 4)))
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    px = prior[:, 0] + pw * 0.5
    py = prior[:, 1] + ph * 0.5
    v = var if var.shape[0] == n else jnp.broadcast_to(var, (n, 4))
    cx = v[:, None, 0] * deltas[:, :, 0] * pw[:, None] + px[:, None]
    cy = v[:, None, 1] * deltas[:, :, 1] * ph[:, None] + py[:, None]
    bw = jnp.exp(jnp.minimum(v[:, None, 2] * deltas[:, :, 2], 10.0)) \
        * pw[:, None]
    bh = jnp.exp(jnp.minimum(v[:, None, 3] * deltas[:, :, 3], 10.0)) \
        * ph[:, None]
    decoded = jnp.stack([cx - bw / 2, cy - bh / 2,
                         cx + bw / 2 - 1.0, cy + bh / 2 - 1.0], axis=2)
    scores = BoxScore.reshape(n, -1)
    # best non-background class (class 0 = background per the reference)
    best = jnp.argmax(scores[:, 1:], axis=1) + 1 \
        if scores.shape[1] > 1 else jnp.zeros((n,), jnp.int32)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].astype(jnp.int32) *
        jnp.ones((1, 1, 4), jnp.int32), axis=1)[:, 0]
    return {"DecodeBox": decoded.reshape(n, -1),
            "OutputAssignBox": assigned}


@register_op("distribute_fpn_proposals", inputs=["FpnRois"],
             outputs=["MultiFpnRois*", "RestoreIndex"], no_grad=True)
def distribute_fpn_proposals(ctx, attrs, FpnRois):
    """Route each ROI to its FPN level by scale
    (distribute_fpn_proposals_op.cc): level = floor(log2(sqrt(area)/224))
    + refer_level, clipped.  TPU-static: each level output keeps the full
    capacity with non-member rows zeroed (RestoreIndex maps rows back)."""
    min_l = int(attrs.get("min_level", 2))
    max_l = int(attrs.get("max_level", 5))
    refer_l = int(attrs.get("refer_level", 4))
    refer_s = float(attrs.get("refer_scale", 224))
    rois = FpnRois.reshape(-1, 4)
    w = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(jnp.log2(jnp.maximum(scale, 1e-6) / refer_s + 1e-12)
                    ) + refer_l
    lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
    outs = []
    for l in range(min_l, max_l + 1):
        m = (lvl == l)[:, None]
        outs.append(jnp.where(m, rois, 0.0))
    restore = jnp.argsort(jnp.argsort(lvl, stable=True), stable=True)
    return {"MultiFpnRois": outs,
            "RestoreIndex": restore[:, None].astype(jnp.int32)}


@register_op("collect_fpn_proposals", inputs=["MultiLevelRois*",
                                              "MultiLevelScores*"],
             outputs=["FpnRois"], no_grad=True)
def collect_fpn_proposals(ctx, attrs, MultiLevelRois, MultiLevelScores):
    """Merge per-level proposals and keep the post_nms_topN best by score
    (collect_fpn_proposals_op.cc)."""
    post_n = int(attrs.get("post_nms_topN", 1000))
    rois = jnp.concatenate([r.reshape(-1, 4) for r in MultiLevelRois], 0)
    scores = jnp.concatenate(
        [s.reshape(-1) for s in MultiLevelScores], 0)
    k = min(post_n, scores.shape[0])
    top, idx = jax.lax.top_k(scores, k)
    return rois[idx]


@register_op("retinanet_target_assign",
             inputs=["Anchor", "GtBoxes", "GtLabels", "IsCrowd", "ImInfo"],
             outputs=["LocationIndex", "ScoreIndex", "TargetLabel",
                      "TargetBBox", "BBoxInsideWeight", "ForegroundNumber"],
             no_grad=True)
def retinanet_target_assign(ctx, attrs, Anchor, GtBoxes, GtLabels,
                            IsCrowd, ImInfo):
    """RetinaNet anchor labeling (retinanet_target_assign_op.cc): like
    rpn_target_assign but with CLASS labels for positives (focal-loss
    head) and no subsampling."""
    pos_thr = float(attrs.get("positive_overlap", 0.5))
    neg_thr = float(attrs.get("negative_overlap", 0.4))
    anchors = Anchor.reshape(-1, 4)
    gts = GtBoxes.reshape(-1, 4)
    glab = (GtLabels.reshape(-1).astype(jnp.int32)
            if GtLabels is not None
            else jnp.ones((gts.shape[0],), jnp.int32))
    a = anchors.shape[0]
    iou = _pairwise_iou(anchors, gts, True)
    gt_valid = (gts[:, 2] > gts[:, 0]) & (gts[:, 3] > gts[:, 1])
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    best_anchor_per_gt = jnp.argmax(iou, axis=0)
    is_best = jnp.zeros((a,), bool).at[best_anchor_per_gt].max(gt_valid)
    positive = (best_iou >= pos_thr) | is_best
    negative = (best_iou < neg_thr) & ~positive
    labels = jnp.where(positive, glab[best_gt],
                       jnp.where(negative, 0, -1))
    order = jnp.argsort(-jnp.where(positive, 1, jnp.where(negative, 0, -1)
                                   ))
    tgt_gt = gts[best_gt]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + aw / 2
    ay = anchors[:, 1] + ah / 2
    gw = tgt_gt[:, 2] - tgt_gt[:, 0]
    gh = tgt_gt[:, 3] - tgt_gt[:, 1]
    gx2 = tgt_gt[:, 0] + gw / 2
    gy2 = tgt_gt[:, 1] + gh / 2
    tgt = jnp.stack([
        (gx2 - ax) / jnp.maximum(aw, 1e-6),
        (gy2 - ay) / jnp.maximum(ah, 1e-6),
        jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-6), 1e-6)),
        jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-6), 1e-6)),
    ], axis=1)
    fg = jnp.sum(positive).astype(jnp.int32)
    return {
        "LocationIndex": jnp.where(
            jnp.arange(a) < fg, order, -1).astype(jnp.int32),
        "ScoreIndex": jnp.where(
            jnp.arange(a) < fg + jnp.sum(negative), order, -1
        ).astype(jnp.int32),
        "TargetLabel": labels.astype(jnp.int32),
        "TargetBBox": tgt,
        "BBoxInsideWeight": jnp.where(positive[:, None], 1.0, 0.0)
                            * jnp.ones((1, 4)),
        "ForegroundNumber": fg.reshape(1),
    }


@register_op("roi_perspective_transform",
             inputs=["X", "ROIs"],
             outputs=["Out", "Mask", "TransformMatrix", "Out2InIdx",
                      "Out2InWeights"],
             no_grad=True,
             stateful_outputs=("Mask", "TransformMatrix", "Out2InIdx",
                               "Out2InWeights"))
def roi_perspective_transform(ctx, attrs, X, ROIs):
    """Perspective-warp quadrilateral ROIs to a fixed rectangle
    (roi_perspective_transform_op.cc, OCR text rectification): solve the
    4-point homography per ROI, then bilinear-sample.  ROIs: [R, 8]
    quad corners (x1..y4), optionally a leading batch index col."""
    h_out = int(attrs.get("transformed_height", 8))
    w_out = int(attrs.get("transformed_width", 8))
    scale = float(attrs.get("spatial_scale", 1.0))
    if ROIs.shape[-1] == 9:
        batch_idx = ROIs[:, 0].astype(jnp.int32)
        quads = ROIs[:, 1:] * scale
    else:
        batch_idx = jnp.zeros((ROIs.shape[0],), jnp.int32)
        quads = ROIs * scale
    r = quads.shape[0]
    n, c, h, w = X.shape
    # homography mapping unit rect corners -> quad corners (per ROI):
    # solve the standard 8x8 DLT system
    src = jnp.asarray([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]])
    dst = quads.reshape(r, 4, 2)

    def solve_h(d):
        rows = []
        rhs = []
        for k in range(4):
            sx, sy = src[k, 0], src[k, 1]
            dx, dy = d[k, 0], d[k, 1]
            rows.append(jnp.asarray(
                [sx, sy, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0])
                .at[6].set(-sx * dx).at[7].set(-sy * dx))
            rhs.append(dx)
            rows.append(jnp.asarray(
                [0.0, 0.0, 0.0, sx, sy, 1.0, 0.0, 0.0])
                .at[6].set(-sx * dy).at[7].set(-sy * dy))
            rhs.append(dy)
        A = jnp.stack(rows)
        b = jnp.asarray(rhs)
        sol = jnp.linalg.solve(A, b)
        return jnp.concatenate([sol, jnp.ones(1)]).reshape(3, 3)

    H = jax.vmap(solve_h)(dst)  # [R, 3, 3]
    ys = (jnp.arange(h_out) + 0.5) / h_out
    xs = (jnp.arange(w_out) + 0.5) / w_out
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    grid = jnp.stack([gx, gy, ones], axis=-1)  # [Ho, Wo, 3]
    mapped = jnp.einsum("rij,hwj->rhwi", H, grid)
    px = mapped[..., 0] / jnp.maximum(mapped[..., 2], 1e-8)
    py = mapped[..., 1] / jnp.maximum(mapped[..., 2], 1e-8)
    from .vision import _bilinear_sample

    gxn = 2.0 * px / jnp.maximum(w - 1, 1) - 1.0
    gyn = 2.0 * py / jnp.maximum(h - 1, 1) - 1.0
    feats = X[batch_idx]  # [R, C, H, W]
    out = _bilinear_sample(feats, gxn, gyn)  # [R, C, Ho, Wo]
    in_img = ((px >= 0) & (px <= w - 1) & (py >= 0)
              & (py <= h - 1)).astype(jnp.int32)
    return {"Out": out, "Mask": in_img[:, None],
            "TransformMatrix": H.reshape(r, 9),
            "Out2InIdx": jnp.zeros((1,), jnp.int32),
            "Out2InWeights": jnp.zeros((1,), jnp.float32)}


@register_op("generate_proposal_labels",
             inputs=["RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                     "ImInfo"],
             outputs=["Rois", "LabelsInt32", "BboxTargets",
                      "BboxInsideWeights", "BboxOutsideWeights"],
             no_grad=True)
def generate_proposal_labels(ctx, attrs, RpnRois, GtClasses, IsCrowd,
                             GtBoxes, ImInfo):
    """Sample foreground/background ROIs and build regression targets
    (generate_proposal_labels_op.cc, single image).  Deterministic
    hardest-first capped selection replaces random subsampling (TPU
    reproducibility); outputs are fixed-capacity, padding rows zeroed."""
    cap = int(attrs.get("batch_size_per_im", 256))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thresh = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    weights = [float(w) for w in attrs.get("bbox_reg_weights",
                                           [0.1, 0.1, 0.2, 0.2])]
    class_nums = int(attrs.get("class_nums", 81))
    rois = RpnRois.reshape(-1, 4)
    gts = GtBoxes.reshape(-1, 4)
    gcls = (GtClasses.reshape(-1).astype(jnp.int32)
            if GtClasses is not None
            else jnp.ones((gts.shape[0],), jnp.int32))
    r = rois.shape[0]
    iou = _pairwise_iou(rois, gts, True)
    gt_valid = (gts[:, 2] > gts[:, 0]) & (gts[:, 3] > gts[:, 1])
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    is_fg = best_iou >= fg_thresh
    is_bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
    fg_quota = int(round(cap * fg_frac))
    # deterministic selection: highest-IoU foregrounds, then backgrounds
    fg_order = jnp.argsort(-jnp.where(is_fg, best_iou, -jnp.inf))
    n_fg = jnp.minimum(jnp.sum(is_fg), fg_quota)
    bg_order = jnp.argsort(-jnp.where(is_bg, best_iou, -jnp.inf))
    n_bg = jnp.minimum(jnp.sum(is_bg), cap - n_fg)
    k = min(cap, r)
    take_fg = jnp.arange(k) < n_fg
    sel = jnp.where(take_fg, fg_order[:k],
                    bg_order[jnp.maximum(jnp.arange(k) - n_fg, 0)])
    valid = jnp.arange(k) < (n_fg + n_bg)
    sel_rois = jnp.where(valid[:, None], rois[sel], 0.0)
    labels = jnp.where(take_fg & valid, gcls[best_gt[sel]], 0)
    # encoded regression targets for foregrounds
    tgt_gt = gts[best_gt[sel]]
    rw = jnp.maximum(sel_rois[:, 2] - sel_rois[:, 0], 1e-6)
    rh = jnp.maximum(sel_rois[:, 3] - sel_rois[:, 1], 1e-6)
    rx = sel_rois[:, 0] + rw / 2
    ry = sel_rois[:, 1] + rh / 2
    gw = jnp.maximum(tgt_gt[:, 2] - tgt_gt[:, 0], 1e-6)
    gh = jnp.maximum(tgt_gt[:, 3] - tgt_gt[:, 1], 1e-6)
    gx = tgt_gt[:, 0] + gw / 2
    gy = tgt_gt[:, 1] + gh / 2
    t = jnp.stack([(gx - rx) / rw / weights[0],
                   (gy - ry) / rh / weights[1],
                   jnp.log(gw / rw) / weights[2],
                   jnp.log(gh / rh) / weights[3]], axis=1)
    fg_mask = (take_fg & valid)[:, None]
    # per-class layout [K, 4*class_nums] like the reference
    tgt_full = jnp.zeros((k, 4 * class_nums))
    col = jnp.maximum(labels, 0)[:, None] * 4 + jnp.arange(4)[None, :]
    tgt_full = jax.vmap(
        lambda row, c, v, m: row.at[c].set(jnp.where(m, v, 0.0))
    )(tgt_full, col, t, fg_mask[:, 0:1].repeat(4, 1) if False else
      jnp.broadcast_to(fg_mask, (k, 4)))
    inside = jax.vmap(
        lambda row, c, m: row.at[c].set(
            jnp.where(m, 1.0, 0.0)))(jnp.zeros((k, 4 * class_nums)), col,
                                     jnp.broadcast_to(fg_mask, (k, 4)))
    return {
        "Rois": sel_rois,
        "LabelsInt32": labels.astype(jnp.int32)[:, None],
        "BboxTargets": tgt_full,
        "BboxInsideWeights": inside,
        "BboxOutsideWeights": inside,
    }


@register_op("generate_mask_labels",
             inputs=["ImInfo", "GtClasses", "IsCrowd", "GtSegms", "Rois",
                     "LabelsInt32"],
             outputs=["MaskRois", "RoiHasMaskInt32", "MaskInt32"],
             no_grad=True)
def generate_mask_labels(ctx, attrs, ImInfo, GtClasses, IsCrowd, GtSegms,
                         Rois, LabelsInt32):
    """Mask targets for Mask R-CNN (generate_mask_labels_op.cc).
    Deviation: GtSegms are PRE-RASTERIZED [G, H, W] binary masks (COCO
    polygon rasterization is host preprocessing); each foreground ROI
    crops+resizes its matched gt mask to resolution^2 via bilinear
    sampling, output one-hot per class like the reference."""
    num_classes = int(attrs.get("num_classes", 81))
    res = int(attrs.get("resolution", 14))
    rois = Rois.reshape(-1, 4)
    labels = jnp.reshape(LabelsInt32, (-1,)).astype(jnp.int32)
    masks = GtSegms  # [G, H, W]
    g, mh, mw = masks.shape
    k = rois.shape[0]
    # match each fg ROI to the gt mask with max overlap of its box...
    # the reference reuses the proposal-label matching; here: center
    # containment heuristic replaced by IoU of boxes derived from masks
    ys = jnp.any(masks > 0.5, axis=2)
    xs_ = jnp.any(masks > 0.5, axis=1)
    def bounds(b, n):
        idx = jnp.arange(n)
        lo = jnp.min(jnp.where(b, idx, n)).astype(jnp.float32)
        hi = jnp.max(jnp.where(b, idx, -1)).astype(jnp.float32)
        return lo, hi
    y1, y2 = jax.vmap(lambda b: bounds(b, mh))(ys)
    x1, x2 = jax.vmap(lambda b: bounds(b, mw))(xs_)
    gboxes = jnp.stack([x1, y1, x2, y2], axis=1)
    iou = _pairwise_iou(rois, gboxes, True)
    best = jnp.argmax(iou, axis=1)
    # crop + resize each roi's matched mask
    from .vision import _bilinear_sample

    sub = (jnp.arange(res, dtype=jnp.float32) + 0.5) / res
    px = rois[:, 0:1] + sub[None, :] * jnp.maximum(
        rois[:, 2:3] - rois[:, 0:1], 1e-6)
    py = rois[:, 1:2] + sub[None, :] * jnp.maximum(
        rois[:, 3:4] - rois[:, 1:2], 1e-6)
    gx = 2.0 * px / jnp.maximum(mw - 1, 1) - 1.0
    gy = 2.0 * py / jnp.maximum(mh - 1, 1) - 1.0
    sel = masks[best][:, None]  # [K, 1, H, W]
    grid_x = jnp.broadcast_to(gx[:, None, :], (k, res, res))
    grid_y = jnp.broadcast_to(gy[:, :, None], (k, res, res))
    crop = _bilinear_sample(sel, grid_x, grid_y)[:, 0]  # [K, res, res]
    binm = (crop > 0.5).astype(jnp.int32)
    has_mask = (labels > 0).astype(jnp.int32)
    # per-class one-hot layout [K, num_classes * res * res]
    out = jnp.zeros((k, num_classes, res, res), jnp.int32)
    out = jax.vmap(
        lambda o, c, m, hm: o.at[c].set(m * hm)
    )(out, jnp.maximum(labels, 0), binm, has_mask)
    return {"MaskRois": rois, "RoiHasMaskInt32": has_mask[:, None],
            "MaskInt32": out.reshape(k, -1)}


@register_op("retinanet_detection_output",
             inputs=["BBoxes*", "Scores*", "Anchors*", "ImInfo"],
             outputs=["Out"], no_grad=True)
def retinanet_detection_output(ctx, attrs, BBoxes, Scores, Anchors,
                               ImInfo):
    """Decode per-level retinanet heads + class-wise NMS
    (retinanet_detection_output_op.cc), single image, fixed-capacity
    padded output [keep_top_k, 6]."""
    score_thr = float(attrs.get("score_threshold", 0.05))
    nms_top_k = int(attrs.get("nms_top_k", 1000))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    all_boxes, all_scores = [], []
    for bb, sc, an in zip(BBoxes, Scores, Anchors):
        deltas = bb.reshape(-1, 4)
        anchors = an.reshape(-1, 4)
        scores = sc.reshape(deltas.shape[0], -1)  # [A, C]
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        ax = anchors[:, 0] + aw / 2
        ay = anchors[:, 1] + ah / 2
        cx = deltas[:, 0] * aw + ax
        cy = deltas[:, 1] * ah + ay
        bw = jnp.exp(jnp.minimum(deltas[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(deltas[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2, cy + bh / 2], axis=1)
        all_boxes.append(boxes)
        all_scores.append(scores)
    boxes = jnp.concatenate(all_boxes, 0)
    scores = jnp.concatenate(all_scores, 0)  # [A, C]
    n_cls = scores.shape[1]
    outs = []
    for c in range(n_cls):
        sc = jnp.where(scores[:, c] > score_thr, scores[:, c], -jnp.inf)
        k = min(nms_top_k, sc.shape[0])
        keep, top_s, top_b, _ = _nms_single_class(
            boxes, sc, score_thr, nms_thr, 1.0, k, False)
        lab = jnp.full((k,), c + 1, jnp.float32)
        outs.append(jnp.concatenate(
            [lab[:, None], jnp.where(keep, top_s, -jnp.inf)[:, None],
             top_b], axis=1))
    cand = jnp.concatenate(outs, 0)  # [C*k, 6]
    kk = min(keep_top_k, cand.shape[0])
    top_s, idx = jax.lax.top_k(cand[:, 1], kk)
    sel = cand[idx]
    valid = jnp.isfinite(top_s)
    return jnp.where(valid[:, None], sel, -1.0)


@register_op("ssd_loss",
             inputs=["Loc", "Conf", "GTBox", "GTLabel", "PriorBox",
                     "PriorBoxVar"],
             outputs=["Loss"])
def ssd_loss(ctx, attrs, Loc, Conf, GTBox, GTLabel, PriorBox, PriorBoxVar):
    """SSD training loss (reference layers/detection.py:1074 composite:
    bipartite_match + target_assign + mine_hard_examples + smooth_l1 +
    softmax CE), redesigned TPU-static in one fused computation:

    Loc [N,P,4], Conf [N,P,C], GTBox [N,G,4] (zero-area padding rows),
    GTLabel [N,G] (-1 padding), PriorBox [P,4], PriorBoxVar [P,4]|None →
    Loss [N,P,1] per-prior weighted loss (normalize divides by the
    per-image positive count, the reference's npos normalization).

    Matching = per-prior argmax IoU thresholded at overlap_threshold,
    plus the bipartite seed (each valid gt force-claims its best prior);
    negatives = unmatched priors with best IoU < neg_overlap, hardest
    ceil(neg_pos_ratio·npos) kept by rank (mining is stop_gradient, like
    the reference's non-differentiable mining op).
    """
    bg = int(attrs.get("background_label", 0))
    ov_th = float(attrs.get("overlap_threshold", 0.5))
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_ov = float(attrs.get("neg_overlap", 0.5))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    normalize = bool(attrs.get("normalize", True))

    P = PriorBox.shape[0]
    G = GTBox.shape[1]
    pcx, pcy, pw, ph = _center_size(PriorBox, True)
    var = (PriorBoxVar if PriorBoxVar is not None
           else jnp.asarray([0.1, 0.1, 0.2, 0.2], Loc.dtype)[None, :]
           * jnp.ones((P, 4), Loc.dtype))

    def one(loc, conf, gtb, gtl):
        gtl = gtl.reshape(-1).astype(jnp.int32)
        valid_gt = gtl >= 0
        iou = _pairwise_iou(gtb, PriorBox, True)          # [G, P]
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=0).astype(jnp.int32)   # [P]
        best_iou = jnp.max(iou, axis=0)                       # [P]
        match = jnp.where(best_iou > ov_th, best_gt, -1)
        # bipartite seed: every valid gt claims its best prior.  Invalid
        # (padding) gts are redirected to the out-of-bounds index P so
        # their scatter is DROPPED — a where() on the update value would
        # still write a stale match[best_prior] at prior 0 for every
        # padding row, clobbering real seeds (last-writer-wins)
        best_prior = jnp.argmax(iou, axis=1).astype(jnp.int32)  # [G]
        seed_idx = jnp.where(valid_gt, best_prior, P)
        match = match.at[seed_idx].set(
            jnp.arange(G, dtype=jnp.int32), mode="drop")
        pos = match >= 0

        # conf CE per prior against matched label (bg for negatives)
        lab = jnp.where(pos, gtl[jnp.maximum(match, 0)], bg)
        logp = jax.nn.log_softmax(conf, axis=-1)
        ce = -jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]

        # loc smooth_l1 on positives, encoded center-size deltas
        tgt = gtb[jnp.maximum(match, 0)]                 # [P, 4]
        tcx = (tgt[:, 0] + tgt[:, 2]) / 2.0
        tcy = (tgt[:, 1] + tgt[:, 3]) / 2.0
        tw = jnp.maximum(tgt[:, 2] - tgt[:, 0], 1e-8)
        th = jnp.maximum(tgt[:, 3] - tgt[:, 1], 1e-8)
        enc = jnp.stack([
            (tcx - pcx) / pw, (tcy - pcy) / ph,
            jnp.log(tw / pw), jnp.log(th / ph)], axis=-1) / var
        d = loc - jax.lax.stop_gradient(enc)
        sl1 = jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d,
                        jnp.abs(d) - 0.5).sum(axis=-1)
        loc_loss = jnp.where(pos, sl1, 0.0)

        # hard-negative mining (stop_gradient selection)
        npos = jnp.sum(pos)
        cand = (~pos) & (best_iou < neg_ov)
        nloss = jnp.where(cand, jax.lax.stop_gradient(ce), -jnp.inf)
        ranks = jnp.argsort(jnp.argsort(-nloss))
        quota = jnp.minimum(
            jnp.ceil(npos.astype(jnp.float32) * ratio).astype(jnp.int32),
            jnp.sum(cand))
        keep_neg = cand & (ranks < quota)

        sel = pos | keep_neg
        per_prior = (conf_w * jnp.where(sel, ce, 0.0)
                     + loc_w * loc_loss)
        if normalize:
            per_prior = per_prior / jnp.maximum(
                npos.astype(per_prior.dtype), 1.0)
        return per_prior

    loss = jax.vmap(one)(Loc, Conf, GTBox,
                         GTLabel.reshape(GTBox.shape[0], G))
    return loss[..., None]
