"""Shared helpers for op lowerings."""

import numpy as np
import jax.numpy as jnp

from ..core import VarDesc, convert_np_dtype_to_dtype_


def resolve_dtype(attr_dtype):
    """Resolve a dtype attr (str / numpy / VarType enum int) to a jnp dtype,
    canonicalized for TPU: 64-bit types map to their 32-bit versions (jax
    default x64-disabled semantics; the graph-level dtype metadata retains
    the declared width)."""
    if isinstance(attr_dtype, (int, VarDesc.VarType)) and not isinstance(
        attr_dtype, bool
    ):
        name = convert_np_dtype_to_dtype_(VarDesc.VarType(int(attr_dtype)))
    else:
        name = convert_np_dtype_to_dtype_(attr_dtype)
    if name == "bfloat16":
        return jnp.bfloat16
    canon = {"int64": "int32", "float64": "float32", "uint64": "uint32"}
    return np.dtype(canon.get(name, name))


def fluid_broadcast(x, y, axis):
    """Fluid elementwise broadcast semantics (reference
    ``operators/elementwise/elementwise_op_function.h``): align y's dims to
    x's starting at `axis` (default -1 = trailing alignment, i.e. numpy)."""
    xnd, ynd = jnp.ndim(x), jnp.ndim(y)
    if xnd == ynd or ynd == 0:
        return x, y
    if xnd > ynd:
        if axis is None or axis == -1:
            axis = xnd - ynd
        new_shape = (1,) * axis + tuple(jnp.shape(y)) + (1,) * (xnd - axis - ynd)
        return x, jnp.reshape(y, new_shape)
    else:
        if axis is None or axis == -1:
            axis = ynd - xnd
        new_shape = (1,) * axis + tuple(jnp.shape(x)) + (1,) * (ynd - axis - xnd)
        return jnp.reshape(x, new_shape), y


def normalize_axis(axis, ndim):
    if axis < 0:
        axis += ndim
    return axis


def flatten_concat(xs, dtype=None):
    """Pack a list of arrays into one flat stream (the multi-tensor /
    bucketed-collective layout), optionally casting each segment."""
    return jnp.concatenate([
        x.reshape(-1).astype(dtype) if dtype is not None else x.reshape(-1)
        for x in xs
    ])


def split_like(flat, refs, cast=True):
    """Unpack a flat stream into segments shaped (and, with ``cast``,
    dtyped) like ``refs`` — the inverse of :func:`flatten_concat`.
    Segment sizes are static (taken from the refs' shapes), so the
    slices stay jit-friendly."""
    outs = []
    off = 0
    for r in refs:
        shape = jnp.shape(r)
        n = 1
        for d in shape:
            n *= int(d)
        seg = flat[off:off + n].reshape(shape)
        outs.append(seg.astype(r.dtype) if cast else seg)
        off += n
    return outs
