"""Pallas TPU kernels for the hot ops.

The reference keeps a hand-tuned native kernel library for its hot loops
(x86 JIT codegen under ``paddle/fluid/operators/jit/``, fused CUDA kernels
under ``operators/fused/``).  The TPU-native analogue is Pallas: kernels
written against VMEM/MXU with explicit blocking, compiled by Mosaic.  Each
kernel here ships with an XLA fallback so every op runs on any backend; the
Pallas path is selected on TPU (or when interpret-mode testing is forced).
"""

# NOTE: deliberately NO `from .flash_attention import flash_attention`
# re-export: it would rebind the package attribute `flash_attention`
# from the submodule to the function, so `import
# paddle_tpu.ops.pallas.flash_attention as FA` (and the from-import of
# the name) silently yields the FUNCTION — which cost a round-5
# hardware window its whole block-shape sweep.  Import the function
# from the submodule: `from paddle_tpu.ops.pallas.flash_attention
# import flash_attention`.
from . import flash_attention  # noqa: F401
from . import flash_decode  # noqa: F401
from . import conv_bn_act  # noqa: F401
from . import embedding  # noqa: F401
