"""Device-side embedding gather as a Pallas TPU kernel (scalar-prefetch
row DMA), with the scatter-add backward.

Reference analogue: the distributed lookup-table path
(``operators/distributed/parameter_prefetch.cc``) whose TPU host-side
redesign is :mod:`paddle_tpu.host_table` — the table lives in host RAM
and every step pays a host gather + H2D of the slab plus a D2H of the
slab gradient.  That round-trip caps DeepFM at its baseline (2720
ex/s/chip flat).  When the table FITS device memory (or a row shard of
it does, ``_is_distributed`` row sharding), the lookups belong on the
chip: this module is that device-side gather.

Kernel: ``pltpu.PrefetchScalarGridSpec`` with the flat id vector as the
scalar-prefetch argument — the grid is one step per id, and the table
BlockSpec's index map reads ``ids_ref[i]`` to DMA exactly row ``ids[i]``
HBM→VMEM (rows never transit as a dense [V, D] read; only the touched
rows move).  The id stream is known before the kernel body runs, so
Mosaic double-buffers the row DMAs across grid steps.

Backward: the standard sparse-embedding gradient — a scatter-add of the
slab gradient into a zero [V, D] buffer (``.at[ids].add``), XLA's
native SelectedRows-equivalent form on TPU, attached via custom_vjp so
both the Pallas and XLA forwards share it.

Fallback: ``jnp.take`` (the exact ``lookup_table`` lowering semantics:
negative ids clamp to row 0, overflowing ids clamp to the last row,
``padding_idx`` rows read zeros) off-TPU or for ineligible shapes;
``PADDLE_TPU_PALLAS=interpret`` forces the kernel on CPU for tests.
"""

import functools
import os

import jax
import jax.numpy as jnp

from .flash_attention import _HAS_PLTPU, pallas_supported, pl, pltpu


def _pallas_mode():
    return os.environ.get("PADDLE_TPU_PALLAS", "")


def gather_eligible(rows, dim):
    """Whether the Pallas gather kernel can take a [rows, dim] table."""
    if not pallas_supported() or _pallas_mode() == "off":
        return False
    if dim % 128 or dim > 8192 or rows < 1:
        return False
    if _pallas_mode() == "interpret":
        return True
    if not _HAS_PLTPU:
        return False
    plat = jax.devices()[0].platform.lower()
    return "tpu" in plat or "axon" in plat


def _gather_kernel(ids_ref, tab_ref, out_ref):
    # the BlockSpec index maps already routed row ids[i] into tab_ref
    out_ref[...] = tab_ref[...]


def _pallas_gather(table, flat_ids):
    n = flat_ids.shape[0]
    v, d = table.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, ids: (i, 0)),
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, d), table.dtype),
        interpret=_pallas_mode() == "interpret",
    )(flat_ids, table)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _gather_core(table, flat_ids, meta):
    """Row gather with clamped int32 ids; Pallas when eligible.
    ``meta`` = (rows, dim, dtype_str) — static, so the backward knows
    the table struct without hauling the table through the residuals."""
    if gather_eligible(*table.shape):
        return _pallas_gather(table, flat_ids)
    return jnp.take(table, flat_ids, axis=0)


def _gather_core_fwd(table, flat_ids, meta):
    return _gather_core(table, flat_ids, meta), flat_ids


def _gather_core_bwd(meta, flat_ids, dout):
    rows, dim, dtype = meta
    # scatter-add: duplicate ids accumulate, exactly the vjp of take
    # (and the reference's SelectedRows sparse-grad merge-add)
    dtab = jnp.zeros((rows, dim), dout.dtype).at[flat_ids].add(dout)
    return dtab.astype(dtype), None


_gather_core.defvjp(_gather_core_fwd, _gather_core_bwd)


def embedding_gather(W, Ids, padding_idx=-1):
    """``W[ids]`` with the framework ``lookup_table`` semantics, Pallas
    row-DMA gather on TPU (XLA take elsewhere).

    W: [V, D]; Ids: any int shape, a trailing dim of 1 is squeezed
    (the reference's ``[..., 1]`` id layout); returns ids.shape + (D,).
    Negative ids clamp to row 0 and ids >= V NaN-fill with no gradient
    (``jnp.take``'s default fill mode — identical to the unfused
    lowering, so the rewrite is value-preserving even on corrupt id
    streams); ``padding_idx`` rows come back zero with no gradient.
    """
    ids = Ids
    squeeze_last = ids.ndim > 1 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids[..., 0]
    ids = ids.astype(jnp.int32)
    v, dim = W.shape
    flat = jnp.clip(ids, 0, v - 1).reshape(-1)
    meta = (int(v), int(dim), str(W.dtype))
    out = _gather_core(W, flat, meta).reshape(ids.shape + (dim,))
    if jnp.issubdtype(out.dtype, jnp.floating):
        # jnp.take's default fill mode NaN-fills ids >= V (and the vjp
        # sends them no gradient) — replicate exactly, so the fused op
        # is value-preserving vs the lookup_table lowering even on
        # corrupt id streams
        out = jnp.where((ids >= v)[..., None],
                        jnp.full_like(out, jnp.nan), out)
    if padding_idx is not None and padding_idx != -1:
        out = jnp.where(
            (ids == padding_idx)[..., None], jnp.zeros_like(out), out)
    return out
