"""Fused BatchNorm + activation epilogue for conv outputs, as a Pallas
TPU kernel.

Reference analogue: the conv+BN+act fusion the reference keeps as
native passes and kernels (``fuse_bn_act_ops`` build-strategy pass and
the inference-time conv+bn fold).  On TPU the conv itself belongs to
XLA — ``lax.conv_general_dilated`` drives the MXU at full rate and a
hand-blocked Pallas conv would re-derive exactly the pipelining Mosaic
already emits — but the r05 ResNet-50 profile shows the EPILOGUE is
what XLA leaves on the floor (MFU 0.250 measured vs 0.381 by XLA's own
accounting): the batch-norm normalize/affine and the relu each cost a
full HBM round-trip of the conv output, and training-mode BN splits
into stats + normalize XLA does not always fuse back into one sweep.

This module is that epilogue as ONE VMEM pass over the conv output in
its channels-last 2-D view ``[R, C]`` (R = N·H·W): normalize with
precomputed per-channel ``mean``/``rstd``, affine with ``gamma``/
``beta``, activation, one read + one write.  The TPP decomposition
argument (arXiv:2104.05755): express the composite as one micro-kernel
over a 2-D tile and let the framework loop over tiles — here the Pallas
grid over row blocks, whose size is an autotunable knob
(``PADDLE_TPU_CONV_BN_BLOCK_ROWS`` caps it; the autotune cache can
re-decide it per (R, C, dtype)).

Backward is the matching one-pass kernel: activation mask, per-channel
``dgamma``/``dbeta``/``dmean``/``drstd`` partials accumulated across
sequential grid steps (the fused-LN discipline — TPU grid steps revisit
the pinned [1, C] output block), and the elementwise ``dy``.  The chain
through the batch statistics to the conv output is OUTSIDE the custom
vjp (plain jnp reductions), so jax composes the full BN-train gradient
correctly.

Eligibility: channels-last 2-D view with ``C % 128 == 0`` (the lane
dimension), ``R % 8 == 0``, relu/identity activation.  Everything else
— NCHW without a profitable transpose, odd channel counts, exotic
activations — takes the pure-XLA composite in ``ops/nn.py``, which is
bit-exact with the unfused conv→batch_norm→act chain by construction.
``PADDLE_TPU_PALLAS=interpret`` forces the kernel on CPU (tests);
``=off`` forces the XLA path.
"""

import functools
import os

import jax
import jax.numpy as jnp

from .flash_attention import _HAS_PLTPU, pallas_supported, pl, pltpu

_DEFAULT_BLOCK_ROWS = 256

# activations the kernel implements in-VMEM; everything else falls back
# to the XLA composite (which supports any registered activation)
KERNEL_ACTS = ("identity", "relu")


def _pallas_mode():
    return os.environ.get("PADDLE_TPU_PALLAS", "")


def _block_rows(n, c, dtype):
    """Rows per grid step: env cap → autotune-cached winner per
    (R, C, dtype) → the hand-set default; always a divisor of n."""
    try:
        from ...autotune import cached_block_cap

        cap = cached_block_cap(
            "conv_bn_act", "PADDLE_TPU_CONV_BN_BLOCK_ROWS",
            "block_rows", _DEFAULT_BLOCK_ROWS,
            rows=n, channels=c, dtype=str(dtype))
    except Exception:  # noqa: BLE001 - autotune unavailable
        cap = _DEFAULT_BLOCK_ROWS
    bn = min(max(cap, 1), n)
    while n % bn:
        bn //= 2
    return max(bn, 1)


def epilogue_eligible(rows, channels, act):
    """Whether the Pallas epilogue kernel can take this site (the caller
    already arranged a channels-last 2-D view)."""
    if not pallas_supported() or _pallas_mode() == "off":
        return False
    if act not in KERNEL_ACTS:
        return False
    if channels % 128 or channels > 4096 or rows % 8:
        return False
    if _pallas_mode() == "interpret":
        return True
    if not _HAS_PLTPU:
        return False
    plat = jax.devices()[0].platform.lower()
    return "tpu" in plat or "axon" in plat


def _apply_act(x, act):
    if act == "relu":
        return jnp.maximum(x, 0)
    return x


def _fwd_kernel(y_ref, g_ref, b_ref, m_ref, r_ref, out_ref, *, act):
    y = y_ref[...].astype(jnp.float32)
    # the same float sequence as the unfused batch_norm lowering:
    # (x - mean) * rstd, then * gamma + beta, then cast, then act —
    # elementwise, so the kernel output is bit-identical per element
    h = (y - m_ref[...].astype(jnp.float32)) * r_ref[...]
    h = h * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    out_ref[...] = _apply_act(h.astype(out_ref.dtype), act)


def _bwd_kernel(dout_ref, y_ref, g_ref, b_ref, m_ref, r_ref,
                dy_ref, dg_ref, db_ref, dm_ref, dr_ref, *, act):
    i = pl.program_id(0)
    dout = dout_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    r = r_ref[...]
    centered = y - m
    xhat = centered * r
    if act == "relu":
        # recompute the pre-cast activation input; the mask at exactly 0
        # matches jnp.maximum's vjp convention (grad flows iff s > 0)
        s = xhat * g + b_ref[...].astype(jnp.float32)
        dout = jnp.where(s > 0, dout, 0.0)

    # per-channel partials accumulate across sequential grid steps into
    # the pinned [1, C] output blocks (index_map (0, 0)) — the fused-LN
    # discipline; a [grid, C] partials array would need a block first
    # dim of 1, which Mosaic's (8, 128) tiling rejects
    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros(dg_ref.shape, dg_ref.dtype)
        db_ref[...] = jnp.zeros(db_ref.shape, db_ref.dtype)
        dm_ref[...] = jnp.zeros(dm_ref.shape, dm_ref.dtype)
        dr_ref[...] = jnp.zeros(dr_ref.shape, dr_ref.dtype)

    dg_ref[...] += jnp.sum(dout * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dout, axis=0, keepdims=True)
    gr = g * r
    dy = dout * gr
    dm_ref[...] += -jnp.sum(dy, axis=0, keepdims=True)
    dr_ref[...] += jnp.sum(dout * g * centered, axis=0, keepdims=True)
    dy_ref[...] = dy.astype(dy_ref.dtype)


def _fwd_call(y, gamma, beta, mean, rstd, act):
    n, d = y.shape
    bn = _block_rows(n, d, y.dtype)
    interpret = _pallas_mode() == "interpret"
    kernel = functools.partial(_fwd_kernel, act=act)
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), y.dtype),
        interpret=interpret,
    )(y, gamma.reshape(1, d), beta.reshape(1, d), mean.reshape(1, d),
      rstd.reshape(1, d))


def _bwd_call(dout, y, gamma, beta, mean, rstd, act):
    n, d = y.shape
    bn = _block_rows(n, d, y.dtype)
    interpret = _pallas_mode() == "interpret"
    kernel = functools.partial(_bwd_kernel, act=act)
    dy, dg, db, dm, dr = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), y.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(dout, y, gamma.reshape(1, d), beta.reshape(1, d),
      mean.reshape(1, d), rstd.reshape(1, d))
    return dy, dg, db, dm, dr


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _epilogue_core(y, gamma, beta, mean, rstd, act):
    return _fwd_call(y, gamma, beta, mean, rstd, act)


def _epilogue_core_fwd(y, gamma, beta, mean, rstd, act):
    out = _fwd_call(y, gamma, beta, mean, rstd, act)
    return out, (y, gamma, beta, mean, rstd)


def _epilogue_core_bwd(act, saved, dout):
    y, gamma, beta, mean, rstd = saved
    dy, dg, db, dm, dr = _bwd_call(dout, y, gamma, beta, mean, rstd, act)
    return (dy,
            dg.reshape(-1).astype(gamma.dtype),
            db.reshape(-1).astype(beta.dtype),
            dm.reshape(-1).astype(mean.dtype),
            dr.reshape(-1).astype(rstd.dtype))


_epilogue_core.defvjp(_epilogue_core_fwd, _epilogue_core_bwd)


def bn_act_epilogue(y2d, gamma, beta, mean, rstd, act="identity"):
    """``act((y - mean) * rstd * gamma + beta)`` over a channels-last
    2-D view in one VMEM pass.

    y2d: [R, C]; gamma/beta/mean/rstd: [C] (rstd precomputed as
    ``rsqrt(var + eps)`` — the caller owns the statistics so train/eval
    and running-stat updates stay with the op lowering).  The caller
    must have checked :func:`epilogue_eligible`.  Differentiable in
    every tensor argument; the chain through mean/rstd to the batch
    statistics composes outside via jax.
    """
    return _epilogue_core(y2d, gamma, beta,
                          mean.astype(jnp.float32),
                          rstd.astype(jnp.float32), act)
