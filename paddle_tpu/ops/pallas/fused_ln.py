"""Fused dropout + residual-add + layer_norm as a Pallas TPU kernel.

Reference analogue: the fused elementwise/normalization kernels the
reference keeps as native code — ``paddle/fluid/operators/fused/
fused_elemwise_activation_op.cc`` (chained elementwise fusion) and the
layer_norm JIT kernel under ``paddle/fluid/operators/jit/`` — hand-fused
hot-path kernels around the big GEMMs.

The transformer encoder's inter-GEMM glue is
``layer_norm(x + dropout(sublayer(x)))``: three HBM-bound ops whose
intermediates (the dropped activations and the residual sum) each cost a
full [N, D] round-trip.  XLA fuses the elementwise chain INTO the LN
reduction only partially (the r05 BERT profile bills dropout+norm ~4.6ms
of a 58ms step across 24 sites).  This kernel does the whole pattern in
one VMEM pass: mask bits from the TPU hardware PRNG (same per-block
counter-seeding discipline as the flash kernel, so the backward
recomputation draws the identical mask), the residual sum ``y`` saved
for backward, and the row stats written as [1, N] f32 so forward and
backward normalize identically.

Backward is the standard LN gradient with dgamma/dbeta accumulated as
per-block partials (summed outside the kernel), plus the dropout mask
re-applied to the dx branch.

Everything falls back to a pure-XLA expression of the same math off-TPU
or for ineligible shapes; ``PADDLE_TPU_PALLAS=interpret`` forces the
kernel in interpreter mode (CPU tests use the same
``PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota`` hash-mask escape as the flash
kernel — ``pltpu`` PRNG has no CPU lowering).
"""

import functools
import os

import jax
import jax.numpy as jnp

from .flash_attention import (_HAS_PLTPU, _hash_bits, _rate_threshold,
                              pallas_supported, pl, pltpu)

_BN = 256  # rows per grid step; D stays whole in the lane dimension


def _pallas_mode():
    return os.environ.get("PADDLE_TPU_PALLAS", "")


def _debug_mask():
    return os.environ.get("PADDLE_TPU_FLASH_DROPOUT_DEBUG") == "iota"


def _block_rows(n, d=None):
    """Rows per grid step: env cap → autotune-cached winner for this
    (n, d) → the hand-set default; always a divisor of n."""
    if d is None:
        cap = _BN
    else:
        try:
            from ...autotune import cached_block_cap

            cap = cached_block_cap(
                "fused_ln", "PADDLE_TPU_FUSED_LN_BLOCK_ROWS",
                "block_rows", _BN, rows=n, d=d)
        except Exception:  # pragma: no cover - autotune unavailable
            cap = _BN
    bn = min(max(cap, 1), n)
    while n % bn:
        bn //= 2
    return max(bn, 1)


def _row_keep_mask(shape, rate, seed_ref, i, bn, debug):
    """Bernoulli keep mask for rows [i*bn, (i+1)*bn); deterministic in
    (seed, i) so forward and backward draw identically."""
    if debug:
        r = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
             + (i * bn).astype(jnp.uint32))
        c = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
        bits = _hash_bits(jnp.uint32(0), r, c, seed_ref[0])
    else:
        pltpu.prng_seed(seed_ref[0], i)
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= _rate_threshold(rate)


def _fwd_kernel(x_ref, res_ref, g_ref, b_ref, seed_ref,
                out_ref, y_ref, mean_ref, rstd_ref,
                *, rate, eps, bn, debug):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    res = res_ref[...].astype(jnp.float32)
    if rate > 0.0:
        keep = _row_keep_mask(x.shape, rate, seed_ref, i, bn, debug)
        x = jnp.where(keep, x * (1.0 / (1.0 - rate)), 0.0)
    y = x + res
    mean = jnp.mean(y, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(y - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (y - mean) * rstd
    out = xhat * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    out_ref[...] = out.astype(out_ref.dtype)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean.reshape(1, -1)
    rstd_ref[...] = rstd.reshape(1, -1)


def _bwd_kernel(dout_ref, y_ref, g_ref, mean_ref, rstd_ref, seed_ref,
                dx_ref, dres_ref, dg_ref, db_ref,
                *, rate, bn, debug):
    i = pl.program_id(0)
    dout = dout_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    mean = mean_ref[...].reshape(-1, 1)
    rstd = rstd_ref[...].reshape(-1, 1)
    xhat = (y - mean) * rstd

    # dgamma/dbeta: TPU grid steps run sequentially and revisit the
    # same [1, D] output block (index_map pins (0, 0)), so accumulate
    # across row blocks in-kernel — a [grid, D] partials array would
    # need a block first-dim of 1, which Mosaic's (8, 128) tiling
    # rejects (this exact lowering error cost the first hardware
    # attempt of the A/B)
    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros(dg_ref.shape, dg_ref.dtype)
        db_ref[...] = jnp.zeros(db_ref.shape, db_ref.dtype)

    dg_ref[...] += jnp.sum(dout * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dout, axis=0, keepdims=True)
    dxhat = dout * g_ref[...].astype(jnp.float32)
    m1 = jnp.mean(dxhat, axis=1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    dy = rstd * (dxhat - m1 - xhat * m2)
    dres_ref[...] = dy.astype(dres_ref.dtype)
    dx = dy
    if rate > 0.0:
        keep = _row_keep_mask(dx.shape, rate, seed_ref, i, bn, debug)
        dx = jnp.where(keep, dx * (1.0 / (1.0 - rate)), 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _eligible(x):
    if not pallas_supported() or _pallas_mode() == "off":
        return False
    n, d = x.shape
    if d % 128 or d > 4096 or n % 8:
        return False
    if _pallas_mode() == "interpret":
        return True
    if not _HAS_PLTPU:
        return False
    plat = jax.devices()[0].platform.lower()
    return "tpu" in plat or "axon" in plat


def _xla_reference(x, residual, gamma, beta, rate, eps, seed, debug):
    """The same math as one jax expression (autodiff provides backward);
    the off-TPU / ineligible-shape fallback."""
    xf = x.astype(jnp.float32)
    if rate > 0.0:
        if debug:
            n, d = x.shape
            r = jnp.arange(n, dtype=jnp.uint32)[:, None]
            c = jnp.arange(d, dtype=jnp.uint32)[None, :]
            keep = _hash_bits(jnp.uint32(0), r, c,
                              seed[0].astype(jnp.uint32)) \
                >= _rate_threshold(rate)
        else:
            keep = jax.random.bernoulli(
                jax.random.PRNGKey(seed[0].astype(jnp.uint32)),
                1.0 - rate, x.shape)
        xf = jnp.where(keep, xf * (1.0 / (1.0 - rate)), 0.0)
    y = xf + residual.astype(jnp.float32)
    mean = jnp.mean(y, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(y - mean), axis=1, keepdims=True)
    xhat = (y - mean) * jax.lax.rsqrt(var + eps)
    out = xhat * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return out.astype(x.dtype)


def _fwd_call(x, residual, gamma, beta, rate, eps, seed):
    n, d = x.shape
    bn = _block_rows(n, d)
    grid = (n // bn,)
    debug = _debug_mask()
    interpret = _pallas_mode() == "interpret"
    kernel = functools.partial(_fwd_kernel, rate=rate, eps=eps, bn=bn,
                               debug=debug)
    out, y, mean, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
    )(x, residual, gamma.reshape(1, d), beta.reshape(1, d), seed)
    return out, y, mean, rstd


def _bwd_call(dout, y, gamma, mean, rstd, rate, seed, dtypes):
    n, d = y.shape
    bn = _block_rows(n, d)
    grid = (n // bn,)
    debug = _debug_mask()
    interpret = _pallas_mode() == "interpret"
    kernel = functools.partial(_bwd_kernel, rate=rate, bn=bn, debug=debug)
    dx, dres, dg_part, db_part = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), dtypes[0]),
            jax.ShapeDtypeStruct((n, d), dtypes[1]),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(dout, y, gamma.reshape(1, d), mean, rstd, seed)
    return dx, dres, dg_part, db_part


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_core(x, residual, gamma, beta, rate, eps, seed):
    out, _, _, _ = _fwd_call(x, residual, gamma, beta, rate, eps, seed)
    return out


def _fused_core_fwd(x, residual, gamma, beta, rate, eps, seed):
    out, y, mean, rstd = _fwd_call(x, residual, gamma, beta, rate, eps,
                                   seed)
    return out, (y, gamma, mean, rstd, seed)


def _fused_core_bwd(rate, eps, saved, dout):
    # y was stored in x's dtype and residual/beta share the model's
    # compute dtypes (y / gamma respectively) — cotangent dtypes follow
    y, gamma, mean, rstd, seed = saved
    dx, dres, dg, db = _bwd_call(
        dout, y, gamma, mean, rstd, rate, seed, (y.dtype, y.dtype))
    return (dx, dres, dg.reshape(-1).astype(gamma.dtype),
            db.reshape(-1).astype(gamma.dtype), None)


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


def fused_dropout_add_ln(x, residual, gamma, beta, dropout_rate=0.0,
                         eps=1e-5, seed=None):
    """``layer_norm(residual + dropout(x)) * gamma + beta`` in one pass.

    x, residual: [N, D] (callers flatten leading dims); gamma/beta: [D].
    dropout is inverted-scale (``upscale_in_train``); rate 0 skips the
    mask entirely (eval / no-dropout configs still save the fused
    HBM round-trips).  seed: int32 array shape [1] (required when
    dropout_rate > 0)."""
    rate = float(dropout_rate or 0.0)
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    if _pallas_mode() == "interpret" and rate > 0.0 and not _debug_mask():
        # the pltpu hardware PRNG has no CPU/interpret lowering — the
        # kernel would die deep in Pallas with an opaque 'prng_seed not
        # found for platform cpu'.  Unlike the flash entry (whose caller
        # explicitly opted into the kernel) this op is routinely
        # INTRODUCED by the fusion pass rewrite, so degrade to the XLA
        # composite instead of raising; set
        # PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota to run the kernel with the
        # deterministic debug hash instead.
        return _xla_reference(x, residual, gamma, beta, rate, eps, seed,
                              False)
    if not _eligible(x):
        return _xla_reference(x, residual, gamma, beta, rate, eps, seed,
                              _debug_mask())
    return _fused_core(x, residual, gamma, beta, rate, eps, seed)
