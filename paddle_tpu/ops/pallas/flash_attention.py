"""Flash attention (fwd + bwd) as Pallas TPU kernels.

Reference analogue: the fused attention kernels under
``paddle/fluid/operators/fused/`` (fusion_* ops) — hand-fused native kernels
for the hot path.  On TPU the hot path is attention; this module implements
the FlashAttention-2 blocked online-softmax algorithm so the [B,H,T,T]
score matrix never touches HBM:

* forward: grid (B*H, Tq/bq, Tk/bk), KV innermost; running (m, l, acc) live
  in VMEM scratch across the KV sweep; output + logsumexp written on the
  last KV block.
* backward: two kernels — dK/dV (grid over KV blocks, sweeping Q) and dQ
  (grid over Q blocks, sweeping KV) — using the saved logsumexp and the
  precomputed delta = rowsum(dO * O), the standard FA2 recomputation split.

Supported bias: an additive key-padding bias of shape [B, Tk] (the common
[B,1,1,Tk] mask squeezed), broadcast over heads and query positions; it is
treated as constant (no gradient — padding masks are data, not parameters).
Causal masking is a flag; above-diagonal blocks are skipped entirely.

Attention-probability dropout IS supported in-kernel (``dropout_rate``):
the FA2 formulation — the softmax denominator l comes from the UNdropped
probabilities, dropout scales the numerator entries feeding the PV matmul
— so the [B,H,T,T] mask never materializes in HBM.  Mask bits come from
the TPU hardware PRNG (``pltpu.prng_seed``/``prng_random_bits``), seeded
per (batch·head, q-block, k-block) so the backward recomputation draws
the IDENTICAL mask.  ``pltpu`` PRNG has no CPU lowering, so interpret-
mode tests set ``PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota``: mask bits then
come from a position hash (same formula exposed as
:func:`debug_keep_mask`) letting CPU tests verify the dropout MATH
against the XLA reference; the hardware PRNG path is validated on-chip.

Per-row stats (m, l) live in (block_q, 128) VMEM scratch with the value
replicated across lanes; rows are recovered with a lanes-reduce and moved
between row/column orientation with 2-D reshapes (both verified supported
by Mosaic on v5e).

Everything falls back to a pure-XLA implementation off-TPU or for shapes
the kernel does not cover; set ``PADDLE_TPU_PALLAS=interpret`` to force the
Pallas kernels in interpreter mode (CPU correctness tests), or ``=off`` to
force the XLA path.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp

try:  # pallas itself may be absent/broken on older jax (the container
    # pins 0.4.x — post-0.4 pallas API moves must not take the whole op
    # library down; the XLA composite below is the supported fallback)
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    _HAS_PALLAS = False

try:  # pallas TPU backend may be absent on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def pallas_supported():
    """Whether the Pallas kernels CAN run here (import succeeded).  On
    jax builds without a working ``jax.experimental.pallas`` every entry
    point silently takes the pure-XLA composite, so the fusion-pass
    plumbing (and tier-1 CPU tests) exercise the rewrites regardless."""
    return _HAS_PALLAS


def _use_pallas():
    if not _HAS_PALLAS:
        return False, False  # even PADDLE_TPU_PALLAS=interpret falls back
    mode = os.environ.get("PADDLE_TPU_PALLAS", "auto")
    if mode == "off":
        return False, False
    if mode == "interpret":
        return True, True
    return jax.default_backend() == "tpu" and _HAS_PLTPU, False


def _row(x2d):
    """(1, n) row from a (n, 1) column value."""
    return x2d.reshape(1, -1)


def _dropout_debug():
    return os.environ.get("PADDLE_TPU_FLASH_DROPOUT_DEBUG") == "iota"


def _rate_threshold(rate):
    """uint32 threshold: keep a cell iff its random bits >= threshold."""
    return jnp.uint32(min(int(rate * 4294967296.0), 4294967295))


def _hash_bits(b, r, c, seed):
    """Position-hash mask bits (debug/CPU path) — uint32 wraparound
    arithmetic, identical inside the kernel and in debug_keep_mask."""
    h = (r * jnp.uint32(2654435761)
         ^ (c * jnp.uint32(97559) + b * jnp.uint32(31)))
    h = h ^ seed.astype(jnp.uint32)
    return h * jnp.uint32(2246822519)


def _keep_mask(shape, rate, seed_ref, bh, qi, kj, block_q, block_k, debug):
    """In-kernel Bernoulli keep mask for the (qi, kj) block of
    batch·head bh.  Hardware path: per-block counter seeding of the TPU
    PRNG (fwd and bwd seed identically, so the draw reproduces)."""
    if debug:
        r = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
             + (qi * block_q).astype(jnp.uint32))
        c = (jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
             + (kj * block_k).astype(jnp.uint32))
        bits = _hash_bits(bh.astype(jnp.uint32), r, c, seed_ref[0])
    else:
        # v5e Mosaic caps prng_seed at 2 words ("Setting seed with more
        # than 2 values is not supported") — use BOTH words: batch·head
        # XORs into the user seed (word 0) so distinct bh never collide,
        # and only (qi, kj) share the mixing word.  Deterministic in
        # (bh, qi, kj), so the bwd recompute draws the identical mask;
        # int32 wraparound is well-defined in Mosaic.
        mix = qi * jnp.int32(7919) + kj * jnp.int32(104729)
        pltpu.prng_seed(seed_ref[0] ^ bh, mix)
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= _rate_threshold(rate)


def debug_keep_mask(bh, tq, tk, rate, seed):
    """Full-matrix keep mask for the debug hash — the OUT-of-kernel twin
    of the kernel's debug path, used by CPU tests and the XLA fallback
    under PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota."""
    b = jnp.arange(bh, dtype=jnp.uint32)[:, None, None]
    r = jnp.arange(tq, dtype=jnp.uint32)[None, :, None]
    c = jnp.arange(tk, dtype=jnp.uint32)[None, None, :]
    bits = _hash_bits(b, r, c, jnp.uint32(seed))
    return bits >= _rate_threshold(rate)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, m_out_ref,
                l_out_ref, acc_ref, m_ref, l_ref, *, sm_scale, causal,
                block_q, block_k, dropout_rate, dropout_debug):
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        # matmuls run at the INPUT dtype with f32 accumulation: under
        # bf16 AMP the MXU's bf16 rate is ~4x its f32 rate, and
        # bf16xbf16->f32 QK^T is bit-identical to upcast-then-f32 (bf16
        # casts are exact; 8-bit-mantissa products fit f32's 24).  Same
        # fix as the r04 XLA-fallback change; f32 inputs are unchanged.
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [bq, bk] f32
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)  # (1, bk) broadcasts
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)

        # lanes of m_ref/l_ref all hold the same value; a lanes-max recovers
        # the (bq, 1) column without lane slicing
        m_prev = jnp.max(m_ref[:], axis=1, keepdims=True)
        l_prev = jnp.max(l_ref[:], axis=1, keepdims=True)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # FA2 dropout: l accumulates the UNdropped p (true softmax
        # denominator); only the numerator entries feeding PV are masked
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(p.shape, dropout_rate, seed_ref, b, i, j,
                              block_q, block_k, dropout_debug)
            p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
        # PV at input dtype (p downcast under AMP): the MXU-rate
        # tradeoff mha_reference makes identically; acc stays f32
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        m = jnp.max(m_ref[:], axis=1, keepdims=True)
        l = jnp.max(l_ref[:], axis=1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # m and l are saved SEPARATELY (not lse = m + log l): when |m| is
        # large (e.g. -1e4 padding bias on every visible key) the f32 sum
        # m + log(l) loses all bits of log(l); exp(s - m)/l in the backward
        # reproduces the forward's p bit-for-bit instead
        m_out_ref[0] = _row(m)
        l_out_ref[0] = _row(l)


def _flash_fwd(q, k, v, bias, seed, causal, sm_scale, block_q, block_k,
               interpret, dropout_rate, dropout_debug):
    bh, tq, d = q.shape
    _, tk, _ = k.shape
    nq, nk = tq // block_q, tk // block_k
    grid = (bh, nq, nk)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [q, k, v]
    kw = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
              block_k=block_k, dropout_rate=dropout_rate,
              dropout_debug=dropout_debug)
    if bias is not None:
        nheads = bh // bias.shape[0]
        in_specs.append(
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j: (b // nheads, 0, j))
        )
        args.append(bias.reshape(bias.shape[0], 1, tk))
        kernel = functools.partial(_fwd_kernel, **kw)
    else:
        def kernel(qr, kr, vr, sr, o, mo, lo, acc, m, l):
            return _fwd_kernel(qr, kr, vr, None, sr, o, mo, lo, acc, m, l,
                               **kw)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    args.append(seed)

    o, m_out, l_out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o, m_out, l_out


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _recompute_p(q, k, bias_ref, m_col, l_col, sm_scale, causal, i, j,
                 block_q, block_k):
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    if causal:
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(rows >= cols, s, NEG_INF)
    return jnp.exp(s - m_col) / l_col


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, do_ref, m_ref,
                    l_ref, dl_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    sm_scale, causal, block_q, block_k, dropout_rate,
                    dropout_debug):
    b = pl.program_id(0)
    j = pl.program_id(1)  # kv block
    i = pl.program_id(2)  # q block (innermost sweep)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        # input-dtype matmuls, f32 accumulation (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        m_col = m_ref[0].reshape(block_q, 1)
        l_col = l_ref[0].reshape(block_q, 1)
        delta_col = dl_ref[0].reshape(block_q, 1)
        p = _recompute_p(q, k, bias_ref, m_col, l_col, sm_scale, causal,
                         i, j, block_q, block_k)
        # dP = dO @ V^T
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            # the SAME (b, i, j) seeding as the forward reproduces the
            # mask; O = P_drop V, so dV uses P_drop and the softmax-
            # jacobian input is the mask-scaled dP (sum P·dP = delta
            # still holds because delta = rowsum(dO·O))
            keep = _keep_mask(p.shape, dropout_rate, seed_ref, b, i, j,
                              block_q, block_k, dropout_debug)
            p_v = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
            dp = jnp.where(keep, dp, 0.0) / (1.0 - dropout_rate)
        else:
            p_v = p
        # dV += P_drop^T @ dO
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dS = P * (dP_masked - delta)
        ds = p * (dp - delta_col)
        # dK += dS^T @ Q * scale
        dk_acc[:] = dk_acc[:] + sm_scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(i * block_q + (block_q - 1) >= j * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, do_ref, m_ref,
                   l_ref, dl_ref, dq_ref, dq_acc, *, sm_scale, causal,
                   block_q, block_k, dropout_rate, dropout_debug):
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        # input-dtype matmuls, f32 accumulation (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        m_col = m_ref[0].reshape(block_q, 1)
        l_col = l_ref[0].reshape(block_q, 1)
        delta_col = dl_ref[0].reshape(block_q, 1)
        p = _recompute_p(q, k, bias_ref, m_col, l_col, sm_scale, causal,
                         i, j, block_q, block_k)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            keep = _keep_mask(p.shape, dropout_rate, seed_ref, b, i, j,
                              block_q, block_k, dropout_debug)
            dp = jnp.where(keep, dp, 0.0) / (1.0 - dropout_rate)
        ds = p * (dp - delta_col)
        dq_acc[:] = dq_acc[:] + sm_scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, bias, seed, o, m, l, do, causal, sm_scale,
               block_q, block_k, interpret, dropout_rate, dropout_debug):
    bh, tq, d = q.shape
    _, tk, _ = k.shape
    nq, nk = tq // block_q, tk // block_k

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )[:, None, :]  # [bh, 1, tq], matching the saved m/l row layout
    bias3 = None if bias is None else bias.reshape(bias.shape[0], 1, tk)
    kw = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
              block_k=block_k, dropout_rate=dropout_rate,
              dropout_debug=dropout_debug)

    # --- dK/dV: grid (bh, kv-block, q-sweep) ---
    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # v
    ]
    dkv_args = [q, k, v]
    if bias is not None:
        nheads = bh // bias.shape[0]
        dkv_specs.append(
            pl.BlockSpec((1, 1, block_k),
                         lambda b, j, i: (b // nheads, 0, j))
        )
        dkv_args.append(bias3)
        dkv_kernel = functools.partial(_bwd_dkv_kernel, **kw)
    else:
        def dkv_kernel(qr, kr, vr, sr, dor, mr, lr, dlr, dkr, dvr, dka,
                       dva):
            return _bwd_dkv_kernel(
                qr, kr, vr, None, sr, dor, mr, lr, dlr, dkr, dvr, dka,
                dva, **kw)
    dkv_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))   # seed
    dkv_args.append(seed)
    dkv_specs += [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),     # do
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),     # m
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),     # l
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),     # delta
    ]
    dkv_args += [do, m, l, delta]

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)

    # --- dQ: grid (bh, q-block, kv-sweep) ---
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    dq_args = [q, k, v]
    if bias is not None:
        nheads = bh // bias.shape[0]
        dq_specs.append(
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j: (b // nheads, 0, j))
        )
        dq_args.append(bias3)
        dq_kernel = functools.partial(_bwd_dq_kernel, **kw)
    else:
        def dq_kernel(qr, kr, vr, sr, dor, mr, lr, dlr, dqr, dqa):
            return _bwd_dq_kernel(
                qr, kr, vr, None, sr, dor, mr, lr, dlr, dqr, dqa, **kw)
    dq_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))   # seed
    dq_args.append(seed)
    dq_specs += [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),     # do
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),     # m
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),     # l
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),     # delta
    ]
    dq_args += [do, m, l, delta]

    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# XLA fallback (also the numerical reference in tests)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, bias=None, causal=False, sm_scale=None,
                  dropout_rate=0.0, seed=None, debug=False):
    """Plain-XLA multi-head attention. q,k,v: [B,H,T,D]; bias: [B,Tk].
    With dropout: upscale-in-train on the probabilities; the mask comes
    from the debug position hash (bit-matching the kernel's debug mode)
    or jax.random (statistically matching the kernel's hardware PRNG)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # matmuls run in the INPUT dtype (bf16 under AMP → full-rate MXU;
    # upcasting the operands to f32 would quarter the matmul rate) with
    # f32 accumulation; softmax statistics stay f32 either way
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if bias is not None:
        s = s + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate and dropout_rate > 0.0:
        b, h, tq, tk = p.shape
        sd = jnp.reshape(jnp.asarray(0 if seed is None else seed,
                                     jnp.int32), (1,))
        if debug:
            keep = debug_keep_mask(b * h, tq, tk, dropout_rate,
                                   sd[0]).reshape(b, h, tq, tk)
        else:
            keep = jax.random.bernoulli(
                jax.random.PRNGKey(sd[0]), 1.0 - dropout_rate, p.shape)
        keep = jax.lax.stop_gradient(keep)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Public entry: custom_vjp'd flash attention
# ---------------------------------------------------------------------------

def _pick_blocks(tq, tk):
    """Block shapes: env caps win (manual override for on-chip sweeps,
    tools/bench_flash.py --blocks), else the autotune cache's measured
    winner for this (tq, tk) on this backend, else the hand-set 512
    defaults; divisibility/alignment still enforced here."""
    cap_q = cap_k = None
    env_q = os.environ.get("PADDLE_TPU_FLASH_BLOCK_Q", "").strip()
    env_k = os.environ.get("PADDLE_TPU_FLASH_BLOCK_K", "").strip()
    if env_q:
        cap_q = int(env_q)
    if env_k:
        cap_k = int(env_k)
    if cap_q is None or cap_k is None:
        try:
            from ...autotune import cached_params

            won = cached_params("flash_blocks",
                                {"block_q": 512, "block_k": 512},
                                tq=tq, tk=tk)
            cap_q = cap_q if cap_q is not None else int(won["block_q"])
            cap_k = cap_k if cap_k is not None else int(won["block_k"])
        except Exception:  # pragma: no cover - autotune unavailable
            cap_q = cap_q if cap_q is not None else 512
            cap_k = cap_k if cap_k is not None else 512
    bq = max(8, min(cap_q, tq))
    while tq % bq:
        bq //= 2
    bk = max(128, min(cap_k, tk))
    while tk % bk:
        bk //= 2
    return bq, bk


def flash_min_t():
    """The sequence length at which the blocked Pallas kernel starts
    beating XLA's fused unblocked attention.  r05 v5e sweep
    (hw_results/bench_flash_sweep.txt): XLA wins at T=128 (model-level
    +26%) and still edges the kernel at T=256 (attention-level 7-16%,
    both dropout regimes); the kernel wins at T=512 (+15% model-level,
    2.1x over XLA / 4.8x over the upstream jax kernel at T=2048) — so
    the boundary sits at 512.  Model builders (models/bert.py
    fuse_attn="auto") route by the same value.

    Resolution order: ``PADDLE_TPU_FLASH_MIN_T`` (manual override) →
    the autotune cache's recorded decision for this backend
    (``tools/decide_flash_min_t.py --write-cache``, or
    ``paddle_tpu.autotune.record_flash_min_t`` from an on-chip sweep)
    → the hand-set 512 default.  ``PADDLE_TPU_AUTOTUNE=0`` restores
    the pure env/default behavior bit-exactly."""
    env = os.environ.get("PADDLE_TPU_FLASH_MIN_T", "").strip()
    if env:
        return int(env)
    try:
        from ...autotune import flash_min_t_decision

        t = flash_min_t_decision()
        if t is not None:
            return int(t)
    except Exception:  # pragma: no cover - autotune unavailable
        pass
    return 512


def _kernel_applicable(q, k, bias):
    bh, tq, d = q.shape
    _, tk, _ = k.shape
    if d > 512:
        return False
    # Perf heuristic (measured on v5e): the blocked kernel wins once the
    # score matrix per head exceeds ~256x256 (2.0-2.4x at T=2048); at
    # T=128 XLA's fused unblocked attention is faster, so let it have it.
    # The boundary is env-tunable (PADDLE_TPU_FLASH_MIN_T) so on-chip
    # sweeps (tools/bench_flash.py) can re-decide it — with in-kernel
    # dropout the break-even may sit lower, since the XLA path then pays
    # a materialized [B,H,T,T] mask the kernel never writes.
    min_t = flash_min_t()
    if max(tq, tk) < min_t and \
            os.environ.get("PADDLE_TPU_PALLAS") != "interpret":
        return False
    bq, bk = _pick_blocks(tq, tk)
    if tq % bq or tk % bk or bq < 8 or bq % 8 or bk < 128 or bk % 128:
        return False
    if bias is not None and (bias.shape[0] == 0 or bh % bias.shape[0] != 0
                             or bias.shape[1] != tk):
        return False
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, bias, seed, causal, sm_scale, block_q, block_k,
           interpret, dropout_rate, dropout_debug):
    o, _, _ = _flash_fwd(q, k, v, bias, seed, causal, sm_scale, block_q,
                         block_k, interpret, dropout_rate, dropout_debug)
    return o


def _flash_fwd_rule(q, k, v, bias, seed, causal, sm_scale, block_q,
                    block_k, interpret, dropout_rate, dropout_debug):
    o, m, l = _flash_fwd(q, k, v, bias, seed, causal, sm_scale, block_q,
                         block_k, interpret, dropout_rate, dropout_debug)
    return o, (q, k, v, bias, seed, o, m, l)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret,
                    dropout_rate, dropout_debug, res, do):
    q, k, v, bias, seed, o, m, l = res
    dq, dk, dv = _flash_bwd(q, k, v, bias, seed, o, m, l, do, causal,
                            sm_scale, block_q, block_k, interpret,
                            dropout_rate, dropout_debug)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return (dq, dk, dv, dbias, None)  # int seed: no cotangent


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    dropout_rate=0.0, dropout_seed=None):
    """Multi-head attention: Pallas flash kernel on TPU, XLA elsewhere.

    q,k,v: [B, H, T, D]; bias: additive key bias [B, Tk] or [B,1,1,Tk]
    (no gradient flows to bias); returns [B, H, Tq, D].

    dropout_rate > 0 applies attention-probability dropout IN-KERNEL
    (upscale-in-train semantics); ``dropout_seed`` is an int32 scalar or
    [1] array that must change per step.  On the XLA fallback the same
    rate is applied with jax.random (debug hash under
    PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota, where both paths draw the
    identical mask for cross-checking).
    """
    if bias is not None:
        # constant on BOTH paths: the Pallas custom_vjp returns zero bias
        # cotangents, so the XLA fallback must not leak real ones either
        bias = jax.lax.stop_gradient(bias)
        if bias.ndim == 4:
            bias = bias.reshape(bias.shape[0], bias.shape[-1])
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    dropout_rate = float(dropout_rate or 0.0)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(
            "dropout_rate must be in [0, 1), got %r (rate 1 would "
            "upscale by 1/0)" % dropout_rate)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    use, interpret = _use_pallas()
    debug = _dropout_debug()
    b, h, tq, _ = q.shape
    tk = k.shape[2]
    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    seed = jnp.reshape(
        jnp.asarray(0 if dropout_seed is None else dropout_seed,
                    jnp.int32), (1,))
    if not (use and _kernel_applicable(qf, kf, bias)):
        return mha_reference(q, k, v, bias=bias, causal=causal,
                             sm_scale=sm_scale,
                             dropout_rate=dropout_rate, seed=seed,
                             debug=debug)
    if interpret and dropout_rate > 0.0 and not debug:
        # the pltpu hardware PRNG has no CPU/interpret lowering — without
        # the debug hash the kernel would die deep in Pallas with an
        # opaque 'prng_seed not found for platform cpu'
        raise ValueError(
            "in-kernel dropout cannot run under PADDLE_TPU_PALLAS="
            "interpret: the pltpu PRNG has no CPU lowering. Set "
            "PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota (deterministic debug "
            "hash, identical masks on kernel and XLA paths) or unset "
            "PADDLE_TPU_PALLAS to use the XLA fallback")
    bq, bk = _pick_blocks(tq, tk)
    o = _flash(qf, kf, vf, bias, seed, causal, sm_scale, bq, bk,
               interpret, dropout_rate, debug)
    return o.reshape(b, h, tq, d)
