"""Paged flash-decode Pallas kernel: single-query attention over a
block-table-indirect KV pool.

The paged cache is ``[num_blocks, H, block_len, Dh]`` — a request's K/V
rows live in the (non-contiguous) blocks its table names, so the ring
kernel's contiguous ``[BH, Tmax, D]`` streaming BlockSpec cannot see
them.  The indirection is the embedding kernel's scalar-prefetch row-DMA
idiom (``ops/pallas/embedding.py``): the flattened per-(sequence, head)
block table rides in as a scalar-prefetch argument, the grid is
``(rows, max_blocks)``, and the K/V BlockSpec index maps read
``table[row, j]`` to DMA exactly the j-th OWNED block HBM→VMEM — blocks
never transit as a dense gather, and Mosaic double-buffers the block
DMAs across grid steps because the whole table is known before the
kernel body runs.  Online softmax across the non-contiguous blocks is
the ring kernel's lanes-replicated m/l accumulation, with the same
``block_start < length`` skip (a request 40 tokens into a 16-block
table touches 3 blocks, not 16).

Factoring note (arXiv 2104.05755): the kernel is a schedule over the
same block-level primitive as the ring kernel — one
``(1, block_len, d)`` tile of scores + online-softmax accumulate — so
the autotune ``decode`` family covers both; the paged layout adds the
``block_len`` knob (``PADDLE_TPU_PAGED_BLOCK_LEN`` → measured winner →
hand-set default).

Like every kernel in the tree it ships with an XLA composite
(:func:`paged_decode_reference`) that is the CPU/GPU fallback AND the
numerical oracle: gather the table's blocks into the contiguous layout,
then defer to :func:`~paddle_tpu.ops.pallas.flash_decode.decode_reference`
(≤1e-5 documented tolerance, bit-identical masked-softmax math — the
paged-vs-ring greedy-token equivalence in bench rides on this).
"""

import functools
import math

import jax
import jax.numpy as jnp

from .flash_attention import NEG_INF, _HAS_PLTPU, pl, pltpu, _use_pallas
from .flash_decode import decode_min_t, decode_reference, _norm_lengths

__all__ = [
    "paged_flash_decode", "paged_decode_reference", "paged_block_len",
    "gather_paged_cache", "DEFAULT_BLOCK_LEN",
]

# hand-set default block length (cache rows per block).  16 keeps the
# pool granular enough that a 30-token generation wastes at most 15
# rows, while a (1, 16, d) f32 tile still fills TPU sublanes.
DEFAULT_BLOCK_LEN = 16


def paged_block_len(d, max_len=None):
    """Pool block length: env cap (``PADDLE_TPU_PAGED_BLOCK_LEN``) →
    the autotune ``decode`` family's measured ``block_len`` for this
    head_dim on this backend → the hand-set default; forced to divide
    ``max_len`` (when given) so a full table gathers to exactly the
    ring cache's depth — the shape identity the bit-exact paged-vs-ring
    A/B rides on."""
    try:
        from ...autotune import cached_block_cap

        cap = cached_block_cap("decode", "PADDLE_TPU_PAGED_BLOCK_LEN",
                               "block_len", DEFAULT_BLOCK_LEN, d=d)
    except Exception:  # pragma: no cover - autotune unavailable
        cap = DEFAULT_BLOCK_LEN
    bl = max(1, int(cap))
    if max_len:
        bl = min(bl, int(max_len))
        while int(max_len) % bl:
            bl //= 2
    return max(bl, 1)


def gather_paged_cache(cache, table):
    """Materialize table-owned blocks contiguously:
    cache ``[N, H, BL, D]`` + table ``[S, MB]`` → ``[S, H, MB*BL, D]``.
    Unmapped (``-1``) entries clamp to block 0 — their columns sit past
    every request's valid length, so the attention mask never reads
    them (and the zero-fill init keeps them finite)."""
    n, h, bl, d = cache.shape
    s, mb = table.shape
    safe = jnp.clip(jnp.asarray(table, jnp.int32), 0, n - 1)
    g = cache[safe]                              # [S, MB, H, BL, D]
    g = jnp.transpose(g, (0, 2, 1, 3, 4))        # [S, H, MB, BL, D]
    return g.reshape(s, h, mb * bl, d)


def paged_decode_reference(q, k_cache, v_cache, lengths, table,
                           sm_scale=None):
    """XLA composite (fallback + oracle): gather the owned blocks into
    the ring layout, then the exact ring-oracle masked softmax.  With a
    full-depth table (``MB*BL == Tmax``) this is the SAME einsum shape
    and mask as the ring path — bit-identical greedy tokens."""
    table = jnp.asarray(table, jnp.int32)
    if table.ndim == 1:
        table = table[None, :]
    k = gather_paged_cache(k_cache, table)
    v = gather_paged_cache(v_cache, table)
    return decode_reference(q, k, v, lengths, sm_scale=sm_scale)


def _paged_decode_kernel(len_ref, tab_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, sm_scale, block_len):
    r = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[r]

    # block j covers cache positions [j*BL, (j+1)*BL) of THIS row's
    # logical sequence — whichever pool block the table routed it to
    @pl.when(j * block_len < length)
    def _compute():
        q = q_ref[0]  # [1, d]
        k = k_ref[0]  # [bl, d]
        v = v_ref[0]  # [bl, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [1, bl] f32
        cols = j * block_len + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_len), 1
        )
        s = jnp.where(cols < length, s, NEG_INF)

        m_prev = jnp.max(m_ref[:], axis=1, keepdims=True)
        l_prev = jnp.max(l_ref[:], axis=1, keepdims=True)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nb - 1)
    def _finalize():
        l = jnp.max(l_ref[:], axis=1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _paged_flash_decode_call(q, k, v, lengths, table, sm_scale,
                             block_len, interpret):
    """q [R, 1, D]; k/v [N*H flattened blocks, BL, D]; table [R, MB]
    (already head-flattened); lengths [R]."""
    rows, _, d = q.shape
    mb = table.shape[1]
    n = k.shape[0]
    kernel = functools.partial(_paged_decode_kernel, sm_scale=sm_scale,
                               block_len=block_len)
    # unmapped (-1) table entries: route the DMA at block 0 — the
    # compute guard (block start >= length) never reads it
    safe_tab = jnp.clip(jnp.asarray(table, jnp.int32), 0, n - 1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(rows, mb),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda r, j, lens, tab: (r, 0, 0)),
            pl.BlockSpec((1, block_len, d),
                         lambda r, j, lens, tab: (tab[r, j], 0, 0)),
            pl.BlockSpec((1, block_len, d),
                         lambda r, j, lens, tab: (tab[r, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d),
                               lambda r, j, lens, tab: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, 1, d), q.dtype),
        interpret=interpret,
    )(lengths, safe_tab, q, k, v)


def paged_flash_decode(q, k_cache, v_cache, lengths, table,
                       sm_scale=None):
    """Single-step decode attention through a block table.

    q ``[S, H, D]``; caches ``[N, H, BL, D]`` (the shared pool); table
    ``[S, MB]`` int32 (``-1`` = unmapped); lengths scalar or ``[S]``
    (valid cache rows per sequence).  Pallas kernel on TPU when the
    table depth ``MB*BL`` is at/above the ``decode`` family's measured
    engagement threshold; gather + ring-oracle composite otherwise.
    """
    s, h, d = q.shape
    n, _, bl, _ = k_cache.shape
    table = jnp.asarray(table, jnp.int32)
    if table.ndim == 1:
        table = table[None, :]
    mb = table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    use, interpret = _use_pallas()
    if not use or mb * bl < decode_min_t() or bl < 1:
        return paged_decode_reference(q, k_cache, v_cache, lengths,
                                      table, sm_scale=sm_scale)
    lens = _norm_lengths(lengths, s)
    lens_rh = jnp.repeat(lens, h)  # [S*H], row-major like the reshape
    # flatten heads into the block axis: pool block n, head hh lives at
    # flat row n*H + hh, so each (sequence, head) row gets its own table
    flat_tab = (table[:, None, :] * h
                + jnp.arange(h, dtype=jnp.int32)[None, :, None])
    flat_tab = jnp.where(table[:, None, :] < 0, -1,
                         flat_tab).reshape(s * h, mb)
    o = _paged_flash_decode_call(
        q.reshape(s * h, 1, d),
        k_cache.reshape(n * h, bl, d),
        v_cache.reshape(n * h, bl, d),
        lens_rh, flat_tab, float(sm_scale), bl, interpret,
    )
    return o.reshape(s, h, d)
