"""Flash-*decode* Pallas kernel: one query row per sequence against the
device-resident KV cache, masked to the write cursor.

The autoregressive inner loop's attention shape is degenerate — q is
``[BH, 1, D]`` while K/V are the full ``[BH, Tmax, D]`` cache — so the
prefill flash kernel's q-blocking buys nothing; what matters is streaming
the cache through VMEM in ``block_k`` chunks with online softmax and
skipping the chunks past the cursor entirely (a request 40 tokens into a
4096-slot cache touches one block, not 32).  Reference shape analysis:
"Tensor Processing Primitives" (arXiv 2104.05755) — the single-pass
shape-stable primitive — applied to the flash-decoding decomposition.

Like ``flash_attention.py`` the kernel ships with an XLA composite
(:func:`decode_reference`) that is both the CPU/GPU fallback and the
numerical oracle (documented tolerance: ≤1e-5 relative); the Pallas path
engages on TPU (or under ``PADDLE_TPU_PALLAS=interpret`` for CPU tests).

Autotune: block size and the engagement threshold are a new ``decode``
family in the PR-6 measure-and-learn cache — ``PADDLE_TPU_DECODE_BLOCK_K``
/ ``PADDLE_TPU_DECODE_MIN_T`` env caps win, then the cache's measured
winner, then the hand-set defaults (512 / 256).  ``PADDLE_TPU_AUTOTUNE=0``
restores the hand-set defaults bit-exactly.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp

from .flash_attention import (NEG_INF, _HAS_PALLAS, _HAS_PLTPU, pl, pltpu,
                              pallas_supported, _use_pallas)

__all__ = [
    "flash_decode", "decode_reference", "pallas_supported",
    "decode_block_k", "decode_min_t",
]

# hand-set defaults: the pre-autotune behavior PADDLE_TPU_AUTOTUNE=0
# must restore bit-exactly
DEFAULT_BLOCK_K = 512
DEFAULT_MIN_T = 256


def decode_block_k(t, d):
    """KV block size: env cap (``PADDLE_TPU_DECODE_BLOCK_K``) → autotune
    cache winner for this (t, d) on this backend (``decode`` family) →
    hand-set 512; divisibility against the cache length enforced here."""
    try:
        from ...autotune import cached_block_cap

        cap = cached_block_cap("decode", "PADDLE_TPU_DECODE_BLOCK_K",
                               "block_k", DEFAULT_BLOCK_K, t=t, d=d)
    except Exception:  # pragma: no cover - autotune unavailable
        cap = DEFAULT_BLOCK_K
    bk = max(128, min(int(cap), t))
    while t % bk:
        bk //= 2
    return max(bk, 1)


def decode_min_t():
    """Cache length below which the XLA composite beats the blocked
    kernel (launch overhead dominates a one-block cache).  Resolution:
    ``PADDLE_TPU_DECODE_MIN_T`` → the autotune cache's recorded decision
    for this backend (``decode_min_t`` family, written by the bench
    sweep) → the hand-set 256."""
    env = os.environ.get("PADDLE_TPU_DECODE_MIN_T", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            return DEFAULT_MIN_T
    try:
        from ...autotune import decode_min_t_decision

        t = decode_min_t_decision()
        if t is not None:
            return int(t)
    except Exception:  # pragma: no cover - autotune unavailable
        pass
    return DEFAULT_MIN_T


def _norm_lengths(lengths, b):
    """Per-sequence valid-entry counts as an int32 [B] vector (a scalar
    cursor broadcasts: every row shares the write position)."""
    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.broadcast_to(lengths, (b,))
    return lengths.reshape(b)


def decode_reference(q, k, v, lengths, sm_scale=None):
    """XLA composite single-query attention (fallback + oracle).

    q [B, H, D]; k/v [B, H, T, D] (ring cache, positions >= length are
    garbage); lengths scalar or [B].  Returns [B, H, D].  f32 softmax
    with input-dtype matmuls, matching the kernel's accumulation."""
    b, h, d = q.shape
    t = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    lengths = _norm_lengths(lengths, b)
    s = jnp.einsum("bhd,bhtd->bht", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.arange(t, dtype=jnp.int32)[None, None, :] < \
        lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l = jnp.where(l == 0.0, 1.0, l)  # empty cache → zeros, not NaN
    p = (p / l).astype(v.dtype)
    return jnp.einsum("bht,bhtd->bhd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, sm_scale, block_k):
    bh = pl.program_id(0)
    j = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[bh]

    @pl.when(j * block_k < length)
    def _compute():
        q = q_ref[0]  # [1, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [1, bk] f32
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        s = jnp.where(cols < length, s, NEG_INF)

        # lanes of m_ref/l_ref all hold the same value (flash_attention's
        # lanes-replicated per-row stats, degenerate single-row case)
        m_prev = jnp.max(m_ref[:], axis=1, keepdims=True)
        l_prev = jnp.max(l_ref[:], axis=1, keepdims=True)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.max(l_ref[:], axis=1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_decode_call(q, k, v, lengths, sm_scale, block_k, interpret):
    bh, _, d = q.shape
    t = k.shape[1]
    grid = (bh, t // block_k)
    kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                               block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
        ],
        interpret=interpret,
    )(lengths, q, k, v)


def _kernel_applicable(t, d, block_k):
    return t >= 1 and d >= 1 and t % block_k == 0


def flash_decode(q, k, v, lengths, sm_scale=None):
    """Single-step decode attention with automatic path selection.

    q [B, H, D] (this step's query), k/v [B, H, Tmax, D] (the ring
    cache), lengths scalar or [B] (the cursor — number of valid cache
    entries).  Pallas kernel on TPU when Tmax is at/above the measured
    :func:`decode_min_t` engagement threshold, XLA composite otherwise.
    """
    b, h, d = q.shape
    t = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    use, interpret = _use_pallas()
    block_k = decode_block_k(t, d)
    if (not use or t < decode_min_t()
            or not _kernel_applicable(t, d, block_k)):
        return decode_reference(q, k, v, lengths, sm_scale=sm_scale)
    lens = _norm_lengths(lengths, b)
    lens_bh = jnp.repeat(lens, h)  # [B*H], row-major like the reshape
    o = _flash_decode_call(
        q.reshape(b * h, 1, d),
        k.reshape(b * h, t, d),
        v.reshape(b * h, t, d),
        lens_bh, float(sm_scale), block_k, interpret,
    )
    return o.reshape(b, h, d)
