"""In-graph metric ops with stateful accumulators.

Reference: ``paddle/fluid/operators/metrics/`` — ``auc_op.h`` (bucketed
TPR/FPR histogram + trapezoid area, sliding-window or global accumulation),
``precision_recall_op.h`` (per-class TP/FP/TN/FN states → macro/micro
metrics).  ``accuracy_op`` lives in ops/basic.py.

TPU-native notes: the reference mutates persistable state vars in place;
here state flows through the op functionally (StatPos in → StatPosOut out,
wired to the same variable by the layer), which the executor writes back to
the scope — same net effect, jit-compatible.  Histogramming uses
``segment_sum`` instead of a scalar loop so it vectorizes on device.
"""

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("auc", inputs=["Predict", "Label", "StatPos", "StatNeg"],
             outputs=["AUC", "StatPosOut", "StatNegOut"], no_grad=True)
def auc(ctx, attrs, Predict, Label, StatPos, StatNeg):
    """Streaming AUC (auc_op.h:27).

    Predict [N, 2] probabilities (column 1 used), Label [N, 1] {0,1}.
    StatPos/StatNeg: [1, T+1] bucket counts when slide_steps == 0 (global
    accumulation), else [slide_steps, T+1] ring buffer of per-step counts.
    """
    num_thresholds = int(attrs.get("num_thresholds", (2 ** 12) - 1))
    slide_steps = int(attrs.get("slide_steps", 1))
    B = num_thresholds + 1

    pred = Predict[:, 1] if Predict.shape[1] > 1 else Predict[:, 0]
    lab = Label.reshape(-1).astype(bool)
    idx = jnp.clip(
        (pred * num_thresholds).astype(jnp.int32), 0, num_thresholds)
    w_pos = lab.astype(StatPos.dtype)
    hist_pos = jax.ops.segment_sum(w_pos, idx, num_segments=B)
    hist_neg = jax.ops.segment_sum(1 - w_pos, idx, num_segments=B)

    if slide_steps == 0:
        pos_out = StatPos + hist_pos[None, :].astype(StatPos.dtype)
        neg_out = StatNeg + hist_neg[None, :].astype(StatNeg.dtype)
        stat_pos, stat_neg = pos_out[0], neg_out[0]
    else:
        # shift window up one step, append the current histogram
        pos_out = jnp.concatenate(
            [StatPos[1:], hist_pos[None, :].astype(StatPos.dtype)], axis=0)
        neg_out = jnp.concatenate(
            [StatNeg[1:], hist_neg[None, :].astype(StatNeg.dtype)], axis=0)
        stat_pos = jnp.sum(pos_out, axis=0)
        stat_neg = jnp.sum(neg_out, axis=0)

    # trapezoid area over buckets scanned from the highest threshold down
    # (auc_op.h calcAuc): cumulative TP/FP counts trace the ROC curve
    pos_rev = stat_pos[::-1].astype(jnp.float32)
    neg_rev = stat_neg[::-1].astype(jnp.float32)
    tot_pos = jnp.cumsum(pos_rev)
    tot_neg = jnp.cumsum(neg_rev)
    tot_pos_prev = tot_pos - pos_rev
    tot_neg_prev = tot_neg - neg_rev
    area = jnp.sum(
        jnp.abs(tot_neg - tot_neg_prev) * (tot_pos + tot_pos_prev) / 2.0)
    denom = tot_pos[-1] * tot_neg[-1]
    auc_val = jnp.where(denom > 0, area / jnp.maximum(denom, 1.0), 0.0)
    return auc_val.reshape(1), pos_out, neg_out


def _calc_precision(tp, fp):
    has = (tp > 0) | (fp > 0)
    return jnp.where(has, tp / jnp.maximum(tp + fp, 1e-38), 1.0)


def _calc_recall(tp, fn):
    has = (tp > 0) | (fn > 0)
    return jnp.where(has, tp / jnp.maximum(tp + fn, 1e-38), 1.0)


def _calc_f1(p, r):
    has = (p > 0) | (r > 0)
    return jnp.where(has, 2 * p * r / jnp.maximum(p + r, 1e-38), 0.0)


def _metrics_from_states(states):
    """states [C, 4] (TP, FP, TN, FN) → [6] macro/micro P/R/F1
    (precision_recall_op.h ComputeMetrics)."""
    tp, fp, fn = states[:, 0], states[:, 1], states[:, 3]
    macro_p = jnp.mean(_calc_precision(tp, fp))
    macro_r = jnp.mean(_calc_recall(tp, fn))
    macro_f1 = _calc_f1(macro_p, macro_r)
    ttp, tfp, tfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    micro_p = _calc_precision(ttp, tfp)
    micro_r = _calc_recall(ttp, tfn)
    micro_f1 = _calc_f1(micro_p, micro_r)
    return jnp.stack([macro_p, macro_r, macro_f1, micro_p, micro_r, micro_f1])


@register_op(
    "precision_recall",
    inputs=["MaxProbs", "Indices", "Labels", "Weights", "StatesInfo"],
    outputs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
    no_grad=True)
def precision_recall(ctx, attrs, MaxProbs, Indices, Labels, Weights,
                     StatesInfo):
    """Multi-class streaming precision/recall (precision_recall_op.h:30).

    Indices/Labels [N, 1] int; Weights optional [N, 1]; StatesInfo optional
    [C, 4] running (TP, FP, TN, FN).  Metrics layout: [macro_p, macro_r,
    macro_f1, micro_p, micro_r, micro_f1].
    """
    C = int(attrs["class_number"])
    ids = Indices.reshape(-1).astype(jnp.int32)
    labels = Labels.reshape(-1).astype(jnp.int32)
    w = (Weights.reshape(-1).astype(jnp.float32)
         if Weights is not None else jnp.ones(ids.shape, jnp.float32))

    correct = ids == labels
    onehot_id = jax.nn.one_hot(ids, C, dtype=jnp.float32)      # [N, C]
    onehot_lab = jax.nn.one_hot(labels, C, dtype=jnp.float32)

    tp = jnp.sum(jnp.where(correct, w, 0.0)[:, None] * onehot_id, axis=0)
    fp = jnp.sum(jnp.where(~correct, w, 0.0)[:, None] * onehot_id, axis=0)
    fn = jnp.sum(jnp.where(~correct, w, 0.0)[:, None] * onehot_lab, axis=0)
    # TN per class: every sample adds w to all classes except the predicted
    # one, and (when wrong) except the labeled one (precision_recall_op.h:69)
    total_w = jnp.sum(w)
    tn = total_w - tp - fp - fn

    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)  # [C, 4]
    batch_metrics = _metrics_from_states(batch_states)
    accum_states = (
        batch_states + StatesInfo.astype(jnp.float32)
        if StatesInfo is not None else batch_states
    )
    accum_metrics = _metrics_from_states(accum_states)
    return batch_metrics, accum_metrics, accum_states


@register_op(
    "positive_negative_pair",
    inputs=["Score", "Label", "QueryID", "AccumulatePositivePair",
            "AccumulateNegativePair", "AccumulateNeutralPair", "Weight"],
    outputs=["PositivePair", "NegativePair", "NeutralPair"], no_grad=True)
def positive_negative_pair(ctx, attrs, Score, Label, QueryID,
                           AccumulatePositivePair=None,
                           AccumulateNegativePair=None,
                           AccumulateNeutralPair=None, Weight=None):
    """Ranking pair statistics (reference
    ``positive_negative_pair_op.cc``): within each query, for every doc
    pair with differing labels, count the pair as positive when score
    order matches label order, negative when inverted, neutral on score
    ties; pair weight is the mean of the two doc weights.

    The reference buckets docs per query in a hash map and loops pairs;
    TPU-native this is one dense B x B pair matrix (same-query upper
    triangle) reduced on device — O(B^2) elementwise, no host loop."""
    col = int(attrs.get("column", 0))  # reference SetDefault(0)
    s = Score[:, col].astype(jnp.float32)
    lab = jnp.reshape(Label, (-1,)).astype(jnp.float32)
    q = jnp.reshape(QueryID, (-1,))
    B = s.shape[0]
    w = (jnp.reshape(Weight, (-1,)).astype(jnp.float32)
         if Weight is not None else jnp.ones((B,), jnp.float32))
    same_q = q[:, None] == q[None, :]
    upper = jnp.arange(B)[:, None] < jnp.arange(B)[None, :]
    differ = lab[:, None] != lab[None, :]
    pair = same_q & upper & differ
    pw = (w[:, None] + w[None, :]) * 0.5
    ds = s[:, None] - s[None, :]
    dl = lab[:, None] - lab[None, :]
    tie = ds == 0.0
    # reference kernel quirk kept for parity: a score-tied pair counts in
    # NeutralPair AND falls through the ternary into NegativePair
    # (positive_negative_pair_op.h has no `continue` after neu += w)
    pos = jnp.sum(jnp.where(pair & (ds * dl > 0), pw, 0.0))
    neg = jnp.sum(jnp.where(pair & ~(ds * dl > 0), pw, 0.0))
    neu = jnp.sum(jnp.where(pair & tie, pw, 0.0))
    if AccumulatePositivePair is not None:
        pos = pos + jnp.reshape(AccumulatePositivePair, ())
    if AccumulateNegativePair is not None:
        neg = neg + jnp.reshape(AccumulateNegativePair, ())
    if AccumulateNeutralPair is not None:
        neu = neu + jnp.reshape(AccumulateNeutralPair, ())
    one = lambda v: jnp.reshape(v, (1,))
    return {"PositivePair": one(pos), "NegativePair": one(neg),
            "NeutralPair": one(neu)}
