"""Recurrent ops: LSTM/GRU as fused lax.scan kernels.

Reference: ``paddle/fluid/operators/lstm_op.cc`` / ``gru_op.cc`` (LoD-batched
CPU/GPU kernels via ``math/detail/lstm_kernel.h``) and the fused variants
(``fused/fusion_lstm_op.cc``).

TPU-native representation: padded dense batches [B, T, ...] with an optional
``SeqLen`` [B] companion instead of LoD offsets (SURVEY.md §5: LoD becomes
padding+masking under XLA static shapes).  The whole recurrence is ONE
lax.scan — XLA pipelines the per-step gate matmuls onto the MXU; masked
steps carry the previous state through, reproducing ragged-batch semantics.
"""

import jax
import jax.numpy as jnp

from .registry import register_op


def _mask_time(SeqLen, B, T):
    """[T, B] bool validity mask."""
    if SeqLen is None:
        return None
    return jnp.arange(T)[:, None] < jnp.reshape(SeqLen, (B,))[None, :]


_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


@register_op(
    "lstm",
    inputs=["Input", "H0", "C0", "Weight", "Bias", "SeqLen"],
    outputs=["Hidden", "Cell"],
)
def lstm(ctx, attrs, Input, H0, C0, Weight, Bias, SeqLen):
    """Input [B,T,4D] (pre-projected x·Wx, as in the reference where the fc
    is applied outside), Weight [D,4D] recurrent weights, Bias [1,4D], or
    [1,7D] with ``use_peepholes`` — the reference's *default* cell
    (layers/nn.py:427, kernel math/detail/lstm_kernel.h): the trailing 3D
    are [W_ic, W_fc, W_oc]; c_prev feeds the i/f gates and the fresh cell
    feeds the o gate, all pre-activation.  Gate order i,f,c,o."""
    B, T, four_d = jnp.shape(Input)
    d = four_d // 4
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    is_reverse = attrs.get("is_reverse", False)
    use_peepholes = bool(attrs.get("use_peepholes", False))
    w_ic = w_fc = w_oc = None
    if use_peepholes:
        if Bias is None or Bias.size < 7 * d:
            raise ValueError(
                "lstm with use_peepholes=True needs a [1, 7*hidden] Bias "
                "([b_i b_f b_c b_o, W_ic, W_fc, W_oc]); got %r"
                % (None if Bias is None else Bias.shape,))
        flat = jnp.reshape(Bias, (-1,))
        w_ic = flat[4 * d:5 * d][None, :]
        w_fc = flat[5 * d:6 * d][None, :]
        w_oc = flat[6 * d:7 * d][None, :]

    h0 = H0 if H0 is not None else jnp.zeros((B, d), Input.dtype)
    c0 = C0 if C0 is not None else jnp.zeros((B, d), Input.dtype)
    x = jnp.moveaxis(Input, 1, 0)  # [T,B,4D]
    if is_reverse:
        x = jnp.flip(x, 0)
    mask = _mask_time(SeqLen, B, T)
    if mask is not None and is_reverse:
        mask = jnp.flip(mask, 0)

    def step(carry, inp):
        h, c = carry
        if mask is not None:
            xt, mt = inp
        else:
            xt, mt = inp, None
        gates = xt + jnp.matmul(h, Weight)
        if Bias is not None:
            gates = gates + jnp.reshape(Bias, (1, -1))[:, : 4 * d]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = gate_act(i), gate_act(f)
        g = cand_act(g)
        c_new = f * c + i * g
        if use_peepholes:
            o = o + c_new * w_oc
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        if mt is not None:
            keep = mt[:, None]
            h_new = jnp.where(keep, h_new, h)
            c_new = jnp.where(keep, c_new, c)
        return (h_new, c_new), (h_new, c_new)

    xs = (x, mask) if mask is not None else x
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), xs)
    if is_reverse:
        hs, cs = jnp.flip(hs, 0), jnp.flip(cs, 0)
    return {
        "Hidden": jnp.moveaxis(hs, 0, 1),
        "Cell": jnp.moveaxis(cs, 0, 1),
    }


@register_op(
    "dynamic_lstm",
    inputs=["Input", "H0", "C0", "Weight", "Bias", "SeqLen"],
    outputs=["Hidden", "Cell"],
)
def dynamic_lstm(ctx, attrs, Input, H0, C0, Weight, Bias, SeqLen):
    return lstm(ctx, attrs, Input, H0, C0, Weight, Bias, SeqLen)


@register_op(
    "gru",
    inputs=["Input", "H0", "Weight", "Bias", "SeqLen"],
    outputs=["Hidden"],
)
def gru(ctx, attrs, Input, H0, Weight, Bias, SeqLen):
    """Input [B,T,3D] pre-projected; Weight [D,3D]: first 2D for
    update/reset gates, last D for candidate (reference gru_op.cc layout)."""
    B, T, three_d = jnp.shape(Input)
    d = three_d // 3
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    is_reverse = attrs.get("is_reverse", False)

    h0 = H0 if H0 is not None else jnp.zeros((B, d), Input.dtype)
    x = jnp.moveaxis(Input, 1, 0)
    if is_reverse:
        x = jnp.flip(x, 0)
    mask = _mask_time(SeqLen, B, T)
    if mask is not None and is_reverse:
        mask = jnp.flip(mask, 0)
    w_gate = Weight[:, : 2 * d]   # [D, 2D]
    w_cand = Weight[:, 2 * d:]    # [D, D]

    def step(carry, inp):
        h = carry
        if mask is not None:
            xt, mt = inp
        else:
            xt, mt = inp, None
        if Bias is not None:
            xt = xt + jnp.reshape(Bias, (1, -1))
        xu, xr, xc = xt[:, :d], xt[:, d:2 * d], xt[:, 2 * d:]
        g = jnp.concatenate([xu, xr], axis=-1) + jnp.matmul(h, w_gate)
        u, r = jnp.split(gate_act(g), 2, axis=-1)
        c = cand_act(xc + jnp.matmul(r * h, w_cand))
        h_new = u * h + (1.0 - u) * c
        if mt is not None:
            h_new = jnp.where(mt[:, None], h_new, h)
        return h_new, h_new

    xs = (x, mask) if mask is not None else x
    _, hs = jax.lax.scan(step, h0, xs)
    if is_reverse:
        hs = jnp.flip(hs, 0)
    return {"Hidden": jnp.moveaxis(hs, 0, 1)}


@register_op(
    "dynamic_gru",
    inputs=["Input", "H0", "Weight", "Bias", "SeqLen"],
    outputs=["Hidden"],
)
def dynamic_gru(ctx, attrs, Input, H0, Weight, Bias, SeqLen):
    return gru(ctx, attrs, Input, H0, Weight, Bias, SeqLen)


@register_op("lstm_unit", inputs=["X", "C_prev"], outputs=["C", "H"])
def lstm_unit(ctx, attrs, X, C_prev):
    """One LSTM cell step on pre-projected gates (lstm_unit_op.h):
    X [B, 4D] in (i, f, o, g) order; c = sigm(f+fb)*c_prev + sigm(i)*tanh(g);
    h = sigm(o)*tanh(c)."""
    fb = float(attrs.get("forget_bias", 0.0))
    d = C_prev.shape[-1]
    i = jax.nn.sigmoid(X[:, :d])
    f = jax.nn.sigmoid(X[:, d:2 * d] + fb)
    o = jax.nn.sigmoid(X[:, 2 * d:3 * d])
    g = jnp.tanh(X[:, 3 * d:])
    c = f * C_prev + i * g
    return {"C": c, "H": o * jnp.tanh(c)}


@register_op("gru_unit", inputs=["Input", "HiddenPrev", "Weight", "Bias"],
             outputs=["Gate", "ResetHiddenPrev", "Hidden"],
             stateful_outputs=("Gate", "ResetHiddenPrev"))
def gru_unit(ctx, attrs, Input, HiddenPrev, Weight, Bias):
    """One GRU cell step (gru_unit_op.h): Input [B,3D] pre-projected;
    Weight [D, 3D] (first 2D update+reset, last D candidate);
    h = u*c + (1-u)*h_prev (origin_mode flips the mix)."""
    d = HiddenPrev.shape[-1]
    g = Input if Bias is None else Input + Bias.reshape(1, -1)
    gate_act = _ACT[{1: "sigmoid", 2: "tanh", 3: "relu", 0: "identity"}.get(
        attrs.get("gate_activation", 1), "sigmoid")] \
        if isinstance(attrs.get("gate_activation", 1), int) \
        else _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[{1: "sigmoid", 2: "tanh", 3: "relu", 0: "identity"}.get(
        attrs.get("activation", 2), "tanh")] \
        if isinstance(attrs.get("activation", 2), int) \
        else _ACT[attrs.get("activation", "tanh")]
    ur = g[:, :2 * d] + jnp.matmul(HiddenPrev, Weight[:, :2 * d])
    ur = gate_act(ur)
    u, r = ur[:, :d], ur[:, d:]
    rhp = r * HiddenPrev
    c = cand_act(g[:, 2 * d:] + jnp.matmul(rhp, Weight[:, 2 * d:]))
    if attrs.get("origin_mode", False):
        h = c + u * (HiddenPrev - c)
    else:
        h = u * c + (1.0 - u) * HiddenPrev
    gate_out = jnp.concatenate([u, r, c], axis=1)
    return {"Gate": gate_out, "ResetHiddenPrev": rhp, "Hidden": h}


@register_op(
    "dynamic_lstmp",
    inputs=["Input", "H0", "C0", "Weight", "ProjWeight", "Bias", "SeqLen"],
    outputs=["Projection", "Cell"],
)
def dynamic_lstmp(ctx, attrs, Input, H0, C0, Weight, ProjWeight, Bias,
                  SeqLen):
    """LSTM with projection (lstmp_op.h): recurrent input is the
    projection r = act(h @ ProjWeight) [B,P]; Weight [P, 4D];
    Input [B,T,4D] pre-projected gates; padded + SeqLen mask.
    ``use_peepholes`` (reference default) takes a [1,7D] Bias whose
    trailing 3D are [W_ic, W_fc, W_oc], applied as in lstm_kernel.h."""
    B, T, four_d = jnp.shape(Input)
    d = four_d // 4
    p = ProjWeight.shape[1]
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    proj_act = _ACT[attrs.get("proj_activation", "identity")]
    is_reverse = attrs.get("is_reverse", False)
    use_peepholes = bool(attrs.get("use_peepholes", False))
    w_ic = w_fc = w_oc = None
    if use_peepholes:
        if Bias is None or Bias.size < 7 * d:
            raise ValueError(
                "dynamic_lstmp with use_peepholes=True needs a "
                "[1, 7*hidden] Bias; got %r"
                % (None if Bias is None else Bias.shape,))
        flat = jnp.reshape(Bias, (-1,))
        w_ic = flat[4 * d:5 * d][None, :]
        w_fc = flat[5 * d:6 * d][None, :]
        w_oc = flat[6 * d:7 * d][None, :]

    r0 = H0 if H0 is not None else jnp.zeros((B, p), Input.dtype)
    c0 = C0 if C0 is not None else jnp.zeros((B, d), Input.dtype)
    x = jnp.moveaxis(Input, 1, 0)
    if is_reverse:
        x = jnp.flip(x, 0)
    mask = _mask_time(SeqLen, B, T)
    if mask is not None and is_reverse:
        mask = jnp.flip(mask, 0)

    def step(carry, inp):
        r, c = carry
        if mask is not None:
            xt, mt = inp
        else:
            xt, mt = inp, None
        gates = xt + jnp.matmul(r, Weight)
        if Bias is not None:
            gates = gates + jnp.reshape(Bias, (1, -1))[:, : 4 * d]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = gate_act(i), gate_act(f)
        g = cand_act(g)
        c_new = f * c + i * g
        if use_peepholes:
            o = o + c_new * w_oc
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        r_new = proj_act(jnp.matmul(h_new, ProjWeight))
        if mt is not None:
            keep = mt[:, None]
            r_new = jnp.where(keep, r_new, r)
            c_new = jnp.where(keep, c_new, c)
        return (r_new, c_new), (r_new, c_new)

    xs = (x, mask) if mask is not None else x
    _, (rs, cs) = jax.lax.scan(step, (r0, c0), xs)
    if is_reverse:
        rs, cs = jnp.flip(rs, 0), jnp.flip(cs, 0)
    return {"Projection": jnp.moveaxis(rs, 0, 1),
            "Cell": jnp.moveaxis(cs, 0, 1)}


@register_op(
    "fusion_lstm",
    inputs=["X", "WeightX", "WeightH", "Bias", "H0", "C0", "SeqLen"],
    outputs=["Hidden", "Cell"],
)
def fusion_lstm(ctx, attrs, X, WeightX, WeightH, Bias, H0, C0, SeqLen):
    """Fused x-projection + LSTM (fused/fusion_lstm_op.cc).  On TPU the
    'fusion' is XLA's job — this lowers to one [B*T,D]x[D,4D] matmul plus
    the same scan as the lstm op."""
    gates = jnp.matmul(X, WeightX)
    return lstm(ctx, dict(attrs), gates, H0, C0, WeightH, Bias, SeqLen)


@register_op(
    "fusion_gru",
    inputs=["X", "WeightX", "WeightH", "Bias", "H0", "SeqLen"],
    outputs=["Hidden"],
)
def fusion_gru(ctx, attrs, X, WeightX, WeightH, Bias, H0, SeqLen):
    """Fused x-projection + GRU (fused/fusion_gru_op.cc)."""
    gates = jnp.matmul(X, WeightX)
    return gru(ctx, dict(attrs), gates, H0, WeightH, Bias, SeqLen)


@register_op(
    "fused_embedding_fc_lstm",
    inputs=["Ids", "Embeddings", "WeightH", "Bias", "H0", "C0", "SeqLen"],
    outputs=["Hidden", "Cell"],
)
def fused_embedding_fc_lstm(ctx, attrs, Ids, Embeddings, WeightH, Bias,
                            H0, C0, SeqLen):
    """fused/fused_embedding_fc_lstm_op.cc: embedding lookup (the table
    already contains W_x-projected gate rows) + LSTM.  Embeddings:
    [V, 4D] pre-projected rows; Ids [B, T]."""
    ids = Ids
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    gates = jnp.take(Embeddings, jnp.maximum(ids.astype(jnp.int32), 0),
                     axis=0)  # [B, T, 4D]
    return lstm(ctx, dict(attrs), gates, H0, C0, WeightH, Bias, SeqLen)


@register_op(
    "attention_lstm",
    inputs=["X", "C0", "H0", "AttentionWeight", "AttentionBias",
            "AttentionScalar", "AttentionScalarBias", "LSTMWeight",
            "LSTMBias", "SeqLen"],
    outputs=["Hidden", "Cell", "AttentionedX", "AttentionFCOut",
             "LSTMX", "LSTMOUT"],
    stateful_outputs=("AttentionedX", "AttentionFCOut", "LSTMX",
                      "LSTMOUT"),
)
def attention_lstm(ctx, attrs, X, C0, H0, AttentionWeight, AttentionBias,
                   AttentionScalar, AttentionScalarBias, LSTMWeight,
                   LSTMBias, SeqLen):
    """fused/attention_lstm_op.cc: per step, score every input row by
    fc([x_t_all, h]) → softmax over time → attention-pooled x feeds one
    LSTM step.  Padded [B, T, D] + lengths; the per-step host loop
    becomes a lax.scan whose body does the [B,T] attention."""
    B, T, D = X.shape
    d = C0.shape[-1]
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    h0 = H0 if H0 is not None else jnp.zeros((B, d), X.dtype)
    c0 = C0
    lengths = (jnp.reshape(SeqLen, (-1,)).astype(jnp.int32)
               if SeqLen is not None else jnp.full((B,), T, jnp.int32))
    tmask = jnp.arange(T)[None, :] < lengths[:, None]  # [B, T]

    def step(carry, _):
        h, c = carry
        # attention scores: fc([x_t, h]) per row
        hx = jnp.concatenate(
            [X, jnp.broadcast_to(h[:, None, :], (B, T, d))], axis=2)
        s = jnp.tanh(jnp.matmul(hx, AttentionWeight)
                     + (AttentionBias.reshape(1, 1, -1)
                        if AttentionBias is not None else 0.0))
        if AttentionScalar is not None:
            s = s * AttentionScalar.reshape(1, 1, -1)
            s = jnp.sum(s, axis=2)
            if AttentionScalarBias is not None:
                s = s + AttentionScalarBias.reshape(1, -1)[:, :1]
        else:
            s = s[..., 0]
        s = jnp.where(tmask, s, -1e30)
        w = jax.nn.softmax(s, axis=1)  # [B, T]
        xt = jnp.einsum("bt,btd->bd", w, X)
        gates = jnp.matmul(jnp.concatenate([xt, h], axis=1), LSTMWeight)
        if LSTMBias is not None:
            gates = gates + LSTMBias.reshape(1, -1)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = gate_act(f) * c + gate_act(i) * cand_act(g)
        h_new = gate_act(o) * cell_act(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), None, length=T)
    hs = jnp.moveaxis(hs, 0, 1)
    cs = jnp.moveaxis(cs, 0, 1)
    zero = jnp.zeros((1,), X.dtype)
    return {"Hidden": hs, "Cell": cs, "AttentionedX": zero,
            "AttentionFCOut": zero, "LSTMX": zero, "LSTMOUT": zero}
