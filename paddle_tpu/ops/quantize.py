"""Fake-quantization ops (quantization-aware training).

Reference: ``paddle/fluid/operators/fake_quantize_op.{h,cc}`` —
``ClipAndFakeQuantFunctor``: Out = round(clip(X, -s, s) * bin_cnt / s),
scale variants {abs_max, channel_wise_abs_max, range_abs_max,
moving_average_abs_max}; ``fake_dequantize_op.cc``: Out = X * s/max_range.

TPU-native notes: the quantize+dequantize pair used by QAT is also
provided fused (``fake_quantize_dequantize_*``) with an explicit
straight-through-estimator grad op (X@GRAD = Out@GRAD masked to the clip
range) — the reference gets STE by transpiler wiring; here it is a
registered ``*_grad`` lowering, so ``append_backward`` picks it up like
any hand-written grad kernel.  Scale state (moving average accum/state)
threads functionally through In*/Out* slots like batch-norm stats.
"""

import jax
import jax.numpy as jnp

from .registry import register_op


def _bin_cnt(attrs):
    return (1 << (int(attrs.get("bit_length", 8)) - 1)) - 1


def _clip_quant(x, scale, bin_cnt):
    s = jnp.maximum(scale, 1e-8)
    clipped = jnp.clip(x, -s, s)
    return jnp.round(clipped * (bin_cnt / s))


@register_op("fake_quantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], no_grad=True)
def fake_quantize_abs_max(ctx, attrs, X):
    bin_cnt = _bin_cnt(attrs)
    scale = jnp.max(jnp.abs(X))
    return _clip_quant(X, scale, bin_cnt), scale.reshape(1)


@register_op("fake_channel_wise_quantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], no_grad=True)
def fake_channel_wise_quantize_abs_max(ctx, attrs, X):
    """Per-output-channel scales (axis 0, conv filter layout)."""
    bin_cnt = _bin_cnt(attrs)
    scale = jnp.max(jnp.abs(X.reshape(X.shape[0], -1)), axis=1)
    s_b = scale.reshape((-1,) + (1,) * (X.ndim - 1))
    return _clip_quant(X, s_b, bin_cnt), scale


@register_op("fake_dequantize_max_abs", inputs=["X", "Scale"],
             outputs=["Out"], no_grad=True)
def fake_dequantize_max_abs(ctx, attrs, X, Scale):
    max_range = float(attrs.get("max_range", 127.0))
    return X * (Scale.reshape(()) / max_range)


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=["X", "Scales*"], outputs=["Out"], no_grad=True)
def fake_channel_wise_dequantize_max_abs(ctx, attrs, X, Scales):
    """One scale: per-channel along axis 0 (conv filter case).  Two scales
    (the mul/fc case, fake_dequantize_op.cc ChannelDequantizeFunctor
    scale_num==2): Scales[0] is per-channel along the LAST axis, Scales[1]
    a scalar."""
    quant_bits = attrs.get("quant_bits", [8] * len(Scales))
    out = X
    for i, s in enumerate(Scales):
        bits = int(quant_bits[i]) if i < len(quant_bits) else 8
        max_range = float((1 << (bits - 1)) - 1)
        if s.ndim >= 1 and s.size > 1:
            if len(Scales) == 1:
                shape = (-1,) + (1,) * (X.ndim - 1)
            else:
                shape = (1,) * (X.ndim - 1) + (-1,)
            out = out * (s.reshape(shape) / max_range)
        else:
            out = out * (s.reshape(()) / max_range)
    return out


def _moving_average_scale(X, InAccum, InState, attrs):
    rate = float(attrs.get("moving_rate", 0.9))
    abs_max = jnp.max(jnp.abs(X))
    accum = InAccum.reshape(()) * rate + abs_max
    state = InState.reshape(()) * rate + 1.0
    scale = accum / state
    return scale, accum, state


@register_op(
    "fake_quantize_moving_average_abs_max",
    inputs=["X", "InScale", "InAccum", "InState"],
    outputs=["Out", "OutScale", "OutAccum", "OutState"], no_grad=True)
def fake_quantize_moving_average_abs_max(ctx, attrs, X, InScale, InAccum,
                                         InState):
    bin_cnt = _bin_cnt(attrs)
    if attrs.get("is_test", False) or InAccum is None:
        scale = InScale.reshape(())
        out = _clip_quant(X, scale, bin_cnt)
        return out, scale.reshape(1), InAccum, InState
    scale, accum, state = _moving_average_scale(X, InAccum, InState, attrs)
    out = _clip_quant(X, scale, bin_cnt)
    return out, scale.reshape(1), accum.reshape(1), state.reshape(1)


# ---------------------------------------------------------------------------
# fused quantize+dequantize (QAT simulation) with STE grads
# ---------------------------------------------------------------------------

def _quant_dequant(x, scale, bin_cnt):
    s = jnp.maximum(scale, 1e-8)
    return _clip_quant(x, s, bin_cnt) * (s / bin_cnt)


@register_op("quantize_dequantize_fixed_scale", inputs=["X", "InScale"],
             outputs=["Out"], no_grad=True)
def quantize_dequantize_fixed_scale(ctx, attrs, X, InScale):
    """Static-scale QDQ simulation for post-training-calibrated
    activations (the role of the reference's calibrated int8 rewrite,
    ``inference/api/mkldnn_quantizer.cc`` — scales computed offline from
    a calibration set, applied as constants at inference)."""
    bin_cnt = _bin_cnt(attrs)
    return _quant_dequant(X, InScale.reshape(()), bin_cnt)


@register_op("fake_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"])
def fake_quantize_dequantize_abs_max(ctx, attrs, X):
    bin_cnt = _bin_cnt(attrs)
    scale = jnp.max(jnp.abs(X))
    return _quant_dequant(X, scale, bin_cnt), scale.reshape(1)


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             inputs=["X"], outputs=["Out", "OutScale"])
def fake_channel_wise_quantize_dequantize_abs_max(ctx, attrs, X):
    """Per-output-channel (axis 0, conv filter layout) QDQ simulation —
    the reference's channel_wise_abs_max weight quantization
    (fake_quantize_op.cc FakeChannelWiseQuantizeDequantizeAbsMax)."""
    bin_cnt = _bin_cnt(attrs)
    scale = jnp.max(jnp.abs(X.reshape(X.shape[0], -1)), axis=1)
    s_b = scale.reshape((-1,) + (1,) * (X.ndim - 1))
    return _quant_dequant(X, s_b, bin_cnt), scale


@register_op("fake_channel_wise_quantize_dequantize_abs_max_grad",
             inputs=["X", "Out", "OutScale", "Out@GRAD"],
             outputs=["X@GRAD"], no_grad=True)
def fake_channel_wise_qdq_abs_max_grad(ctx, attrs, X, Out, OutScale,
                                       Out_grad):
    # straight-through estimator (abs_max never clips interior values)
    return Out_grad


@register_op("fake_quantize_dequantize_abs_max_grad",
             inputs=["X", "Out", "OutScale", "Out@GRAD"],
             outputs=["X@GRAD"], no_grad=True)
def fake_quantize_dequantize_abs_max_grad(ctx, attrs, X, Out, OutScale,
                                          Out_grad):
    # straight-through estimator; abs_max scale never clips interior values
    return Out_grad


@register_op(
    "fake_quantize_dequantize_moving_average_abs_max",
    inputs=["X", "InScale", "InAccum", "InState"],
    outputs=["Out", "OutScale", "OutAccum", "OutState"],
    stateful_outputs=("OutAccum", "OutState", "OutScale"))
def fake_quantize_dequantize_moving_average_abs_max(ctx, attrs, X, InScale,
                                                    InAccum, InState):
    bin_cnt = _bin_cnt(attrs)
    if attrs.get("is_test", False) or InAccum is None:
        scale = InScale.reshape(())
        return (_quant_dequant(X, scale, bin_cnt), scale.reshape(1),
                InAccum, InState)
    scale, accum, state = _moving_average_scale(X, InAccum, InState, attrs)
    return (_quant_dequant(X, scale, bin_cnt), scale.reshape(1),
            accum.reshape(1), state.reshape(1))


@register_op(
    "fake_quantize_dequantize_moving_average_abs_max_grad",
    inputs=["X", "InScale", "InAccum", "InState", "Out", "OutScale",
            "OutAccum", "OutState", "Out@GRAD"],
    outputs=["X@GRAD"], no_grad=True)
def fake_qdq_moving_average_grad(ctx, attrs, X, InScale, InAccum, InState,
                                 Out, OutScale, OutAccum, OutState,
                                 Out_grad):
    # STE with clip masking: values clipped by the running scale get no grad
    s = jnp.maximum(OutScale.reshape(()), 1e-8)
    inside = (jnp.abs(X) <= s).astype(Out_grad.dtype)
    return Out_grad * inside


@register_op("fake_quantize_range_abs_max",
             inputs=["X", "InScale", "Iter"],
             outputs=["Out", "OutScale", "OutScales"],
             no_grad=True, stateful_outputs=("OutScale", "OutScales"))
def fake_quantize_range_abs_max(ctx, attrs, X, InScale, Iter):
    """Windowed running-max scale (fake_quantize_op.cc range_abs_max):
    scale = max(current |X| max, previous scale) inside the window."""
    bin_cnt = _bin_cnt(attrs)
    cur = jnp.max(jnp.abs(X))
    window = int(attrs.get("window_size", 10000))
    if InScale is None:
        scale = cur
    else:
        prev = InScale.reshape(())
        if Iter is not None:
            # window boundary resets the running max (reference
            # FindRangeAbsMaxFunctor: it = iter % window == 0 restarts)
            at_boundary = (Iter.reshape(()).astype(jnp.int32)
                           % window) == 0
            scale = jnp.where(at_boundary, cur, jnp.maximum(cur, prev))
        else:
            scale = jnp.maximum(cur, prev)
    return {
        "Out": _clip_quant(X, scale, bin_cnt),
        "OutScale": scale.reshape(1),
        "OutScales": scale.reshape(1),
    }


@register_op("moving_average_abs_max_scale",
             inputs=["X", "InAccum", "InState"],
             outputs=["Out", "OutScale", "OutAccum", "OutState"],
             no_grad=True,
             stateful_outputs=("OutScale", "OutAccum", "OutState"))
def moving_average_abs_max_scale(ctx, attrs, X, InAccum, InState):
    """Scale observer without quantization
    (fake_quantize_op.cc moving_average_abs_max_scale)."""
    rate = float(attrs.get("moving_rate", 0.9))
    cur = jnp.max(jnp.abs(X))
    accum = (InAccum.reshape(()) * rate + cur if InAccum is not None
             else cur)
    state = (InState.reshape(()) * rate + 1.0 if InState is not None
             else jnp.asarray(1.0))
    return {"Out": X, "OutScale": (accum / state).reshape(1),
            "OutAccum": accum.reshape(1), "OutState": state.reshape(1)}


def _affine_q(x, scale, shift, bits):
    qmax = (1 << bits) - 1
    return jnp.clip(jnp.round(x * scale + shift), 0, qmax)


@register_op("quantize", inputs=["Input"], outputs=["Output"],
             no_grad=True)
def quantize(ctx, attrs, Input):
    """INT8 affine quantize (mkldnn quantize_op.cc: Out = round(X*Scale)
    + Shift as uint8)."""
    scale = float(attrs.get("Scale", 1.0))
    shift = float(attrs.get("Shift", 0.0))
    return _affine_q(Input, scale, shift, 8).astype(jnp.uint8)


@register_op("dequantize", inputs=["Input"], outputs=["Output"],
             no_grad=True)
def dequantize(ctx, attrs, Input):
    """INT8 affine dequantize (mkldnn dequantize_op.cc)."""
    scale = float(attrs.get("Scale", 1.0))
    shift = float(attrs.get("Shift", 0.0))
    return (Input.astype(jnp.float32) - shift) / max(scale, 1e-12)


@register_op("requantize", inputs=["Input"], outputs=["Output"],
             no_grad=True)
def requantize(ctx, attrs, Input):
    """INT8 rescale (mkldnn requantize_op.cc)."""
    sin = float(attrs.get("Scale_in", 1.0))
    sout = float(attrs.get("Scale_out", 1.0))
    x = Input.astype(jnp.float32) * (sout / max(sin, 1e-12))
    return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)
