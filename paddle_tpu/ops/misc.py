"""Miscellaneous op lowerings: chunk evaluation, CVM, SelectedRows shims,
host callbacks, tree conv, similarity focus.

Reference kernels: ``paddle/fluid/operators/{chunk_eval,cvm,
get_tensor_from_selected_rows,merge_selected_rows,py_func,tree_conv,
similarity_focus}_op.*``."""

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("chunk_eval",
             inputs=["Inference", "Label", "SeqLength"],
             outputs=["Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"],
             no_grad=True)
def chunk_eval(ctx, attrs, Inference, Label, SeqLength):
    """Chunk-level P/R/F1 for sequence labeling (chunk_eval_op.h).
    Schemes: IOB (tag = type*2 + {0:B,1:I}) and plain (tag == type).
    Padded [B, T] tags + SeqLength; a predicted chunk is correct when its
    begin, end, and type all match a gold chunk — evaluated with a
    per-position begin/end/type encoding, no host loops."""
    scheme = attrs.get("chunk_scheme", "IOB")
    num_types = int(attrs.get("num_chunk_types"))
    excluded = set(attrs.get("excluded_chunk_types", []) or [])
    B, T = Inference.shape[0], Inference.shape[1]
    inf = jnp.reshape(Inference, (B, T)).astype(jnp.int32)
    lab = jnp.reshape(Label, (B, T)).astype(jnp.int32)
    lengths = (jnp.reshape(SeqLength, (-1,)).astype(jnp.int32)
               if SeqLength is not None else jnp.full((B,), T, jnp.int32))
    valid = jnp.arange(T)[None, :] < lengths[:, None]

    def decompose(tags):
        # the O (outside) tag encodes as chunk_type >= num_chunk_types
        # (reference chunk_eval_op.h: IOB O = num_types*2, plain O =
        # num_types); outside positions belong to no chunk
        if scheme == "plain":
            ctype = tags
            is_b = jnp.ones_like(tags, dtype=bool)
        else:  # IOB: B = type*2, I = type*2 + 1
            ctype = tags // 2
            is_b = (tags % 2) == 0
        inside = valid & (ctype < num_types)
        prev_type = jnp.concatenate(
            [jnp.full((B, 1), -1, jnp.int32), ctype[:, :-1]], axis=1)
        prev_inside = jnp.concatenate(
            [jnp.zeros((B, 1), bool), inside[:, :-1]], axis=1)
        if scheme == "plain":
            begin = inside & ((~prev_inside) | (ctype != prev_type))
        else:
            begin = inside & (is_b | (~prev_inside)
                              | (ctype != prev_type))
        # end position of the chunk starting at p: next begin - 1 or len-1
        nxt_begin = jnp.concatenate(
            [begin[:, 1:], jnp.ones((B, 1), bool)], axis=1)
        # compute chunk id per position: cumsum of begins
        return begin, ctype

    def chunk_key(begin, ctype, tags):
        """Encode each chunk as (batch, start, end, type); represented as
        a per-START-position integer key; -1 where no chunk starts."""
        idx = jnp.arange(T)[None, :]
        # end = (next start or len) - 1, computed via reverse cummax of
        # next-begin positions
        begin_pos = jnp.where(begin, idx, T + 1)

        def nxt(carry, x):
            carry = jnp.minimum(carry, x)
            return carry, carry

        # scan right-to-left over positions for next begin AFTER p
        bp_rev = begin_pos[:, ::-1]
        init = jnp.full((B,), T + 1)
        _, nb_rev = jax.lax.scan(
            lambda c, x: (jnp.minimum(c, x), jnp.minimum(c, x)),
            init, bp_rev[:, :].T)
        nb = nb_rev.T[:, ::-1]  # next begin at or after p
        nb_after = jnp.concatenate(
            [nb[:, 1:], jnp.full((B, 1), T + 1)], axis=1)
        end = jnp.minimum(nb_after - 1, lengths[:, None] - 1)
        key = (idx * (T + 2) + (end + 1)) * (num_types + 1) + ctype
        return jnp.where(begin, key, -1)

    ib, it = decompose(inf)
    lb, lt = decompose(lab)
    ikey = chunk_key(ib, it, inf)
    lkey = chunk_key(lb, lt, lab)
    if excluded:
        exc = jnp.asarray(sorted(excluded), jnp.int32)
        ib = ib & ~jnp.isin(it, exc)
        lb = lb & ~jnp.isin(lt, exc)
        ikey = jnp.where(ib, ikey, -1)
        lkey = jnp.where(lb, lkey, -1)
    n_inf = jnp.sum(ib & valid)
    n_lab = jnp.sum(lb & valid)
    correct = jnp.sum((ikey == lkey) & (ikey >= 0) & valid)
    p = correct / jnp.maximum(n_inf, 1)
    r = correct / jnp.maximum(n_lab, 1)
    f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    as_f = lambda v: v.astype(jnp.float32).reshape(1)
    return {
        "Precision": as_f(p), "Recall": as_f(r), "F1-Score": as_f(f1),
        "NumInferChunks": n_inf.reshape(1).astype(jnp.int64),
        "NumLabelChunks": n_lab.reshape(1).astype(jnp.int64),
        "NumCorrectChunks": correct.reshape(1).astype(jnp.int64),
    }


@register_op("cvm", inputs=["X", "CVM"], outputs=["Y"])
def cvm(ctx, attrs, X, CVM):
    """Continuous-value model (cvm_op.cc): X = [show, click, emb...];
    use_cvm=True -> log-transform the two lead features; False -> strip
    them."""
    use_cvm = bool(attrs.get("use_cvm", True))
    if not use_cvm:
        return X[:, 2:]
    show = jnp.log(jnp.maximum(X[:, :1], 1e-20) + 1.0)
    click = jnp.log(jnp.maximum(X[:, 1:2], 1e-20) + 1.0) - show
    return jnp.concatenate([show, click, X[:, 2:]], axis=1)


@register_op("get_tensor_from_selected_rows", inputs=["X"], outputs=["Out"])
def get_tensor_from_selected_rows(ctx, attrs, X):
    """SelectedRows were replaced by dense scatter-add grads (SURVEY §2.1
    Tensor row); the conversion is the identity."""
    return X


@register_op("merge_selected_rows", inputs=["X"], outputs=["Out"])
def merge_selected_rows(ctx, attrs, X):
    """Row-duplicate merging happened implicitly in the scatter-add grad;
    identity on dense tensors."""
    return X


@register_op("py_func", inputs=["X*"], outputs=["Out*"], no_grad=True)
def py_func(ctx, attrs, X):
    """Host-python callback (py_func_op.cc) via jax.pure_callback: the
    registered callable runs on host per execution; output shapes/dtypes
    must be declared (TPU static shapes)."""
    from . import py_func_registry

    fn_id = int(attrs["func_id"])
    fn, out_specs = py_func_registry.get(fn_id)
    result_shape = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in out_specs]
    outs = jax.pure_callback(
        lambda *a: fn(*a), result_shape, *X, vmap_method="sequential")
    return {"Out": list(outs)}


@register_op("tree_conv", inputs=["NodesVector", "EdgeSet", "Filter"],
             outputs=["Out"])
def tree_conv(ctx, attrs, NodesVector, EdgeSet, Filter):
    """Tree-based convolution (tree_conv_op.h, simplified continuous
    binary tree form): for each node, aggregate its edge-neighbors with
    the 3-way filter [D, H, 3] per output channel.  NodesVector [B,N,D],
    EdgeSet [B,E,2] (parent,child pairs, 0-padded), Filter [D,H,3]
    (self/left-ish/right-ish mixing)."""
    B, N, D = NodesVector.shape
    w_self, w_l, w_r = Filter[..., 0], Filter[..., 1], Filter[..., 2]
    edges = EdgeSet.astype(jnp.int32)
    parent, child = edges[..., 0], edges[..., 1]  # [B, E]
    # padding rows are (0, 0); a real tree edge never has parent == child,
    # so self-loops mark padding and contribute nothing
    real = (parent != child).astype(NodesVector.dtype)  # [B, E]

    def agg(nodes, par, chi, m):
        up = jnp.zeros_like(nodes).at[par].add(nodes[chi] * m[:, None])
        down = jnp.zeros_like(nodes).at[chi].add(nodes[par] * m[:, None])
        return up, down

    up, down = jax.vmap(agg)(NodesVector, parent, child, real)
    out = (jnp.matmul(NodesVector, w_self) + jnp.matmul(up, w_l)
           + jnp.matmul(down, w_r))
    return jnp.tanh(out)


@register_op("similarity_focus", inputs=["X"], outputs=["Out"])
def similarity_focus(ctx, attrs, X):
    """Similarity-focus mask (similarity_focus_op.h): for each selected
    channel (axis/indexes attrs), mark rows/cols containing that
    channel's per-row/col maxima; output is X's shape with the focus mask
    values 1.0/0.0."""
    axis = int(attrs.get("axis", 1))
    indexes = [int(i) for i in attrs.get("indexes", [0])]
    assert axis == 1, "similarity_focus: only channel axis supported"
    B, C, H, W = X.shape
    mask = jnp.zeros((B, H, W), X.dtype)
    for idx in indexes:
        ch = X[:, idx]  # [B, H, W]
        row_max = ch == jnp.max(ch, axis=2, keepdims=True)
        col_max = ch == jnp.max(ch, axis=1, keepdims=True)
        mask = jnp.maximum(mask, (row_max | col_max).astype(X.dtype))
    return jnp.broadcast_to(mask[:, None], X.shape)
