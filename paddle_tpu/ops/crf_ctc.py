"""Linear-chain CRF, CTC, and edit-distance lowerings.

Reference kernels: ``paddle/fluid/operators/linear_chain_crf_op.h`` (alpha
recursion with L1 renormalization), ``crf_decoding_op.h`` (viterbi),
``warpctc_op.*`` (external warp-ctc), ``edit_distance_op.h``,
``ctc_align_op.h``.  TPU redesign: ragged LoD batches become padded
[B,T,...] + length tensors; every dynamic recursion is a lax.scan in log
space (no L1-renorm trick needed — logsumexp is stable), so the losses are
differentiable by jax.vjp instead of hand-written grad kernels."""

import jax
import jax.numpy as jnp

from .registry import register_op

NEG = -1e30


def _len_mask(T, lengths):
    # [T, B] step-active mask
    return jnp.arange(T)[:, None] < lengths[None, :]


@register_op("linear_chain_crf",
             inputs=["Emission", "Transition", "Label", "Length"],
             outputs=["Alpha", "EmissionExps", "TransitionExps",
                      "LogLikelihood"],
             stateful_outputs=("Alpha", "EmissionExps", "TransitionExps"))
def linear_chain_crf(ctx, attrs, Emission, Transition, Label, Length):
    """Negative log-likelihood of a linear-chain CRF
    (linear_chain_crf_op.h ForwardOneSequence): returns logZ - gold_score
    per sequence.  Transition row 0 = start weights, row 1 = end weights,
    rows 2.. = state transitions w[j+2, i] = score(j -> i).
    Padded [B,T,D] emissions + Length[B] replace the reference's LoD."""
    B, T, D = Emission.shape
    w_start = Transition[0]
    w_end = Transition[1]
    w_trans = Transition[2:]  # [D, D], [from, to]
    lengths = (jnp.reshape(Length, (-1,)).astype(jnp.int32)
               if Length is not None else jnp.full((B,), T, jnp.int32))
    labels = jnp.reshape(Label, (B, T)).astype(jnp.int32)
    em_t = jnp.moveaxis(Emission, 1, 0)  # [T, B, D]
    lab_t = jnp.moveaxis(labels, 1, 0)   # [T, B]
    mask = _len_mask(T, lengths)         # [T, B]

    # --- logZ by alpha recursion in log space ---
    alpha0 = w_start[None, :] + em_t[0]  # [B, D]

    def step(carry, xt):
        alpha = carry
        em, m = xt  # [B, D], [B]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + w_trans[None, :, :], axis=1) + em
        alpha = jnp.where(m[:, None], nxt, alpha)
        return alpha, alpha

    alpha_last, alphas = jax.lax.scan(step, alpha0, (em_t[1:], mask[1:]))
    logz = jax.nn.logsumexp(alpha_last + w_end[None, :], axis=1)  # [B]

    # --- gold path score ---
    t_idx = jnp.arange(T)
    em_lab = jnp.take_along_axis(
        Emission, labels[:, :, None], axis=2)[:, :, 0]  # [B, T]
    em_score = jnp.sum(jnp.where(mask.T, em_lab, 0.0), axis=1)
    trans_lab = w_trans[labels[:, :-1], labels[:, 1:]]  # [B, T-1]
    trans_score = jnp.sum(
        jnp.where(mask.T[:, 1:], trans_lab, 0.0), axis=1)
    last_idx = jnp.maximum(lengths - 1, 0)
    last_lab = jnp.take_along_axis(labels, last_idx[:, None], axis=1)[:, 0]
    gold = (w_start[labels[:, 0]] + em_score + trans_score
            + w_end[last_lab])
    ll = (logz - gold)[:, None]  # [B, 1], reference sign (NLL)
    alphas_full = jnp.concatenate(
        [alpha0[None], alphas], axis=0)  # [T, B, D]
    return {
        "LogLikelihood": ll,
        "Alpha": jnp.moveaxis(alphas_full, 0, 1),
        "EmissionExps": jnp.exp(
            Emission - jnp.max(Emission, axis=2, keepdims=True)),
        "TransitionExps": jnp.exp(Transition),
    }


@register_op("crf_decoding",
             inputs=["Emission", "Transition", "Label", "Length"],
             outputs=["ViterbiPath"], no_grad=True)
def crf_decoding(ctx, attrs, Emission, Transition, Label, Length):
    """Viterbi decode (crf_decoding_op.h).  Output: [B, T] best tag ids
    (padded steps 0); with Label given, outputs 1 where the label
    DISAGREES with the viterbi path is the reference convention inverted —
    the reference emits 1 for correct tags; we match it."""
    B, T, D = Emission.shape
    w_start = Transition[0]
    w_end = Transition[1]
    w_trans = Transition[2:]
    lengths = (jnp.reshape(Length, (-1,)).astype(jnp.int32)
               if Length is not None else jnp.full((B,), T, jnp.int32))
    em_t = jnp.moveaxis(Emission, 1, 0)
    mask = _len_mask(T, lengths)

    v0 = w_start[None, :] + em_t[0]

    def step(carry, xt):
        v = carry
        em, m = xt
        scores = v[:, :, None] + w_trans[None, :, :]  # [B, from, to]
        best = jnp.max(scores, axis=1) + em
        back = jnp.argmax(scores, axis=1)  # [B, D]
        v = jnp.where(m[:, None], best, v)
        return v, (back, m)

    v_last, (backs, ms) = jax.lax.scan(step, v0, (em_t[1:], mask[1:]))
    # add end weights at each sequence's true last position: emulate by
    # adding w_end to v_last (v_last holds the value at position len-1)
    v_final = v_last + w_end[None, :]
    last_tag = jnp.argmax(v_final, axis=1)  # [B]

    def backtrack(carry, xt):
        tag = carry
        back, m = xt
        prev = jnp.take_along_axis(back, tag[:, None], axis=1)[:, 0]
        tag = jnp.where(m, prev, tag)
        return tag, tag

    _, path_rev = jax.lax.scan(
        backtrack, last_tag, (backs, ms), reverse=True)
    path = jnp.concatenate([path_rev, last_tag[None]], axis=0)  # [T, B]
    path = jnp.moveaxis(path, 0, 1)
    path = jnp.where(mask.T, path, 0)
    if Label is not None:
        lab = jnp.reshape(Label, (B, T)).astype(path.dtype)
        return jnp.where(mask.T, (lab == path).astype(jnp.int64), 0)
    return path.astype(jnp.int64)


@register_op("edit_distance", inputs=["Hyps", "Refs", "HypsLength",
                                      "RefsLength"],
             outputs=["Out", "SequenceNum"], no_grad=True)
def edit_distance(ctx, attrs, Hyps, Refs, HypsLength, RefsLength):
    """Levenshtein distance per sequence pair (edit_distance_op.h), DP
    rows scanned over hypothesis positions; padded [B, L] + lengths."""
    B, L1 = Hyps.shape[0], Hyps.shape[1]
    L2 = Refs.shape[1]
    hl = jnp.reshape(HypsLength, (-1,)).astype(jnp.int32) \
        if HypsLength is not None else jnp.full((B,), L1, jnp.int32)
    rl = jnp.reshape(RefsLength, (-1,)).astype(jnp.int32) \
        if RefsLength is not None else jnp.full((B,), L2, jnp.int32)
    hyps = jnp.reshape(Hyps, (B, L1))
    refs = jnp.reshape(Refs, (B, L2))
    ignored = [int(t) for t in attrs.get("ignored_tokens", []) or []]
    if ignored:
        # erase ignored tokens (reference erases them before the DP):
        # left-pack the kept tokens and shrink the lengths
        ig = jnp.asarray(ignored, jnp.int32)

        def compact(seq, lens, L):
            in_range = jnp.arange(L)[None, :] < lens[:, None]
            keep = (~jnp.isin(seq.astype(jnp.int32), ig)) & in_range
            pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
            safe_pos = jnp.where(keep, pos, L - 1)
            packed = jax.vmap(
                lambda s, p, k: jnp.zeros((L,), seq.dtype).at[p].set(
                    jnp.where(k, s, 0)))(seq, safe_pos, keep)
            return packed, jnp.sum(keep, axis=1).astype(jnp.int32)

        hyps, hl = compact(hyps, hl, L1)
        refs, rl = compact(refs, rl, L2)
    cols = jnp.arange(L2 + 1, dtype=jnp.float32)
    row0 = jnp.broadcast_to(cols, (B, L2 + 1))

    def step(carry, xt):
        prev_row, i = carry
        h = xt  # [B] hyp tokens at position i
        active = i < hl  # [B]
        sub_cost = (refs != h[:, None]).astype(jnp.float32)  # [B, L2]
        # new_row[0] = i+1
        def inner(c, xs):
            left = c  # new_row[j-1]
            up, diag, sc = xs  # prev_row[j], prev_row[j-1], sub cost
            val = jnp.minimum(jnp.minimum(up + 1, left + 1), diag + sc)
            return val, val

        first = jnp.full((B,), 0.0) + (i + 1)
        _, rest = jax.lax.scan(
            inner, first,
            (prev_row[:, 1:].T, prev_row[:, :-1].T, sub_cost.T))
        new_row = jnp.concatenate([first[:, None], rest.T], axis=1)
        new_row = jnp.where(active[:, None], new_row, prev_row)
        return (new_row, i + 1), None

    (final_row, _), _ = jax.lax.scan(
        step, (row0, jnp.asarray(0, jnp.int32)), hyps.T)
    dist = jnp.take_along_axis(final_row, rl[:, None], axis=1)  # [B,1]
    # empty-ref convention (reference): distance = hyp length
    dist = jnp.where((rl == 0)[:, None], hl[:, None].astype(jnp.float32),
                     dist)
    if attrs.get("normalized", True):
        dist = dist / jnp.maximum(rl[:, None].astype(jnp.float32), 1.0)
    return {"Out": dist, "SequenceNum": jnp.asarray([B], jnp.int64)}


@register_op("ctc_align", inputs=["Input", "InputLength"],
             outputs=["Output", "OutputLength"], no_grad=True,
             stateful_outputs=("OutputLength",))
def ctc_align(ctx, attrs, Input, InputLength):
    """CTC greedy post-processing (ctc_align_op.h): collapse repeats,
    strip blanks, left-pack; padded [B, T] + lengths; padding value fills
    the tail (attr padding_value, default 0)."""
    blank = int(attrs.get("blank", 0))
    pad_val = int(attrs.get("padding_value", 0))
    B, T = Input.shape[0], Input.shape[1]
    x = jnp.reshape(Input, (B, T)).astype(jnp.int32)
    lengths = (jnp.reshape(InputLength, (-1,)).astype(jnp.int32)
               if InputLength is not None
               else jnp.full((B,), T, jnp.int32))
    in_range = jnp.arange(T)[None, :] < lengths[:, None]
    prev = jnp.concatenate(
        [jnp.full((B, 1), -1, jnp.int32), x[:, :-1]], axis=1)
    keep = (x != blank) & (x != prev) & in_range  # [B, T]
    # left-pack kept tokens: target position = cumsum(keep)-1
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_len = jnp.maximum(pos[:, -1] + 1, 0) * (
        jnp.sum(keep, axis=1) > 0).astype(jnp.int32)
    out = jnp.full((B, T), pad_val, jnp.int32)
    # scatter kept tokens to packed positions
    safe_pos = jnp.where(keep, pos, T - 1)
    dummy = jnp.full((B, T), pad_val, jnp.int32)
    vals = jnp.where(keep, x, pad_val)
    out = jax.vmap(
        lambda o, p, v, k: o.at[p].set(jnp.where(k, v, o[p]))
    )(dummy, safe_pos, vals, keep)
    return {"Output": out.astype(jnp.int64),
            "OutputLength": out_len[:, None].astype(jnp.int64)}


@register_op("warpctc", inputs=["Logits", "Label", "LogitsLength",
                                "LabelLength"],
             outputs=["WarpCTCGrad", "Loss"],
             stateful_outputs=("WarpCTCGrad",))
def warpctc(ctx, attrs, Logits, Label, LogitsLength, LabelLength):
    """CTC loss (warpctc_op.*; the reference links Baidu warp-ctc — here
    the standard log-space alpha recursion as a lax.scan, differentiable
    by jax.vjp, so no hand-written gradient kernel is needed).
    Padded convention: Logits [B, T, C] activations (softmax applied
    internally, like warp-ctc), Label [B, L] (padded with blank), plus
    length tensors."""
    blank = int(attrs.get("blank", 0))
    norm_by_times = bool(attrs.get("norm_by_times", False))
    B, T, C = Logits.shape
    L = Label.shape[1]
    log_probs = jax.nn.log_softmax(Logits.astype(jnp.float32), axis=2)
    lab = jnp.reshape(Label, (B, L)).astype(jnp.int32)
    tl = (jnp.reshape(LogitsLength, (-1,)).astype(jnp.int32)
          if LogitsLength is not None else jnp.full((B,), T, jnp.int32))
    ll = (jnp.reshape(LabelLength, (-1,)).astype(jnp.int32)
          if LabelLength is not None else jnp.full((B,), L, jnp.int32))

    # extended sequence: blank y1 blank y2 ... blank  -> S = 2L+1
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    s_idx = jnp.arange(S)
    s_active = s_idx[None, :] < (2 * ll + 1)[:, None]  # [B, S]
    # allow diagonal skip when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    lp_t = jnp.moveaxis(log_probs, 1, 0)  # [T, B, C]

    def emit(lp):
        return jnp.take_along_axis(lp, ext, axis=1)  # [B, S]

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(emit(lp_t[0])[:, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(ll > 0, emit(lp_t[0])[:, 1], NEG))

    def step(carry, xt):
        alpha, t = carry
        lp = xt
        a_prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        a_prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        a_prev2 = jnp.where(can_skip, a_prev2, NEG)
        nxt = jnp.logaddexp(
            jnp.logaddexp(alpha, a_prev1), a_prev2) + emit(lp)
        nxt = jnp.where(s_active, nxt, NEG)
        active_t = (t < tl)[:, None]
        alpha = jnp.where(active_t, nxt, alpha)
        return (alpha, t + 1), None

    (alpha_T, _), _ = jax.lax.scan(
        step, (alpha0, jnp.asarray(1, jnp.int32)), lp_t[1:])
    end1 = jnp.take_along_axis(alpha_T, (2 * ll)[:, None], axis=1)[:, 0]
    end2 = jnp.take_along_axis(
        alpha_T, jnp.maximum(2 * ll - 1, 0)[:, None], axis=1)[:, 0]
    nll = -jnp.logaddexp(end1, end2)  # [B]
    if norm_by_times:
        nll = nll / jnp.maximum(tl.astype(jnp.float32), 1.0)
    return {"Loss": nll[:, None],
            "WarpCTCGrad": jnp.zeros_like(log_probs)}
