"""Host-callable registry for the py_func op (reference py_func_op.cc
keeps a global vector of PyObject callables indexed by an int attr — same
pattern here; the executable program stores only the index)."""

_funcs = {}
_next_id = [0]


def register(fn, out_specs):
    """Register `fn` returning arrays matching out_specs
    [(shape, dtype), ...]; returns the func_id attr value."""
    fid = _next_id[0]
    _next_id[0] += 1
    _funcs[fid] = (fn, list(out_specs))
    return fid


def get(fid):
    return _funcs[fid]
