"""Collective ops (reference: ``paddle/fluid/operators/collective/``
c_allreduce_{sum,max,min,prod}, c_broadcast, c_allgather, c_reducescatter,
c_comm_init/c_gen_nccl_id + sync-stream ops).

TPU-native: under a shard_map with a named mesh axis these lower to
``lax.psum``-family collectives over ICI; under plain jit/GSPMD (the normal
path) the partitioner inserts collectives itself and these ops act on
already-global values, so they are identity.  The ctx carries the active
axis name when the executor runs inside shard_map (`ctx.collective_axis`).
The NCCL bootstrap ops (c_gen_nccl_id, c_comm_init) are no-ops: device-mesh
membership comes from the jax coordination service
(``jax.distributed.initialize``), not a rank-0 RPC broadcast
(``gen_nccl_id_op.cc:188``)."""

import jax
import jax.numpy as jnp

from .registry import register_op


def _axis(ctx):
    return getattr(ctx, "collective_axis", None)


def _slice_groups(ax, c):
    """Contiguous intra-slice groups: chips [si*c, si*c+c) per slice."""
    from ..jax_compat import axis_size

    n = axis_size(ax)
    c = max(min(int(c), n), 1)
    return [[si * c + i for i in range(c)] for si in range(n // c)]


def _cross_groups(ax, s):
    """Cross-slice groups: same intra-slice position across slices
    (the DCN hop's participants under the contiguous-slice layout)."""
    from ..jax_compat import axis_size

    n = axis_size(ax)
    s = max(min(int(s), n), 1)
    c = max(n // s, 1)
    return [[i + si * c for si in range(s)] for i in range(c)]


def _cross_slice_sum(x, ax, attrs):
    """Grouped cross-slice sum via all_gather + ascending-slice-order
    add (grouped psum trips shard_map's replication checker; grouped
    all_gather does not, and the explicit ascending sum is the same
    bits on every member of the group)."""
    s = int(attrs.get("comm_nranks") or attrs.get("hier_slices") or 1)
    g = jax.lax.all_gather(x, ax,
                           axis_index_groups=_cross_groups(ax, s))
    acc = g[0]
    for si in range(1, g.shape[0]):
        acc = acc + g[si]
    return acc


def _allreduce(name, fn):
    @register_op(name, inputs=["X"], outputs=["Out"], no_grad=True)
    def _op(ctx, attrs, X, _fn=fn):
        ax = _axis(ctx)
        if ax is None:
            # GSPMD path: the value is already global — and any averaging
            # pre_scale must be skipped with it (a separate scale op would
            # wrongly shrink the identity path; this is why averaging
            # rides ON the collective, reference scale_loss_grad role)
            return X
        s = attrs.get("pre_scale")
        if s:
            X = X * jnp.asarray(s, X.dtype)
        if attrs.get("hier_groups") == "cross":
            # the DCN hop of a hierarchical decomposition: sum only
            # across slices (this chip's chunk-shard peers)
            return _cross_slice_sum(X, ax, attrs)
        return _fn(X, ax)

    return _op


_allreduce("c_allreduce_sum", lambda x, ax: jax.lax.psum(x, ax))
_allreduce("c_allreduce_max", lambda x, ax: jax.lax.pmax(x, ax))
_allreduce("c_allreduce_min", lambda x, ax: jax.lax.pmin(x, ax))
_allreduce("c_allreduce_prod",
           lambda x, ax: jnp.exp(jax.lax.psum(jnp.log(x), ax)))
_allreduce("allreduce", lambda x, ax: jax.lax.psum(x, ax))


@register_op("c_fused_allreduce_sum", inputs=["X*"], outputs=["Out*"],
             no_grad=True)
def c_fused_allreduce_sum(ctx, attrs, X):
    """Bucketed gradient allreduce (the fusion pipeline's rewrite of
    Fluid's ``fuse_all_reduce_op_pass``; EQuARX-style coalescing): N
    same-(ring, dtype) grads flatten into one buffer, ONE ring allreduce
    runs over ICI, and the buffer splits back.  Ring volume is unchanged
    (sum of members); the win is N-1 fewer collective launches.

    GSPMD path (no shard_map axis): identity, like the scalar op — the
    partitioner already reduced the values, so the rewrite is bit-exact
    with the unfused program.  shard_map path: ``psum(concat(xs))`` is
    elementwise-identical to ``concat(psum(x) for x)``, so numerics
    match the unfused schedule exactly."""
    from .common import flatten_concat, split_like

    ax = _axis(ctx)
    if ax is None:
        return {"Out": list(X)}
    s = attrs.get("pre_scale")
    flat = flatten_concat(X)
    if s:
        flat = flat * jnp.asarray(s, flat.dtype)
    flat = jax.lax.psum(flat, ax)
    return {"Out": split_like(flat, X, cast=False)}


@register_op("c_allreduce_quant", inputs=["X*"], outputs=["Out*"],
             no_grad=True)
def c_allreduce_quant(ctx, attrs, X):
    """Bucketed allreduce with int8 block-quantized exchange (EQuARX;
    ``quant.collective``): flatten like ``c_fused_allreduce_sum``, then
    quantize → reduce-scatter int8 → dequant-sum-requant → allgather.
    ~2x ICI byte cut at the quantization error documented in
    ``quant.blockwise``; the planner only emits it for buckets the cost
    model prices as ICI-bound winners.

    GSPMD path (no shard_map axis): identity, exactly like the bf16
    fused op — the partitioner already reduced the values, so with
    quant disabled OR under GSPMD this op is bit-exact with the dense
    path."""
    from ..quant.collective import quantized_allreduce
    from .common import flatten_concat, split_like

    ax = _axis(ctx)
    if ax is None:
        return {"Out": list(X)}
    s = attrs.get("pre_scale")
    flat = flatten_concat(X)
    if s:
        flat = flat * jnp.asarray(s, flat.dtype)
    if attrs.get("hier_groups") == "cross":
        # DCN hop of a hierarchical decomposition: int8 exchange across
        # slices only (EQuARX pays most on the slow tier).  Grouped
        # all_gather of the quantized payload + scales, then a
        # deterministic ascending-slice dequant-sum — identical bits on
        # every member of the cross group.
        from ..quant.blockwise import block_dequantize, block_quantize

        q, scales = block_quantize(
            flat, block=attrs.get("quant_block") or None, kernel=False)
        groups = _cross_groups(
            ax, int(attrs.get("comm_nranks") or 1))
        gq = jax.lax.all_gather(q, ax, axis_index_groups=groups)
        gs = jax.lax.all_gather(scales, ax, axis_index_groups=groups)
        acc = None
        for si in range(gq.shape[0]):
            d = block_dequantize(gq[si], gs[si], size=flat.size,
                                 dtype=flat.dtype, kernel=False)
            acc = d if acc is None else acc + d
        return {"Out": split_like(acc, X, cast=False)}
    flat = quantized_allreduce(flat, ax,
                               block=attrs.get("quant_block") or None)
    return {"Out": split_like(flat, X, cast=False)}


@register_op("c_allreduce_start", inputs=["X*"], outputs=["Out*"],
             no_grad=True)
def c_allreduce_start(ctx, attrs, X):
    """Async half of a bucketed allreduce (the overlap scheduler's split
    of ``c_fused_allreduce_sum`` / ``c_allreduce_quant``): emits the
    collective at the hoisted schedule position so XLA's async scheduler
    can overlap the ring transfer with the compute between start and
    wait.  The math is byte-identical to the fused synchronous op — the
    pair differs only in WHERE the collective sits in the schedule, so
    ``PADDLE_TPU_OVERLAP=0`` (which keeps the fused form) is bit-exact
    by construction.  ``attrs["quant"]`` selects the int8 block-quantized
    exchange (the ``c_allreduce_quant`` path); ``attrs["overlap_bucket"]``
    links this op to its ``c_allreduce_wait`` twin."""
    from .common import flatten_concat, split_like

    ax = _axis(ctx)
    if ax is None:
        return {"Out": list(X)}
    s = attrs.get("pre_scale")
    flat = flatten_concat(X)
    if s:
        flat = flat * jnp.asarray(s, flat.dtype)
    if attrs.get("quant"):
        from ..quant.collective import quantized_allreduce

        flat = quantized_allreduce(flat, ax,
                                   block=attrs.get("quant_block") or None)
    else:
        flat = jax.lax.psum(flat, ax)
    return {"Out": split_like(flat, X, cast=False)}


@register_op("c_allreduce_wait", inputs=["X*"], outputs=["Out*"],
             no_grad=True)
def c_allreduce_wait(ctx, attrs, X):
    """Consumer barrier of the start/wait pair: identity on the reduced
    values, placed just before the first consumer so every use of a
    bucket member data-depends on the collective having completed.  No
    wire traffic of its own (the cost model prices it at zero ICI
    bytes); it exists purely to pin the earliest legal consume point in
    the schedule."""
    return {"Out": list(X)}


@register_op("c_hier_reducescatter", inputs=["X*"], outputs=["Out"],
             no_grad=True)
def c_hier_reducescatter(ctx, attrs, X):
    """Intra-slice half of a hierarchical allreduce (ring 5): flatten
    the bucket like ``c_fused_allreduce_sum``, apply the averaging
    pre_scale, pad to a multiple of ``hier_chips`` and reduce-scatter
    within the slice — each chip ends with its 1/c chunk of the
    slice-local sum, ready for the cross-slice DCN hop.

    GSPMD path (no shard_map axis): the triple must be net-identity
    like the flat op, so this half just carries the padded flat buffer
    through (no scale, no scatter) and ``c_hier_allgather`` splits it
    back."""
    from .common import flatten_concat

    ax = _axis(ctx)
    flat = flatten_concat(X)
    c = int(attrs.get("hier_chips", 1))
    total = flat.size
    pad = -(-total // c) * c - total
    if ax is None:
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat
    s = attrs.get("pre_scale")
    if s:
        flat = flat * jnp.asarray(s, flat.dtype)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return jax.lax.psum_scatter(
        flat, ax, scatter_dimension=0,
        axis_index_groups=_slice_groups(ax, c), tiled=True)


@register_op("c_hier_allgather", inputs=["X*"], outputs=["Out*"],
             no_grad=True)
def c_hier_allgather(ctx, attrs, X):
    """Intra-slice gather-back (ring 5): after the cross-slice hop the
    chunk holds the GLOBAL sum of its shard — allgather within the
    slice reassembles the full bucket, trims the reduce-scatter pad,
    and splits the members back to ``attrs["member_shapes"]``.

    GSPMD path: the input is the padded flat buffer the identity
    reduce-scatter carried through — trim and split, net identity."""
    ax = _axis(ctx)
    flat = X[0]
    if ax is not None:
        c = int(attrs.get("hier_chips", 1))
        flat = jax.lax.all_gather(
            flat, ax, axis_index_groups=_slice_groups(ax, c),
            tiled=True)
    total = int(attrs.get("hier_total", flat.size))
    if flat.size < total:
        # metadata replay (eval_shape against the chunk var's recorded
        # shard shape): pad so the splits below type-check — every real
        # path (gathered shard_map shard, identity GSPMD buffer)
        # arrives with >= total elements
        flat = jnp.pad(flat, (0, total - flat.size))
    flat = flat[:total]
    outs = []
    off = 0
    for sh in attrs.get("member_shapes", ()):
        shape = tuple(int(d) for d in sh)
        k = 1
        for d in shape:
            k *= d
        outs.append(flat[off:off + k].reshape(shape))
        off += k
    return {"Out": outs}


@register_op("c_broadcast", inputs=["X"], outputs=["Out"], no_grad=True)
def c_broadcast(ctx, attrs, X):
    ax = _axis(ctx)
    if ax is None:
        return X
    root = int(attrs.get("root", 0))
    # select root's value on every member of the axis
    return jax.lax.all_gather(X, ax)[root]


@register_op("broadcast", inputs=["X"], outputs=["Out"], no_grad=True)
def broadcast(ctx, attrs, X):
    return c_broadcast(ctx, attrs, X)


@register_op("c_allgather", inputs=["X"], outputs=["Out"], no_grad=True)
def c_allgather(ctx, attrs, X):
    ax = _axis(ctx)
    if ax is None:
        return X
    g = jax.lax.all_gather(X, ax)  # [n, ...]
    return jnp.reshape(g, (-1,) + tuple(jnp.shape(X)[1:]))


@register_op("c_reducescatter", inputs=["X"], outputs=["Out"], no_grad=True)
def c_reducescatter(ctx, attrs, X):
    ax = _axis(ctx)
    if ax is None:
        return X
    return jax.lax.psum_scatter(X, ax, tiled=True)


@register_op("c_sync_calc_stream", inputs=["X"], outputs=["Out"],
             no_grad=True)
def c_sync_calc_stream(ctx, attrs, X):
    return X  # stream ordering is XLA's job


@register_op("c_sync_comm_stream", inputs=["X"], outputs=["Out"],
             no_grad=True)
def c_sync_comm_stream(ctx, attrs, X):
    return X


@register_op("c_gen_nccl_id", inputs=[], outputs=["Out"], no_grad=True)
def c_gen_nccl_id(ctx, attrs):
    return jnp.zeros((1,), jnp.int32)  # bootstrap handled by jax.distributed


@register_op("c_comm_init", inputs=["X"], outputs=[], no_grad=True)
def c_comm_init(ctx, attrs, X):
    return {}


# ---------------------------------------------------------------------------
# reshard / p2p collectives (the parallel program emitters:
# parallel/{moe,ulysses}.py emit all_to_all, parallel/ring_attention.py
# emits ppermute hops, parallel/pipeline.transpile_pipeline emits
# send_v2/recv_v2 stage boundaries).  In the IR these ops carry GLOBAL
# shapes (GSPMD view): the static analyzer reads their ring_id/peer
# attrs and payload metadata; under plain jit they are identity (the
# partitioner owns resharding) and under shard_map they issue the real
# lax collective.
# ---------------------------------------------------------------------------

def _identity_infer(op, block):
    """Out shape/dtype = X shape/dtype (global-view reshard ops)."""
    src = block._find_var_recursive(op.inputs["X"][0])
    for n in op.outputs.get("Out", []):
        v = block._find_var_recursive(n)
        if v is not None and src is not None:
            v.shape = src.shape
            v.dtype = src.dtype


@register_op("all_to_all", inputs=["X"], outputs=["Out"], no_grad=True,
             infer_shape=_identity_infer)
def all_to_all(ctx, attrs, X):
    ax = _axis(ctx)
    if ax is None:
        return X  # GSPMD: the partitioner re-lays-out the global value
    return jax.lax.all_to_all(
        X, ax, split_axis=int(attrs.get("split_axis", 0)),
        concat_axis=int(attrs.get("concat_axis", 0)), tiled=True)


@register_op("ppermute", inputs=["X"], outputs=["Out"], no_grad=True,
             infer_shape=_identity_infer)
def ppermute(ctx, attrs, X):
    ax = _axis(ctx)
    if ax is None:
        return X
    perm = [tuple(p) for p in attrs.get("perm", [])]
    if not perm:
        n = jax.lax.psum(1, ax)
        perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(X, ax, perm)


def _send_infer(op, block):
    pass  # no outputs


@register_op("send_v2", inputs=["X"], outputs=[], no_grad=True,
             infer_shape=_send_infer)
def send_v2(ctx, attrs, X):
    # structural p2p marker: the analyzable pipeline stage boundary.
    # The runnable TPU pipeline schedule is parallel.gpipe (one SPMD
    # computation, ppermute hops); a per-stage program containing this
    # op is a deployment/analysis artifact like the reference's
    # pserver programs, not an executor fast path.
    return {}


def _recv_infer(op, block):
    for n in op.outputs.get("Out", []):
        v = block._find_var_recursive(n)
        if v is not None:
            if op.attrs.get("out_shape") is not None:
                v.shape = tuple(op.attrs["out_shape"])
            if op.attrs.get("dtype") is not None:
                from ..core import convert_np_dtype_to_dtype_

                v.dtype = convert_np_dtype_to_dtype_(op.attrs["dtype"])


@register_op("recv_v2", inputs=[], outputs=["Out"], no_grad=True,
             infer_shape=_recv_infer)
def recv_v2(ctx, attrs, X=None):
    shape = tuple(max(int(d), 1) for d in attrs.get("out_shape", (1,)))
    dtype = attrs.get("dtype", "float32")
    if str(dtype) == "bfloat16":
        dtype = jnp.bfloat16
    return jnp.zeros(shape, dtype)  # structural twin of send_v2
