"""Vision / image-manipulation op lowerings.

Reference kernels: ``paddle/fluid/operators/{pixel_shuffle,shuffle_channel,
space_to_depth,temporal_shift,affine_channel,crop,pad_constant_like,
maxout,lrn,fsp,grid_sampler,affine_grid,roi_pool,psroi_pool,unfold,pool,
conv_transpose}_op.*``.  TPU-native notes: every rearrangement lowers to
reshape/transpose (free layout changes under XLA); samplers/pools become
gathers + segment reductions with static shapes; nothing loops on the
host."""

import jax
import jax.numpy as jnp

from .registry import register_op
from .common import normalize_axis


@register_op("pixel_shuffle", inputs=["X"], outputs=["Out"])
def pixel_shuffle(ctx, attrs, X):
    """[N, C*r^2, H, W] -> [N, C, H*r, W*r] (pixel_shuffle_op.cc)."""
    r = int(attrs.get("upscale_factor", 1))
    n, c, h, w = X.shape
    x = X.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, c // (r * r), h * r, w * r)


@register_op("shuffle_channel", inputs=["X"], outputs=["Out"])
def shuffle_channel(ctx, attrs, X):
    """Group-interleave channels (shuffle_channel_op.cc)."""
    g = int(attrs.get("group", 1))
    n, c, h, w = X.shape
    x = X.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
    return x.reshape(n, c, h, w)


@register_op("space_to_depth", inputs=["X"], outputs=["Out"])
def space_to_depth(ctx, attrs, X):
    """[N,C,H,W] -> [N, C*b^2, H/b, W/b] (space_to_depth_op.cc)."""
    b = int(attrs.get("blocksize", 1))
    n, c, h, w = X.shape
    x = X.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("temporal_shift", inputs=["X"], outputs=["Out"])
def temporal_shift(ctx, attrs, X):
    """[N*T, C, H, W]: shift the first fold of channels backward in time,
    the second fold forward, keep the rest (temporal_shift_op.cc)."""
    t = int(attrs.get("seg_num", 1))
    ratio = float(attrs.get("shift_ratio", 0.25))
    nt, c, h, w = X.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    x = X.reshape(n, t, c, h, w)
    pad = jnp.zeros((n, 1, c, h, w), X.dtype)
    slow = jnp.concatenate([x[:, 1:, :c1], pad[:, :, :c1]], axis=1)
    fast = jnp.concatenate([pad[:, :, c1:c2], x[:, :-1, c1:c2]], axis=1)
    keep = x[:, :, c2:]
    out = jnp.concatenate([slow, fast, keep], axis=2)
    return out.reshape(nt, c, h, w)


@register_op("affine_channel", inputs=["X", "Scale", "Bias"],
             outputs=["Out"])
def affine_channel(ctx, attrs, X, Scale, Bias):
    """x*scale[C]+bias[C] per channel (affine_channel_op.cc); NCHW/NHWC."""
    layout = attrs.get("data_layout", "NCHW")
    if layout == "NCHW":
        shape = (1, -1) + (1,) * (X.ndim - 2)
    else:
        shape = (1,) * (X.ndim - 1) + (-1,)
    return X * Scale.reshape(shape) + Bias.reshape(shape)


@register_op("crop", inputs=["X", "Y", "Offsets"], outputs=["Out"])
def crop(ctx, attrs, X, Y, Offsets):
    """Static crop to `shape` at `offsets` (crop_op.cc); Y supplies the
    target shape when given."""
    shape = [int(s) for s in attrs.get("shape", [])] if Y is None \
        else list(Y.shape)
    if Offsets is not None:
        offsets = [int(o) for o in jnp.ravel(Offsets)] \
            if not hasattr(Offsets, "aval") else None
        if offsets is None:
            # traced offsets: dynamic_slice
            starts = jnp.ravel(Offsets).astype(jnp.int32)
            return jax.lax.dynamic_slice(
                X, [starts[i] for i in range(X.ndim)], shape)
    else:
        offsets = [int(o) for o in attrs.get("offsets", [0] * X.ndim)]
    sl = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return X[sl]


@register_op("pad_constant_like", inputs=["X", "Y"], outputs=["Out"])
def pad_constant_like(ctx, attrs, X, Y):
    """Pad Y at the high end of every dim up to X's shape
    (pad_constant_like_op.cc)."""
    val = float(attrs.get("pad_value", 0.0))
    pads = [(0, int(xs - ys)) for xs, ys in zip(X.shape, Y.shape)]
    return jnp.pad(Y, pads, constant_values=jnp.asarray(val, Y.dtype))


@register_op("random_crop", inputs=["X", "Seed"], outputs=["Out", "SeedOut"],
             no_grad=True, stateful_outputs=("SeedOut",))
def random_crop(ctx, attrs, X, Seed):
    """Uniform-offset crop of the trailing dims to `shape`
    (random_crop_op.h); the leading (batch) dims are kept."""
    shape = [int(s) for s in attrs["shape"]]
    k = len(shape)
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        dim = X.shape[X.ndim - k + i]
        key, sub = jax.random.split(key)
        starts.append(
            jax.random.randint(sub, (), 0, dim - s + 1, jnp.int32))
    full_starts = [jnp.zeros((), jnp.int32)] * (X.ndim - k) + starts
    out = jax.lax.dynamic_slice(
        X, full_starts, list(X.shape[: X.ndim - k]) + shape)
    seed_out = Seed if Seed is not None else jnp.zeros((1,), jnp.int64)
    return {"Out": out, "SeedOut": seed_out}


@register_op("maxout", inputs=["X"], outputs=["Out"])
def maxout(ctx, attrs, X):
    """[N,C,H,W] -> [N, C/groups, H, W], max across each channel group
    (math/maxouting.cc: out[c] = max_g in[c*groups+g])."""
    g = int(attrs.get("groups", 1))
    n, c, h, w = X.shape
    return jnp.max(X.reshape(n, c // g, g, h, w), axis=2)


@register_op("lrn", inputs=["X"], outputs=["Out", "MidOut"],
             stateful_outputs=("MidOut",))
def lrn(ctx, attrs, X):
    """Across-channel local response norm (lrn_op.cc):
    mid = k + alpha * sum_{window n} x^2 ; out = x * mid^-beta."""
    n = int(attrs.get("n", 5))
    k = float(attrs.get("k", 2.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    sq = jnp.square(X)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    window = sum(pad[:, i:i + X.shape[1]] for i in range(n))
    mid = k + alpha * window
    return {"Out": X * jnp.power(mid, -beta), "MidOut": mid}


@register_op("fsp", inputs=["X", "Y"], outputs=["Out"])
def fsp(ctx, attrs, X, Y):
    """FSP matrix for distillation (fsp_op.cc):
    out[b,i,j] = (1/HW) sum_hw X[b,i,h,w] * Y[b,j,h,w]."""
    b, c1, h, w = X.shape
    c2 = Y.shape[1]
    xf = X.reshape(b, c1, h * w)
    yf = Y.reshape(b, c2, h * w)
    return jnp.einsum("bik,bjk->bij", xf, yf) / jnp.asarray(
        h * w, X.dtype)


def _bilinear_sample(x, gx, gy, align_corners=True):
    """Sample NCHW `x` at normalized [-1,1] grid coords (gx, gy) [N,Ho,Wo]
    with zero padding outside — grid_sampler_op.cc convention."""
    n, c, h, w = x.shape
    if align_corners:
        fx = (gx + 1) * 0.5 * (w - 1)
        fy = (gy + 1) * 0.5 * (h - 1)
    else:
        fx = ((gx + 1) * w - 1) * 0.5
        fy = ((gy + 1) * h - 1) * 0.5
    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    dx = fx - x0
    dy = fy - y0

    def gather(yi, xi):
        valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = x[jnp.arange(n)[:, None, None], :, yc, xc]  # [N,Ho,Wo,C]
        return jnp.where(valid[..., None], v, 0.0)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    dx = dx[..., None]
    dy = dy[..., None]
    out = (v00 * (1 - dx) * (1 - dy) + v01 * dx * (1 - dy)
           + v10 * (1 - dx) * dy + v11 * dx * dy)
    return jnp.moveaxis(out, -1, 1)  # [N,C,Ho,Wo]


@register_op("grid_sampler", inputs=["X", "Grid"], outputs=["Output"])
def grid_sampler(ctx, attrs, X, Grid):
    """Bilinear sampling of X [N,C,H,W] at Grid [N,Ho,Wo,2] (x,y in
    [-1,1]), zeros outside (grid_sampler_op.cc, align_corners=True)."""
    return _bilinear_sample(X, Grid[..., 0], Grid[..., 1])


@register_op("affine_grid", inputs=["Theta"], outputs=["Output"])
def affine_grid(ctx, attrs, Theta):
    """2x3 affine params -> sampling grid [N,H,W,2] (affine_grid_op.cc,
    align_corners semantics of the reference: linspace over [-1,1])."""
    n, c, h, w = [int(v) for v in attrs["output_shape"]]
    ys = jnp.linspace(-1.0, 1.0, h, dtype=Theta.dtype)
    xs = jnp.linspace(-1.0, 1.0, w, dtype=Theta.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
    out = jnp.einsum("hwk,njk->nhwj", base, Theta)  # [N,H,W,2]
    return out


def _roi_regions(rois, spatial_scale, pooled_h, pooled_w, hin, win,
                 round_mode):
    """Per-ROI bin boundaries (roi_pool_op.cc integer arithmetic)."""
    x1 = jnp.round(rois[:, 0] * spatial_scale)
    y1 = jnp.round(rois[:, 1] * spatial_scale)
    x2 = jnp.round(rois[:, 2] * spatial_scale)
    y2 = jnp.round(rois[:, 3] * spatial_scale)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    bin_h = rh / pooled_h
    bin_w = rw / pooled_w
    return x1, y1, bin_h, bin_w


@register_op("roi_pool", inputs=["X", "ROIs", "RoisLod"],
             outputs=["Out", "Argmax"], stateful_outputs=("Argmax",))
def roi_pool(ctx, attrs, X, ROIs, RoisLod):
    """Max-pool each ROI bin (roi_pool_op.cc).  ROIs: [R, 4] boxes plus a
    batch-index column convention: here RoisLod (or a 5-col ROIs with
    leading batch id) maps each ROI to its image; TPU-static via a dense
    per-bin mask-max over the feature map."""
    scale = float(attrs.get("spatial_scale", 1.0))
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    if ROIs.shape[-1] == 5:
        batch_idx = ROIs[:, 0].astype(jnp.int32)
        boxes = ROIs[:, 1:]
    else:
        batch_idx = (jnp.zeros((ROIs.shape[0],), jnp.int32)
                     if RoisLod is None
                     else RoisLod.astype(jnp.int32)[: ROIs.shape[0]])
        boxes = ROIs
    n, c, h, w = X.shape
    r = boxes.shape[0]
    x1, y1, bin_h, bin_w = _roi_regions(boxes, scale, ph, pw, h, w, "round")

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)
    # bin start/end per (roi, bin): floor/ceil as in the reference
    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    hstart = jnp.floor(y1[:, None] + iy[None, :] * bin_h[:, None])
    hend = jnp.ceil(y1[:, None] + (iy[None, :] + 1) * bin_h[:, None])
    wstart = jnp.floor(x1[:, None] + ix[None, :] * bin_w[:, None])
    wend = jnp.ceil(x1[:, None] + (ix[None, :] + 1) * bin_w[:, None])
    hstart = jnp.clip(hstart, 0, h)
    hend = jnp.clip(hend, 0, h)
    wstart = jnp.clip(wstart, 0, w)
    wend = jnp.clip(wend, 0, w)
    # mask [R, ph, H] / [R, pw, W]
    hmask = ((ys[None, None, :] >= hstart[:, :, None])
             & (ys[None, None, :] < hend[:, :, None]))
    wmask = ((xs[None, None, :] >= wstart[:, :, None])
             & (xs[None, None, :] < wend[:, :, None]))
    feats = X[batch_idx]  # [R, C, H, W]
    neg = jnp.asarray(-3.4e38, X.dtype)
    # separable masked max (static ph/pw loops): reduce H per bin-row,
    # then W per bin-col — peak intermediate [R,C,H,W], not
    # [R,C,ph,pw,H,W]
    hred = jnp.stack([
        jnp.max(jnp.where(hmask[:, i, None, :, None], feats, neg), axis=2)
        for i in range(ph)], axis=2)                   # [R,C,ph,W]
    out = jnp.stack([
        jnp.max(jnp.where(wmask[:, j, None, None, :], hred, neg), axis=-1)
        for j in range(pw)], axis=3)                   # [R,C,ph,pw]
    empty = (jnp.sum(hmask, 2)[:, None, :, None] *
             jnp.sum(wmask, 2)[:, None, None, :]) == 0
    out = jnp.where(empty, jnp.zeros_like(out), out)
    return {"Out": out, "Argmax": jnp.zeros(out.shape, jnp.int64)}


@register_op("psroi_pool", inputs=["X", "ROIs"], outputs=["Out"])
def psroi_pool(ctx, attrs, X, ROIs):
    """Position-sensitive ROI average pool (psroi_pool_op.cc): input
    channels C = out_c * ph * pw; bin (i,j) of output channel k averages
    input channel k*ph*pw + i*pw + j inside the bin."""
    scale = float(attrs.get("spatial_scale", 1.0))
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    out_c = int(attrs.get("output_channels"))
    if ROIs.shape[-1] == 5:
        batch_idx = ROIs[:, 0].astype(jnp.int32)
        boxes = ROIs[:, 1:]
    else:
        batch_idx = jnp.zeros((ROIs.shape[0],), jnp.int32)
        boxes = ROIs
    n, c, h, w = X.shape
    r = boxes.shape[0]
    x1 = boxes[:, 0] * scale
    y1 = boxes[:, 1] * scale
    x2 = boxes[:, 2] * scale
    y2 = boxes[:, 3] * scale
    rh = jnp.maximum(y2 - y1, 0.1)
    rw = jnp.maximum(x2 - x1, 0.1)
    bin_h = rh / ph
    bin_w = rw / pw
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)
    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    hstart = jnp.floor(y1[:, None] + iy[None, :] * bin_h[:, None])
    hend = jnp.ceil(y1[:, None] + (iy[None, :] + 1) * bin_h[:, None])
    wstart = jnp.floor(x1[:, None] + ix[None, :] * bin_w[:, None])
    wend = jnp.ceil(x1[:, None] + (ix[None, :] + 1) * bin_w[:, None])
    hstart = jnp.clip(hstart, 0, h)
    hend = jnp.clip(hend, 0, h)
    wstart = jnp.clip(wstart, 0, w)
    wend = jnp.clip(wend, 0, w)
    hmask = ((ys[None, None, :] >= hstart[:, :, None])
             & (ys[None, None, :] < hend[:, :, None])).astype(X.dtype)
    wmask = ((xs[None, None, :] >= wstart[:, :, None])
             & (xs[None, None, :] < wend[:, :, None])).astype(X.dtype)
    feats = X[batch_idx].reshape(r, out_c, ph, pw, h, w)
    # separable masked sum: einsum contracts H then W per bin without a
    # [R,out_c,ph,pw,H,W] mask product
    s = jnp.einsum("rkijhw,rih,rjw->rkij", feats, hmask, wmask)
    area = jnp.maximum(
        jnp.sum(hmask, 2)[:, None, :, None]
        * jnp.sum(wmask, 2)[:, None, None, :], 1.0)
    return s / area


@register_op("unfold", inputs=["X"], outputs=["Y"])
def unfold(ctx, attrs, X):
    """im2col (unfold_op.cc): [N,C,H,W] -> [N, C*kh*kw, L]."""
    kh, kw = [int(v) for v in attrs["kernel_sizes"]]
    sh, sw = [int(v) for v in attrs.get("strides", [1, 1])]
    pads = [int(v) for v in attrs.get("paddings", [0, 0, 0, 0])]
    dh, dw = [int(v) for v in attrs.get("dilations", [1, 1])]
    n, c, h, w = X.shape
    x = jnp.pad(X, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    hp, wp = x.shape[2], x.shape[3]
    oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(
                x[:, :, i * dh:i * dh + oh * sh:sh,
                  j * dw:j * dw + ow * sw:sw])
    out = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
    return out.reshape(n, c * kh * kw, oh * ow)


@register_op("deformable_conv", inputs=["Input", "Offset", "Mask", "Filter"],
             outputs=["Output"])
def deformable_conv(ctx, attrs, Input, Offset, Mask, Filter):
    """Modulated deformable conv v2 (deformable_conv_op.cu): for each
    kernel tap (ki,kj), bilinear-sample the input at
    base + dilation placement + learned offset, scale by the modulation
    mask, then contract taps x channels with the filter.  Static loops
    over the (small) kernel; the sampling is a batched gather — no host
    loops, MXU does the final contraction."""
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1])]
    groups = int(attrs.get("groups", 1) or 1)
    dg = int(attrs.get("deformable_groups", 1) or 1)
    n, c, h, w = Input.shape
    m, c_g, kh, kw = Filter.shape
    oh = (h + 2 * pads[0] - (dil[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (w + 2 * pads[1] - (dil[1] * (kw - 1) + 1)) // strides[1] + 1
    # offset layout: [N, dg*2*kh*kw, OH, OW], channel 2t = y_t, 2t+1 = x_t
    # per tap (deformable_conv_op.cu modulated_deformable_im2col)
    off = Offset.reshape(n, dg, kh * kw, 2, oh, ow)
    msk = (Mask.reshape(n, dg, kh * kw, oh, ow)
           if Mask is not None else None)
    base_y = (jnp.arange(oh) * strides[0] - pads[0])[None, :, None]
    base_x = (jnp.arange(ow) * strides[1] - pads[1])[None, None, :]
    cpg = c // dg  # channels per deformable group
    taps = []
    for t in range(kh * kw):
        ki, kj = t // kw, t % kw
        group_feats = []
        for g in range(dg):
            py = (base_y + ki * dil[0] + off[:, g, t, 0]).astype(jnp.float32)
            px = (base_x + kj * dil[1] + off[:, g, t, 1]).astype(jnp.float32)
            # normalize to [-1, 1] for the shared bilinear sampler
            gx = 2.0 * px / jnp.maximum(w - 1, 1) - 1.0
            gy = 2.0 * py / jnp.maximum(h - 1, 1) - 1.0
            v = _bilinear_sample(
                Input[:, g * cpg:(g + 1) * cpg], gx, gy)  # [N,cpg,OH,OW]
            if msk is not None:
                v = v * msk[:, g, t][:, None]
            group_feats.append(v)
        taps.append(jnp.concatenate(group_feats, axis=1))  # [N,C,OH,OW]
    col = jnp.stack(taps, axis=2)  # [N, C, kh*kw, OH, OW]
    col = col.reshape(n, c, kh * kw, oh, ow)
    if groups == 1:
        return jnp.einsum("nckhw,mck->nmhw", col,
                          Filter.reshape(m, c, kh * kw))
    # grouped contraction (deformable_conv_op InferShape: Filter is
    # [M, C/g, kh, kw]; output channel block gi reads channel block gi)
    cg, mg = c // groups, m // groups
    return jnp.concatenate(
        [jnp.einsum("nckhw,mck->nmhw",
                    col[:, gi * cg:(gi + 1) * cg],
                    Filter[gi * mg:(gi + 1) * mg].reshape(mg, cg, kh * kw))
         for gi in range(groups)], axis=1)


@register_op("deformable_psroi_pooling",
             inputs=["Input", "ROIs", "Trans"],
             outputs=["Output", "TopCount"], stateful_outputs=("TopCount",))
def deformable_psroi_pooling(ctx, attrs, Input, ROIs, Trans):
    """Deformable position-sensitive ROI pooling
    (deformable_psroi_pooling_op.cu): each bin's sampling window is
    shifted by a learned normalized offset (Trans [R, 2, ph, pw]) scaled
    by trans_std and the ROI extent; average-pool the shifted bin from
    the bin's position-sensitive channel group."""
    scale = float(attrs.get("spatial_scale", 1.0))
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    out_c = int(attrs.get("output_dim"))
    trans_std = float(attrs.get("trans_std", 0.1))
    sample_per_part = int(attrs.get("sample_per_part", 4))
    no_trans = bool(attrs.get("no_trans", False))
    if ROIs.shape[-1] == 5:
        batch_idx = ROIs[:, 0].astype(jnp.int32)
        boxes = ROIs[:, 1:]
    else:
        batch_idx = jnp.zeros((ROIs.shape[0],), jnp.int32)
        boxes = ROIs
    n, c, h, w = Input.shape
    r = boxes.shape[0]
    x1 = boxes[:, 0] * scale - 0.5
    y1 = boxes[:, 1] * scale - 0.5
    x2 = (boxes[:, 2] + 1.0) * scale - 0.5
    y2 = (boxes[:, 3] + 1.0) * scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_w = rw / pw
    bin_h = rh / ph
    if c == out_c * ph * pw:
        # position-sensitive layout: bin (i,j) samples its own channel
        # group
        feats = Input[batch_idx].reshape(r, out_c, ph, pw, h, w)
    elif c == out_c:
        # plain deformable ROI pooling: every bin samples all channels
        feats = jnp.broadcast_to(
            Input[batch_idx][:, :, None, None],
            (r, out_c, ph, pw, h, w))
    else:
        raise ValueError(
            "deformable_psroi_pooling: channels %d fit neither the "
            "position-sensitive (out_c*ph*pw=%d) nor plain (out_c=%d) "
            "layout" % (c, out_c * ph * pw, out_c))
    if Trans is not None and not no_trans:
        tr = Trans.reshape(r, 2, ph, pw) * trans_std
        dy = tr[:, 0] * rh[:, None, None]
        dx = tr[:, 1] * rw[:, None, None]
    else:
        dy = jnp.zeros((r, ph, pw))
        dx = jnp.zeros((r, ph, pw))
    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    # sample_per_part^2 bilinear samples per bin, averaged
    sub = (jnp.arange(sample_per_part, dtype=jnp.float32) + 0.5) \
        / sample_per_part
    ys = (y1[:, None, None, None] + iy[None, :, None, None]
          * bin_h[:, None, None, None]
          + sub[None, None, None, :] * bin_h[:, None, None, None]
          + dy[:, :, :, None])  # [R, ph, pw, S]
    xs = (x1[:, None, None, None] + ix[None, None, :, None]
          * bin_w[:, None, None, None]
          + sub[None, None, None, :] * bin_w[:, None, None, None]
          + dx[:, :, :, None])
    acc = jnp.zeros((r, out_c, ph, pw))
    for sy in range(sample_per_part):
        for sx in range(sample_per_part):
            gy = 2.0 * ys[..., sy] / jnp.maximum(h - 1, 1) - 1.0
            gx = 2.0 * xs[..., sx] / jnp.maximum(w - 1, 1) - 1.0
            # sample each bin's own channel group: flatten bins into the
            # batch to reuse the NCHW sampler per (i,j)
            for i in range(ph):
                for j in range(pw):
                    v = _bilinear_sample(
                        feats[:, :, i, j], gx[:, i, j][:, None, None],
                        gy[:, i, j][:, None, None])  # [R,out_c,1,1]
                    acc = acc.at[:, :, i, j].add(v[:, :, 0, 0])
    out = acc / float(sample_per_part * sample_per_part)
    return {"Output": out,
            "TopCount": jnp.ones((r, out_c, ph, pw), jnp.float32)}
