"""Creation / casting / misc ops.

Reference kernels: ``paddle/fluid/operators/fill_constant_op.cc``,
``gaussian_random_op.cc``, ``uniform_random_op.cc``, ``cast_op.cc``,
``scale_op.cc``, ``sum_op.cc``, ``assign_op.cc`` — here each is a few lines of
jnp lowered into the block's jaxpr.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op
from .common import resolve_dtype


@register_op("fill_constant", inputs=[], outputs=["Out"], no_grad=True)
def fill_constant(ctx, attrs):
    dtype = resolve_dtype(attrs.get("dtype", "float32"))
    shape = tuple(int(s) for s in attrs.get("shape", []))
    return jnp.full(shape, attrs.get("value", 0.0), dtype=dtype)


@register_op("fill_constant_batch_size_like", inputs=["Input"], outputs=["Out"],
             no_grad=True)
def fill_constant_batch_size_like(ctx, attrs, Input):
    dtype = resolve_dtype(attrs.get("dtype", "float32"))
    shape = [int(s) for s in attrs.get("shape", [])]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = jnp.shape(Input)[in_idx]
    return jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dtype)


@register_op("fill_any_like", inputs=["X"], outputs=["Out"], no_grad=True)
def fill_any_like(ctx, attrs, X):
    dtype = attrs.get("dtype", -1)
    dt = jnp.result_type(X) if dtype in (-1, None) else resolve_dtype(dtype)
    return jnp.full(jnp.shape(X), attrs.get("value", 0.0), dtype=dt)


@register_op("fill_zeros_like", inputs=["X"], outputs=["Out"], no_grad=True)
def fill_zeros_like(ctx, attrs, X):
    return jnp.zeros_like(X)


@register_op("gaussian_random", inputs=[], outputs=["Out"], no_grad=True)
def gaussian_random(ctx, attrs):
    dtype = resolve_dtype(attrs.get("dtype", "float32"))
    shape = tuple(int(s) for s in attrs.get("shape", []))
    seed = int(attrs.get("seed", 0))
    key = jax.random.key(seed) if seed else ctx.rng()
    return (
        attrs.get("mean", 0.0)
        + attrs.get("std", 1.0) * jax.random.normal(key, shape)
    ).astype(dtype)


@register_op("uniform_random", inputs=[], outputs=["Out"], no_grad=True)
def uniform_random(ctx, attrs):
    dtype = resolve_dtype(attrs.get("dtype", "float32"))
    shape = tuple(int(s) for s in attrs.get("shape", []))
    seed = int(attrs.get("seed", 0))
    key = jax.random.key(seed) if seed else ctx.rng()
    return jax.random.uniform(
        key, shape, dtype=jnp.float32,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0),
    ).astype(dtype)


@register_op("truncated_gaussian_random", inputs=[], outputs=["Out"], no_grad=True)
def truncated_gaussian_random(ctx, attrs):
    dtype = resolve_dtype(attrs.get("dtype", "float32"))
    shape = tuple(int(s) for s in attrs.get("shape", []))
    seed = int(attrs.get("seed", 0))
    key = jax.random.key(seed) if seed else ctx.rng()
    std = attrs.get("std", 1.0)
    mean = attrs.get("mean", 0.0)
    return (
        mean + std * jax.random.truncated_normal(key, -2.0, 2.0, shape)
    ).astype(dtype)


@register_op("randint", inputs=[], outputs=["Out"], no_grad=True)
def randint(ctx, attrs):
    shape = tuple(int(s) for s in attrs.get("shape", []))
    seed = int(attrs.get("seed", 0))
    key = jax.random.key(seed) if seed else ctx.rng()
    dtype = resolve_dtype(attrs.get("dtype", "int64"))
    return jax.random.randint(
        key, shape, attrs.get("low", 0), attrs.get("high", 100)
    ).astype(dtype)


@register_op("assign", inputs=["X"], outputs=["Out"])
def assign(ctx, attrs, X):
    return X


@register_op("assign_value", inputs=[], outputs=["Out"], no_grad=True)
def assign_value(ctx, attrs):
    import numpy as np

    values = attrs.get("values")
    if values is None:  # reference attr spelling: fp32_values / int32_values
        values = attrs.get("fp32_values", attrs.get("int32_values"))
    arr = np.asarray(values).reshape(tuple(int(s) for s in attrs["shape"]))
    return jnp.asarray(arr).astype(resolve_dtype(attrs.get("dtype", arr.dtype)))


@register_op("share_data", inputs=["X"], outputs=["Out"])
def share_data(ctx, attrs, X):
    return X


@register_op("cast", inputs=["X"], outputs=["Out"])
def cast(ctx, attrs, X):
    return X.astype(resolve_dtype(attrs.get("out_dtype", "float32")))


@register_op("scale", inputs=["X"], outputs=["Out"])
def scale(ctx, attrs, X):
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return X * jnp.asarray(s, X.dtype) + jnp.asarray(b, X.dtype)
    return (X + jnp.asarray(b, X.dtype)) * jnp.asarray(s, X.dtype)


@register_op("sum", inputs=["X*"], outputs=["Out"])
def sum_op(ctx, attrs, X):
    out = X[0]
    for x in X[1:]:
        out = out + x
    return out


@register_op("shape", inputs=["Input"], outputs=["Out"], no_grad=True)
def shape_op(ctx, attrs, Input):
    return jnp.asarray(jnp.shape(Input), dtype=jnp.int32)


@register_op("increment", inputs=["X"], outputs=["Out"], no_grad=True)
def increment(ctx, attrs, X):
    return X + jnp.asarray(attrs.get("step", 1.0), X.dtype)


@register_op("clip", inputs=["X"], outputs=["Out"])
def clip(ctx, attrs, X):
    return jnp.clip(X, attrs.get("min"), attrs.get("max"))


@register_op("clip_by_norm", inputs=["X"], outputs=["Out"])
def clip_by_norm(ctx, attrs, X):
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(X)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return X * scale.astype(X.dtype)


@register_op("squared_l2_norm", inputs=["X"], outputs=["Out"])
def squared_l2_norm(ctx, attrs, X):
    return jnp.sum(jnp.square(X)).reshape(1)


@register_op("isfinite", inputs=["X*"], outputs=["Out"], no_grad=True)
def isfinite(ctx, attrs, X):
    ok = jnp.array(True)
    for x in X:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return ok.reshape(1)


@register_op("isinf", inputs=["X*"], outputs=["Out"], no_grad=True)
def isinf(ctx, attrs, X):
    hit = jnp.array(False)
    for x in X:
        hit = jnp.logical_or(hit, jnp.any(jnp.isinf(x)))
    return hit.reshape(1)


@register_op("isnan", inputs=["X*"], outputs=["Out"], no_grad=True)
def isnan(ctx, attrs, X):
    hit = jnp.array(False)
    for x in X:
        hit = jnp.logical_or(hit, jnp.any(jnp.isnan(x)))
    return hit.reshape(1)


def _infer_range_shape(op, block):
    out = block._find_var_recursive(op.outputs["Out"][0])
    if out is None:
        return
    a = op.attrs
    if all(k in a for k in ("start", "end", "step")) and a["step"]:
        import math

        n = max(0, math.ceil((a["end"] - a["start"]) / a["step"]))
        out.shape = (n,)


@register_op("range", inputs=["Start", "End", "Step"], outputs=["Out"],
             no_grad=True, infer_shape=_infer_range_shape)
def range_op(ctx, attrs, Start=None, End=None, Step=None):
    # XLA requires static shapes, so the bounds must be trace-time
    # constants: taken from attrs (set by layers.range for python scalars)
    # or from concrete (non-traced) input arrays
    import numpy as np

    def _const(v, attr, default=None):
        if attr in attrs:
            return float(attrs[attr])
        if v is None:
            return default
        try:
            return float(np.asarray(v).reshape(()))
        except Exception:
            raise ValueError(
                "range op bounds must be static on TPU (python scalars or "
                "constants); got a traced tensor for %r" % attr
            )

    s = _const(Start, "start", 0.0)
    e = _const(End, "end")
    st = _const(Step, "step", 1.0)
    from .common import resolve_dtype

    dt = resolve_dtype(attrs["dtype"]) if "dtype" in attrs else jnp.float32
    return jnp.arange(s, e, st, dtype=dt)


@register_op("feed", inputs=["X"], outputs=["Out"], no_grad=True)
def feed(ctx, attrs, X):
    return X


@register_op("fetch", inputs=["X"], outputs=["Out"], no_grad=True)
def fetch(ctx, attrs, X):
    return X


def _linspace_infer_shape(op, block):
    num = op.attr("num")
    if num is not None:
        v = block._find_var_recursive(op.output("Out")[0])
        if v is not None:
            v.shape = (int(num),)
    # Variable Num: length unknown until lowering — leave declared shape


@register_op("linspace", inputs=["Start", "Stop", "Num"], outputs=["Out"],
             no_grad=True, infer_shape=_linspace_infer_shape)
def linspace(ctx, attrs, Start, Stop, Num=None):
    """Evenly spaced values (reference ``linspace_op.cc``: Start/Stop/Num
    arrive as 1-element tensors).  XLA needs a static output length, so
    Num must be a compile-time constant: either the ``num`` attr (set by
    ``layers.linspace``) or a concrete (untraced) Num input."""
    num = attrs.get("num")
    if num is None:
        if Num is None:
            raise ValueError("linspace needs the num attr or a Num input")
        try:
            num = int(np.asarray(Num).reshape(()))
        except Exception:
            raise ValueError(
                "linspace Num must be compile-time constant on TPU "
                "(dynamic output shapes are not XLA-compatible); pass "
                "num as a python int so it lands in the num attr")
    start = jnp.reshape(Start, ())
    stop = jnp.reshape(Stop, ())
    return jnp.linspace(start, stop, int(num), dtype=Start.dtype)
