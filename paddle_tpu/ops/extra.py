"""Remaining op-parity batch: simple losses/math, pooling-with-index,
unpool, SPP, interpolation aliases, fused compositions, debug print.

Reference kernels: ``paddle/fluid/operators/{hinge_loss,modified_huber_loss,
l1_norm,squared_l2_distance,minus,fill,diag,is_empty,cross_entropy2,norm,
conv_shift,cos_sim,pool_with_index,unpool,spp,interpolate,print}_op.*`` and
``operators/fused/*``.  The fused family lowers to compositions — XLA's
fusion pass IS the fused kernel on TPU."""

import jax
import jax.numpy as jnp

from .registry import register_op


# ---- simple losses / math ----------------------------------------------

@register_op("hinge_loss", inputs=["Logits", "Labels"], outputs=["Loss"])
def hinge_loss(ctx, attrs, Logits, Labels):
    """max(0, 1 - (2y-1) * logit) (hinge_loss_op.h)."""
    return jnp.maximum(0.0, 1.0 - (2.0 * Labels - 1.0) * Logits)


@register_op("modified_huber_loss", inputs=["X", "Y"],
             outputs=["Out", "IntermediateVal"],
             stateful_outputs=("IntermediateVal",))
def modified_huber_loss(ctx, attrs, X, Y):
    """Modified Huber for classification (modified_huber_loss_op.h):
    z = (2y-1)*x; z >= -1: max(0,1-z)^2 ; else -4z."""
    z = (2.0 * Y - 1.0) * X
    loss = jnp.where(z >= -1.0, jnp.square(jnp.maximum(0.0, 1.0 - z)),
                     -4.0 * z)
    return {"Out": loss, "IntermediateVal": z}


@register_op("l1_norm", inputs=["X"], outputs=["Out"])
def l1_norm(ctx, attrs, X):
    return jnp.sum(jnp.abs(X))


@register_op("squared_l2_distance", inputs=["X", "Y"],
             outputs=["Out", "sub_result"], stateful_outputs=("sub_result",))
def squared_l2_distance(ctx, attrs, X, Y):
    sub = X - Y
    return {"Out": jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                           keepdims=True)[:, :1],
            "sub_result": sub}


@register_op("minus", inputs=["X", "Y"], outputs=["Out"])
def minus(ctx, attrs, X, Y):
    return X - Y


@register_op("fill", inputs=[], outputs=["Out"], no_grad=True)
def fill(ctx, attrs, **kw):
    from .common import resolve_dtype

    shape = [int(s) for s in attrs["shape"]]
    value = attrs.get("value", [0.0])
    dtype = resolve_dtype(attrs.get("dtype", 5))
    import numpy as np

    return jnp.asarray(np.asarray(value, dtype).reshape(shape))


@register_op("diag", inputs=["Diagonal"], outputs=["Out"], no_grad=True)
def diag(ctx, attrs, Diagonal):
    return jnp.diag(jnp.ravel(Diagonal))


@register_op("is_empty", inputs=["X"], outputs=["Out"], no_grad=True)
def is_empty(ctx, attrs, X):
    return jnp.asarray([X.size == 0])


@register_op("cross_entropy2", inputs=["X", "Label"],
             outputs=["Y", "XShape", "MatchX"],
             stateful_outputs=("XShape", "MatchX"))
def cross_entropy2(ctx, attrs, X, Label):
    """Hard-label cross entropy keeping the matched probability
    (cross_entropy2_op.cc; used by softmax+CE decompositions)."""
    lab = Label
    if lab.ndim == X.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    lab = lab.astype(jnp.int32)
    ignore_index = int(attrs.get("ignore_index", -100))
    picked = jnp.take_along_axis(
        X, jnp.maximum(lab, 0)[..., None], axis=-1)[..., 0]
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    loss = jnp.where(lab == ignore_index, 0.0, loss)
    return {"Y": loss[..., None], "XShape": jnp.zeros((1,), jnp.int32),
            "MatchX": picked[..., None]}


@register_op("norm", inputs=["X"], outputs=["Out", "Norm"],
             stateful_outputs=("Norm",))
def norm(ctx, attrs, X):
    """L2-normalize along `axis` (norm_op.h)."""
    from .common import normalize_axis

    axis = normalize_axis(int(attrs.get("axis", 1)), X.ndim)
    eps = float(attrs.get("epsilon", 1e-10))
    n = jnp.sqrt(jnp.sum(jnp.square(X), axis=axis, keepdims=True) + eps)
    return {"Out": X / n, "Norm": n}


@register_op("conv_shift", inputs=["X", "Y"], outputs=["Out"])
def conv_shift(ctx, attrs, X, Y):
    """Circular correlation (conv_shift_op.cc): X [B,M], Y [B,N] (N odd,
    N <= M); out[b,i] = sum_j x[b, (i+j-N/2) mod M] * y[b,j]."""
    B, M = X.shape
    N = Y.shape[1]
    half = N // 2
    outs = []
    for j in range(N):
        outs.append(jnp.roll(X, half - j, axis=1) * Y[:, j:j + 1])
    return sum(outs)


@register_op("cos_sim", inputs=["X", "Y"],
             outputs=["Out", "XNorm", "YNorm"],
             stateful_outputs=("XNorm", "YNorm"))
def cos_sim_op(ctx, attrs, X, Y):
    """Row-wise cosine similarity (cos_sim_op.h); Y may be [1, D]."""
    xn = jnp.sqrt(jnp.sum(jnp.square(X), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(Y), axis=1, keepdims=True))
    dot = jnp.sum(X * Y, axis=1, keepdims=True)
    return {"Out": dot / jnp.maximum(xn * yn, 1e-12),
            "XNorm": xn, "YNorm": yn}


@register_op("fill_zeros_like2", inputs=["X"], outputs=["Out"],
             no_grad=True)
def fill_zeros_like2(ctx, attrs, X):
    return jnp.zeros_like(X)


@register_op("squared_l2_norm", inputs=["X"], outputs=["Out"])
def squared_l2_norm2(ctx, attrs, X):
    return jnp.sum(jnp.square(X)).reshape(1)


# ---- pooling with index / unpool / spp ---------------------------------

@register_op("max_pool2d_with_index", inputs=["X"],
             outputs=["Out", "Mask"], stateful_outputs=("Mask",))
def max_pool2d_with_index(ctx, attrs, X):
    """Max pool returning flat argmax indices (pool_with_index_op.cc)."""
    ksize = [int(k) for k in attrs.get("ksize", [2, 2])]
    strides = [int(s) for s in attrs.get("strides", ksize)]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    if attrs.get("global_pooling", False):
        ksize = list(X.shape[2:])
        strides = [1, 1]
        paddings = [0, 0]
    n, c, h, w = X.shape
    xp = jnp.pad(X, ((0, 0), (0, 0), (paddings[0], paddings[0]),
                     (paddings[1], paddings[1])),
                 constant_values=-jnp.inf)
    idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    idxp = jnp.pad(idx, ((0, 0), (0, 0), (paddings[0], paddings[0]),
                         (paddings[1], paddings[1])), constant_values=-1)
    oh = (h + 2 * paddings[0] - ksize[0]) // strides[0] + 1
    ow = (w + 2 * paddings[1] - ksize[1]) // strides[1] + 1
    windows = []
    wins_idx = []
    for i in range(ksize[0]):
        for j in range(ksize[1]):
            windows.append(
                xp[:, :, i:i + oh * strides[0]:strides[0],
                   j:j + ow * strides[1]:strides[1]])
            wins_idx.append(
                jnp.broadcast_to(
                    idxp[:, :, i:i + oh * strides[0]:strides[0],
                         j:j + ow * strides[1]:strides[1]],
                    (n, c, oh, ow)))
    stack = jnp.stack(windows, 0)       # [K, N, C, OH, OW]
    istack = jnp.stack(wins_idx, 0)
    arg = jnp.argmax(stack, axis=0)
    out = jnp.max(stack, axis=0)
    mask = jnp.take_along_axis(istack, arg[None], axis=0)[0]
    return {"Out": out, "Mask": mask.astype(jnp.int32)}


@register_op("max_pool3d_with_index", inputs=["X"],
             outputs=["Out", "Mask"], stateful_outputs=("Mask",))
def max_pool3d_with_index(ctx, attrs, X):
    """3-D max pool returning flat d*h*w argmax indices
    (pool_with_index_op.cc 3-D registration)."""
    ksize = [int(k) for k in attrs.get("ksize", [2, 2, 2])]
    strides = [int(s) for s in attrs.get("strides", ksize)]
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0])]
    if attrs.get("global_pooling", False):
        ksize = list(X.shape[2:])
        strides = [1, 1, 1]
        pads = [0, 0, 0]
    n, c, d, h, w = X.shape
    xp = jnp.pad(X, ((0, 0), (0, 0)) + tuple((p, p) for p in pads),
                 constant_values=-jnp.inf)
    idx = jnp.arange(d * h * w, dtype=jnp.float32).reshape(1, 1, d, h, w)
    idxp = jnp.pad(idx, ((0, 0), (0, 0)) + tuple((p, p) for p in pads),
                   constant_values=-1)
    od = (d + 2 * pads[0] - ksize[0]) // strides[0] + 1
    oh = (h + 2 * pads[1] - ksize[1]) // strides[1] + 1
    ow = (w + 2 * pads[2] - ksize[2]) // strides[2] + 1
    wins, wins_idx = [], []
    for i in range(ksize[0]):
        for j in range(ksize[1]):
            for k in range(ksize[2]):
                sl = (slice(None), slice(None),
                      slice(i, i + od * strides[0], strides[0]),
                      slice(j, j + oh * strides[1], strides[1]),
                      slice(k, k + ow * strides[2], strides[2]))
                wins.append(xp[sl])
                wins_idx.append(
                    jnp.broadcast_to(idxp[sl], (n, c, od, oh, ow)))
    stack = jnp.stack(wins, 0)
    istack = jnp.stack(wins_idx, 0)
    arg = jnp.argmax(stack, axis=0)
    out = jnp.max(stack, axis=0)
    mask = jnp.take_along_axis(istack, arg[None], axis=0)[0]
    return {"Out": out, "Mask": mask.astype(jnp.int32)}


@register_op("unpool", inputs=["X", "Indices"], outputs=["Out"])
def unpool(ctx, attrs, X, Indices):
    """Max unpooling (unpool_op.cc): scatter values back to the argmax
    positions recorded by max_pool2d_with_index."""
    out_h, out_w = [int(v) for v in attrs.get("unpooling_type_shape",
                                              attrs.get("output_size"))]
    n, c, h, w = X.shape
    flat = jnp.zeros((n, c, out_h * out_w), X.dtype)
    idx = Indices.reshape(n, c, h * w).astype(jnp.int32)
    vals = X.reshape(n, c, h * w)
    flat = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].add(v)))(
        flat, idx, vals)
    return flat.reshape(n, c, out_h, out_w)


@register_op("spp", inputs=["X"], outputs=["Out"])
def spp(ctx, attrs, X):
    """Spatial pyramid pooling (spp_op.cc): concat flattened adaptive
    pools at 1x1, 2x2, ... 2^(L-1) bins."""
    from .nn import _pool_nd

    levels = int(attrs.get("pyramid_height", 2))
    ptype = attrs.get("pooling_type", "max")
    n = X.shape[0]
    outs = []
    for l in range(levels):
        bins = 2 ** l
        pooled = _pool_nd({"pooling_type": ptype, "adaptive": True,
                           "ksize": [bins, bins]}, X, 2)
        outs.append(pooled.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


# ---- interpolation canonical names -------------------------------------

def _interp(ctx, attrs, X, OutSize, method):
    """Exact reference semantics (interpolate_op.h): ratio is
    (in-1)/(out-1) under align_corners else in/out; bilinear with
    align_mode=0 and no corner alignment uses half-pixel source coords
    (clamped at 0), otherwise src = ratio*k; nearest rounds under
    align_corners and truncates otherwise."""
    shape = attrs.get("out_shape") or [int(attrs.get("out_h")),
                                       int(attrs.get("out_w"))]
    oh, ow = int(shape[0]), int(shape[1])
    align = bool(attrs.get("align_corners", True))
    amode = int(attrs.get("align_mode", 1))
    n, c, h, w = X.shape

    def ratio(in_len, out_len):
        if out_len <= 1:
            return 0.0
        return ((in_len - 1) / (out_len - 1)) if align else in_len / out_len

    if method == "nearest":
        def near_idx(in_len, out_len):
            j = jnp.arange(out_len, dtype=jnp.float32) * ratio(in_len,
                                                               out_len)
            j = j + 0.5 if align else j
            return jnp.clip(j.astype(jnp.int32), 0, in_len - 1)

        return X[:, :, near_idx(h, oh)][:, :, :, near_idx(w, ow)]

    half_pixel = (amode == 0 and not align)

    def src(in_len, out_len):
        j = jnp.arange(out_len, dtype=jnp.float32)
        r = ratio(in_len, out_len)
        if half_pixel:
            return jnp.maximum(r * (j + 0.5) - 0.5, 0.0)
        return r * j

    fy, fx = src(h, oh), src(w, ow)
    y0 = jnp.clip(jnp.floor(fy).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(fx).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    dy = (fy - y0)[None, None, :, None]
    dx = (fx - x0)[None, None, None, :]

    def g(yy, xx):
        return X[:, :, yy][:, :, :, xx]

    top = g(y0, x0) * (1 - dx) + g(y0, x1) * dx
    bot = g(y1, x0) * (1 - dx) + g(y1, x1) * dx
    return top * (1 - dy) + bot * dy


@register_op("bilinear_interp", inputs=["X", "OutSize"], outputs=["Out"])
def bilinear_interp(ctx, attrs, X, OutSize):
    """interpolate_op.cc bilinear registration."""
    return _interp(ctx, attrs, X, OutSize, "bilinear")


@register_op("nearest_interp", inputs=["X", "OutSize"], outputs=["Out"])
def nearest_interp(ctx, attrs, X, OutSize):
    """interpolate_op.cc nearest registration."""
    return _interp(ctx, attrs, X, OutSize, "nearest")


# ---- debug print --------------------------------------------------------

@register_op("print", inputs=["In"], outputs=["Out"])
def print_op(ctx, attrs, In):
    """Debug tensor printer (print_op.cc) via jax.debug.print — works
    under jit, prints asynchronously from the runtime."""
    msg = attrs.get("message", "")
    jax.debug.print(msg + "{x}", x=In)
    return In


# ---- fused compositions (XLA fuses; these keep op-level parity) ---------

@register_op("fused_elemwise_activation", inputs=["X", "Y"],
             outputs=["Out", "IntermediateOut"],
             stateful_outputs=("IntermediateOut",))
def fused_elemwise_activation(ctx, attrs, X, Y):
    """fused/fused_elemwise_activation_op.cc — IsBinaryCompound keys on
    functor_list[0]:

    * [binary, unary] → Binary(X, Unary(Y)), intermediate = Unary(Y)
    * [unary, binary] → Unary(Binary(X, Y)), intermediate = Binary(X, Y)
    """
    from .registry import get_op_def

    functors = list(attrs.get("functor_list", ["elementwise_add", "relu"]))
    binary = [f for f in functors if f.startswith("elementwise_")][0]
    unary = [f for f in functors if not f.startswith("elementwise_")][0]
    bin_fn = {"elementwise_add": jnp.add, "elementwise_sub": jnp.subtract,
              "elementwise_mul": jnp.multiply}[binary]
    un_def = get_op_def(unary)

    def un(v):
        r = un_def.fn(ctx, {}, v)
        return list(r.values())[0] if isinstance(r, dict) else r

    if functors[0] == binary:
        mid = un(Y)
        out = bin_fn(X, mid)
    else:
        mid = bin_fn(X, Y)
        out = un(mid)
    return {"Out": out, "IntermediateOut": mid}


@register_op("fused_embedding_seq_pool", inputs=["W", "Ids", "SeqLen"],
             outputs=["Out"])
def fused_embedding_seq_pool(ctx, attrs, W, Ids, SeqLen):
    """fused/fused_embedding_seq_pool_op.cc: lookup + sum-pool over the
    sequence dim; padded [B, L] ids (+ optional lengths)."""
    ids = Ids
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    emb = jnp.take(W, jnp.maximum(ids.astype(jnp.int32), 0), axis=0)
    if SeqLen is not None:
        lengths = jnp.reshape(SeqLen, (-1,)).astype(jnp.int32)
        m = (jnp.arange(ids.shape[1])[None, :]
             < lengths[:, None])[:, :, None]
        emb = jnp.where(m, emb, 0.0)
    return jnp.sum(emb, axis=1)


@register_op("fusion_repeated_fc_relu", inputs=["X", "W*", "Bias*"],
             outputs=["ReluOut", "Out"], stateful_outputs=("ReluOut",))
def fusion_repeated_fc_relu(ctx, attrs, X, W, Bias):
    """fused/fusion_repeated_fc_relu_op.cc: chain of fc+relu."""
    x = X
    for i, (w, b) in enumerate(zip(W, Bias)):
        x = jnp.matmul(x, w) + b.reshape(1, -1)
        if i < len(W) - 1:
            x = jnp.maximum(x, 0.0)
    return {"Out": x, "ReluOut": x}


@register_op("fusion_seqconv_eltadd_relu",
             inputs=["X", "Filter", "Bias", "SeqLen"],
             outputs=["Out", "ColMat"], stateful_outputs=("ColMat",))
def fusion_seqconv_eltadd_relu(ctx, attrs, X, Filter, Bias, SeqLen):
    """fused/fusion_seqconv_eltadd_relu_op.cc = sequence_conv + bias +
    relu."""
    from .sequence import sequence_conv

    out = sequence_conv(ctx, attrs, X, Filter, SeqLen)
    out = out + Bias.reshape(1, 1, -1)
    return {"Out": jnp.maximum(out, 0.0), "ColMat": out}


@register_op("fusion_seqpool_concat", inputs=["X*", "SeqLen*"],
             outputs=["Out"])
def fusion_seqpool_concat(ctx, attrs, X, SeqLen):
    """fused/fusion_seqpool_concat_op.cc: per-input sequence sum/avg pool,
    then concat."""
    ptype = attrs.get("pooltype", "SUM").upper()
    outs = []
    for i, x in enumerate(X):
        sl = SeqLen[i] if SeqLen and i < len(SeqLen) else None
        if sl is not None:
            lengths = jnp.reshape(sl, (-1,)).astype(jnp.int32)
            m = (jnp.arange(x.shape[1])[None, :]
                 < lengths[:, None])[:, :, None]
            xm = jnp.where(m, x, 0.0)
            s = jnp.sum(xm, axis=1)
            if ptype == "AVERAGE":
                s = s / jnp.maximum(lengths[:, None].astype(x.dtype), 1)
        else:
            s = (jnp.mean(x, axis=1) if ptype == "AVERAGE"
                 else jnp.sum(x, axis=1))
        outs.append(s)
    return jnp.concatenate(outs, axis=1)


@register_op("fusion_seqexpand_concat_fc",
             inputs=["X*", "FCWeight", "FCBias"], outputs=["Out", "FCOut"],
             stateful_outputs=("FCOut",))
def fusion_seqexpand_concat_fc(ctx, attrs, X, FCWeight, FCBias):
    """fused/fusion_seqexpand_concat_fc_op.cc: X[0] is [B,T,D0]; the rest
    are [B,Di] rows broadcast over T; concat + fc + activation."""
    from . import activations as acts
    from .registry import get_op_def

    base = X[0]
    T = base.shape[1]
    parts = [base]
    for x in X[1:]:
        parts.append(jnp.broadcast_to(
            x[:, None, :], (x.shape[0], T, x.shape[1])))
    cat = jnp.concatenate(parts, axis=2)
    out = jnp.matmul(cat, FCWeight)
    if FCBias is not None:
        out = out + FCBias.reshape(1, 1, -1)
    act = attrs.get("fc_activation", "identity")
    if act not in ("identity", "", None):
        out = get_op_def(act).fn(ctx, {}, out)
        if isinstance(out, dict):
            out = list(out.values())[0]
    return {"Out": out, "FCOut": out}


@register_op("fusion_squared_mat_sub", inputs=["X", "Y"],
             outputs=["SquaredX", "SquaredY", "SquaredXY", "Out"],
             stateful_outputs=("SquaredX", "SquaredY", "SquaredXY"))
def fusion_squared_mat_sub(ctx, attrs, X, Y):
    """fused/fusion_squared_mat_sub_op.cc: scalar * ((XY)^2 - X^2 Y^2),
    the FM second-order interaction kernel."""
    scalar = float(attrs.get("scalar", 1.0))
    xy = jnp.matmul(X, Y)
    x2y2 = jnp.matmul(jnp.square(X), jnp.square(Y))
    return {"SquaredX": jnp.square(X), "SquaredY": jnp.square(Y),
            "SquaredXY": jnp.square(xy),
            "Out": scalar * (jnp.square(xy) - x2y2)}


@register_op("fc", inputs=["Input", "W", "Bias"], outputs=["Out"])
def fc_op(ctx, attrs, Input, W, Bias):
    """Standalone fc op (fc_op.cc; the mkldnn-era fused fc)."""
    in_num_col_dims = int(attrs.get("in_num_col_dims", 1))
    import math as _math

    shape = Input.shape
    x = Input.reshape(_math.prod(shape[:in_num_col_dims]), -1)
    out = jnp.matmul(x, W)
    if Bias is not None:
        out = out + Bias.reshape(1, -1)
    return out.reshape(tuple(shape[:in_num_col_dims]) + (W.shape[1],))


@register_op("get_places", inputs=[], outputs=["Out"], no_grad=True)
def get_places(ctx, attrs, **kw):
    """Device-count query (get_places_op.cc) — the mesh owns placement on
    TPU; returns the device count as a tensor."""
    import jax as _jax

    return jnp.asarray([_jax.device_count()], jnp.int32)


@register_op("sample_logits",
             inputs=["Logits", "Labels"],
             outputs=["Samples", "Probabilities", "SampledLogits",
                      "SampledLabels"],
             stateful_outputs=("Samples", "Probabilities"))
def sample_logits(ctx, attrs, Logits, Labels):
    """sample_logits_op.cc: gather true + log-uniform sampled logits with
    -log q correction (the decomposed sampled-softmax front half)."""
    from .nn import _draw_negatives, _sampler_logq

    s_count = int(attrs.get("num_samples", 10))
    B, C = Logits.shape
    lbl = jnp.reshape(Labels, (B,)).astype(jnp.int32)
    neg = _draw_negatives(ctx, 1, s_count, C, attrs.get("seed", 0))
    s_true = jnp.take_along_axis(Logits, lbl[:, None], axis=1)
    s_neg = jnp.take(Logits, neg, axis=1)
    adj_true = s_true - _sampler_logq(1, lbl, C)[:, None]
    adj_neg = s_neg - _sampler_logq(1, neg, C)[None, :]
    if attrs.get("remove_accidental_hits", True):
        adj_neg = jnp.where(neg[None, :] == lbl[:, None], -1e30, adj_neg)
    sampled = jnp.concatenate([adj_true, adj_neg], axis=1)
    samples = jnp.concatenate(
        [lbl[:, None], jnp.broadcast_to(neg[None, :], (B, s_count))],
        axis=1)
    return {
        "Samples": samples.astype(jnp.int64),
        "Probabilities": jnp.exp(jax.nn.log_softmax(sampled, axis=1)),
        "SampledLogits": sampled,
        "SampledLabels": jnp.zeros((B,), jnp.int64),
    }


@register_op("depthwise_conv2d_transpose", inputs=["Input", "Filter"],
             outputs=["Output"])
def depthwise_conv2d_transpose(ctx, attrs, Input, Filter):
    """conv_transpose_op.cc depthwise registration: per-channel transpose
    conv (groups == channels)."""
    from .nn import _conv_transpose_padding

    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = attrs.get("paddings", [0, 0])
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    ksize = Filter.shape[2:]
    pad = _conv_transpose_padding(paddings, ksize, dilations)
    c = Input.shape[1]
    outs = []
    for ch in range(c):
        outs.append(jax.lax.conv_transpose(
            Input[:, ch:ch + 1], Filter[ch:ch + 1, :1],
            strides=strides, padding=pad, rhs_dilation=dilations,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True))
    return jnp.concatenate(outs, axis=1)


@register_op("lstmp", inputs=["Input", "H0", "C0", "Weight", "ProjWeight",
                              "Bias", "SeqLen"],
             outputs=["Projection", "Cell"])
def lstmp(ctx, attrs, Input, H0, C0, Weight, ProjWeight, Bias, SeqLen):
    """lstmp_op.cc canonical name for dynamic_lstmp."""
    from .rnn import dynamic_lstmp

    return dynamic_lstmp(ctx, attrs, Input, H0, C0, Weight, ProjWeight,
                         Bias, SeqLen)


@register_op("max_sequence_len", inputs=["RankTable"], outputs=["Out"],
             no_grad=True)
def max_sequence_len(ctx, attrs, RankTable):
    """max_sequence_len_op.cc: with padded batches the rank table is the
    lengths tensor; returns its max."""
    return jnp.max(RankTable).reshape(1).astype(jnp.int64)


@register_op("fusion_transpose_flatten_concat", inputs=["X*"],
             outputs=["Out"])
def fusion_transpose_flatten_concat(ctx, attrs, X):
    """fused/fusion_transpose_flatten_concat_op.cc: per-input transpose →
    flatten from `flatten_axis` → concat on `concat_axis`."""
    trans = [int(a) for a in attrs.get("trans_axis", [])]
    flat_axis = int(attrs.get("flatten_axis", 1))
    concat_axis = int(attrs.get("concat_axis", 1))
    import math as _math

    outs = []
    for x in X:
        t = jnp.transpose(x, trans) if trans else x
        outs.append(t.reshape(
            _math.prod(t.shape[:flat_axis]), -1))
    return jnp.concatenate(outs, axis=concat_axis)


@register_op("conv2d_fusion", inputs=["Input", "Filter", "Bias",
                                      "ResidualData"],
             outputs=["Output"])
def conv2d_fusion(ctx, attrs, Input, Filter, Bias, ResidualData):
    """conv2d_fusion_op.cc: conv + bias + (residual add) + activation —
    XLA fuses the epilogue; registered for op-level parity."""
    from .nn import _conv_nd
    from .registry import get_op_def

    out = _conv_nd(ctx, attrs, Input, Filter, 2)
    if Bias is not None:
        out = out + Bias.reshape(1, -1, 1, 1)
    if ResidualData is not None:
        out = out + ResidualData
    act = attrs.get("activation", "relu")
    if act and act not in ("identity", ""):
        res = get_op_def(act).fn(ctx, {}, out)
        out = list(res.values())[0] if isinstance(res, dict) else res
    return out


@register_op("cudnn_lstm",
             inputs=["Input", "InitH", "InitC", "W", "SeqLen"],
             outputs=["Out", "last_h", "last_c"],
             stateful_outputs=("last_h", "last_c"))
def cudnn_lstm(ctx, attrs, Input, InitH, InitC, W, SeqLen):
    """Single fused multi-step LSTM (cudnn_lstm_op.cc, single layer,
    unidirectional): W packs [D+H, 4H] input+recurrent weights followed
    by the 4H bias, the cuDNN parameter layout flattened."""
    from .rnn import lstm as lstm_op

    B, T, D = Input.shape
    hidden = int(attrs.get("hidden_size", D))
    wx_sz = D * 4 * hidden
    wh_sz = hidden * 4 * hidden
    flat = W.reshape(-1)
    wx = flat[:wx_sz].reshape(D, 4 * hidden)
    wh = flat[wx_sz:wx_sz + wh_sz].reshape(hidden, 4 * hidden)
    bias = flat[wx_sz + wh_sz:wx_sz + wh_sz + 4 * hidden].reshape(
        1, 4 * hidden)
    gates = jnp.matmul(Input, wx)
    h0 = InitH.reshape(-1, hidden) if InitH is not None else None
    c0 = InitC.reshape(-1, hidden) if InitC is not None else None
    res = lstm_op(ctx, dict(attrs), gates, h0, c0, wh, bias, SeqLen)
    hs, cs = res["Hidden"], res["Cell"]
    return {"Out": hs, "last_h": hs[:, -1][None],
            "last_c": cs[:, -1][None]}


@register_op("conv2d_inception_fusion",
             inputs=["Input", "Filter*", "Bias*"], outputs=["Output"])
def conv2d_inception_fusion(ctx, attrs, Input, Filter, Bias):
    """Inception branch fusion (conv2d_inception_fusion_op.cc): parallel
    conv towers concatenated on channels; XLA fuses the epilogues."""
    from .nn import _conv_nd

    outs = []
    for f, b in zip(Filter, Bias):
        k = f.shape[-1]
        o = _conv_nd(ctx, {"strides": [1, 1],
                           "paddings": [(k - 1) // 2] * 2,
                           "dilations": [1, 1], "groups": 1}, Input, f, 2)
        if b is not None:
            o = o + b.reshape(1, -1, 1, 1)
        outs.append(jnp.maximum(o, 0.0))
    return jnp.concatenate(outs, axis=1)


@register_op("split_ids", inputs=["Ids"], outputs=["Out*"], no_grad=True)
def split_ids(ctx, attrs, Ids):
    """Shard sparse ids round-robin (split_ids_op.cc fed the pserver
    shards; here it documents/serves the row-sharded-table path).
    TPU-static: each shard keeps full length with non-members masked to
    -1."""
    n = int(attrs.get("num_shards", 1))
    ids = jnp.reshape(Ids, (-1,)).astype(jnp.int64)
    outs = []
    for s in range(n):
        m = (ids % n) == s
        outs.append(jnp.where(m, ids, -1))
    return {"Out": outs}


@register_op("merge_ids", inputs=["Ids", "Rows*", "X*"], outputs=["Out"],
             no_grad=True)
def merge_ids(ctx, attrs, Ids, Rows, X):
    """Merge per-shard embedding lookups back to the original id order
    (merge_ids_op.cc): shard s owns ids with id %% n == s; its X rows are
    the lookups for its (masked) slots."""
    ids = jnp.reshape(Ids, (-1,)).astype(jnp.int64)
    n = len(X)
    d = X[0].shape[-1]
    out = jnp.zeros((ids.shape[0], d), X[0].dtype)
    for s in range(n):
        m = ((ids % n) == s)[:, None]
        out = jnp.where(m, X[s], out)
    return out


@register_op("split_selected_rows", inputs=["X"], outputs=["Out*"],
             no_grad=True)
def split_selected_rows(ctx, attrs, X):
    """Split rows into height-section shards
    (split_selected_rows_op.cc); dense equivalent: contiguous row
    ranges."""
    sections = [int(s) for s in attrs.get("height_sections", [])]
    outs = []
    start = 0
    for sec in sections:
        outs.append(X[start:start + sec])
        start += sec
    return {"Out": outs}


@register_op("fake_init", inputs=[], outputs=["Out"], no_grad=True)
def fake_init(ctx, attrs, **kw):
    """Placeholder init for remote-table vars (fake_init_op.cc); dense
    zeros here."""
    from .common import resolve_dtype

    shape = [int(s) for s in attrs.get("shape", [1])]
    return jnp.zeros(shape, resolve_dtype(attrs.get("dtype", 5)))
