"""Sequence ops on padded batches + explicit lengths.

Reference: ``paddle/fluid/operators/sequence_ops/`` (15 LoD-aware ops over
ragged LoDTensors).  TPU-native representation (SURVEY.md §5): a "sequence"
is a padded dense [B, T, ...] tensor plus an optional ``SeqLen`` [B] int
companion; masking reproduces ragged semantics under XLA static shapes.
Ops that reorganize raggedness itself (sequence_unpad to ragged, LoD level
manipulation) keep the padded form.
"""

import jax
import jax.numpy as jnp

from .registry import register_op


def _mask(SeqLen, B, T, dtype=jnp.float32):
    if SeqLen is None:
        return jnp.ones((B, T), dtype)
    return (
        jnp.arange(T)[None, :] < jnp.reshape(SeqLen, (B,))[:, None]
    ).astype(dtype)


@register_op("sequence_pool", inputs=["X", "SeqLen"],
             outputs=["Out", "MaxIndex"], stateful_outputs=("MaxIndex",))
def sequence_pool(ctx, attrs, X, SeqLen):
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    feat_rank = X.ndim - 2
    m = _mask(SeqLen, B, T, X.dtype).reshape((B, T) + (1,) * feat_rank)
    lengths = (
        jnp.reshape(SeqLen, (B,)).astype(X.dtype)
        if SeqLen is not None else jnp.full((B,), T, X.dtype)
    ).reshape((B,) + (1,) * feat_rank)
    if ptype == "SUM":
        out = jnp.sum(X * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(X * m, axis=1) / jnp.maximum(lengths, 1)
    elif ptype == "SQRT":
        out = jnp.sum(X * m, axis=1) / jnp.sqrt(jnp.maximum(lengths, 1))
    elif ptype == "MAX":
        neg = jnp.asarray(-1e30, X.dtype)
        out = jnp.max(jnp.where(m > 0, X, neg), axis=1)
    elif ptype == "LAST":
        idx = (
            jnp.reshape(SeqLen, (B,)).astype(jnp.int32) - 1
            if SeqLen is not None
            else jnp.full((B,), T - 1, jnp.int32)
        )
        out = jnp.take_along_axis(
            X, idx.reshape((B, 1) + (1,) * feat_rank), axis=1
        )[:, 0]
    elif ptype == "FIRST":
        out = X[:, 0]
    else:
        raise NotImplementedError("sequence_pool type %s" % ptype)
    return {"Out": out, "MaxIndex": jnp.zeros((B,), jnp.int32)}


@register_op("sequence_softmax", inputs=["X", "SeqLen"], outputs=["Out"])
def sequence_softmax(ctx, attrs, X, SeqLen):
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    m = _mask(SeqLen, B, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    logits = jnp.where(m > 0, X, jnp.asarray(-1e30, X.dtype))
    p = jax.nn.softmax(logits, axis=1)
    return p * m


@register_op("sequence_reverse", inputs=["X", "SeqLen"], outputs=["Y"])
def sequence_reverse(ctx, attrs, X, SeqLen):
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    if SeqLen is None:
        return jnp.flip(X, axis=1)
    lens = jnp.reshape(SeqLen, (B,)).astype(jnp.int32)
    t = jnp.arange(T)[None, :]
    # position i maps to len-1-i within the valid prefix; padding unchanged
    src = jnp.where(t < lens[:, None], lens[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        X, src.reshape((B, T) + (1,) * (X.ndim - 2)), axis=1
    )


@register_op("sequence_expand", inputs=["X", "Y"], outputs=["Out"])
def sequence_expand(ctx, attrs, X, Y):
    """Tile X rows to match Y's time dimension (padded analogue of the
    LoD-driven expand used by attention decoders)."""
    T = jnp.shape(Y)[1]
    return jnp.repeat(jnp.expand_dims(X, 1), T, axis=1) if X.ndim == 2 else X


@register_op("sequence_concat", inputs=["X*"], outputs=["Out"], no_grad=True)
def sequence_concat(ctx, attrs, X):
    return jnp.concatenate(X, axis=1)


@register_op("sequence_pad", inputs=["X", "PadValue", "SeqLen"],
             outputs=["Out", "Length"], stateful_outputs=("Length",))
def sequence_pad(ctx, attrs, X, PadValue, SeqLen):
    # inputs are already padded in this representation; normalize padding
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    m = _mask(SeqLen, B, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    pad = jnp.reshape(PadValue, ()) if PadValue is not None else 0.0
    out = jnp.where(m > 0, X, jnp.asarray(pad, X.dtype))
    length = (
        jnp.reshape(SeqLen, (B,)).astype(jnp.int32)
        if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    )
    return {"Out": out, "Length": length}


@register_op("sequence_unpad", inputs=["X", "Length"], outputs=["Out"])
def sequence_unpad(ctx, attrs, X, Length):
    # stays padded under static shapes; zero out beyond Length
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    m = _mask(Length, B, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    return X * m


@register_op("sequence_mask", inputs=["X"], outputs=["Y"], no_grad=True)
def sequence_mask(ctx, attrs, X):
    maxlen = int(attrs.get("maxlen", -1))
    from .common import resolve_dtype

    dtype = resolve_dtype(attrs.get("out_dtype", "int64"))
    lens = jnp.reshape(X, (-1,)).astype(jnp.int32)
    if maxlen < 0:
        raise ValueError(
            "sequence_mask needs a static maxlen attr on TPU (dynamic "
            "max-length output shapes are not XLA-compatible)"
        )
    return (
        jnp.arange(maxlen)[None, :] < lens[:, None]
    ).astype(dtype)


@register_op("sequence_slice", inputs=["X", "Offset", "Length"],
             outputs=["Out"], no_grad=True)
def sequence_slice(ctx, attrs, X, Offset, Length):
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    off = jnp.reshape(Offset, (B,)).astype(jnp.int32)
    t = jnp.arange(T)[None, :]
    src = jnp.minimum(t + off[:, None], T - 1)
    out = jnp.take_along_axis(
        X, src.reshape((B, T) + (1,) * (X.ndim - 2)), axis=1
    )
    m = _mask(Length, B, T, X.dtype)
    while m.ndim < out.ndim:
        m = m[..., None]
    return out * m


@register_op("sequence_enumerate", inputs=["X"], outputs=["Out"],
             no_grad=True)
def sequence_enumerate(ctx, attrs, X):
    win = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    cols = []
    for k in range(win):
        shifted = jnp.concatenate(
            [X[:, k:], jnp.full((B, k), pad, X.dtype)], axis=1
        )
        cols.append(shifted)
    return jnp.stack(cols, axis=-1)
