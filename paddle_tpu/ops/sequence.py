"""Sequence ops on padded batches + explicit lengths.

Reference: ``paddle/fluid/operators/sequence_ops/`` (15 LoD-aware ops over
ragged LoDTensors).  TPU-native representation (SURVEY.md §5): a "sequence"
is a padded dense [B, T, ...] tensor plus an optional ``SeqLen`` [B] int
companion; masking reproduces ragged semantics under XLA static shapes.
Ops that reorganize raggedness itself (sequence_unpad to ragged, LoD level
manipulation) keep the padded form.
"""

import jax
import jax.numpy as jnp

from .registry import register_op


def _mask(SeqLen, B, T, dtype=jnp.float32):
    if SeqLen is None:
        return jnp.ones((B, T), dtype)
    return (
        jnp.arange(T)[None, :] < jnp.reshape(SeqLen, (B,))[:, None]
    ).astype(dtype)


@register_op("sequence_pool", inputs=["X", "SeqLen"],
             outputs=["Out", "MaxIndex"], stateful_outputs=("MaxIndex",))
def sequence_pool(ctx, attrs, X, SeqLen):
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    feat_rank = X.ndim - 2
    m = _mask(SeqLen, B, T, X.dtype).reshape((B, T) + (1,) * feat_rank)
    lengths = (
        jnp.reshape(SeqLen, (B,)).astype(X.dtype)
        if SeqLen is not None else jnp.full((B,), T, X.dtype)
    ).reshape((B,) + (1,) * feat_rank)
    if ptype == "SUM":
        out = jnp.sum(X * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(X * m, axis=1) / jnp.maximum(lengths, 1)
    elif ptype == "SQRT":
        out = jnp.sum(X * m, axis=1) / jnp.sqrt(jnp.maximum(lengths, 1))
    elif ptype == "MAX":
        neg = jnp.asarray(-1e30, X.dtype)
        out = jnp.max(jnp.where(m > 0, X, neg), axis=1)
    elif ptype == "LAST":
        idx = (
            jnp.reshape(SeqLen, (B,)).astype(jnp.int32) - 1
            if SeqLen is not None
            else jnp.full((B,), T - 1, jnp.int32)
        )
        out = jnp.take_along_axis(
            X, idx.reshape((B, 1) + (1,) * feat_rank), axis=1
        )[:, 0]
    elif ptype == "FIRST":
        out = X[:, 0]
    else:
        raise NotImplementedError("sequence_pool type %s" % ptype)
    return {"Out": out, "MaxIndex": jnp.zeros((B,), jnp.int32)}


@register_op("sequence_softmax", inputs=["X", "SeqLen"], outputs=["Out"])
def sequence_softmax(ctx, attrs, X, SeqLen):
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    m = _mask(SeqLen, B, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    logits = jnp.where(m > 0, X, jnp.asarray(-1e30, X.dtype))
    p = jax.nn.softmax(logits, axis=1)
    return p * m


@register_op("sequence_reverse", inputs=["X", "SeqLen"], outputs=["Y"])
def sequence_reverse(ctx, attrs, X, SeqLen):
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    if SeqLen is None:
        return jnp.flip(X, axis=1)
    lens = jnp.reshape(SeqLen, (B,)).astype(jnp.int32)
    t = jnp.arange(T)[None, :]
    # position i maps to len-1-i within the valid prefix; padding unchanged
    src = jnp.where(t < lens[:, None], lens[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        X, src.reshape((B, T) + (1,) * (X.ndim - 2)), axis=1
    )


@register_op("sequence_expand", inputs=["X", "Y"], outputs=["Out"])
def sequence_expand(ctx, attrs, X, Y):
    """Tile X rows to match Y's time dimension (padded analogue of the
    LoD-driven expand used by attention decoders)."""
    T = jnp.shape(Y)[1]
    return jnp.repeat(jnp.expand_dims(X, 1), T, axis=1) if X.ndim == 2 else X


@register_op("sequence_concat", inputs=["X*"], outputs=["Out"], no_grad=True)
def sequence_concat(ctx, attrs, X):
    return jnp.concatenate(X, axis=1)


@register_op("sequence_pad", inputs=["X", "PadValue", "SeqLen"],
             outputs=["Out", "Length"], stateful_outputs=("Length",))
def sequence_pad(ctx, attrs, X, PadValue, SeqLen):
    # inputs are already padded in this representation; normalize padding
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    m = _mask(SeqLen, B, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    pad = jnp.reshape(PadValue, ()) if PadValue is not None else 0.0
    out = jnp.where(m > 0, X, jnp.asarray(pad, X.dtype))
    length = (
        jnp.reshape(SeqLen, (B,)).astype(jnp.int32)
        if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    )
    return {"Out": out, "Length": length}


@register_op("sequence_unpad", inputs=["X", "Length"], outputs=["Out"])
def sequence_unpad(ctx, attrs, X, Length):
    # stays padded under static shapes; zero out beyond Length
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    m = _mask(Length, B, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    return X * m


@register_op("sequence_mask", inputs=["X"], outputs=["Y"], no_grad=True)
def sequence_mask(ctx, attrs, X):
    maxlen = int(attrs.get("maxlen", -1))
    from .common import resolve_dtype

    dtype = resolve_dtype(attrs.get("out_dtype", "int64"))
    lens = jnp.reshape(X, (-1,)).astype(jnp.int32)
    if maxlen < 0:
        raise ValueError(
            "sequence_mask needs a static maxlen attr on TPU (dynamic "
            "max-length output shapes are not XLA-compatible)"
        )
    return (
        jnp.arange(maxlen)[None, :] < lens[:, None]
    ).astype(dtype)


@register_op("sequence_slice", inputs=["X", "Offset", "Length"],
             outputs=["Out"], no_grad=True)
def sequence_slice(ctx, attrs, X, Offset, Length):
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    off = jnp.reshape(Offset, (B,)).astype(jnp.int32)
    t = jnp.arange(T)[None, :]
    src = jnp.minimum(t + off[:, None], T - 1)
    out = jnp.take_along_axis(
        X, src.reshape((B, T) + (1,) * (X.ndim - 2)), axis=1
    )
    m = _mask(Length, B, T, X.dtype)
    while m.ndim < out.ndim:
        m = m[..., None]
    return out * m


@register_op("sequence_enumerate", inputs=["X"], outputs=["Out"],
             no_grad=True)
def sequence_enumerate(ctx, attrs, X):
    win = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    cols = []
    for k in range(win):
        shifted = jnp.concatenate(
            [X[:, k:], jnp.full((B, k), pad, X.dtype)], axis=1
        )
        cols.append(shifted)
    return jnp.stack(cols, axis=-1)


@register_op("sequence_conv", inputs=["X", "Filter", "SeqLen"],
             outputs=["Out"])
def sequence_conv(ctx, attrs, X, Filter, SeqLen):
    """Context-window convolution over padded [B,T,D] sequences
    (sequence_conv_op.h + math/context_project.h): each step concatenates
    contextLength rows starting at contextStart, then matmuls the
    [ctx*D, M] filter; rows past a sequence's length contribute zeros."""
    ctx_len = int(attrs.get("contextLength", 3))
    ctx_start = int(attrs.get("contextStart", -1))
    B, T, D = X.shape
    if SeqLen is not None:
        lengths = jnp.reshape(SeqLen, (-1,)).astype(jnp.int32)
        tmask = (jnp.arange(T)[None, :] < lengths[:, None])[:, :, None]
        x = jnp.where(tmask, X, 0.0)
    else:
        x = X
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        if off < 0:
            shifted = jnp.pad(x[:, :T + off], ((0, 0), (-off, 0), (0, 0)))
        elif off > 0:
            shifted = jnp.pad(x[:, off:], ((0, 0), (0, off), (0, 0)))
        else:
            shifted = x
        cols.append(shifted)
    ctx_mat = jnp.concatenate(cols, axis=2)  # [B, T, ctx*D]
    return jnp.matmul(ctx_mat, Filter)


@register_op("sequence_expand_as", inputs=["X", "Y", "RefLen"],
             outputs=["Out"])
def sequence_expand_as(ctx, attrs, X, Y, RefLen):
    """Repeat each row of X to match Y's per-sequence lengths
    (sequence_expand_as_op.h).  Padded form: X [B, D], ref lengths [B],
    output [B, Tmax, D] with rows repeated up to each length, zeros
    beyond."""
    lengths = jnp.reshape(RefLen, (-1,)).astype(jnp.int32) \
        if RefLen is not None else None
    Tmax = Y.shape[1]
    out = jnp.repeat(X[:, None, :], Tmax, axis=1)
    if lengths is not None:
        m = (jnp.arange(Tmax)[None, :] < lengths[:, None])[:, :, None]
        out = jnp.where(m, out, 0.0)
    return out


@register_op("sequence_reshape", inputs=["X"], outputs=["Out"])
def sequence_reshape(ctx, attrs, X):
    """Change the inner dim, folding factor into time
    (sequence_reshape_op.h): [B, T, D] -> [B, T*D/new_dim, new_dim]."""
    new_dim = int(attrs["new_dim"])
    B, T, D = X.shape
    return X.reshape(B, T * D // new_dim, new_dim)


@register_op("sequence_scatter", inputs=["X", "Ids", "Updates", "SeqLen"],
             outputs=["Out"])
def sequence_scatter(ctx, attrs, X, Ids, Updates, SeqLen):
    """Scatter-ADD per-sequence updates into X (sequence_scatter_op.h):
    X [B, D]; Ids/Updates [B, L] (padded; positions past SeqLen masked)."""
    B, L = Ids.shape[0], Ids.shape[1]
    ids = jnp.reshape(Ids, (B, L)).astype(jnp.int32)
    upd = jnp.reshape(Updates, (B, L))
    if SeqLen is not None:
        lengths = jnp.reshape(SeqLen, (-1,)).astype(jnp.int32)
        valid = jnp.arange(L)[None, :] < lengths[:, None]
        upd = jnp.where(valid, upd, 0.0)
    def one(row, idx, u):
        return row.at[idx].add(u)
    return jax.vmap(one)(X, ids, upd)


@register_op("sequence_erase", inputs=["X", "SeqLen"],
             outputs=["Out", "OutLen"], no_grad=True,
             stateful_outputs=("OutLen",))
def sequence_erase(ctx, attrs, X, SeqLen):
    """Remove every occurrence of the attr tokens from each sequence and
    compact left (reference ``sequence_ops/sequence_erase_op.cc``: LoD
    recomputed after deletion).  Padded design: kept elements scatter to
    their post-compaction slot, erased slots scatter out of bounds and
    drop; the new lengths come back as the companion OutLen tensor, in
    place of the reference's shrunken LoD."""
    tokens = [int(t) for t in attrs.get("tokens", [])]
    B, T = jnp.shape(X)[0], jnp.shape(X)[1]
    valid = _mask(SeqLen, B, T, jnp.int32) > 0
    keep = valid
    for t in tokens:
        keep = keep & (X != t)
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    # erased/padding slots target column T → dropped by scatter mode
    col = jnp.where(keep, pos, T)
    row = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    out = jnp.zeros_like(X).at[row, col].set(X, mode="drop")
    new_len = keep.astype(jnp.int32).sum(axis=1)
    return {"Out": out, "OutLen": new_len}
