"""Loss / metric op lowerings beyond the core set.

Reference kernels: ``paddle/fluid/operators/{log_loss,kldiv_loss,rank_loss,
margin_rank_loss,bpr_loss,teacher_student_sigmoid_loss,mean_iou,
bilinear_tensor_product}_op.*``."""

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("log_loss", inputs=["Predicted", "Labels"], outputs=["Loss"])
def log_loss(ctx, attrs, Predicted, Labels):
    """-y*log(p+eps) - (1-y)*log(1-p+eps) (log_loss_op.h)."""
    eps = float(attrs.get("epsilon", 1e-4))
    p, y = Predicted, Labels
    return -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)


@register_op("kldiv_loss", inputs=["X", "Target"], outputs=["Loss"])
def kldiv_loss(ctx, attrs, X, Target):
    """target * (log(target) - x), with 'none'/'batchmean'/'mean'/'sum'
    reduction (kldiv_loss_op.h; x is already log-probability)."""
    red = attrs.get("reduction", "mean")
    t = Target
    loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-38)) - X), 0.0)
    if red == "none":
        return loss
    if red == "sum":
        return jnp.sum(loss)
    if red == "batchmean":
        return jnp.sum(loss) / jnp.asarray(X.shape[0], X.dtype)
    return jnp.mean(loss)


@register_op("rank_loss", inputs=["Label", "Left", "Right"], outputs=["Out"])
def rank_loss(ctx, attrs, Label, Left, Right):
    """RankNet pairwise loss (rank_loss_op.h):
    log(1 + exp(left-right)) - label*(left-right), computed stably."""
    o = Left - Right
    return jnp.logaddexp(0.0, o) - Label * o


@register_op("margin_rank_loss", inputs=["Label", "X1", "X2"],
             outputs=["Out", "Activated"], stateful_outputs=("Activated",))
def margin_rank_loss(ctx, attrs, Label, X1, X2):
    """max(0, -label*(x1-x2) + margin) (margin_rank_loss_op.h)."""
    margin = float(attrs.get("margin", 0.0))
    raw = -Label * (X1 - X2) + margin
    out = jnp.maximum(raw, 0.0)
    return {"Out": out, "Activated": (raw > 0).astype(X1.dtype)}


@register_op("bpr_loss", inputs=["X", "Label"], outputs=["Y"])
def bpr_loss(ctx, attrs, X, Label):
    """Bayesian personalized ranking (bpr_loss_op.h): per sample,
    mean over negatives j != y of log(1 + exp(x_j - x_y))."""
    b, c = X.shape
    lbl = jnp.reshape(Label, (b,)).astype(jnp.int32)
    pos = jnp.take_along_axis(X, lbl[:, None], axis=1)  # [B,1]
    # log(1+exp(neg-pos)) summed over j != y
    all_terms = jnp.logaddexp(0.0, X - pos)  # j == y term is log(2)...
    # ...so subtract the diagonal contribution exactly
    diag = jnp.logaddexp(0.0, jnp.zeros((b, 1), X.dtype))
    s = jnp.sum(all_terms, axis=1, keepdims=True) - diag
    return s / jnp.asarray(c - 1, X.dtype)


@register_op("teacher_student_sigmoid_loss", inputs=["X", "Label"],
             outputs=["Y"])
def teacher_student_sigmoid_loss(ctx, attrs, X, Label):
    """CTR distillation loss (teacher_student_sigmoid_loss_op.h): label
    encodes click and optional teacher score z':
    label < -1: no z', clk=0;  -1 <= label < 0: no z', clk=1;
    0 <= label < 1: z'=label, clk=0;  label >= 1: z'=label-1, clk=1."""
    x, lbl = X, Label
    sce = jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))  # BCE@z=0
    sce1 = sce - x                                               # BCE@z=1
    no_t_clk0 = sce
    no_t_clk1 = sce1
    t_clk0 = sce + jnp.maximum(x, 0.0) - x * lbl \
        + jnp.log1p(jnp.exp(-jnp.abs(x)))
    t_clk1 = sce1 + jnp.maximum(x, 0.0) - x * (lbl - 1.0) \
        + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return jnp.where(
        lbl < -1.0, no_t_clk0,
        jnp.where(lbl < 0.0, no_t_clk1,
                  jnp.where(lbl < 1.0, t_clk0, t_clk1)))


@register_op("mean_iou", inputs=["Predictions", "Labels"],
             outputs=["OutMeanIou", "OutWrong", "OutCorrect"],
             no_grad=True)
def mean_iou(ctx, attrs, Predictions, Labels):
    """Mean IoU over classes (mean_iou_op.h): per class
    iou = correct / (pred_count + label_count - correct); classes absent
    from both are excluded from the mean."""
    n = int(attrs["num_classes"])
    pred = jnp.ravel(Predictions).astype(jnp.int32)
    lab = jnp.ravel(Labels).astype(jnp.int32)
    pred_cnt = jnp.bincount(pred, length=n).astype(jnp.float32)
    lab_cnt = jnp.bincount(lab, length=n).astype(jnp.float32)
    correct = jnp.bincount(
        jnp.where(pred == lab, pred, n), length=n + 1
    )[:n].astype(jnp.float32)
    union = pred_cnt + lab_cnt - correct
    present = union > 0
    iou = jnp.where(present, correct / jnp.maximum(union, 1.0), 0.0)
    denom = jnp.maximum(jnp.sum(present.astype(jnp.float32)), 1.0)
    wrong = (pred_cnt + lab_cnt - 2.0 * correct).astype(jnp.int32)
    return {
        "OutMeanIou": jnp.sum(iou) / denom,
        "OutWrong": wrong,
        "OutCorrect": correct.astype(jnp.int32),
    }


@register_op("bilinear_tensor_product", inputs=["X", "Y", "Weight", "Bias"],
             outputs=["Out"])
def bilinear_tensor_product(ctx, attrs, X, Y, Weight, Bias):
    """out[b,k] = x[b] @ W[k] @ y[b]^T (+ bias)
    (bilinear_tensor_product_op.h); W: [K, dx, dy]."""
    out = jnp.einsum("bi,kij,bj->bk", X, Weight, Y)
    if Bias is not None:
        out = out + Bias.reshape(1, -1)
    return out
