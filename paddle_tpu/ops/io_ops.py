"""In-graph checkpoint ops: ``save`` / ``load`` / ``save_combine`` /
``load_combine`` (reference: ``operators/save_op.cc``, ``load_op.cc``,
``save_combine_op.cc``, ``load_combine_op.cc``).

The reference runs these as device kernels that serialize LoDTensors to
its binary framing.  TPU-native, file IO cannot live inside the jitted
step (XLA programs are pure); instead the Executor detects blocks
containing these op types and interprets them host-side against the
scope (``executor.py run_host_io_block``) — matching the reference's
actual usage, where save/load programs are dedicated op lists built by
``io.py`` and run once, never fused into a training step.

Storage format is ``.npy`` (the repo-wide container; ``io.py`` module
docstring), not the reference binary framing — a program serialized by
THIS framework round-trips; foreign reference checkpoints need a
one-time conversion.
"""

import os

import numpy as np

from .registry import register_op

HOST_IO_OP_TYPES = ("save", "load", "save_combine", "load_combine")


def _jit_path_error(ctx, attrs, *a, **k):
    raise RuntimeError(
        "save/load ops are host-IO and cannot be traced into a jitted "
        "block; the Executor runs them via run_host_io_block (a program "
        "mixing save/load ops with compute ops is not supported — the "
        "reference's io.py emits dedicated save/load programs)")


def _io_infer_shape(op, block):
    """Output shapes come from the file at runtime, not the graph — the
    declared var shapes stand (reference load_op.cc InferShape is
    likewise a no-op)."""


for _t, _ins, _outs in (
    ("save", ["X"], []),
    ("load", [], ["Out"]),
    ("save_combine", ["X*"], []),
    ("load_combine", [], ["Out*"]),
):
    register_op(_t, inputs=_ins, outputs=_outs, no_grad=True,
                infer_shape=_io_infer_shape)(_jit_path_error)


def _npy_path(file_path):
    return file_path if file_path.endswith(".npy") else file_path + ".npy"


def _exec_save(op, scope):
    name = op.input("X")[0]
    if not scope.has(name):
        raise RuntimeError("save op: %r not in scope" % name)
    val = np.asarray(scope.get(name))
    if op.attr("save_as_fp16"):
        val = val.astype(np.float16)
    path = _npy_path(op.attr("file_path"))
    overwrite = op.attr("overwrite")
    if overwrite is not None and not overwrite and os.path.exists(path):
        raise RuntimeError(
            "save op: %r exists and overwrite=False (save_op.cc enforce)"
            % path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    np.save(path, val)


def _exec_load(op, scope):
    import jax.numpy as jnp

    path = _npy_path(op.attr("file_path"))
    if not os.path.exists(path):
        raise RuntimeError("load op: file %r does not exist" % path)
    val = np.load(path)
    if op.attr("load_as_fp16"):
        val = val.astype(np.float16)
    scope.set(op.output("Out")[0], jnp.asarray(val))


def _exec_save_combine(op, scope):
    names = op.input("X")
    arrays = {}
    for n in names:
        if not scope.has(n):
            raise RuntimeError("save_combine op: %r not in scope" % n)
        v = np.asarray(scope.get(n))
        if op.attr("save_as_fp16"):
            v = v.astype(np.float16)
        arrays[n] = v
    path = op.attr("file_path")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # order-preserving container: load_combine restores by POSITION, as
    # the reference format does (load_combine_op.cc reads sequentially)
    np.savez(path if path.endswith(".npz") else path + ".npz",
             **{"arr_%d" % i: arrays[n] for i, n in enumerate(names)},
             **{"__names__": np.array(list(names))})


def _exec_load_combine(op, scope):
    import jax.numpy as jnp

    path = op.attr("file_path")
    if not path.endswith(".npz"):
        path = path + ".npz"
    if not os.path.exists(path):
        raise RuntimeError("load_combine op: file %r does not exist" % path)
    data = np.load(path)
    outs = op.output("Out")
    for i, n in enumerate(outs):
        key = "arr_%d" % i
        if key not in data:
            raise RuntimeError(
                "load_combine op: file %r holds %d arrays, needs %d"
                % (path, i, len(outs)))
        v = data[key]
        if op.attr("load_as_fp16"):
            v = v.astype(np.float16)
        scope.set(n, jnp.asarray(v))


_HOST_EXEC = {
    "save": _exec_save,
    "load": _exec_load,
    "save_combine": _exec_save_combine,
    "load_combine": _exec_load_combine,
}


def run_host_io_block(block, scope, phase="all"):
    """Execute a block's host-IO ops against the scope (Executor entry
    point).  Compute ops are left for the jit path; ``phase`` selects
    loads (run BEFORE the jitted compute, so loaded vars are visible to
    it) or saves (run AFTER, so they see the step's writebacks) —
    preserving the reference's in-block op order semantics for the
    standard load→compute→save layout."""
    load_types = ("load", "load_combine")
    for op in block.ops:
        fn = _HOST_EXEC.get(op.type)
        if fn is None:
            continue
        if phase == "load" and op.type not in load_types:
            continue
        if phase == "save" and op.type in load_types:
            continue
        fn(op, scope)
