"""Optimizer update ops (reference: ``paddle/fluid/operators/optimizers/`` —
sgd_op.cc, momentum_op.cc, adam_op.cc, adagrad_op.cc, rmsprop_op.cc,
lamb_op.cc, lars_momentum_op.cc …).

Each op reads Param (+ accumulators) and writes the same variables (the
executor's SSA env rebinds the names), so under jit the whole optimizer
update fuses into the step function and the param buffers are donated —
the TPU analogue of the reference's in-place updates plus its fused-optimizer
graph passes (``ir/fuse_optimizer_ops_pass/``), which XLA fusion subsumes.
"""

import jax.numpy as jnp
import numpy as np

from .registry import register_op


def _lr(LearningRate, dtype):
    return LearningRate.reshape(()).astype(dtype)


@register_op("sgd", inputs=["Param", "Grad", "LearningRate"],
             outputs=["ParamOut"], no_grad=True)
def sgd(ctx, attrs, Param, Grad, LearningRate):
    return Param - _lr(LearningRate, Param.dtype) * Grad.astype(Param.dtype)


@register_op(
    "momentum",
    inputs=["Param", "Grad", "Velocity", "LearningRate"],
    outputs=["ParamOut", "VelocityOut"],
    no_grad=True,
)
def momentum(ctx, attrs, Param, Grad, Velocity, LearningRate):
    mu = attrs.get("mu", 0.9)
    lr = _lr(LearningRate, Param.dtype)
    g = Grad.astype(Param.dtype)
    v = jnp.asarray(mu, Param.dtype) * Velocity + g
    if attrs.get("use_nesterov", False):
        p = Param - (g + jnp.asarray(mu, Param.dtype) * v) * lr
    else:
        p = Param - lr * v
    return {"ParamOut": p, "VelocityOut": v}


@register_op(
    "adam",
    inputs=["Param", "Grad", "LearningRate", "Moment1", "Moment2",
            "Beta1Pow", "Beta2Pow"],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"],
    no_grad=True,
)
def adam(ctx, attrs, Param, Grad, LearningRate, Moment1, Moment2,
         Beta1Pow, Beta2Pow):
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(LearningRate, jnp.float32)
    g = Grad.astype(jnp.float32)
    m1 = Moment1.astype(jnp.float32)
    m2 = Moment2.astype(jnp.float32)
    b1p = Beta1Pow.reshape(()).astype(jnp.float32)
    b2p = Beta2Pow.reshape(()).astype(jnp.float32)
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * jnp.square(g)
    # Beta{1,2}Pow hold beta^t when this op reads them (init=beta, advanced
    # after use) — matches reference adam_op.h:93 bias correction
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p = Param.astype(jnp.float32) - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    return {
        "ParamOut": p.astype(Param.dtype),
        "Moment1Out": m1n.astype(Moment1.dtype),
        "Moment2Out": m2n.astype(Moment2.dtype),
        "Beta1PowOut": (b1p * beta1).reshape(Beta1Pow.shape).astype(Beta1Pow.dtype),
        "Beta2PowOut": (b2p * beta2).reshape(Beta2Pow.shape).astype(Beta2Pow.dtype),
    }


@register_op(
    "adamax",
    inputs=["Param", "Grad", "LearningRate", "Moment", "InfNorm", "Beta1Pow"],
    outputs=["ParamOut", "MomentOut", "InfNormOut"],
    no_grad=True,
)
def adamax(ctx, attrs, Param, Grad, LearningRate, Moment, InfNorm, Beta1Pow):
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(LearningRate, Param.dtype)
    m = beta1 * Moment + (1 - beta1) * Grad
    inf = jnp.maximum(beta2 * InfNorm, jnp.abs(Grad) + eps)
    b1p = Beta1Pow.reshape(()).astype(Param.dtype)
    p = Param - (lr / (1 - b1p)) * (m / inf)
    return {"ParamOut": p, "MomentOut": m, "InfNormOut": inf}


@register_op(
    "adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    no_grad=True,
)
def adagrad(ctx, attrs, Param, Grad, Moment, LearningRate):
    eps = attrs.get("epsilon", 1e-6)
    m = Moment + jnp.square(Grad)
    p = Param - _lr(LearningRate, Param.dtype) * Grad / (jnp.sqrt(m) + eps)
    return {"ParamOut": p, "MomentOut": m}


@register_op(
    "decayed_adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    no_grad=True,
)
def decayed_adagrad(ctx, attrs, Param, Grad, Moment, LearningRate):
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m = decay * Moment + (1 - decay) * jnp.square(Grad)
    p = Param - _lr(LearningRate, Param.dtype) * Grad / (jnp.sqrt(m) + eps)
    return {"ParamOut": p, "MomentOut": m}


@register_op(
    "adadelta",
    inputs=["Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"],
    outputs=["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
    no_grad=True,
)
def adadelta(ctx, attrs, Param, Grad, AvgSquaredGrad, AvgSquaredUpdate):
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    asg = rho * AvgSquaredGrad + (1 - rho) * jnp.square(Grad)
    update = -jnp.sqrt((AvgSquaredUpdate + eps) / (asg + eps)) * Grad
    asu = rho * AvgSquaredUpdate + (1 - rho) * jnp.square(update)
    return {
        "ParamOut": Param + update,
        "AvgSquaredGradOut": asg,
        "AvgSquaredUpdateOut": asu,
    }


@register_op(
    "rmsprop",
    inputs=["Param", "Grad", "MeanSquare", "MeanGrad", "Moment",
            "LearningRate"],
    outputs=["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
    no_grad=True,
)
def rmsprop(ctx, attrs, Param, Grad, MeanSquare, MeanGrad, Moment,
            LearningRate):
    eps = attrs.get("epsilon", 1e-10)
    decay = attrs.get("decay", 0.9)
    mom_coef = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    lr = _lr(LearningRate, Param.dtype)
    ms = decay * MeanSquare + (1 - decay) * jnp.square(Grad)
    if centered:
        mg = decay * MeanGrad + (1 - decay) * Grad
        denom = ms - jnp.square(mg) + eps
    else:
        mg = MeanGrad
        denom = ms + eps
    mom = mom_coef * Moment + lr * Grad / jnp.sqrt(denom)
    return {
        "ParamOut": Param - mom,
        "MomentOut": mom,
        "MeanSquareOut": ms,
        "MeanGradOut": mg,
    }


@register_op(
    "ftrl",
    inputs=["Param", "SquaredAccumulator", "LinearAccumulator", "Grad",
            "LearningRate"],
    outputs=["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
    no_grad=True,
)
def ftrl(ctx, attrs, Param, SquaredAccumulator, LinearAccumulator, Grad,
         LearningRate):
    l1 = attrs.get("l1", 0.0) + 1e-10
    l2 = attrs.get("l2", 0.0) + 1e-10
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(LearningRate, Param.dtype)
    new_sq = SquaredAccumulator + jnp.square(Grad)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(SquaredAccumulator)) / lr
    else:
        sigma = (new_sq ** (-lr_power) - SquaredAccumulator ** (-lr_power)) / lr
    linear = LinearAccumulator + Grad - sigma * Param
    if lr_power == -0.5:
        x = l2 + jnp.sqrt(new_sq) / lr
    else:
        x = l2 + new_sq ** (-lr_power) / lr
    pre_shrink = (l1 * jnp.sign(linear) - linear) / x
    p = jnp.where(jnp.abs(linear) > l1, pre_shrink, jnp.zeros_like(Param))
    return {"ParamOut": p, "SquaredAccumOut": new_sq, "LinearAccumOut": linear}


@register_op(
    "lars_momentum",
    inputs=["Param", "Grad", "Velocity", "LearningRate"],
    outputs=["ParamOut", "VelocityOut"],
    no_grad=True,
)
def lars_momentum(ctx, attrs, Param, Grad, Velocity, LearningRate):
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    lr = _lr(LearningRate, jnp.float32)
    p32, g32 = Param.astype(jnp.float32), Grad.astype(jnp.float32)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + decay * p_norm + 1e-12),
        lr,
    )
    v = mu * Velocity.astype(jnp.float32) + local_lr * (g32 + decay * p32)
    return {
        "ParamOut": (p32 - v).astype(Param.dtype),
        "VelocityOut": v.astype(Velocity.dtype),
    }


@register_op(
    "lamb",
    inputs=["Param", "Grad", "LearningRate", "Moment1", "Moment2",
            "Beta1Pow", "Beta2Pow"],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"],
    no_grad=True,
)
def lamb(ctx, attrs, Param, Grad, LearningRate, Moment1, Moment2,
         Beta1Pow, Beta2Pow):
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    lr = _lr(LearningRate, jnp.float32)
    p32 = Param.astype(jnp.float32)
    g32 = Grad.astype(jnp.float32)
    b1p = Beta1Pow.reshape(()).astype(jnp.float32)
    b2p = Beta2Pow.reshape(()).astype(jnp.float32)
    m1 = beta1 * Moment1.astype(jnp.float32) + (1 - beta1) * g32
    m2 = beta2 * Moment2.astype(jnp.float32) + (1 - beta2) * jnp.square(g32)
    m1_hat = m1 / (1 - b1p)
    m2_hat = m2 / (1 - b2p)
    update = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p32
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
    ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
    p = p32 - lr * ratio * update
    return {
        "ParamOut": p.astype(Param.dtype),
        "Moment1Out": m1.astype(Moment1.dtype),
        "Moment2Out": m2.astype(Moment2.dtype),
        "Beta1PowOut": (b1p * beta1).reshape(Beta1Pow.shape).astype(Beta1Pow.dtype),
        "Beta2PowOut": (b2p * beta2).reshape(Beta2Pow.shape).astype(Beta2Pow.dtype),
    }


@register_op(
    "average_accumulates",
    inputs=["param", "in_sum_1", "in_sum_2", "in_sum_3",
            "in_num_accumulates", "in_old_num_accumulates",
            "in_num_updates"],
    outputs=["out_sum_1", "out_sum_2", "out_sum_3", "out_num_accumulates",
             "out_old_num_accumulates", "out_num_updates"],
    no_grad=True,
)
def average_accumulates(ctx, attrs, param, in_sum_1, in_sum_2, in_sum_3,
                        in_num_accumulates, in_old_num_accumulates,
                        in_num_updates):
    """Sliding-window parameter-sum accumulator for ModelAverage
    (reference ``paddle/fluid/operators/average_accumulates_op.h:30``):
    three-tier sums avoid fp precision loss; the window restarts when
    num_accumulates exceeds min(max_average_window,
    num_updates*average_window).  The C++ kernel's host-side branches
    become jnp.where selects so the whole update stays inside jit."""
    s1, s2, s3 = in_sum_1, in_sum_2, in_sum_3
    na, ona, nu = in_num_accumulates, in_old_num_accumulates, in_num_updates
    k_max = 16384  # kMaxNumAccumulates, precision-preserving fold period
    avg_window = float(attrs.get("average_window", 0.0))
    max_w = int(attrs.get("max_average_window", 10000))
    min_w = int(attrs.get("min_average_window", 10000))

    nu = nu + 1
    na = na + 1
    s1 = s1 + param.astype(s1.dtype)
    fold = (nu % k_max) == 0
    s2 = jnp.where(fold, s2 + s1, s2)
    s1 = jnp.where(fold, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.asarray(max_w, jnp.float32), nu.astype(jnp.float32) * avg_window
    )
    restart = (na >= min_w) & (na.astype(jnp.float32) >= window)
    s3 = jnp.where(restart, s1 + s2, s3)
    s1 = jnp.where(restart, jnp.zeros_like(s1), s1)
    s2 = jnp.where(restart, jnp.zeros_like(s2), s2)
    ona = jnp.where(restart, na, ona)
    na = jnp.where(restart, jnp.zeros_like(na), na)
    return {
        "out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
        "out_num_accumulates": na, "out_old_num_accumulates": ona,
        "out_num_updates": nu,
    }


@register_op(
    "proximal_gd", inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"], no_grad=True)
def proximal_gd(ctx, attrs, Param, Grad, LearningRate):
    """Proximal gradient descent (reference
    ``optimizers/proximal_gd_op.cc``): prox_param = p - lr*g, then the
    soft-threshold / shrinkage step with l1 and l2."""
    l1 = jnp.asarray(attrs.get("l1", 0.0), Param.dtype)
    l2 = jnp.asarray(attrs.get("l2", 0.0), Param.dtype)
    lr = _lr(LearningRate, Param.dtype)
    prox = Param - lr * Grad
    shrink = jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return jnp.sign(prox) * shrink / (1.0 + lr * l2)


@register_op(
    "proximal_adagrad",
    inputs=["Param", "Moment", "Grad", "LearningRate"],
    outputs=["ParamOut", "MomentOut"], no_grad=True)
def proximal_adagrad(ctx, attrs, Param, Moment, Grad, LearningRate):
    """Proximal Adagrad (reference ``optimizers/proximal_adagrad_op.cc``):
    accumulate squared grads, take the proximal step with the
    per-element adaptive lr."""
    l1 = jnp.asarray(attrs.get("l1", 0.0), Param.dtype)
    l2 = jnp.asarray(attrs.get("l2", 0.0), Param.dtype)
    lr = _lr(LearningRate, Param.dtype)
    m = Moment + Grad * Grad
    # adaptive lr drives the gradient step; the shrinkage uses the PLAIN
    # scalar lr (proximal_adagrad_op.h: prox_param - lr*l1 thresholds,
    # 1/(1+lr*l2) decay)
    prox = Param - (lr / jnp.sqrt(m)) * Grad
    shrink = jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return jnp.sign(prox) * shrink / (1.0 + lr * l2), m


@register_op(
    "fused_sgd",
    inputs=["Param*", "Grad*", "LearningRate"],
    outputs=["ParamOut*"],
    no_grad=True,
)
def fused_sgd(ctx, attrs, Param, Grad, LearningRate):
    """Multi-tensor SGD: all same-(dtype, lr) param updates of a step as
    one flat stream (the sgd face of Fluid's fuse_optimizer_ops_pass;
    the fusion pipeline groups per dtype so the stream stays uniform).
    Bit-exact vs the per-param op: concatenation does not change the
    elementwise ``p - lr*g`` each segment computes."""
    from .common import flatten_concat, split_like

    dtype = Param[0].dtype
    lr = _lr(LearningRate, dtype)
    new = flatten_concat(Param) - lr * flatten_concat(Grad, dtype)
    return {"ParamOut": split_like(new, Param, cast=False)}


@register_op(
    "fused_adam",
    inputs=["Param*", "Grad*", "LearningRate", "Moment1*", "Moment2*",
            "Beta1Pow*", "Beta2Pow*"],
    outputs=["ParamOut*", "Moment1Out*", "Moment2Out*", "Beta1PowOut*",
             "Beta2PowOut*"],
    no_grad=True,
)
def fused_adam(ctx, attrs, Param, Grad, LearningRate, Moment1, Moment2,
               Beta1Pow, Beta2Pow):
    """All per-param Adam updates of a step in ONE streamed kernel.

    The executor rewrites groups of same-hyperparameter ``adam`` ops into
    this op (reference precedent: the
    ``fuse_optimizer_ops_pass`` ir pass,
    ``framework/ir/fuse_optimizer_ops_pass/fuse_adam_op_pass.cc``, which
    coalesces per-param Adam kernels into one).  On TPU the win is
    bandwidth scheduling: N small elementwise fusions (~185 for
    BERT-base, each paying ramp-up on a few-KB..few-MB tensor) become a
    single flat ~7-bytes/param stream that runs at HBM line rate.

    Math is bit-identical to the per-param op: everything is flattened
    and concatenated in fp32, updated once, and split back; the beta-pow
    scalars stay per-param (cheap) so each param's bias correction reads
    ITS OWN accumulator exactly as before — though the rewrite only
    groups params whose beta pows are in lockstep anyway."""
    from .common import flatten_concat, split_like

    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(LearningRate, jnp.float32)
    shapes = [p.shape for p in Param]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]

    p, g, m1, m2 = (flatten_concat(xs, jnp.float32)
                    for xs in (Param, Grad, Moment1, Moment2))
    # bias correction stays PER PARAM: each member's own beta-pow drives
    # its lr_t (a checkpoint-resumed model can hold accumulators at
    # different steps, e.g. a freshly added layer), broadcast to its
    # segment of the flat stream
    lr_ts = jnp.stack([
        lr * jnp.sqrt(1 - b2.reshape(()).astype(jnp.float32))
        / (1 - b1.reshape(()).astype(jnp.float32))
        for b1, b2 in zip(Beta1Pow, Beta2Pow)
    ])
    # the segment map is STATIC — concat of scalar broadcasts instead of
    # jnp.repeat: repeat's cumsum lowering XLA constant-folds for seconds
    # on every compile (flat-stream-sized scan), and an index-gather
    # alternative would bake a stream-sized int32 constant into HBM;
    # broadcasts fuse to nothing
    lr_t = jnp.concatenate([
        jnp.broadcast_to(lr_ts[i], (n,)) for i, n in enumerate(sizes)
    ])
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * jnp.square(g)
    pn = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)

    return {
        "ParamOut": split_like(pn, Param),
        "Moment1Out": split_like(m1n, Moment1),
        "Moment2Out": split_like(m2n, Moment2),
        "Beta1PowOut": [
            (b.reshape(()).astype(jnp.float32) * beta1)
            .reshape(b.shape).astype(b.dtype) for b in Beta1Pow],
        "Beta2PowOut": [
            (b.reshape(()).astype(jnp.float32) * beta2)
            .reshape(b.shape).astype(b.dtype) for b in Beta2Pow],
    }
