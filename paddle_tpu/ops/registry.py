"""Op registry: op type → XLA lowering rule.

The reference registers each op natively with C++ kernels per (place, dtype,
layout, library) (``paddle/fluid/framework/op_registry.h:197,237``), separate
``InferShape`` functions, and hand-written grad-op makers
(``grad_op_desc_maker.h:36``).  TPU-native, one registered jax lowering
function per op subsumes all three:

* **kernels** — the lowering *is* the kernel; XLA compiles/fuses it for the
  actual device, so there is no per-place kernel table;
* **InferShape** — derived with ``jax.eval_shape`` over the lowering
  (see :func:`infer_shapes`);
* **grad ops** — a generic ``<type>_grad`` lowering is derived with
  ``jax.vjp`` over the forward lowering (:func:`generic_grad_fn`).  Because
  the Executor lowers the whole block into one jaxpr, XLA CSEs the forward
  recomputation inside the vjp against the original forward ops, so the
  default grad costs no extra FLOPs; ops can still register a hand-written
  ``<type>_grad`` where a different formula is preferable.

This mirrors the precedent the reference itself set for graph-compiler
backends: the nGraph bridge's per-op builders (``operators/ngraph/ops/*.h``,
``ngraph_engine.cc:474``), generalized to every op.
"""

import functools

import numpy as np

__all__ = [
    "register_op",
    "get_op_def",
    "has_op",
    "OpDef",
    "OpNotRegistered",
    "LoweringContext",
    "call_op",
    "infer_shapes",
    "infer_output_structs",
    "EMPTY_VAR_NAME",
]

EMPTY_VAR_NAME = "@EMPTY@"

_OP_REGISTRY = {}

_SHAPE_SENTINELS = (100003, 100019, 100043, 100057, 100069, 100103, 100109)


class OpNotRegistered(KeyError):
    pass


def _parse_slots(slots):
    """'X' plain, 'X*' duplicable (list-valued slot)."""
    out = []
    for s in slots or []:
        if s.endswith("*"):
            out.append((s[:-1], True))
        else:
            out.append((s, False))
    return out


def _kwarg_name(slot):
    return slot.replace("@GRAD", "_grad").replace("@", "_")


class OpDef:
    def __init__(self, type, fn, inputs, outputs, no_grad=False,
                 infer_shape=None, grad_maker=None, stateful_outputs=()):
        self.type = type
        self.fn = fn
        self.inputs = _parse_slots(inputs)  # [(slot, duplicable)]
        self.outputs = _parse_slots(outputs)
        self.no_grad = no_grad
        self.custom_infer_shape = infer_shape
        # custom grad maker: fn(op, block, out_grads: {slot: [names]},
        #   in_grads: {slot: [names]}) -> list of op-desc dicts
        self.grad_maker = grad_maker
        # output slots that are state (e.g. batch_norm running stats) —
        # excluded from differentiation paths
        self.stateful_outputs = set(stateful_outputs)

    @property
    def input_slot_names(self):
        return [s for s, _ in self.inputs]

    @property
    def output_slot_names(self):
        return [s for s, _ in self.outputs]


def register_op(type, inputs, outputs, no_grad=False, infer_shape=None,
                grad_maker=None, stateful_outputs=()):
    """Decorator: register `fn(ctx, attrs, **slots)` as the lowering of `type`.

    Slot kwargs are arrays (or lists of arrays for duplicable slots, or None
    for absent optional slots).  Return value: a single array (one output
    slot), a tuple in declared output order, or a dict slot→array/list.
    """

    def deco(fn):
        _OP_REGISTRY[type] = OpDef(
            type, fn, inputs, outputs, no_grad=no_grad,
            infer_shape=infer_shape, grad_maker=grad_maker,
            stateful_outputs=stateful_outputs,
        )
        return fn

    return deco


def has_op(type):
    if type in _OP_REGISTRY:
        return True
    if type.endswith("_grad") and type[: -len("_grad")] in _OP_REGISTRY:
        return True
    return False


def get_op_def(type):
    d = _OP_REGISTRY.get(type)
    if d is not None:
        return d
    if type.endswith("_grad"):
        base = _OP_REGISTRY.get(type[: -len("_grad")])
        if base is not None:
            d = _make_generic_grad_def(base)
            _OP_REGISTRY[type] = d
            return d
    raise OpNotRegistered(type)


class LoweringContext:
    """Per-lowering state threaded through op fns.

    RNG: keys are derived deterministically from (step key, op id, draw index)
    so that a grad op recomputing its forward (vjp) draws identical randomness
    — which both makes dropout-style grads correct and lets XLA CSE the
    recompute against the original forward.
    """

    def __init__(self, base_key=None, mode="train"):
        self.base_key = base_key
        self.mode = mode
        self._op_id = 0
        self._rng_count = 0
        # hook for control-flow ops to lower sub-blocks; set by the executor
        self.lower_sub_block = None
        self.scope = None
        # unbounded-while support (two-pass, reference while_op.cc:189):
        # probing=True makes the `while` op run a host-level Python loop on
        # concrete values recording iteration counts into trip_counts
        # {sub_block_idx: n}; the jit trace then reads the counts as static
        # scan lengths for while_grad
        self.probing = False
        self.trip_counts = None
        # resilience fault injection: optional (name, value) -> value hook
        # applied to every op output at trace time (executor sets it when
        # a PADDLE_TPU_FAULT_SPEC names value faults; None = zero cost)
        self.fault_value_hook = None

    def set_op(self, op_id):
        self._op_id = op_id
        self._rng_count = 0

    def rng(self):
        import jax

        key = self.base_key
        if key is None:
            key = jax.random.key(0)
        k = jax.random.fold_in(jax.random.fold_in(key, self._op_id), self._rng_count)
        self._rng_count += 1
        return k


def _normalize_result(opdef, res):
    """Normalize an op fn's return value to {slot: [values]}."""
    if isinstance(res, dict):
        named = res
    elif isinstance(res, tuple):
        named = {s: v for (s, _), v in zip(opdef.outputs, res)}
    else:
        slot = opdef.outputs[0][0]
        named = {slot: res}
    out = {}
    for slot, dup in opdef.outputs:
        if slot not in named or named[slot] is None:
            continue
        v = named[slot]
        out[slot] = list(v) if isinstance(v, (list, tuple)) else [v]
    return out


def call_op(opdef, ctx, ins, attrs, op_id=0):
    """Invoke an op lowering. `ins`: {slot: [value-or-None]}."""
    ctx.set_op(op_id)
    kwargs = {}
    for slot, dup in opdef.inputs:
        vals = ins.get(slot) or []
        if dup:
            kwargs[_kwarg_name(slot)] = [v for v in vals]
        else:
            kwargs[_kwarg_name(slot)] = vals[0] if vals else None
    res = opdef.fn(ctx, dict(attrs), **kwargs)
    return _normalize_result(opdef, res)


# ---------------------------------------------------------------------------
# Generic grad op derivation via jax.vjp
# ---------------------------------------------------------------------------

def _make_generic_grad_def(fwd_def):
    import jax
    import jax.numpy as jnp

    grad_inputs = []
    for slot, dup in fwd_def.inputs:
        grad_inputs.append(slot + ("*" if dup else ""))
    for slot, dup in fwd_def.outputs:
        grad_inputs.append(slot + ("*" if dup else ""))
        grad_inputs.append(slot + "@GRAD" + ("*" if dup else ""))
    grad_outputs = [
        slot + "@GRAD" + ("*" if dup else "") for slot, dup in fwd_def.inputs
    ]

    def grad_fn(ctx, attrs, **kwargs):
        # reconstruct raw slot dicts from kwargs
        fwd_in = {}
        for slot, dup in fwd_def.inputs:
            v = kwargs.get(_kwarg_name(slot))
            if v is None:
                continue
            fwd_in[slot] = list(v) if dup else [v]
        out_grads = {}
        for slot, dup in fwd_def.outputs:
            g = kwargs.get(_kwarg_name(slot + "@GRAD"))
            if g is None:
                continue
            out_grads[slot] = list(g) if dup else [g]

        fwd_op_id = attrs.get("__fwd_op_id__", attrs.get("__op_id__", 0))

        def f(fin):
            return call_op(fwd_def, ctx, fin, attrs, op_id=fwd_op_id)

        primal, vjp_fn = jax.vjp(f, fwd_in)
        # build cotangents matching the primal pytree exactly
        cot = {}
        for slot, vals in primal.items():
            gs = out_grads.get(slot)
            lst = []
            for i, p in enumerate(vals):
                g = gs[i] if gs is not None and i < len(gs) and gs[i] is not None else None
                if g is None or slot in fwd_def.stateful_outputs:
                    g = jnp.zeros(jnp.shape(p), _cotangent_dtype(p))
                else:
                    g = g.astype(_cotangent_dtype(p))
                # under shard_map the primal may be varying over manual
                # mesh axes; a freshly built cotangent is replicated and
                # jax rejects the vma mismatch — promote it to match.
                # (jax.typeof only exists on jax versions that track vma
                # avals; without it there is no mismatch to repair)
                _typeof = getattr(jax, "typeof", None)
                missing = frozenset() if _typeof is None else (
                    getattr(_typeof(p), "vma", frozenset())
                    - getattr(_typeof(g), "vma", frozenset()))
                if missing:
                    if hasattr(jax.lax, "pcast"):
                        g = jax.lax.pcast(
                            g, tuple(missing), to="varying")
                    else:
                        g = jax.lax.pvary(g, tuple(missing))
                lst.append(g)
            cot[slot] = lst
        (gin,) = vjp_fn(cot)
        result = {}
        for slot, dup in fwd_def.inputs:
            if slot not in gin:
                continue
            vals = []
            for i, g in enumerate(gin[slot]):
                if g is None or g.dtype == jax.dtypes.float0:
                    # non-differentiable (int) input: emit zeros so the slot
                    # is well-formed if someone requested it anyway
                    p = fwd_in[slot][i]
                    g = jnp.zeros(jnp.shape(p), jnp.float32)
                vals.append(g)
            result[slot + "@GRAD"] = vals
        return result

    return OpDef(
        fwd_def.type + "_grad", grad_fn, grad_inputs, grad_outputs, no_grad=True
    )


def _cotangent_dtype(p):
    import jax.numpy as jnp

    d = jnp.result_type(p)
    if jnp.issubdtype(d, jnp.floating) or jnp.issubdtype(d, jnp.complexfloating):
        return d
    return jnp.float32


# ---------------------------------------------------------------------------
# Shape/dtype inference via jax.eval_shape
# ---------------------------------------------------------------------------

def _np_dtype_of(var):
    import jax.numpy as jnp

    if var.dtype == "bfloat16":
        return jnp.bfloat16
    return np.dtype(var.dtype)


def infer_shapes(op, block):
    """Infer output var shapes/dtypes for a freshly appended op by running
    jax.eval_shape over its lowering, with -1 dims replaced by sentinel
    primes (mapped back to -1 afterwards).  Static shapes here are
    graph-construction metadata only; execution re-traces with concrete feed
    shapes, so approximation is acceptable (the reference's InferShape has
    the same -1-propagation looseness, framework.py:985)."""
    opdef = get_op_def(op.type)

    if opdef.custom_infer_shape is not None:
        opdef.custom_infer_shape(op, block)
        return

    inferred = infer_output_structs(op, block)
    if inferred is None:
        return
    for n, (shape, dtype) in inferred.items():
        var = block._find_var_recursive(n)
        if var is None:
            continue
        var.shape = shape
        var.dtype = dtype


def infer_output_structs(op, block):
    """Non-mutating core of :func:`infer_shapes`: eval_shape the op's
    lowering against the recorded input metadata and return
    ``{out_var_name: (shape_with_-1_dims, dtype_str)}``, or None when the
    op is not inferable this way (custom InferShape, un-inferable inputs,
    sentinel arithmetic broke the trace).  The verifier diffs this against
    recorded Variable metadata to catch drift introduced by pass rewrites
    without touching the graph."""
    import jax

    opdef = get_op_def(op.type)
    if opdef.custom_infer_shape is not None:
        return None

    ins = {}
    used_sentinel = False
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n == EMPTY_VAR_NAME:
                vals.append(None)
                continue
            var = block._find_var_recursive(n)
            if var is None or var.shape is None:
                return None  # cannot infer
            shape = []
            for i, d in enumerate(var.shape):
                if d is None or d < 0:
                    shape.append(_SHAPE_SENTINELS[i % len(_SHAPE_SENTINELS)])
                    used_sentinel = True
                else:
                    shape.append(int(d))
            vals.append(jax.ShapeDtypeStruct(tuple(shape), _np_dtype_of(var)))
        ins[slot] = vals

    ctx = LoweringContext(base_key=None, mode="infer")

    def f(ins_):
        return call_op(opdef, ctx, ins_, op.attrs, op_id=op.attrs.get("__op_id__", 0))

    try:
        out_structs = jax.eval_shape(f, ins)
    except Exception:
        if used_sentinel:
            return None  # sentinel arithmetic broke the trace
        raise

    sent = set(_SHAPE_SENTINELS)
    out = {}
    for slot, names in op.outputs.items():
        structs = out_structs.get(slot)
        if structs is None:
            continue
        for n, s in zip(names, structs):
            if s is None or n == EMPTY_VAR_NAME:
                continue
            shape = tuple(-1 if d in sent else int(d) for d in s.shape)
            dtype = ("bfloat16" if s.dtype == _np_dtype_of_bf16()
                     else np.dtype(s.dtype).name)
            out[n] = (shape, dtype)
    return out


@functools.lru_cache(maxsize=1)
def _np_dtype_of_bf16():
    import jax.numpy as jnp

    return jnp.bfloat16
