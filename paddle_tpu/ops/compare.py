"""Comparison / logical ops (reference:
``paddle/fluid/operators/controlflow/compare_op.cc``, ``logical_op.cc``)."""

import jax.numpy as jnp

from .registry import register_op
from .common import fluid_broadcast


def _compare(name, fn):
    @register_op(name, inputs=["X", "Y"], outputs=["Out"], no_grad=True)
    def _op(ctx, attrs, X, Y, _fn=fn):
        x, y = fluid_broadcast(X, Y, attrs.get("axis", -1))
        return _fn(x, y)

    return _op


_compare("less_than", jnp.less)
_compare("less_equal", jnp.less_equal)
_compare("greater_than", jnp.greater)
_compare("greater_equal", jnp.greater_equal)
_compare("equal", jnp.equal)
_compare("not_equal", jnp.not_equal)


@register_op("logical_and", inputs=["X", "Y"], outputs=["Out"], no_grad=True)
def logical_and(ctx, attrs, X, Y):
    return jnp.logical_and(X, Y)


@register_op("logical_or", inputs=["X", "Y"], outputs=["Out"], no_grad=True)
def logical_or(ctx, attrs, X, Y):
    return jnp.logical_or(X, Y)


@register_op("logical_xor", inputs=["X", "Y"], outputs=["Out"], no_grad=True)
def logical_xor(ctx, attrs, X, Y):
    return jnp.logical_xor(X, Y)


@register_op("logical_not", inputs=["X"], outputs=["Out"], no_grad=True)
def logical_not(ctx, attrs, X):
    return jnp.logical_not(X)


@register_op("where", inputs=["Condition", "X", "Y"], outputs=["Out"])
def where(ctx, attrs, Condition, X, Y):
    return jnp.where(Condition, X, Y)
