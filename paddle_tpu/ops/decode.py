"""Autoregressive decoding ops: ring-buffer KV cache and sampling.

The recompile-free decode contract: the KV cache is a device-resident
ring buffer with a STATIC max shape (``[B, H, Tmax, D]``) and an integer
write cursor, so every decode step lowers to the same jaxpr regardless
of how many tokens have been generated — the Executor's jit cache holds
ONE entry for the whole generation (the reference's
``DecoderBase``/``TrainingHelper`` per-step graphs re-specialize on the
growing sequence; see the ``decode-shape-unbucketed`` lint check).

All ops here are grad-free forward-only registrations (the
LoDTensorArray pattern in ``ops/control_flow.py``): generation is pure
inference, and keeping the while body grad-free is what keeps the
executor off the unbounded-while host-probing path (a per-step host
sync that would fail the PR-10 zero-sync certificate).

Cursor convention: ``Cursor`` is int32 of shape ``[1]`` (shared scalar
cursor — every row at the same position, the single-program decode
loop) or ``[B]`` with attr ``per_row=True`` (continuous batching:
each serving slot is at its own generation depth).  Writes wrap at
``Tmax`` (ring semantics); reads mask to ``min(cursor, Tmax)``.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

NEG_INF = -1e30


def _cursor_starts(Cursor, per_row, batch):
    """int32 [B] positions from a [1]/[] shared cursor or [B] per-row."""
    cur = jnp.asarray(Cursor, jnp.int32).reshape(-1)
    if per_row:
        return jnp.broadcast_to(cur, (batch,))
    return jnp.broadcast_to(cur[0], (batch,))


def _norm_kv(X, cache):
    """New K/V entries as [B, H, 1, D] (accepts [B, H, D] too)."""
    if X.ndim == cache.ndim - 1:
        X = X[:, :, None, :]
    return X.astype(cache.dtype)


@register_op("kv_cache_write", inputs=["Cache", "X", "Cursor"],
             outputs=["Out"], no_grad=True)
def kv_cache_write(ctx, attrs, Cache, X, Cursor):
    """Write this step's K (or V) row into the ring cache at the cursor.

    Cache [B, H, Tmax, D]; X [B, H, D] (or [B, H, 1, D]); Cursor [1] or
    [B] (``per_row=True``).  Position wraps at Tmax — the ring-buffer
    half of the static-shape contract.  The shared-cursor path is a
    single ``dynamic_update_slice``; the per-row path is a one-hot
    masked merge (each serving slot writes its own depth).
    """
    b, h, t, d = Cache.shape
    X = _norm_kv(X, Cache)
    per_row = bool(attrs.get("per_row", False))
    if not per_row:
        pos = jnp.asarray(Cursor, jnp.int32).reshape(-1)[0] % t
        return lax.dynamic_update_slice(Cache, X, (0, 0, pos, 0))
    pos = _cursor_starts(Cursor, True, b) % t          # [B]
    onehot = jax.nn.one_hot(pos, t, dtype=Cache.dtype)  # [B, T]
    m = onehot[:, None, :, None]                        # [B, 1, T, 1]
    return Cache * (1.0 - m) + X * m


@register_op("kv_cache_prefill", inputs=["Cache", "X", "Slot"],
             outputs=["Out"], no_grad=True)
def kv_cache_prefill(ctx, attrs, Cache, X, Slot):
    """Bulk-write a prompt's K/V rows into cache positions [0, L).

    Cache [B, H, Tmax, D]; X [B, H, L, D] (L static — the prompt
    bucket).  With ``Slot`` given ([1] int32), X is [1, H, L, D] and
    lands in cache row ``slot`` — the serving path that carves the
    per-slot cache blocks out of one resident buffer.
    """
    X = X.astype(Cache.dtype)
    if Slot is None:
        return lax.dynamic_update_slice(Cache, X, (0, 0, 0, 0))
    slot = jnp.asarray(Slot, jnp.int32).reshape(-1)[0]
    return lax.dynamic_update_slice(Cache, X, (slot, 0, 0, 0))


@register_op("flash_decode_attention",
             inputs=["Q", "KCache", "VCache", "Cursor"],
             outputs=["Out"], no_grad=True)
def flash_decode_attention(ctx, attrs, Q, KCache, VCache, Cursor):
    """Single-query attention against the ring cache, masked to the
    cursor.  Q [B, H, D] (or [B, H, 1, D]); caches [B, H, Tmax, D];
    Cursor = number of VALID entries (typically prompt_len + step + 1).
    Pallas flash-decode kernel on TPU past the measured engagement
    threshold, XLA composite otherwise (ops/pallas/flash_decode.py)."""
    from .pallas.flash_decode import flash_decode

    squeeze = False
    if Q.ndim == 4:
        Q = Q[:, :, 0, :]
        squeeze = True
    b, h, d = Q.shape
    t = KCache.shape[2]
    sm_scale = attrs.get("sm_scale")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    per_row = bool(attrs.get("per_row", False))
    lens = _cursor_starts(Cursor, per_row, b)
    lens = jnp.minimum(lens, t)  # ring: at most Tmax entries are live
    out = flash_decode(Q, KCache, VCache, lens, sm_scale=float(sm_scale))
    return out[:, :, None, :] if squeeze else out


def _sampling_key(ctx, attrs, Step):
    """Deterministic per-(op, seed, step) key: the registry's derived
    base key, folded with the user seed and the loop index so every
    decode step draws fresh noise yet replays bit-exactly."""
    key = ctx.rng()
    key = jax.random.fold_in(key, int(attrs.get("seed", 0)) & 0x7FFFFFFF)
    if Step is not None:
        step = jnp.asarray(Step, jnp.int32).reshape(-1)[0]
        key = jax.random.fold_in(key, step)
    return key


@register_op("top_k_sampling", inputs=["X", "Step"], outputs=["Out"],
             no_grad=True)
def top_k_sampling(ctx, attrs, X, Step):
    """Sample token ids from the top-k of each row of logits X [B, V].

    attrs: ``k`` (1 = greedy), ``temperature`` (<= 0 = greedy argmax),
    ``seed``.  ``Step`` (optional [1] int32, the decode loop index) is
    folded into the RNG key — inside a while body the op lowers once,
    so without it every step would redraw identical noise.  Gumbel-max
    over the top-k keeps the draw a single fused argmax."""
    k = int(attrs.get("k", 1))
    temp = float(attrs.get("temperature", 1.0))
    if k <= 1 or temp <= 0.0:
        return jnp.argmax(X, axis=-1).astype(jnp.int32)
    k = min(k, X.shape[-1])
    vals, idx = lax.top_k(X, k)  # [B, k]
    g = jax.random.gumbel(_sampling_key(ctx, attrs, Step), vals.shape,
                          jnp.float32)
    choice = jnp.argmax(vals.astype(jnp.float32) / temp + g, axis=-1)
    out = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
    return out.astype(jnp.int32)


@register_op("top_p_sampling", inputs=["X", "Step"], outputs=["Out"],
             no_grad=True)
def top_p_sampling(ctx, attrs, X, Step):
    """Nucleus sampling: keep the smallest prefix of the descending
    softmax whose mass reaches ``p`` (the head token always survives),
    then gumbel-max over the survivors.  attrs: ``p``, ``temperature``
    (<= 0 = greedy), ``seed``; ``Step`` as in top_k_sampling."""
    p = float(attrs.get("p", 0.9))
    temp = float(attrs.get("temperature", 1.0))
    if temp <= 0.0:
        return jnp.argmax(X, axis=-1).astype(jnp.int32)
    order = jnp.argsort(-X, axis=-1)
    sorted_logits = jnp.take_along_axis(X, order, axis=-1) / temp
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = (csum - probs) < p  # exclusive prefix mass: head always kept
    masked = jnp.where(keep, sorted_logits.astype(jnp.float32), NEG_INF)
    g = jax.random.gumbel(_sampling_key(ctx, attrs, Step), masked.shape,
                          jnp.float32)
    choice = jnp.argmax(masked + g, axis=-1)
    out = jnp.take_along_axis(order, choice[:, None], axis=1)[:, 0]
    return out.astype(jnp.int32)
