"""Autoregressive decoding ops: ring-buffer KV cache and sampling.

The recompile-free decode contract: the KV cache is a device-resident
ring buffer with a STATIC max shape (``[B, H, Tmax, D]``) and an integer
write cursor, so every decode step lowers to the same jaxpr regardless
of how many tokens have been generated — the Executor's jit cache holds
ONE entry for the whole generation (the reference's
``DecoderBase``/``TrainingHelper`` per-step graphs re-specialize on the
growing sequence; see the ``decode-shape-unbucketed`` lint check).

All ops here are grad-free forward-only registrations (the
LoDTensorArray pattern in ``ops/control_flow.py``): generation is pure
inference, and keeping the while body grad-free is what keeps the
executor off the unbounded-while host-probing path (a per-step host
sync that would fail the PR-10 zero-sync certificate).

Cursor convention: ``Cursor`` is int32 of shape ``[1]`` (shared scalar
cursor — every row at the same position, the single-program decode
loop) or ``[B]`` with attr ``per_row=True`` (continuous batching:
each serving slot is at its own generation depth).  Writes wrap at
``Tmax`` (ring semantics); reads mask to ``min(cursor, Tmax)``.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

NEG_INF = -1e30


def _cursor_starts(Cursor, per_row, batch):
    """int32 [B] positions from a [1]/[] shared cursor or [B] per-row."""
    cur = jnp.asarray(Cursor, jnp.int32).reshape(-1)
    if per_row:
        return jnp.broadcast_to(cur, (batch,))
    return jnp.broadcast_to(cur[0], (batch,))


def _norm_kv(X, cache):
    """New K/V entries as [B, H, 1, D] (accepts [B, H, D] too)."""
    if X.ndim == cache.ndim - 1:
        X = X[:, :, None, :]
    return X.astype(cache.dtype)


@register_op("kv_cache_write", inputs=["Cache", "X", "Cursor"],
             outputs=["Out"], no_grad=True)
def kv_cache_write(ctx, attrs, Cache, X, Cursor):
    """Write this step's K (or V) row into the ring cache at the cursor.

    Cache [B, H, Tmax, D]; X [B, H, D] (or [B, H, 1, D]); Cursor [1] or
    [B] (``per_row=True``).  Position wraps at Tmax — the ring-buffer
    half of the static-shape contract.  The shared-cursor path is a
    single ``dynamic_update_slice``; the per-row path is a one-hot
    masked merge (each serving slot writes its own depth).
    """
    b, h, t, d = Cache.shape
    X = _norm_kv(X, Cache)
    per_row = bool(attrs.get("per_row", False))
    if not per_row:
        pos = jnp.asarray(Cursor, jnp.int32).reshape(-1)[0] % t
        return lax.dynamic_update_slice(Cache, X, (0, 0, pos, 0))
    pos = _cursor_starts(Cursor, True, b) % t          # [B]
    onehot = jax.nn.one_hot(pos, t, dtype=Cache.dtype)  # [B, T]
    m = onehot[:, None, :, None]                        # [B, 1, T, 1]
    return Cache * (1.0 - m) + X * m


@register_op("kv_cache_prefill", inputs=["Cache", "X", "Slot"],
             outputs=["Out"], no_grad=True)
def kv_cache_prefill(ctx, attrs, Cache, X, Slot):
    """Bulk-write a prompt's K/V rows into cache positions [0, L).

    Cache [B, H, Tmax, D]; X [B, H, L, D] (L static — the prompt
    bucket).  With ``Slot`` given ([1] int32), X is [1, H, L, D] and
    lands in cache row ``slot`` — the serving path that carves the
    per-slot cache blocks out of one resident buffer.
    """
    X = X.astype(Cache.dtype)
    if Slot is None:
        return lax.dynamic_update_slice(Cache, X, (0, 0, 0, 0))
    slot = jnp.asarray(Slot, jnp.int32).reshape(-1)[0]
    return lax.dynamic_update_slice(Cache, X, (slot, 0, 0, 0))


@register_op("flash_decode_attention",
             inputs=["Q", "KCache", "VCache", "Cursor"],
             outputs=["Out"], no_grad=True)
def flash_decode_attention(ctx, attrs, Q, KCache, VCache, Cursor):
    """Single-query attention against the ring cache, masked to the
    cursor.  Q [B, H, D] (or [B, H, 1, D]); caches [B, H, Tmax, D];
    Cursor = number of VALID entries (typically prompt_len + step + 1).
    Pallas flash-decode kernel on TPU past the measured engagement
    threshold, XLA composite otherwise (ops/pallas/flash_decode.py)."""
    from .pallas.flash_decode import flash_decode

    squeeze = False
    if Q.ndim == 4:
        Q = Q[:, :, 0, :]
        squeeze = True
    b, h, d = Q.shape
    t = KCache.shape[2]
    sm_scale = attrs.get("sm_scale")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    per_row = bool(attrs.get("per_row", False))
    lens = _cursor_starts(Cursor, per_row, b)
    lens = jnp.minimum(lens, t)  # ring: at most Tmax entries are live
    out = flash_decode(Q, KCache, VCache, lens, sm_scale=float(sm_scale))
    return out[:, :, None, :] if squeeze else out


# ---------------------------------------------------------------------------
# paged KV cache (ISSUE 19): fixed-size blocks + per-request block tables
# ---------------------------------------------------------------------------


def _norm_table(BlockTable, rows):
    """int32 ``[rows, MB]`` block table (accepts a single ``[MB]``
    row, broadcast is NOT implied — a 1-D table means rows == 1)."""
    table = jnp.asarray(BlockTable, jnp.int32)
    if table.ndim == 1:
        table = table[None, :]
    return table.reshape(rows, -1)


@register_op("paged_kv_cache_write",
             inputs=["Cache", "X", "Cursor", "BlockTable"],
             outputs=["Out"], no_grad=True)
def paged_kv_cache_write(ctx, attrs, Cache, X, Cursor, BlockTable):
    """Write this step's K (or V) rows into the paged pool through the
    block table.

    Cache ``[N, H, BL, D]`` (the shared pool); X ``[S, H, D]`` (or
    ``[S, H, 1, D]``); Cursor ``[S]`` with ``per_row=True`` (each
    stream's own depth — the serving default) or ``[1]`` shared;
    BlockTable ``[S, MB]`` int32, ``-1`` = unmapped.  Row ``s`` lands in
    pool block ``table[s, cursor//BL]`` at offset ``cursor % BL``; a row
    routed to an unmapped entry (or an inactive stream carrying ``-1``)
    is dropped, leaving the pool untouched — the scatter-level
    ownership guarantee the allocator's no-double-assign invariant
    builds on."""
    n, h, bl, d = Cache.shape
    X = _norm_kv(X, Cache)[:, :, 0, :]                   # [S, H, D]
    s = X.shape[0]
    per_row = bool(attrs.get("per_row", True))
    pos = _cursor_starts(Cursor, per_row, s)             # [S]
    table = _norm_table(BlockTable, s)
    blk = jnp.take_along_axis(
        table, jnp.clip(pos // bl, 0, table.shape[1] - 1)[:, None],
        axis=1)[:, 0]                                    # [S]
    off = pos % bl
    # unmapped → an out-of-range index that mode="drop" discards
    blk = jnp.where(blk < 0, n, blk)
    return Cache.at[blk, :, off, :].set(X, mode="drop")


@register_op("paged_kv_cache_prefill",
             inputs=["Cache", "X", "Len", "BlockTable"],
             outputs=["Out"], no_grad=True)
def paged_kv_cache_prefill(ctx, attrs, Cache, X, Len, BlockTable):
    """Bulk-write a prompt's K/V rows into the table's blocks.

    Cache ``[N, H, BL, D]``; X ``[1, H, L, D]`` (L static — the prompt
    bucket); Len ``[1]`` int32 (real prompt length — padded positions
    ``>= Len`` are dropped, not written); BlockTable ``[MB]`` (or
    ``[1, MB]``).  Logical position ``p`` lands in block
    ``table[p // BL]`` offset ``p % BL``."""
    n, h, bl, d = Cache.shape
    if X.ndim == 4:
        X = X[0]
    X = X.astype(Cache.dtype)                            # [H, L, D]
    L = X.shape[1]
    table = _norm_table(BlockTable, 1)[0]                # [MB]
    pos = jnp.arange(L, dtype=jnp.int32)
    blk = table[jnp.clip(pos // bl, 0, table.shape[0] - 1)]
    off = pos % bl
    ln = jnp.asarray(Len, jnp.int32).reshape(-1)[0]
    blk = jnp.where((pos < ln) & (blk >= 0), blk, n)     # else dropped
    Xl = jnp.transpose(X, (1, 0, 2))                     # [L, H, D]
    return Cache.at[blk, :, off, :].set(Xl, mode="drop")


@register_op("paged_flash_decode_attention",
             inputs=["Q", "KCache", "VCache", "Cursor", "BlockTable"],
             outputs=["Out"], no_grad=True)
def paged_flash_decode_attention(ctx, attrs, Q, KCache, VCache, Cursor,
                                 BlockTable):
    """Single-query attention through the block table, masked to the
    cursor.  Q ``[S, H, D]`` (or ``[S, H, 1, D]``); pool caches
    ``[N, H, BL, D]``; Cursor = valid entries per stream (``per_row``
    default true).  Rows are independent — the speculative-decoding
    verify feeds ``k+1`` rows per stream with graduated cursors and
    repeated table rows, scoring every draft position in ONE launch.
    Pallas paged kernel on TPU past the ``decode`` family's engagement
    threshold, gather + ring-oracle composite otherwise
    (ops/pallas/paged_flash_decode.py)."""
    from .pallas.paged_flash_decode import paged_flash_decode

    squeeze = False
    if Q.ndim == 4:
        Q = Q[:, :, 0, :]
        squeeze = True
    s, h, d = Q.shape
    bl = KCache.shape[2]
    sm_scale = attrs.get("sm_scale")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    per_row = bool(attrs.get("per_row", True))
    table = _norm_table(BlockTable, s)
    lens = _cursor_starts(Cursor, per_row, s)
    # at most the table's mapped depth is live
    lens = jnp.minimum(lens, table.shape[1] * bl)
    out = paged_flash_decode(Q, KCache, VCache, lens, table,
                             sm_scale=float(sm_scale))
    return out[:, :, None, :] if squeeze else out


def _sampling_key(ctx, attrs, Step):
    """Deterministic per-(op, seed, step) key: the registry's derived
    base key, folded with the user seed and the loop index so every
    decode step draws fresh noise yet replays bit-exactly."""
    key = ctx.rng()
    key = jax.random.fold_in(key, int(attrs.get("seed", 0)) & 0x7FFFFFFF)
    if Step is not None:
        step = jnp.asarray(Step, jnp.int32).reshape(-1)[0]
        key = jax.random.fold_in(key, step)
    return key


@register_op("top_k_sampling", inputs=["X", "Step"], outputs=["Out"],
             no_grad=True)
def top_k_sampling(ctx, attrs, X, Step):
    """Sample token ids from the top-k of each row of logits X [B, V].

    attrs: ``k`` (1 = greedy), ``temperature`` (<= 0 = greedy argmax),
    ``seed``.  ``Step`` (optional [1] int32, the decode loop index) is
    folded into the RNG key — inside a while body the op lowers once,
    so without it every step would redraw identical noise.  Gumbel-max
    over the top-k keeps the draw a single fused argmax."""
    k = int(attrs.get("k", 1))
    temp = float(attrs.get("temperature", 1.0))
    if k <= 1 or temp <= 0.0:
        return jnp.argmax(X, axis=-1).astype(jnp.int32)
    k = min(k, X.shape[-1])
    vals, idx = lax.top_k(X, k)  # [B, k]
    g = jax.random.gumbel(_sampling_key(ctx, attrs, Step), vals.shape,
                          jnp.float32)
    choice = jnp.argmax(vals.astype(jnp.float32) / temp + g, axis=-1)
    out = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
    return out.astype(jnp.int32)


@register_op("top_p_sampling", inputs=["X", "Step"], outputs=["Out"],
             no_grad=True)
def top_p_sampling(ctx, attrs, X, Step):
    """Nucleus sampling: keep the smallest prefix of the descending
    softmax whose mass reaches ``p`` (the head token always survives),
    then gumbel-max over the survivors.  attrs: ``p``, ``temperature``
    (<= 0 = greedy), ``seed``; ``Step`` as in top_k_sampling."""
    p = float(attrs.get("p", 0.9))
    temp = float(attrs.get("temperature", 1.0))
    if temp <= 0.0:
        return jnp.argmax(X, axis=-1).astype(jnp.int32)
    order = jnp.argsort(-X, axis=-1)
    sorted_logits = jnp.take_along_axis(X, order, axis=-1) / temp
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = (csum - probs) < p  # exclusive prefix mass: head always kept
    masked = jnp.where(keep, sorted_logits.astype(jnp.float32), NEG_INF)
    g = jax.random.gumbel(_sampling_key(ctx, attrs, Step), masked.shape,
                          jnp.float32)
    choice = jnp.argmax(masked + g, axis=-1)
    out = jnp.take_along_axis(order, choice[:, None], axis=1)[:, 0]
    return out.astype(jnp.int32)
