"""Activation ops (reference: ``paddle/fluid/operators/activation_op.cc`` —
one REGISTER_OPERATOR + CPU/CUDA functor pair per activation; here one jnp
expression each, fused by XLA into whatever op precedes them)."""

import jax
import jax.numpy as jnp

from .registry import register_op


def _unary(name, fn):
    @register_op(name, inputs=["X"], outputs=["Out"])
    def _op(ctx, attrs, X, _fn=fn):
        return _fn(X)

    return _op


_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("logsigmoid", jax.nn.log_sigmoid)
_unary("tanh", jnp.tanh)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("abs", jnp.abs)
_unary("square", jnp.square)
_unary("reciprocal", jnp.reciprocal)
_unary("softplus", jax.nn.softplus)
_unary("softsign", jax.nn.soft_sign)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("cos", jnp.cos)
_unary("sin", jnp.sin)
_unary("acos", jnp.arccos)
_unary("asin", jnp.arcsin)
_unary("atan", jnp.arctan)
_unary("tanh_shrink", lambda x: x - jnp.tanh(x))
_unary("relu6", lambda x: jnp.clip(x, 0.0, 6.0))
_unary("sign", jnp.sign)
_unary("erf", jax.lax.erf)


@register_op("gelu", inputs=["X"], outputs=["Out"])
def gelu(ctx, attrs, X):
    return jax.nn.gelu(X, approximate=bool(attrs.get("approximate", False)))


@register_op("leaky_relu", inputs=["X"], outputs=["Out"])
def leaky_relu(ctx, attrs, X):
    alpha = attrs.get("alpha", 0.02)
    return jnp.where(X >= 0, X, jnp.asarray(alpha, X.dtype) * X)


@register_op("elu", inputs=["X"], outputs=["Out"])
def elu(ctx, attrs, X):
    return jax.nn.elu(X, alpha=attrs.get("alpha", 1.0))


@register_op("hard_sigmoid", inputs=["X"], outputs=["Out"])
def hard_sigmoid(ctx, attrs, X):
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return jnp.clip(slope * X + offset, 0.0, 1.0).astype(X.dtype)


@register_op("hard_swish", inputs=["X"], outputs=["Out"])
def hard_swish(ctx, attrs, X):
    threshold = attrs.get("threshold", 6.0)
    s = attrs.get("scale", 6.0)
    offset = attrs.get("offset", 3.0)
    return X * jnp.clip(X + offset, 0.0, threshold).astype(X.dtype) / s


@register_op("swish", inputs=["X"], outputs=["Out"])
def swish(ctx, attrs, X):
    beta = attrs.get("beta", 1.0)
    return X * jax.nn.sigmoid(jnp.asarray(beta, X.dtype) * X)


@register_op("brelu", inputs=["X"], outputs=["Out"])
def brelu(ctx, attrs, X):
    return jnp.clip(X, attrs.get("t_min", 0.0), attrs.get("t_max", 24.0))


@register_op("soft_relu", inputs=["X"], outputs=["Out"])
def soft_relu(ctx, attrs, X):
    threshold = attrs.get("threshold", 40.0)
    return jnp.log1p(jnp.exp(jnp.clip(X, -threshold, threshold)))


@register_op("stanh", inputs=["X"], outputs=["Out"])
def stanh(ctx, attrs, X):
    a = attrs.get("scale_a", 0.67)
    b = attrs.get("scale_b", 1.7159)
    return jnp.asarray(b, X.dtype) * jnp.tanh(jnp.asarray(a, X.dtype) * X)


@register_op("thresholded_relu", inputs=["X"], outputs=["Out"])
def thresholded_relu(ctx, attrs, X):
    t = attrs.get("threshold", 1.0)
    return jnp.where(X > t, X, jnp.zeros_like(X))


@register_op("hard_shrink", inputs=["X"], outputs=["Out"])
def hard_shrink(ctx, attrs, X):
    t = attrs.get("threshold", 0.5)
    return jnp.where(jnp.abs(X) > t, X, jnp.zeros_like(X))


@register_op("softshrink", inputs=["X"], outputs=["Out"])
def softshrink(ctx, attrs, X):
    lam = attrs.get("lambda", 0.5)
    return jnp.where(
        X > lam, X - lam, jnp.where(X < -lam, X + lam, jnp.zeros_like(X))
    )
