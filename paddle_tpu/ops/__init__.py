"""Op library: each module registers XLA lowerings with the registry.

Importing this package populates the registry (the analogue of the
reference's static REGISTER_OPERATOR initializers,
``paddle/fluid/framework/op_registry.h:197``).
"""

from . import registry
from .registry import (
    register_op,
    get_op_def,
    has_op,
    OpDef,
    OpNotRegistered,
    LoweringContext,
    call_op,
    EMPTY_VAR_NAME,
)

# op families — import order is unimportant; each module only registers
from . import basic  # noqa: F401
from . import math  # noqa: F401
from . import activations  # noqa: F401
from . import nn  # noqa: F401
from . import tensor_manip  # noqa: F401
from . import compare  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import control_flow  # noqa: F401
from . import rnn  # noqa: F401
from . import sequence  # noqa: F401
from . import collective  # noqa: F401
from . import detection  # noqa: F401
from . import metrics  # noqa: F401
from . import beam_search  # noqa: F401
from . import decode  # noqa: F401
from . import quantize  # noqa: F401
from . import vision  # noqa: F401
from . import losses  # noqa: F401
from . import crf_ctc  # noqa: F401
from . import misc  # noqa: F401
from . import extra  # noqa: F401
from . import io_ops  # noqa: F401
