"""CompiledProgram + build/exec strategies (reference:
``python/paddle/fluid/compiler.py`` + ``details/build_strategy.h:36``).

The reference's ``with_data_parallel`` constructs a C++ ParallelExecutor
that clones the graph per GPU and inserts NCCL all-reduce op-handles
(``multi_devices_graph_pass.cc:454``).  TPU-native, the same call records a
``jax.sharding.Mesh`` over the data axis and the executor jits the SAME
program with batch-sharded inputs and replicated params — GSPMD emits the
grad all-reduce over ICI.  The BuildStrategy knobs that survive are the ones
XLA doesn't subsume: donation, remat, and the ``fuse_*`` family — which
since the fusion-pipeline PR drive REAL cost-guided Program-IR rewrites
(``static_analysis/fusion.py``: Pallas attention/LN kernels, fused
bias+act, one-op softmax+xent, multi-tensor optimizer updates, bucketed
gradient allreduce).  Only reduce-strategy / hierarchical-allreduce remain
accepted-for-parity no-ops (GSPMD always emits fused ring allreduce).
"""

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = (
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        )
        self.memory_optimize = False
        self.enable_inplace = True  # buffer donation
        # the fuse_* knobs drive the REAL cost-guided fusion pass
        # pipeline (static_analysis/fusion.py), the TPU realization of
        # the reference's fuse_all_reduce_op_pass /
        # fuse_elewise_add_act_pass / fuse_optimizer_ops_pass:
        #   fuse_all_reduce_ops      -> bucketed gradient allreduce
        #                               (PADDLE_TPU_ALLREDUCE_BUCKET_MB)
        #   fuse_elewise_add_act_ops -> fused_bias_act +
        #                               fused_dropout_add_ln rewrites
        #   fuse_all_optimizer_ops   -> multi-tensor fused_adam/fused_sgd
        #                               (cost-gated: BERT-scale groups
        #                               are rejected, see the r04 A/B)
        # PADDLE_TPU_FUSION=0 kills the whole pipeline;
        # CompiledProgram.fusion_report() shows what fired and why not.
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_all_optimizer_ops = True
        # TPU-native pattern families beyond the reference's flags:
        # attention subgraph -> Pallas flash kernel (gated on the
        # measured engagement threshold), softmax+cross_entropy -> one
        # numerically-stable op
        self.fuse_attention = True
        self.fuse_softmax_xent = True
        # reference fuse_bn_act_ops, extended to ride the conv too:
        # conv2d -> batch_norm -> (act) becomes one fused_conv_bn_act
        # (Pallas epilogue on TPU); lookup_table/embedding on device
        # tables dispatch to the Pallas row-DMA gather kernel.  Both
        # gates weigh predicted deltas by the autotune calibration
        # factors (paddle_tpu.autotune) when a silicon sweep recorded
        # them.
        self.fuse_bn_act_ops = True
        self.fuse_embedding_gather = True
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.num_trainers = 1
        self.trainer_id = 0
        self.trainers_endpoints = []
        # under jit+GSPMD batch-norm stats of a batch-sharded input are
        # ALWAYS global (the partitioner emits the cross-device reduction),
        # so DP batch norm is inherently synchronized — the reference's
        # sync_batch_norm_pass is subsumed; the knob is kept for API parity
        # (tests/test_grad_accum_syncbn.py proves the global-stats parity)
        self.sync_batch_norm = False
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        # TPU-native extensions
        # jax.checkpoint: honored by pipeline stages (parallel/pipeline.py)
        # and ring attention; the plain executor path warns (explicit grad
        # ops read named activations, so segment remat must be chosen at
        # the model level)
        self.remat = False
        # ZeRO-1: partition param-shaped optimizer accumulators (Adam
        # moments etc.) over the data axis — per-chip optimizer memory
        # drops by dp_degree (the fleet "sharding" strategy, TPU-style)
        self.shard_optimizer_state = False
        self.donate_params = True
        # microbatch gradient accumulation (reference
        # ir/multi_batch_merge_pass.cc "repeat"): split the batch into k
        # microbatches, scan fwd+bwd accumulating grads, apply the
        # optimizer once on the average
        self.batch_merge_repeat = 1
        # tensor parallelism (SURVEY §2.3 TP row — beyond the reference,
        # which only row-shards PS parameter blocks): devices reshape to a
        # (data, model) mesh and params annotated with
        # ParamAttr(shard_spec=...) partition over the model axis
        self.tensor_parallel_degree = 1


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._loss_name = None
        self._exec_strategy = None
        self._places = None
        self._share_vars_from = None
        self._parallel_runner = None
        self._last_fusion_report = None
        self._last_fusion_key = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._warn_inert_knobs(self._build_strategy)
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._places = places
        self._share_vars_from = share_vars_from
        return self

    @staticmethod
    def _warn_inert_knobs(bs):
        """A user porting reference code must not get silently different
        behavior: warn for knobs this backend does not honor."""
        import warnings

        if bs.reduce_strategy != BuildStrategy.ReduceStrategy.AllReduce:
            warnings.warn(
                "BuildStrategy.reduce_strategy=Reduce has no TPU "
                "equivalent: GSPMD always emits fused all-reduce over ICI; "
                "proceeding with AllReduce semantics", stacklevel=3)
        if (bs.gradient_scale_strategy
                == BuildStrategy.GradientScaleStrategy.Customized):
            warnings.warn(
                "GradientScaleStrategy.Customized is not supported: scale "
                "the loss explicitly in the program instead "
                "(reference multi_devices_graph_pass ScaleLossGrad)",
                stacklevel=3)
        if getattr(bs, "remat", False):
            warnings.warn(
                "BuildStrategy.remat applies to pipeline stages "
                "(PipelineOptimizer) and ring attention only; for the "
                "plain executor pick recompute boundaries at the model "
                "level with `with fluid.layers.recompute():`",
                stacklevel=3)

    def with_inference_optimize(self, config):
        # analysis passes are XLA's job under jit; clone(for_test) is enough
        self._program = self._program.clone(for_test=True)
        return self

    @property
    def program(self):
        return self._program

    def fusion_report(self):
        """The fusion pipeline's outcome for this program under this
        BuildStrategy: applied rewrites with op coordinates and
        predicted deltas, plus matched-but-skipped patterns with the
        cost-model reason.  Resolves the fused program on demand if no
        run has happened yet (fetch-target protection then defaults to
        'nothing fetched')."""
        from .static_analysis import fusion as _fusion

        if self._parallel_runner is not None \
                and self._parallel_runner._last_fusion_report is not None:
            return self._parallel_runner._last_fusion_report
        if self._last_fusion_report is not None:
            return self._last_fusion_report
        _, report = _fusion.resolve_fused_program(
            self._program,
            config=_fusion.FusionConfig.from_build_strategy(
                self._build_strategy))
        return report

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        accum = getattr(self._build_strategy, "batch_merge_repeat", 1) or 1
        iters = int(getattr(self._exec_strategy, "num_iteration_per_run",
                            1) or 1) if self._exec_strategy else 1
        if not self._is_data_parallel and accum <= 1 and iters <= 1:
            # hand the BuildStrategy-derived fusion config to the
            # executor so the fuse_* flags are honored on the plain path
            # too — including when every pass no-ops (the executor must
            # not fall back to the default config and re-enable families
            # the strategy disabled)
            from .framework import Variable
            from .static_analysis import fusion as _fusion

            config = _fusion.FusionConfig.from_build_strategy(
                self._build_strategy)
            targets = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]
            # refresh the report only when its resolve key changes —
            # steady-state steps skip the (cached) resolve entirely
            key = (config.signature(self._program), self._program._version,
                   tuple(sorted(set(targets))))
            if key != self._last_fusion_key:
                _, self._last_fusion_report = _fusion.resolve_fused_program(
                    self._program, config=config, targets=targets)
                self._last_fusion_key = key
            return executor.run(
                self._program, feed=feed, fetch_list=fetch_list,
                scope=scope, return_numpy=return_numpy,
                use_program_cache=True, _fusion_config=config,
            )
        from .parallel import SPMDRunner

        if self._parallel_runner is None:
            self._parallel_runner = SPMDRunner(
                self._program, self._build_strategy, self._places,
                data_parallel=self._is_data_parallel,
                exec_strategy=self._exec_strategy,
            )
        return self._parallel_runner.run(
            executor, feed, fetch_list, scope, return_numpy
        )
