"""Reader decorators (reference: ``python/paddle/reader/decorator.py`` —
cache, map_readers, shuffle, chain, compose, buffered, firstn,
xmap_readers, multiprocess_reader) and ``python/paddle/batch.py``.

A *reader creator* is a zero-arg callable returning an iterator of
samples; decorators wrap creators.  Threaded variants use threads (not
processes) — the consumers feed a jitted step, so the GIL is released
during device execution and thread workers overlap fine.
"""

import itertools
import queue as _queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "firstn", "xmap_readers", "batch", "ComposeNotAligned",
]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Cache the first *complete* pass in memory; later passes replay it.
    A partially consumed pass is discarded (not mixed into a later one)."""
    all_data = []
    filled = []

    def impl():
        if not filled:
            fresh = []
            for item in reader():
                fresh.append(item)
                yield item
            all_data[:] = fresh
            filled.append(True)
        else:
            for item in all_data:
                yield item

    return impl


def map_readers(func, *readers):
    """Zip readers, map func over the per-reader samples."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (reference decorator.py:82)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers end to end."""

    def reader():
        for r in readers:
            for item in r():
                yield item

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, b1, b2) from ((a,), (b1, b2)).
    check_alignment=True (default) raises ComposeNotAligned on length
    mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples.  Reader
    exceptions propagate to the consumer (instead of hanging the queue)."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def read_worker():
            try:
                for d in r:
                    q.put(d)
                q.put(_End)
            except BaseException as exc:  # propagate to consumer
                q.put(exc)

        t = threading.Thread(target=read_worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                return
            if isinstance(e, BaseException):
                raise e
            yield e

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                return
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with `process_num` worker threads (reference uses
    threads too despite the name)."""

    end = object()

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as exc:
                out_q.put(exc)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as exc:
                    out_q.put(exc)
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                i, mapped = item
                pending[i] = mapped
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            assert not pending, "xmap order protocol violated"
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                yield item[1]

    return data_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of `batch_size` (reference batch.py)."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
