"""Reader decorators (reference: ``python/paddle/reader/decorator.py`` —
cache, map_readers, shuffle, chain, compose, buffered, firstn,
xmap_readers, multiprocess_reader) and ``python/paddle/batch.py``.

A *reader creator* is a zero-arg callable returning an iterator of
samples; decorators wrap creators.  Threaded variants use threads (not
processes) — the consumers feed a jitted step, so the GIL is released
during device execution and thread workers overlap fine.
"""

import itertools
import queue as _queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "device_buffered", "firstn", "xmap_readers", "batch",
    "ComposeNotAligned", "multiprocess_reader", "Fake", "PipeReader",
    "np_array", "text_file", "recordio",
]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Cache the first *complete* pass in memory; later passes replay it.
    A partially consumed pass is discarded (not mixed into a later one)."""
    all_data = []
    filled = []

    def impl():
        if not filled:
            fresh = []
            for item in reader():
                fresh.append(item)
                yield item
            all_data[:] = fresh
            filled.append(True)
        else:
            for item in all_data:
                yield item

    return impl


def map_readers(func, *readers):
    """Zip readers, map func over the per-reader samples."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (reference decorator.py:82)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers end to end."""

    def reader():
        for r in readers:
            for item in r():
                yield item

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, b1, b2) from ((a,), (b1, b2)).
    check_alignment=True (default) raises ComposeNotAligned on length
    mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples.  Reader
    exceptions propagate to the consumer (instead of hanging the queue)."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def read_worker():
            try:
                for d in r:
                    q.put(d)
                q.put(_End)
            except BaseException as exc:  # propagate to consumer
                q.put(exc)

        t = threading.Thread(target=read_worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                return
            if isinstance(e, BaseException):
                raise e
            yield e

    return data_reader


def device_buffered(reader, size=None):
    """Background-thread prefetch that ALSO stages each item's numpy
    arrays on device (``jax.device_put`` off the consumer thread) — the
    TPU-native ``double_buffer``: batch k+1's H2D transfer overlaps the
    async-dispatched step k.  ``size`` defaults to
    ``PADDLE_TPU_PIPELINE_DEPTH`` (2).  Items may be dicts (feed
    name→array; placement cached for repeated arrays), tuples/lists of
    arrays, or bare arrays; non-array leaves pass through.  Reader
    exceptions propagate to the consumer (the ``buffered`` contract)."""
    from .pipeline import DeviceFeedPipeline

    def data_reader():
        return iter(DeviceFeedPipeline(reader, depth=size))

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                return
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with `process_num` worker threads (reference uses
    threads too despite the name)."""

    end = object()

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as exc:
                out_q.put(exc)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as exc:
                    out_q.put(exc)
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                i, mapped = item
                pending[i] = mapped
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            assert not pending, "xmap order protocol violated"
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                yield item[1]

    return data_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of `batch_size` (reference batch.py)."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Merge samples from several readers, each driven by its own OS
    process (reference decorator.py:441).  Queue mode uses a shared
    multiprocessing.Queue; pipe mode one Pipe per reader with samples
    JSON-framed, exactly the reference's two transports.  Samples must
    be picklable (queue) / JSON-able (pipe)."""
    import multiprocessing

    if not isinstance(readers, list) or not readers:
        raise AssertionError("readers must be a non-empty list")

    # error sentinel: a child that dies without its end-sentinel would
    # deadlock the parent's blocking get (same propagate-don't-hang
    # contract as `buffered` above, crossing a process boundary)
    _ERR = "__multiprocess_reader_error__"

    def _read_into_queue(reader, q):
        try:
            for sample in reader():
                if sample is None:
                    raise ValueError("sample has None")
                q.put(sample)
            q.put(None)
        except BaseException as exc:  # noqa: BLE001 - must reach parent
            q.put((_ERR, repr(exc)))

    def queue_reader():
        q = multiprocessing.Queue(queue_size)
        procs = [
            multiprocessing.Process(
                target=_read_into_queue, args=(r, q), daemon=True)
            for r in readers
        ]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            try:
                sample = q.get(timeout=5)
            except _queue.Empty:
                # a child killed outright (OOM/SIGKILL) never sends any
                # sentinel — detect the dead-and-drained state instead
                # of blocking forever
                if any(not p.is_alive() and p.exitcode not in (0, None)
                       for p in procs) and q.empty():
                    for p in procs:
                        p.terminate()
                    raise RuntimeError(
                        "multiprocess_reader child killed (exitcodes %s)"
                        % [p.exitcode for p in procs])
                continue
            if sample is None:
                finished += 1
            elif isinstance(sample, tuple) and len(sample) == 2 \
                    and sample[0] == _ERR:
                for p in procs:
                    p.terminate()
                raise RuntimeError(
                    "multiprocess_reader child failed: %s" % sample[1])
            else:
                yield sample
        for p in procs:
            p.join()

    def _read_into_pipe(reader, conn):
        import json

        try:
            for sample in reader():
                if sample is None:
                    raise ValueError("sample has None")
                conn.send(json.dumps(sample))
            conn.send(json.dumps(None))
        except BaseException as exc:  # noqa: BLE001 - must reach parent
            try:
                conn.send(json.dumps({_ERR: repr(exc)}))
            except (OSError, TypeError, ValueError):
                pass
        finally:
            conn.close()

    def pipe_reader():
        import json

        conns = []
        procs = []
        for r in readers:
            parent, child = multiprocessing.Pipe()
            conns.append(parent)
            p = multiprocessing.Process(
                target=_read_into_pipe, args=(r, child), daemon=True)
            procs.append(p)
            p.start()
        live = list(conns)
        finished = 0
        while finished < len(readers):
            for conn in list(live):
                try:
                    sample = json.loads(conn.recv())
                except EOFError:
                    for p in procs:
                        p.terminate()
                    raise RuntimeError(
                        "multiprocess_reader child died without its end "
                        "sentinel (crashed before sending error)")
                if sample is None:
                    finished += 1
                    conn.close()
                    live.remove(conn)
                elif isinstance(sample, dict) and _ERR in sample:
                    for p in procs:
                        p.terminate()
                    raise RuntimeError(
                        "multiprocess_reader child failed: %s"
                        % sample[_ERR])
                else:
                    yield sample
        for p in procs:
            p.join()

    return pipe_reader if use_pipe else queue_reader


class Fake:
    """Cache the first sample and replay it data_num times (reference
    decorator.py:531) — isolates input-pipeline cost for speed tests."""

    def __init__(self):
        self.data = None
        self.yield_num = 0

    def __call__(self, reader, data_num):
        def fake_reader():
            if self.data is None:
                self.data = next(reader())
            while self.yield_num < data_num:
                yield self.data
                self.yield_num += 1
            self.yield_num = 0

        return fake_reader


class PipeReader:
    """Stream a shell command's stdout and yield decoded lines
    (reference decorator.py:388) — the HDFS/S3/curl ingestion path.
    gzip file_type inflates on the fly."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import shlex
        import subprocess
        import zlib

        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type %s is not allowed" % file_type)
        if file_type == "gzip":
            # wbits offset 32: auto-detect gzip header
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        self.file_type = file_type
        self.bufsize = bufsize
        self.process = subprocess.Popen(
            shlex.split(command), bufsize=bufsize, stdout=subprocess.PIPE)

    def get_line(self, cut_lines=True, line_break="\n"):
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if buff:
                if self.file_type == "gzip":
                    decomp = self.dec.decompress(buff).decode(
                        "utf-8", "replace")
                else:
                    decomp = buff.decode("utf-8", "replace")
                if cut_lines:
                    parts = (remained + decomp).split(line_break)
                    remained = parts[-1]
                    for line in parts[:-1]:
                        yield line
                else:
                    yield decomp
            else:
                break
        if remained:
            yield remained


# ---------------------------------------------------------------------------
# reader creators (reference: python/paddle/reader/creator.py)
# ---------------------------------------------------------------------------


def np_array(x):
    """Creator from a numpy array: yields one row per sample
    (reference creator.py:22)."""
    import numpy as _np

    x = _np.asarray(x)
    if x.ndim < 1:
        raise ValueError("np_array needs at least a 1-D array")

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    """Creator yielding stripped lines of a text file
    (reference creator.py:42)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Creator over RecordIO file(s) (reference creator.py:63 reads via
    the recordio client); here the native-or-python reader from
    recordio_writer.  Accepts a path, comma-joined paths, or a list."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from .recordio_writer import recordio_reader

        for p in paths:
            for rec in recordio_reader(p)():
                yield rec

    # reference parity: reads are prefetched through the buffered
    # decorator with the caller's buf_size
    return buffered(reader, buf_size)
