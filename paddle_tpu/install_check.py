"""Install sanity check (reference:
``python/paddle/fluid/install_check.py`` run_check — builds and runs a
tiny linear model to prove the stack works end to end)."""

import numpy as np

__all__ = ["run_check"]


def run_check():
    from . import (CPUPlace, Executor, Program, layers, optimizer,
                   program_guard)
    from .executor import Scope, scope_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("install_check_x", shape=[2], dtype="float32")
        y = layers.fc(x, size=1)
        loss = layers.mean(y)
        optimizer.SGD(0.01).minimize(loss)
    exe = Executor(CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        out = exe.run(
            main,
            feed={"install_check_x": np.ones((2, 2), "float32")},
            fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all()
    print("Your paddle_tpu works well on this machine.")
    return True
