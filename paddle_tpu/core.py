"""Core runtime types: places, dtypes, VarType.

The reference implements these natively (``paddle/fluid/platform/place.h``,
``paddle/fluid/framework/framework.proto:105`` VarType) and exposes them via
pybind (``paddle/fluid/pybind/pybind.cc``).  On TPU the device abstraction is
jax's; a Place here is a thin selector that maps onto a ``jax.Device`` (or the
whole default device set), so `Executor(place)` keeps the reference API shape
while jit/XLA own actual placement.
"""

import enum

import numpy as np


class VarDesc:
    """Namespace mirroring the reference's VarDesc proto enums
    (``framework.proto:105-163``)."""

    class VarType(enum.IntEnum):
        # tensor types
        BOOL = 0
        INT16 = 1
        INT32 = 2
        INT64 = 3
        FP16 = 4
        FP32 = 5
        FP64 = 6
        SIZE_T = 19
        UINT8 = 20
        INT8 = 21
        BF16 = 22
        # container / special types
        LOD_TENSOR = 7
        SELECTED_ROWS = 8
        FEED_MINIBATCH = 9
        FETCH_LIST = 10
        STEP_SCOPES = 11
        LOD_RANK_TABLE = 12
        LOD_TENSOR_ARRAY = 13
        PLACE_LIST = 14
        READER = 15
        RAW = 17
        TUPLE = 18


_DTYPE_TO_VARTYPE = {
    np.dtype("bool"): VarDesc.VarType.BOOL,
    np.dtype("int16"): VarDesc.VarType.INT16,
    np.dtype("int32"): VarDesc.VarType.INT32,
    np.dtype("int64"): VarDesc.VarType.INT64,
    np.dtype("float16"): VarDesc.VarType.FP16,
    np.dtype("float32"): VarDesc.VarType.FP32,
    np.dtype("float64"): VarDesc.VarType.FP64,
    np.dtype("uint8"): VarDesc.VarType.UINT8,
    np.dtype("int8"): VarDesc.VarType.INT8,
}

_VARTYPE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_VARTYPE.items()}


def convert_np_dtype_to_dtype_(dtype):
    """Normalize a user dtype spec (str / np.dtype / VarType) to a canonical
    string.  'bfloat16' is kept as a string since numpy has no native bf16."""
    if isinstance(dtype, VarDesc.VarType):
        if dtype == VarDesc.VarType.BF16:
            return "bfloat16"
        return _VARTYPE_TO_DTYPE[dtype].name
    if isinstance(dtype, str):
        if dtype in ("bfloat16", "bf16"):
            return "bfloat16"
        return np.dtype(dtype).name
    return np.dtype(dtype).name


def dtype_is_floating(dtype):
    d = convert_np_dtype_to_dtype_(dtype)
    return d in ("float16", "float32", "float64", "bfloat16")


class Place:
    """Base device selector."""

    _kind = "base"

    def __init__(self, device_id=0):
        self._device_id = int(device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self._device_id == other._device_id

    def __hash__(self):
        return hash((type(self).__name__, self._device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self._device_id)

    def jax_device(self):
        """Resolve to a concrete jax.Device (lazy import keeps `core` light)."""
        import jax

        if isinstance(self, CPUPlace):
            devs = jax.devices("cpu")
        else:
            devs = jax.devices()
        return devs[self._device_id % len(devs)]


class CPUPlace(Place):
    _kind = "cpu"


class TPUPlace(Place):
    """The native accelerator place of this framework (reference analogue:
    CUDAPlace, ``platform/place.h``)."""

    _kind = "tpu"


# Alias for source compatibility with reference user scripts; on this
# framework "CUDA" places simply select the default jax accelerator.
CUDAPlace = TPUPlace


class CUDAPinnedPlace(Place):
    _kind = "pinned"


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def get_device_count():
    import jax

    return jax.device_count()


# ---------------------------------------------------------------------------
# Flag system (reference: gflags exposed through __bootstrap__ forwarding
# whitelisted FLAGS_* env vars, python/paddle/fluid/__init__.py:124-199).
# TPU-native: the debugging flags map onto jax config switches.
# ---------------------------------------------------------------------------

_flags = {
    # NaN/Inf debugging (reference FLAGS_check_nan_inf: per-op nan printers
    # via lodtensor_printer; here jax re-runs the offending op de-optimized
    # and raises with the op name — same diagnosis, compiler-native)
    "FLAGS_check_nan_inf": False,
    # bit-exact cross-platform determinism (reference FLAGS_cpu_deterministic)
    "FLAGS_cpu_deterministic": False,
    "FLAGS_benchmark": False,
}


def set_flags(flags):
    """Set runtime debugging flags (reference ``fluid.set_flags``)."""
    import jax

    flags = dict(flags)
    unknown = [n for n in flags if n not in _flags]
    if unknown:
        raise KeyError("unknown flag(s) %r (known: %s)"
                       % (unknown, sorted(_flags)))
    for name, value in flags.items():
        _flags[name] = value
        if name == "FLAGS_check_nan_inf":
            jax.config.update("jax_debug_nans", bool(value))
        elif name == "FLAGS_cpu_deterministic" and value:
            import os

            os.environ.setdefault("PADDLE_TPU_RNG_IMPL", "threefry2x32")


def get_flags(names):
    if isinstance(names, str):
        return {names: _flags[names]}
    return {n: _flags[n] for n in names}


def _bootstrap_flags():
    """Forward FLAGS_* env vars into the flag registry at import, the
    reference ``__bootstrap__`` pattern."""
    import os

    for name in list(_flags):
        raw = os.environ.get(name)
        if raw is None:
            continue
        set_flags({name: raw.lower() in ("1", "true", "yes", "on")})


_bootstrap_flags()
