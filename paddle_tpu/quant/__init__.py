"""Block-quantized collectives: int8 gradient exchange as a priced axis.

Reference analogue: Fluid's gradient-compression family —
``DGCMomentumOptimizer`` (``python/paddle/fluid/optimizer.py:787``) and
the ``dgc``/``quantize`` op clusters — bandwidth-saving gradient
exchange bolted onto the RPC transport.  TPU-native framing (EQuARX,
arXiv 2506.17615): the win is not sparsity bookkeeping but cutting the
ICI payload of the dense allreduce in half by moving int8 blocks with a
per-block f32 scale sidecar, and the decision of WHERE to do so belongs
to the planner's placement search (arXiv 2110.10548), not a global
toggle — only ICI-bound buckets quantize; compute-bound buckets stay
bf16.

Layers:

- :mod:`.blockwise` — the quantize/dequantize primitives with the
  documented error model, Pallas fused kernels (autotune family
  ``quant``) and an XLA composite fallback.
- :mod:`.collective` — the ``c_allreduce_quant`` math: quantize →
  reduce-scatter in int8 → dequant-sum-requant → allgather.

Kill switches: ``PADDLE_TPU_QUANT=0`` disables the whole subsystem
(planner stops enumerating quant candidates, the fusion rewrite emits
plain ``c_fused_allreduce_sum``, and collectives are bit-exactly the
pre-quant bf16 path); ``PADDLE_TPU_QUANT_BLOCK`` overrides the block
size (default 256); ``PADDLE_TPU_QUANT_MIN_BYTES`` forces the
per-bucket engagement threshold without a planner mark.
"""

from .blockwise import (block_dequantize, block_quantize, predicted_rms_error,
                        quant_block, quant_enabled, quantization_error)
from .collective import (quant_min_bytes, quantized_allreduce,
                         quantized_wire_bytes)

__all__ = [
    "block_quantize", "block_dequantize", "quant_block", "quant_enabled",
    "predicted_rms_error", "quantization_error", "quantized_allreduce",
    "quantized_wire_bytes", "quant_min_bytes",
]
