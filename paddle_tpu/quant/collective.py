"""The ``c_allreduce_quant`` math: int8 block-quantized ring allreduce.

EQuARX-style (arXiv 2506.17615) two-phase exchange, expressed with the
explicit lax collectives so the wire payload really is int8:

1. quantize the flat bucket (``blockwise.block_quantize``), padded so
   every rank's chunk is a whole number of blocks;
2. reduce-scatter in int8: ``all_to_all`` the per-rank chunks (int8 q +
   f32 scale sidecar), then each rank dequant-sums its chunk over peers
   in fixed ascending rank order — the deterministic-reduction
   discipline of PR 12's ``reduce_gradients`` (same summands, same
   order, on every rank);
3. requantize the reduced chunk and ``all_gather`` it back (int8 +
   sidecar), dequant, trim the pad.

Wire bytes per rank ≈ ``2 · (n-1)/n · numel`` int8 plus the scale
sidecar (4/B per element) vs ``2 · (n-1)/n · 2·numel`` for the bf16
ring — the ~2x cut :func:`quantized_wire_bytes` prices for the planner.
Error: the payload is quantized twice (once per direction), so the
end-to-end RMS error is ≈ √2 × the single-pass model in
:mod:`.blockwise`; the drift gauge measures against exactly that.

Determinism: quantization is a pure function of the input bits and the
dequant-sum runs in rank order, so every rank computes bit-identical
results from the identical collective output — cross-process replay is
exact (covered by the multiprocess test).
"""

import os

import jax
import jax.numpy as jnp

from .blockwise import (block_quantize, padded_size, quant_block,
                        quant_enabled)

__all__ = ["quantized_allreduce", "quantized_wire_bytes",
           "quant_min_bytes"]


def quantized_allreduce(flat, axis_name, block=None):
    """Allreduce-sum a flat f32/bf16 vector with int8 block-quantized
    exchange.  Call inside shard_map over ``axis_name``; returns the
    (approximate) cross-replica sum in ``flat``'s dtype."""
    from ..jax_compat import axis_size

    b = int(block) if block else quant_block()
    n = axis_size(axis_name)  # static — no extra collective
    dtype = flat.dtype
    numel = flat.size
    npad = padded_size(numel, n * b)
    chunk = npad // n

    # kernel=False: pallas_call has no shard_map replication rule, and
    # this function is by contract traced under the mesh axis — the XLA
    # composite is the same math, same bits
    q, scales = block_quantize(flat, block=b, kernel=False)  # pads to npad
    if q.size != npad:  # block multiple < rank multiple: re-pad
        q2, s2 = (jnp.zeros(npad, jnp.int8),
                  jnp.ones(npad // b, jnp.float32))
        q = q2.at[:q.size].set(q)
        scales = s2.at[:scales.size].set(scales)

    # reduce-scatter in int8: ship each rank its chunk from every peer
    q_peer = jax.lax.all_to_all(q.reshape(n, chunk), axis_name,
                                split_axis=0, concat_axis=0, tiled=False)
    s_peer = jax.lax.all_to_all(scales.reshape(n, chunk // b), axis_name,
                                split_axis=0, concat_axis=0, tiled=False)
    # dequant-sum in ascending rank order (deterministic on every rank)
    peer_vals = (q_peer.astype(jnp.float32)
                 * jnp.repeat(s_peer, b, axis=1))
    part = jnp.sum(peer_vals, axis=0)  # [chunk]

    # requantize the reduced shard and gather it back
    q_r, s_r = block_quantize(part, block=b, kernel=False)
    q_all = jax.lax.all_gather(q_r, axis_name)  # [n, chunk]
    s_all = jax.lax.all_gather(s_r, axis_name)  # [n, chunk // b]
    out = (q_all.astype(jnp.float32)
           * jnp.repeat(s_all, b, axis=1)).reshape(-1)
    return out[:numel].astype(dtype)


def quantized_wire_bytes(numel, nranks, block=None, dtype_bytes=2):
    """(quant_bytes, dense_bytes) one ring allreduce moves per rank for a
    ``numel``-element bucket: the cost-model payload rule.  Both sides
    include the 2·(n-1)/n ring factor's *payload* term only (the factor
    itself is applied by ``collective_ici_bytes``), i.e. these are the
    B in ``2·B·(n-1)/n``.  quant side = int8 elements (padded to rank ×
    block alignment) + the f32-per-block scale sidecar, counted for both
    the scatter and gather phases by the shared ring factor."""
    b = int(block) if block else quant_block()
    n = max(int(nranks), 1)
    npad = padded_size(numel, n * b)
    quant_bytes = npad + (npad // b) * 4
    dense_bytes = int(numel) * int(dtype_bytes)
    return quant_bytes, dense_bytes


def quant_min_bytes(program=None):
    """The per-bucket engagement threshold in bytes, or None when
    quantized collectives are off for this program.

    Precedence: global kill switch (``PADDLE_TPU_QUANT=0`` → None) →
    planner ``_quant_buckets`` program mark (``{"min_bytes": …,
    "block": …}``) → ``PADDLE_TPU_QUANT_MIN_BYTES`` env → None (quant
    never engages without an explicit plan or env opt-in — the default
    path stays bit-exact bf16)."""
    if not quant_enabled():
        return None
    mark = getattr(program, "_quant_buckets", None) if program else None
    if isinstance(mark, dict) and mark.get("min_bytes") is not None:
        try:
            return int(mark["min_bytes"])
        except (TypeError, ValueError):
            return None
    env = os.environ.get("PADDLE_TPU_QUANT_MIN_BYTES", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            return None
    return None
