"""bf16/f32 → int8 block quantization with per-block scales.

The wire format: a tensor is flattened, zero-padded to a multiple of the
block size B (``PADDLE_TPU_QUANT_BLOCK``, default 256), and each block
carries ``q = clip(round(x / s), -127, 127)`` as int8 plus one f32 scale
``s = absmax / 127``.  Dequant is exactly ``q * s`` — the round trip is a
pure function of the input bits, so replay is bit-exact and the forward
op needs no saved state.

Error model (documented, consumed by the drift monitor's ``quant_error``
gauge): within a block of absmax ``m`` the quantization step is
``Δ = m/127``; rounding gives per-element absolute error ≤ ``Δ/2 =
m/254`` and, for the usual dense-gradient case of values spread across
the step, RMS error ≈ ``Δ/√12 = m/(127·√12) ≈ m/440``.  Relative error
is bounded by the block's dynamic range — elements much smaller than the
block absmax see proportionally larger relative error, which is why B is
a knob: smaller blocks localize the scale (better dynamic range) at the
cost of a larger scale sidecar (4/B bytes per element; B=256 → 1.6%
overhead on the int8 payload).

Zero/denormal guard: an all-zero block would give scale 0 and
``x / s = NaN``; the scale is therefore ``where(absmax > 0, absmax/127,
1)`` so zero blocks quantize to zeros with a harmless unit scale.

Kernels: the quantize direction fuses absmax-reduce + scale + round +
cast in one VMEM pass (the XLA composite materializes the [N] absmax and
re-reads x); autotune family ``quant`` caches the rows-per-grid-step
winner.  Everything falls back to the identical-math XLA composite
off-TPU or for ineligible shapes; ``PADDLE_TPU_PALLAS=interpret`` forces
the kernel in interpreter mode (CPU tests).
"""

import functools
import os

import jax
import jax.numpy as jnp

from ..ops.pallas.flash_attention import (_HAS_PLTPU, pallas_supported, pl,
                                          pltpu)

__all__ = ["quant_enabled", "quant_block", "block_quantize",
           "block_dequantize", "predicted_rms_error", "quantization_error"]

_DEFAULT_BLOCK = 256
_QMAX = 127.0
_BN = 256  # blocks per grid step (rows of the [nblocks, B] view)


def quant_enabled():
    """Global kill switch: ``PADDLE_TPU_QUANT=0`` disables quantized
    collectives everywhere (planner, fusion rewrite, runtime) and
    restores the bf16 paths bit-exactly."""
    return os.environ.get("PADDLE_TPU_QUANT", "").strip() != "0"


def quant_block(default=_DEFAULT_BLOCK):
    """Quantization block size: ``PADDLE_TPU_QUANT_BLOCK`` → default."""
    env = os.environ.get("PADDLE_TPU_QUANT_BLOCK", "").strip()
    if env:
        try:
            v = int(env)
            if v > 0:
                return v
        except ValueError:
            pass
    return default


def padded_size(numel, block):
    """numel rounded up to a whole number of blocks."""
    return -(-int(numel) // int(block)) * int(block)


def _pallas_mode():
    return os.environ.get("PADDLE_TPU_PALLAS", "")


def _block_rows(nblocks, block):
    """Grid-step row count for the [nblocks, block] view: env cap →
    autotune-cached winner (family ``quant``) → default; a divisor of
    nblocks."""
    try:
        from ..autotune import cached_block_cap

        cap = cached_block_cap("quant", "PADDLE_TPU_QUANT_BLOCK_ROWS",
                               "block_rows", _BN, nblocks=nblocks,
                               block=block)
    except Exception:  # pragma: no cover - autotune unavailable
        cap = _BN
    bn = min(max(cap, 1), nblocks)
    while nblocks % bn:
        bn //= 2
    return max(bn, 1)


def _eligible(nblocks, block):
    if not pallas_supported() or _pallas_mode() == "off":
        return False
    if block % 128 or nblocks % 8:
        return False
    if _pallas_mode() == "interpret":
        return True
    if not _HAS_PLTPU:
        return False
    plat = jax.devices()[0].platform.lower()
    return "tpu" in plat or "axon" in plat


def _scale_of(absmax):
    # zero/denormal blocks: unit scale, so q = round(0/1) = 0 — no NaN
    return jnp.where(absmax > 0.0, absmax / _QMAX, 1.0)


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = _scale_of(absmax)
    q = jnp.clip(jnp.round(x / scale), -_QMAX, _QMAX)
    q_ref[...] = q.astype(jnp.int8)
    s_ref[...] = scale.reshape(1, -1)


def _dequant_kernel(q_ref, s_ref, out_ref):
    q = q_ref[...].astype(jnp.float32)
    s = s_ref[...].reshape(-1, 1)
    out_ref[...] = (q * s).astype(out_ref.dtype)


def _quantize_xla(blocks):
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = _scale_of(absmax)
    q = jnp.clip(jnp.round(blocks / scale[:, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def _quantize_call(blocks, kernel=True):
    nblocks, block = blocks.shape
    if not kernel or not _eligible(nblocks, block):
        return _quantize_xla(blocks)
    bn = _block_rows(nblocks, block)
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=(nblocks // bn,),
        in_specs=[pl.BlockSpec((bn, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((bn, block), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nblocks, block), jnp.int8),
            jax.ShapeDtypeStruct((1, nblocks), jnp.float32),
        ],
        interpret=_pallas_mode() == "interpret",
    )(blocks)
    return q, s.reshape(-1)


def _dequantize_call(q, scales, dtype, kernel=True):
    nblocks, block = q.shape
    if not kernel or not _eligible(nblocks, block):
        return (q.astype(jnp.float32) * scales[:, None]).astype(dtype)
    bn = _block_rows(nblocks, block)
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(nblocks // bn,),
        in_specs=[
            pl.BlockSpec((bn, block), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((bn, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, block), dtype),
        interpret=_pallas_mode() == "interpret",
    )(q, scales.reshape(1, -1))
    return out


def block_quantize(x, block=None, kernel=True):
    """Quantize ``x`` (any shape, float dtype) to int8 blocks.

    Returns ``(q, scales)``: q int8 of shape [npad] (flat, zero-padded to
    a block multiple), scales f32 of shape [npad // block].  Odd-sized
    tails are zero-padded — the pad elements quantize to 0 under the
    tail block's real absmax, so dequant + trim is exact about them.

    ``kernel=False`` pins the XLA composite: pallas_call has no
    shard_map replication rule, so callers tracing under a mesh axis
    (the quantized collective) must take the composite — same math,
    same bits."""
    b = int(block) if block else quant_block()
    flat = x.reshape(-1).astype(jnp.float32)
    npad = padded_size(flat.size, b)
    if npad != flat.size:
        flat = jnp.pad(flat, (0, npad - flat.size))
    q, scales = _quantize_call(flat.reshape(npad // b, b), kernel=kernel)
    return q.reshape(-1), scales


def block_dequantize(q, scales, size=None, shape=None, dtype=jnp.float32,
                     kernel=True):
    """Exact dequant ``q * scale``; trims the pad back to ``size`` (or
    ``shape``'s numel) and reshapes when asked.  ``kernel=False`` as in
    :func:`block_quantize`."""
    nblocks = scales.shape[0]
    block = q.size // nblocks
    out = _dequantize_call(q.reshape(nblocks, block), scales,
                           jnp.dtype(dtype), kernel=kernel).reshape(-1)
    if shape is not None:
        size = 1
        for d in shape:
            size *= int(d)
    if size is not None and size != out.size:
        out = out[:size]
    if shape is not None:
        out = out.reshape(shape)
    return out


def predicted_rms_error(scales):
    """The error model's predicted RMS quantization error for a tensor
    with the given per-block scales: per-block RMS ≈ Δ/√12 with Δ = the
    block scale, averaged over blocks in quadrature."""
    s = jnp.asarray(scales, jnp.float32)
    return jnp.sqrt(jnp.mean(jnp.square(s)) / 12.0)


def quantization_error(x, block=None):
    """Measured vs predicted round-trip error (drift-gauge feed).

    Returns dict(measured_rms, predicted_rms, rel_error) — rel_error is
    measured RMS over the tensor's own RMS (0 for an all-zero input)."""
    xf = jnp.asarray(x).reshape(-1).astype(jnp.float32)
    q, scales = block_quantize(xf, block=block)
    back = block_dequantize(q, scales, size=xf.size)
    err = back - xf
    measured = jnp.sqrt(jnp.mean(jnp.square(err)))
    x_rms = jnp.sqrt(jnp.mean(jnp.square(xf)))
    rel = jnp.where(x_rms > 0.0, measured / x_rms, 0.0)
    return {"measured_rms": measured,
            "predicted_rms": predicted_rms_error(scales),
            "rel_error": rel}
