"""Inference stack: analysis passes + predictor API.

Reference: ``paddle/fluid/inference/`` — ``AnalysisPredictor``
(``api/analysis_predictor.h``: load ``__model__`` + params, run the
Analyzer pass pipeline, execute with ``NaiveExecutor``), config objects
(``api/paddle_analysis_config.h``), and the python
``transpiler/inference_transpiler.py`` (conv+bn folding).

TPU-native notes: XLA already fuses elementwise chains into the conv, so
the payoff of conv+bn folding here is removing the bn op's extra
params/state from the graph (smaller program, fewer buffers) and matching
the reference's transpiler surface; the predictor's "optimization" is
mostly jit-cache warmth — the Executor jit-compiles the pruned program
whole.
"""

import numpy as np

from . import io as fluid_io
from .executor import Executor, Scope, scope_guard
from .framework import Program
from .core import TPUPlace

__all__ = [
    "InferenceTranspiler",
    "AnalysisConfig",
    "AnalysisPredictor",
    "create_paddle_predictor",
    "fuse_conv_bn",
]


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def fuse_conv_bn(program, scope, eps_default=1e-5):
    """Fold batch_norm (inference mode) into the preceding conv2d
    (reference inference_transpiler.py:  _fuse_param / fuse_batch_norm).

    W' = W * gamma / sqrt(var + eps)   (per output channel)
    b' = beta - mean * gamma / sqrt(var + eps)
    The bn op is replaced by an elementwise_add of b' (XLA fuses it into
    the conv).  Returns the number of folded pairs.
    """
    block = program.global_block()
    # map: var name -> (op index, op) of its single producer; count readers
    producers = {}
    read_count = {}
    for i, op in enumerate(block.ops):
        for name in op.input_arg_names:
            read_count[name] = read_count.get(name, 0) + 1
        for name in op.output_arg_names:
            producers[name] = (i, op)

    fused = 0
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type != "batch_norm" or not (
                op.attrs.get("is_test") or op.attrs.get("use_global_stats")):
            i += 1
            continue
        x_name = op.inputs["X"][0]
        if read_count.get(x_name, 0) != 1:
            i += 1
            continue
        prod = producers.get(x_name)
        # chain shapes: conv2d → bn, or conv2d → elementwise_add(bias) → bn
        # (the conv2d layer appends a separate bias add, layers/nn.py)
        conv_op = None
        bias_add_op = None
        if prod is not None and prod[1].type in ("conv2d",
                                                 "depthwise_conv2d"):
            conv_op = prod[1]
        elif (prod is not None and prod[1].type == "elementwise_add"
              and prod[1].attrs.get("axis", -1) == 1):
            add_x = prod[1].inputs["X"][0]
            up = producers.get(add_x)
            if (up is not None
                    and up[1].type in ("conv2d", "depthwise_conv2d")
                    and read_count.get(add_x, 0) == 1):
                conv_op = up[1]
                bias_add_op = prod[1]
        if conv_op is None:
            i += 1
            continue
        conv_fmt = conv_op.attrs.get("data_format", "NCHW")
        bn_fmt = op.attrs.get("data_layout", "NCHW")
        if conv_fmt != bn_fmt or conv_fmt not in ("NCHW", "NHWC"):
            i += 1
            continue
        channels_last = conv_fmt == "NHWC"
        if channels_last and bias_add_op is not None:
            # the conv-bias chain is detected by its NCHW axis=1 add;
            # don't mix layouts — fold only the direct conv→bn pair
            i += 1
            continue
        # never fold into weight-shared params (another op would see the
        # scaled filter/bias)
        w_shared = read_count.get(conv_op.inputs["Filter"][0], 0) != 1
        b_shared = (bias_add_op is not None
                    and read_count.get(bias_add_op.inputs["Y"][0], 0) != 1)
        if w_shared or b_shared:
            i += 1
            continue

        scale = np.asarray(scope.get(op.inputs["Scale"][0]))
        bias = np.asarray(scope.get(op.inputs["Bias"][0]))
        mean = np.asarray(scope.get(op.inputs["Mean"][0]))
        var = np.asarray(scope.get(op.inputs["Variance"][0]))
        eps = float(op.attrs.get("epsilon", eps_default))
        std = np.sqrt(var + eps)
        gamma_over_std = scale / std

        w_name = conv_op.inputs["Filter"][0]
        w = np.asarray(scope.get(w_name))
        w = w * gamma_over_std[:, None, None, None]
        scope.set(w_name, w.astype(np.float32))

        y_name = op.outputs["Y"][0]
        if bias_add_op is not None:
            # fold into the existing conv bias; rewire the add to produce
            # the bn's output var
            cb_name = bias_add_op.inputs["Y"][0]
            cb = np.asarray(scope.get(cb_name)).reshape(-1)
            b_new = ((cb - mean) * gamma_over_std + bias).astype(np.float32)
            scope.set(cb_name, b_new.reshape(np.shape(scope.get(cb_name))))
            bias_add_op.outputs["Out"] = [y_name]
            block._remove_op(i)
            # i now points at the op after the removed bn; don't advance
        else:
            b_new = (bias - mean * gamma_over_std).astype(np.float32)
            bias_var_name = y_name + ".fused_bn_bias"
            bias_var = block.create_var(
                name=bias_var_name, shape=(b_new.shape[0],),
                dtype="float32", persistable=True)
            bias_var.stop_gradient = True
            scope.set(bias_var_name, b_new)
            # replace the bn op with the add: the [C] bias broadcasts on
            # the channel axis — 1 for NCHW, last for NHWC
            block._remove_op(i)
            block._insert_op(
                i, type="elementwise_add",
                inputs={"X": [x_name], "Y": [bias_var_name]},
                outputs={"Out": [y_name]},
                attrs={"axis": -1 if channels_last else 1},
            )
            i += 1
        fused += 1
    if fused:
        program._bump_version()
    return fused


class InferenceTranspiler:
    """Reference ``transpiler/inference_transpiler.py`` surface."""

    def transpile(self, program, place=None, scope=None):
        if scope is None:
            from .executor import global_scope

            scope = global_scope()
        fuse_conv_bn(program, scope)
        return program


class AnalysisConfig:
    """Reference ``api/paddle_analysis_config.h`` (subset: model path +
    optimization switches + pass pipeline; device knobs are meaningless
    off-GPU)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        from .analysis import PassBuilder

        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._ir_optim = True
        self._bf16 = False
        self._pass_builder = PassBuilder()

    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_bf16(self, flag=True):
        """bf16 the loaded graph AFTER the analysis passes (reference
        analogue: ``EnableMkldnnBfloat16`` in later reference versions).
        Order matters: rewriting before conv+bn folding would insert
        f32 casts between conv and bn (bn is AMP-black-listed) and
        defeat the fold's producer-pattern match."""
        self._bf16 = bool(flag)

    def bf16_enabled(self):
        return self._bf16

    def pass_builder(self):
        """Mutable pipeline (reference AnalysisConfig::pass_builder)."""
        return self._pass_builder


class AnalysisPredictor:
    """Load → analyze → run (reference analysis_predictor.h:50).

    Owns a private scope (like the reference's sub-scope) so concurrent
    predictors don't clash; ``run`` takes/returns numpy arrays in feed
    order.
    """

    def __init__(self, config):
        self._config = config
        self._scope = Scope()
        self._place = TPUPlace()
        self._exe = Executor(self._place)
        # accept both forms: model_dir (+ optional relative filenames) or
        # full prog_file/params_file paths (reference AnalysisConfig)
        import os

        model_dir = config.model_dir
        prog_file, params_file = config.prog_file, config.params_file
        if model_dir is None:
            if prog_file is None:
                raise ValueError(
                    "AnalysisConfig needs model_dir or prog_file")
            model_dir = os.path.dirname(os.path.abspath(prog_file))
            prog_file = os.path.basename(prog_file)
            if params_file is not None:
                params_file = os.path.basename(params_file)
        with scope_guard(self._scope):
            program, feed_names, fetch_vars = fluid_io.load_inference_model(
                model_dir, self._exe,
                model_filename=prog_file,
                params_filename=params_file)
            if config.ir_optim():
                from .analysis import Analyzer

                program = Analyzer(config.pass_builder()).run(
                    program, scope=self._scope,
                    targets=[v.name for v in fetch_vars])
            if config.bf16_enabled():
                from .contrib.mixed_precision import rewrite_program_bf16

                rewrite_program_bf16(program)
        self._program = program
        self._feed_names = feed_names
        self._fetch_vars = fetch_vars

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [v.name for v in self._fetch_vars]

    @property
    def program(self):
        return self._program

    def run(self, inputs, return_numpy=True):
        """inputs: list of numpy arrays in get_input_names() order (or a
        dict name→array).  Returns list of numpy arrays (ONE batched
        device→host sync after the step is dispatched); with
        return_numpy=False, lazy ``FetchHandle``\\ s — no host sync at
        all until a handle is materialized, so serving-style callers can
        keep batches in flight and block once at the end (see
        :meth:`run_async` / :meth:`run_batches`)."""
        feed = self._as_feed(inputs)
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_vars,
                                 return_numpy=return_numpy)
        # numpy conversion (batched, one sync) already happened in
        # Executor.run for return_numpy=True; handles pass through
        return list(outs)

    def _as_feed(self, inputs):
        if isinstance(inputs, dict):
            return dict(inputs)
        inputs = _as_list(inputs)
        if len(inputs) != len(self._feed_names):
            raise ValueError(
                "expected %d inputs (%s), got %d" % (
                    len(self._feed_names), self._feed_names,
                    len(inputs)))
        return dict(zip(self._feed_names, inputs))

    def run_async(self, inputs):
        """Dispatch one batch WITHOUT waiting: returns lazy
        ``FetchHandle``\\ s the moment the step is enqueued (the
        NaiveExecutor-style async serving call).  Materialize with
        ``np.asarray(handle)`` / ``handle.numpy()``, or batch many
        handles' syncs with ``paddle_tpu.pipeline.materialize``."""
        return self.run(inputs, return_numpy=False)

    def run_batches(self, batches, max_in_flight=2, return_numpy=True,
                    verify=False, request_ids=None):
        """Streamed serving loop: generator yielding one result list per
        input batch, keeping up to ``max_in_flight`` dispatched batches'
        results un-synced while a background thread device-stages
        upcoming feeds (``paddle_tpu.pipeline.DeviceFeedPipeline``).

        ``max_in_flight`` is the latency-vs-throughput knob: 1 ≈ the
        synchronous loop (lowest per-request latency, no overlap);
        2-4 overlaps host prep + H2D + D2H with device compute (serving
        throughput); larger mainly adds queueing delay.  With
        ``return_numpy=False`` the generator yields un-synced handles
        and never blocks on results at all.

        ``verify=True`` gates entry on the static concurrency analyzer
        (:mod:`paddle_tpu.static_analysis.concurrency`): the program
        the executor will actually run (fused twin included) is
        race-checked at this in-flight depth and certified free of
        host-sync points; a finding raises ``VerifyError`` naming the
        op — before any batch is dispatched.

        Every batch is validated against the program's
        ``need_check_feed`` declarations AT ENQUEUE TIME (on the
        prefetch thread, before device staging), so a malformed feed
        raises a ``ValueError`` attributed to the offending batch —
        optionally by the matching entry of ``request_ids`` — instead
        of surfacing ``max_in_flight`` steps later as a raw jit shape
        error."""
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1, got %d"
                             % max_in_flight)
        # the serving-path marks: strict-sync promotion + the in-flight
        # depth the race checks assume for this program from now on
        self._program._serving_hot_loop = True
        self._program._max_in_flight = max(
            max_in_flight,
            int(getattr(self._program, "_max_in_flight", 1) or 1))
        if verify:
            from .static_analysis.concurrency import verify_async_hot_path

            verify_async_hot_path(
                self._program,
                targets=[v.name for v in self._fetch_vars],
                max_in_flight=max_in_flight, label="serving hot loop")
        if request_ids is not None:
            request_ids = list(request_ids)
        return self._run_batches(batches, max_in_flight, return_numpy,
                                 request_ids)

    def _run_batches(self, batches, max_in_flight, return_numpy,
                     request_ids=None):
        import collections

        from . import pipeline as pl
        from .executor import _check_feed_shapes

        def feeds():
            for i, b in enumerate(batches):
                rid = None
                if request_ids is not None and i < len(request_ids):
                    rid = request_ids[i]
                try:
                    feed = self._as_feed(b)
                    _check_feed_shapes(self._program, feed)
                except ValueError as exc:
                    who = ("request %r (batch #%d)" % (rid, i)
                           if rid is not None else "batch #%d" % i)
                    raise ValueError("%s: %s" % (who, exc)) from None
                yield feed

        def finish(handles):
            return pl.materialize(handles) if return_numpy else handles

        inflight = collections.deque()
        for feed in pl.DeviceFeedPipeline(feeds, depth=max_in_flight):
            inflight.append(self.run_async(feed))
            if len(inflight) >= max_in_flight:
                yield finish(inflight.popleft())
        while inflight:
            yield finish(inflight.popleft())


def create_paddle_predictor(config):
    """Reference ``CreatePaddlePredictor<AnalysisConfig>``."""
    return AnalysisPredictor(config)
