"""Async gradient communicator facade (reference:
``python/paddle/fluid/communicator.py`` → ``pybind/communicator_py.cc`` →
``operators/distributed/communicator.h:160`` — background send/recv
threads shipping grads to parameter servers between steps).

TPU redesign: there is no parameter server and no background threads —
gradient communication is the GSPMD all-reduce fused INTO the step by the
partitioner (SURVEY §2.3); the async PS mode the Communicator served maps
to ``transpiler.collective.AsyncSGD`` (staleness-1 delayed gradient
exchange — the head collective ships LAST step's grads so XLA overlaps it
with compute, the scheduler-level analogue of the send/recv threads) and
to ``host_table.HostEmbeddingTable.update_async`` for the sparse path.
The class keeps the reference's lifecycle API so PS-era training scripts
run unchanged; the state answers honestly (communication is always
'running' while a distributed mesh is active)."""

__all__ = ["Communicator"]


class Communicator:
    def __init__(self, program=None, mode=None, kwargs=None, envs=None):
        self._program = program
        self._running = False

    def start(self):
        """No background threads to spawn: the all-reduce rides the jitted
        step over ICI."""
        self._running = True

    def stop(self):
        self._running = False

    def is_running(self):
        return self._running
