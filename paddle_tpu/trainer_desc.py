"""Trainer configuration facades (reference:
``python/paddle/fluid/trainer_desc.py`` — TrainerDesc/MultiTrainer/
DistMultiTrainer/PipelineTrainer emit a TrainerDesc proto consumed by the
C++ trainer runtime, ``framework/trainer.h:38``).

TPU redesign: there is no thread-per-core C++ worker runtime — one jitted
SPMD step IS the worker (SURVEY §2.1 Trainer/DeviceWorker row), so these
classes carry the SAME configuration surface (thread num, fetch config,
debug, device worker choice) as plain Python state;
``dataset_runtime.run_from_dataset`` RECORDS the resolved trainer on the
program (``program._trainer_desc``) for inspection — the knobs configure
nothing at runtime because the jitted step already owns all cores."""

from . import device_worker as dw

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer", "TrainerFactory"]


class TrainerDesc:
    """reference trainer_desc.py:21."""

    def __init__(self):
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100
        self._debug = False
        self._thread_num = 1
        self._device_worker = None
        self._infer = False
        self._program = None
        self._fleet_desc = None

    def _set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = list(fetch_vars or [])
        self._fetch_info = list(fetch_info or [])
        self._print_period = print_period

    def _set_debug(self, debug):
        self._debug = debug

    def _set_thread(self, thread_num):
        # the jitted step owns all cores; recorded for API parity
        self._thread_num = thread_num

    def _set_device_worker(self, device_worker):
        self._device_worker = device_worker
        device_worker._set_trainer(self)

    def _set_infer(self, infer):
        self._infer = infer

    def _set_program(self, program):
        self._program = program

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _gen_trainer_desc(self):
        return self


class MultiTrainer(TrainerDesc):
    """reference trainer_desc.py MultiTrainer (thread-per-core Hogwild in
    C++; one SPMD step here)."""

    def _gen_trainer_desc(self):
        if self._device_worker is None:
            self._set_device_worker(dw.Hogwild())
        return self


class DistMultiTrainer(TrainerDesc):
    """reference DistMultiTrainer (pserver pull/push workers).  The PS
    runtime is replaced by sharded embeddings (is_distributed=True); this
    trainer runs the same local loop."""

    def _gen_trainer_desc(self):
        if self._device_worker is None:
            self._set_device_worker(dw.DownpourSGD())
        return self


class PipelineTrainer(TrainerDesc):
    """reference PipelineTrainer + SectionWorker: the pipeline schedule is
    parallel.gpipe (shard_map + ppermute), configured by
    PipelineOptimizer's program._pipeline_opt."""

    def _gen_trainer_desc(self):
        if self._device_worker is None:
            self._set_device_worker(dw.Section())
        return self


class TrainerFactory:
    """reference trainer_factory.py: map (TrainerDesc name, DeviceWorker
    name) strings from a Dataset/opt config onto the classes above."""

    _TRAINERS = {
        "MultiTrainer": MultiTrainer,
        "DistMultiTrainer": DistMultiTrainer,
        "PipelineTrainer": PipelineTrainer,
    }

    def _create_trainer(self, opt_info=None):
        import warnings

        opt_info = opt_info or {}
        name = opt_info.get("trainer", "MultiTrainer")
        worker = opt_info.get("device_worker", None)
        cls = self._TRAINERS.get(name)
        if cls is None:
            warnings.warn(
                "unknown trainer %r; falling back to MultiTrainer" % name)
            cls = MultiTrainer
        trainer = cls()
        if worker:
            wcls = getattr(dw, worker, None)
            if wcls is None:
                warnings.warn(
                    "unknown device worker %r; using the trainer default"
                    % worker)
            else:
                trainer._set_device_worker(wcls())
        return trainer._gen_trainer_desc()
