"""Checkpoint save/load + inference export (reference:
``python/paddle/fluid/io.py``: save/load_vars :108, save_persistables :475,
load_persistables :714, save_inference_model :921, load_inference_model
:1109).

Storage format: one ``.npy`` per var (filename = var name) or a combined
``.npz`` — numpy containers instead of the reference's LoDTensor binary
framing.  The orbax-style sharded checkpoint path for multi-host lands with
the distributed batch."""

import os

import numpy as np

from .framework import Program, Parameter, default_main_program
from .executor import global_scope
from . import proto

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "get_program_parameter",
]

MODEL_FILENAME = "__model__"


def _is_persistable(var):
    return var.persistable and not var.is_data


def _is_parameter(var):
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [
            v for v in main_program.list_vars()
            if (predicate or _is_persistable)(v)
        ]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is None:
        for v in vars:
            val = scope.get(v.name)
            if val is None:
                continue
            np.save(os.path.join(dirname, v.name.replace("/", "_")),
                    np.asarray(val))
    else:
        arrays = {}
        for v in vars:
            val = scope.get(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        np.savez(os.path.join(dirname, filename), **arrays)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp

    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [
            v for v in main_program.list_vars()
            if (predicate or _is_persistable)(v)
        ]
    scope = global_scope()
    if filename is None:
        for v in vars:
            path = os.path.join(dirname, v.name.replace("/", "_") + ".npy")
            if not os.path.exists(path):
                continue
            scope.set(v.name, jnp.asarray(np.load(path)))
    else:
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path = path + ".npz"
        data = np.load(path)
        for v in vars:
            if v.name in data:
                scope.set(v.name, jnp.asarray(data[v.name]))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """Prune to the inference subgraph + serialize (reference io.py:921)."""
    if main_program is None:
        main_program = default_main_program()
    pruned = main_program.clone(for_test=True)
    target_names = [v.name for v in target_vars]
    pruned = pruned._prune(feeded_var_names, target_names)
    os.makedirs(dirname, exist_ok=True)
    proto.save_program(
        pruned, os.path.join(dirname, model_filename or MODEL_FILENAME)
    )
    meta = {"feed": list(feeded_var_names), "fetch": target_names}
    import json

    with open(os.path.join(dirname, "__meta__.json"), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, main_program=pruned,
                      filename=params_filename)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import json

    program = proto.load_program(
        os.path.join(dirname, model_filename or MODEL_FILENAME)
    )
    with open(os.path.join(dirname, "__meta__.json")) as f:
        meta = json.load(f)
    load_persistables(executor, dirname, main_program=program,
                      filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch"]]
    return program, meta["feed"], fetch_vars


def get_program_parameter(program):
    return list(program.all_parameters())
