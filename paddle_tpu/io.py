"""Checkpoint save/load + inference export (reference:
``python/paddle/fluid/io.py``: save/load_vars :108, save_persistables :475,
load_persistables :714, save_inference_model :921, load_inference_model
:1109).

Storage format: one ``.npy`` per var (filename = var name) or a combined
``.npz`` — numpy containers instead of the reference's LoDTensor binary
framing.

Sharded vars (row-sharded ``is_distributed`` tables and their table-shaped
optimizer accumulators — the reference's pserver-sliced persistables,
``python/paddle/fluid/io.py:294`` ``_save_distributed_persistables``) are
saved WITHOUT gathering: each process writes only its addressable shards
(replica 0) into ``<var>.shards/`` keyed by global index range, and load
reassembles directly onto the live sharding via ``make_array_from_callback``
— each device reads only its own rows, so a multi-host table never
materializes on any single host in either direction."""

import json
import os

import numpy as np

from .framework import Program, Parameter, default_main_program
from .executor import global_scope
from . import proto

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "get_program_parameter",
    "PyReader",
]

MODEL_FILENAME = "__model__"


# the one atomic-write idiom, shared with the resilience runtime
# (stdlib-only module: no import-cycle risk)
from .resilience.atomic import atomic_write as _atomic_write


def _atomic_np_save(path, arr):
    _atomic_write(path, lambda f: np.save(f, arr))


def _load_array(path, var_name):
    """np.load with failures renamed to something actionable: which file,
    which variable, what's wrong — instead of a bare numpy/zipfile
    traceback from deep inside a restore."""
    import zipfile

    if not os.path.exists(path):
        raise RuntimeError(
            "checkpoint file %r for variable %r is missing — the "
            "checkpoint directory is incomplete (torn save or wrong "
            "dirname)" % (path, var_name))
    try:
        return np.load(path)
    except (ValueError, OSError, EOFError, zipfile.BadZipFile) as e:
        raise RuntimeError(
            "checkpoint file %r for variable %r is corrupt or "
            "unreadable: %s" % (path, var_name, e)) from e


def _is_persistable(var):
    return var.persistable and not var.is_data


def _is_sharded_value(val):
    """True for a jax Array actually laid out across devices (vs
    replicated) — the values that must not be gathered to one host."""
    sharding = getattr(val, "sharding", None)
    if sharding is None:
        return False
    try:
        return not val.is_fully_replicated
    except (AttributeError, TypeError):
        return False


def _index_key(index, shape):
    """Canonical start/stop bounds of a shard's global slice."""
    bounds = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        bounds.append((start, stop))
    return tuple(bounds)


def _shard_fname(bounds):
    return "shard-" + "-".join("%d_%d" % b for b in bounds) + ".npy"


def _save_sharded(dirname, name, val):
    """Per-process shard save: each process writes only the shards it can
    address, one file per distinct global slice (replica 0 only, so a
    table replicated over a second mesh axis is written once).  meta.json
    records the COMPLETE global file list (derivable on every process
    from the sharding), so load ignores stale files from an earlier save
    with a different layout and can detect missing shards."""
    safe = name.replace("/", "_")
    shard_dir = os.path.join(dirname, safe + ".shards")
    os.makedirs(shard_dir, exist_ok=True)
    all_files = sorted({
        _shard_fname(_index_key(idx, val.shape))
        for idx in val.sharding.devices_indices_map(val.shape).values()
    })
    for shard in val.addressable_shards:
        if shard.replica_id != 0:
            continue
        bounds = _index_key(shard.index, val.shape)
        _atomic_np_save(os.path.join(shard_dir, _shard_fname(bounds)),
                        np.asarray(shard.data))
    # meta is tiny and identical on every process; write-then-rename so
    # concurrent writers on a shared filesystem can never leave a torn
    # meta.json (os.replace is atomic on POSIX)
    meta_tmp = os.path.join(
        shard_dir, ".meta.json.tmp.%d" % os.getpid())
    with open(meta_tmp, "w") as f:
        json.dump({"shape": list(val.shape), "dtype": str(val.dtype),
                   "files": all_files}, f)
    os.replace(meta_tmp, os.path.join(shard_dir, "meta.json"))


def _shard_entries(shard_dir, meta):
    """(bounds, path) for each shard file of THIS save (meta-listed)."""
    names = meta.get("files")
    if names is None:  # pre-meta-list checkpoint dirs
        names = [f for f in os.listdir(shard_dir)
                 if f.startswith("shard-") and f.endswith(".npy")]
    entries = []
    for fname in names:
        fb = tuple(tuple(int(x) for x in part.split("_"))
                   for part in fname[len("shard-"):-len(".npy")].split("-"))
        entries.append((fb, os.path.join(shard_dir, fname)))
    return entries


def _read_sharded_region(entries, meta, bounds, name):
    """Assemble the [start, stop) region from the shard files overlapping
    it — reads only the overlapping files, not the whole table.  A region
    not fully covered raises: silently zero-filling rows (e.g. loading a
    2-host checkpoint where only one host's shards are visible) would
    resume training from a corrupted model."""
    region = np.zeros([b[1] - b[0] for b in bounds],
                      dtype=np.dtype(meta["dtype"]))
    covered = np.zeros(region.shape, dtype=bool)
    for fb, path in entries:
        overlap = [(max(a0, b0), min(a1, b1))
                   for (a0, a1), (b0, b1) in zip(fb, bounds)]
        if any(o0 >= o1 for o0, o1 in overlap):
            continue
        if not os.path.exists(path):
            raise RuntimeError(
                "sharded checkpoint for %r is missing %s — all shard "
                "files listed in meta.json must be reachable from this "
                "process (on multi-host, merge the per-host checkpoint "
                "dirs or load on the saving topology)" % (name, path))
        data = _load_array(path, name)
        src = tuple(slice(o0 - f0, o1 - f0)
                    for (o0, o1), (f0, _) in zip(overlap, fb))
        dst = tuple(slice(o0 - b0, o1 - b0)
                    for (o0, o1), (b0, _) in zip(overlap, bounds))
        region[dst] = data[src]
        covered[dst] = True
    if not covered.all():
        raise RuntimeError(
            "sharded checkpoint for %r does not cover region %s — the "
            "meta.json shard list leaves gaps (partial or corrupted "
            "checkpoint dir)" % (name, bounds))
    return region


def _load_sharded(shard_dir, current, name):
    """Rebuild a sharded var.  When the live scope value still carries a
    device layout, place each device's rows directly (no host-level full
    table); otherwise fall back to a host assembly (single-device use)."""
    import jax
    import jax.numpy as jnp

    meta_path = os.path.join(shard_dir, "meta.json")
    if not os.path.exists(meta_path):
        raise RuntimeError(
            "sharded checkpoint for %r has no meta.json under %r — torn "
            "or pre-meta save; re-save the checkpoint" % (name, shard_dir))
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except ValueError as e:
        raise RuntimeError(
            "sharded checkpoint meta %r for %r is corrupt: %s"
            % (meta_path, name, e)) from e
    shape = tuple(meta["shape"])
    entries = _shard_entries(shard_dir, meta)
    if current is not None and _is_sharded_value(current) \
            and tuple(current.shape) == shape:
        sharding = current.sharding

        def cb(index):
            return _read_sharded_region(
                entries, meta, _index_key(index, shape), name)

        return jax.make_array_from_callback(shape, sharding, cb)
    full_bounds = tuple((0, d) for d in shape)
    return jnp.asarray(
        _read_sharded_region(entries, meta, full_bounds, name))


def _is_parameter(var):
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [
            v for v in main_program.list_vars()
            if (predicate or _is_persistable)(v)
        ]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    from .resilience.faults import get_injector

    inj = get_injector()
    if inj.active:
        inj.maybe_fire("io_write")
    if filename is None:
        for v in vars:
            val = scope.get(v.name)
            if val is None:
                continue
            if _is_sharded_value(val):
                _save_sharded(dirname, v.name, val)
            else:
                _atomic_np_save(
                    os.path.join(dirname,
                                 v.name.replace("/", "_") + ".npy"),
                    np.asarray(val))
    else:
        arrays = {}
        for v in vars:
            val = scope.get(v.name)
            if val is None:
                continue
            if _is_sharded_value(val):
                # sharded vars never enter the combined container: a
                # gather would defeat the per-process shard contract
                _save_sharded(dirname, v.name, val)
            else:
                arrays[v.name] = np.asarray(val)
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path += ".npz"
        _atomic_write(path, lambda f: np.savez(f, **arrays))


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


from .reader import PyReader  # noqa: F401  (reference fluid.io.PyReader)


def _host_tables_of(main_program):
    from . import host_table as _ht

    prog = main_program or default_main_program()
    names = {spec["table"]
             for spec in getattr(prog, "_host_tables", None) or []}
    return [_ht.get_table(n) for n in sorted(names)]


def save_persistables(executor, dirname, main_program=None, filename=None):
    r = save_vars(executor, dirname, main_program,
                  predicate=_is_persistable, filename=filename)
    # host-resident embedding tables persist in the same per-shard
    # layout (reshard-compatible with device-sharded checkpoints)
    for tab in _host_tables_of(main_program):
        tab.save(dirname)
    return r


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp

    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [
            v for v in main_program.list_vars()
            if (predicate or _is_persistable)(v)
        ]
    scope = global_scope()
    from .resilience.faults import get_injector

    inj = get_injector()
    if inj.active:
        inj.maybe_fire("io_read")
    if filename is None:
        for v in vars:
            safe = v.name.replace("/", "_")
            shard_dir = os.path.join(dirname, safe + ".shards")
            if os.path.isdir(shard_dir):
                cur = scope.get(v.name) if scope.has(v.name) else None
                scope.set(v.name, _load_sharded(shard_dir, cur, v.name))
                continue
            path = os.path.join(dirname, safe + ".npy")
            if not os.path.exists(path):
                # historically a silent skip; at least surface the
                # partial restore — the var keeps its current (likely
                # freshly-initialized) value.  Raising here would break
                # legitimate subset loads (load_params over a program
                # that also holds never-saved state), so: warn.
                import warnings

                warnings.warn(
                    "checkpoint dir %r has no file for variable %r — "
                    "it keeps its current value (partial restore?)"
                    % (dirname, v.name), RuntimeWarning, stacklevel=2)
                continue
            scope.set(v.name, jnp.asarray(_load_array(path, v.name)))
    else:
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path = path + ".npz"
        if not os.path.exists(path):
            raise RuntimeError(
                "combined checkpoint file %r does not exist — nothing "
                "was saved under filename %r in %r"
                % (path, filename, dirname))
        data = _load_array(path, "<combined>")
        for v in vars:
            shard_dir = os.path.join(
                dirname, v.name.replace("/", "_") + ".shards")
            if os.path.isdir(shard_dir):
                cur = scope.get(v.name) if scope.has(v.name) else None
                scope.set(v.name, _load_sharded(shard_dir, cur, v.name))
            elif v.name in data:
                # npz loads lazily: a truncated/corrupt MEMBER surfaces
                # here, not at np.load — name the var and file
                import zipfile
                import zlib

                try:
                    arr = data[v.name]
                except (ValueError, OSError, EOFError,
                        zipfile.BadZipFile, zlib.error) as e:
                    raise RuntimeError(
                        "member %r of combined checkpoint %r is corrupt "
                        "or unreadable: %s" % (v.name, path, e)) from e
                scope.set(v.name, jnp.asarray(arr))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    r = load_vars(executor, dirname, main_program,
                  predicate=_is_persistable, filename=filename)
    for tab in _host_tables_of(main_program):
        if tab.has_checkpoint(dirname):
            tab.load(dirname)
    return r


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """Prune to the inference subgraph + serialize (reference io.py:921)."""
    if main_program is None:
        main_program = default_main_program()
    pruned = main_program.clone(for_test=True)
    target_names = [v.name for v in target_vars]
    pruned = pruned._prune(feeded_var_names, target_names)
    os.makedirs(dirname, exist_ok=True)
    proto.save_program(
        pruned, os.path.join(dirname, model_filename or MODEL_FILENAME)
    )
    meta = {"feed": list(feeded_var_names), "fetch": target_names}
    import json

    with open(os.path.join(dirname, "__meta__.json"), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, main_program=pruned,
                      filename=params_filename)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import json

    program = proto.load_program(
        os.path.join(dirname, model_filename or MODEL_FILENAME)
    )
    with open(os.path.join(dirname, "__meta__.json")) as f:
        meta = json.load(f)
    load_persistables(executor, dirname, main_program=program,
                      filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch"]]
    return program, meta["feed"], fetch_vars


def get_program_parameter(program):
    return list(program.all_parameters())
