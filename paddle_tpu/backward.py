"""Program-level reverse-mode autodiff.

Mirrors the reference's ``python/paddle/fluid/backward.py``: `append_backward`
(backward.py:432) walks the op list in reverse, appends one ``<type>_grad`` op
per forward op, inserts `sum` ops where a var's grad fans in from several
consumers (``_addup_repetitive_outputs_``, backward.py:135), and prunes
branches with no grad path (backward.py:211,655).

Where the reference asks a C++ registry for hand-written grad-op descs
(``core.get_grad_op_desc``, grad_op_desc_maker.h:36), the grad op here is by
default the *generic* ``<type>_grad`` whose lowering is ``jax.vjp`` over the
forward lowering (ops/registry.py) — the grad program structure is identical,
but every op's grad rule is derived from its own XLA lowering, and XLA CSEs
the recomputed forward against the original forward ops at jit time.
"""

from .framework import Parameter, Variable, grad_var_name
from . import unique_name
from .ops import registry as op_registry

__all__ = ["append_backward", "gradients", "calc_gradient"]


def _find_op_path(block, targets, sources=None):
    """Indices of ops contributing to `targets` (reference backward.py:655)."""
    needed = set(t.name if isinstance(t, Variable) else t for t in targets)
    path = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if needed & set(op.output_arg_names):
            path.append(idx)
            needed.update(op.input_arg_names)
    path.reverse()
    return path


def _var_can_have_grad(block, name, no_grad_set):
    if name in no_grad_set or not name or name == op_registry.EMPTY_VAR_NAME:
        return False
    v = block._find_var_recursive(name)
    if v is None:
        return False
    if v.stop_gradient:
        return False
    if v.dtype is not None and v.dtype not in (
        "float16", "float32", "float64", "bfloat16"
    ):
        return False
    return True


def _create_grad_var(block, fwd_name, grad_name):
    fwd = block._find_var_recursive(fwd_name)
    if block.has_var(grad_name):
        return block.var(grad_name)
    return block.create_var(
        name=grad_name,
        shape=fwd.shape if fwd is not None else None,
        dtype=fwd.dtype if fwd is not None else "float32",
        persistable=False,
        stop_gradient=False,
    )


class _GradEngine:
    """Reverse accumulation over one block."""

    def __init__(self, block, no_grad_set):
        self.block = block
        self.no_grad_set = set(no_grad_set or [])
        # forward var name -> list of pending grad var names (fan-in)
        self.pending = {}
        # forward var name -> resolved (summed) grad var name
        self.resolved = {}

    def seed(self, var_name, grad_name):
        self.pending.setdefault(var_name, []).append(grad_name)

    def resolve(self, var_name):
        """Sum pending grads of `var_name` (reference
        _addup_repetitive_outputs_)."""
        if var_name in self.resolved:
            return self.resolved[var_name]
        plist = self.pending.get(var_name)
        if not plist:
            return None
        if len(plist) == 1:
            g = plist[0]
        else:
            g = grad_var_name(var_name)
            if g in plist:  # canonical name already used by one producer
                g = unique_name.generate(grad_var_name(var_name) + "@SUM")
            _create_grad_var(self.block, var_name, g)
            self.block.append_op(
                type="sum",
                inputs={"X": list(plist)},
                outputs={"Out": [g]},
                attrs={"op_role": "backward"},
            )
        self.resolved[var_name] = g
        return g

    def new_grad_name(self, var_name):
        base = grad_var_name(var_name)
        n = len(self.pending.get(var_name, []))
        if n == 0 and not self.block.has_var(base):
            return base
        return unique_name.generate(base + "@RENAME")

    def backprop_op(self, op):
        """Append the grad op(s) for `op`; returns True if appended."""
        from .ops import control_flow as cf_ops

        if op.type in ("while", "conditional_block", "recurrent",
                       "recompute_block"):
            return self._backprop_sub_block_op(op)
        try:
            opdef = op_registry.get_op_def(op.type)
        except op_registry.OpNotRegistered:
            return False
        if opdef.no_grad:
            return False

        # resolve available output grads
        out_grads = {}
        any_grad = False
        for slot, names in op.outputs.items():
            if slot in opdef.stateful_outputs:
                continue
            gnames = []
            got = False
            for y in names:
                g = self.resolve(y)
                gnames.append(g if g is not None else op_registry.EMPTY_VAR_NAME)
                got = got or g is not None
            if got:
                out_grads[slot + "@GRAD"] = gnames
                any_grad = True
        if not any_grad:
            return False

        # which input grads to produce; a var appearing in SEVERAL input
        # slots (e.g. merge_lod_tensor's InTrue also bound to X) must get
        # DISTINCT grad names per slot, else the later slot's (often zero)
        # grad overwrites the real one in the SSA env
        in_grads = {}
        used_gnames = set()
        for slot, names in op.inputs.items():
            gnames = []
            need = False
            for x in names:
                if _var_can_have_grad(self.block, x, self.no_grad_set):
                    gn = self.new_grad_name(x)
                    while gn in used_gnames:
                        gn = unique_name.generate(
                            grad_var_name(x) + "@RENAME")
                    used_gnames.add(gn)
                    gnames.append(gn)
                    need = True
                else:
                    gnames.append(op_registry.EMPTY_VAR_NAME)
            if need:
                in_grads[slot + "@GRAD"] = gnames
        if not in_grads:
            return False

        if opdef.grad_maker is not None:
            descs = opdef.grad_maker(op, self.block, out_grads, in_grads)
        else:
            # default maker: grad op sees all fwd inputs, outputs, out-grads
            inputs = {}
            for slot, names in op.inputs.items():
                inputs[slot] = list(names)
            for slot, names in op.outputs.items():
                inputs[slot] = list(names)
            inputs.update(out_grads)
            attrs = dict(op.attrs)
            attrs["__fwd_op_id__"] = op.attrs.get("__op_id__", 0)
            attrs["op_role"] = "backward"
            attrs.pop("__op_id__", None)
            descs = [
                {
                    "type": op.type + "_grad",
                    "inputs": inputs,
                    "outputs": in_grads,
                    "attrs": attrs,
                }
            ]

        for d in descs:
            for slot, gnames in d["outputs"].items():
                if not slot.endswith("@GRAD"):
                    continue
                fwd_slot = slot[: -len("@GRAD")]
                fwd_names = op.inputs.get(fwd_slot, [])
                for fn_, gn in zip(fwd_names, gnames):
                    if gn != op_registry.EMPTY_VAR_NAME:
                        _create_grad_var(self.block, fn_, gn)
            self.block.append_op(
                type=d["type"],
                inputs=d["inputs"],
                outputs=d["outputs"],
                attrs=d.get("attrs", {}),
            )

        # register produced grads as pending on the forward inputs
        for slot, names in op.inputs.items():
            gnames = in_grads.get(slot + "@GRAD")
            if not gnames:
                continue
            for x, g in zip(names, gnames):
                if g != op_registry.EMPTY_VAR_NAME:
                    self.pending.setdefault(x, []).append(g)
        return True

    def _backprop_sub_block_op(self, op):
        """Grads through control-flow ops (reference: while_grad,
        recurrent_grad ops registered in C++; here the grad op's lowering is
        jax.vjp over the scan/cond closure, differentiating w.r.t. declared
        inputs AND the sub-block's captured outer vars — the parameters used
        inside the step block)."""
        from .ops import control_flow as cf_ops

        out_slot = {"recurrent": "outputs", "conditional_block": "Out",
                    "while": "Out", "recompute_block": "Out"}[op.type]
        out_names = op.outputs.get(out_slot, [])
        gnames = []
        any_grad = False
        for y in out_names:
            g = self.resolve(y)
            gnames.append(g if g is not None else op_registry.EMPTY_VAR_NAME)
            any_grad = any_grad or g is not None
        if not any_grad:
            return False
        # unbounded `while` (no max_trip_count) is allowed: the executor
        # probes the concrete trip count before tracing and the grad
        # lowers as a masked scan of that length (while_op.cc:189 parity,
        # two-pass because XLA has no reverse-mode while_loop)

        sub_block = self.block.program.block(op.attrs["sub_block"])
        exclude = set()
        if op.type == "recurrent":
            exclude.update(op.attrs.get("step_input_names", []))
            exclude.update(op.attrs.get("state_names", []))
        if op.type == "while":
            # loop-state vars get their grads through StateIn@GRAD (w.r.t.
            # their pre-loop values), not through the captured-closure path
            exclude.update(op.outputs.get("Out", []))
        captured = [
            n for n in cf_ops.sub_block_external_reads(sub_block, exclude)
            if self.block._find_var_recursive(n) is not None
        ]

        inputs = {k: list(v) for k, v in op.inputs.items()}
        inputs["Captured"] = captured
        inputs[out_slot] = list(out_names)
        inputs[out_slot + "@GRAD"] = gnames

        outputs = {}
        grad_targets = []  # (fwd_name, grad_name) to register as pending
        if op.type == "while":
            # the same names flow in and out of the loop: the grads just
            # resolved above were w.r.t. the POST-loop values; reset the
            # accumulator so grads seeded below (w.r.t. the PRE-loop values)
            # reach the pre-loop producers
            gouts = []
            for x in out_names:
                self.resolved.pop(x, None)
                self.pending[x] = []
                if _var_can_have_grad(self.block, x, self.no_grad_set):
                    gn = self.new_grad_name(x)
                    gouts.append(gn)
                    grad_targets.append((x, gn))
                else:
                    gouts.append(op_registry.EMPTY_VAR_NAME)
            if any(g != op_registry.EMPTY_VAR_NAME for g in gouts):
                outputs["StateIn@GRAD"] = gouts
        for slot in (("inputs", "initial_states") if op.type == "recurrent"
                     else ()):
            names = op.inputs.get(slot, [])
            gouts = []
            for x in names:
                if _var_can_have_grad(self.block, x, self.no_grad_set):
                    gn = self.new_grad_name(x)
                    gouts.append(gn)
                    grad_targets.append((x, gn))
                else:
                    gouts.append(op_registry.EMPTY_VAR_NAME)
            if any(g != op_registry.EMPTY_VAR_NAME for g in gouts):
                outputs[slot + "@GRAD"] = gouts
        cap_gouts = []
        for x in captured:
            if _var_can_have_grad(self.block, x, self.no_grad_set):
                gn = self.new_grad_name(x)
                cap_gouts.append(gn)
                grad_targets.append((x, gn))
            else:
                cap_gouts.append(op_registry.EMPTY_VAR_NAME)
        outputs["Captured@GRAD"] = cap_gouts
        if not grad_targets:
            return False

        attrs = dict(op.attrs)
        attrs["__fwd_op_id__"] = op.attrs.get("__op_id__", 0)
        attrs["op_role"] = "backward"
        attrs.pop("__op_id__", None)
        for fwd_name, gn in grad_targets:
            _create_grad_var(self.block, fwd_name, gn)
        self.block.append_op(
            type=op.type + "_grad", inputs=inputs, outputs=outputs,
            attrs=attrs,
        )
        for fwd_name, gn in grad_targets:
            self.pending.setdefault(fwd_name, []).append(gn)
        return True


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append grad ops for `loss`; returns [(param, grad_var)] (reference
    backward.py:432)."""
    assert isinstance(loss, Variable)
    block = loss.block
    program = block.program

    no_grad = set(no_grad_set or [])
    for v in block.vars.values():
        if v.stop_gradient and not isinstance(v, Parameter):
            no_grad.add(v.name)

    op_path = _find_op_path(block, [loss])

    # d(loss)/d(loss) = 1
    loss_g_name = grad_var_name(loss.name)
    loss_grad = block.create_var(
        name=loss_g_name,
        shape=loss.shape or (1,),
        dtype=loss.dtype,
        persistable=False,
    )
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={
            "shape": list(loss.shape or (1,)),
            "value": 1.0,
            "dtype": loss.dtype,
            "op_role": "backward",
        },
    )

    engine = _GradEngine(block, no_grad)
    engine.seed(loss.name, loss_g_name)
    for idx in reversed(op_path):
        engine.backprop_op(block.ops[idx])

    if parameter_list is not None:
        params = [
            block.program.global_block().var(p) if isinstance(p, str) else p
            for p in parameter_list
        ]
    else:
        params = [p for p in program.all_parameters() if p.trainable]

    params_grads = []
    for p in params:
        g = engine.resolve(p.name)
        if g is None:
            continue
        params_grads.append((p, block.var(g)))
    return params_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Grads of `targets` w.r.t. `inputs` (reference backward.py:695)."""
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    if isinstance(target_gradients, Variable):
        target_gradients = [target_gradients]
    block = targets[0].block

    engine = _GradEngine(block, no_grad_set)
    op_path = _find_op_path(block, targets)
    for i, t in enumerate(targets):
        tg = target_gradients[i] if target_gradients else None
        if tg is None:
            gname = grad_var_name(t.name)
            gv = block.create_var(
                name=gname, shape=t.shape, dtype=t.dtype
            )
            block.append_op(
                type="fill_constant",
                outputs={"Out": [gv]},
                attrs={
                    "shape": list(t.shape or (1,)),
                    "value": 1.0,
                    "dtype": t.dtype,
                    "op_role": "backward",
                },
            )
            engine.seed(t.name, gname)
        else:
            engine.seed(t.name, tg.name)
    for idx in reversed(op_path):
        engine.backprop_op(block.ops[idx])
    outs = []
    for x in inputs:
        g = engine.resolve(x.name)
        outs.append(block.var(g) if g is not None else None)
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
