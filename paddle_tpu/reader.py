"""Host input pipeline (reference: ``python/paddle/fluid/reader.py`` PyReader
→ background thread → LoDTensorBlockingQueue → read op).

TPU-native: a double-buffered background-thread prefetcher that overlaps
host batch assembly + H2D transfer with device compute — the role the
reference's blocking queue + read op play, without graph-side reader ops.
With ``use_double_buffer`` (the default, matching the reference's
double_buffer decorator) the prefetch thread additionally
``jax.device_put``\\ s each staged batch via ``paddle_tpu.pipeline``, so
the Executor's async dispatch never pays per-step H2D latency; depth is
``PADDLE_TPU_PIPELINE_DEPTH`` (default 2).  In-process batches pass by
REFERENCE through a bounded queue.Queue (its condition variables already
release the GIL during waits; serializing numpy batches here would only
add copies).  The native byte-buffer queue (``native.BlockingQueue``,
blocking_queue.cc) serves the serialized-batch/multi-process role of the
reference's LoDTensorBlockingQueue instead."""

import queue as _queue
import threading

import numpy as np

__all__ = ["PyReader", "DataLoader"]

_SENTINEL = "__paddle_tpu_epoch_end__"


class _Prefetcher:
    def __init__(self, gen_fn, capacity):
        self.gen_fn = gen_fn
        self.capacity = capacity
        self.queue = None
        self.thread = None
        self._stop = threading.Event()

    def start(self):
        self.queue = _queue.Queue(maxsize=self.capacity)
        self._stop.clear()

        def worker():
            try:
                for item in self.gen_fn():
                    if self._stop.is_set():
                        return
                    self.queue.put(item)
            finally:
                self.queue.put(_SENTINEL)  # end-of-epoch sentinel

        self.thread = threading.Thread(target=worker, daemon=True)
        self.thread.start()

    def stop(self):
        self._stop.set()
        if self.queue is not None:
            try:
                while True:
                    self.queue.get_nowait()
            except _queue.Empty:
                pass

    def __iter__(self):
        while True:
            item = self.queue.get()
            if isinstance(item, str) and item == _SENTINEL:
                return
            yield item


class _DeviceStagedPrefetcher:
    """Two-stage prefetch: ``capacity`` host batches buffered by the
    classic background thread (the user's knob, unchanged), with the
    device pipeline staging the front ``PADDLE_TPU_PIPELINE_DEPTH`` of
    them via ``jax.device_put`` — deep host buffering rides out jittery
    sample generators while device residency stays bounded."""

    def __init__(self, gen_fn, capacity):
        from .pipeline import DeviceFeedPipeline

        self._host = _Prefetcher(gen_fn, capacity)
        self._dev = DeviceFeedPipeline(lambda: iter(self._host))

    def start(self):
        self._host.start()
        self._dev.start()

    def stop(self):
        self._dev.stop()
        self._host.stop()

    def __iter__(self):
        return iter(self._dev)


class PyReader:
    """Iterable/decorated reader (reference reader.py:46).  Use
    ``decorate_sample_list_generator``/``decorate_batch_generator`` then
    iterate: each item is a feed dict."""

    def __init__(self, feed_list=None, capacity=16, use_double_buffer=True,
                 iterable=True, return_list=False):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._use_double_buffer = bool(use_double_buffer)
        self._prefetcher = None
        self._feeder = None

    def decorate_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder

        feeder = DataFeeder(self._feed_list, places)

        def gen():
            for batch in reader():
                yield feeder.feed(batch)

        self._gen = gen
        return self

    def decorate_batch_generator(self, reader, places=None):
        def gen():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {
                        v.name: np.asarray(b)
                        for v, b in zip(self._feed_list, batch)
                    }

        self._gen = gen
        return self

    decorate_paddle_reader = decorate_sample_list_generator

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        """reference PyReader.decorate_sample_generator: batch a
        per-sample generator then feed (reader_py.cc role)."""

        def batched():
            buf = []
            for sample in sample_generator():
                buf.append(sample)
                if len(buf) == batch_size:
                    yield buf
                    buf = []
            if buf and not drop_last:
                yield buf

        return self.decorate_sample_list_generator(batched, places)

    def start(self):
        if self._use_double_buffer:
            self._prefetcher = _DeviceStagedPrefetcher(
                self._gen, self._capacity)
        else:
            self._prefetcher = _Prefetcher(self._gen, self._capacity)
        self._prefetcher.start()

    def reset(self):
        if self._prefetcher:
            self._prefetcher.stop()
        self._prefetcher = None

    def __iter__(self):
        if self._prefetcher is None:
            self.start()
        p = self._prefetcher
        self._prefetcher = None
        return iter(p)


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=16, use_double_buffer=True,
                       iterable=True, return_list=False):
        return PyReader(feed_list, capacity, use_double_buffer, iterable,
                        return_list)
