"""Static analysis over the paddle_tpu Program IR: def-use graph,
program verifier, and lint pass framework.

Motivation (ISSUE 1): the Executor lowers a whole Program to one jaxpr, so
a malformed program — dangling read after a bad fuse, dtype drift, a
double write aliasing donated param buffers — surfaces only as an opaque
trace-time JAX error or silently wrong numerics.  This package restores
the reference's graph-level validation (``ir::Graph`` checkers, per-op
``InferShape``, ``PADDLE_ENFORCE``) as a TPU-native battery of structured
checks runnable at any point, especially *between* Analyzer rewrite
passes.

Surfaces:

* ``verify_program(program, targets=...)`` / ``Program.lint()``
* ``analysis.verify_pass`` — registered pass; ``Analyzer`` brackets every
  rewrite with it when enabled (``PADDLE_TPU_VERIFY_PASSES=1``, on in
  tests)
* ``python -m paddle_tpu.tools.lint_program <model_dir>`` — lint a saved
  inference model; exit 1 on ERROR findings
* ``Executor.run(..., verify=True)`` — debug hook
"""

from .diagnostics import Diagnostic, Severity, format_diagnostics
from .defuse import DefUseGraph, build_def_use, sub_block_reads_recursive
from .checks import VerifyContext, all_checks, get_check, register_check
from .verifier import (
    VerifyError,
    assert_valid,
    pass_verification_enabled,
    set_pass_verification,
    verify_program,
)

__all__ = [
    "Diagnostic",
    "Severity",
    "format_diagnostics",
    "DefUseGraph",
    "build_def_use",
    "sub_block_reads_recursive",
    "VerifyContext",
    "all_checks",
    "get_check",
    "register_check",
    "VerifyError",
    "assert_valid",
    "pass_verification_enabled",
    "set_pass_verification",
    "verify_program",
]
