"""Static analysis over the paddle_tpu Program IR: def-use graph,
program verifier, and lint pass framework.

Motivation (ISSUE 1): the Executor lowers a whole Program to one jaxpr, so
a malformed program — dangling read after a bad fuse, dtype drift, a
double write aliasing donated param buffers — surfaces only as an opaque
trace-time JAX error or silently wrong numerics.  This package restores
the reference's graph-level validation (``ir::Graph`` checkers, per-op
``InferShape``, ``PADDLE_ENFORCE``) as a TPU-native battery of structured
checks runnable at any point, especially *between* Analyzer rewrite
passes.

Surfaces:

* ``verify_program(program, targets=...)`` / ``Program.lint()``
* ``analysis.verify_pass`` — registered pass; ``Analyzer`` brackets every
  rewrite with it when enabled (``PADDLE_TPU_VERIFY_PASSES=1``, on in
  tests)
* ``python -m paddle_tpu.tools.lint_program <model_dir>`` — lint a saved
  inference model; exit 1 on ERROR findings
* ``Executor.run(..., verify=True)`` — debug hook

ISSUE 3 grows the substrate into a whole-program distributed static
analyzer: an abstract interpreter over the IR (:mod:`.interp` — shape /
dtype / persistability / sharding lattice), a static cost model
(:mod:`.cost` — FLOPs, bytes, ICI bytes, liveness-based peak memory
against an HBM budget), and a cross-worker collective schedule
extractor + deadlock-freedom proof (:mod:`.distributed`), surfaced as
``Program.analyze()`` (:mod:`.analyze`), four analyzer-backed lint
checks, and ``python -m paddle_tpu.tools.analyze_program``.

ISSUE 10 adds whole-program concurrency analysis (:mod:`.concurrency`):
a happens-before model of the runtime's overlap sources — K in-flight
steps, the prefetch thread, lazy FetchHandles, donated buffers — that
detects in-flight races (``race-inflight-write``,
``donated-buffer-live-read``), proves scope isolation between
co-resident programs (``scope-overlap``), and certifies a hot loop
free of host syncs (``sync-in-hot-loop``), surfaced through
``Program.analyze(concurrency=True)``, the analyze CLI's
``--concurrency`` / ``--certify-zero-sync`` flags, and enforcement
gates in ``run_batches(verify=True)`` and the fusion/planner rewrite
brackets.

ISSUE 16 adds the overlap scheduler (:mod:`.overlap`): bucketed
collectives split into ``c_allreduce_start`` / ``c_allreduce_wait``
pairs scheduled by a liveness pass (start after the bucket's last def,
wait before its first consumer), bracketed by the race and deadlock
provers with per-bucket revert, priced by an overlap-aware window
model in :mod:`.cost` (``exposed_wire_ms`` / ``overlap_fraction``),
and surfaced through the planner's third axis, the
``overlap-opportunity-unexploited`` advisory, and
``analyze_program --overlap``.
"""

from .diagnostics import Diagnostic, Severity, format_diagnostics
from .defuse import DefUseGraph, build_def_use, sub_block_reads_recursive
from .checks import VerifyContext, all_checks, get_check, register_check
from .verifier import (
    VerifyError,
    assert_valid,
    pass_verification_enabled,
    set_pass_verification,
    verify_program,
)
from .interp import (AbstractVal, InterpResult, Sharding,
                     interpret_program, register_transfer)
from .cost import (CostReport, OpCost, PlanPrice, collective_ici_bytes,
                   estimate_cost, hbm_budget, price_plan,
                   price_program, register_flops)
from .distributed import (CollectiveEvent, check_schedule_consistency,
                          extract_collective_schedule,
                          prove_deadlock_free)
from .concurrency import (CONCURRENCY_CHECK_IDS, RACE_CHECK_IDS,
                          ConcurrencyReport, ScopeFootprint,
                          SyncPoint, ZeroSyncCertificate,
                          analyze_concurrency, assert_no_new_races,
                          certify_zero_sync, find_inflight_races,
                          find_overlap_window_races,
                          prove_scope_isolation, race_signatures,
                          resolve_max_in_flight, scope_footprint,
                          strict_sync_enabled, verify_async_hot_path)
from .analyze import AnalysisReport, analyze_program
from .fusion import (FusionConfig, FusionReport, apply_fusion_passes,
                     fusion_enabled, resolve_fused_program,
                     scan_fusible_patterns)
from .overlap import (OverlapDecision, OverlapReport,
                      apply_overlap_pass, overlap_enabled)

__all__ = [
    "Diagnostic",
    "Severity",
    "format_diagnostics",
    "DefUseGraph",
    "build_def_use",
    "sub_block_reads_recursive",
    "VerifyContext",
    "all_checks",
    "get_check",
    "register_check",
    "VerifyError",
    "assert_valid",
    "pass_verification_enabled",
    "set_pass_verification",
    "verify_program",
    "AbstractVal",
    "InterpResult",
    "Sharding",
    "interpret_program",
    "register_transfer",
    "CostReport",
    "OpCost",
    "PlanPrice",
    "collective_ici_bytes",
    "estimate_cost",
    "hbm_budget",
    "price_plan",
    "price_program",
    "register_flops",
    "CollectiveEvent",
    "check_schedule_consistency",
    "extract_collective_schedule",
    "prove_deadlock_free",
    "CONCURRENCY_CHECK_IDS",
    "RACE_CHECK_IDS",
    "ConcurrencyReport",
    "ScopeFootprint",
    "SyncPoint",
    "ZeroSyncCertificate",
    "analyze_concurrency",
    "assert_no_new_races",
    "certify_zero_sync",
    "find_inflight_races",
    "find_overlap_window_races",
    "prove_scope_isolation",
    "race_signatures",
    "resolve_max_in_flight",
    "scope_footprint",
    "strict_sync_enabled",
    "verify_async_hot_path",
    "AnalysisReport",
    "analyze_program",
    "FusionConfig",
    "FusionReport",
    "apply_fusion_passes",
    "fusion_enabled",
    "resolve_fused_program",
    "scan_fusible_patterns",
    "OverlapDecision",
    "OverlapReport",
    "apply_overlap_pass",
    "overlap_enabled",
]
