"""``Program.analyze()`` — the whole-program distributed static
analyzer driver.

One call composes the three analyses this package provides:

* the **abstract interpretation** (:mod:`.interp`) — shape / dtype /
  persistability / sharding per var;
* the **cost model** (:mod:`.cost`) — FLOPs, bytes, ICI bytes, and the
  liveness-based peak-memory estimate against the HBM budget;
* the **collective schedule** (:mod:`.distributed`) — this worker's
  per-ring schedule, and, when the N per-worker programs are supplied,
  the cross-worker deadlock-freedom proof;

plus the lint battery (including the analyzer-backed checks
``peak-memory-over-budget``, ``collective-schedule-divergence``,
``degenerate-sharding`` and ``oversized-replicated-persistable``)
— all folded into one structured :class:`AnalysisReport`.
"""

from .cost import estimate_cost
from .diagnostics import Severity, format_diagnostics
from .distributed import (check_schedule_consistency,
                          extract_collective_schedule)
from .interp import interpret_program

__all__ = ["AnalysisReport", "analyze_program"]


class AnalysisReport:
    """Everything the static analyzer can prove about a program.

    Fields
    ------
    interp:            :class:`~.interp.InterpResult`
    cost:              :class:`~.cost.CostReport`
    schedule:          {ring_id: [CollectiveEvent]} for THIS program
    worker_schedules:  per-worker schedules when ``workers`` was given
    diagnostics:       lint findings (most severe first)
    """

    def __init__(self, program, interp, cost, schedule,
                 worker_schedules, diagnostics, concurrency=None):
        self.program = program
        self.interp = interp
        self.cost = cost
        self.schedule = schedule
        self.worker_schedules = worker_schedules
        self.diagnostics = list(diagnostics)
        #: :class:`~.concurrency.ConcurrencyReport` when the analysis
        #: ran with ``concurrency=True`` (races, scope footprint /
        #: isolation, zero-sync certificate), else None
        self.concurrency = concurrency

    @property
    def errors(self):
        return [d for d in self.diagnostics
                if d.severity >= Severity.ERROR]

    @property
    def ok(self):
        return not self.errors

    @property
    def schedule_consistent(self):
        """True when the cross-worker proof ran and found no divergence
        (None when no worker set was supplied)."""
        if self.worker_schedules is None:
            return None
        return not any(d.check == "collective-schedule-divergence"
                       for d in self.errors)

    def to_dict(self):
        return {
            "ok": self.ok,
            "cost": self.cost.to_dict(),
            "schedule": {
                str(r): [e.to_dict() for e in evs]
                for r, evs in self.schedule.items()},
            "worker_schedules": None if self.worker_schedules is None
            else [
                {str(r): [e.to_dict() for e in evs]
                 for r, evs in s.items()}
                for s in self.worker_schedules],
            "schedule_consistent": self.schedule_consistent,
            "sharding": {
                n: repr(v.sharding)
                for n, v in sorted(self.interp.sharded_vars().items())},
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "concurrency": self.concurrency.to_dict()
            if self.concurrency is not None else None,
        }

    def format(self, top_ops=12):
        """Human report: cost/memory table, schedules, diagnostics."""
        lines = [self.cost.format_table(top=top_ops)]
        if self.schedule:
            lines.append("collective schedule:")
            for ring, evs in sorted(self.schedule.items(),
                                    key=lambda kv: repr(kv[0])):
                lines.append("  ring %r (%d op(s)):" % (ring, len(evs)))
                for e in evs:
                    lines.append(
                        "    block %d op %3d %-16s %s x%s%s"
                        % (e.block_idx, e.op_idx, e.kind, e.dtype,
                           e.numel,
                           " peer=%s" % e.peer
                           if e.peer is not None else ""))
        if self.worker_schedules is not None:
            lines.append(
                "cross-worker schedule (%d workers): %s"
                % (len(self.worker_schedules),
                   "consistent (deadlock-free)"
                   if self.schedule_consistent else "DIVERGENT"))
        if self.concurrency is not None:
            lines.append(self.concurrency.format())
        if self.diagnostics:
            lines.append(format_diagnostics(
                self.diagnostics, header="diagnostics:"))
        else:
            lines.append("diagnostics: none")
        return "\n".join(lines)

    def __repr__(self):
        return "AnalysisReport(ok=%s, flops=%d, peak=%dB, %d diag(s))" % (
            self.ok, self.cost.total_flops,
            self.cost.peak_memory_bytes, len(self.diagnostics))


def analyze_program(program, targets=None, workers=None, nranks=None,
                    batch_size=None, hbm_budget=None, checks=None,
                    exclude=(), concurrency=False, max_in_flight=None,
                    coresident=None, certify_zero_sync=False):
    """Run the full static analyzer over ``program``.

    Parameters
    ----------
    program:    the (transpiled) main program of this worker
    targets:    fetch targets (kept live for peak memory; enables the
                fetch-related lint checks)
    workers:    optional list of ALL per-worker main programs (this one
                included) — enables the cross-worker collective schedule
                proof; ``program`` need not be in the list, worker
                indices follow list order
    nranks:     worker count for the sharding lattice / ICI model
                (default: len(workers) if given, else
                ``program._num_trainers``, else 1)
    batch_size: what ``-1`` dims resolve to (default
                ``PADDLE_TPU_ANALYZE_BATCH`` or 1)
    hbm_budget: peak-memory budget in bytes (default
                ``program._hbm_budget`` / ``PADDLE_TPU_HBM_BUDGET``)
    concurrency: also run the happens-before concurrency analysis
                (:mod:`.concurrency`) — race checks at ``max_in_flight``
                (default 2, the async serving depth), the scope
                footprint, and the report's ``concurrency`` section
    max_in_flight: in-flight depth for the race model (implies
                ``concurrency=True`` when > 1)
    coresident: programs (or ``(label, program)`` pairs) sharing this
                program's Executor scope — runs the ``scope-overlap``
                isolation proof
    certify_zero_sync: emit the zero-sync certificate; any host-sync
                point in the steady-state loop becomes a
                ``sync-in-hot-loop`` ERROR naming the introducing API

    Returns an :class:`AnalysisReport`; raises nothing — gating on
    ``report.errors`` is the caller's choice.
    """
    from .verifier import verify_program

    want_concurrency = bool(concurrency or coresident
                            or certify_zero_sync
                            or (max_in_flight or 0) > 1)
    k = None
    if want_concurrency:
        from .concurrency import resolve_max_in_flight

        k = resolve_max_in_flight(program, explicit=max_in_flight,
                                  default=2)
    if nranks is None and workers:
        nranks = len(workers)
    interp = interpret_program(program, nranks=nranks,
                               batch_size=batch_size)
    cost = estimate_cost(program, interp=interp, targets=targets or (),
                         budget=hbm_budget)
    schedule = extract_collective_schedule(program, interp=interp)

    worker_schedules = None
    if workers:
        worker_schedules = [
            extract_collective_schedule(p, worker=w, nranks=nranks,
                                        batch_size=batch_size)
            for w, p in enumerate(workers)
        ]

    diags = verify_program(program, targets=targets, checks=checks,
                           exclude=exclude, workers=workers,
                           max_in_flight=k, coresident=coresident,
                           certify_zero_sync=certify_zero_sync,
                           _analysis=(interp, cost),
                           _worker_schedules=worker_schedules)

    conc_report = None
    if want_concurrency:
        from .concurrency import (RACE_CHECK_IDS, ConcurrencyReport,
                                  certify_zero_sync as _certify,
                                  scope_footprint)
        from ..observability import runtime as _obs

        races = [d for d in diags if d.check in RACE_CHECK_IDS]
        isolation = [d for d in diags if d.check == "scope-overlap"]
        cert = _certify(program, targets=targets or (),
                        max_in_flight=k) if certify_zero_sync else None
        conc_report = ConcurrencyReport(
            k, races, isolation, footprint=scope_footprint(program),
            certificate=cert)
        _obs.record_concurrency_check(len(races) + len(isolation),
                                      gate="analyze")
    return AnalysisReport(program, interp, cost, schedule,
                          worker_schedules, diags,
                          concurrency=conc_report)
