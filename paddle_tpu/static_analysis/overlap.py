"""Overlap scheduler: liveness-driven collective hoisting (ISSUE 16).

Fluid's ParallelExecutor overlaps gradient allreduce with backward
compute as a graph-level scheduling decision; after the fusion pipeline
our programs still fire every bucketed collective exactly where the
rewrite dropped it, so ICI-bound plans serialize compute behind wire
time.  Latency hiding is a *schedule* property, not a kernel property
(arXiv 2301.13062): this pass splits each bucketed collective
(``c_fused_allreduce_sum`` / ``c_allreduce_quant``) into a
``c_allreduce_start`` / ``c_allreduce_wait`` pair and schedules them
with a liveness pass over the def-use graph —

* the **start** hoists to the earliest point all bucket members are
  fully defined (just after the last def of any member, including
  sub-block closure writes, and never above a reader that expects the
  un-reduced local value);
* the **wait** sinks to just before the first consumer (the optimizer
  ops; sub-block closure reads count), maximizing the in-flight window
  XLA's async scheduler can fill with compute.

Every rewritten program is bracketed by both provers:

* **race proof** — a write to any bucket member between start and wait
  is a ``race-inflight-write`` ERROR
  (:func:`~.concurrency.find_overlap_window_races`, K-independent: the
  ring transfer is in flight *within* one step);
* **deadlock proof** — hoisting must preserve the rank-symmetric
  per-ring start order (the pre-rewrite schedule, with each fused
  collective mapped to its start half, must match position-for-position
  per ring), and the rewritten schedule replicated across ranks must
  pass :func:`~.distributed.check_schedule_consistency`.

A failed proof **reverts that bucket** to the fused synchronous form —
the pass never crashes and never ships an unproven schedule.  The pair
is bit-exact with the fused op by construction (the start performs the
identical reduction; the wait is an identity consumer barrier), so
``PADDLE_TPU_OVERLAP=0`` — which keeps the fused form — restores
today's schedule bit-exactly.

Knob precedence (the ``allreduce_bucket_mb`` idiom): the program's
``_overlap`` mark (how the planner scopes its chosen schedule to ONE
program) → ``PADDLE_TPU_OVERLAP`` → default on.
"""

import os

from .defuse import (resolve_sub_block, sub_block_reads_recursive,
                     sub_block_writes_recursive)

__all__ = [
    "OVERLAPPABLE_OP_TYPES", "overlap_enabled", "OverlapDecision",
    "OverlapReport", "apply_overlap_pass",
]

#: the bucketed synchronous collectives the pass splits into pairs
OVERLAPPABLE_OP_TYPES = ("c_fused_allreduce_sum", "c_allreduce_quant")


def _truthy(val):
    return str(val).strip().lower() not in ("0", "", "false", "off",
                                            "none")


def overlap_enabled(program=None):
    """Is overlap scheduling on for this program?  The program's
    ``_overlap`` mark wins (the planner's in-place apply stamps it so a
    plan scopes its schedule to one program instead of leaking a
    process-global env change), else ``PADDLE_TPU_OVERLAP``, default
    on.  ``PADDLE_TPU_OVERLAP=0`` is the kill switch that restores the
    fused synchronous schedule bit-exactly."""
    mark = getattr(program, "_overlap", None) if program is not None \
        else None
    if mark is not None:
        return _truthy(mark)
    return os.environ.get("PADDLE_TPU_OVERLAP", "1").strip() != "0"


class OverlapDecision:
    """What the pass did with one bucketed collective: the pair's final
    op coordinates when applied, or why the bucket kept its fused
    synchronous form."""

    __slots__ = ("bucket", "op_type", "ring_id", "vars", "fused_idx",
                 "start_idx", "wait_idx", "window_ops", "status",
                 "note", "quant")

    #: status values: ``applied`` (pair scheduled, proofs passed),
    #: ``no-window`` (hoist/sink left zero ops in flight — splitting
    #: buys nothing), ``reverted-race`` / ``reverted-deadlock`` (a
    #: proof failed; the fused form was kept)
    def __init__(self, bucket, op_type, ring_id, vars, fused_idx,
                 start_idx=None, wait_idx=None, window_ops=0,
                 status="applied", note="", quant=False):
        self.bucket = int(bucket)
        self.op_type = op_type
        self.ring_id = ring_id
        self.vars = tuple(vars)
        self.fused_idx = fused_idx      # coordinate of the fused op
        self.start_idx = start_idx      # final coordinate of the start
        self.wait_idx = wait_idx        # final coordinate of the wait
        self.window_ops = int(window_ops)
        self.status = status
        self.note = note
        self.quant = bool(quant)

    def to_dict(self):
        return {"bucket": self.bucket, "op_type": self.op_type,
                "ring_id": self.ring_id, "vars": list(self.vars),
                "fused_idx": self.fused_idx,
                "start_idx": self.start_idx, "wait_idx": self.wait_idx,
                "window_ops": self.window_ops, "status": self.status,
                "note": self.note, "quant": self.quant}

    def __repr__(self):
        if self.status == "applied":
            return ("[overlap] bucket %d (%d vars, ring %r%s): start@%s "
                    "wait@%s, %d ops in flight") % (
                self.bucket, len(self.vars), self.ring_id,
                ", int8" if self.quant else "", self.start_idx,
                self.wait_idx, self.window_ops)
        return "[overlap] bucket %d (%d vars, ring %r): %s%s" % (
            self.bucket, len(self.vars), self.ring_id, self.status,
            " — %s" % self.note if self.note else "")


class OverlapReport:
    """Outcome of one overlap pass over one program."""

    def __init__(self, enabled=True):
        self.enabled = enabled
        self.decisions = []
        self.note = ""

    @property
    def applied(self):
        return [d for d in self.decisions if d.status == "applied"]

    @property
    def reverted(self):
        return [d for d in self.decisions
                if d.status.startswith("reverted")]

    def to_dict(self):
        return {"enabled": self.enabled,
                "decisions": [d.to_dict() for d in self.decisions]}

    def format(self):
        lines = ["overlap report (%d applied, %d kept synchronous; %s)"
                 % (len(self.applied),
                    len(self.decisions) - len(self.applied),
                    "enabled" if self.enabled
                    else "DISABLED (PADDLE_TPU_OVERLAP=0)")]
        for d in self.decisions:
            lines.append("  %r" % d)
        return "\n".join(lines)

    def __repr__(self):
        return self.format()


# ---------------------------------------------------------------------------
# liveness planning
# ---------------------------------------------------------------------------

def _op_writes(program, block, op, members):
    """Member names ``op`` writes — output slots plus sub-block closure
    writes (a while body updating a grad is a write no slot shows)."""
    hit = members.intersection(op.output_arg_names)
    sub = resolve_sub_block(program, op, host_block_idx=block.idx)
    if sub is not None:
        hit = hit | (members
                     & set(sub_block_writes_recursive(program, sub)))
    return hit


def _op_reads(program, block, op, members):
    hit = members.intersection(op.input_arg_names)
    sub = resolve_sub_block(program, op, host_block_idx=block.idx)
    if sub is not None:
        hit = hit | (members
                     & set(sub_block_reads_recursive(program, sub)))
    return hit


def _start_position(program, block, members, fused_idx):
    """Earliest legal insertion index for the start op: just after the
    last def of any member (closure writes included), then pushed below
    any reader of the still-un-reduced value — a reader between the last
    def and the fused site expects the LOCAL gradient, and hoisting the
    reduction above it would hand it the ring sum (a semantics change no
    write-race scan would catch)."""
    pos = 0
    for j in range(fused_idx):
        if _op_writes(program, block, block.ops[j], members):
            pos = j + 1
    for j in range(pos, fused_idx):
        if _op_reads(program, block, block.ops[j], members):
            pos = j + 1
    return pos


def _wait_position(program, block, members, fused_idx):
    """Insertion index for the wait op: just before the first op after
    the fused site that touches any member (the optimizer reads the
    reduced grad; closure reads count; a write would also need the
    reduction settled).  No consumer → the end of the block, so the
    step's final state is the reduced value."""
    for j in range(fused_idx + 1, len(block.ops)):
        op = block.ops[j]
        if _op_reads(program, block, op, members) \
                or _op_writes(program, block, op, members):
            return j
    return len(block.ops)


def _plan(program, targets, exclude):
    """One planning sweep over the global block: a list of
    :class:`OverlapDecision` (bucket ids are the sequence index over
    bucketed collectives in program order — stable across revert
    retries because the block is restored before each sweep), plus the
    rebuild schedule for the applied ones."""
    block = program.global_block()
    decisions = []
    schedule = []   # (decision, fused_idx, start_pos, wait_pos, ops)
    bucket = -1
    for fi, op in enumerate(block.ops):
        if op.type not in OVERLAPPABLE_OP_TYPES:
            continue
        if op.attrs.get("hier_groups"):
            # the cross-slice hop of a hierarchical decomposition: it
            # reuses the allreduce op types but its ring is the DCN
            # group — splitting it into a start/wait pair would drop
            # the group attrs and mis-lower to a full-ring collective
            continue
        bucket += 1
        members = frozenset(op.inputs.get("X", ()))
        quant = op.type == "c_allreduce_quant"
        dec = OverlapDecision(
            bucket, op.type, op.attrs.get("ring_id"),
            sorted(members), fused_idx=(block.idx, fi), quant=quant)
        if bucket in exclude:
            dec.status, dec.note = exclude[bucket]
            decisions.append(dec)
            continue
        start_pos = _start_position(program, block, members, fi)
        wait_pos = _wait_position(program, block, members, fi)
        # window = ops left in flight once the fused op itself is gone
        window = (wait_pos - start_pos) - 1
        if window <= 0:
            dec.status = "no-window"
            dec.note = ("last member def and first consumer are "
                        "adjacent — nothing to hide the wire under")
            decisions.append(dec)
            continue
        dec.window_ops = window
        schedule.append((dec, fi, start_pos, wait_pos,
                         _make_pair(block, op, bucket, members)))
        decisions.append(dec)
    return decisions, schedule


def _make_pair(block, fused_op, bucket, members):
    """Build the start/wait twins of one fused collective.  The start
    carries the whole reduction (quant path included) so the pair is
    bit-exact with the fused op; ``overlap_bucket`` links the twins for
    the cost model, the provers, and the lint pairing checks."""
    from ..framework import Operator

    names = list(fused_op.inputs.get("X", ()))
    base = {"ring_id": fused_op.attrs.get("ring_id"),
            "op_role": "backward", "overlap_bucket": int(bucket)}
    start_attrs = dict(base)
    if fused_op.attrs.get("pre_scale"):
        start_attrs["pre_scale"] = fused_op.attrs["pre_scale"]
    if fused_op.type == "c_allreduce_quant":
        start_attrs["quant"] = True
        if fused_op.attrs.get("quant_block"):
            start_attrs["quant_block"] = fused_op.attrs["quant_block"]
    start = Operator(block, "c_allreduce_start", {"X": names},
                     {"Out": list(names)}, start_attrs)
    wait = Operator(block, "c_allreduce_wait", {"X": names},
                    {"Out": list(names)}, dict(base))
    return start, wait


def _rebuild(block, schedule):
    """Apply the planned splits in one block rebuild: drop each fused
    op, insert its start before the hoist index and its wait before the
    sink index.  Waits emit before starts at a shared index (an earlier
    bucket's window closes before a later bucket's opens there)."""
    starts, waits, removed = {}, {}, set()
    for dec, fi, start_pos, wait_pos, (start, wait) in schedule:
        starts.setdefault(start_pos, []).append(start)
        waits.setdefault(wait_pos, []).append(wait)
        removed.add(fi)
    new_ops = []
    for i in range(len(block.ops) + 1):
        new_ops.extend(waits.get(i, ()))
        new_ops.extend(starts.get(i, ()))
        if i < len(block.ops) and i not in removed:
            new_ops.append(block.ops[i])
    block.ops[:] = new_ops
    block.program._bump_version()


def _stamp_final_coords(block, decisions):
    """Record each decision's final op coordinates in the rewritten
    program: start/wait by their ``overlap_bucket`` attr, kept-fused
    buckets by sequence over the surviving bucketed collectives."""
    by_bucket = {d.bucket: d for d in decisions}
    fused_seq = iter(sorted(
        d.bucket for d in decisions if d.status != "applied"))
    for idx, op in enumerate(block.ops):
        if op.type == "c_allreduce_start":
            d = by_bucket.get(op.attrs.get("overlap_bucket"))
            if d is not None:
                d.start_idx = (block.idx, idx)
        elif op.type == "c_allreduce_wait":
            d = by_bucket.get(op.attrs.get("overlap_bucket"))
            if d is not None:
                d.wait_idx = (block.idx, idx)
        elif op.type in OVERLAPPABLE_OP_TYPES:
            b = next(fused_seq, None)
            if b is not None:
                by_bucket[b].fused_idx = (block.idx, idx)


# ---------------------------------------------------------------------------
# the proof bracket
# ---------------------------------------------------------------------------

def _normalized_ring_order(sched):
    """Per-ring signature sequences with the fused↔start identity
    applied: a fused collective and the start half of its split pair
    are the SAME rendezvous, so mapping both onto the start kind lets
    the pre- and post-rewrite schedules compare position-for-position.
    Wire identity (int8 vs dense dtype, coalesced numel) is preserved
    by the extraction itself."""
    out = {}
    for ring, evs in sched.items():
        sigs = []
        for e in evs:
            kind = e.kind
            if kind in OVERLAPPABLE_OP_TYPES:
                kind = "c_allreduce_start"
            sigs.append((kind, str(e.dtype), e.numel))
        out[ring] = sigs
    return out


def _prove(program, pre_schedule, nranks, decisions):
    """Run both proofs over the rewritten program.  Returns a dict of
    ``bucket -> (status, note)`` for every bucket a proof rejects
    (empty = both proofs PASS)."""
    from .concurrency import find_overlap_window_races
    from .distributed import (check_schedule_consistency,
                              extract_collective_schedule)

    offenders = {}
    applied = [d for d in decisions if d.status == "applied"]

    # ---- race proof: no write to a member inside its window ----
    for diag in find_overlap_window_races(program):
        hit = set(diag.var_names)
        for d in applied:
            if d.bucket in offenders or not hit & set(d.vars):
                continue
            offenders[d.bucket] = (
                "reverted-race",
                "in-flight write: %s" % diag.message.split(":")[0])

    # ---- deadlock proof: rank-symmetric per-ring start order ----
    post_schedule = extract_collective_schedule(program, nranks=nranks)
    pre = _normalized_ring_order(pre_schedule)
    post = _normalized_ring_order(post_schedule)
    bad_rings = {r for r in set(pre) | set(post)
                 if pre.get(r, []) != post.get(r, [])}
    diags = check_schedule_consistency(
        [post_schedule] * max(int(nranks or 2), 2))
    if diags:
        # a replicated-schedule inconsistency implicates every ring the
        # rewrite touched — conservative, and the revert loop converges
        bad_rings.update(d.ring_id for d in applied)
    for d in applied:
        if d.bucket not in offenders and d.ring_id in bad_rings:
            offenders[d.bucket] = (
                "reverted-deadlock",
                "hoist would reorder ring %r collectives across ranks"
                % (d.ring_id,))
    return offenders


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def apply_overlap_pass(program, targets=(), nranks=None):
    """Split + schedule every provable bucket of ``program`` IN PLACE
    (run it on the resolved clone, never the user's program); returns
    the :class:`OverlapReport`, also stamped on the program as
    ``_overlap_report``.

    Revert loop: plan → rebuild → prove; any bucket a proof rejects is
    excluded and the whole rewrite replans from the pristine op list,
    so a reverted bucket's fused op sits at its ORIGINAL position
    (schedule identity with the kill switch, not an approximation).
    Bounded by the bucket count, so it always terminates.
    """
    report = OverlapReport(enabled=overlap_enabled(program))
    program._overlap_report = report
    if not report.enabled:
        return report
    block = program.global_block()
    if not any(op.type in OVERLAPPABLE_OP_TYPES for op in block.ops):
        return report
    if nranks is None:
        nranks = getattr(program, "_num_trainers", None) or 2

    from .distributed import extract_collective_schedule

    try:
        pre_schedule = extract_collective_schedule(program,
                                                   nranks=nranks)
    except Exception as e:  # noqa: BLE001 - never break resolve
        report.decisions = []
        report.note = "schedule extraction failed: %s" % e
        return report

    orig_ops = list(block.ops)
    exclude = {}
    for _ in range(len(orig_ops) + 1):
        block.ops[:] = list(orig_ops)
        program._bump_version()
        decisions, schedule = _plan(program, targets, exclude)
        if not schedule:
            # nothing (left) to split — the block is already pristine
            _stamp_final_coords(block, decisions)
            report.decisions = decisions
            return report
        _rebuild(block, schedule)
        offenders = _prove(program, pre_schedule, nranks, decisions)
        if not offenders:
            _stamp_final_coords(block, decisions)
            report.decisions = decisions
            return report
        exclude.update(offenders)
    # unreachable unless a proof keeps rejecting fresh buckets beyond
    # the bucket count; keep the synchronous schedule rather than crash
    block.ops[:] = orig_ops
    program._bump_version()
    decisions, _ = _plan(program, targets,
                         dict.fromkeys(exclude,
                                       ("reverted-deadlock", "")))
    report.decisions = decisions
    return report
