"""Structured diagnostics for the program verifier.

The reference surfaces graph-level violations as ``PADDLE_ENFORCE`` aborts
deep inside C++ (``paddle/fluid/framework/operator.cc``, ``ir/graph.cc``)
with a stack but no graph coordinates.  Here every finding is a structured
:class:`Diagnostic` — check id, severity, block/op coordinates, the vars
involved and a fix hint — so callers (tests, the Analyzer's verify_pass,
the lint CLI) can filter, format and gate on them uniformly.
"""

import enum

__all__ = ["Severity", "Diagnostic", "format_diagnostics"]


class Severity(enum.IntEnum):
    """Ordered: gating compares with ``>=`` (e.g. fail on ERROR only)."""

    INFO = 1
    WARNING = 2
    ERROR = 3

    def __str__(self):
        return self.name


class Diagnostic:
    """One finding: where in the Program, what rule, how bad, how to fix.

    Fields
    ------
    check:     registered check id (e.g. ``"use-before-def"``)
    severity:  :class:`Severity`
    message:   human-readable statement of the violation
    block_idx: block the finding anchors to (None for program-level)
    op_idx:    position of the op in its block (None for var-level)
    op_type:   op type string, if anchored to an op
    op_id:     the op's ``__op_id__`` attr (stable across clones), if any
    var_names: tuple of var names involved
    hint:      suggested fix, may be empty
    """

    __slots__ = ("check", "severity", "message", "block_idx", "op_idx",
                 "op_type", "op_id", "var_names", "hint")

    def __init__(self, check, severity, message, block_idx=None, op_idx=None,
                 op_type=None, op_id=None, var_names=(), hint=""):
        self.check = check
        self.severity = Severity(severity)
        self.message = message
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.op_id = op_id
        self.var_names = tuple(var_names)
        self.hint = hint

    def _loc(self):
        parts = []
        if self.block_idx is not None:
            parts.append("block %d" % self.block_idx)
        if self.op_idx is not None:
            parts.append("op %d" % self.op_idx)
        if self.op_type:
            parts.append("(%s)" % self.op_type)
        return " ".join(parts)

    def to_dict(self):
        """JSON-ready form (the lint CLI's ``--json`` output)."""
        return {
            "check": self.check,
            "severity": str(self.severity),
            "message": self.message,
            "block_idx": self.block_idx,
            "op_idx": self.op_idx,
            "op_type": self.op_type,
            "op_id": self.op_id,
            "var_names": list(self.var_names),
            "hint": self.hint,
        }

    def __str__(self):
        loc = self._loc()
        s = "[%s] %s: %s" % (self.severity, self.check, self.message)
        if loc:
            s += " @ " + loc
        if self.hint:
            s += "\n    hint: " + self.hint
        return s

    __repr__ = __str__


def format_diagnostics(diags, header=None):
    """Multi-line report, most severe first (stable within a severity)."""
    lines = []
    if header:
        lines.append(header)
    for d in sorted(diags, key=lambda d: -int(d.severity)):
        lines.append(str(d))
    return "\n".join(lines)
