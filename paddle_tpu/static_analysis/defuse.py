"""Def-use graph over ``Program ⊃ Block ⊃ Operator``.

The reference gets this structure for free from ``ir::Graph`` (every var is
a node wired producer→consumer, ``paddle/fluid/framework/ir/graph.cc``); our
IR keeps ops as flat per-block lists with string-named slots, so the checks
need an explicit walk.  The walker descends into ``attrs["sub_block"]``
bodies (``while`` / ``conditional_block`` / ``recurrent`` /
``recompute_block``) in program order, threading the set of names defined so
far — a use inside a loop body of a var defined in the parent *after* the
loop op is still a use-before-def.

Grad twins (``while_grad`` …) share the forward's ``sub_block`` attr but
re-run it via ``jax.vjp`` with their own declared inputs, so the walk does
NOT descend into them a second time.
"""

__all__ = ["VarSite", "DefUseGraph", "build_def_use",
           "sub_block_reads_recursive", "sub_block_writes_recursive",
           "resolve_sub_block", "SUB_BLOCK_DESCENT_OPS"]

# forward control-flow ops whose sub-block the walker descends into
SUB_BLOCK_DESCENT_OPS = ("while", "conditional_block", "recurrent",
                         "recompute_block")

from ..ops.registry import EMPTY_VAR_NAME


class VarSite:
    """One def or use of a var name: (block_idx, op_idx, op)."""

    __slots__ = ("block_idx", "op_idx", "op")

    def __init__(self, block_idx, op_idx, op):
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op = op

    def __repr__(self):
        return "VarSite(block=%d, op=%d, %s)" % (
            self.block_idx, self.op_idx, self.op.type)


def resolve_sub_block(program, op, host_block_idx=None):
    """The single policy for following an op's ``attrs["sub_block"]``:
    returns the sub-Block, or None when the attr is absent, non-int,
    out of range, or self-referential (malformed programs — the
    verifier's sub-block-index check reports those; walkers must
    degrade, not crash).  Callers layer their own descent-op filters
    and visited sets on top."""
    idx = op.attrs.get("sub_block")
    if not isinstance(idx, int) or not 0 <= idx < program.num_blocks:
        return None
    if host_block_idx is not None and idx == host_block_idx:
        return None
    return program.block(idx)


def _machinery_defined_names(op):
    """Names a control-flow op's runtime machinery binds inside its
    sub-block before any sub-block op runs (they have no producing op):
    the recurrent op's per-step input/state slices."""
    if op.type == "recurrent":
        return (list(op.attrs.get("step_input_names", []))
                + list(op.attrs.get("state_names", [])))
    return []


def sub_block_reads_recursive(program, sub_block, exclude=(), _visited=None):
    """All names a sub-block reads before writing, including reads of
    nested sub-blocks (``cf_ops.sub_block_external_reads`` is one level;
    a conditional_block nested in a while body also captures closure
    vars that never appear on any op's input slots).  ``_visited`` guards
    against sub_block-attr cycles in malformed programs — a cycle here
    must degrade to partial reads, not a RecursionError (the verifier's
    sub-block-index check reports the cycle itself)."""
    from ..ops import control_flow as cf_ops

    if _visited is None:
        _visited = set()
    if sub_block.idx in _visited:
        return []
    _visited.add(sub_block.idx)
    reads = list(cf_ops.sub_block_external_reads(sub_block, exclude))
    written = set(exclude)
    for op in sub_block.ops:
        if op.type in SUB_BLOCK_DESCENT_OPS:
            inner = resolve_sub_block(program, op)
            if inner is not None and inner.idx not in _visited:
                inner_exclude = set(_machinery_defined_names(op))
                for n in sub_block_reads_recursive(program, inner,
                                                   inner_exclude, _visited):
                    if n not in written and n not in reads:
                        reads.append(n)
        written.update(op.output_arg_names)
    return reads


def sub_block_writes_recursive(program, sub_block, _visited=None):
    """All names a sub-block writes, including writes of nested
    sub-blocks — the closure-write twin of
    :func:`sub_block_reads_recursive` (the overlap scheduler's liveness
    pass needs the last DEF of a bucket member, and a while body
    updating a grad is a def no output slot of the host op shows).
    Same cycle guard: a malformed sub_block-attr cycle degrades to
    partial writes instead of a RecursionError."""
    if _visited is None:
        _visited = set()
    if sub_block.idx in _visited:
        return set()
    _visited.add(sub_block.idx)
    writes = set()
    for op in sub_block.ops:
        writes.update(n for n in op.output_arg_names
                      if n and n != EMPTY_VAR_NAME)
        if op.type in SUB_BLOCK_DESCENT_OPS:
            inner = resolve_sub_block(program, op)
            if inner is not None:
                writes |= sub_block_writes_recursive(program, inner,
                                                     _visited)
    return writes


class DefUseGraph:
    """Def/use sites per var name, in program (execution) order.

    ``defs[name]`` / ``uses[name]``: ordered lists of :class:`VarSite`.
    ``order``: flat list of (block_idx, op_idx, op) in walk order.
    ``machinery_defined``: names bound by control-flow machinery rather
    than a producing op (recurrent step inputs / states).
    ``walked_blocks``: block indices the walker visited — blocks NOT in
    this set are orphaned (no surviving control-flow op references them).
    """

    def __init__(self, program):
        self.program = program
        self.defs = {}
        self.uses = {}
        self.order = []
        self.machinery_defined = set()
        self.walked_blocks = set()
        self._walk(program.global_block())

    def _note(self, table, name, site):
        if not name or name == EMPTY_VAR_NAME:
            return
        table.setdefault(name, []).append(site)

    def _walk(self, block):
        if block.idx in self.walked_blocks:
            return  # defensive: a sub_block attr cycle must not recurse
        self.walked_blocks.add(block.idx)
        for op_idx, op in enumerate(block.ops):
            site = VarSite(block.idx, op_idx, op)
            self.order.append((block.idx, op_idx, op))
            for n in op.input_arg_names:
                self._note(self.uses, n, site)
            if op.type in SUB_BLOCK_DESCENT_OPS:
                inner = resolve_sub_block(self.program, op)
                if inner is not None:
                    self.machinery_defined.update(_machinery_defined_names(op))
                    self._walk(inner)
            for n in op.output_arg_names:
                self._note(self.defs, n, site)

    # ---- queries ----
    def producers(self, name):
        return list(self.defs.get(name, []))

    def consumers(self, name):
        return list(self.uses.get(name, []))

    def is_produced(self, name):
        return name in self.defs or name in self.machinery_defined


def build_def_use(program):
    return DefUseGraph(program)
