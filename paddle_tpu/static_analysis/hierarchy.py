"""Hierarchical collective decomposition (the multi-tier rewrite of the
flat data-parallel allreduce; arXiv 2110.10548's slice/pod hierarchy).

A flat ``c_allreduce_sum`` / ``c_fused_allreduce_sum`` /
``c_allreduce_quant`` whose ring spans slices moves the FULL bucket over
the slow DCN tier: ring volume ``2B(n-1)/n`` priced at DCN bandwidth.
The hierarchical form decomposes it into

    reduce-scatter within the slice   (ring 5, ICI, payload B)
    allreduce across slices           (ring 6, DCN, payload B/c)
    allgather back within the slice   (ring 5, ICI, payload B)

so only ``2*(B/c)*(s-1)/s`` bytes cross the slow tier — a ~c× cut, and
the hop where the PR-15 int8 wire format pays most (EQuARX,
arXiv 2506.17615): a quantized bucket keeps its int8 exchange on the
cross-slice hop while the intra-slice hops stay dense.

Like the overlap scheduler this is a *proved* rewrite: every emitted
schedule is re-checked by the deadlock prover (schedule extraction +
:func:`check_schedule_consistency` + payload conservation per bucket)
and the race prover (:func:`race_signatures` delta), and any offending
bucket reverts to its flat form — ``PADDLE_TPU_HIERARCHY=0`` (or a
topology-free ClusterSpec) keeps the flat schedule bit-exactly.

Ring-id conventions (established in ``parallel/``): 0=dp, 1=pipe,
2=moe, 3=ulysses, 4=ring-attention — the hierarchy claims 5 (intra-
slice) and 6 (cross-slice).
"""

import os

from ..framework import Operator
from .concurrency import race_signatures
from .distributed import extract_collective_schedule, \
    check_schedule_consistency

__all__ = [
    "HIER_SLICE_RING", "HIER_CROSS_RING", "HIER_OP_TYPES",
    "HierarchyDecision", "HierarchyReport", "hierarchy_enabled",
    "hierarchy_topology", "hierarchy_min_bytes", "hierarchy_signature",
    "apply_hierarchy_pass",
]

HIER_SLICE_RING = 5   # intra-slice hops (reduce-scatter / allgather, ICI)
HIER_CROSS_RING = 6   # cross-slice hop (allreduce, DCN)

# flat forms the rewrite decomposes (ring 0 / data-parallel only)
HIER_OP_TYPES = ("c_allreduce_sum", "c_fused_allreduce_sum",
                 "c_allreduce_quant")


def _truthy(v):
    return str(v).strip().lower() not in ("", "0", "false", "no", "off")


def hierarchy_enabled(program=None):
    """Kill-switch resolution: program mark ``_hierarchy`` wins (False
    disables; a dict or True enables), else ``PADDLE_TPU_HIERARCHY``
    (default on — but the pass is still inert without a topology)."""
    mark = getattr(program, "_hierarchy", None) if program is not None \
        else None
    if mark is not None:
        return bool(mark)
    return _truthy(os.environ.get("PADDLE_TPU_HIERARCHY", "1"))


def hierarchy_topology(program=None, nranks=None, spec=None):
    """Resolve chips-per-slice ``c`` for the rewrite, or None when no
    topology is known.  Precedence: explicit ``spec`` arg > the
    ``_hierarchy`` mark's dict > the ``_cluster_spec`` mark >
    ``PADDLE_TPU_CLUSTER_SPEC`` — mirroring the quant/bucket mark
    precedence the planner stamps."""
    mark = getattr(program, "_hierarchy", None) if program is not None \
        else None
    if isinstance(mark, dict):
        c = mark.get("chips_per_slice")
        if c:
            return int(c)
        slices = int(mark.get("slices") or 0)
        if slices > 1 and nranks and nranks % slices == 0:
            return nranks // slices
    from ..parallel.planner import ClusterSpec

    if spec is None:
        raw = getattr(program, "_cluster_spec", None) \
            if program is not None else None
        if raw is None:
            raw = os.environ.get("PADDLE_TPU_CLUSTER_SPEC") or None
        if raw is None:
            return None
        try:
            spec = ClusterSpec.coerce(raw)
        except (ValueError, TypeError):
            return None
    if not getattr(spec, "has_topology", False):
        return None
    return int(spec.chips_per_slice)


def hierarchy_min_bytes(program=None):
    """Bucket-size floor: below it the DCN saving can't beat the two
    extra launches.  Mark dict ``min_bytes`` > env > 0."""
    mark = getattr(program, "_hierarchy", None) if program is not None \
        else None
    if isinstance(mark, dict) and mark.get("min_bytes") is not None:
        return int(mark["min_bytes"])
    try:
        return int(os.environ.get("PADDLE_TPU_HIERARCHY_MIN_BYTES", "0"))
    except ValueError:
        return 0


def hierarchy_signature(program=None):
    """Hashable identity of every knob the pass reads — folded into
    ``FusionConfig.signature`` so stamping a topology (or a
    ``PADDLE_TPU_CLUSTER_SPEC`` change) after a resolve invalidates the
    cached fused clone, exactly like the quant/overlap signature
    fixes."""
    mark = getattr(program, "_hierarchy", None) if program is not None \
        else None
    spec = getattr(program, "_cluster_spec", None) \
        if program is not None else None
    if spec is None:
        spec = os.environ.get("PADDLE_TPU_CLUSTER_SPEC") or None
    return (hierarchy_enabled(program), repr(mark), repr(spec),
            hierarchy_min_bytes(program))


_DTYPE_BYTES = {"float32": 4, "float64": 8, "float16": 2, "bfloat16": 2,
                "int32": 4, "int64": 8, "int8": 1, "uint8": 1, "bool": 1}


def _var_numel(block, name):
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return None
    n = 1
    for d in v.shape:
        if d is None or int(d) < 0:
            return None  # dynamic dim: not statically decomposable
        n *= int(d)
    return n


class HierarchyDecision:
    """One flat collective's verdict.  ``status``: applied / skipped /
    reverted-race / reverted-deadlock, with ``note`` carrying the
    reason (mirrors the overlap scheduler's decision discipline)."""

    __slots__ = ("bucket", "op_type", "ring_id", "vars", "op_idx",
                 "chips", "slices", "numel", "quant", "status", "note")

    def __init__(self, bucket, op_type, ring_id, vars, op_idx, chips=0,
                 slices=0, numel=0, quant=False, status="skipped",
                 note=""):
        self.bucket = bucket
        self.op_type = op_type
        self.ring_id = ring_id
        self.vars = tuple(vars)
        self.op_idx = op_idx
        self.chips = chips
        self.slices = slices
        self.numel = numel
        self.quant = quant
        self.status = status
        self.note = note

    def to_dict(self):
        return {
            "bucket": self.bucket, "op_type": self.op_type,
            "ring_id": self.ring_id, "vars": list(self.vars),
            "op_idx": self.op_idx, "chips": self.chips,
            "slices": self.slices, "numel": self.numel,
            "quant": self.quant, "status": self.status,
            "note": self.note,
        }

    def __repr__(self):
        return "HierarchyDecision(bucket=%d %s ring=%r %s%s)" % (
            self.bucket, self.op_type, self.ring_id, self.status,
            ": %s" % self.note if self.note else "")


class HierarchyReport:
    """Stamped on the resolved program as ``_hierarchy_report`` —
    the auditable record of what decomposed, what didn't, and why."""

    __slots__ = ("enabled", "chips_per_slice", "slices", "decisions",
                 "note")

    def __init__(self, enabled, chips_per_slice=0, slices=0,
                 decisions=None, note=""):
        self.enabled = enabled
        self.chips_per_slice = chips_per_slice
        self.slices = slices
        self.decisions = list(decisions or ())
        self.note = note

    @property
    def applied(self):
        return [d for d in self.decisions if d.status == "applied"]

    @property
    def reverted(self):
        return [d for d in self.decisions
                if d.status.startswith("reverted")]

    def to_dict(self):
        return {
            "enabled": self.enabled,
            "chips_per_slice": self.chips_per_slice,
            "slices": self.slices,
            "note": self.note,
            "decisions": [d.to_dict() for d in self.decisions],
        }

    def format(self):
        lines = ["hierarchy: enabled=%s chips_per_slice=%d slices=%d%s"
                 % (self.enabled, self.chips_per_slice, self.slices,
                    " (%s)" % self.note if self.note else "")]
        for d in self.decisions:
            lines.append(
                "  bucket %d %s x%d [%d vars] -> %s%s"
                % (d.bucket, d.op_type, d.numel, len(d.vars), d.status,
                   ": %s" % d.note if d.note else ""))
        return "\n".join(lines)


def _plan(program, c, nranks, min_bytes, exclude):
    """Decide per flat collective.  Returns (decisions, schedule) where
    schedule = [(op_idx, op, members, total_numel, decision)]."""
    block = program.global_block()
    decisions = []
    schedule = []
    bucket = 0
    s = nranks // c
    for idx, op in enumerate(block.ops):
        if op.type not in HIER_OP_TYPES:
            continue
        if op.attrs.get("hier_groups"):
            continue  # already a decomposition product
        members = list(op.inputs.get("X", ()))
        d = HierarchyDecision(
            bucket, op.type, op.attrs.get("ring_id"), members, idx,
            chips=c, slices=s, quant=(op.type == "c_allreduce_quant"))
        bucket += 1
        decisions.append(d)
        if op.attrs.get("ring_id", 0) not in (0, None):
            d.note = "ring %r is not the data-parallel ring" \
                % op.attrs.get("ring_id")
            continue
        if not members or \
                set(members) != set(op.outputs.get("Out", ())):
            d.note = "not an in-place allreduce"
            continue
        key = frozenset(members)
        if key in exclude:
            d.status, d.note = exclude[key]
            continue
        numels = [_var_numel(block, n) for n in members]
        if any(n is None for n in numels):
            d.note = "non-static member shape"
            continue
        total = sum(numels)
        d.numel = total
        v0 = block._find_var_recursive(members[0])
        nbytes = total * _DTYPE_BYTES.get(str(v0.dtype), 4)
        if nbytes < min_bytes:
            d.note = "below min_bytes (%d < %d)" % (nbytes, min_bytes)
            continue
        schedule.append((idx, op, members, total, d))
    return decisions, schedule


def _decompose(block, op, members, total, k, c, s):
    """The three replacement ops for bucket ``k``: RS (ring 5) ->
    cross allreduce (ring 6) -> AG (ring 5).  The chunk buffer is
    padded to a multiple of ``c`` so the tiled reduce-scatter splits
    evenly; the allgather trims the pad back."""
    v0 = block._find_var_recursive(members[0])
    chunk_len = -(-total // c)          # ceil
    chunk_name = "hier_chunk_%d" % k
    block.create_var(name=chunk_name, shape=[chunk_len], dtype=v0.dtype,
                     persistable=False)
    quant = op.type == "c_allreduce_quant"
    role = op.attrs.get("op_role", "backward")
    member_shapes = [list(block._find_var_recursive(n).shape)
                     for n in members]
    common = {"hier_bucket": k, "hier_chips": c, "hier_slices": s,
              "op_role": role}
    rs_attrs = dict(common, ring_id=HIER_SLICE_RING, comm_nranks=c,
                    tier="ici", hier_groups="slice", hier_total=total)
    if op.attrs.get("pre_scale"):
        rs_attrs["pre_scale"] = op.attrs["pre_scale"]
    rs = Operator(block, "c_hier_reducescatter", {"X": members},
                  {"Out": [chunk_name]}, rs_attrs)
    cross_attrs = dict(common, ring_id=HIER_CROSS_RING, comm_nranks=s,
                       tier="dcn", hier_groups="cross")
    if quant and op.attrs.get("quant_block"):
        cross_attrs["quant_block"] = op.attrs["quant_block"]
    cross = Operator(
        block, "c_allreduce_quant" if quant else "c_allreduce_sum",
        {"X": [chunk_name]}, {"Out": [chunk_name]}, cross_attrs)
    ag_attrs = dict(common, ring_id=HIER_SLICE_RING, comm_nranks=c,
                    tier="ici", hier_groups="slice", hier_total=total,
                    member_shapes=member_shapes)
    ag = Operator(block, "c_hier_allgather", {"X": [chunk_name]},
                  {"Out": members}, ag_attrs)
    return [rs, cross, ag]


def _rebuild(block, schedule, c, s):
    """Whole-block rebuild: each planned flat op is replaced in place
    by its three-op decomposition (schedule order preserved — the
    rewrite never reorders relative to compute or other collectives)."""
    planned = {idx: (op, members, total, d)
               for idx, op, members, total, d in schedule}
    new_ops = []
    for idx, op in enumerate(block.ops):
        hit = planned.get(idx)
        if hit is None:
            new_ops.append(op)
            continue
        _, members, total, d = hit
        d.op_idx = len(new_ops)
        new_ops.extend(_decompose(block, op, members, total, d.bucket,
                                  c, s))
        d.status = "applied"
        d.note = ""
    block.ops[:] = new_ops
    block.program._bump_version()


def _prove(program, nranks, c, schedule, baseline_races):
    """Re-prove the rewritten program; returns {member-frozenset:
    (status, note)} offenders (empty = proven).

    Race prover (PR 10): :func:`race_signatures` delta vs the flat
    baseline — any NEW race introduced by a bucket's chunk buffer or
    members reverts that bucket.  Deadlock prover (PR 3): extract the
    schedule, replicate across ``nranks`` symmetric workers, and run
    :func:`check_schedule_consistency` (per-ring sequences + rendezvous
    simulation over rings 0/5/6); plus per-bucket payload conservation
    — the RS and AG must move the full bucket on ring 5 and the cross
    hop exactly ceil(total/c) elements on ring 6."""
    offenders = {}
    by_bucket = {d.bucket: (frozenset(members), total, d)
                 for _, _, members, total, d in schedule}

    def _blame(var_names, status, note):
        hit = False
        for key, total, d in by_bucket.values():
            chunk = "hier_chunk_%d" % d.bucket
            if any(v and (v in key or chunk in v) for v in var_names):
                offenders[key] = (status, note)
                hit = True
        if not hit:  # unattributable: revert everything this round
            for key, total, d in by_bucket.values():
                offenders[key] = (status, note)

    new_races = race_signatures(program) - baseline_races
    for check, var_names in sorted(new_races):
        _blame(var_names, "reverted-race",
               "new race (%s) on %s" % (check, ",".join(var_names)))
    if offenders:
        return offenders

    post = extract_collective_schedule(program, nranks=nranks)
    diags = check_schedule_consistency([post] * max(nranks, 2))
    for dg in diags:
        _blame(dg.var_names, "reverted-deadlock", dg.message)
    if offenders:
        return offenders

    # payload conservation per bucket across the three hops
    slice_evs = {}
    cross_evs = {}
    for ev in post.get(HIER_SLICE_RING, ()):
        slice_evs.setdefault(ev.kind, []).append(ev)
    for ev in post.get(HIER_CROSS_RING, ()):
        cross_evs.setdefault(ev.kind, []).append(ev)
    n_applied = len(by_bucket)
    rs_n = len(slice_evs.get("c_hier_reducescatter", ()))
    ag_n = len(slice_evs.get("c_hier_allgather", ()))
    cr_n = sum(len(v) for v in cross_evs.values())
    if (rs_n, ag_n, cr_n) != (n_applied, n_applied, n_applied):
        _blame((), "reverted-deadlock",
               "decomposition dropped a hop: %d buckets -> %d RS, "
               "%d cross, %d AG" % (n_applied, rs_n, cr_n, ag_n))
        return offenders
    totals = sorted(t for _, t, _ in by_bucket.values())
    chunks = sorted(-(-t // c) for t in totals)
    if sorted(e.numel for e in slice_evs.get(
            "c_hier_reducescatter", ())) != totals \
            or sorted(e.numel for e in slice_evs.get(
                "c_hier_allgather", ())) != totals \
            or sorted(e.numel for v in cross_evs.values()
                      for e in v) != chunks:
        _blame((), "reverted-deadlock",
               "payload not conserved across the RS/cross/AG hops")
    return offenders


def apply_hierarchy_pass(program, targets=(), nranks=None, spec=None):
    """Decompose spanning flat collectives, prove, revert offenders.

    Bounded revert loop exactly like the overlap scheduler's: restore
    the flat ops, re-plan with the offending buckets excluded, rebuild,
    re-prove — each iteration excludes at least one bucket, so it
    terminates.  Stamps ``program._hierarchy_report``; returns True
    when at least one bucket decomposed."""
    enabled = hierarchy_enabled(program)
    report = HierarchyReport(enabled)
    program._hierarchy_report = report
    if not enabled:
        report.note = "disabled"
        return False
    nranks = int(nranks or getattr(program, "_num_trainers", 0) or 0)
    if nranks < 2:
        report.note = "single worker"
        return False
    c = hierarchy_topology(program, nranks=nranks, spec=spec)
    if not c:
        report.note = "no topology in ClusterSpec"
        return False
    if nranks <= c:
        report.note = "ring fits inside one slice (%d <= %d)" \
            % (nranks, c)
        return False
    if nranks % c:
        report.note = "asymmetric topology: nranks=%d not divisible " \
            "by chips_per_slice=%d" % (nranks, c)
        return False
    report.chips_per_slice = c
    report.slices = nranks // c
    min_bytes = hierarchy_min_bytes(program)
    block = program.global_block()
    orig_ops = list(block.ops)
    baseline_races = race_signatures(program)
    exclude = {}
    for _ in range(len(orig_ops) + 1):
        block.ops[:] = list(orig_ops)
        program._bump_version()
        decisions, schedule = _plan(program, c, nranks, min_bytes,
                                    exclude)
        report.decisions = decisions
        if not schedule:
            return False
        _rebuild(block, schedule, c, report.slices)
        offenders = _prove(program, nranks, c, schedule,
                           baseline_races)
        if not offenders:
            return True
        exclude.update(offenders)
    block.ops[:] = list(orig_ops)  # unreachable safety net
    program._bump_version()
    return False
