"""Program verifier: run the check battery over a Program.

Entry points:

* ``verify_program(program, targets=...)`` → list of Diagnostics
* ``assert_valid(program, ...)`` → raises :class:`VerifyError` on ERRORs
* ``Program.lint()`` (framework.py) delegates here
* ``analysis.verify_pass`` wraps the Analyzer pipeline with it
* ``Executor.run(..., verify=True)`` runs it before lowering

The reference's equivalent is scattered: per-op ``InferShape`` +
``PADDLE_ENFORCE`` at build, ``ir::Graph`` sanity in each pass.  Here the
whole battery is one function over the finished Program, runnable at any
point — crucially *between* rewrite passes, where TVM/XLA-style fusion
pipelines introduce exactly the dangling-edge bugs these checks catch.
"""

import os

from .checks import VerifyContext, all_checks
from .defuse import DefUseGraph
from .diagnostics import Severity, format_diagnostics

__all__ = ["verify_program", "assert_valid", "VerifyError",
           "pass_verification_enabled", "set_pass_verification"]


class VerifyError(RuntimeError):
    """Raised when a program fails verification; carries the structured
    diagnostics (``.diagnostics``) in addition to the formatted text."""

    def __init__(self, message, diagnostics=()):
        super().__init__(message)
        self.diagnostics = list(diagnostics)


def verify_program(program, targets=None, checks=None, exclude=(),
                   workers=None, max_in_flight=None, coresident=None,
                   certify_zero_sync=False, _analysis=None,
                   _worker_schedules=None):
    """Run lint/verifier checks over ``program``.

    Parameters
    ----------
    program:  framework.Program
    targets:  optional fetch-target names (Variables or strings); enables
              the orphaned-fetch check and informs unreferenced-op
    checks:   optional iterable of check ids to run (default: all)
    exclude:  check ids to skip
    workers:  optional list of ALL per-worker main programs — enables
              the cross-worker ``collective-schedule-divergence`` check
              (worker indices follow list order)
    max_in_flight: in-flight step depth the concurrency race checks
              assume (default: the program's ``_max_in_flight`` mark /
              ``PADDLE_TPU_MAX_IN_FLIGHT``, else 1 — sequential, races
              vacuously impossible)
    coresident: optional programs (or ``(label, program)`` pairs) that
              share this program's Executor scope — enables the
              ``scope-overlap`` isolation proof
    certify_zero_sync: run the ``sync-in-hot-loop`` certificate check
              even without strict-sync mode
    _analysis: internal — a precomputed (InterpResult, CostReport) pair
              from ``Program.analyze`` so the analyzer-backed checks
              don't recompute it
    _worker_schedules: internal — precomputed per-worker schedules from
              ``Program.analyze`` so the divergence check doesn't
              re-interpret every worker program

    Returns the list of Diagnostics, deduped and in a total order that
    is stable across passes and runs: most-severe-first, then (block,
    op) coordinates, then check id and message.
    """
    from ..framework import Variable

    target_names = [
        t.name if isinstance(t, Variable) else str(t)
        for t in (targets or ())
    ]
    graph = DefUseGraph(program)
    ctx = VerifyContext(program, graph, targets=target_names,
                        workers=workers, analysis=_analysis,
                        worker_schedules=_worker_schedules,
                        max_in_flight=max_in_flight,
                        coresident=coresident,
                        certify_zero_sync=certify_zero_sync)
    registry = all_checks()
    if checks is not None:
        unknown = [c for c in checks if c not in registry]
        if unknown:
            raise KeyError("unknown check ids %s (have %s)"
                           % (unknown, sorted(registry)))
        registry = {k: registry[k] for k in checks}
    diags = []
    seen = set()
    for check_id, fn in registry.items():
        if check_id in exclude:
            continue
        for d in fn(ctx):
            # identical findings can arrive twice (e.g. a check run by
            # both lint() and an analyze() battery feeding one report);
            # CI diffs depend on each appearing once
            key = (d.check, int(d.severity), d.message, d.block_idx,
                   d.op_idx, d.op_type, tuple(d.var_names), d.hint)
            if key in seen:
                continue
            seen.add(key)
            diags.append(d)
    diags.sort(key=lambda d: (-int(d.severity),
                              d.block_idx if d.block_idx is not None else -1,
                              d.op_idx if d.op_idx is not None else -1,
                              d.check, d.message))
    return diags


def assert_valid(program, targets=None, min_severity=Severity.ERROR,
                 header=None, **kw):
    """verify_program + raise VerifyError if any finding reaches
    ``min_severity``.  Returns all diagnostics (incl. advisories) when
    the program is acceptable."""
    diags = verify_program(program, targets=targets, **kw)
    bad = [d for d in diags if d.severity >= min_severity]
    if bad:
        raise VerifyError(
            format_diagnostics(
                bad, header=header or "program failed verification:"),
            diagnostics=bad)
    return diags


# ---------------------------------------------------------------------------
# pass-pipeline gating flag (analysis.Analyzer reads this)
# ---------------------------------------------------------------------------

_PASS_VERIFY_OVERRIDE = None  # None → env var decides


def pass_verification_enabled():
    """Should Analyzer wrap each rewrite pass with verification?  Off by
    default in production (it re-traces every op's lowering); tests turn
    it on via ``PADDLE_TPU_VERIFY_PASSES=1`` (tests/conftest.py) or
    :func:`set_pass_verification`."""
    if _PASS_VERIFY_OVERRIDE is not None:
        return _PASS_VERIFY_OVERRIDE
    val = os.environ.get("PADDLE_TPU_VERIFY_PASSES", "0")
    return val.strip().lower() not in ("0", "", "false", "off")


def set_pass_verification(flag):
    """Force pass verification on/off (None → defer to the env var
    again).  Returns the previous override."""
    global _PASS_VERIFY_OVERRIDE
    old = _PASS_VERIFY_OVERRIDE
    _PASS_VERIFY_OVERRIDE = flag
    return old
