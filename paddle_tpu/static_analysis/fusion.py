"""Cost-guided Program-IR fusion pass pipeline — the TPU-native
realization of Fluid's ``BuildStrategy.fuse_*`` graph passes
(``fuse_elewise_add_act_pass``, ``framework/ir/fuse_optimizer_ops_pass``,
``fuse_all_reduce_op_pass``) plus the attention/softmax-xent fusions the
reference keeps as hand-written ``operators/fused/`` kernels.

XLA fuses instruction-level chains on its own, but it demonstrably
leaves two classes of rewrite on the table (Operator Fusion in XLA,
arXiv:2301.13062): *algorithmic* fusions that change the memory-access
schedule (FlashAttention's blocked online softmax, the one-pass
dropout+residual+layer_norm kernel) and *collective* coalescing
(bucketed gradient allreduce, EQuARX arXiv:2506.17615).  This module
pattern-matches those subgraphs on the Program IR via the PR-1 def-use
machinery and rewrites them in place — each family gated by the PR-3
cost model so a rewrite only fires when the predicted FLOP/byte or ICI
schedule improves:

========================  ==================================================
family                    rewrite
========================  ==================================================
``attention``             matmul(QKᵀ·α) → (+bias) → softmax → (dropout) →
                          matmul(·V) ⇒ one ``fused_multihead_attention``
                          (the Pallas flash kernel on TPU); gated on the
                          measured flash engagement threshold
                          (``PADDLE_TPU_FLASH_MIN_T`` — below it XLA's
                          unblocked attention wins, r05 sweep)
``dropout_add_ln``        (dropout) → elementwise_add → layer_norm ⇒ one
                          ``fused_dropout_add_ln`` (one VMEM pass instead
                          of three HBM round trips)
``bias_act``              elementwise_add(·, 1-D bias) → activation ⇒
                          ``fused_bias_act`` (Fluid's
                          fuse_elewise_add_act_pass; program-level parity,
                          bit-exact composite)
``softmax_xent``          softmax → cross_entropy ⇒ one numerically-stable
                          ``softmax_with_cross_entropy`` (logsumexp form;
                          loss differs from the eps-guarded unfused pair
                          by ~1e-6 relative — documented, not bit-exact)
``conv_bn_act``           conv2d → batch_norm → (act) ⇒ one
                          ``fused_conv_bn_act`` (XLA keeps the MXU conv
                          schedule; the BN+act epilogue is one Pallas
                          VMEM pass — the ResNet-50 MFU 0.250-vs-0.381
                          gap); gated by predicted HBM savings x the
                          autotune calibration factor
``embedding_gather``      ``lookup_table``/``embedding`` on a device-
                          resident table ⇒ ``fused_embedding_gather``
                          (Pallas scalar-prefetch row-DMA gather;
                          scatter-add backward) — value-preserving
                          kernel dispatch, gated on lane alignment +
                          slab size x calibration
``optimizer``             N per-param ``adam``/``sgd`` ops ⇒ one
                          ``fused_adam``/``fused_sgd`` multi-tensor update
                          per (hyperparams, lr, dtype) group — gated by a
                          flat-stream traffic model (the r04 hardware A/B:
                          concat+split costs ~3x the update's own bytes,
                          so BERT-scale groups are *rejected* while
                          many-small-param models fuse)
``allreduce``             per-grad ``c_allreduce_sum`` ⇒ size-capped
                          ``c_fused_allreduce_sum`` buckets
                          (``PADDLE_TPU_ALLREDUCE_BUCKET_MB``), keeping
                          the PR-3 "optimizer-consumed grads only"
                          semantics and ring conventions
========================  ==================================================

Training programs are rewritten **with their grad twins**: every grad op
carries ``__fwd_op_id__`` (framework.py), so the matcher locates the
backward chain of a matched forward subgraph exactly and replaces it
with the fused op's single ``<type>_grad`` (derived via ``jax.vjp`` over
the fused lowering — registry.generic_grad_fn — which recomputes with
the SAME deterministic RNG stream, so in-kernel dropout masks reproduce).

Every rewrite is bracketed by ``verify_pass`` when pass verification is
enabled (on in tests), and the fused ops are visible to the analyzer:
cost rules in :mod:`.cost`, sharding transfers in :mod:`.interp`, and
the collective-schedule deadlock proof in :mod:`.distributed` all
understand them.

Kill switch: ``PADDLE_TPU_FUSION=0`` disables the whole pipeline.
Introspection: ``CompiledProgram.fusion_report()`` lists applied
rewrites with op coordinates and predicted deltas, plus every matched-
but-skipped pattern with the cost-model reason (also surfaced as the
``fusible-pattern-not-fused`` advisory lint check).
"""

import os

from ..ops.registry import EMPTY_VAR_NAME
from .cost import dtype_bytes

__all__ = [
    "FusionConfig", "FusionRewrite", "FusionSkip", "FusionReport",
    "fusion_enabled", "allreduce_bucket_mb", "apply_fusion_passes",
    "resolve_fused_program", "scan_fusible_patterns",
    "conv_bn_min_bytes", "embed_fuse_min_bytes",
    "FUSED_FORWARD_OP_TYPES",
]

# fused forward op types this pipeline emits (roster for the
# fused-op-missing-grad lint check and for introspection)
FUSED_FORWARD_OP_TYPES = frozenset((
    "fused_multihead_attention", "fused_dropout_add_ln",
    "fused_bias_act", "softmax_with_cross_entropy",
    "fused_conv_bn_act", "fused_embedding_gather",
    # decode family: emitted by layers.decode_loop/flash_decode, never
    # by a rewrite here — listed so the matchers and the
    # fused-op-missing-grad lint treat it as an already-fused kernel
    # (forward-only by design: generation is inference)
    "flash_decode_attention", "paged_flash_decode_attention",
))

_ACT_TYPES = ("relu", "gelu", "tanh", "sigmoid", "relu6", "leaky_relu",
              "elu", "softplus", "swish")

# program attrs the executor/analyzer read that Program.clone() does not
# carry — the fused clone must behave identically to the original.
# WARNING: any NEW behavior-bearing Program/Variable attr must be added
# to these lists, or it silently vanishes on the clone the executor
# actually runs whenever a fusion family fires (fusion-off still works,
# which makes the divergence easy to miss)
_PROGRAM_MARKS = ("_num_trainers", "_trainer_id", "_host_tables",
                  "_hbm_budget", "_nan_guard", "_guard_loss_name",
                  "_pipeline_stage", "_guard_abort_after",
                  "_allreduce_bucket_mb", "_shard_optimizer_state",
                  "_quant_buckets", "_overlap", "_hierarchy",
                  "_cluster_spec")

# per-var attrs execution semantics depend on; Program.clone() now
# preserves these itself (framework.CLONE_VAR_MARKS) — this copy pass
# remains for rewrite paths that build vars without clone()
from ..framework import CLONE_VAR_MARKS as _VAR_MARKS  # noqa: E402


def _copy_var_marks(src_program, dst_program):
    for sb, db in zip(src_program.blocks, dst_program.blocks):
        for name, sv in sb.vars.items():
            dv = db.vars.get(name)
            if dv is None:
                continue
            for mark in _VAR_MARKS:
                val = getattr(sv, mark, None)
                if val is not None and not getattr(dv, mark, None):
                    setattr(dv, mark, val)


def fusion_enabled():
    """Global kill switch: ``PADDLE_TPU_FUSION=0`` disables every pass."""
    return os.environ.get("PADDLE_TPU_FUSION", "1") != "0"


def conv_bn_min_bytes():
    """Minimum conv-output bytes the conv+BN+act fusion must save per
    removed op for the rewrite to fire (``PADDLE_TPU_CONV_BN_MIN_BYTES``,
    default 4096 — tiny convs aren't worth an op identity change)."""
    try:
        return int(os.environ.get(
            "PADDLE_TPU_CONV_BN_MIN_BYTES", "4096") or 4096)
    except ValueError:
        return 4096


def embed_fuse_min_bytes():
    """Minimum gathered-slab bytes for the embedding-gather rewrite
    (``PADDLE_TPU_EMBED_FUSE_MIN_BYTES``, default 4096)."""
    try:
        return int(os.environ.get(
            "PADDLE_TPU_EMBED_FUSE_MIN_BYTES", "4096") or 4096)
    except ValueError:
        return 4096


def _autotune_state():
    """The autotune-cache state token — part of the fusion signature so
    an in-process sweep invalidates resolved program clones whose gates
    used the old calibration."""
    try:
        from ..autotune import state_token

        return state_token()
    except Exception:  # pragma: no cover - autotune subsystem broken
        return ("autotune-unavailable",)


def _calibration(family, **key):
    """(factor, sig, calibrated) for one fusion site: the autotune
    calibration factor the gate multiplies its predicted delta by, the
    signature it looked under, and whether a measured entry existed."""
    try:
        from ..autotune import (autotune_enabled, calibration_factor,
                                lookup, sweep_signature)

        sig = sweep_signature(family, key)
        if not autotune_enabled():
            return 1.0, sig, False
        return calibration_factor(sig), sig, lookup(sig) is not None
    except Exception:  # pragma: no cover - autotune subsystem broken
        return 1.0, str(family), False


def allreduce_bucket_mb(program=None):
    """Gradient-allreduce bucket cap in MB: the program's own
    ``_allreduce_bucket_mb`` mark (how the auto-parallelism planner's
    in-place apply scopes its chosen bucket to ONE program instead of
    leaking a process-global env change), else
    ``PADDLE_TPU_ALLREDUCE_BUCKET_MB``, default 32."""
    mark = getattr(program, "_allreduce_bucket_mb", None) \
        if program is not None else None
    if mark:
        try:
            return float(mark)
        except (TypeError, ValueError):
            pass
    try:
        return float(os.environ.get(
            "PADDLE_TPU_ALLREDUCE_BUCKET_MB", "32") or 32)
    except ValueError:
        return 32.0


def optimizer_fuse_overhead_bytes():
    """Per-op overhead the multi-tensor optimizer fusion is credited
    with removing, expressed as HBM-bytes-equivalent (a separate small
    elementwise kernel pays launch + ramp that the cost model prices at
    this many streamed bytes).  ``PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES``
    overrides; the default is backend-aware — 8 MiB (~8 µs at v5e HBM
    rate) on TPU, 256 KiB on CPU where XLA has no per-kernel ramp to
    amortize (a CPU A/B of the mnist MLP measured the concat/split
    rewrite 1.7x SLOWER, the same shape as the r04 BERT-base hardware
    regression the gate exists to prevent)."""
    val = os.environ.get("PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES", "").strip()
    if val:
        try:
            return int(val)
        except ValueError:
            pass
    global _BACKEND_DEFAULT_OVERHEAD
    if _BACKEND_DEFAULT_OVERHEAD is None:
        # backend identity is fixed for the process; signature() calls
        # this on the dispatch hot path
        try:
            import jax

            tpu = jax.default_backend() == "tpu"
        except Exception:  # pragma: no cover - no backend at all
            tpu = False
        _BACKEND_DEFAULT_OVERHEAD = (8 << 20) if tpu else (256 << 10)
    return _BACKEND_DEFAULT_OVERHEAD


_BACKEND_DEFAULT_OVERHEAD = None


class FusionConfig:
    """Which families run — resolved from ``BuildStrategy`` flags (the
    reference's knobs) + the env kill switch."""

    __slots__ = ("enabled", "fuse_attention", "fuse_elewise",
                 "fuse_softmax_xent", "fuse_optimizer", "fuse_allreduce",
                 "fuse_conv_bn_act", "fuse_embedding_gather")

    def __init__(self, enabled=None, fuse_attention=True, fuse_elewise=True,
                 fuse_softmax_xent=True, fuse_optimizer=True,
                 fuse_allreduce=True, fuse_conv_bn_act=True,
                 fuse_embedding_gather=True):
        self.enabled = fusion_enabled() if enabled is None else bool(enabled)
        self.fuse_attention = bool(fuse_attention)
        self.fuse_elewise = bool(fuse_elewise)
        self.fuse_softmax_xent = bool(fuse_softmax_xent)
        self.fuse_optimizer = bool(fuse_optimizer)
        self.fuse_allreduce = bool(fuse_allreduce)
        self.fuse_conv_bn_act = bool(fuse_conv_bn_act)
        self.fuse_embedding_gather = bool(fuse_embedding_gather)

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def from_build_strategy(cls, bs):
        c = cls()
        if bs is None:
            return c
        c.fuse_elewise = bool(getattr(bs, "fuse_elewise_add_act_ops", True))
        # ZeRO-1 shards the moments over the data axis: the flat-stream
        # concat would re-gather them every step, defeating the partition
        c.fuse_optimizer = (
            bool(getattr(bs, "fuse_all_optimizer_ops", True))
            and not getattr(bs, "shard_optimizer_state", False))
        c.fuse_allreduce = bool(getattr(bs, "fuse_all_reduce_ops", True))
        c.fuse_attention = bool(getattr(bs, "fuse_attention", True))
        c.fuse_softmax_xent = bool(getattr(bs, "fuse_softmax_xent", True))
        c.fuse_conv_bn_act = bool(getattr(bs, "fuse_bn_act_ops", True))
        c.fuse_embedding_gather = bool(
            getattr(bs, "fuse_embedding_gather", True))
        return c

    def signature(self, program=None):
        """Hashable identity — part of the executor's jit cache key.

        Pass the program whose rewrite is being keyed: the bucket cap,
        quant threshold, and overlap knob resolve mark → env → default,
        and the MARK must win in the key too — ``allreduce_bucket_mb()``
        bare would record the env value for a program whose
        ``_allreduce_bucket_mb`` mark overrides it, so a plan re-stamp
        (same program version) could hit a stale fused clone built for
        the old bucket size.  Same for ``_overlap``: stamping the mark
        after a resolve must miss the cached clone, or the executor
        keeps running yesterday's schedule."""
        from ..quant.collective import quant_min_bytes as _qmb
        from ..quant.blockwise import quant_block as _qb
        from .hierarchy import hierarchy_signature as _hier
        from .overlap import overlap_enabled as _ov

        return (self.enabled, self.fuse_attention, self.fuse_elewise,
                self.fuse_softmax_xent, self.fuse_optimizer,
                self.fuse_allreduce, self.fuse_conv_bn_act,
                self.fuse_embedding_gather, allreduce_bucket_mb(program),
                optimizer_fuse_overhead_bytes(), _flash_min_t(),
                conv_bn_min_bytes(), embed_fuse_min_bytes(),
                _qmb(program), _qb(), _ov(program), _hier(program),
                _autotune_state())

    def __repr__(self):
        return "FusionConfig%r" % (self.signature(),)


class FusionRewrite:
    """One applied rewrite: family, fused op type, op coordinates of the
    replaced subgraph, and the cost model's predicted deltas."""

    __slots__ = ("family", "fused_op_type", "block_idx", "op_idxs",
                 "vars", "predicted", "note", "inserted")

    def __init__(self, family, fused_op_type, block_idx, op_idxs,
                 vars=(), predicted=None, note="", inserted=1):
        self.family = family
        self.fused_op_type = fused_op_type
        self.block_idx = block_idx
        self.op_idxs = tuple(op_idxs)   # original coordinates (pre-rewrite)
        self.vars = tuple(vars)
        self.predicted = dict(predicted or {})
        self.note = note
        self.inserted = inserted        # fused ops added (fwd [+ grad])

    def to_dict(self):
        return {"family": self.family, "fused_op_type": self.fused_op_type,
                "block_idx": self.block_idx, "op_idxs": list(self.op_idxs),
                "vars": list(self.vars), "predicted": dict(self.predicted),
                "note": self.note, "inserted": self.inserted}

    def __repr__(self):
        pred = ", ".join("%s=%s" % kv for kv in sorted(
            self.predicted.items()))
        return "[%s] block %d ops %s -> %s (%s)%s" % (
            self.family, self.block_idx, list(self.op_idxs),
            self.fused_op_type, pred or "no predicted delta",
            " %s" % self.note if self.note else "")


class FusionSkip:
    """A matched-but-not-rewritten pattern and why (the cost-model or
    structural reason — surfaced by ``fusion_report()`` and by the
    ``fusible-pattern-not-fused`` advisory check)."""

    __slots__ = ("family", "block_idx", "op_idx", "op_type", "reason",
                 "key")

    def __init__(self, family, block_idx, op_idx, op_type, reason,
                 key=None):
        self.family = family
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.reason = reason
        self.key = key          # anchor __op_id__ — stable site identity

    def to_dict(self):
        return {"family": self.family, "block_idx": self.block_idx,
                "op_idx": self.op_idx, "op_type": self.op_type,
                "reason": self.reason}

    def __repr__(self):
        return "[%s] block %d op %d (%s) skipped: %s" % (
            self.family, self.block_idx, self.op_idx, self.op_type,
            self.reason)


class FusionReport:
    """Outcome of one pipeline run over one program."""

    def __init__(self, config):
        self.config = config
        self.applied = []
        self.skipped = []

    def record(self, rewrite):
        self.applied.append(rewrite)

    def skip(self, family, op_idx, op_type, reason, block_idx=0,
             key=None):
        entry = FusionSkip(family, block_idx, op_idx, op_type, reason,
                           key=key)
        if key is not None:
            # the family loop re-scans after every applied rewrite and
            # re-encounters still-gated sites: refresh in place (latest
            # coordinates are the ones valid in the reported program)
            # instead of recording the same site N+1 times
            for n, s in enumerate(self.skipped):
                if s.family == family and s.key == key:
                    self.skipped[n] = entry
                    return
        self.skipped.append(entry)

    def counts(self):
        out = {}
        for r in self.applied:
            out[r.family] = out.get(r.family, 0) + 1
        return out

    @property
    def ops_removed(self):
        return sum(len(r.op_idxs) - r.inserted for r in self.applied)

    def to_dict(self):
        return {"config": repr(self.config),
                "applied": [r.to_dict() for r in self.applied],
                "skipped": [s.to_dict() for s in self.skipped],
                "counts": self.counts()}

    def format(self):
        lines = ["fusion report (%d applied, %d skipped; %s)" % (
            len(self.applied), len(self.skipped),
            "enabled" if self.config.enabled else
            "DISABLED (PADDLE_TPU_FUSION=0)")]
        for r in self.applied:
            lines.append("  + %r" % r)
        for s in self.skipped:
            lines.append("  - %r" % s)
        return "\n".join(lines)

    def __repr__(self):
        return self.format()


# ---------------------------------------------------------------------------
# global-block view: consumers/producers/grad twins
# ---------------------------------------------------------------------------

def _is_grad_op(op):
    return op.type.endswith("_grad") \
        or op.attrs.get("op_role") == "backward"


class _GlobalView:
    """Def/use indexes over the global block, rebuilt after every
    rewrite (the fc_fuse_pass lesson: a consumer map built once goes
    stale the moment ops are replaced).  Sub-block closure reads count
    as consumers — fusing away a var a ``while`` body captures would
    leave a dangling read no input slot shows."""

    def __init__(self, program, targets=()):
        self.program = program
        self.block = program.global_block()
        self.targets = {getattr(t, "name", t) for t in (targets or ())}
        self.refresh()

    def refresh(self):
        from .defuse import resolve_sub_block, sub_block_reads_recursive

        block = self.block
        self.consumers = {}    # name -> [(idx, op)]  (all ops)
        self.producers = {}    # name -> [(idx, op)]
        self.closure_reads = set()   # names read inside sub-blocks
        self.grad_twins = {}   # fwd __op_id__ -> [(idx, grad op)]
        self.op_index = {}     # id(op) -> idx
        for idx, op in enumerate(block.ops):
            self.op_index[id(op)] = idx
            for n in op.input_arg_names:
                if n and n != EMPTY_VAR_NAME:
                    self.consumers.setdefault(n, []).append((idx, op))
            for n in op.output_arg_names:
                if n and n != EMPTY_VAR_NAME:
                    self.producers.setdefault(n, []).append((idx, op))
            sub = resolve_sub_block(self.program, op,
                                    host_block_idx=block.idx)
            if sub is not None:
                self.closure_reads.update(
                    sub_block_reads_recursive(self.program, sub))
            fwd_id = op.attrs.get("__fwd_op_id__")
            if fwd_id is not None and _is_grad_op(op):
                self.grad_twins.setdefault(fwd_id, []).append((idx, op))

    def idx_of(self, op):
        return self.op_index[id(op)]

    def shape(self, name):
        v = self.block._find_var_recursive(name)
        return None if v is None else v.shape

    def var(self, name):
        return self.block._find_var_recursive(name)

    def sole_fwd_consumer(self, name):
        """The single forward-op consumer of ``name``, or None when the
        name has 0 or >1 forward consumers, is read by a sub-block, or
        is observable (fetched)."""
        if name in self.targets or name in self.closure_reads:
            return None
        fwd = [(i, o) for i, o in self.consumers.get(name, ())
               if not _is_grad_op(o)]
        if len(fwd) != 1:
            return None
        return fwd[0]

    def unconsumed(self, name, group_ops):
        """True when every consumer of ``name`` is inside ``group_ops``
        (by identity) and the name is neither fetched nor persistable —
        i.e. removing its producer leaves no dangling read."""
        if name in self.targets or name in self.closure_reads:
            return False
        v = self.var(name)
        if v is not None and v.persistable:
            return False
        ids = {id(o) for o in group_ops}
        return all(id(o) in ids for _, o in self.consumers.get(name, ()))

    def twin(self, op, expect_type):
        """The unique grad twin of ``op`` with the expected type, or
        None (no grads).  Returns False when the twin structure is
        unexpected (refuse the match rather than mis-rewrite)."""
        twins = self.grad_twins.get(op.attrs.get("__op_id__"), [])
        twins = [t for t in twins if t[1].type == expect_type]
        if not twins:
            return None
        if len(twins) > 1:
            return False
        return twins[0]


def _replace_ops(block, replacements, removals):
    """Apply a rewrite: ``replacements`` maps op index -> new op;
    ``removals`` is the set of indices to drop."""
    new_ops = []
    for i, op in enumerate(block.ops):
        if i in replacements:
            new_ops.append(replacements[i])
        elif i in removals:
            continue
        else:
            new_ops.append(op)
    block.ops[:] = new_ops
    block.program._bump_version()


def _new_op(block, type, inputs, outputs, attrs):
    """Build a replacement op.  ``block=None`` (dry-run scans) draws the
    op id from the global counter instead of the program's, so a
    side-effect-free scan never shifts the program's deterministic op-id
    sequence (the RNG-reproducibility contract)."""
    from ..framework import Operator

    return Operator(block, type, inputs, outputs, attrs)


def _grad_attrs(fwd_op, extra=None):
    attrs = dict(fwd_op.attrs)
    attrs.pop("__op_id__", None)
    attrs["__fwd_op_id__"] = fwd_op.attrs.get("__op_id__", 0)
    attrs["op_role"] = "backward"
    if extra:
        attrs.update(extra)
    return attrs


def _numel(shape, batch=1):
    if shape is None:
        return None
    n = 1
    for d in shape:
        n *= batch if (d is None or int(d) < 0) else max(int(d), 1)
    return n


def _var_bytes(view, name, batch=1):
    v = view.var(name)
    if v is None or v.shape is None:
        return 0
    return (_numel(v.shape, batch) or 0) * dtype_bytes(v.dtype)


def _flash_min_t():
    try:
        from ..ops.pallas.flash_attention import flash_min_t

        return flash_min_t()
    except Exception:  # pragma: no cover - jax/pallas unavailable
        return int(os.environ.get("PADDLE_TPU_FLASH_MIN_T", "512") or 512)


# ---------------------------------------------------------------------------
# family: attention
# ---------------------------------------------------------------------------

def _find_attention(view, report, dry_run=False):
    """matmul(QKᵀ·α) → (+bias) → softmax → (dropout) → matmul(·V)."""
    block = view.block
    for i, op in enumerate(block.ops):
        if op.type != "matmul" or _is_grad_op(op):
            continue
        if not op.attrs.get("transpose_Y") or op.attrs.get("transpose_X"):
            continue
        q = op.inputs.get("X", [None])[0]
        k = op.inputs.get("Y", [None])[0]
        qs, ks = view.shape(q), view.shape(k)
        if not qs or not ks or len(qs) != 4 or len(ks) != 4:
            continue
        s0 = op.outputs["Out"][0]
        alpha = float(op.attrs.get("alpha", 1.0))
        group = [op]
        nxt = view.sole_fwd_consumer(s0)
        bias = None
        add_op = None
        if nxt is not None and nxt[1].type == "elementwise_add":
            add_op = nxt[1]
            if add_op.inputs.get("X", [None])[0] != s0:
                continue
            if int(add_op.attrs.get("axis", -1)) != -1:
                continue
            bias = add_op.inputs.get("Y", [None])[0]
            bs = view.shape(bias)
            # the fused op broadcasts its bias per BATCH over heads and
            # query rows — only the [B,1,1,Tk] form (or [1,Tk]) has the
            # same meaning under the unfused add's trailing alignment.
            # A general rank-2 [B,Tk] trailing-aligns to the (Tq,Tk)
            # score dims, i.e. a per-QUERY-ROW bias: different math
            # whenever B==Tq>1, so it must stay unfused.
            if not bs or not (
                    (len(bs) == 4 and bs[1] == 1 and bs[2] == 1)
                    or (len(bs) == 2 and bs[0] == 1)):
                continue
            bvar = view.var(bias)
            # the fused path treats the bias as constant (padding masks
            # are data): a bias that needs a gradient must stay unfused
            bias_twin = view.twin(add_op, "elementwise_add_grad")
            if bias_twin is False:
                continue
            if bias_twin is not None:
                yg = bias_twin[1].outputs.get("Y@GRAD", [EMPTY_VAR_NAME])
                if yg and yg[0] != EMPTY_VAR_NAME:
                    report.skip("attention", i, op.type,
                                "additive bias %r requires a gradient — "
                                "the flash path treats the mask bias as "
                                "constant" % bias,
                                key=op.attrs.get("__op_id__"))
                    continue
            if bvar is None:
                continue
            group.append(add_op)
            nxt = view.sole_fwd_consumer(add_op.outputs["Out"][0])
        if nxt is None or nxt[1].type != "softmax":
            continue
        sm_op = nxt[1]
        ax = int(sm_op.attrs.get("axis", -1))
        if ax not in (-1, 3):
            continue
        group.append(sm_op)
        nxt = view.sole_fwd_consumer(sm_op.outputs["Out"][0])
        drop_op = None
        rate = 0.0
        if nxt is not None and nxt[1].type == "dropout":
            drop_op = nxt[1]
            if drop_op.attrs.get("dropout_implementation") \
                    != "upscale_in_train":
                report.skip("attention", i, op.type,
                            "attention dropout uses downgrade_in_infer — "
                            "the fused kernel implements upscale_in_train "
                            "only", key=op.attrs.get("__op_id__"))
                continue
            mask = drop_op.outputs.get("Mask", [None])[0]
            probe = group + [drop_op]
            if mask and not view.unconsumed(
                    mask, probe + _twin_ops(view, probe)):
                continue
            rate = float(drop_op.attrs.get("dropout_prob", 0.0) or 0.0)
            group.append(drop_op)
            nxt = view.sole_fwd_consumer(drop_op.outputs["Out"][0])
        if nxt is None or nxt[1].type != "matmul":
            continue
        mm2 = nxt[1]
        if mm2.attrs.get("transpose_X") or mm2.attrs.get("transpose_Y") \
                or float(mm2.attrs.get("alpha", 1.0)) != 1.0:
            continue
        probs = (drop_op or sm_op).outputs["Out"][0]
        if mm2.inputs.get("X", [None])[0] != probs:
            continue
        v = mm2.inputs.get("Y", [None])[0]
        vs = view.shape(v)
        if not vs or len(vs) != 4:
            continue
        group.append(mm2)

        # ---- cost gate: the blocked flash kernel only beats XLA's
        # fused unblocked attention above the measured engagement
        # threshold (r05 v5e sweep, env-tunable) ----
        tq = int(qs[2]) if qs[2] and int(qs[2]) > 0 else 0
        tk = int(ks[2]) if ks[2] and int(ks[2]) > 0 else 0
        min_t = _flash_min_t()
        if max(tq, tk) < min_t:
            report.skip(
                "attention", i, op.type,
                "cost model: T=%d below the flash engagement threshold "
                "%d (XLA's unblocked attention is faster there, r05 "
                "sweep; PADDLE_TPU_FLASH_MIN_T re-decides)"
                % (max(tq, tk), min_t),
                key=op.attrs.get("__op_id__"))
            continue

        match = _match_attention_grads(view, report, group, i, q, k, v,
                                       bias, alpha, rate, drop_op, mm2,
                                       dry_run=dry_run)
        if match is None:
            continue
        if dry_run:
            report.record(match["rewrite"])
            continue
        return match
    return None


def _match_attention_grads(view, report, group, i, q, k, v, bias, alpha,
                           rate, drop_op, mm2, dry_run=False):
    mm1, sm_op = group[0], next(o for o in group if o.type == "softmax")
    add_op = next((o for o in group if o.type == "elementwise_add"), None)
    ctx_out = mm2.outputs["Out"][0]

    # grad twins (empty for inference programs)
    twins = []
    for o in group:
        t = view.twin(o, o.type + "_grad")
        if t is False:
            return None
        if t is not None:
            twins.append(t)
    mm2_twin = view.twin(mm2, "matmul_grad")
    mm1_twin = view.twin(mm1, "matmul_grad")
    if twins and (mm2_twin is None or mm1_twin in (None, False)
                  or mm2_twin is False or len(twins) != len(group)):
        # partial backward chain — refuse rather than mis-rewrite
        return None

    # every removed intermediate (and its grad) must be internal
    removed_fwd = [o.outputs["Out"][0] for o in group[:-1]]
    all_group_ops = list(group) + [t[1] for t in twins]
    for n in removed_fwd:
        if not view.unconsumed(n, all_group_ops):
            return None
    if twins:
        for _, g in twins:
            for n in g.output_arg_names:
                if n == EMPTY_VAR_NAME:
                    continue
                # grads the outside world keeps: q/k/v grads survive
                if n in (_grad_out(mm1_twin[1], "X@GRAD"),
                         _grad_out(mm1_twin[1], "Y@GRAD"),
                         _grad_out(mm2_twin[1], "Y@GRAD")):
                    continue
                if not view.unconsumed(n, all_group_ops):
                    return None

    block = view.block
    op_block = None if dry_run else block
    qs, ks = view.shape(q), view.shape(k)
    # a dynamic batch dim is fine (_numel maps it to None) but the
    # head/seq/depth dims must be static: the flash kernel blocks on
    # them, and a mixed case (dynamic Tq, static Tk over the threshold)
    # reaches here past the cost gate
    dyn = [d for d in (qs[1], qs[2], qs[3], ks[2])
           if not (isinstance(d, int) and d > 0)]
    if dyn:
        report.skip(
            "attention", i, mm1.type,
            "dynamic head/seq dims %r — the fused attention kernel "
            "needs static non-batch shapes" % (dyn,),
            key=mm1.attrs.get("__op_id__"))
        return None
    b, h, tq, dh = (_numel((qs[0],)), int(qs[1]), int(qs[2]), int(qs[3]))
    tk = int(ks[2])
    # predicted delta: the [B,H,Tq,Tk] score/prob tensors never touch HBM
    score_bytes = 4 * (b or 1) * h * tq * tk
    n_inter = len(group) - 1
    predicted = {
        "hbm_bytes_saved": 2 * n_inter * score_bytes,
        "ops_removed": len(group) - 1,
        "flash_kernel": "tpu" if max(tq, tk) >= _flash_min_t() else "xla",
    }

    ins = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        ins["BiasQK"] = [bias]
    attrs = {"causal": False, "scale": alpha, "dropout_rate": rate}
    if drop_op is not None and "is_test" in drop_op.attrs:
        attrs["is_test"] = drop_op.attrs["is_test"]
    fused = _new_op(op_block, "fused_multihead_attention", ins,
                    {"Out": [ctx_out]}, attrs)

    replacements = {view.idx_of(mm2): fused}
    removals = {view.idx_of(o) for o in group} - set(replacements)
    if twins:
        g_ins = dict(ins)
        g_ins["Out"] = [ctx_out]
        g_ins["Out@GRAD"] = list(mm2_twin[1].inputs.get(
            "Out@GRAD", [EMPTY_VAR_NAME]))
        g_outs = {
            "Q@GRAD": [_grad_out(mm1_twin[1], "X@GRAD")],
            "K@GRAD": [_grad_out(mm1_twin[1], "Y@GRAD")],
            "V@GRAD": [_grad_out(mm2_twin[1], "Y@GRAD")],
        }
        gfused = _new_op(op_block, "fused_multihead_attention_grad",
                         g_ins, g_outs, _grad_attrs(fused))
        first_twin = min(t[0] for t in twins)
        replacements[first_twin] = gfused
        removals |= {t[0] for t in twins} - set(replacements)

    op_idxs = sorted({view.idx_of(o) for o in group}
                     | {t[0] for t in twins})
    rewrite = FusionRewrite(
        "attention", "fused_multihead_attention", block.idx, op_idxs,
        vars=(q, k, v) + ((bias,) if bias else ()), predicted=predicted,
        note="dropout rate %.3g (mask stream differs from the unfused "
             "dropout op — documented)" % rate if rate else "",
        inserted=len(replacements))
    return {"replacements": replacements, "removals": removals,
            "rewrite": rewrite}


def _grad_out(grad_op, slot):
    names = grad_op.outputs.get(slot, [])
    return names[0] if names else EMPTY_VAR_NAME


# ---------------------------------------------------------------------------
# family: dropout + residual-add + layer_norm
# ---------------------------------------------------------------------------

def _find_dropout_add_ln(view, report, dry_run=False):
    block = view.block
    for i, op in enumerate(block.ops):
        if op.type != "layer_norm" or _is_grad_op(op):
            continue
        x_in = op.inputs.get("X", [None])[0]
        scale = op.inputs.get("Scale", [None])
        bias = op.inputs.get("Bias", [None])
        if not scale or not bias or scale[0] is None or bias[0] is None:
            continue
        xs = view.shape(x_in)
        if not xs or int(op.attrs.get("begin_norm_axis", 1)) \
                != len(xs) - 1:
            continue
        d = xs[-1]
        if d is None or int(d) <= 0:
            continue
        prods = view.producers.get(x_in, [])
        if len(prods) != 1 or prods[0][1].type != "elementwise_add":
            continue
        add_op = prods[0][1]
        sole = view.sole_fwd_consumer(x_in)
        if sole is None or sole[1] is not op:
            continue
        a = add_op.inputs.get("X", [None])[0]
        bm = add_op.inputs.get("Y", [None])[0]
        if view.shape(a) != view.shape(bm):
            continue
        # which side is a dropout output?
        drop_op = None
        x_name, res_name = bm, a
        for cand, other in ((a, bm), (bm, a)):
            p = view.producers.get(cand, [])
            if len(p) == 1 and p[0][1].type == "dropout" \
                    and not _is_grad_op(p[0][1]):
                dp = p[0][1]
                sole = view.sole_fwd_consumer(cand)
                if sole is None or sole[1] is not add_op:
                    continue
                if dp.attrs.get("dropout_implementation") \
                        != "upscale_in_train":
                    continue
                drop_op = dp
                x_name, res_name = dp.inputs["X"][0], other
                break
        rate = 0.0
        group = ([drop_op] if drop_op else []) + [add_op, op]
        if drop_op is not None:
            rate = float(drop_op.attrs.get("dropout_prob", 0.0) or 0.0)
            mask = drop_op.outputs.get("Mask", [None])[0]
            if mask and not view.unconsumed(
                    mask, group + _twin_ops(view, group)):
                continue

        # grad twins
        twins = []
        bad = False
        for o in group:
            t = view.twin(o, o.type + "_grad")
            if t is False:
                bad = True
                break
            if t is not None:
                twins.append((o, t))
        if bad:
            continue
        if twins and len(twins) != len(group):
            continue
        all_ops = group + [t[1][1] for t in twins]
        # removed intermediates: add out (x_in), dropout out, Mean/Var
        removed = [x_in] + ([drop_op.outputs["Out"][0]] if drop_op else [])
        removed += [n for s in ("Mean", "Variance")
                    for n in op.outputs.get(s, []) if n]
        if not all(view.unconsumed(n, all_ops) for n in removed):
            continue
        ln_twin = next((t for o, t in twins if o is op), None)
        add_twin = next((t for o, t in twins if o is add_op), None)
        drop_twin = next((t for o, t in twins if o is drop_op), None)
        if twins:
            internal_grads = []
            internal_grads.append(_grad_out(ln_twin[1], "X@GRAD"))
            if drop_op is not None:
                slot = "Y@GRAD" if add_op.inputs["Y"][0] \
                    == drop_op.outputs["Out"][0] else "X@GRAD"
                internal_grads.append(_grad_out(add_twin[1], slot))
            for n in internal_grads:
                if n != EMPTY_VAR_NAME \
                        and not view.unconsumed(n, all_ops):
                    bad = True
            if bad:
                continue

        n_rows = _numel(xs[:-1])
        predicted = {
            "hbm_bytes_saved": 2 * (len(group) - 1)
            * (n_rows or 1) * int(d) * 4,
            "ops_removed": len(group) - 1,
        }
        fattrs = {"dropout_prob": rate,
                  "epsilon": float(op.attrs.get("epsilon", 1e-5))}
        if drop_op is not None and "is_test" in drop_op.attrs:
            fattrs["is_test"] = drop_op.attrs["is_test"]
        ins = {"X": [x_name], "Residual": [res_name],
               "Scale": [scale[0]], "Bias": [bias[0]]}
        fused = _new_op(None if dry_run else block, "fused_dropout_add_ln", ins,
                        {"Out": [op.outputs["Y"][0]]}, fattrs)
        replacements = {view.idx_of(op): fused}
        removals = {view.idx_of(o) for o in group} - set(replacements)
        if twins:
            if drop_op is not None:
                x_grad = _grad_out(drop_twin[1], "X@GRAD")
                res_slot = "X@GRAD" if add_op.inputs["X"][0] == res_name \
                    else "Y@GRAD"
                res_grad = _grad_out(add_twin[1], res_slot)
            else:
                x_slot = "Y@GRAD" if add_op.inputs["Y"][0] == x_name \
                    else "X@GRAD"
                res_slot = "X@GRAD" if x_slot == "Y@GRAD" else "Y@GRAD"
                x_grad = _grad_out(add_twin[1], x_slot)
                res_grad = _grad_out(add_twin[1], res_slot)
            g_ins = dict(ins)
            g_ins["Out"] = [op.outputs["Y"][0]]
            g_ins["Out@GRAD"] = list(ln_twin[1].inputs.get(
                "Y@GRAD", [EMPTY_VAR_NAME]))
            g_outs = {
                "X@GRAD": [x_grad], "Residual@GRAD": [res_grad],
                "Scale@GRAD": [_grad_out(ln_twin[1], "Scale@GRAD")],
                "Bias@GRAD": [_grad_out(ln_twin[1], "Bias@GRAD")],
            }
            gfused = _new_op(None if dry_run else block, "fused_dropout_add_ln_grad", g_ins,
                             g_outs, _grad_attrs(fused))
            first_twin = min(t[0] for _, t in twins)
            replacements[first_twin] = gfused
            removals |= {t[0] for _, t in twins} - set(replacements)
        op_idxs = sorted({view.idx_of(o) for o in group}
                         | {t[0] for _, t in twins})
        rewrite = FusionRewrite(
            "dropout_add_ln", "fused_dropout_add_ln", block.idx, op_idxs,
            vars=(x_name, res_name), predicted=predicted,
            note=("dropout rate %.3g (mask stream differs from the "
                  "unfused dropout op — documented)" % rate) if rate
            else "rate 0: bit-exact in f32",
            inserted=len(replacements))
        match = {"replacements": replacements, "removals": removals,
                 "rewrite": rewrite}
        if dry_run:
            report.record(rewrite)
            continue
        return match
    return None


def _twin_ops(view, group):
    out = []
    for o in group:
        twins = view.grad_twins.get(o.attrs.get("__op_id__"), [])
        out.extend(t for _, t in twins)
    return out


# ---------------------------------------------------------------------------
# family: bias + activation  (fuse_elewise_add_act_pass)
# ---------------------------------------------------------------------------

def _find_bias_act(view, report, dry_run=False):
    block = view.block
    for i, op in enumerate(block.ops):
        if op.type != "elementwise_add" or _is_grad_op(op):
            continue
        b = op.inputs.get("Y", [None])[0]
        bv = view.var(b) if b else None
        if bv is None or not bv.persistable or bv.shape is None \
                or len(bv.shape) != 1:
            continue
        out = op.outputs["Out"][0]
        nxt = view.sole_fwd_consumer(out)
        if nxt is None or nxt[1].type not in _ACT_TYPES:
            continue
        act_op = nxt[1]
        group = [op, act_op]
        twins = []
        bad = False
        for o in group:
            t = view.twin(o, o.type + "_grad")
            if t is False:
                bad = True
                break
            if t is not None:
                twins.append((o, t))
        if bad or (twins and len(twins) != len(group)):
            continue
        all_ops = group + [t[1][1] for t in twins]
        if not view.unconsumed(out, all_ops):
            continue
        add_twin = next((t for o, t in twins if o is op), None)
        act_twin = next((t for o, t in twins if o is act_op), None)
        if twins:
            inter_grad = _grad_out(act_twin[1], "X@GRAD")
            if inter_grad != EMPTY_VAR_NAME \
                    and not view.unconsumed(inter_grad, all_ops):
                continue
        predicted = {"ops_removed": 1,
                     "hbm_bytes_saved": 2 * _var_bytes(view, out)}
        fattrs = {k: v for k, v in act_op.attrs.items()
                  if not k.startswith("__") and k != "op_namescope"}
        fattrs["act_type"] = act_op.type
        fattrs["axis"] = int(op.attrs.get("axis", -1))
        fused = _new_op(None if dry_run else block, "fused_bias_act",
                        {"X": [op.inputs["X"][0]], "Bias": [b]},
                        {"Out": [act_op.outputs["Out"][0]]}, fattrs)
        replacements = {view.idx_of(act_op): fused}
        removals = {view.idx_of(op)}
        if twins:
            g_ins = {"X": [op.inputs["X"][0]], "Bias": [b],
                     "Out": [act_op.outputs["Out"][0]],
                     "Out@GRAD": list(act_twin[1].inputs.get(
                         "Out@GRAD", [EMPTY_VAR_NAME]))}
            g_outs = {"X@GRAD": [_grad_out(add_twin[1], "X@GRAD")],
                      "Bias@GRAD": [_grad_out(add_twin[1], "Y@GRAD")]}
            gfused = _new_op(None if dry_run else block, "fused_bias_act_grad", g_ins, g_outs,
                             _grad_attrs(fused))
            first_twin = min(t[0] for _, t in twins)
            replacements[first_twin] = gfused
            removals |= {t[0] for _, t in twins} - set(replacements)
        op_idxs = sorted({view.idx_of(o) for o in group}
                         | {t[0] for _, t in twins})
        rewrite = FusionRewrite(
            "bias_act", "fused_bias_act", block.idx, op_idxs,
            vars=(op.inputs["X"][0], b), predicted=predicted,
            note="bit-exact composite (%s)" % act_op.type,
            inserted=len(replacements))
        match = {"replacements": replacements, "removals": removals,
                 "rewrite": rewrite}
        if dry_run:
            report.record(rewrite)
            continue
        return match
    return None


# ---------------------------------------------------------------------------
# family: softmax + cross_entropy
# ---------------------------------------------------------------------------

def _find_softmax_xent(view, report, dry_run=False):
    block = view.block
    for i, op in enumerate(block.ops):
        if op.type != "softmax" or _is_grad_op(op):
            continue
        p_name = op.outputs["Out"][0]
        xs = view.shape(op.inputs["X"][0])
        ax = int(op.attrs.get("axis", -1))
        if xs and ax not in (-1, len(xs) - 1):
            continue
        ce_ops = [(j, o) for j, o in view.consumers.get(p_name, ())
                  if o.type == "cross_entropy" and not _is_grad_op(o)]
        if len(ce_ops) != 1:
            continue
        j, ce = ce_ops[0]
        if ce.inputs.get("X", [None])[0] != p_name:
            continue
        label = ce.inputs.get("Label", [None])[0]
        # the fused op is placed at the softmax's index so consumers of
        # the (still-produced) softmax output between the two sites stay
        # valid — the label must already be defined there
        lv = view.var(label)
        label_ready = lv is not None and (lv.is_data or lv.persistable)
        if not label_ready:
            lp = view.producers.get(label, [])
            label_ready = bool(lp) and all(idx < i for idx, _ in lp)
        if not label_ready:
            report.skip("softmax_xent", i, op.type,
                        "label %r is produced after the softmax — cannot "
                        "hoist the fused op" % label,
                        key=op.attrs.get("__op_id__"))
            continue
        group = [op, ce]
        sm_twin = view.twin(op, "softmax_grad")
        ce_twin = view.twin(ce, "cross_entropy_grad")
        if sm_twin is False or ce_twin is False:
            continue
        twins = [t for t in (ce_twin, sm_twin) if t is not None]
        if twins and len(twins) != 2:
            continue
        all_ops = group + [t[1] for t in twins]
        if twins:
            # the probability grad must be exclusively internal: other
            # consumers of the softmax output (metrics) are fine, but a
            # second grad contribution means a second loss path reads
            # the probabilities — the fused op's Softmax output is
            # stop_gradient and would silently drop it
            pg = _grad_out(ce_twin[1], "X@GRAD")
            if pg == EMPTY_VAR_NAME \
                    or not view.unconsumed(pg, all_ops):
                report.skip(
                    "softmax_xent", i, op.type,
                    "softmax output %r receives gradients from outside "
                    "the cross_entropy — fusing would drop them"
                    % p_name, key=op.attrs.get("__op_id__"))
                continue
            # the fused grad emits Logits@GRAD only: a differentiable
            # soft label (distillation teacher) whose Label@GRAD is
            # read downstream would be left dangling
            lg = _grad_out(ce_twin[1], "Label@GRAD")
            if lg != EMPTY_VAR_NAME and not view.unconsumed(lg, all_ops):
                report.skip(
                    "softmax_xent", i, op.type,
                    "label %r is differentiable and its gradient %r is "
                    "consumed — the fused op emits no Label@GRAD"
                    % (label, lg), key=op.attrs.get("__op_id__"))
                continue
        cs = view.shape(p_name)
        predicted = {
            "ops_removed": 1,
            "hbm_bytes_saved": 2 * _var_bytes(view, p_name),
            "flops_saved": 3 * (_numel(cs) or 0),
        }
        fattrs = {"soft_label": ce.attrs.get("soft_label", False),
                  "ignore_index": int(ce.attrs.get("ignore_index", -100)),
                  "axis": -1}
        fused = _new_op(
            None if dry_run else block, "softmax_with_cross_entropy",
            {"Logits": list(op.inputs["X"]), "Label": [label]},
            {"Softmax": [p_name], "Loss": list(ce.outputs["Y"])}, fattrs)
        replacements = {i: fused}
        removals = {j}
        if twins:
            g_ins = {"Logits": list(op.inputs["X"]), "Label": [label],
                     "Softmax": [p_name],
                     "Loss": list(ce.outputs["Y"]),
                     "Loss@GRAD": list(ce_twin[1].inputs.get(
                         "Y@GRAD", [EMPTY_VAR_NAME]))}
            g_outs = {"Logits@GRAD": [_grad_out(sm_twin[1], "X@GRAD")]}
            gfused = _new_op(None if dry_run else block, "softmax_with_cross_entropy_grad",
                             g_ins, g_outs, _grad_attrs(fused))
            first_twin = min(t[0] for t in twins)
            replacements[first_twin] = gfused
            removals |= {t[0] for t in twins} - set(replacements)
        op_idxs = sorted({i, j} | {t[0] for t in twins})
        rewrite = FusionRewrite(
            "softmax_xent", "softmax_with_cross_entropy", block.idx,
            op_idxs, vars=(op.inputs["X"][0], label), predicted=predicted,
            note="logsumexp form: loss differs from the eps-guarded "
                 "unfused pair by ~1e-6 relative (documented)",
            inserted=len(replacements))
        match = {"replacements": replacements, "removals": removals,
                 "rewrite": rewrite}
        if dry_run:
            report.record(rewrite)
            continue
        return match
    return None


# ---------------------------------------------------------------------------
# family: conv2d + batch_norm + activation  (fuse_bn_act_ops)
# ---------------------------------------------------------------------------

def _find_conv_bn_act(view, report, dry_run=False):
    """conv2d → batch_norm → (activation) ⇒ ``fused_conv_bn_act``.

    The biggest remaining kernel gap (ResNet-50 MFU 0.250 vs XLA's own
    0.381 accounting): the BN normalize/affine and the relu each pay a
    full HBM round-trip of the conv output, plus the framework op
    boundaries keep XLA from fusing training-mode BN stats back into
    one sweep.  The fused op keeps the conv on XLA's MXU schedule and
    runs the whole epilogue in one pass (Pallas where eligible —
    ops/pallas/conv_bn_act.py).  Gated by predicted HBM savings times
    the autotune calibration factor for the site's signature."""
    block = view.block
    for i, op in enumerate(block.ops):
        if op.type != "conv2d" or _is_grad_op(op):
            continue
        conv_out = op.outputs["Output"][0]
        cv = view.var(conv_out)
        conv_dtype = str(cv.dtype) if cv is not None else "float32"
        nxt = view.sole_fwd_consumer(conv_out)
        # AMP cast-sandwich: the bf16 rewrite inserts conv -> cast(f32)
        # -> batch_norm -> cast(compute dtype) -> act.  The fused op IS
        # that sandwich (f32 stats/normalize, output cast to the conv
        # dtype), so absorb the cast pair into the match.
        cast_in = None
        if nxt is not None and nxt[1].type == "cast" \
                and str(nxt[1].attrs.get("out_dtype")) == "float32" \
                and conv_dtype != "float32":
            cast_in = nxt[1]
            nxt = view.sole_fwd_consumer(cast_in.outputs["Out"][0])
        if nxt is None or nxt[1].type != "batch_norm":
            continue
        bn = nxt[1]
        bn_x = cast_in.outputs["Out"][0] if cast_in is not None \
            else conv_out
        if bn.inputs.get("X", [None])[0] != bn_x:
            continue
        conv_fmt = op.attrs.get("data_format", "NCHW")
        if conv_fmt == "AnyLayout":
            conv_fmt = "NCHW"
        bn_fmt = bn.attrs.get("data_layout", "NCHW")
        if conv_fmt != bn_fmt:
            continue
        scale = bn.inputs.get("Scale", [None])[0]
        bias = bn.inputs.get("Bias", [None])[0]
        mean = bn.inputs.get("Mean", [None])[0]
        var = bn.inputs.get("Variance", [None])[0]
        if None in (scale, bias, mean, var):
            continue
        y = bn.outputs["Y"][0]
        cast_out = None
        nxt2 = view.sole_fwd_consumer(y)
        if cast_in is not None and nxt2 is not None \
                and nxt2[1].type == "cast" \
                and str(nxt2[1].attrs.get("out_dtype")) == conv_dtype:
            cast_out = nxt2[1]
            nxt2 = view.sole_fwd_consumer(cast_out.outputs["Out"][0])
        if (cast_in is None) != (cast_out is None):
            continue  # half a sandwich — refuse rather than mis-type
        act_op = None
        if nxt2 is not None and nxt2[1].type in _ACT_TYPES \
                and not _is_grad_op(nxt2[1]):
            act_op = nxt2[1]
        group = [op] \
            + ([cast_in] if cast_in is not None else []) \
            + [bn] \
            + ([cast_out] if cast_out is not None else []) \
            + ([act_op] if act_op is not None else [])
        if act_op is not None:
            out_final = act_op.outputs["Out"][0]
        elif cast_out is not None:
            out_final = cast_out.outputs["Out"][0]
        else:
            out_final = y

        # grad twins (all-or-nothing; empty for inference programs)
        twins = []
        bad = False
        for o in group:
            t = view.twin(o, o.type + "_grad")
            if t is False:
                bad = True
                break
            if t is not None:
                twins.append((o, t))
        if bad or (twins and len(twins) != len(group)):
            continue
        all_ops = group + [t[1] for _, t in twins]
        # removed intermediates: conv out, the AMP cast temps, bn Y
        # (when anything follows it), and the saved batch stats
        # (consumed only by batch_norm_grad, which the fused grad's vjp
        # recompute replaces)
        removed = [conv_out]
        if cast_in is not None:
            removed.append(cast_in.outputs["Out"][0])
        if cast_out is not None or act_op is not None:
            removed.append(y)
        if cast_out is not None and act_op is not None:
            removed.append(cast_out.outputs["Out"][0])
        removed += [n for s in ("SavedMean", "SavedVariance")
                    for n in bn.outputs.get(s, []) if n]
        if not all(view.unconsumed(n, all_ops) for n in removed):
            continue
        conv_twin = next((t for o, t in twins if o is op), None)
        bn_twin = next((t for o, t in twins if o is bn), None)
        act_twin = next((t for o, t in twins if o is act_op), None)
        cout_twin = next((t for o, t in twins if o is cast_out), None)
        cin_twin = next((t for o, t in twins if o is cast_in), None)
        if twins:
            internal_grads = [_grad_out(bn_twin[1], "X@GRAD")]
            for tw in (act_twin, cout_twin, cin_twin):
                if tw is not None:
                    internal_grads.append(_grad_out(tw[1], "X@GRAD"))
            if not all(n == EMPTY_VAR_NAME or view.unconsumed(n, all_ops)
                       for n in internal_grads):
                continue

        # ---- cost gate: predicted HBM savings x autotune calibration
        # (the measure-and-learn loop: silicon re-weighs the constant) --
        out_bytes = _var_bytes(view, conv_out)
        n_removed = len(group) - 1
        act_name = act_op.type if act_op is not None else "identity"
        ov = view.var(conv_out)
        factor, sig, calibrated = _calibration(
            "conv_bn_act",
            shape=tuple(ov.shape) if ov is not None and ov.shape else (),
            dtype=str(ov.dtype) if ov is not None else "float32",
            act=act_name)
        threshold = conv_bn_min_bytes()
        if out_bytes * factor < threshold:
            report.skip(
                "conv_bn_act", i, op.type,
                "cost model: fused epilogue saves ~%d B of HBM traffic "
                "per removed op, below the %d B gate (calibration x%.2f"
                "%s)" % (
                    int(out_bytes * factor), threshold, factor,
                    "" if calibrated else
                    " — uncalibrated: no autotune cache entry for %r "
                    "yet; a silicon sweep (paddle_tpu.autotune.sweep) "
                    "re-decides this gate" % sig),
                key=op.attrs.get("__op_id__"))
            continue

        predicted = {
            "hbm_bytes_saved": 2 * n_removed * out_bytes,
            "ops_removed": n_removed,
            "calibration": factor,
        }
        ins = {"Input": list(op.inputs["Input"]),
               "Filter": list(op.inputs["Filter"]),
               "Scale": [scale], "Bias": [bias],
               "Mean": [mean], "Variance": [var]}
        outs = {"Out": [out_final],
                "MeanOut": list(bn.outputs.get("MeanOut", [])),
                "VarianceOut": list(bn.outputs.get("VarianceOut", []))}
        fattrs = {k: v for k, v in op.attrs.items()
                  if not k.startswith("__") and k != "op_namescope"}
        for k in ("epsilon", "momentum", "is_test", "use_global_stats",
                  "data_layout"):
            if k in bn.attrs:
                fattrs[k] = bn.attrs[k]
        if act_op is not None:
            fattrs.update({k: v for k, v in act_op.attrs.items()
                           if not k.startswith("__")
                           and k != "op_namescope"})
        fattrs["act_type"] = act_name if act_op is not None else ""
        anchor = act_op if act_op is not None else (
            cast_out if cast_out is not None else bn)
        fused = _new_op(None if dry_run else block, "fused_conv_bn_act",
                        ins, outs, fattrs)
        replacements = {view.idx_of(anchor): fused}
        removals = {view.idx_of(o) for o in group} - set(replacements)
        if twins:
            g_ins = dict(ins)
            g_ins["Out"] = [out_final]
            if act_twin is not None:
                last_twin, og_slot = act_twin, "Out@GRAD"
            elif cout_twin is not None:
                last_twin, og_slot = cout_twin, "Out@GRAD"
            else:
                last_twin, og_slot = bn_twin, "Y@GRAD"
            g_ins["Out@GRAD"] = list(last_twin[1].inputs.get(
                og_slot, [EMPTY_VAR_NAME]))
            g_outs = {
                "Input@GRAD": [_grad_out(conv_twin[1], "Input@GRAD")],
                "Filter@GRAD": [_grad_out(conv_twin[1], "Filter@GRAD")],
                "Scale@GRAD": [_grad_out(bn_twin[1], "Scale@GRAD")],
                "Bias@GRAD": [_grad_out(bn_twin[1], "Bias@GRAD")],
            }
            gfused = _new_op(None if dry_run else block,
                             "fused_conv_bn_act_grad", g_ins, g_outs,
                             _grad_attrs(fused))
            first_twin = min(t[0] for _, t in twins)
            replacements[first_twin] = gfused
            removals |= {t[0] for _, t in twins} - set(replacements)
        op_idxs = sorted({view.idx_of(o) for o in group}
                         | {t[0] for _, t in twins})
        rewrite = FusionRewrite(
            "conv_bn_act", "fused_conv_bn_act", block.idx, op_idxs,
            vars=(op.inputs["Input"][0], op.inputs["Filter"][0], scale,
                  bias),
            predicted=predicted,
            note="%s epilogue%s%s; f32 XLA-composite path bit-exact, "
                 "Pallas path ~1e-6; AMP sandwich lets XLA reassociate "
                 "the BN scale/bias grad reductions (~1e-4 rel, "
                 "documented)" % (
                     act_name,
                     " +AMP cast sandwich" if cast_in is not None else "",
                     "" if calibrated else " (uncalibrated gate)"),
            inserted=len(replacements))
        match = {"replacements": replacements, "removals": removals,
                 "rewrite": rewrite}
        if dry_run:
            report.record(rewrite)
            continue
        return match
    return None


# ---------------------------------------------------------------------------
# family: embedding gather  (device-side lookup_table)
# ---------------------------------------------------------------------------

_LOOKUP_OP_TYPES = ("lookup_table", "lookup_table_v2", "embedding",
                    "lookup_sparse_table")


def _find_embedding_gather(view, report, dry_run=False):
    """lookup_table/embedding on a device-resident table ⇒
    ``fused_embedding_gather`` (the Pallas row-DMA gather kernel on
    TPU).  A 1:1 op-identity rewrite — semantics are value-preserving
    (ops/pallas/embedding.py) — so the gate is purely about whether the
    kernel can win: lane-aligned dim, slab big enough, calibration."""
    block = view.block
    for i, op in enumerate(block.ops):
        if op.type not in _LOOKUP_OP_TYPES or _is_grad_op(op):
            continue
        w = op.inputs.get("W", [None])[0]
        wv = view.var(w) if w else None
        if wv is None or not wv.persistable or wv.shape is None \
                or len(wv.shape) != 2:
            continue
        rows, dim = wv.shape
        if not all(isinstance(d, int) and d > 0 for d in (rows, dim)):
            continue
        out = op.outputs["Out"][0]
        t = view.twin(op, op.type + "_grad")
        if t is False:
            continue
        if dim % 128:
            report.skip(
                "embedding_gather", i, op.type,
                "table dim %d is not lane-aligned (128) — the Pallas "
                "row-DMA gather is ineligible and XLA's take is already "
                "optimal for this shape" % dim,
                key=op.attrs.get("__op_id__"))
            continue
        # the slab scales with the batch: resolve the dynamic batch dim
        # at a nominal 8 (batch=1 would gate out every per-example slab
        # whose real deployment batch is in the thousands)
        slab_bytes = _var_bytes(view, out, batch=8)
        factor, sig, calibrated = _calibration(
            "embedding_gather", rows=rows, dim=dim,
            dtype=str(wv.dtype))
        threshold = embed_fuse_min_bytes()
        if slab_bytes * factor < threshold:
            report.skip(
                "embedding_gather", i, op.type,
                "cost model: gathered slab is ~%d B, below the %d B "
                "gate (calibration x%.2f%s)" % (
                    int(slab_bytes * factor), threshold, factor,
                    "" if calibrated else
                    " — uncalibrated: no autotune cache entry for %r "
                    "yet; a silicon sweep (paddle_tpu.autotune.sweep) "
                    "re-decides this gate" % sig),
                key=op.attrs.get("__op_id__"))
            continue
        fattrs = {k: v for k, v in op.attrs.items()
                  if not k.startswith("__") and k != "op_namescope"}
        fused = _new_op(None if dry_run else block,
                        "fused_embedding_gather",
                        {"W": list(op.inputs["W"]),
                         "Ids": list(op.inputs["Ids"])},
                        {"Out": [out]}, fattrs)
        replacements = {i: fused}
        removals = set()
        op_idxs = [i]
        if t is not None:
            g_ins = {"W": list(op.inputs["W"]),
                     "Ids": list(op.inputs["Ids"]),
                     "Out": [out],
                     "Out@GRAD": list(t[1].inputs.get(
                         "Out@GRAD", [EMPTY_VAR_NAME]))}
            g_outs = {"W@GRAD": [_grad_out(t[1], "W@GRAD")]}
            gfused = _new_op(None if dry_run else block,
                             "fused_embedding_gather_grad", g_ins,
                             g_outs, _grad_attrs(fused))
            replacements[t[0]] = gfused
            op_idxs.append(t[0])
        predicted = {
            "device_gather_bytes": slab_bytes,
            "calibration": factor,
            "ops_removed": 0,
        }
        rewrite = FusionRewrite(
            "embedding_gather", "fused_embedding_gather", block.idx,
            sorted(op_idxs), vars=(w,), predicted=predicted,
            note="value-preserving kernel dispatch (V=%d, D=%d)%s"
                 % (rows, dim,
                    "" if calibrated else " (uncalibrated gate)"),
            inserted=len(replacements))
        match = {"replacements": replacements, "removals": removals,
                 "rewrite": rewrite}
        if dry_run:
            report.record(rewrite)
            continue
        return match
    return None


# ---------------------------------------------------------------------------
# family: multi-tensor optimizer update  (fuse_all_optimizer_ops)
# ---------------------------------------------------------------------------

_OPT_SLOTS = {
    "adam": (("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
              "Beta2Pow"),
             ("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
              "Beta2PowOut")),
    "sgd": (("Param", "Grad"), ("ParamOut",)),
}


def _opt_key(view, op):
    if op.type not in _OPT_SLOTS or _is_grad_op(op):
        return None
    pname = op.inputs.get("Param", [None])[0]
    pv = view.var(pname) if pname else None
    if pv is None or pv.shape is None:
        return None
    # row-sharded tables / TP-sharded weights stay unfused: the concat
    # would force XLA to re-gather them (same guard as _fuse_adam_ops)
    if getattr(pv, "_is_distributed", False) \
            or getattr(pv, "shard_spec", None):
        return None
    gname = op.inputs.get("Grad", [None])[0]
    gv = view.var(gname) if gname else None
    key = (op.type, str(pv.dtype),
           str(gv.dtype) if gv is not None else str(pv.dtype),
           tuple(op.inputs.get("LearningRate", [])))
    if op.type == "adam":
        key += (op.attrs.get("beta1", 0.9), op.attrs.get("beta2", 0.999),
                op.attrs.get("epsilon", 1e-8))
    return key


def _find_optimizer(view, report, dry_run=False):
    block = view.block
    runs = []
    cur, cur_key = [], None
    for i, op in enumerate(block.ops):
        key = _opt_key(view, op)
        if key is not None and key == cur_key:
            cur.append((i, op))
            continue
        if len(cur) >= 2:
            runs.append((cur_key, cur))
        cur, cur_key = ([(i, op)], key) if key is not None else ([], None)
    if len(cur) >= 2:
        runs.append((cur_key, cur))

    matches = []
    for key, members in runs:
        if any(view.idx_of(o) != i for i, o in members):
            continue
        op_type = key[0]
        dt_bytes = dtype_bytes(key[1])
        total = sum(
            (_numel(view.var(o.inputs["Param"][0]).shape) or 0)
            for _, o in members)
        # cost gate (the r04 hardware A/B, BENCH_r04): the flat-stream
        # concat+split reads and writes every member through fp32
        # copies, so the fused op pays ~(n_in + n_out) extra stream
        # round-trips on top of the update's own bytes.  Benefit: each
        # member no longer pays a separate kernel launch/ramp, priced
        # at PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES of HBM-equivalent.
        n_streams = 7 if op_type == "adam" else 3
        extra_bytes = n_streams * total * max(dt_bytes, 4)
        benefit = (len(members) - 1) * optimizer_fuse_overhead_bytes()
        first_idx = members[0][0]
        if benefit <= extra_bytes:
            report.skip(
                "optimizer", first_idx, op_type,
                "cost model: flat-stream concat/split would add ~%d MB "
                "of HBM traffic vs ~%d MB of launch savings for %d "
                "params (the r04 A/B regressed MFU 0.42->0.30 fusing "
                "BERT-scale groups)" % (
                    extra_bytes >> 20, benefit >> 20, len(members)),
                key=members[0][1].attrs.get("__op_id__"))
            continue
        in_slots, out_slots = _OPT_SLOTS[op_type]
        ins = {"LearningRate": list(
            members[0][1].inputs.get("LearningRate", []))}
        for s in in_slots:
            ins[s] = [o.inputs[s][0] for _, o in members]
        outs = {s: [o.outputs[s][0] for _, o in members]
                for s in out_slots}
        attrs = {k: v for k, v in members[0][1].attrs.items()
                 if not k.startswith("__")}
        attrs["op_role"] = "optimize"
        fused = _new_op(None if dry_run else block, "fused_" + op_type, ins, outs, attrs)
        predicted = {
            "ops_removed": len(members) - 1,
            "hbm_bytes_added": extra_bytes,
            "launch_bytes_saved": benefit,
        }
        rewrite = FusionRewrite(
            "optimizer", "fused_" + op_type, block.idx,
            [i for i, _ in members],
            vars=tuple(ins["Param"]), predicted=predicted,
            note="bit-exact multi-tensor update (%d params, %d elems)"
                 % (len(members), total))
        matches.append({
            "replacements": {first_idx: fused},
            "removals": {i for i, _ in members[1:]},
            "rewrite": rewrite,
        })
    if dry_run:
        for m in matches:
            report.record(m["rewrite"])
        return None
    return matches[0] if matches else None


# ---------------------------------------------------------------------------
# family: bucketed gradient allreduce  (fuse_all_reduce_ops)
# ---------------------------------------------------------------------------

def _find_allreduce(view, report, dry_run=False):
    from .defuse import resolve_sub_block, sub_block_reads_recursive

    block = view.block
    groups = {}
    for i, op in enumerate(block.ops):
        if op.type != "c_allreduce_sum":
            continue
        x = op.inputs.get("X", [None])
        o = op.outputs.get("Out", [None])
        if len(x) != 1 or len(o) != 1 or x[0] != o[0] or x[0] is None:
            continue  # only the in-place grad-allreduce shape buckets
        nbytes = _var_bytes(view, x[0])
        if not nbytes:
            continue
        key = (op.attrs.get("ring_id"), op.attrs.get("pre_scale"),
               str(view.var(x[0]).dtype))
        groups.setdefault(key, []).append((i, op, nbytes))

    cap = int(allreduce_bucket_mb(block.program) * (1 << 20))
    # quantized-collective engagement: the planner's _quant_buckets mark
    # (or the env override) names the per-bucket byte threshold; None =
    # quant off for this program → plain bf16 coalescing only
    from ..quant.blockwise import quant_block as _quant_block
    from ..quant.collective import (quant_min_bytes as _quant_min,
                                    quantized_wire_bytes)

    qmin = _quant_min(block.program)
    qblock = _quant_block()
    matches = []
    for key, members in sorted(groups.items(),
                               key=lambda kv: kv[1][0][0]):
        # split into size-capped buckets, in program order
        buckets = []
        cur, cur_bytes = [], 0
        for i, op, nbytes in members:
            if cur and cur_bytes + nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append((i, op, nbytes))
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
        for bucket in buckets:
            # a quantizable bucket engages at ANY member count (a lone
            # big grad still wins the byte cut); without quant a
            # single-member bucket has nothing to coalesce
            quantizable = (qmin is not None
                           and key[2] in ("float32", "bfloat16")
                           and sum(b for _, _, b in bucket) >= qmin)
            if len(bucket) < 2 and not quantizable:
                continue  # nothing to coalesce; no advisory noise
            flush_idx = bucket[-1][0]
            member_ids = {id(op) for _, op, _ in bucket}
            # safety: coalescing delays each member's reduction to the
            # flush site — no op in between may read or write the grad
            # (the optimizer consumes it later; a clip/scale in between
            # would read the un-reduced value under shard_map)
            safe = []
            for i, op, nbytes in bucket:
                g = op.inputs["X"][0]
                ok = True
                for j in range(i + 1, flush_idx + 1):
                    other = block.ops[j]
                    if id(other) in member_ids:
                        continue
                    if g in other.input_arg_names \
                            or g in other.output_arg_names:
                        ok = False
                        break
                    # closure reads never show on input slots: a
                    # while/conditional body capturing the grad in the
                    # window would see the un-reduced local value
                    sub = resolve_sub_block(view.program, other,
                                            host_block_idx=block.idx)
                    if sub is not None and g in sub_block_reads_recursive(
                            view.program, sub):
                        ok = False
                        break
                if ok:
                    safe.append((i, op, nbytes))
                else:
                    report.skip(
                        "allreduce", i, op.type,
                        "grad %r is read/written between its allreduce "
                        "and the bucket flush site — stays unfused" % g,
                        key=op.attrs.get("__op_id__"))
            total = sum(b for _, _, b in safe)
            quant = (qmin is not None
                     and key[2] in ("float32", "bfloat16")
                     and total >= qmin)
            if len(safe) < (1 if quant else 2):
                continue
            names = [op.inputs["X"][0] for _, op, _ in safe]
            attrs = {"ring_id": key[0], "op_role": "backward"}
            if key[1]:
                attrs["pre_scale"] = key[1]
            if quant:
                attrs["quant_block"] = qblock
            fused_type = "c_allreduce_quant" if quant \
                else "c_fused_allreduce_sum"
            fused = _new_op(None if dry_run else block, fused_type,
                            {"X": list(names)}, {"Out": list(names)},
                            attrs)
            if quant:
                numel = total // max(dtype_bytes(key[2]), 1)
                wire, dense = quantized_wire_bytes(
                    numel, 2, block=qblock, dtype_bytes=dtype_bytes(key[2]))
                predicted = {
                    "collectives_removed": len(safe) - 1,
                    "ici_bytes_saved": dense - wire,
                    "quant_block": qblock,
                    "bucket_mb_cap": allreduce_bucket_mb(block.program),
                }
                note = ("ring %r; int8 wire %d -> %d bytes, "
                        "%d launches -> 1"
                        % (key[0], dense, wire, len(safe)))
            else:
                predicted = {
                    "collectives_removed": len(safe) - 1,
                    "ici_bytes_unchanged": total,
                    "bucket_mb_cap": allreduce_bucket_mb(block.program),
                }
                note = ("ring %r; ICI volume unchanged, %d launches -> 1"
                        % (key[0], len(safe)))
            rewrite = FusionRewrite(
                "allreduce", fused_type, block.idx,
                [i for i, _, _ in safe], vars=tuple(names),
                predicted=predicted, note=note)
            matches.append({
                "replacements": {safe[-1][0]: fused},
                "removals": {i for i, _, _ in safe[:-1]},
                "rewrite": rewrite,
            })
    if dry_run:
        for m in matches:
            report.record(m["rewrite"])
        return None
    return matches[0] if matches else None


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

_FAMILIES = (
    ("attention", "fuse_attention", _find_attention),
    ("conv_bn_act", "fuse_conv_bn_act", _find_conv_bn_act),
    ("softmax_xent", "fuse_softmax_xent", _find_softmax_xent),
    ("dropout_add_ln", "fuse_elewise", _find_dropout_add_ln),
    ("bias_act", "fuse_elewise", _find_bias_act),
    ("embedding_gather", "fuse_embedding_gather", _find_embedding_gather),
    ("optimizer", "fuse_optimizer", _find_optimizer),
    ("allreduce", "fuse_allreduce", _find_allreduce),
)

_MAX_REWRITES = 10000  # runaway-loop backstop
_FUSION_CACHE_CAP = 16  # resolved-clone cache entries per program


def _run_family(view, find, report):
    # re-scans after an applied rewrite re-encounter still-gated sites;
    # FusionReport.skip dedupes them by anchor-op identity
    applied = 0
    while applied < _MAX_REWRITES:
        match = find(view, report)
        if match is None:
            return applied
        _replace_ops(view.block, match["replacements"],
                     match["removals"])
        report.record(match["rewrite"])
        view.refresh()
        applied += 1
    return applied


def apply_fusion_passes(program, config=None, targets=(), verify=None,
                        baseline=None):
    """Run the fusion pipeline over ``program`` IN PLACE; returns the
    :class:`FusionReport`.  Each family is bracketed by the verifier
    when pass verification is enabled (on in tests) so a bad rewrite is
    named instead of surfacing as an opaque trace error.

    The bracket is BASELINE-AWARE: only errors a fusion pass *introduces*
    fail it.  Programs can legitimately arrive with pre-existing
    ERROR-severity metadata drift (e.g. the AMP bf16 rewrite flips var
    dtypes without re-running inference on every recorded shape) that
    the executor tolerates — a rewrite pass must not be blamed for it."""
    config = config or FusionConfig.default()
    report = FusionReport(config)
    if not config.enabled:
        return report
    if verify is None:
        from .verifier import pass_verification_enabled

        verify = pass_verification_enabled()
    view = _GlobalView(program, targets)

    if verify and baseline is None:
        baseline = _error_signatures(program, view.targets)
    for family, flag, find in _FAMILIES:
        if not getattr(config, flag):
            continue
        n = _run_family(view, find, report)
        if n and verify:
            _assert_no_new_errors(program, view.targets, baseline,
                                  "after fuse_%s_pass" % family)
    return report


# advisory-only checks skipped inside the pass brackets: the bracket
# gates on ERROR findings only, and fusible-pattern-not-fused re-runs
# every matcher (O(families x ops) per verify) just to produce INFO
# lines the bracket would filter out anyway
_BRACKET_EXCLUDE = ("fusible-pattern-not-fused", "unreferenced-op",
                    "resilience-finite-guard",
                    "executor-host-sync-in-loop", "sync-in-hot-loop",
                    "quantizable-bucket-not-quantized",
                    "collective-crosses-slow-tier",
                    "overlap-opportunity-unexploited")


# the in-flight depth the bracket's race checks assume: a fusion
# rewrite must be safe for the async serving/training paths whatever
# depth the caller later picks, so the bracket models the overlapped
# case (K=2) even for a program that will run sequentially —
# baseline-aware diffing means pre-existing races are never blamed on
# the pass, only INTRODUCED ones fail it
_BRACKET_MAX_IN_FLIGHT = 2


def _finding_signature(d):
    """Baseline-diff key for one ERROR finding.  Op indices are
    deliberately excluded so removing ops ahead of a pre-existing
    finding does not make it look new; race findings also drop the
    message, which names the writing op's TYPE — rewriting ``sgd`` into
    ``fused_sgd`` must not make a pre-existing race look introduced."""
    from .concurrency import RACE_CHECK_IDS

    if d.check in RACE_CHECK_IDS:
        return (d.check, d.var_names)
    return (d.check, d.message, d.var_names)


def _error_signatures(program, targets):
    """Signatures of every ERROR finding (see
    :func:`_finding_signature`)."""
    from .diagnostics import Severity
    from .verifier import verify_program

    return {
        _finding_signature(d)
        for d in verify_program(program, targets=list(targets),
                                exclude=_BRACKET_EXCLUDE,
                                max_in_flight=_BRACKET_MAX_IN_FLIGHT)
        if d.severity >= Severity.ERROR
    }


def _assert_no_new_errors(program, targets, baseline, context):
    from .diagnostics import Severity, format_diagnostics
    from .verifier import VerifyError, verify_program

    diags = verify_program(program, targets=list(targets),
                           exclude=_BRACKET_EXCLUDE,
                           max_in_flight=_BRACKET_MAX_IN_FLIGHT)
    new = [d for d in diags
           if d.severity >= Severity.ERROR
           and _finding_signature(d) not in baseline]
    if new:
        raise VerifyError(
            format_diagnostics(
                new, header="program failed verification (%s):" % context),
            diagnostics=new)


def scan_fusible_patterns(program, config=None, targets=()):
    """Dry-run the matchers without mutating the program — the engine
    behind the ``fusible-pattern-not-fused`` advisory check.  Returns a
    :class:`FusionReport` whose ``applied`` lists patterns that WOULD
    fuse and ``skipped`` the matched-but-gated-out ones."""
    config = config or FusionConfig.default()
    report = FusionReport(config)
    view = _GlobalView(program, targets)
    for family, flag, find in _FAMILIES:
        if not getattr(config, flag):
            continue
        find(view, report, dry_run=True)
    return report


# registered pass-pipeline entry points (analysis.register_pass idiom);
# each runs ONE family so a PassBuilder can compose them individually
def _make_pass(family, flag, find):
    def _pass(program, scope=None, targets=None):
        config = FusionConfig.default()
        if not config.enabled or not getattr(config, flag):
            return program
        report = FusionReport(config)
        view = _GlobalView(program, targets or ())
        _run_family(view, find, report)
        return program

    _pass.__name__ = "fuse_%s_pass" % family
    return _pass


def _register_passes():
    from ..analysis import register_pass

    for family, flag, find in _FAMILIES:
        register_pass("fuse_%s_pass" % family)(
            _make_pass(family, flag, find))


_register_passes()


def _run_hierarchy_pass(clone, targets, baseline=None):
    """Run the hierarchical-collective decomposition on the resolved
    clone after the fusion pipeline (it decomposes the bucketed
    collectives fusion just emitted) and BEFORE the overlap scheduler
    (the remaining flat buckets can still split into start/wait pairs;
    the hierarchical hops themselves opt out of overlap).  Bracketed by
    the verifier like a fusion family; returns whether any bucket
    decomposed — the resolve cache must keep the clone for a
    hierarchy-only rewrite."""
    from .hierarchy import apply_hierarchy_pass, hierarchy_enabled

    if not hierarchy_enabled(clone):
        clone._hierarchy_report = None
        return False
    from .verifier import pass_verification_enabled

    verify = pass_verification_enabled()
    if verify and baseline is None:
        baseline = _error_signatures(clone, set(targets))
    applied = apply_hierarchy_pass(clone, targets=targets)
    if applied and verify:
        _assert_no_new_errors(clone, set(targets), baseline,
                              "after hierarchy_pass")
    return applied


def _run_overlap_pass(clone, targets, baseline=None):
    """Run the overlap scheduler on the resolved clone after the fusion
    pipeline (it splits the bucketed collectives fusion just emitted),
    bracketed by the verifier exactly like a fusion family.  Returns
    whether any bucket was actually split — the resolve cache must keep
    the clone even when no FUSION family fired, or the overlap-only
    rewrite would be thrown away.

    ``baseline`` is the pre-fusion error-signature set the fusion
    pipeline already computed; reusing it keeps the bracket one verify
    per resolve instead of two (each family that fired already asserted
    it introduced nothing over the same baseline)."""
    from .overlap import apply_overlap_pass, overlap_enabled

    if not overlap_enabled(clone):
        return False
    from .verifier import pass_verification_enabled

    verify = pass_verification_enabled()
    if verify and baseline is None:
        baseline = _error_signatures(clone, set(targets))
    ov = apply_overlap_pass(clone, targets=targets)
    if ov.applied and verify:
        _assert_no_new_errors(clone, set(targets), baseline,
                              "after overlap_schedule_pass")
    return bool(ov.applied)


# ---------------------------------------------------------------------------
# executor entry: fused-clone resolution + caching
# ---------------------------------------------------------------------------

def resolve_fused_program(program, config=None, targets=()):
    """Resolve the fusion-rewritten twin of ``program`` for execution.

    Returns ``(program_to_run, FusionReport)``.  The rewritten program
    is a CLONE (the user's program object is never mutated — fusion-off
    runs stay bit-exact with the pre-fusion paths), cached on the
    original keyed by (config signature, program version, fetch set), so
    the executor's jit cache — which keys on the resolved program's
    identity/version plus the fusion signature — compiles each fusion
    config exactly once.  Cloning preserves ``__op_id__``s, so the
    deterministic RNG streams of UNtouched ops (dropout elsewhere in the
    model) are identical with fusion on and off.
    """
    config = config or FusionConfig.default()
    if getattr(program, "_fusion_applied", False):
        return program, getattr(program, "_fusion_report", None) \
            or FusionReport(config)
    if not config.enabled:
        report = FusionReport(config)
        return program, report
    from ..observability import runtime as _obs

    tkey = tuple(sorted({getattr(t, "name", t) for t in (targets or ())}))
    key = (config.signature(program), program._version, tkey)
    cache = program.__dict__.setdefault("_fusion_cache", {})
    hit = cache.get(key)
    if hit is not None:
        _obs.record_fusion_resolve(True)
        fused, report = hit
        return (fused if fused is not None else program), report
    _obs.record_fusion_resolve(False)
    # drop entries of stale versions so a mutated-every-step program
    # cannot leak clones
    for k in [k for k in cache if k[1] != program._version]:
        del cache[k]
    # and cap distinct (config, fetch-set) entries: a serving loop
    # fetching per-request variable subsets must not accumulate
    # unbounded program clones (FIFO — dicts preserve insertion order)
    while len(cache) >= _FUSION_CACHE_CAP:
        del cache[next(iter(cache))]
    clone = program.clone()
    for mark in _PROGRAM_MARKS:
        if hasattr(program, mark):
            setattr(clone, mark, getattr(program, mark))
    _copy_var_marks(program, clone)
    clone._fusion_applied = True
    from .verifier import pass_verification_enabled

    baseline = None
    if pass_verification_enabled():
        # one pre-rewrite verify shared by the fusion families AND the
        # overlap pass bracket (each asserts against the same baseline)
        baseline = _error_signatures(clone, set(tkey))
    report = apply_fusion_passes(clone, config, targets=tkey,
                                 baseline=baseline)
    hier_applied = _run_hierarchy_pass(clone, tkey, baseline=baseline)
    overlap_applied = _run_overlap_pass(clone, tkey, baseline=baseline)
    if not report.applied and not overlap_applied and not hier_applied:
        cache[key] = (None, report)
        return program, report
    clone._fusion_sig = config.signature(program)
    clone._fusion_report = report
    cache[key] = (clone, report)
    try:
        from ..observability import journal as _journal

        _journal.emit(
            "fusion-applied",
            applied={name: count for name, count
                     in sorted(report.applied.items())}
            if isinstance(report.applied, dict)
            else list(report.applied),
            signature=config.signature(program))
    except Exception:  # noqa: BLE001 - telemetry never breaks resolve
        pass
    return clone, report
