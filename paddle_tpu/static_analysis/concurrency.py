"""Static concurrency analysis over the Program IR (ISSUE 10).

Everything PR-4 onward made fast is *overlap*: ``run_batches`` /
``run_async`` keep up to K steps in flight, ``DeviceFeedPipeline``
device-stages upcoming batches from a background thread, fetch results
ride lazy :class:`~paddle_tpu.pipeline.FetchHandle`\\ s that materialize
long after the step dispatched, and the jitted step donates its
read-write persistable buffers (``donate_argnums``) so XLA can update
params in place.  None of the PR-1/PR-3 passes reason about any of it.

This module adds the missing happens-before model.  Within one step,
program order gives happens-before; *across* the in-flight window there
is no ordering except the data dependency the donation chain creates —
so any buffer visible both to a pending consumer (an un-materialized
fetch handle, the prefetch thread's staging slot) and to a later
in-flight step's write/donate is a hazard.  Three analyses fall out:

**Race detection** (``race-inflight-write``, ``donated-buffer-live-read``)
    A persistable scope var that is both *written* by the step and
    *fetched* races under ``max_in_flight>1``: step N donates the very
    buffer step N-1's un-materialized handle still reads.  When the
    writer is an in-place/aliasing op (a fused multi-tensor optimizer's
    ``Param -> ParamOut``, an in-place collective), the fetched handle
    aliases the donated buffer directly — ``donated-buffer-live-read``.
    A program that overwrites one of its own fed data vars is the
    classic double-buffer feed overwrite: the prefetch thread stages the
    next batch into the same slot while this step's write is in flight.

**Scope isolation** (``scope-overlap``)
    Two programs sharing an Executor/predictor scope are proven to
    touch disjoint scope-variable footprints (writes of one disjoint
    from reads+writes of the other) — the precondition for multi-tenant
    serving and elastic re-transpile.  Shared read-only state (a frozen
    embedding) is allowed.

**Zero-sync certificate** (``sync-in-hot-loop``)
    A proof that the steady-state loop of a program contains no
    host-sync point: no host-IO op, no host-table per-step prefetch
    (``np.asarray`` on ids/grads), no per-run eager while trip-count
    probe.  The opt-in NaN step-guard's scalar flag is recorded as an
    *allowed* sync — guarded training pays it by design.  This upgrades
    the PR-4 ``executor-host-sync-in-loop`` advisory into a checkable
    contract (``PADDLE_TPU_STRICT_SYNC=1`` / the serving path promote
    the advisory itself to ERROR).

Surfaces: ``Program.analyze(concurrency=True, max_in_flight=K,
coresident=[...], certify_zero_sync=True)``, the four registered checks
(active only when an in-flight context exists, so plain ``lint()``
stays unchanged), ``python -m paddle_tpu.tools.analyze_program
--concurrency [--max-in-flight K] [--certify-zero-sync] [--coresident
P.json ...]``, and two gates: ``AnalysisPredictor.run_batches(...,
verify=True)`` and the fusion/planner rewrite brackets (a rewrite may
not introduce a race its input did not have).
"""

import os

from .checks import register_check
from .defuse import DefUseGraph
from .diagnostics import Diagnostic, Severity, format_diagnostics

__all__ = [
    "RACE_CHECK_IDS", "CONCURRENCY_CHECK_IDS",
    "ScopeFootprint", "scope_footprint", "prove_scope_isolation",
    "SyncPoint", "ZeroSyncCertificate", "certify_zero_sync",
    "ConcurrencyReport", "analyze_concurrency",
    "find_inflight_races", "find_overlap_window_races",
    "resolve_max_in_flight",
    "strict_sync_enabled", "race_signatures", "assert_no_new_races",
    "verify_async_hot_path",
]

#: the two race checks the rewrite brackets re-run
RACE_CHECK_IDS = ("race-inflight-write", "donated-buffer-live-read")

#: everything this module registers
CONCURRENCY_CHECK_IDS = RACE_CHECK_IDS + ("scope-overlap",
                                          "sync-in-hot-loop")


def _truthy(val):
    return str(val).strip().lower() not in ("0", "", "false", "off",
                                            "none")


def strict_sync_enabled(program=None):
    """Is the host-sync advisory promoted to a hard ERROR?  Env wins
    (``PADDLE_TPU_STRICT_SYNC=1``); a program that has entered the
    serving hot loop (``run_batches`` stamps ``_serving_hot_loop``) is
    strict by definition — a per-step sync there is a throughput bug,
    not a style note."""
    env = os.environ.get("PADDLE_TPU_STRICT_SYNC")
    if env is not None and _truthy(env):
        return True
    return bool(getattr(program, "_serving_hot_loop", False))


def resolve_max_in_flight(program=None, explicit=None, default=1):
    """The K the happens-before model assumes: an explicit argument,
    else the ``program._max_in_flight`` mark (``run_batches`` stamps
    it), else ``PADDLE_TPU_MAX_IN_FLIGHT``, else ``default``.  K<=1
    means sequential execution — every overlap window is empty and the
    race checks are vacuously silent."""
    if explicit is not None:
        return max(int(explicit), 1)
    mark = getattr(program, "_max_in_flight", None)
    if mark:
        try:
            return max(int(mark), 1)
        except (TypeError, ValueError):
            pass
    env = os.environ.get("PADDLE_TPU_MAX_IN_FLIGHT")
    if env:
        try:
            return max(int(env), 1)
        except ValueError:
            pass
    return max(int(default), 1)


# ---------------------------------------------------------------------------
# scope footprints + isolation proof
# ---------------------------------------------------------------------------

class ScopeFootprint:
    """The scope-variable footprint of one program: which persistable
    (scope-resident) names it reads and which it writes.  Disjointness
    of footprints is what makes two programs safe to run against one
    shared Executor scope with steps of both in flight."""

    __slots__ = ("reads", "writes")

    def __init__(self, reads=(), writes=()):
        self.reads = frozenset(reads)
        self.writes = frozenset(writes)

    def conflicts(self, other):
        """Scope vars that break isolation: any var one program writes
        while the other touches it at all.  Shared read-only state is
        fine (both only read it)."""
        return ((self.writes & (other.reads | other.writes))
                | (other.writes & self.reads))

    def isolated_from(self, other):
        return not self.conflicts(other)

    def to_dict(self):
        return {"reads": sorted(self.reads),
                "writes": sorted(self.writes)}

    def __repr__(self):
        return "ScopeFootprint(%d read(s), %d write(s))" % (
            len(self.reads), len(self.writes))


def _persistable_name(program, block_idx, name):
    b = program.block(block_idx) if block_idx < program.num_blocks \
        else program.global_block()
    v = b._find_var_recursive(name)
    return v is not None and v.persistable


def scope_footprint(program, graph=None):
    """Compute the program's :class:`ScopeFootprint` from the def-use
    graph (all walked blocks, sub-blocks included)."""
    graph = graph or DefUseGraph(program)
    reads, writes = set(), set()
    for name, sites in graph.uses.items():
        if any(_persistable_name(program, s.block_idx, name)
               for s in sites):
            reads.add(name)
    for name, sites in graph.defs.items():
        if any(s.op.type != "feed"
               and _persistable_name(program, s.block_idx, name)
               for s in sites):
            writes.add(name)
    return ScopeFootprint(reads, writes)


def prove_scope_isolation(programs, labels=None):
    """Prove N programs sharing one Executor/predictor scope touch
    disjoint scope-variable footprints.

    ``programs``: list of Programs; ``labels``: optional display names
    (default ``program[i]``).  Returns ``(footprints, diagnostics)`` —
    an empty diagnostics list IS the proof; each ``scope-overlap``
    ERROR names the offending pair and the conflicting vars."""
    labels = list(labels or [])
    while len(labels) < len(programs):
        labels.append("program[%d]" % len(labels))
    prints = [scope_footprint(p) for p in programs]
    # declared KV-block handoffs: a prefill tenant fills cache blocks a
    # decode tenant then owns (ownership transfer of block-table
    # entries, no copy).  The written overlap is intentional and
    # scheduler-serialized per block — downgraded to INFO when BOTH
    # programs declare the var, so an accidental collision on one side
    # still fails the proof
    declared = [frozenset(getattr(p, "_kv_handoff_vars", ()) or ())
                for p in programs]
    diags = []
    for i in range(len(prints)):
        for j in range(i + 1, len(prints)):
            conflicts = prints[i].conflicts(prints[j])
            handoff = sorted(conflicts & declared[i] & declared[j])
            if handoff:
                shown = ", ".join(handoff[:8]) + (
                    ", ... (%d total)" % len(handoff)
                    if len(handoff) > 8 else "")
                diags.append(Diagnostic(
                    "scope-handoff", Severity.INFO,
                    "%s and %s share written KV-pool vars by declared "
                    "block handoff: %s — ownership of block-table "
                    "entries transfers prefill -> decode without a "
                    "copy; block-level disjointness is the allocator's "
                    "no-double-assign invariant, not a scope-name "
                    "property" % (labels[i], labels[j], shown),
                    var_names=tuple(handoff),
                    hint="the paging property test "
                         "(admit/generate/retire churn) is the "
                         "correctness carrier for this allowance"))
            bad = sorted(conflicts - (declared[i] & declared[j]))
            if bad:
                shown = ", ".join(bad[:8]) + (
                    ", ... (%d total)" % len(bad) if len(bad) > 8
                    else "")
                diags.append(Diagnostic(
                    "scope-overlap", Severity.ERROR,
                    "%s and %s share a written scope var: %s — running "
                    "both against one Executor scope lets an in-flight "
                    "step of one donate/overwrite state the other is "
                    "reading" % (labels[i], labels[j], shown),
                    var_names=tuple(bad),
                    hint="give each program its own Scope "
                         "(scope_guard), or rename/split the shared "
                         "persistables; shared READ-ONLY state is "
                         "allowed"))
                continue
            shared_ro = sorted((prints[i].reads & prints[j].reads)
                               - prints[i].writes - prints[j].writes)
            if shared_ro:
                shown = ", ".join(shared_ro[:8]) + (
                    ", ... (%d total)" % len(shared_ro)
                    if len(shared_ro) > 8 else "")
                diags.append(Diagnostic(
                    "scope-overlap", Severity.WARNING,
                    "%s and %s read identically-named persistables: %s "
                    "— safe only if both programs intend to SHARE that "
                    "state; two independent models colliding on default "
                    "names will silently read whichever loaded last"
                    % (labels[i], labels[j], shown),
                    var_names=tuple(shared_ro),
                    hint="intended sharing (e.g. a common embedding "
                         "table) is fine; otherwise load each model "
                         "under its own Scope or unique_name "
                         "namespace"))
    return prints, diags


# ---------------------------------------------------------------------------
# in-flight race detection
# ---------------------------------------------------------------------------

def _fetch_names(program, targets, graph):
    """Explicit fetch targets plus inputs of any ``fetch`` ops a saved
    model carries — both produce pending FetchHandles at run time."""
    names = []
    for t in targets or ():
        names.append(t.name if hasattr(t, "name") else str(t))
    for _, _, op in graph.order:
        if op.type == "fetch":
            names.extend(op.input_arg_names)
    # de-dup, preserve order
    seen = set()
    out = []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return out


def find_inflight_races(program, targets=(), max_in_flight=None,
                        graph=None):
    """The happens-before race scan.  Returns Diagnostics (ERROR) for
    every pair of operations that can overlap under ``max_in_flight>1``
    and touch the same buffer without an ordering edge:

    * ``donated-buffer-live-read`` — a fetch target whose last writer
      ALIASES it (the var is also an input of the writing op: a fused /
      plain optimizer update, an in-place collective).  The pending
      handle of step N-1 holds exactly the buffer step N donates.
    * ``race-inflight-write`` — a fetched persistable written by a
      non-aliasing op (step N's scope write-back + donation vs the
      pending read), or an op overwriting a fed data var (write-write
      with the ``DeviceFeedPipeline`` prefetch thread's staging slot —
      the double-buffer feed overwrite).

    * ``race-inflight-write`` (overlap window) — a write to a bucket
      member between its ``c_allreduce_start`` and ``c_allreduce_wait``
      (:func:`find_overlap_window_races`).  Unlike the cross-step
      hazards this is K-INDEPENDENT: the ring transfer is in flight
      within one step, so even sequential execution races.

    K<=1 (sequential) proves every cross-step window empty: returns
    only the overlap-window findings.
    """
    k = resolve_max_in_flight(program, explicit=max_in_flight)
    # the overlap scheduler's start→wait windows race at ANY depth —
    # checked before the sequential early-out on purpose
    diags = find_overlap_window_races(program)
    if k <= 1:
        return diags
    graph = graph or DefUseGraph(program)

    def _mk(check, message, site, var, hint):
        return Diagnostic(
            check, Severity.ERROR, message,
            block_idx=site.block_idx, op_idx=site.op_idx,
            op_type=site.op.type,
            op_id=site.op.attrs.get("__op_id__"),
            var_names=(var,), hint=hint)

    # (1) pending fetch handle vs in-flight write/donate
    for name in _fetch_names(program, targets, graph):
        sites = [s for s in graph.defs.get(name, ())
                 if s.op.type != "feed"]
        if not sites:
            continue
        writer = sites[-1]
        persistable = _persistable_name(program, writer.block_idx, name)
        if name in writer.op.input_arg_names and persistable:
            diags.append(_mk(
                "donated-buffer-live-read",
                "fetch target %r aliases the buffer op %r updates in "
                "place: with max_in_flight=%d the jitted step donates "
                "its read-write persistables, so step N invalidates "
                "the very buffer step N-1's un-materialized "
                "FetchHandle still reads"
                % (name, writer.op.type, k),
                writer, name,
                hint="materialize the handle before dispatching the "
                     "next step, fetch a copy (assign to a fresh var), "
                     "or drop max_in_flight to 1"))
        elif persistable:
            diags.append(_mk(
                "race-inflight-write",
                "persistable %r is fetched AND written by op %r: with "
                "max_in_flight=%d, step N's scope write-back (donated "
                "buffer) overlaps step N-1's pending FetchHandle read "
                "of the same scope var"
                % (name, writer.op.type, k),
                writer, name,
                hint="fetch a non-persistable copy of the value, or "
                     "materialize each step's handles before the next "
                     "dispatch"))

    # (2) write-write with the prefetch thread: overwriting a fed slot
    for block_idx, op_idx, op in graph.order:
        if op.type == "feed":
            continue
        for name in op.output_arg_names:
            b = program.block(block_idx)
            v = b._find_var_recursive(name)
            if v is None or not getattr(v, "is_data", False):
                continue
            diags.append(Diagnostic(
                "race-inflight-write", Severity.ERROR,
                "op %r overwrites fed data var %r — the double-buffer "
                "feed overwrite: with max_in_flight=%d the "
                "DeviceFeedPipeline prefetch thread stages the next "
                "batch into this slot while the in-flight step's "
                "write is still dispatched"
                % (op.type, name, k),
                block_idx=block_idx, op_idx=op_idx, op_type=op.type,
                op_id=op.attrs.get("__op_id__"), var_names=(name,),
                hint="write results to a fresh var; feed slots belong "
                     "to the feed pipeline"))
    return diags


def find_overlap_window_races(program):
    """The overlap scheduler's in-flight window scan: between a
    ``c_allreduce_start`` and its ``c_allreduce_wait`` (paired by the
    ``overlap_bucket`` attr) the ring transfer holds the bucket members
    in flight — an op writing any member inside that window (output
    slot or sub-block closure write) clobbers the buffer the collective
    is still reducing.  ERROR per (window, writer, member).

    K-independent by design: this is intra-step overlap, not the
    cross-step pipelining :func:`find_inflight_races` models — the
    overlap pass's proof bracket reverts the bucket on any finding."""
    from .defuse import resolve_sub_block, sub_block_writes_recursive

    diags = []
    block = program.global_block()
    open_windows = {}   # bucket -> (start idx, member set)
    for idx, op in enumerate(block.ops):
        if op.type == "c_allreduce_start":
            b = op.attrs.get("overlap_bucket")
            if b is not None:
                open_windows[int(b)] = (
                    idx, frozenset(op.outputs.get("Out", ())))
            continue
        if op.type != "c_allreduce_wait":
            continue
        b = op.attrs.get("overlap_bucket")
        if b is None or int(b) not in open_windows:
            continue
        start_idx, members = open_windows.pop(int(b))
        for j in range(start_idx + 1, idx):
            other = block.ops[j]
            written = members.intersection(other.output_arg_names)
            sub = resolve_sub_block(program, other,
                                    host_block_idx=block.idx)
            if sub is not None:
                written = written | (
                    members
                    & set(sub_block_writes_recursive(program, sub)))
            for name in sorted(written):
                diags.append(Diagnostic(
                    "race-inflight-write", Severity.ERROR,
                    "op %r writes bucket member %r inside the overlap "
                    "window of bucket %d (start at op %d, wait at op "
                    "%d) — the in-flight ring transfer is still "
                    "reducing this buffer"
                    % (other.type, name, int(b), start_idx, idx),
                    block_idx=block.idx, op_idx=j, op_type=other.type,
                    op_id=other.attrs.get("__op_id__"),
                    var_names=(name,),
                    hint="let the overlap pass place the start after "
                         "the member's last def (it reverts the bucket "
                         "to the fused synchronous form when it "
                         "cannot), or write to a fresh var"))
    return diags


# ---------------------------------------------------------------------------
# zero-sync certificate
# ---------------------------------------------------------------------------

class SyncPoint:
    """One host-sync source in a hot loop: where it is, and which
    runtime API introduces the sync."""

    __slots__ = ("api", "reason", "block_idx", "op_idx", "op_type",
                 "var_names", "allowed")

    def __init__(self, api, reason, block_idx=None, op_idx=None,
                 op_type=None, var_names=(), allowed=False):
        self.api = api
        self.reason = reason
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.var_names = tuple(var_names)
        self.allowed = bool(allowed)

    def where(self):
        if self.block_idx is None:
            return "program-level"
        return "block %d op %d (%s)" % (self.block_idx, self.op_idx,
                                        self.op_type)

    def to_dict(self):
        return {"api": self.api, "reason": self.reason,
                "block_idx": self.block_idx, "op_idx": self.op_idx,
                "op_type": self.op_type,
                "var_names": list(self.var_names),
                "allowed": self.allowed}

    def __repr__(self):
        return "SyncPoint(%s, %s%s)" % (
            self.api, self.where(), ", allowed" if self.allowed else "")


class ZeroSyncCertificate:
    """The checkable contract: ``ok`` iff the steady-state loop of this
    program contains no host-sync point outside the explicitly allowed
    ones (today: the opt-in NaN step-guard's scalar flag)."""

    __slots__ = ("label", "violations", "allowed", "max_in_flight")

    def __init__(self, label, violations=(), allowed=(),
                 max_in_flight=1):
        self.label = label
        self.violations = list(violations)
        self.allowed = list(allowed)
        self.max_in_flight = max_in_flight

    @property
    def ok(self):
        return not self.violations

    def to_dict(self):
        return {"label": self.label, "ok": self.ok,
                "max_in_flight": self.max_in_flight,
                "violations": [s.to_dict() for s in self.violations],
                "allowed": [s.to_dict() for s in self.allowed]}

    def format(self):
        lines = ["zero-sync certificate for %s: %s"
                 % (self.label, "PASS" if self.ok else "FAIL")]
        for s in self.violations:
            lines.append("  SYNC %s — %s: %s"
                         % (s.where(), s.api, s.reason))
        for s in self.allowed:
            lines.append("  allowed %s — %s: %s"
                         % (s.where(), s.api, s.reason))
        if self.ok and not self.allowed:
            lines.append("  steady-state loop is one pure dispatch — "
                         "no D2H fetch, host-IO, or eager host probe")
        return "\n".join(lines)

    def __repr__(self):
        return "ZeroSyncCertificate(%s, ok=%s, %d violation(s))" % (
            self.label, self.ok, len(self.violations))


def certify_zero_sync(program, targets=(), graph=None, label=None,
                      max_in_flight=None):
    """Scan ``program`` for every construct that forces the Executor
    onto the host each step, and return the
    :class:`ZeroSyncCertificate`.  Sources modeled (each names the
    introducing API, so a FAIL is actionable):

    * host-IO ops (``save``/``load``/...) — ``Executor.run`` brackets
      the jitted step with ``ops.io_ops.run_host_io_block``;
    * host-resident embedding tables (``program._host_tables``) — the
      per-step prefetch/grad-push calls ``np.asarray`` on ids and slab
      grads;
    * an unbounded ``while_grad`` — ``Executor.run`` re-probes trip
      counts with an eager host loop before EVERY dispatch;
    * the NaN step-guard scalar flag — *allowed* (explicitly opted in
      via ``PADDLE_TPU_NAN_GUARD`` / ``program._nan_guard``).
    """
    from .cost import HOST_IO_OP_TYPES

    graph = graph or DefUseGraph(program)
    k = resolve_max_in_flight(program, explicit=max_in_flight)
    violations, allowed = [], []
    for block_idx, op_idx, op in graph.order:
        if op.type in HOST_IO_OP_TYPES:
            violations.append(SyncPoint(
                "Executor.run host-IO phase "
                "(ops.io_ops.run_host_io_block)",
                "host-IO op %r runs on the host around every jitted "
                "step — a full pipeline drain per call" % op.type,
                block_idx=block_idx, op_idx=op_idx, op_type=op.type,
                var_names=tuple(op.output_arg_names
                                or op.input_arg_names)))
        elif op.type == "while_grad" \
                and not op.attrs.get("max_trip_count"):
            violations.append(SyncPoint(
                "executor._probe_trip_counts (eager host probe)",
                "while_grad without max_trip_count makes Executor.run "
                "probe trip counts with an eager host loop before "
                "every dispatch",
                block_idx=block_idx, op_idx=op_idx, op_type=op.type))
    for spec in getattr(program, "_host_tables", None) or ():
        name = getattr(spec, "name", None) or str(spec)
        violations.append(SyncPoint(
            "host_table per-step prefetch/push (np.asarray on ids and "
            "slab grads)",
            "host-resident table %r bounces ids and gradients through "
            "the host every step" % name,
            var_names=(name,)))
    from ..resilience.guard import guard_enabled

    if guard_enabled(program):
        allowed.append(SyncPoint(
            "NaN step-guard finite flag (resilience.guard.record_step)",
            "opted-in scalar sync per step; skip bookkeeping must see "
            "the flag on the host", allowed=True))
    return ZeroSyncCertificate(
        label or getattr(program, "_name", None) or "program",
        violations, allowed, max_in_flight=k)


# ---------------------------------------------------------------------------
# registered checks (active only when an in-flight context exists, so
# the default lint battery is unchanged)
# ---------------------------------------------------------------------------

def _ctx_races(ctx):
    """Compute (and cache on the ctx) the race scan for this battery
    run — both race checks share one walk."""
    cached = getattr(ctx, "_inflight_races", None)
    if cached is None:
        cached = find_inflight_races(
            ctx.program, targets=ctx.targets,
            max_in_flight=getattr(ctx, "max_in_flight", None),
            graph=ctx.graph)
        ctx._inflight_races = cached
    return cached


@register_check("race-inflight-write")
def check_race_inflight_write(ctx):
    """Write-write / write-vs-pending-read races under
    ``max_in_flight>1`` (see :func:`find_inflight_races`)."""
    for d in _ctx_races(ctx):
        if d.check == "race-inflight-write":
            yield d


@register_check("donated-buffer-live-read")
def check_donated_buffer_live_read(ctx):
    """A pending FetchHandle aliasing a buffer a later in-flight step
    donates (see :func:`find_inflight_races`)."""
    for d in _ctx_races(ctx):
        if d.check == "donated-buffer-live-read":
            yield d


@register_check("scope-overlap")
def check_scope_overlap(ctx):
    """Scope-isolation proof against the coresident programs supplied
    via ``analyze(coresident=[...])`` / ``verify_program(coresident=
    ...)``; silent when the program runs alone."""
    coresident = getattr(ctx, "coresident", None)
    if not coresident:
        return
    programs = [ctx.program]
    labels = ["this program"]
    for i, entry in enumerate(coresident):
        if isinstance(entry, tuple):
            labels.append(str(entry[0]))
            programs.append(entry[1])
        else:
            labels.append("coresident[%d]" % i)
            programs.append(entry)
    _, diags = prove_scope_isolation(programs, labels)
    for d in diags:
        yield d


@register_check("sync-in-hot-loop")
def check_sync_in_hot_loop(ctx):
    """The zero-sync certificate as a lint check: every violating sync
    point is an ERROR naming the introducing op and API.  Runs when a
    certificate was requested (``analyze(certify_zero_sync=True)`` /
    ``--certify-zero-sync``) or the program is strict
    (``PADDLE_TPU_STRICT_SYNC=1`` / the serving hot loop)."""
    if not (getattr(ctx, "certify_zero_sync", False)
            or strict_sync_enabled(ctx.program)):
        return
    cert = certify_zero_sync(ctx.program, targets=ctx.targets,
                             graph=ctx.graph)
    for s in cert.violations:
        yield ctx.diag(
            "sync-in-hot-loop", Severity.ERROR,
            "host-sync point in the hot loop at %s — introduced by %s: "
            "%s" % (s.where(), s.api, s.reason),
            block_idx=s.block_idx, op_idx=s.op_idx,
            var_names=s.var_names,
            hint="the steady-state loop must stay one pure dispatch; "
                 "move the sync to step boundaries or a separate "
                 "program (certificate: analyze_program "
                 "--certify-zero-sync)")


# ---------------------------------------------------------------------------
# report driver + gates
# ---------------------------------------------------------------------------

class ConcurrencyReport:
    """What ``Program.analyze(concurrency=True)`` proved: the assumed
    in-flight depth, the race findings, the scope footprint (and
    isolation verdict when coresident programs were supplied), and the
    zero-sync certificate when requested."""

    __slots__ = ("max_in_flight", "races", "isolation", "footprint",
                 "certificate")

    def __init__(self, max_in_flight, races=(), isolation=(),
                 footprint=None, certificate=None):
        self.max_in_flight = max_in_flight
        self.races = list(races)
        self.isolation = list(isolation)
        self.footprint = footprint
        self.certificate = certificate

    @property
    def race_free(self):
        return not self.races

    @property
    def isolated(self):
        return not self.isolation

    def to_dict(self):
        return {
            "max_in_flight": self.max_in_flight,
            "race_free": self.race_free,
            "races": [d.to_dict() for d in self.races],
            "isolated": self.isolated,
            "scope_overlaps": [d.to_dict() for d in self.isolation],
            "footprint": self.footprint.to_dict()
            if self.footprint else None,
            "certificate": self.certificate.to_dict()
            if self.certificate else None,
        }

    def format(self):
        lines = ["concurrency (max_in_flight=%d): %s"
                 % (self.max_in_flight,
                    "race-free" if self.race_free
                    else "%d race(s)" % len(self.races))]
        if self.footprint is not None:
            lines.append("  scope footprint: %d read(s), %d write(s)"
                         % (len(self.footprint.reads),
                            len(self.footprint.writes)))
        if self.isolation:
            lines.append("  scope isolation: VIOLATED (%d overlap(s))"
                         % len(self.isolation))
        if self.certificate is not None:
            lines.append(self.certificate.format())
        return "\n".join(lines)

    def __repr__(self):
        return ("ConcurrencyReport(K=%d, race_free=%s, isolated=%s%s)"
                % (self.max_in_flight, self.race_free, self.isolated,
                   "" if self.certificate is None
                   else ", zero_sync=%s" % self.certificate.ok))


def analyze_concurrency(program, targets=(), max_in_flight=None,
                        coresident=None, certify=False, graph=None):
    """Standalone driver (``Program.analyze(concurrency=True)`` builds
    the same report through the shared check battery).  Assumes K=2
    when nothing specifies a depth — the async serving default — since
    a concurrency question about a sequential program is vacuous."""
    graph = graph or DefUseGraph(program)
    k = resolve_max_in_flight(program, explicit=max_in_flight,
                              default=2)
    races = find_inflight_races(program, targets=targets,
                                max_in_flight=k, graph=graph)
    isolation = []
    if coresident:
        programs = [program] + [e[1] if isinstance(e, tuple) else e
                                for e in coresident]
        labels = ["this program"] + [
            e[0] if isinstance(e, tuple) else "coresident[%d]" % i
            for i, e in enumerate(coresident)]
        _, isolation = prove_scope_isolation(programs, labels)
    cert = certify_zero_sync(program, targets=targets, graph=graph,
                             max_in_flight=k) if certify else None
    report = ConcurrencyReport(k, races, isolation,
                               footprint=scope_footprint(program, graph),
                               certificate=cert)
    from ..observability import runtime as _obs

    _obs.record_concurrency_check(len(races) + len(isolation),
                                  gate="analyze")
    return report


def race_signatures(program, targets=(), max_in_flight=2):
    """Order-insensitive signatures of the race findings — the rewrite
    brackets diff these, so a pass is only blamed for races it
    *introduces* (op indices excluded: removing ops ahead of a
    pre-existing race must not make it look new)."""
    return {(d.check, d.var_names)
            for d in find_inflight_races(program, targets=targets,
                                         max_in_flight=max_in_flight)}


def assert_no_new_races(program, baseline, context, targets=(),
                        max_in_flight=2):
    """Raise :class:`~.verifier.VerifyError` if ``program`` has a race
    signature not in ``baseline`` (from :func:`race_signatures` on the
    pre-rewrite program)."""
    diags = find_inflight_races(program, targets=targets,
                                max_in_flight=max_in_flight)
    new = [d for d in diags
           if (d.check, d.var_names) not in baseline]
    if new:
        from .verifier import VerifyError
        from ..observability import runtime as _obs

        _obs.record_concurrency_check(len(new), gate=context,
                                      tripped=True)
        raise VerifyError(
            format_diagnostics(
                new, header="rewrite introduced a race (%s):" % context),
            diagnostics=new)


def verify_async_hot_path(program, targets=(), max_in_flight=2,
                          label=None):
    """The ``run_batches(..., verify=True)`` gate: race-check the
    program the executor will actually run (the fused twin when fusion
    is enabled) at the requested in-flight depth, and enforce the
    strict-sync promotion for the serving path.  Raises
    :class:`~.verifier.VerifyError` naming every finding; returns the
    (possibly empty) advisory diagnostics otherwise."""
    from .verifier import VerifyError
    from ..observability import runtime as _obs

    checked = program
    try:
        from .fusion import fusion_enabled, resolve_fused_program

        if fusion_enabled():
            checked, _ = resolve_fused_program(program, targets=[
                t.name if hasattr(t, "name") else str(t)
                for t in targets])
    except Exception:
        checked = program  # the gate must not be harder than the run
    graph = DefUseGraph(checked)
    diags = list(find_inflight_races(checked, targets=targets,
                                     max_in_flight=max_in_flight,
                                     graph=graph))
    cert = certify_zero_sync(checked, targets=targets, graph=graph,
                             label=label, max_in_flight=max_in_flight)
    for s in cert.violations:
        diags.append(Diagnostic(
            "sync-in-hot-loop", Severity.ERROR,
            "host-sync point in the serving hot loop at %s — "
            "introduced by %s: %s" % (s.where(), s.api, s.reason),
            block_idx=s.block_idx, op_idx=s.op_idx, op_type=s.op_type,
            var_names=s.var_names,
            hint="run_batches keeps %d step(s) in flight; a per-step "
                 "host sync serializes them" % max_in_flight))
    _obs.record_concurrency_check(len(diags), gate="run_batches",
                                  tripped=bool(diags))
    if diags:
        raise VerifyError(
            format_diagnostics(
                diags,
                header="async hot path failed concurrency verification "
                       "(max_in_flight=%d):" % max_in_flight),
            diagnostics=diags)
    return diags
