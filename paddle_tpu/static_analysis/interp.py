"""Abstract interpretation over the Program IR.

The transpilers and parallel passes rewrite distribution INTO the same
``Program`` the executor runs, so the facts that matter for a
distributed run — what shape/dtype every value has, which values are
sharded over which mesh axis, which are replicated on every worker —
are statically derivable before a single device cycle is spent.  This
module walks the Program in execution order (descending
``attrs["sub_block"]`` bodies like the def-use walker) propagating an
:class:`AbstractVal` per var:

* **shape** — the recorded static shape with ``-1`` (batch) dims
  resolved against a configurable assumed batch size, so downstream
  consumers (the cost model) see concrete element counts;
* **dtype** — recorded dtype string;
* **persistable** — scope-resident across steps (params, optimizer
  state);
* **sharding** — a small lattice (BOTTOM < REPLICATED | SHARDED <
  UNKNOWN) seeded from transpiler/parallel annotations
  (``Parameter.shard_spec``, ``_is_distributed`` row-sharding,
  ``program._num_trainers`` batch sharding of fed data vars) and
  propagated through ops by per-type transfer rules
  (:func:`register_transfer`, the ``register_check`` idiom).

The interpreter never executes a lowering: it reads the Variable
metadata the build-time ``jax.eval_shape`` inference recorded (the
``shape-dtype-drift`` check separately proves that metadata is still
consistent with the lowerings), which keeps ``analyze()`` cheap enough
to run in CI over every example program.
"""

import os

from .defuse import SUB_BLOCK_DESCENT_OPS, resolve_sub_block

__all__ = [
    "Sharding", "AbstractVal", "OpRecord", "InterpResult",
    "interpret_program", "register_transfer", "assumed_batch_size",
    "DATA_AXIS",
]

# mesh-axis naming convention: fed data vars of an N-trainer program are
# batch-sharded over this axis (parallel/__init__._make_mesh)
DATA_AXIS = "data"


def assumed_batch_size(default=1):
    """The batch size ``-1`` dims resolve to during analysis.  Static
    analysis needs concrete element counts for FLOP/byte totals; the env
    var ``PADDLE_TPU_ANALYZE_BATCH`` pins it (default 1 — every total
    then reads as "per example")."""
    val = os.environ.get("PADDLE_TPU_ANALYZE_BATCH", "").strip()
    if val:
        return max(1, int(val))
    return default


class Sharding:
    """One point of the sharding/replication lattice.

    ``BOTTOM`` (no information yet) < ``REPLICATED`` / ``SHARDED(axis,
    dim, parts)`` < ``UNKNOWN`` (conflicting facts).  ``join`` moves up
    the lattice; transfer rules move values sideways (a collective
    turns SHARDED into REPLICATED, an explicit reshard changes the
    axis/dim)."""

    BOTTOM = "bottom"
    REPLICATED = "replicated"
    SHARDED = "sharded"
    UNKNOWN = "unknown"

    __slots__ = ("kind", "axis", "dim", "parts")

    def __init__(self, kind, axis=None, dim=None, parts=1):
        self.kind = kind
        self.axis = axis
        self.dim = dim
        self.parts = int(parts or 1)

    @classmethod
    def bottom(cls):
        return cls(cls.BOTTOM)

    @classmethod
    def replicated(cls):
        return cls(cls.REPLICATED)

    @classmethod
    def sharded(cls, axis, dim, parts):
        if parts <= 1:
            return cls.replicated()
        return cls(cls.SHARDED, axis=axis, dim=dim, parts=parts)

    @classmethod
    def unknown(cls):
        return cls(cls.UNKNOWN)

    @property
    def is_sharded(self):
        return self.kind == self.SHARDED

    def __eq__(self, other):
        return (isinstance(other, Sharding) and self.kind == other.kind
                and self.axis == other.axis and self.dim == other.dim
                and self.parts == other.parts)

    def __hash__(self):
        return hash((self.kind, self.axis, self.dim, self.parts))

    def join(self, other):
        if self == other:
            return self
        if self.kind == self.BOTTOM:
            return other
        if other.kind == self.BOTTOM:
            return self
        return Sharding.unknown()

    def __repr__(self):
        if self.kind == self.SHARDED:
            return "sharded(%s, dim=%s, parts=%d)" % (
                self.axis, self.dim, self.parts)
        return self.kind


class AbstractVal:
    """Everything the analyzer statically knows about one var."""

    __slots__ = ("name", "shape", "dtype", "persistable", "sharding")

    def __init__(self, name, shape, dtype, persistable=False,
                 sharding=None):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = str(dtype) if dtype is not None else None
        self.persistable = bool(persistable)
        self.sharding = sharding or Sharding.bottom()

    @property
    def numel(self):
        """Global element count (None when the shape is unknown)."""
        if self.shape is None:
            return None
        n = 1
        for d in self.shape:
            n *= max(int(d), 1)
        return n

    @property
    def local_numel(self):
        """Per-worker element count: global / parts when sharded."""
        n = self.numel
        if n is None:
            return None
        if self.sharding.is_sharded:
            return max(1, n // self.sharding.parts)
        return n

    def __repr__(self):
        return "AbstractVal(%s: %s %s%s, %r)" % (
            self.name, self.shape, self.dtype,
            " persistable" if self.persistable else "", self.sharding)


class OpRecord:
    """One interpreted op: coordinates + resolved input/output values,
    in walk (execution) order — the unit the cost model consumes."""

    __slots__ = ("index", "block_idx", "op_idx", "op", "ins", "outs")

    def __init__(self, index, block_idx, op_idx, op, ins, outs):
        self.index = index
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op = op
        self.ins = ins      # [AbstractVal] in input_arg_names order
        self.outs = outs    # [AbstractVal] in output_arg_names order

    def __repr__(self):
        return "OpRecord(%d: block %d op %d %s)" % (
            self.index, self.block_idx, self.op_idx, self.op.type)


class InterpResult:
    """Final abstract environment + per-op trace.

    ``env``:      {var name: AbstractVal} after the walk
    ``records``:  [OpRecord] in execution order
    ``nranks``:   worker count the sharding lattice was seeded with
    ``batch_size``: what -1 dims resolved to
    """

    def __init__(self, program, env, records, nranks, batch_size):
        self.program = program
        self.env = env
        self.records = records
        self.nranks = nranks
        self.batch_size = batch_size

    def val(self, name):
        return self.env.get(name)

    def sharded_vars(self):
        return {n: v for n, v in self.env.items()
                if v.sharding.is_sharded}

    def replicated_persistables(self):
        return {n: v for n, v in self.env.items()
                if v.persistable and not v.sharding.is_sharded}


# ---------------------------------------------------------------------------
# transfer rules
# ---------------------------------------------------------------------------

_TRANSFERS = {}


def register_transfer(op_type):
    """Register ``fn(op, in_vals, out_val) -> Sharding`` as the sharding
    transfer rule for ``op_type`` (``in_vals``: [AbstractVal];
    ``out_val``: the AbstractVal being produced, sharding not yet set).
    Later registration replaces earlier, like ``register_check``."""

    def deco(fn):
        _TRANSFERS[op_type] = fn
        return fn

    return deco


def _default_transfer(op, in_vals, out_val):
    """Join of the input shardings, with a shape guard: a sharded input
    propagates only when the output has the same global shape (the
    elementwise/unary case); shape-changing ops degrade to UNKNOWN
    rather than invent a wrong placement."""
    s = Sharding.bottom()
    for v in in_vals:
        s = s.join(v.sharding)
    if s.kind == Sharding.BOTTOM:
        return Sharding.replicated()
    if s.is_sharded:
        shaped = [v for v in in_vals if v.sharding.is_sharded]
        if any(v.shape != out_val.shape for v in shaped):
            return Sharding.unknown()
    return s


def _replicating_transfer(op, in_vals, out_val):
    return Sharding.replicated()


# collectives produce replicated values (allreduce/allgather/broadcast
# materialize the global value on every participant)
for _t in ("c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
           "c_allreduce_prod", "allreduce", "c_broadcast", "broadcast",
           "c_allgather", "fill_constant", "c_fused_allreduce_sum",
           "c_allreduce_quant", "c_allreduce_start", "c_allreduce_wait",
           "c_hier_reducescatter", "c_hier_allgather"):
    register_transfer(_t)(_replicating_transfer)


@register_transfer("fused_conv_bn_act")
def _fused_conv_bn_transfer(op, in_vals, out_val):
    # conv preserves the batch dim: a batch-sharded input stays
    # batch-sharded even though the spatial/channel shape changes (the
    # default rule would degrade the shape change to UNKNOWN).  Applies
    # to the rank-preserving Out ONLY — the [C]-shaped MeanOut/
    # VarianceOut running stats are replicated, and stamping them
    # sharded would report C/parts local elements for a full vector
    if in_vals and in_vals[0].sharding.is_sharded \
            and in_vals[0].sharding.dim == 0 \
            and out_val.shape is not None \
            and in_vals[0].shape is not None \
            and len(out_val.shape) == len(in_vals[0].shape) \
            and out_val.shape[0] == in_vals[0].shape[0]:
        return in_vals[0].sharding
    if out_val.shape is not None and len(out_val.shape) == 1:
        return Sharding.replicated()  # the running-stat outputs
    return _default_transfer(op, in_vals, out_val)


@register_transfer("fused_embedding_gather")
def _fused_embedding_transfer(op, in_vals, out_val):
    # the gathered slab follows the ID stream's (batch) sharding; the
    # table's row sharding does not shard the output (each worker
    # resolves its batch's rows — GSPMD inserts the halo exchange)
    if len(in_vals) > 1 and in_vals[1].sharding.is_sharded:
        return in_vals[1].sharding
    return Sharding.replicated()


@register_transfer("c_reducescatter")
def _reducescatter_transfer(op, in_vals, out_val):
    parts = max((v.sharding.parts for v in in_vals
                 if v.sharding.is_sharded), default=1)
    return Sharding.sharded(DATA_AXIS, 0, parts) if parts > 1 \
        else Sharding.unknown()


@register_transfer("kv_cache_write")
@register_transfer("kv_cache_prefill")
@register_transfer("paged_kv_cache_write")
@register_transfer("paged_kv_cache_prefill")
def _kv_cache_transfer(op, in_vals, out_val):
    # the output IS the cache (ring-buffer update): it keeps the cache's
    # placement.  The default join would degrade to UNKNOWN whenever the
    # [B,H,D] step row is sharded (different shape from the cache)
    if in_vals:
        return in_vals[0].sharding
    return _default_transfer(op, in_vals, out_val)


@register_transfer("flash_decode_attention")
@register_transfer("paged_flash_decode_attention")
def _flash_decode_transfer(op, in_vals, out_val):
    # out [B,H,D] follows the query row's placement (batch-sharded
    # serving slots stay batch-sharded); the cache inputs don't shard
    # the output — each worker reads its own slots' cache blocks
    if in_vals and in_vals[0].sharding.is_sharded:
        return in_vals[0].sharding
    return Sharding.replicated()


@register_transfer("top_k_sampling")
@register_transfer("top_p_sampling")
def _sampling_transfer(op, in_vals, out_val):
    # ids [B] from logits [B,V]: batch sharding survives the vocab-dim
    # reduction; a vocab-sharded input would need a cross-worker argmax,
    # which the lowering doesn't do — flag UNKNOWN so the analyzer warns
    if in_vals and in_vals[0].sharding.is_sharded:
        s = in_vals[0].sharding
        return s if s.dim == 0 else Sharding.unknown()
    return Sharding.replicated()


@register_transfer("all_to_all")
def _all_to_all_transfer(op, in_vals, out_val):
    # a reshard: stays sharded over the same axis, the sharded tensor
    # dim moves from split_axis to concat_axis
    for v in in_vals:
        if v.sharding.is_sharded:
            return Sharding.sharded(
                v.sharding.axis, int(op.attrs.get("concat_axis", 0)),
                v.sharding.parts)
    return _default_transfer(op, in_vals, out_val)


def _transfer(op, in_vals, out_val):
    fn = _TRANSFERS.get(op.type, _default_transfer)
    return fn(op, in_vals, out_val)


# ---------------------------------------------------------------------------
# the walk
# ---------------------------------------------------------------------------

def _resolve_shape(shape, batch_size):
    if shape is None:
        return None
    return tuple(batch_size if (d is None or int(d) < 0) else int(d)
                 for d in shape)


def _seed_sharding(var, nranks, data_parallel=True):
    """Initial lattice point from build/transpiler annotations."""
    if nranks <= 1:
        return Sharding.replicated()
    spec = getattr(var, "shard_spec", None)
    if spec:
        # shard_spec: {tensor_dim: mesh_axis} or (axis names per dim)
        if isinstance(spec, dict):
            for dim, axis in spec.items():
                if axis:
                    return Sharding.sharded(axis, int(dim), nranks)
        else:
            for dim, axis in enumerate(spec):
                if axis:
                    return Sharding.sharded(axis, dim, nranks)
    if getattr(var, "_is_distributed", False) or getattr(
            var, "is_distributed", False):
        return Sharding.sharded(DATA_AXIS, 0, nranks)  # row-sharded table
    if var.is_data and data_parallel:
        # N-trainer programs shard every feed's batch dim over the data
        # axis (parallel/__init__.SPMDRunner); pipeline-stage worker
        # programs (nranks = #stages) feed each stage its LOCAL batch
        return Sharding.sharded(DATA_AXIS, 0, nranks)
    return Sharding.replicated()


def interpret_program(program, nranks=None, batch_size=None,
                      shard_overrides=None):
    """Walk ``program`` and return an :class:`InterpResult`.

    ``nranks``: worker count for the sharding lattice (default: the
    ``program._num_trainers`` the transpiler recorded, else 1).
    ``batch_size``: what ``-1`` dims resolve to (default
    :func:`assumed_batch_size`).
    ``shard_overrides``: ``{var name: Sharding}`` candidate seeding —
    pins the named vars to the given lattice points for the whole walk
    (seed AND after every producing op), overriding both the recorded
    annotations and the transfer rules.  This is how the
    auto-parallelism planner prices hypothetical per-layer shard specs
    (e.g. ZeRO-sharded optimizer state) without mutating the program.
    """
    if nranks is None:
        nranks = int(getattr(program, "_num_trainers", 1) or 1)
    if batch_size is None:
        batch_size = assumed_batch_size()
    # pipeline-stage workers feed each stage its LOCAL batch (feeds
    # replicated) — EXCEPT hierarchical pipeline x dp stages, which
    # carry _num_trainers = dp subgroup size and shard their feeds over
    # it like any data-parallel program
    data_parallel = (getattr(program, "_pipeline_stage", None) is None
                     or int(getattr(program, "_num_trainers", 0)
                            or 0) > 1)
    shard_overrides = shard_overrides or {}

    env = {}
    records = []
    visited_blocks = set()

    def lookup(name, block):
        v = env.get(name)
        if v is not None:
            return v
        var = block._find_var_recursive(name)
        if var is None:
            av = AbstractVal(name, None, None)
        else:
            av = AbstractVal(
                name, _resolve_shape(var.shape, batch_size), var.dtype,
                persistable=var.persistable,
                sharding=_seed_sharding(var, nranks, data_parallel))
        if name in shard_overrides:
            av.sharding = shard_overrides[name]
        env[name] = av
        return av

    def walk(block):
        if block.idx in visited_blocks:
            return
        visited_blocks.add(block.idx)
        for op_idx, op in enumerate(block.ops):
            in_vals = [lookup(n, block) for n in op.input_arg_names]
            if op.type in SUB_BLOCK_DESCENT_OPS:
                inner = resolve_sub_block(program, op,
                                          host_block_idx=block.idx)
                if inner is not None:
                    walk(inner)
            out_vals = []
            for n in op.output_arg_names:
                var = block._find_var_recursive(n)
                av = AbstractVal(
                    n,
                    _resolve_shape(
                        var.shape if var is not None else None,
                        batch_size),
                    var.dtype if var is not None else None,
                    persistable=bool(var is not None and var.persistable))
                av.sharding = shard_overrides.get(
                    n) or _transfer(op, in_vals, av)
                env[n] = av
                out_vals.append(av)
            records.append(OpRecord(len(records), block.idx, op_idx, op,
                                    in_vals, out_vals))

    walk(program.global_block())
    # vars no op references (freshly created params, orphaned temps)
    # still exist in the scope — seed them so persistable-memory and
    # sharding summaries cover the whole program, not just the op graph
    for block in program.blocks:
        for name in block.vars:
            if name not in env:
                lookup(name, block)
    return InterpResult(program, env, records, nranks, batch_size)
