"""Cross-worker collective schedule extraction and static
deadlock-freedom proof.

A distributed Fluid program is N per-worker programs that must agree on
their communication schedule: every participant of a ring must issue
the SAME ordered sequence of symmetric collectives (kind, dtype,
element count), and every ``send_v2`` must meet a matching ``recv_v2``
on the peer, in the same relative order — otherwise the cluster
deadlocks (or silently reduces mismatched buffers).  Because the
transpilers (``DistributeTranspiler``, ``transpiler/collective.py``)
and the parallel program emitters (``parallel/pipeline.py``
``transpile_pipeline``, ``parallel/{moe,ulysses,ring_attention}``
collective emitters) insert these ops into the same Program IR the
executor runs, the whole schedule is statically visible — this module
extracts it and proves consistency, or names the first diverging pair.

The proof obligations (the ``collective-schedule-divergence`` check):

1. per ring_id, every worker's ordered list of symmetric collectives
   matches worker 0's in length, op kind, dtype, and element count;
2. per directed channel (src worker → dst worker, per ring), the
   ordered ``send_v2`` list on src matches the ordered ``recv_v2`` list
   on dst in length, dtype, and element count;
3. the whole interleaved schedule completes under **rendezvous
   semantics** (every collective blocks until all its participants
   arrive; a send blocks on its recv and vice versa) — proven by
   simulating the N queues to exhaustion.  This is what catches
   cross-channel reorderings that per-ring/per-channel matching cannot
   (worker A does send-then-recv while worker B does send-then-recv of
   the opposite channels: both channels match pairwise, yet both
   workers block forever).

Together these rule out the classic static deadlocks: reordered
collectives, mismatched reduce payloads, and orphaned/mispaired p2p.
The model is conservative: a runtime with buffered (eager) sends may
survive some schedules the rendezvous model rejects — but a schedule
that passes here is safe under either semantics.
"""

from .cost import COLLECTIVE_OP_TYPES, P2P_OP_TYPES
from .diagnostics import Diagnostic, Severity
from .interp import interpret_program

__all__ = [
    "CollectiveEvent", "extract_collective_schedule",
    "flatten_schedule", "check_schedule_consistency",
    "prove_deadlock_free",
]


class CollectiveEvent:
    """One collective op in one worker's schedule.  ``order`` is the
    op's position in the worker's global execution order (across
    rings) — what the rendezvous simulation queues on."""

    __slots__ = ("worker", "ring_id", "kind", "dtype", "numel",
                 "block_idx", "op_idx", "op_type", "var", "peer",
                 "order")

    def __init__(self, worker, ring_id, kind, dtype, numel, block_idx,
                 op_idx, op_type, var=None, peer=None, order=0):
        self.worker = worker
        self.ring_id = ring_id
        self.kind = kind          # op type for symmetric, send/recv for p2p
        self.dtype = dtype
        self.numel = numel
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.var = var
        self.peer = peer
        self.order = order

    @property
    def is_p2p(self):
        return self.op_type in P2P_OP_TYPES

    def signature(self):
        """What must match across participants."""
        return (self.kind, self.dtype, self.numel)

    def where(self):
        return "worker %s block %d op %d (%s%s)" % (
            self.worker, self.block_idx, self.op_idx, self.op_type,
            " %s" % self.var if self.var else "")

    def to_dict(self):
        return {
            "worker": self.worker, "ring_id": self.ring_id,
            "kind": self.kind, "dtype": self.dtype, "numel": self.numel,
            "block_idx": self.block_idx, "op_idx": self.op_idx,
            "op_type": self.op_type, "var": self.var, "peer": self.peer,
        }

    def __repr__(self):
        return "CollectiveEvent(%s ring=%r %s[%s x%s]%s)" % (
            self.where(), self.ring_id, self.kind, self.dtype,
            self.numel,
            " peer=%s" % self.peer if self.peer is not None else "")


def extract_collective_schedule(program, worker=0, interp=None,
                                nranks=None, batch_size=None):
    """Ordered per-ring collective sequences of one worker's program.

    Returns ``{ring_id: [CollectiveEvent]}`` in execution order
    (sub-blocks included via the interpreter's walk).  Element counts
    come from the abstract interpretation, so ``-1`` dims resolve the
    same way the cost model resolves them.
    """
    if interp is None:
        interp = interpret_program(program, nranks=nranks,
                                   batch_size=batch_size)
    schedule = {}
    for rec in interp.records:
        op = rec.op
        if op.type not in COLLECTIVE_OP_TYPES \
                and op.type not in P2P_OP_TYPES:
            continue
        ring = op.attrs.get("ring_id")
        payload = rec.outs[0] if (op.type == "recv_v2" and rec.outs) \
            else (rec.ins[0] if rec.ins else
                  (rec.outs[0] if rec.outs else None))
        numel = payload.local_numel if payload is not None else None
        var = payload.name if payload is not None else None
        if op.type == "c_allreduce_quant" and rec.ins:
            # quantized bucket: like the fused op it moves one coalesced
            # buffer, but the WIRE identity is int8 + scale sidecar —
            # recording dtype "int8" keeps a quantized ring from
            # signature-matching a bf16 ring with the same numel (a
            # worker pair that disagreed about quantizing a bucket must
            # be flagged as divergent, not proven consistent)
            numel = sum(v.local_numel or 0 for v in rec.ins)
            var = "%s(+%d coalesced, int8)" % (rec.ins[0].name,
                                               len(rec.ins) - 1)
            ev = CollectiveEvent(
                worker, ring, op.type, "int8", numel,
                rec.block_idx, rec.op_idx, op.type,
                var=var, peer=op.attrs.get("peer"), order=rec.index)
            schedule.setdefault(ring, []).append(ev)
            continue
        if op.type == "c_allreduce_start" and rec.ins:
            # the async half of an overlap pair is the rendezvous (the
            # wait half is a zero-byte local barrier and never appears
            # here): one coalesced buffer, wire identity int8 when the
            # start carries the quantized path.  Because the signature
            # embeds the hoisted ORDER via the per-ring sequence, a
            # worker pair whose overlap passes hoisted starts into
            # different relative ring positions is flagged divergent —
            # exactly the rank-asymmetry the overlap prover must reject
            numel = sum(v.local_numel or 0 for v in rec.ins)
            wire_dtype = "int8" if op.attrs.get("quant") \
                else (payload.dtype if payload is not None else None)
            var = "%s(+%d coalesced%s)" % (
                rec.ins[0].name, len(rec.ins) - 1,
                ", int8" if op.attrs.get("quant") else "")
            ev = CollectiveEvent(
                worker, ring, op.type, wire_dtype, numel,
                rec.block_idx, rec.op_idx, op.type,
                var=var, peer=op.attrs.get("peer"), order=rec.index)
            schedule.setdefault(ring, []).append(ev)
            continue
        if op.type in ("c_hier_reducescatter", "c_hier_allgather"):
            # hierarchical intra-slice hops: like the fused op they move
            # one coalesced buffer — the RS is signed by its member
            # inputs, the AG by its member outputs (its input is just
            # the 1/c chunk).  Both hops carry the FULL bucket around
            # the slice ring, so the signature numel is the member sum:
            # two slices that disagreed about decomposing a bucket
            # diverge on ring 5 length, not silently on payload
            vals = rec.ins if op.type == "c_hier_reducescatter" \
                else rec.outs
            if vals:
                numel = sum(v.local_numel or 0 for v in vals)
                var = "%s(+%d coalesced)" % (vals[0].name,
                                             len(vals) - 1)
        if op.type == "c_fused_allreduce_sum" and rec.ins:
            # the bucketed allreduce moves ONE coalesced buffer: its
            # schedule signature is the summed member payload (identical
            # on every worker because the fusion pass is deterministic
            # over identical per-worker programs)
            numel = sum(v.local_numel or 0 for v in rec.ins)
            var = "%s(+%d coalesced)" % (rec.ins[0].name,
                                         len(rec.ins) - 1)
        ev = CollectiveEvent(
            worker, ring,
            "send" if op.type == "send_v2"
            else ("recv" if op.type == "recv_v2" else op.type),
            payload.dtype if payload is not None else None,
            numel,
            rec.block_idx, rec.op_idx, op.type,
            var=var,
            peer=op.attrs.get("peer"), order=rec.index)
        schedule.setdefault(ring, []).append(ev)
    return schedule


def flatten_schedule(schedule):
    """One worker's events across all rings, in execution order."""
    evs = [e for ring_evs in schedule.values() for e in ring_evs]
    evs.sort(key=lambda e: e.order)
    return evs


def _diag(message, ev, check="collective-schedule-divergence",
          severity=Severity.ERROR, hint=""):
    return Diagnostic(
        check, severity, message,
        block_idx=ev.block_idx if ev is not None else None,
        op_idx=ev.op_idx if ev is not None else None,
        op_type=ev.op_type if ev is not None else None,
        var_names=(ev.var,) if ev is not None and ev.var else (),
        hint=hint)


def _simulate_rendezvous(schedules):
    """Run the interleaved schedule to exhaustion under rendezvous
    semantics.  Returns [] when every queue drains, else diagnostics
    naming the mutually-blocked head events (the diverging pair).

    Fire rules per step:
    * p2p — worker ``src``'s head is a send to ``dst`` and ``dst``'s
      head is the matching recv from ``src`` (same ring, dtype, numel):
      both advance;
    * symmetric — every participant of the ring (any worker with events
      on it) sits at a same-signature head collective on that ring: all
      advance.
    """
    queues = [flatten_schedule(s) for s in schedules]
    ring_members = {}
    for w, q in enumerate(queues):
        for e in q:
            if not e.is_p2p:
                ring_members.setdefault(e.ring_id, set()).add(w)
    heads = [0] * len(queues)

    def head(w):
        return queues[w][heads[w]] if heads[w] < len(queues[w]) else None

    progress = True
    while progress:
        progress = False
        for w in range(len(queues)):
            e = head(w)
            if e is None:
                continue
            if e.op_type == "send_v2":
                d = e.peer
                if not isinstance(d, int) or not 0 <= d < len(queues):
                    continue
                r = head(d)
                if (r is not None and r.op_type == "recv_v2"
                        and r.peer == w and r.ring_id == e.ring_id
                        and (r.dtype, r.numel) == (e.dtype, e.numel)):
                    heads[w] += 1
                    heads[d] += 1
                    progress = True
            elif not e.is_p2p:
                members = ring_members.get(e.ring_id, {w})
                peers = [head(m) for m in sorted(members)]
                if all(p is not None and not p.is_p2p
                       and p.ring_id == e.ring_id
                       and p.signature() == e.signature()
                       for p in peers):
                    for m in sorted(members):
                        heads[m] += 1
                    progress = True
            # a recv head can only be advanced by its sender's turn

    stuck = [(w, head(w)) for w in range(len(queues))
             if head(w) is not None]
    if not stuck:
        return []
    (w0, e0) = stuck[0]
    others = ", ".join(e.where() for _, e in stuck[1:3]) or \
        "every peer has drained its schedule"
    return [_diag(
        "collective schedule deadlocks under rendezvous semantics: %s "
        "waits forever (blocked against: %s)" % (e0.where(), others),
        e0,
        hint="reorder the collectives so matching pairs meet in the "
             "same relative position on every participant")]


def check_schedule_consistency(schedules):
    """Prove the per-worker schedules deadlock-free, or return precise
    ERROR diagnostics naming the first diverging pair.

    ``schedules``: list (indexed by worker) of the per-ring dicts
    :func:`extract_collective_schedule` returns.  Three layers: per-ring
    symmetric-sequence comparison, per-channel p2p matching (both give
    position-precise messages), then the rendezvous simulation
    (:func:`_simulate_rendezvous`) for cross-channel orderings the
    pairwise layers cannot see.
    """
    diags = []
    if len(schedules) <= 1:
        return diags
    rings = sorted({r for s in schedules for r in s},
                   key=lambda r: repr(r))
    for ring in rings:
        per_worker = [
            [e for e in s.get(ring, ()) if not e.is_p2p]
            for s in schedules
        ]
        ref = per_worker[0]
        for w in range(1, len(per_worker)):
            cur = per_worker[w]
            stop = False
            for i, (a, b) in enumerate(zip(ref, cur)):
                if a.signature() != b.signature():
                    diags.append(_diag(
                        "collective schedule diverges on ring %r at "
                        "position %d: %s issues %s[%s x%s] but %s "
                        "issues %s[%s x%s]"
                        % (ring, i, a.where(), a.kind, a.dtype, a.numel,
                           b.where(), b.kind, b.dtype, b.numel),
                        b,
                        hint="all participants of a ring must issue "
                             "the same collectives in the same order "
                             "with the same payload"))
                    stop = True
                    break
            if not stop and len(ref) != len(cur):
                longer, which = ((ref, 0) if len(ref) > len(cur)
                                 else (cur, w))
                extra = longer[min(len(ref), len(cur))]
                diags.append(_diag(
                    "ring %r: worker 0 issues %d collective(s) but "
                    "worker %d issues %d — first unmatched is %s"
                    % (ring, len(ref), w, len(cur), extra.where()),
                    extra,
                    hint="a transpiler inserted a collective on some "
                         "workers only — every participant must issue "
                         "it or none"))
        # ---- p2p channels on this ring ----
        sends = {}
        recvs = {}
        for w, s in enumerate(schedules):
            for e in s.get(ring, ()):
                if e.op_type == "send_v2":
                    sends.setdefault((w, e.peer), []).append(e)
                elif e.op_type == "recv_v2":
                    recvs.setdefault((e.peer, w), []).append(e)
        for chan in sorted(set(sends) | set(recvs)):
            src, dst = chan
            ss = sends.get(chan, [])
            rr = recvs.get(chan, [])
            for i, (a, b) in enumerate(zip(ss, rr)):
                if (a.dtype, a.numel) != (b.dtype, b.numel):
                    diags.append(_diag(
                        "p2p channel %s->%s on ring %r diverges at "
                        "position %d: %s sends [%s x%s] but %s "
                        "receives [%s x%s]"
                        % (src, dst, ring, i, a.where(), a.dtype,
                           a.numel, b.where(), b.dtype, b.numel),
                        b,
                        hint="matched send_v2/recv_v2 pairs must agree "
                             "on dtype and element count"))
                    break
            else:
                if len(ss) != len(rr):
                    extra = (ss if len(ss) > len(rr)
                             else rr)[min(len(ss), len(rr))]
                    diags.append(_diag(
                        "p2p channel %s->%s on ring %r: %d send(s) vs "
                        "%d recv(s) — first unmatched is %s"
                        % (src, dst, ring, len(ss), len(rr),
                           extra.where()),
                        extra,
                        hint="every send_v2 must meet exactly one "
                             "recv_v2 on the peer (and vice versa)"))
    if not diags:
        # pairwise layers are clean — prove the interleaving too
        diags.extend(_simulate_rendezvous(schedules))
    return diags


def prove_deadlock_free(programs, nranks=None, batch_size=None):
    """Extract every worker's schedule and check consistency.

    Returns ``(schedules, diagnostics)`` — empty diagnostics means the
    schedule is proven consistent (deadlock-free under the static
    model).  ``programs``: the N transpiled per-worker main programs.
    """
    if nranks is None:
        nranks = len(programs)
    schedules = [
        extract_collective_schedule(p, worker=w, nranks=nranks,
                                    batch_size=batch_size)
        for w, p in enumerate(programs)
    ]
    return schedules, check_schedule_consistency(schedules)
